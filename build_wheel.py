#!/usr/bin/env python
"""Build the client_tpu wheel with native libraries included.

The reference stages generated pb2 modules and native shm libs into the
package before calling setup (reference src/python/library/build_wheel.py:
120-185); here `make protos native` produces them in-tree, then bdist_wheel
packages everything.  Usage: python build_wheel.py [--dest-dir dist]
"""

import argparse
import os
import subprocess
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--dest-dir", default="dist")
    parser.add_argument("--skip-native", action="store_true",
                        help="package without rebuilding native libs")
    args = parser.parse_args()

    if not args.skip_native:
        subprocess.check_call(["make", "protos", "native"], cwd=_HERE)

    lib = os.path.join(
        _HERE, "client_tpu", "utils", "shared_memory", "libcshm_tpu.so"
    )
    if not os.path.exists(lib):
        print(f"error: {lib} missing (run `make native`)", file=sys.stderr)
        return 1

    subprocess.check_call(
        [sys.executable, "setup.py", "-q", "bdist_wheel",
         "--dist-dir", args.dest_dir],
        cwd=_HERE,
    )
    wheels = [f for f in os.listdir(os.path.join(_HERE, args.dest_dir))
              if f.endswith(".whl")]
    print(f"built: {args.dest_dir}/{sorted(wheels)[-1]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
