// Minimal Node client for the KServe-v2 gRPC service (parity with reference
// src/grpc_generated/javascript): health + add/sub inference on "simple",
// protos loaded at runtime from proto/inference.proto.
const grpc = require("@grpc/grpc-js");
const protoLoader = require("@grpc/proto-loader");
const path = require("path");

const url =
  process.argv.includes("-u")
    ? process.argv[process.argv.indexOf("-u") + 1]
    : "localhost:8001";

const definition = protoLoader.loadSync(
  path.join(__dirname, "../../../proto/inference.proto"),
  { keepCase: true, longs: Number, defaults: true }
);
const inference = grpc.loadPackageDefinition(definition).inference;
const client = new inference.GRPCInferenceService(
  url, grpc.credentials.createInsecure()
);

function int32Bytes(values) {
  const buf = Buffer.alloc(values.length * 4);
  values.forEach((v, i) => buf.writeInt32LE(v, i * 4));
  return buf;
}

client.ServerLive({}, (err, live) => {
  if (err || !live.live) {
    console.error("server not live:", err);
    process.exit(1);
  }
  const input0 = Array.from({ length: 16 }, (_, i) => i);
  const input1 = Array.from({ length: 16 }, () => 1);
  const request = {
    model_name: "simple",
    inputs: [
      { name: "INPUT0", datatype: "INT32", shape: [1, 16] },
      { name: "INPUT1", datatype: "INT32", shape: [1, 16] },
    ],
    outputs: [{ name: "OUTPUT0" }, { name: "OUTPUT1" }],
    raw_input_contents: [int32Bytes(input0), int32Bytes(input1)],
  };
  client.ModelInfer(request, (err, response) => {
    if (err) {
      console.error("infer failed:", err.message);
      process.exit(1);
    }
    const sum = response.raw_output_contents[0];
    for (let i = 0; i < 16; i++) {
      if (sum.readInt32LE(i * 4) !== input0[i] + input1[i]) {
        console.error("wrong arithmetic at", i);
        process.exit(1);
      }
    }
    console.log("PASS: js simple infer");
  });
});
