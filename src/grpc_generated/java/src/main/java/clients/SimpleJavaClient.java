// Java gRPC example over generated KServe-v2 stubs (the analog of the
// reference's src/grpc_generated/java example): drives the add/sub "simple"
// model with raw tensor contents and verifies the arithmetic.
//   mvn exec:java -Dexec.mainClass=clients.SimpleJavaClient -Dexec.args="host:port"
package clients;

import com.google.protobuf.ByteString;
import inference.GRPCInferenceServiceGrpc;
import inference.Inference.InferTensorContents;
import inference.Inference.ModelInferRequest;
import inference.Inference.ModelInferResponse;
import inference.Inference.ServerLiveRequest;
import inference.Inference.ServerLiveResponse;
import io.grpc.ManagedChannel;
import io.grpc.ManagedChannelBuilder;
import java.nio.ByteBuffer;
import java.nio.ByteOrder;

public final class SimpleJavaClient {
  private SimpleJavaClient() {}

  private static ByteString int32Tensor(int[] values) {
    ByteBuffer buf =
        ByteBuffer.allocate(values.length * 4).order(ByteOrder.LITTLE_ENDIAN);
    for (int v : values) buf.putInt(v);
    buf.flip();
    return ByteString.copyFrom(buf);
  }

  public static void main(String[] args) throws Exception {
    String target = args.length > 0 ? args[0] : "localhost:8001";
    ManagedChannel channel =
        ManagedChannelBuilder.forTarget(target).usePlaintext().build();
    try {
      GRPCInferenceServiceGrpc.GRPCInferenceServiceBlockingStub stub =
          GRPCInferenceServiceGrpc.newBlockingStub(channel);

      ServerLiveResponse live =
          stub.serverLive(ServerLiveRequest.getDefaultInstance());
      if (!live.getLive()) {
        System.err.println("error: server not live");
        System.exit(1);
      }

      int[] input0 = new int[16];
      int[] input1 = new int[16];
      for (int i = 0; i < 16; i++) {
        input0[i] = i;
        input1[i] = 1;
      }
      ModelInferRequest request =
          ModelInferRequest.newBuilder()
              .setModelName("simple")
              .addInputs(
                  ModelInferRequest.InferInputTensor.newBuilder()
                      .setName("INPUT0")
                      .setDatatype("INT32")
                      .addShape(1)
                      .addShape(16))
              .addInputs(
                  ModelInferRequest.InferInputTensor.newBuilder()
                      .setName("INPUT1")
                      .setDatatype("INT32")
                      .addShape(1)
                      .addShape(16))
              .addRawInputContents(int32Tensor(input0))
              .addRawInputContents(int32Tensor(input1))
              .build();
      ModelInferResponse response = stub.modelInfer(request);

      ByteBuffer sum = response.getRawOutputContents(0).asReadOnlyByteBuffer()
                           .order(ByteOrder.LITTLE_ENDIAN);
      ByteBuffer diff = response.getRawOutputContents(1).asReadOnlyByteBuffer()
                            .order(ByteOrder.LITTLE_ENDIAN);
      for (int i = 0; i < 16; i++) {
        int s = sum.getInt();
        int d = diff.getInt();
        System.out.printf("%d + %d = %d, %d - %d = %d%n",
            input0[i], input1[i], s, input0[i], input1[i], d);
        if (s != input0[i] + input1[i] || d != input0[i] - input1[i]) {
          System.err.println("error: wrong arithmetic");
          System.exit(1);
        }
      }
      System.out.println("PASS: java grpc stubs");
    } finally {
      channel.shutdownNow();
    }
  }
}
