// Scala gRPC example over the same generated KServe-v2 stubs (the analog
// of the reference's src/grpc_generated/java Scala example): grpc-java's
// blocking stub used from Scala — no separate ScalaPB toolchain needed.
//   mvn exec:java -Dexec.mainClass=clients.SimpleClient -Dexec.args="host:port"
package clients

import com.google.protobuf.ByteString
import inference.GRPCInferenceServiceGrpc
import inference.Inference.{ModelInferRequest, ServerLiveRequest}
import io.grpc.ManagedChannelBuilder
import java.nio.{ByteBuffer, ByteOrder}

object SimpleClient {
  private def int32Tensor(values: Array[Int]): ByteString = {
    val buf =
      ByteBuffer.allocate(values.length * 4).order(ByteOrder.LITTLE_ENDIAN)
    values.foreach(buf.putInt)
    buf.flip()
    ByteString.copyFrom(buf)
  }

  def main(args: Array[String]): Unit = {
    val target = if (args.nonEmpty) args(0) else "localhost:8001"
    val channel =
      ManagedChannelBuilder.forTarget(target).usePlaintext().build()
    try {
      val stub = GRPCInferenceServiceGrpc.newBlockingStub(channel)
      require(
        stub.serverLive(ServerLiveRequest.getDefaultInstance).getLive,
        "server not live")

      val input0 = Array.tabulate(16)(identity)
      val input1 = Array.fill(16)(1)
      val request = ModelInferRequest
        .newBuilder()
        .setModelName("simple")
        .addInputs(
          ModelInferRequest.InferInputTensor
            .newBuilder()
            .setName("INPUT0")
            .setDatatype("INT32")
            .addShape(1)
            .addShape(16))
        .addInputs(
          ModelInferRequest.InferInputTensor
            .newBuilder()
            .setName("INPUT1")
            .setDatatype("INT32")
            .addShape(1)
            .addShape(16))
        .addRawInputContents(int32Tensor(input0))
        .addRawInputContents(int32Tensor(input1))
        .build()
      val response = stub.modelInfer(request)

      val sum = response
        .getRawOutputContents(0)
        .asReadOnlyByteBuffer()
        .order(ByteOrder.LITTLE_ENDIAN)
      val diff = response
        .getRawOutputContents(1)
        .asReadOnlyByteBuffer()
        .order(ByteOrder.LITTLE_ENDIAN)
      for (i <- 0 until 16) {
        val s = sum.getInt()
        val d = diff.getInt()
        println(s"${input0(i)} + ${input1(i)} = $s, " +
          s"${input0(i)} - ${input1(i)} = $d")
        require(s == input0(i) + input1(i), "wrong sum")
        require(d == input0(i) - input1(i), "wrong diff")
      }
      println("PASS: scala grpc stubs")
    } finally {
      channel.shutdownNow()
    }
  }
}
