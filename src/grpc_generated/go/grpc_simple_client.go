// Minimal Go client for the KServe-v2 gRPC service (parity with reference
// src/grpc_generated/go/grpc_simple_client.go:66-142): health check +
// add/sub inference against the "simple" model using stubs generated from
// proto/inference.proto (see README.md).
package main

import (
	"bytes"
	"context"
	"encoding/binary"
	"flag"
	"log"
	"time"

	"google.golang.org/grpc"
	"google.golang.org/grpc/credentials/insecure"

	pb "client_tpu_go/inference"
)

func main() {
	url := flag.String("u", "localhost:8001", "server host:port")
	flag.Parse()

	conn, err := grpc.NewClient(
		*url, grpc.WithTransportCredentials(insecure.NewCredentials()))
	if err != nil {
		log.Fatalf("connect: %v", err)
	}
	defer conn.Close()
	client := pb.NewGRPCInferenceServiceClient(conn)
	ctx, cancel := context.WithTimeout(context.Background(), 30*time.Second)
	defer cancel()

	live, err := client.ServerLive(ctx, &pb.ServerLiveRequest{})
	if err != nil || !live.Live {
		log.Fatalf("server not live: %v", err)
	}

	input0 := make([]int32, 16)
	input1 := make([]int32, 16)
	for i := range input0 {
		input0[i] = int32(i)
		input1[i] = 1
	}
	raw0 := new(bytes.Buffer)
	raw1 := new(bytes.Buffer)
	binary.Write(raw0, binary.LittleEndian, input0)
	binary.Write(raw1, binary.LittleEndian, input1)

	request := &pb.ModelInferRequest{
		ModelName: "simple",
		Inputs: []*pb.ModelInferRequest_InferInputTensor{
			{Name: "INPUT0", Datatype: "INT32", Shape: []int64{1, 16}},
			{Name: "INPUT1", Datatype: "INT32", Shape: []int64{1, 16}},
		},
		Outputs: []*pb.ModelInferRequest_InferRequestedOutputTensor{
			{Name: "OUTPUT0"}, {Name: "OUTPUT1"},
		},
		RawInputContents: [][]byte{raw0.Bytes(), raw1.Bytes()},
	}
	response, err := client.ModelInfer(ctx, request)
	if err != nil {
		log.Fatalf("infer: %v", err)
	}
	sum := make([]int32, 16)
	diff := make([]int32, 16)
	binary.Read(bytes.NewReader(response.RawOutputContents[0]),
		binary.LittleEndian, sum)
	binary.Read(bytes.NewReader(response.RawOutputContents[1]),
		binary.LittleEndian, diff)
	for i := range sum {
		if sum[i] != input0[i]+input1[i] || diff[i] != input0[i]-input1[i] {
			log.Fatalf("wrong arithmetic at %d", i)
		}
	}
	log.Println("PASS: go simple infer")
}
