// Dependency-free JSON writer + minimal parser for the KServe-v2 HTTP
// protocol (the Java twin of src/cpp/client/json.{h,cc}).  The parser
// covers exactly the JSON the server emits: objects, arrays, strings with
// escapes, numbers, booleans, null.
package clienttpu;

import java.util.ArrayList;
import java.util.LinkedHashMap;
import java.util.List;
import java.util.Map;

public final class Json {
  private Json() {}

  // ---- writing ------------------------------------------------------------

  public static String escape(String s) {
    StringBuilder out = new StringBuilder("\"");
    for (int i = 0; i < s.length(); i++) {
      char c = s.charAt(i);
      switch (c) {
        case '"':
          out.append("\\\"");
          break;
        case '\\':
          out.append("\\\\");
          break;
        case '\n':
          out.append("\\n");
          break;
        case '\r':
          out.append("\\r");
          break;
        case '\t':
          out.append("\\t");
          break;
        default:
          if (c < 0x20) {
            out.append(String.format("\\u%04x", (int) c));
          } else {
            out.append(c);
          }
      }
    }
    return out.append('"').toString();
  }

  public static String write(Object value) {
    StringBuilder sb = new StringBuilder();
    writeValue(value, sb);
    return sb.toString();
  }

  private static void writeValue(Object value, StringBuilder sb) {
    if (value == null) {
      sb.append("null");
    } else if (value instanceof String) {
      sb.append(escape((String) value));
    } else if (value instanceof Map) {
      sb.append('{');
      boolean first = true;
      for (Map.Entry<?, ?> e : ((Map<?, ?>) value).entrySet()) {
        if (!first) sb.append(',');
        first = false;
        sb.append(escape(String.valueOf(e.getKey()))).append(':');
        writeValue(e.getValue(), sb);
      }
      sb.append('}');
    } else if (value instanceof List) {
      sb.append('[');
      boolean first = true;
      for (Object v : (List<?>) value) {
        if (!first) sb.append(',');
        first = false;
        writeValue(v, sb);
      }
      sb.append(']');
    } else if (value instanceof long[]) {
      sb.append('[');
      long[] arr = (long[]) value;
      for (int i = 0; i < arr.length; i++) {
        if (i > 0) sb.append(',');
        sb.append(arr[i]);
      }
      sb.append(']');
    } else {
      sb.append(value); // Number / Boolean
    }
  }

  // ---- parsing ------------------------------------------------------------

  public static Object parse(String text) throws InferenceException {
    Parser p = new Parser(text);
    Object v = p.value();
    p.skipWs();
    if (!p.done()) throw new InferenceException("trailing JSON content");
    return v;
  }

  @SuppressWarnings("unchecked")
  public static Map<String, Object> parseObject(String text)
      throws InferenceException {
    Object v = parse(text);
    if (!(v instanceof Map)) {
      throw new InferenceException("expected a JSON object");
    }
    return (Map<String, Object>) v;
  }

  private static final class Parser {
    private final String s;
    private int pos = 0;

    Parser(String s) {
      this.s = s;
    }

    boolean done() {
      return pos >= s.length();
    }

    void skipWs() {
      while (pos < s.length() && Character.isWhitespace(s.charAt(pos))) pos++;
    }

    Object value() throws InferenceException {
      skipWs();
      if (done()) throw new InferenceException("unexpected end of JSON");
      char c = s.charAt(pos);
      switch (c) {
        case '{':
          return object();
        case '[':
          return array();
        case '"':
          return string();
        case 't':
          expect("true");
          return Boolean.TRUE;
        case 'f':
          expect("false");
          return Boolean.FALSE;
        case 'n':
          expect("null");
          return null;
        default:
          return number();
      }
    }

    private void expect(String word) throws InferenceException {
      if (!s.startsWith(word, pos)) {
        throw new InferenceException("malformed JSON literal at " + pos);
      }
      pos += word.length();
    }

    private Map<String, Object> object() throws InferenceException {
      Map<String, Object> out = new LinkedHashMap<>();
      pos++; // '{'
      skipWs();
      if (!done() && s.charAt(pos) == '}') {
        pos++;
        return out;
      }
      while (true) {
        skipWs();
        String key = string();
        skipWs();
        if (done() || s.charAt(pos) != ':') {
          throw new InferenceException("expected ':' at " + pos);
        }
        pos++;
        out.put(key, value());
        skipWs();
        if (done()) throw new InferenceException("unterminated object");
        char c = s.charAt(pos++);
        if (c == '}') return out;
        if (c != ',') throw new InferenceException("expected ',' at " + pos);
      }
    }

    private List<Object> array() throws InferenceException {
      List<Object> out = new ArrayList<>();
      pos++; // '['
      skipWs();
      if (!done() && s.charAt(pos) == ']') {
        pos++;
        return out;
      }
      while (true) {
        out.add(value());
        skipWs();
        if (done()) throw new InferenceException("unterminated array");
        char c = s.charAt(pos++);
        if (c == ']') return out;
        if (c != ',') throw new InferenceException("expected ',' at " + pos);
      }
    }

    private String string() throws InferenceException {
      if (done() || s.charAt(pos) != '"') {
        throw new InferenceException("expected string at " + pos);
      }
      pos++;
      StringBuilder out = new StringBuilder();
      while (pos < s.length()) {
        char c = s.charAt(pos++);
        if (c == '"') return out.toString();
        if (c == '\\') {
          if (pos >= s.length()) break;
          char esc = s.charAt(pos++);
          switch (esc) {
            case 'n':
              out.append('\n');
              break;
            case 'r':
              out.append('\r');
              break;
            case 't':
              out.append('\t');
              break;
            case 'b':
              out.append('\b');
              break;
            case 'f':
              out.append('\f');
              break;
            case 'u':
              if (pos + 4 > s.length()) {
                throw new InferenceException("bad \\u escape");
              }
              out.append((char) Integer.parseInt(s.substring(pos, pos + 4), 16));
              pos += 4;
              break;
            default:
              out.append(esc); // covers \" \\ \/
          }
        } else {
          out.append(c);
        }
      }
      throw new InferenceException("unterminated string");
    }

    private Object number() throws InferenceException {
      int start = pos;
      while (pos < s.length() && "+-0123456789.eE".indexOf(s.charAt(pos)) >= 0) {
        pos++;
      }
      String token = s.substring(start, pos);
      try {
        if (token.contains(".") || token.contains("e") || token.contains("E")) {
          return Double.parseDouble(token);
        }
        return Long.parseLong(token);
      } catch (NumberFormatException e) {
        throw new InferenceException("malformed number '" + token + "'");
      }
    }
  }
}
