// KServe datatype table (parity with reference pojo/DataType.java and the
// dtype map in client_tpu/utils/__init__.py).
package clienttpu;

public enum DataType {
  BOOL(1),
  UINT8(1),
  UINT16(2),
  UINT32(4),
  UINT64(8),
  INT8(1),
  INT16(2),
  INT32(4),
  INT64(8),
  FP16(2),
  BF16(2),
  FP32(4),
  FP64(8),
  BYTES(-1);

  private final int byteSize;

  DataType(int byteSize) {
    this.byteSize = byteSize;
  }

  /** Element width in bytes; -1 for the variable-length BYTES type. */
  public int byteSize() {
    return byteSize;
  }
}
