// One requested output (parity with reference InferRequestedOutput.java).
package clienttpu;

import java.util.LinkedHashMap;
import java.util.Map;

public class InferRequestedOutput {
  private final String name;
  private final Map<String, Object> parameters = new LinkedHashMap<>();

  public InferRequestedOutput(String name) {
    this(name, true, 0);
  }

  public InferRequestedOutput(String name, boolean binaryData, int classCount) {
    this.name = name;
    if (binaryData) parameters.put("binary_data", Boolean.TRUE);
    if (classCount > 0) parameters.put("classification", classCount);
  }

  public String getName() {
    return name;
  }

  Map<String, Object> parameters() {
    return parameters;
  }

  public void setSharedMemory(String regionName, long byteSize, long offset) {
    parameters.remove("binary_data");
    parameters.put("shared_memory_region", regionName);
    parameters.put("shared_memory_byte_size", byteSize);
    if (offset != 0) parameters.put("shared_memory_offset", offset);
  }
}
