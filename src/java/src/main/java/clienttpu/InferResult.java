// Parsed inference response (parity with reference InferResult.java):
// JSON header + binary section split by Inference-Header-Content-Length.
package clienttpu;

import java.nio.ByteBuffer;
import java.nio.ByteOrder;
import java.nio.charset.StandardCharsets;
import java.util.ArrayList;
import java.util.LinkedHashMap;
import java.util.List;
import java.util.Map;

public class InferResult {
  private final Map<String, Object> response;
  private final Map<String, byte[]> binaryOutputs = new LinkedHashMap<>();
  private final Map<String, Map<String, Object>> outputsByName =
      new LinkedHashMap<>();

  @SuppressWarnings("unchecked")
  InferResult(byte[] body, int headerLength) throws InferenceException {
    String headerJson =
        headerLength > 0
            ? new String(body, 0, headerLength, StandardCharsets.UTF_8)
            : new String(body, StandardCharsets.UTF_8);
    this.response = Json.parseObject(headerJson);
    int cursor = headerLength > 0 ? headerLength : body.length;
    Object outputs = response.get("outputs");
    if (outputs instanceof List) {
      for (Object o : (List<Object>) outputs) {
        Map<String, Object> out = (Map<String, Object>) o;
        String name = (String) out.get("name");
        outputsByName.put(name, out);
        Object params = out.get("parameters");
        if (params instanceof Map) {
          Object size = ((Map<String, Object>) params).get("binary_data_size");
          if (size instanceof Long) {
            int n = ((Long) size).intValue();
            byte[] blob = new byte[n];
            System.arraycopy(body, cursor, blob, 0, n);
            binaryOutputs.put(name, blob);
            cursor += n;
          }
        }
      }
    }
  }

  public String getId() {
    Object id = response.get("id");
    return id == null ? "" : id.toString();
  }

  public String getModelName() {
    Object name = response.get("model_name");
    return name == null ? "" : name.toString();
  }

  public Map<String, Object> getResponse() {
    return response;
  }

  public long[] getShape(String output) throws InferenceException {
    Map<String, Object> out = requireOutput(output);
    @SuppressWarnings("unchecked")
    List<Object> dims = (List<Object>) out.get("shape");
    long[] shape = new long[dims.size()];
    for (int i = 0; i < shape.length; i++) shape[i] = (Long) dims.get(i);
    return shape;
  }

  public int[] getOutputAsInt(String output) throws InferenceException {
    ByteBuffer buf = binaryBuffer(output);
    int[] values = new int[buf.remaining() / 4];
    for (int i = 0; i < values.length; i++) values[i] = buf.getInt();
    return values;
  }

  public float[] getOutputAsFloat(String output) throws InferenceException {
    ByteBuffer buf = binaryBuffer(output);
    float[] values = new float[buf.remaining() / 4];
    for (int i = 0; i < values.length; i++) values[i] = buf.getFloat();
    return values;
  }

  public double[] getOutputAsDouble(String output) throws InferenceException {
    ByteBuffer buf = binaryBuffer(output);
    double[] values = new double[buf.remaining() / 8];
    for (int i = 0; i < values.length; i++) values[i] = buf.getDouble();
    return values;
  }

  /** BYTES output: 4-byte little-endian length-prefixed elements. */
  public String[] getOutputAsString(String output) throws InferenceException {
    byte[] blob = binaryOutputs.get(output);
    if (blob != null) {
      ByteBuffer buf = ByteBuffer.wrap(blob).order(ByteOrder.LITTLE_ENDIAN);
      List<String> values = new ArrayList<>();
      while (buf.remaining() >= 4) {
        int n = buf.getInt();
        byte[] raw = new byte[n];
        buf.get(raw);
        values.add(new String(raw, StandardCharsets.UTF_8));
      }
      return values.toArray(new String[0]);
    }
    // non-binary JSON payload
    Map<String, Object> out = requireOutput(output);
    @SuppressWarnings("unchecked")
    List<Object> data = (List<Object>) out.get("data");
    if (data == null) {
      throw new InferenceException("output '" + output + "' carries no data");
    }
    String[] values = new String[data.size()];
    for (int i = 0; i < values.length; i++) values[i] = String.valueOf(data.get(i));
    return values;
  }

  private Map<String, Object> requireOutput(String output)
      throws InferenceException {
    Map<String, Object> out = outputsByName.get(output);
    if (out == null) {
      throw new InferenceException("unknown output '" + output + "'");
    }
    return out;
  }

  private ByteBuffer binaryBuffer(String output) throws InferenceException {
    byte[] blob = binaryOutputs.get(output);
    if (blob == null) {
      throw new InferenceException(
          "output '" + output + "' has no binary data");
    }
    return ByteBuffer.wrap(blob).order(ByteOrder.LITTLE_ENDIAN);
  }
}
