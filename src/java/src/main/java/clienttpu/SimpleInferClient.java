// Smoke main for the Java client — the add/sub example every other client
// language ships (reference src/java/.../examples/SimpleInferClient.java).
//   java -cp build clienttpu.SimpleInferClient http://localhost:8000
package clienttpu;

import java.util.Arrays;
import java.util.List;

public final class SimpleInferClient {
  public static void main(String[] args) throws Exception {
    String url = args.length > 0 ? args[0] : "http://localhost:8000";
    try (InferenceServerClient client = new InferenceServerClient(url)) {
      if (!client.isServerLive()) {
        System.err.println("error: server not live");
        System.exit(1);
      }
      int[] input0 = new int[16];
      int[] input1 = new int[16];
      for (int i = 0; i < 16; i++) {
        input0[i] = i;
        input1[i] = 1;
      }
      InferInput in0 = new InferInput("INPUT0", new long[] {1, 16}, DataType.INT32);
      InferInput in1 = new InferInput("INPUT1", new long[] {1, 16}, DataType.INT32);
      in0.setData(input0);
      in1.setData(input1);
      List<InferRequestedOutput> outputs = Arrays.asList(
          new InferRequestedOutput("OUTPUT0"),
          new InferRequestedOutput("OUTPUT1"));
      InferResult result =
          client.infer("simple", Arrays.asList(in0, in1), outputs);
      int[] sum = result.getOutputAsInt("OUTPUT0");
      int[] diff = result.getOutputAsInt("OUTPUT1");
      for (int i = 0; i < 16; i++) {
        System.out.printf("%d + %d = %d, %d - %d = %d%n", input0[i], input1[i],
                          sum[i], input0[i], input1[i], diff[i]);
        if (sum[i] != input0[i] + input1[i] || diff[i] != input0[i] - input1[i]) {
          System.err.println("error: wrong arithmetic");
          System.exit(1);
        }
      }
      System.out.println("PASS: java simple infer");
    }
  }
}
