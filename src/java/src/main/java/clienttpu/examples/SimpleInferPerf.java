// Async throughput/latency smoke for the Java client — parity with
// reference src/java/.../examples/SimpleInferPerf.java: keep `concurrency`
// requests in flight via asyncInfer for a fixed request count, then report
// infer/sec and latency percentiles.
//   java clienttpu.examples.SimpleInferPerf <host:port> [requests] [concurrency]
package clienttpu.examples;

import clienttpu.DataType;
import clienttpu.InferInput;
import clienttpu.InferRequestedOutput;
import clienttpu.InferenceServerClient;
import java.util.ArrayList;
import java.util.Collections;
import java.util.List;
import java.util.concurrent.CompletableFuture;
import java.util.concurrent.Semaphore;
import java.util.concurrent.atomic.AtomicInteger;

public final class SimpleInferPerf {
  private SimpleInferPerf() {}

  public static void main(String[] args) throws Exception {
    String url = args.length > 0 ? args[0] : "localhost:8000";
    int requests = args.length > 1 ? Integer.parseInt(args[1]) : 200;
    int concurrency = args.length > 2 ? Integer.parseInt(args[2]) : 8;

    try (InferenceServerClient client = new InferenceServerClient(url)) {
      int[] data0 = new int[16];
      int[] data1 = new int[16];
      for (int i = 0; i < 16; i++) {
        data0[i] = i;
        data1[i] = 2 * i;
      }
      InferInput in0 = new InferInput("INPUT0", new long[] {1, 16}, DataType.INT32);
      InferInput in1 = new InferInput("INPUT1", new long[] {1, 16}, DataType.INT32);
      in0.setData(data0);
      in1.setData(data1);
      List<InferInput> inputs = List.of(in0, in1);
      List<InferRequestedOutput> outputs =
          List.of(new InferRequestedOutput("OUTPUT0"));

      // warm up
      for (int i = 0; i < 10; i++) {
        client.infer("simple", inputs, outputs);
      }

      Semaphore slots = new Semaphore(concurrency);
      AtomicInteger failures = new AtomicInteger();
      List<Long> latenciesNs = Collections.synchronizedList(new ArrayList<>());
      List<CompletableFuture<?>> pending = new ArrayList<>();
      long start = System.nanoTime();
      for (int i = 0; i < requests; i++) {
        slots.acquire();
        long t0 = System.nanoTime();
        CompletableFuture<?> f =
            client.asyncInfer("simple", inputs, outputs)
                .whenComplete((result, error) -> {
                  latenciesNs.add(System.nanoTime() - t0);
                  if (error != null) failures.incrementAndGet();
                  slots.release();
                });
        pending.add(f);
      }
      CompletableFuture.allOf(pending.toArray(new CompletableFuture[0]))
          .exceptionally(e -> null).join();
      double elapsedS = (System.nanoTime() - start) / 1e9;

      List<Long> sorted = new ArrayList<>(latenciesNs);
      Collections.sort(sorted);
      long p50 = sorted.get(sorted.size() / 2);
      long p99 = sorted.get(Math.min(sorted.size() - 1, sorted.size() * 99 / 100));
      System.out.printf(
          "requests=%d concurrency=%d throughput=%.1f infer/sec "
              + "p50=%.2fms p99=%.2fms failures=%d%n",
          requests, concurrency, requests / elapsedS, p50 / 1e6, p99 / 1e6,
          failures.get());
      if (failures.get() > 0) {
        System.err.println("FAIL: " + failures.get() + " request failures");
        System.exit(1);
      }
      System.out.println("PASS: SimpleInferPerf");
    }
  }
}
