// Long-running inference loop watching for client-side memory growth —
// parity with reference src/java/.../examples/MemoryGrowthTest.java: run N
// iterations against a live server, sample heap usage before/after (with
// forced GC), and fail when the retained heap grows beyond a tolerance.
//   java clienttpu.examples.MemoryGrowthTest <host:port> [iterations]
package clienttpu.examples;

import clienttpu.DataType;
import clienttpu.InferInput;
import clienttpu.InferRequestedOutput;
import clienttpu.InferResult;
import clienttpu.InferenceServerClient;
import java.util.List;

public final class MemoryGrowthTest {
  private MemoryGrowthTest() {}

  private static long retainedHeap() {
    Runtime rt = Runtime.getRuntime();
    for (int i = 0; i < 3; i++) {
      rt.gc();
      try {
        Thread.sleep(50);
      } catch (InterruptedException e) {
        Thread.currentThread().interrupt();
      }
    }
    return rt.totalMemory() - rt.freeMemory();
  }

  public static void main(String[] args) throws Exception {
    String url = args.length > 0 ? args[0] : "localhost:8000";
    int iterations = args.length > 1 ? Integer.parseInt(args[1]) : 500;

    try (InferenceServerClient client = new InferenceServerClient(url)) {
      int[] data0 = new int[16];
      int[] data1 = new int[16];
      for (int i = 0; i < 16; i++) {
        data0[i] = i;
        data1[i] = 1;
      }
      InferInput in0 = new InferInput("INPUT0", new long[] {1, 16}, DataType.INT32);
      InferInput in1 = new InferInput("INPUT1", new long[] {1, 16}, DataType.INT32);
      in0.setData(data0);
      in1.setData(data1);
      List<InferInput> inputs = List.of(in0, in1);
      List<InferRequestedOutput> outputs =
          List.of(new InferRequestedOutput("OUTPUT0"));

      // warm the transport + JIT before the baseline sample
      for (int i = 0; i < 20; i++) {
        client.infer("simple", inputs, outputs);
      }
      long before = retainedHeap();
      for (int i = 0; i < iterations; i++) {
        InferResult result = client.infer("simple", inputs, outputs);
        int[] sum = result.getOutputAsInt("OUTPUT0");
        if (sum[3] != data0[3] + data1[3]) {
          System.err.println("FAIL: wrong result at iteration " + i);
          System.exit(1);
        }
      }
      long after = retainedHeap();
      long growth = after - before;
      System.out.println(
          "iterations=" + iterations + " heap_before=" + before
          + " heap_after=" + after + " growth_bytes=" + growth);
      // tolerance: 8MB of retained growth over the run indicates a leak in
      // the client (each request is ~1KB; transient garbage is collected
      // by retainedHeap()'s forced GCs)
      if (growth > 8L * 1024 * 1024) {
        System.err.println("FAIL: client memory growth " + growth + " bytes");
        System.exit(1);
      }
      System.out.println("PASS: MemoryGrowthTest");
    }
  }
}
