// One named input tensor (parity with reference
// src/java/src/main/java/triton/client/InferInput.java): typed setters
// produce little-endian wire bytes for the binary extension, or a
// shared-memory reference.
package clienttpu;

import java.nio.ByteBuffer;
import java.nio.ByteOrder;
import java.nio.charset.StandardCharsets;
import java.util.LinkedHashMap;
import java.util.Map;

public class InferInput {
  private final String name;
  private final long[] shape;
  private final DataType datatype;
  private byte[] data;
  private final Map<String, Object> parameters = new LinkedHashMap<>();

  public InferInput(String name, long[] shape, DataType datatype) {
    this.name = name;
    this.shape = shape.clone();
    this.datatype = datatype;
  }

  public String getName() {
    return name;
  }

  public long[] getShape() {
    return shape.clone();
  }

  public DataType getDatatype() {
    return datatype;
  }

  byte[] rawData() {
    return data;
  }

  Map<String, Object> parameters() {
    return parameters;
  }

  public void setData(int[] values) {
    ByteBuffer buf =
        ByteBuffer.allocate(values.length * 4).order(ByteOrder.LITTLE_ENDIAN);
    for (int v : values) buf.putInt(v);
    this.data = buf.array();
  }

  public void setData(long[] values) {
    ByteBuffer buf =
        ByteBuffer.allocate(values.length * 8).order(ByteOrder.LITTLE_ENDIAN);
    for (long v : values) buf.putLong(v);
    this.data = buf.array();
  }

  public void setData(float[] values) {
    ByteBuffer buf =
        ByteBuffer.allocate(values.length * 4).order(ByteOrder.LITTLE_ENDIAN);
    for (float v : values) buf.putFloat(v);
    this.data = buf.array();
  }

  public void setData(double[] values) {
    ByteBuffer buf =
        ByteBuffer.allocate(values.length * 8).order(ByteOrder.LITTLE_ENDIAN);
    for (double v : values) buf.putDouble(v);
    this.data = buf.array();
  }

  public void setData(byte[] rawBytes) {
    this.data = rawBytes.clone();
  }

  /** BYTES tensors: 4-byte little-endian length prefix per element. */
  public void setData(String[] values) {
    int total = 0;
    byte[][] encoded = new byte[values.length][];
    for (int i = 0; i < values.length; i++) {
      encoded[i] = values[i].getBytes(StandardCharsets.UTF_8);
      total += 4 + encoded[i].length;
    }
    ByteBuffer buf = ByteBuffer.allocate(total).order(ByteOrder.LITTLE_ENDIAN);
    for (byte[] e : encoded) {
      buf.putInt(e.length);
      buf.put(e);
    }
    this.data = buf.array();
  }

  public void setSharedMemory(String regionName, long byteSize, long offset) {
    parameters.put("shared_memory_region", regionName);
    parameters.put("shared_memory_byte_size", byteSize);
    if (offset != 0) parameters.put("shared_memory_offset", offset);
    data = null;
  }
}
