// KServe-v2 HTTP client over java.net.http (parity with reference
// src/java/src/main/java/triton/client/InferenceServerClient.java:59-221:
// health, metadata, model control, statistics, shared memory verbs, infer
// with the binary-tensor extension).
package clienttpu;

import java.io.IOException;
import java.net.URI;
import java.net.http.HttpClient;
import java.net.http.HttpRequest;
import java.net.http.HttpResponse;
import java.nio.charset.StandardCharsets;
import java.time.Duration;
import java.util.ArrayList;
import java.util.LinkedHashMap;
import java.util.List;
import java.util.Map;

public class InferenceServerClient implements AutoCloseable {
  private final String baseUrl;
  private final HttpClient http;
  private final Duration requestTimeout;

  public InferenceServerClient(String url) {
    this(url, Duration.ofSeconds(60), Duration.ofSeconds(60));
  }

  public InferenceServerClient(
      String url, Duration connectTimeout, Duration requestTimeout) {
    String base = url;
    if (!base.startsWith("http://") && !base.startsWith("https://")) {
      base = "http://" + base;
    }
    if (base.endsWith("/")) base = base.substring(0, base.length() - 1);
    this.baseUrl = base;
    this.requestTimeout = requestTimeout;
    this.http = HttpClient.newBuilder().connectTimeout(connectTimeout).build();
  }

  @Override
  public void close() {}

  // ---- health -------------------------------------------------------------

  public boolean isServerLive() throws InferenceException {
    return get("/v2/health/live").statusCode() == 200;
  }

  public boolean isServerReady() throws InferenceException {
    return get("/v2/health/ready").statusCode() == 200;
  }

  public boolean isModelReady(String modelName) throws InferenceException {
    return get("/v2/models/" + enc(modelName) + "/ready").statusCode() == 200;
  }

  // ---- metadata / control -------------------------------------------------

  public Map<String, Object> getServerMetadata() throws InferenceException {
    return json(get("/v2"));
  }

  public Map<String, Object> getModelMetadata(String modelName)
      throws InferenceException {
    return json(get("/v2/models/" + enc(modelName)));
  }

  public Map<String, Object> getModelConfig(String modelName)
      throws InferenceException {
    return json(get("/v2/models/" + enc(modelName) + "/config"));
  }

  @SuppressWarnings("unchecked")
  public List<Object> getModelRepositoryIndex() throws InferenceException {
    HttpResponse<byte[]> r = post("/v2/repository/index", new byte[0], null);
    check(r);
    try {
      return (List<Object>) Json.parse(
          new String(r.body(), StandardCharsets.UTF_8));
    } catch (ClassCastException e) {
      throw new InferenceException("malformed repository index", e);
    }
  }

  public void loadModel(String modelName) throws InferenceException {
    check(post("/v2/repository/models/" + enc(modelName) + "/load",
               new byte[0], null));
  }

  public void unloadModel(String modelName) throws InferenceException {
    check(post("/v2/repository/models/" + enc(modelName) + "/unload",
               new byte[0], null));
  }

  public Map<String, Object> getInferenceStatistics(String modelName)
      throws InferenceException {
    String path = modelName.isEmpty()
        ? "/v2/models/stats"
        : "/v2/models/" + enc(modelName) + "/stats";
    return json(get(path));
  }

  // ---- shared memory ------------------------------------------------------

  public void registerSystemSharedMemory(
      String name, String key, long byteSize) throws InferenceException {
    Map<String, Object> body = new LinkedHashMap<>();
    body.put("key", key);
    body.put("offset", 0L);
    body.put("byte_size", byteSize);
    check(post("/v2/systemsharedmemory/region/" + enc(name) + "/register",
               Json.write(body).getBytes(StandardCharsets.UTF_8), null));
  }

  public void unregisterSystemSharedMemory(String name)
      throws InferenceException {
    String path = name.isEmpty()
        ? "/v2/systemsharedmemory/unregister"
        : "/v2/systemsharedmemory/region/" + enc(name) + "/unregister";
    check(post(path, new byte[0], null));
  }

  public Map<String, Object> getSystemSharedMemoryStatus()
      throws InferenceException {
    HttpResponse<byte[]> r = get("/v2/systemsharedmemory/status");
    check(r);
    Map<String, Object> out = new LinkedHashMap<>();
    try {
      Object parsed =
          Json.parse(new String(r.body(), StandardCharsets.UTF_8));
      out.put("regions", parsed);
    } catch (InferenceException e) {
      throw e;
    }
    return out;
  }

  // ---- inference ----------------------------------------------------------

  public InferResult infer(
      String modelName, List<InferInput> inputs,
      List<InferRequestedOutput> outputs) throws InferenceException {
    return infer(modelName, "", inputs, outputs, "");
  }

  /**
   * Asynchronous inference (parity with the reference's HttpAsyncClient
   * transport, reference InferenceServerClient.java:59-221): the request
   * rides {@code HttpClient.sendAsync} on the client's executor, so many
   * requests can be in flight with no thread-per-request.  The future
   * completes with the parsed result or exceptionally with an
   * {@link InferenceException}.
   */
  public java.util.concurrent.CompletableFuture<InferResult> asyncInfer(
      String modelName, List<InferInput> inputs,
      List<InferRequestedOutput> outputs) {
    return asyncInfer(modelName, "", inputs, outputs, "");
  }

  public java.util.concurrent.CompletableFuture<InferResult> asyncInfer(
      String modelName, String modelVersion, List<InferInput> inputs,
      List<InferRequestedOutput> outputs, String requestId) {
    EncodedRequest encoded;
    try {
      encoded = encodeInfer(requestId, inputs, outputs);
    } catch (RuntimeException e) {
      return java.util.concurrent.CompletableFuture.failedFuture(
          new InferenceException("failed to encode request: " + e, e));
    }
    String path = "/v2/models/" + enc(modelName)
        + (modelVersion.isEmpty() ? "" : "/versions/" + modelVersion)
        + "/infer";
    HttpRequest.Builder builder =
        HttpRequest.newBuilder(URI.create(baseUrl + path))
            .timeout(requestTimeout)
            .POST(HttpRequest.BodyPublishers.ofByteArray(encoded.body))
            .header("Content-Type", "application/octet-stream")
            .header(
                "Inference-Header-Content-Length",
                Integer.toString(encoded.headerLength));
    return http.sendAsync(
            builder.build(), HttpResponse.BodyHandlers.ofByteArray())
        .thenApply(r -> {
          try {
            check(r);
            int respHeaderLen = 0;
            String lengthHeader = r.headers()
                .firstValue("inference-header-content-length").orElse("");
            if (!lengthHeader.isEmpty()) {
              respHeaderLen = Integer.parseInt(lengthHeader);
            }
            return new InferResult(r.body(), respHeaderLen);
          } catch (InferenceException e) {
            throw new java.util.concurrent.CompletionException(e);
          }
        });
  }

  public InferResult infer(
      String modelName, String modelVersion, List<InferInput> inputs,
      List<InferRequestedOutput> outputs, String requestId)
      throws InferenceException {
    // one request/response pipeline: the sync call is the async call joined
    try {
      return asyncInfer(modelName, modelVersion, inputs, outputs, requestId)
          .join();
    } catch (java.util.concurrent.CompletionException e) {
      if (e.getCause() instanceof InferenceException) {
        throw (InferenceException) e.getCause();
      }
      throw new InferenceException("infer failed: " + e.getCause(), e);
    }
  }

  /**
   * Binary-extension request body: JSON header + raw tensors appended.
   * Package-visible so GoldenWireTest can assert the encoding against the
   * Python-generated golden bytes (tests/golden/).
   */
  static final class EncodedRequest {
    final byte[] body;
    final int headerLength;

    EncodedRequest(byte[] body, int headerLength) {
      this.body = body;
      this.headerLength = headerLength;
    }
  }

  static EncodedRequest encodeInfer(
      String requestId, List<InferInput> inputs,
      List<InferRequestedOutput> outputs) {
    Map<String, Object> header = new LinkedHashMap<>();
    if (!requestId.isEmpty()) header.put("id", requestId);
    List<Object> ins = new ArrayList<>();
    List<byte[]> blobs = new ArrayList<>();
    for (InferInput input : inputs) {
      Map<String, Object> entry = new LinkedHashMap<>();
      entry.put("name", input.getName());
      entry.put("shape", input.getShape());
      entry.put("datatype", input.getDatatype().name());
      Map<String, Object> params = new LinkedHashMap<>(input.parameters());
      byte[] raw = input.rawData();
      if (raw != null) {
        params.put("binary_data_size", (long) raw.length);
        blobs.add(raw);
      }
      if (!params.isEmpty()) entry.put("parameters", params);
      ins.add(entry);
    }
    header.put("inputs", ins);
    if (outputs != null && !outputs.isEmpty()) {
      List<Object> outs = new ArrayList<>();
      for (InferRequestedOutput output : outputs) {
        Map<String, Object> entry = new LinkedHashMap<>();
        entry.put("name", output.getName());
        if (!output.parameters().isEmpty()) {
          entry.put("parameters", output.parameters());
        }
        outs.add(entry);
      }
      header.put("outputs", outs);
    }
    byte[] headerBytes = Json.write(header).getBytes(StandardCharsets.UTF_8);
    int total = headerBytes.length;
    for (byte[] b : blobs) total += b.length;
    byte[] body = new byte[total];
    int cursor = headerBytes.length;
    System.arraycopy(headerBytes, 0, body, 0, headerBytes.length);
    for (byte[] b : blobs) {
      System.arraycopy(b, 0, body, cursor, b.length);
      cursor += b.length;
    }
    return new EncodedRequest(body, headerBytes.length);
  }

  // ---- plumbing -----------------------------------------------------------

  private static String enc(String s) {
    return java.net.URLEncoder.encode(s, StandardCharsets.UTF_8)
        .replace("+", "%20");
  }

  private HttpResponse<byte[]> get(String path) throws InferenceException {
    try {
      HttpRequest request = HttpRequest.newBuilder(URI.create(baseUrl + path))
          .timeout(requestTimeout).GET().build();
      return http.send(request, HttpResponse.BodyHandlers.ofByteArray());
    } catch (IOException | InterruptedException e) {
      throw new InferenceException("GET " + path + " failed: " + e, e);
    }
  }

  private HttpResponse<byte[]> post(
      String path, byte[] body, Map<String, String> headers)
      throws InferenceException {
    try {
      HttpRequest.Builder builder =
          HttpRequest.newBuilder(URI.create(baseUrl + path))
              .timeout(requestTimeout)
              .POST(HttpRequest.BodyPublishers.ofByteArray(body));
      if (headers != null) {
        for (Map.Entry<String, String> h : headers.entrySet()) {
          builder.header(h.getKey(), h.getValue());
        }
      }
      return http.send(builder.build(), HttpResponse.BodyHandlers.ofByteArray());
    } catch (IOException | InterruptedException e) {
      throw new InferenceException("POST " + path + " failed: " + e, e);
    }
  }

  private Map<String, Object> json(HttpResponse<byte[]> r)
      throws InferenceException {
    check(r);
    return Json.parseObject(new String(r.body(), StandardCharsets.UTF_8));
  }

  private void check(HttpResponse<byte[]> r) throws InferenceException {
    if (r.statusCode() == 200) return;
    String body = new String(r.body(), StandardCharsets.UTF_8);
    String message = body;
    try {
      Object err = Json.parseObject(body).get("error");
      if (err != null) message = err.toString();
    } catch (InferenceException ignored) {
      // non-JSON error body: report it raw
    }
    throw new InferenceException(message, r.statusCode());
  }
}
