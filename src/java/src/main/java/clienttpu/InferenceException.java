// Error type carrying the server's HTTP status (parity with reference
// src/java/src/main/java/triton/client/InferenceException.java).
package clienttpu;

public class InferenceException extends Exception {
  private final int status;

  public InferenceException(String message) {
    this(message, 0);
  }

  public InferenceException(String message, int status) {
    super(message);
    this.status = status;
  }

  public InferenceException(String message, Throwable cause) {
    super(message, cause);
    this.status = 0;
  }

  /** HTTP status of the failed request, or 0 for client-side failures. */
  public int getStatus() {
    return status;
  }
}
