// Golden-wire verification for the Java client (no server needed): the
// committed bytes in tests/golden/ were produced by the PYTHON client and
// the in-process server (tests/test_golden_wire.py keeps them current);
// this main asserts the Java client speaks the same KServe HTTP binary
// protocol — so the Java path is machine-checked on any JDK-equipped
// machine, offline.  Reference protocol:
// src/java/src/main/java/triton/client/InferenceServerClient.java:59-221.
//
//   java clienttpu.GoldenWireTest <path-to-tests/golden>
//
// Checks:
//  1. encodeInfer() on the golden scenario yields a header JSON that is
//     CANONICALLY equal to the golden request header (two independent JSON
//     writers need not agree on key order/whitespace byte-for-byte) and a
//     binary section that IS byte-identical.
//  2. The golden response parses to the exact expected tensors
//     (OUTPUT0 = INPUT0+INPUT1, OUTPUT1 = INPUT0-INPUT1).
package clienttpu;

import java.nio.charset.StandardCharsets;
import java.nio.file.Files;
import java.nio.file.Path;
import java.util.ArrayList;
import java.util.Arrays;
import java.util.List;
import java.util.Map;
import java.util.TreeMap;

public final class GoldenWireTest {
  private static int checks = 0;
  private static int failures = 0;

  private static void check(boolean ok, String what) {
    checks++;
    if (!ok) {
      failures++;
      System.out.println("FAIL " + what);
    }
  }

  /** Canonical form: objects with sorted keys, no whitespace — makes two
   * independently ordered JSON headers comparable. */
  @SuppressWarnings("unchecked")
  private static String canonical(Object value) {
    if (value instanceof Map) {
      TreeMap<String, Object> sorted =
          new TreeMap<>((Map<String, Object>) value);
      StringBuilder sb = new StringBuilder("{");
      boolean first = true;
      for (Map.Entry<String, Object> e : sorted.entrySet()) {
        if (!first) sb.append(',');
        first = false;
        sb.append(Json.escape(e.getKey())).append(':')
            .append(canonical(e.getValue()));
      }
      return sb.append('}').toString();
    }
    if (value instanceof List) {
      StringBuilder sb = new StringBuilder("[");
      List<Object> list = (List<Object>) value;
      for (int i = 0; i < list.size(); i++) {
        if (i > 0) sb.append(',');
        sb.append(canonical(list.get(i)));
      }
      return sb.append(']').toString();
    }
    // numbers: golden (python) writes ints; Json.parse yields Long — align
    // any integral Double to Long so 64 == 64.0 canonically
    if (value instanceof Double && ((Double) value) == Math.floor((Double) value)
        && !((Double) value).isInfinite()) {
      return Long.toString(((Double) value).longValue());
    }
    if (value instanceof long[]) {
      List<Object> boxed = new ArrayList<>();
      for (long v : (long[]) value) boxed.add(v);
      return canonical(boxed);
    }
    return Json.write(value);
  }

  public static void main(String[] args) throws Exception {
    Path golden = Path.of(args.length > 0 ? args[0] : "tests/golden");
    byte[] goldenRequest =
        Files.readAllBytes(golden.resolve("kserve_infer_request.bin"));
    byte[] goldenResponse =
        Files.readAllBytes(golden.resolve("kserve_infer_response.bin"));
    Map<String, Object> meta = Json.parseObject(Files.readString(
        golden.resolve("kserve_infer.meta.json"), StandardCharsets.UTF_8));
    int reqHeaderLen = ((Long) meta.get("request_header_length")).intValue();
    int respHeaderLen = ((Long) meta.get("response_header_length")).intValue();

    // -- 1. request encoding matches the Python client's bytes ------------
    int[] in0 = new int[16];
    int[] in1 = new int[16];
    for (int i = 0; i < 16; i++) {
      in0[i] = i;
      in1[i] = i + 1;
    }
    InferInput i0 = new InferInput("INPUT0", new long[] {1, 16}, DataType.INT32);
    i0.setData(in0);
    InferInput i1 = new InferInput("INPUT1", new long[] {1, 16}, DataType.INT32);
    i1.setData(in1);
    List<InferRequestedOutput> outs = Arrays.asList(
        new InferRequestedOutput("OUTPUT0", true, 0),
        new InferRequestedOutput("OUTPUT1", true, 0));
    InferenceServerClient.EncodedRequest encoded =
        InferenceServerClient.encodeInfer(
            "golden-1", Arrays.asList(i0, i1), outs);

    String goldenHeader =
        new String(goldenRequest, 0, reqHeaderLen, StandardCharsets.UTF_8);
    String javaHeader = new String(
        encoded.body, 0, encoded.headerLength, StandardCharsets.UTF_8);
    check(
        canonical(Json.parseObject(goldenHeader))
            .equals(canonical(Json.parseObject(javaHeader))),
        "request header JSON canonically equal\n  golden: " + goldenHeader
            + "\n  java:   " + javaHeader);
    byte[] goldenBinary = Arrays.copyOfRange(
        goldenRequest, reqHeaderLen, goldenRequest.length);
    byte[] javaBinary = Arrays.copyOfRange(
        encoded.body, encoded.headerLength, encoded.body.length);
    check(Arrays.equals(goldenBinary, javaBinary),
        "request binary section byte-identical");

    // -- 2. golden response parses to the exact tensors -------------------
    InferResult result = new InferResult(goldenResponse, respHeaderLen);
    check("simple".equals(result.getModelName()), "response model name");
    check("golden-1".equals(result.getId()), "response id echo");
    int[] sum = result.getOutputAsInt("OUTPUT0");
    int[] diff = result.getOutputAsInt("OUTPUT1");
    check(sum.length == 16 && diff.length == 16, "output lengths");
    boolean valuesOk = true;
    for (int i = 0; i < 16; i++) {
      valuesOk &= sum[i] == in0[i] + in1[i] && diff[i] == in0[i] - in1[i];
    }
    check(valuesOk, "response tensor values (sum/diff)");
    check(Arrays.equals(
              result.getShape("OUTPUT0"), new long[] {1, 16}),
        "response shape");

    System.out.println(checks + " checks, " + failures + " failures");
    if (failures == 0) {
      System.out.println("PASS: java golden wire");
      return;
    }
    System.exit(1);
  }
}
