// Native image-classification client — the C++ analog of the reference's
// flagship image_client.cc (reference src/c++/examples/image_client.cc:
// 85-128 preprocess + classify via the classification extension), without
// the OpenCV dependency: reads a raw float32 CHW file or synthesizes an
// input, sizes it from the model's metadata, and prints the top-N
// "score (index) = label" lines.
//
// Usage: image_client [-u host:port] [-m model] [-c top_n] [raw_f32_file]
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <iostream>
#include <random>
#include <string>
#include <vector>

#include "grpc_client.h"

namespace tc = ctpu;

#define FAIL_IF_ERR(X, MSG)                                 \
  do {                                                      \
    tc::Error err__ = (X);                                  \
    if (!err__.IsOk()) {                                    \
      fprintf(stderr, "error: %s: %s\n", (MSG),            \
              err__.Message().c_str());                     \
      return 1;                                             \
    }                                                       \
  } while (false)

int
main(int argc, char** argv)
{
  std::string url = "localhost:8001";
  std::string model = "classifier";
  int classes = 2;
  std::string file;
  for (int i = 1; i < argc; ++i) {
    if (!std::strcmp(argv[i], "-u") && i + 1 < argc) url = argv[++i];
    else if (!std::strcmp(argv[i], "-m") && i + 1 < argc) model = argv[++i];
    else if (!std::strcmp(argv[i], "-c") && i + 1 < argc)
      classes = std::atoi(argv[++i]);
    else if (argv[i][0] != '-') file = argv[i];
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url), "create client");

  // size the input tensor from the model's metadata (image_client.cc does
  // the same via ParseModel)
  inference::ModelMetadataResponse meta;
  FAIL_IF_ERR(client->ModelMetadata(&meta, model), "model metadata");
  if (meta.inputs_size() != 1 || meta.outputs_size() != 1) {
    std::cerr << "error: expected a single-input single-output classifier"
              << std::endl;
    return 1;
  }
  const auto& spec = meta.inputs(0);
  std::vector<int64_t> dims;
  size_t elements = 1;
  for (int64_t d : spec.shape()) {
    dims.push_back(d < 0 ? 1 : d);
    elements *= static_cast<size_t>(dims.back());
  }
  std::cout << "model " << model << ": input " << spec.name() << " x"
            << elements << " " << spec.datatype() << std::endl;

  std::vector<float> image(elements);
  if (!file.empty()) {
    std::ifstream in(file, std::ios::binary);
    if (!in ||
        !in.read(
            reinterpret_cast<char*>(image.data()),
            elements * sizeof(float))) {
      std::cerr << "error: cannot read " << elements * sizeof(float)
                << " bytes from " << file << std::endl;
      return 1;
    }
  } else {
    std::mt19937 rng(0);
    std::normal_distribution<float> dist(0.f, 1.f);
    for (float& v : image) v = dist(rng);
  }

  tc::InferInput input(spec.name(), dims, spec.datatype());
  input.AppendRaw(
      reinterpret_cast<const uint8_t*>(image.data()),
      image.size() * sizeof(float));
  tc::InferRequestedOutput output(meta.outputs(0).name(), classes);

  tc::InferOptions options(model);
  tc::InferResult* result = nullptr;
  FAIL_IF_ERR(
      client->Infer(&result, options, {&input}, {&output}),
      "inference failed");
  std::unique_ptr<tc::InferResult> owner(result);

  // classification extension: top-N "score:index[:label]" strings
  std::vector<std::string> entries;
  FAIL_IF_ERR(
      result->StringData(meta.outputs(0).name(), &entries), "classification");
  if (static_cast<int>(entries.size()) != classes) {
    std::cerr << "error: wanted top-" << classes << ", got "
              << entries.size() << std::endl;
    return 1;
  }
  double prev = 1e30;
  for (const auto& entry : entries) {
    const size_t c1 = entry.find(':');
    if (c1 == std::string::npos) {
      std::cerr << "error: malformed classification entry '" << entry << "'"
                << std::endl;
      return 1;
    }
    const size_t c2 = entry.find(':', c1 + 1);
    double score = 0.0;
    try {
      score = std::stod(entry.substr(0, c1));
    }
    catch (...) {
      std::cerr << "error: non-numeric score in '" << entry << "'"
                << std::endl;
      return 1;
    }
    const std::string idx = entry.substr(c1 + 1, c2 - c1 - 1);
    const std::string label =
        c2 == std::string::npos ? "" : entry.substr(c2 + 1);
    std::cout << "  " << score << " (" << idx << ") = " << label
              << std::endl;
    if (score > prev) {
      std::cerr << "error: classification not sorted" << std::endl;
      return 1;
    }
    prev = score;
  }
  std::cout << "PASS: image_client (native)" << std::endl;
  return 0;
}
