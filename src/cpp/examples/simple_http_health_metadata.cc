// Native HTTP health + metadata example: liveness/readiness probes, server
// and model metadata, repository index (parity with reference
// src/c++/examples/simple_http_health_metadata.cc).
//
// Usage: simple_http_health_metadata [-u host:port]
#include <cstdio>
#include <cstring>
#include <iostream>
#include <string>

#include "http_client.h"

namespace tc = ctpu;

#define FAIL_IF_ERR(X, MSG)                                 \
  do {                                                      \
    tc::Error err__ = (X);                                  \
    if (!err__.IsOk()) {                                    \
      fprintf(stderr, "error: %s: %s\n", (MSG),            \
              err__.Message().c_str());                     \
      return 1;                                             \
    }                                                       \
  } while (false)

int
main(int argc, char** argv)
{
  std::string url = "localhost:8000";
  for (int i = 1; i < argc - 1; ++i) {
    if (!std::strcmp(argv[i], "-u")) url = argv[++i];
  }

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerHttpClient::Create(&client, url), "create client");

  bool live = false, ready = false, model_ready = false;
  FAIL_IF_ERR(client->IsServerLive(&live), "server live");
  FAIL_IF_ERR(client->IsServerReady(&ready), "server ready");
  FAIL_IF_ERR(client->IsModelReady(&model_ready, "simple"), "model ready");
  std::cout << "live=" << live << " ready=" << ready
            << " simple_ready=" << model_ready << std::endl;
  if (!live || !ready || !model_ready) {
    std::cerr << "error: server/model not ready" << std::endl;
    return 1;
  }

  tc::json::ValuePtr meta;
  FAIL_IF_ERR(client->ServerMetadata(&meta), "server metadata");
  const tc::json::Value* name = meta->Get("name");
  if (name == nullptr || name->AsString().empty()) {
    std::cerr << "error: empty server name" << std::endl;
    return 1;
  }
  std::cout << "server: " << name->AsString() << std::endl;

  tc::json::ValuePtr model_meta;
  FAIL_IF_ERR(
      client->ModelMetadata(&model_meta, "simple"), "model metadata");
  const tc::json::Value* model_name = model_meta->Get("name");
  if (model_name == nullptr || model_name->AsString() != "simple") {
    std::cerr << "error: model metadata name mismatch" << std::endl;
    return 1;
  }

  tc::json::ValuePtr index;
  FAIL_IF_ERR(client->ModelRepositoryIndex(&index), "repository index");
  std::cout << "repository index has " << index->arr.size() << " models"
            << std::endl;
  if (index->arr.empty()) {
    std::cerr << "error: empty repository index" << std::endl;
    return 1;
  }
  std::cout << "PASS: simple_http_health_metadata (native)" << std::endl;
  return 0;
}
