// Asynchronous gRPC inference: a burst of AsyncInfer requests completed by
// the connection's reactor thread — no thread-per-request (parity with
// reference src/c++/examples/simple_grpc_async_infer_client.cc).
//
// Usage: simple_grpc_async_infer_client [-u host:port] [-n count]
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "grpc_client.h"

namespace tc = ctpu;

int
main(int argc, char** argv)
{
  std::string url = "localhost:8001";
  int count = 16;
  for (int i = 1; i < argc - 1; ++i) {
    if (!std::strcmp(argv[i], "-u")) url = argv[++i];
    if (!std::strcmp(argv[i], "-n")) count = std::atoi(argv[++i]);
  }
  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  tc::Error err = tc::InferenceServerGrpcClient::Create(&client, url);
  if (!err.IsOk()) {
    fprintf(stderr, "error: %s\n", err.Message().c_str());
    return 1;
  }
  std::vector<int32_t> input0(16), input1(16);
  for (int i = 0; i < 16; ++i) {
    input0[i] = i;
    input1[i] = 2;
  }
  tc::InferInput in0("INPUT0", {1, 16}, "INT32");
  tc::InferInput in1("INPUT1", {1, 16}, "INT32");
  in0.AppendRaw(reinterpret_cast<const uint8_t*>(input0.data()),
                input0.size() * sizeof(int32_t));
  in1.AppendRaw(reinterpret_cast<const uint8_t*>(input1.data()),
                input1.size() * sizeof(int32_t));
  tc::InferOptions options("simple");

  std::mutex mu;
  std::condition_variable cv;
  int done = 0, good = 0;
  for (int r = 0; r < count; ++r) {
    err = client->AsyncInfer(
        [&](tc::InferResultPtr result) {
          std::lock_guard<std::mutex> lk(mu);
          ++done;
          const uint8_t* data = nullptr;
          size_t nbytes = 0;
          if (result->RequestStatus().IsOk() &&
              result->RawData("OUTPUT0", &data, &nbytes).IsOk() &&
              reinterpret_cast<const int32_t*>(data)[3] == 5) {
            ++good;
          }
          cv.notify_all();
        },
        options, {&in0, &in1});
    if (!err.IsOk()) {
      fprintf(stderr, "error: submit: %s\n", err.Message().c_str());
      return 1;
    }
  }
  std::unique_lock<std::mutex> lk(mu);
  cv.wait_for(lk, std::chrono::seconds(60), [&] { return done == count; });
  if (good != count) {
    fprintf(stderr, "error: %d/%d correct async completions\n", good, count);
    return 1;
  }
  printf("PASS : grpc_async_infer x%d\n", count);
  return 0;
}
