// Native TPU shared-memory example — the C++ face of the framework's
// CUDA-shm replacement (SURVEY.md §3.5 north star; Python twin:
// examples/simple_grpc_tpushm_client.py): allocate TPU regions through the
// libctpushm C ABI, hand the serialized raw handle to the server over
// gRPC, run infer with inputs and outputs referenced by region, and read
// the results back through the region window — tensor bytes never ride the
// request.
//
// Usage: simple_grpc_tpushm_client [-u host:port]
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <iostream>
#include <string>
#include <vector>

#include "grpc_client.h"

// libctpushm C ABI (src/cpp/shm/ctpushm.cc — linked into this binary; the
// Python wheel loads the same code as libctpushm.so)
#include "../shm/ctpushm.h"

// shm windows outlive the process (POSIX): destroy on EVERY exit path so
// failed runs don't accumulate /dev/shm/tpushm-* objects
struct RegionGuard {
  void* region;
  ~RegionGuard() {
    if (region != nullptr) TpuHbmRegionDestroy(region);
  }
};

namespace tc = ctpu;

#define FAIL_IF_ERR(X, MSG)                                 \
  do {                                                      \
    tc::Error err__ = (X);                                  \
    if (!err__.IsOk()) {                                    \
      fprintf(stderr, "error: %s: %s\n", (MSG),            \
              err__.Message().c_str());                     \
      return 1;                                             \
    }                                                       \
  } while (false)

#define FAIL_IF_SHM(X, MSG)                                 \
  do {                                                      \
    if ((X) != 0) {                                         \
      fprintf(stderr, "error: %s: %s\n", (MSG),            \
              TpuHbmLastError());                           \
      return 1;                                             \
    }                                                       \
  } while (false)

int
main(int argc, char** argv)
{
  std::string url = "localhost:8001";
  for (int i = 1; i < argc - 1; ++i) {
    if (!std::strcmp(argv[i], "-u")) url = argv[++i];
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url), "create client");

  constexpr uint64_t kTensorBytes = 16 * sizeof(int32_t);
  void* in_region = TpuHbmRegionCreate(2 * kTensorBytes, 0);
  void* out_region = TpuHbmRegionCreate(2 * kTensorBytes, 0);
  RegionGuard in_guard{in_region}, out_guard{out_region};
  if (in_region == nullptr || out_region == nullptr) {
    fprintf(stderr, "error: region create: %s\n", TpuHbmLastError());
    return 1;
  }

  int32_t input0[16], input1[16];
  for (int i = 0; i < 16; ++i) {
    input0[i] = i;
    input1[i] = 5;
  }
  FAIL_IF_SHM(
      TpuHbmWrite(in_region, 0, input0, kTensorBytes), "write INPUT0");
  FAIL_IF_SHM(
      TpuHbmWrite(in_region, kTensorBytes, input1, kTensorBytes),
      "write INPUT1");

  // GetRawHandle returns the JSON length (>0) on success, negative on error
  char in_handle[512], out_handle[512];
  if (TpuHbmGetRawHandle(in_region, in_handle, sizeof(in_handle)) <= 0 ||
      TpuHbmGetRawHandle(out_region, out_handle, sizeof(out_handle)) <= 0) {
    fprintf(stderr, "error: raw handle: %s\n", TpuHbmLastError());
    return 1;
  }

  client->UnregisterTpuSharedMemory();
  FAIL_IF_ERR(
      client->RegisterTpuSharedMemory(
          "tpu_in_cc", in_handle, 0, 2 * kTensorBytes),
      "register input region");
  FAIL_IF_ERR(
      client->RegisterTpuSharedMemory(
          "tpu_out_cc", out_handle, 0, 2 * kTensorBytes),
      "register output region");

  tc::InferInput in0("INPUT0", {1, 16}, "INT32");
  tc::InferInput in1("INPUT1", {1, 16}, "INT32");
  in0.SetSharedMemory("tpu_in_cc", kTensorBytes, 0);
  in1.SetSharedMemory("tpu_in_cc", kTensorBytes, kTensorBytes);
  tc::InferRequestedOutput out0("OUTPUT0"), out1("OUTPUT1");
  out0.SetSharedMemory("tpu_out_cc", kTensorBytes, 0);
  out1.SetSharedMemory("tpu_out_cc", kTensorBytes, kTensorBytes);

  tc::InferOptions options("simple");
  tc::InferResult* result = nullptr;
  FAIL_IF_ERR(
      client->Infer(&result, options, {&in0, &in1}, {&out0, &out1}),
      "inference failed");
  std::unique_ptr<tc::InferResult> owner(result);

  int32_t sum[16], diff[16];
  FAIL_IF_SHM(TpuHbmRead(out_region, 0, sum, kTensorBytes), "read OUTPUT0");
  FAIL_IF_SHM(
      TpuHbmRead(out_region, kTensorBytes, diff, kTensorBytes),
      "read OUTPUT1");
  for (int i = 0; i < 16; ++i) {
    std::cout << input0[i] << " + " << input1[i] << " = " << sum[i]
              << std::endl;
    if (sum[i] != input0[i] + input1[i] ||
        diff[i] != input0[i] - input1[i]) {
      std::cerr << "error: incorrect result in TPU region" << std::endl;
      return 1;
    }
  }

  FAIL_IF_ERR(
      client->UnregisterTpuSharedMemory("tpu_in_cc"), "unregister input");
  FAIL_IF_ERR(
      client->UnregisterTpuSharedMemory("tpu_out_cc"), "unregister output");

  std::cout << "PASS: simple_grpc_tpushm_client (native)" << std::endl;
  return 0;
}
