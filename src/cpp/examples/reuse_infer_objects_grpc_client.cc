// Native gRPC object-reuse example: one InferInput/InferRequestedOutput set
// serves many requests via Reset + AppendRaw (reference
// src/c++/examples/reuse_infer_objects_client.cc — allocation-free steady
// state is the point).
//
// Usage: reuse_infer_objects_grpc_client [-u host:port]
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <iostream>
#include <string>
#include <vector>

#include "grpc_client.h"

namespace tc = ctpu;

#define FAIL_IF_ERR(X, MSG)                                 \
  do {                                                      \
    tc::Error err__ = (X);                                  \
    if (!err__.IsOk()) {                                    \
      fprintf(stderr, "error: %s: %s\n", (MSG),            \
              err__.Message().c_str());                     \
      return 1;                                             \
    }                                                       \
  } while (false)

int
main(int argc, char** argv)
{
  std::string url = "localhost:8001";
  for (int i = 1; i < argc - 1; ++i) {
    if (!std::strcmp(argv[i], "-u")) url = argv[++i];
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url), "create client");

  tc::InferInput in0("INPUT0", {1, 16}, "INT32");
  tc::InferInput in1("INPUT1", {1, 16}, "INT32");
  tc::InferRequestedOutput out0("OUTPUT0"), out1("OUTPUT1");
  tc::InferOptions options("simple");

  for (int round = 0; round < 5; ++round) {
    std::vector<int32_t> input0(16), input1(16);
    for (int i = 0; i < 16; ++i) {
      input0[i] = round * 100 + i;
      input1[i] = round;
    }
    // Reset clears buffers and shm bindings; the objects themselves persist
    FAIL_IF_ERR(in0.Reset(), "reset INPUT0");
    FAIL_IF_ERR(in1.Reset(), "reset INPUT1");
    FAIL_IF_ERR(
        in0.AppendRaw(
            reinterpret_cast<const uint8_t*>(input0.data()),
            input0.size() * sizeof(int32_t)),
        "append INPUT0");
    FAIL_IF_ERR(
        in1.AppendRaw(
            reinterpret_cast<const uint8_t*>(input1.data()),
            input1.size() * sizeof(int32_t)),
        "append INPUT1");

    tc::InferResult* result = nullptr;
    FAIL_IF_ERR(
        client->Infer(&result, options, {&in0, &in1}, {&out0, &out1}),
        "inference failed");
    std::unique_ptr<tc::InferResult> owner(result);
    const uint8_t* data = nullptr;
    size_t size = 0;
    FAIL_IF_ERR(result->RawData("OUTPUT0", &data, &size), "OUTPUT0");
    const int32_t* sum = reinterpret_cast<const int32_t*>(data);
    for (int i = 0; i < 16; ++i) {
      if (sum[i] != input0[i] + input1[i]) {
        std::cerr << "error: wrong sum in round " << round << std::endl;
        return 1;
      }
    }
    std::cout << "round " << round << " ok (sum[0]=" << sum[0] << ")"
              << std::endl;
  }
  std::cout << "PASS: reuse_infer_objects_grpc_client (native)" << std::endl;
  return 0;
}
