// Native gRPC custom-arguments example — per-request metadata headers and
// request ids (the reference's simple_grpc_custom_args_client.cc exercises
// per-call channel/request arguments): the custom headers ride the HTTP/2
// HEADERS frame; a request id set in InferOptions must round-trip through
// the server's response.
//
// Usage: simple_grpc_custom_args_client [-u host:port]
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <iostream>
#include <string>
#include <vector>

#include "grpc_client.h"

namespace tc = ctpu;

#define FAIL_IF_ERR(X, MSG)                                 \
  do {                                                      \
    tc::Error err__ = (X);                                  \
    if (!err__.IsOk()) {                                    \
      fprintf(stderr, "error: %s: %s\n", (MSG),            \
              err__.Message().c_str());                     \
      return 1;                                             \
    }                                                       \
  } while (false)

int
main(int argc, char** argv)
{
  std::string url = "localhost:8001";
  for (int i = 1; i < argc - 1; ++i) {
    if (!std::strcmp(argv[i], "-u")) url = argv[++i];
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url), "create client");

  std::vector<int32_t> input0(16), input1(16);
  tc::InferInput in0("INPUT0", {1, 16}, "INT32");
  tc::InferInput in1("INPUT1", {1, 16}, "INT32");
  for (int i = 0; i < 16; ++i) {
    input0[i] = i;
    input1[i] = 100;
  }
  in0.AppendRaw(
      reinterpret_cast<const uint8_t*>(input0.data()), 16 * sizeof(int32_t));
  in1.AppendRaw(
      reinterpret_cast<const uint8_t*>(input1.data()), 16 * sizeof(int32_t));

  tc::InferOptions options("simple");
  options.request_id = "custom-args-77";
  options.priority = 3;  // request parameter, visible server-side
  const std::vector<std::pair<std::string, std::string>> headers = {
      {"x-example-trace", "abc123"},
      {"x-tenant", "examples"},
  };
  tc::InferResult* result = nullptr;
  FAIL_IF_ERR(
      client->Infer(&result, options, {&in0, &in1}, {}, headers),
      "inference failed");
  std::unique_ptr<tc::InferResult> owner(result);

  if (result->Id() != "custom-args-77") {
    std::cerr << "error: request id did not round-trip (got '"
              << result->Id() << "')" << std::endl;
    return 1;
  }
  const uint8_t* data = nullptr;
  size_t nbytes = 0;
  FAIL_IF_ERR(result->RawData("OUTPUT0", &data, &nbytes), "OUTPUT0");
  const int32_t* sum = reinterpret_cast<const int32_t*>(data);
  for (int i = 0; i < 16; ++i) {
    if (sum[i] != input0[i] + input1[i]) {
      std::cerr << "error: incorrect result" << std::endl;
      return 1;
    }
  }
  std::cout << "request id round-tripped with custom headers" << std::endl;
  std::cout << "PASS: simple_grpc_custom_args_client (native)" << std::endl;
  return 0;
}
