// Explicit model control over the native gRPC client (parity with
// reference src/c++/examples/simple_grpc_model_control.cc): unload,
// observe readiness, reload, list the repository index.
//
// Usage: simple_grpc_model_control [-u host:port]
#include <cstdio>
#include <cstring>
#include <string>

#include "grpc_client.h"

namespace tc = ctpu;

#define FAIL_IF_ERR(X, MSG)                                                \
  do {                                                                     \
    tc::Error err__ = (X);                                                 \
    if (!err__.IsOk()) {                                                   \
      fprintf(stderr, "error: %s: %s\n", (MSG), err__.Message().c_str());  \
      return 1;                                                            \
    }                                                                      \
  } while (false)

int
main(int argc, char** argv)
{
  std::string url = "localhost:8001";
  for (int i = 1; i < argc - 1; ++i)
    if (!std::strcmp(argv[i], "-u")) url = argv[++i];
  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(tc::InferenceServerGrpcClient::Create(&client, url), "create");

  const std::string model = "identity";
  FAIL_IF_ERR(client->UnloadModel(model), "unload");
  bool ready = true;
  client->IsModelReady(&ready, model);
  if (ready) {
    fprintf(stderr, "error: model still ready after unload\n");
    return 1;
  }
  FAIL_IF_ERR(client->LoadModel(model), "load");
  FAIL_IF_ERR(client->IsModelReady(&ready, model), "ready");
  if (!ready) {
    fprintf(stderr, "error: model not ready after load\n");
    return 1;
  }
  inference::RepositoryIndexResponse index;
  FAIL_IF_ERR(client->ModelRepositoryIndex(&index), "index");
  printf("repository holds %d models\n", index.models_size());
  printf("PASS : grpc_model_control\n");
  return 0;
}
