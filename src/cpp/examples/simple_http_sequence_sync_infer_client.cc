// Native HTTP stateful-sequence example over unary requests — the HTTP
// twin of simple_grpc_sequence_sync_infer_client.cc (reference
// src/c++/examples/simple_http_sequence_sync_infer_client.cc): two
// interleaved sequences, one numeric and one string correlation id, each
// accumulating independently on the server.
//
// Usage: simple_http_sequence_sync_infer_client [-u host:port]
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <iostream>
#include <string>
#include <vector>

#include "http_client.h"

namespace tc = ctpu;

#define FAIL_IF_ERR(X, MSG)                                 \
  do {                                                      \
    tc::Error err__ = (X);                                  \
    if (!err__.IsOk()) {                                    \
      fprintf(stderr, "error: %s: %s\n", (MSG),            \
              err__.Message().c_str());                     \
      return 1;                                             \
    }                                                       \
  } while (false)

static int
SendStep(
    tc::InferenceServerHttpClient* client, uint64_t seq_id,
    const std::string& seq_id_str, int step, int last_step, int32_t value,
    int32_t* accumulated)
{
  tc::InferInput input("INPUT", {1}, "INT32");
  input.AppendRaw(reinterpret_cast<const uint8_t*>(&value), sizeof(value));
  tc::InferOptions options("simple_sequence");
  options.sequence_id = seq_id;
  options.sequence_id_str = seq_id_str;
  options.sequence_start = (step == 0);
  options.sequence_end = (step == last_step);
  tc::InferResultPtr result;
  tc::Error err = client->Infer(&result, options, {&input});
  if (!err.IsOk()) {
    fprintf(stderr, "error: sequence step: %s\n", err.Message().c_str());
    return -1;
  }
  const uint8_t* data = nullptr;
  size_t size = 0;
  err = result->RawData("OUTPUT", &data, &size);
  if (!err.IsOk() || size != sizeof(int32_t)) {
    fprintf(stderr, "error: sequence output\n");
    return -1;
  }
  *accumulated = *reinterpret_cast<const int32_t*>(data);
  return 0;
}

int
main(int argc, char** argv)
{
  std::string url = "localhost:8000";
  for (int i = 1; i < argc - 1; ++i) {
    if (!std::strcmp(argv[i], "-u")) url = argv[++i];
  }

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerHttpClient::Create(&client, url), "create client");

  const int32_t values[3] = {1, 2, 4};
  int32_t sum_numeric = 0, sum_string = 0;
  int32_t acc = 0;
  for (int step = 0; step < 3; ++step) {
    sum_numeric += values[step];
    if (SendStep(client.get(), 31337, "", step, 2, values[step], &acc) != 0)
      return 1;
    std::cout << "seq 31337 step " << step << ": " << acc << std::endl;
    if (acc != sum_numeric) {
      std::cerr << "error: numeric-id accumulator mismatch" << std::endl;
      return 1;
    }
    sum_string += 10 * values[step];
    if (SendStep(
            client.get(), 0, "http-seq-str", step, 2, 10 * values[step],
            &acc) != 0)
      return 1;
    std::cout << "seq 'http-seq-str' step " << step << ": " << acc
              << std::endl;
    if (acc != sum_string) {
      std::cerr << "error: string-id accumulator mismatch" << std::endl;
      return 1;
    }
  }
  std::cout << "PASS: simple_http_sequence_sync_infer_client (native)"
            << std::endl;
  return 0;
}
