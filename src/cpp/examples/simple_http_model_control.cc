// Native HTTP model-control example: explicit unload/load cycle with
// readiness probes between steps (parity with reference
// src/c++/examples/simple_http_model_control.cc).
//
// Usage: simple_http_model_control [-u host:port]
#include <cstdio>
#include <cstring>
#include <iostream>
#include <string>

#include "http_client.h"

namespace tc = ctpu;

#define FAIL_IF_ERR(X, MSG)                                 \
  do {                                                      \
    tc::Error err__ = (X);                                  \
    if (!err__.IsOk()) {                                    \
      fprintf(stderr, "error: %s: %s\n", (MSG),            \
              err__.Message().c_str());                     \
      return 1;                                             \
    }                                                       \
  } while (false)

int
main(int argc, char** argv)
{
  std::string url = "localhost:8000";
  std::string model = "simple";
  for (int i = 1; i < argc - 1; ++i) {
    if (!std::strcmp(argv[i], "-u")) url = argv[++i];
    if (!std::strcmp(argv[i], "-m")) model = argv[++i];
  }

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerHttpClient::Create(&client, url), "create client");

  bool ready = false;
  FAIL_IF_ERR(client->IsModelReady(&ready, model), "initial readiness");
  std::cout << model << " initially ready=" << ready << std::endl;
  if (!ready) {
    std::cerr << "error: model must start loaded" << std::endl;
    return 1;
  }

  FAIL_IF_ERR(client->UnloadModel(model), "unload");
  tc::Error e = client->IsModelReady(&ready, model);
  // unloaded: server answers ready=false or NOT_FOUND; both are "not ready"
  if (e.IsOk() && ready) {
    std::cerr << "error: model still ready after unload" << std::endl;
    return 1;
  }
  std::cout << model << " unloaded" << std::endl;

  FAIL_IF_ERR(client->LoadModel(model), "load");
  FAIL_IF_ERR(client->IsModelReady(&ready, model), "readiness after load");
  if (!ready) {
    std::cerr << "error: model not ready after load" << std::endl;
    return 1;
  }
  std::cout << model << " reloaded and ready" << std::endl;
  std::cout << "PASS: simple_http_model_control (native)" << std::endl;
  return 0;
}
