// Sequence inference over one bidi gRPC stream: two interleaved sequences
// share a ModelStreamInfer stream; the server's stateful accumulator returns
// the running sum per sequence (parity with reference
// src/c++/examples/simple_grpc_sequence_stream_infer_client.cc:168-260).
//
// Usage: simple_grpc_sequence_stream_infer_client [-u host:port]
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

#include "grpc_client.h"

namespace tc = ctpu;

#define FAIL_IF_ERR(X, MSG)                                 \
  do {                                                      \
    tc::Error err__ = (X);                                  \
    if (!err__.IsOk()) {                                    \
      fprintf(stderr, "error: %s: %s\n", (MSG),            \
              err__.Message().c_str());                     \
      return 1;                                             \
    }                                                       \
  } while (false)

int
main(int argc, char** argv)
{
  std::string url = "localhost:8001";
  for (int i = 1; i < argc - 1; ++i)
    if (!std::strcmp(argv[i], "-u")) url = argv[++i];

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url), "create client");

  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::vector<int32_t>> results;  // request id -> sums
  size_t expected = 0;

  FAIL_IF_ERR(
      client->StartStream(
          [&](tc::InferResultPtr result) {
            std::lock_guard<std::mutex> lk(mu);
            if (!result->RequestStatus().IsOk()) {
              fprintf(stderr, "stream error: %s\n",
                      result->RequestStatus().Message().c_str());
              results["<error>"].push_back(-1);
            } else {
              const uint8_t* data = nullptr;
              size_t nbytes = 0;
              if (result->RawData("OUTPUT", &data, &nbytes).IsOk())
                results[result->Id()].push_back(
                    *reinterpret_cast<const int32_t*>(data));
            }
            cv.notify_all();
          }),
      "start stream");

  // Two sequences, interleaved on the same stream: ids 100 (values 1..4)
  // and 200 (values 10..40 by 10).
  const int steps = 4;
  for (int step = 0; step < steps; ++step) {
    for (const uint64_t seq_id : {100ull, 200ull}) {
      int32_t value = (step + 1) * (seq_id == 100 ? 1 : 10);
      tc::InferInput input("INPUT", {1}, "INT32");
      input.AppendRaw(
          reinterpret_cast<const uint8_t*>(&value), sizeof(value));
      tc::InferOptions options("simple_sequence");
      options.sequence_id = seq_id;
      options.sequence_start = (step == 0);
      options.sequence_end = (step == steps - 1);
      options.request_id =
          std::to_string(seq_id) + "_" + std::to_string(step);
      FAIL_IF_ERR(
          client->AsyncStreamInfer(options, {&input}), "stream infer");
      ++expected;
    }
  }

  {
    std::unique_lock<std::mutex> lk(mu);
    cv.wait_for(lk, std::chrono::seconds(30), [&] {
      size_t n = 0;
      for (auto& kv : results) n += kv.second.size();
      return n >= expected;
    });
  }
  FAIL_IF_ERR(client->StopStream(), "stop stream");

  // Validate the running sums: seq 100 -> 1,3,6,10; seq 200 -> 10,30,60,100.
  int32_t acc100 = 0, acc200 = 0;
  for (int step = 0; step < steps; ++step) {
    acc100 += step + 1;
    acc200 += (step + 1) * 10;
    const auto& r100 = results[std::to_string(100) + "_" + std::to_string(step)];
    const auto& r200 = results[std::to_string(200) + "_" + std::to_string(step)];
    if (r100.size() != 1 || r100[0] != acc100 || r200.size() != 1 ||
        r200[0] != acc200) {
      fprintf(stderr, "error: step %d got [%zu:%d] [%zu:%d] want %d / %d\n",
              step, r100.size(), r100.empty() ? -1 : r100[0], r200.size(),
              r200.empty() ? -1 : r200[0], acc100, acc200);
      return 1;
    }
    printf("seq 100 step %d -> %d ; seq 200 step %d -> %d\n", step, acc100,
           step, acc200);
  }
  printf("PASS : grpc_sequence_stream\n");
  return 0;
}
