// Native gRPC example: two INT32 vectors in, sum/difference out — the gRPC
// twin of simple_http_infer_client.cc (parity with reference
// src/c++/examples/simple_grpc_infer_client.cc:259-437).
//
// Usage: simple_grpc_infer_client [-u host:port] [-m model]
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "grpc_client.h"

namespace tc = ctpu;

#define FAIL_IF_ERR(X, MSG)                                 \
  do {                                                      \
    tc::Error err__ = (X);                                  \
    if (!err__.IsOk()) {                                    \
      fprintf(stderr, "error: %s: %s\n", (MSG),            \
              err__.Message().c_str());                     \
      return 1;                                             \
    }                                                       \
  } while (false)

int
main(int argc, char** argv)
{
  std::string url = "localhost:8001";
  std::string model = "simple";
  for (int i = 1; i < argc - 1; ++i) {
    if (!std::strcmp(argv[i], "-u")) url = argv[++i];
    if (!std::strcmp(argv[i], "-m")) model = argv[++i];
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url), "create client");

  bool live = false;
  FAIL_IF_ERR(client->IsServerLive(&live), "server live");
  if (!live) {
    fprintf(stderr, "error: server not live\n");
    return 1;
  }

  std::vector<int32_t> input0(16), input1(16);
  for (int i = 0; i < 16; ++i) {
    input0[i] = i;
    input1[i] = 1;
  }

  tc::InferInput in0("INPUT0", {1, 16}, "INT32");
  tc::InferInput in1("INPUT1", {1, 16}, "INT32");
  in0.AppendRaw(
      reinterpret_cast<const uint8_t*>(input0.data()),
      input0.size() * sizeof(int32_t));
  in1.AppendRaw(
      reinterpret_cast<const uint8_t*>(input1.data()),
      input1.size() * sizeof(int32_t));
  tc::InferRequestedOutput out0("OUTPUT0"), out1("OUTPUT1");

  tc::InferOptions options(model);
  tc::InferResult* result = nullptr;
  FAIL_IF_ERR(
      client->Infer(&result, options, {&in0, &in1}, {&out0, &out1}), "infer");
  std::unique_ptr<tc::InferResult> result_owner(result);

  const uint8_t* sum_bytes = nullptr;
  const uint8_t* diff_bytes = nullptr;
  size_t nbytes = 0;
  FAIL_IF_ERR(result->RawData("OUTPUT0", &sum_bytes, &nbytes), "OUTPUT0");
  FAIL_IF_ERR(result->RawData("OUTPUT1", &diff_bytes, &nbytes), "OUTPUT1");
  const int32_t* sum = reinterpret_cast<const int32_t*>(sum_bytes);
  const int32_t* diff = reinterpret_cast<const int32_t*>(diff_bytes);
  for (int i = 0; i < 16; ++i) {
    printf(
        "%d + %d = %d, %d - %d = %d\n", input0[i], input1[i], sum[i],
        input0[i], input1[i], diff[i]);
    if (sum[i] != input0[i] + input1[i] || diff[i] != input0[i] - input1[i]) {
      fprintf(stderr, "error: wrong arithmetic in response\n");
      return 1;
    }
  }
  printf("PASS : grpc_infer\n");
  return 0;
}
