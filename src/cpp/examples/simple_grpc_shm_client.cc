// Native gRPC system-shared-memory example: stage both inputs in one POSIX
// region, take both outputs in another, so tensor bytes never ride the
// socket (parity with reference src/c++/examples/simple_grpc_shm_client.cc).
//
// Usage: simple_grpc_shm_client [-u host:port]
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <iostream>
#include <string>
#include <vector>

#include "grpc_client.h"
#include "shm_utils.h"

namespace tc = ctpu;

#define FAIL_IF_ERR(X, MSG)                                 \
  do {                                                      \
    tc::Error err__ = (X);                                  \
    if (!err__.IsOk()) {                                    \
      fprintf(stderr, "error: %s: %s\n", (MSG),            \
              err__.Message().c_str());                     \
      return 1;                                             \
    }                                                       \
  } while (false)

int
main(int argc, char** argv)
{
  std::string url = "localhost:8001";
  for (int i = 1; i < argc - 1; ++i) {
    if (!std::strcmp(argv[i], "-u")) url = argv[++i];
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url), "create client");

  constexpr size_t kTensorBytes = 16 * sizeof(int32_t);
  const std::string in_key = "/grpc_shm_example_in";
  const std::string out_key = "/grpc_shm_example_out";
  // start clean even if a previous run crashed mid-example
  tc::UnlinkSharedMemoryRegion(in_key);
  tc::UnlinkSharedMemoryRegion(out_key);
  client->UnregisterSystemSharedMemory("grpc_shm_example_in");
  client->UnregisterSystemSharedMemory("grpc_shm_example_out");

  int in_fd = -1, out_fd = -1;
  void* in_addr = nullptr;
  void* out_addr = nullptr;
  FAIL_IF_ERR(
      tc::CreateSharedMemoryRegion(in_key, 2 * kTensorBytes, &in_fd),
      "create input region");
  FAIL_IF_ERR(
      tc::MapSharedMemory(in_fd, 0, 2 * kTensorBytes, &in_addr),
      "map input region");
  FAIL_IF_ERR(
      tc::CreateSharedMemoryRegion(out_key, 2 * kTensorBytes, &out_fd),
      "create output region");
  FAIL_IF_ERR(
      tc::MapSharedMemory(out_fd, 0, 2 * kTensorBytes, &out_addr),
      "map output region");

  int32_t* in_ptr = static_cast<int32_t*>(in_addr);
  for (int i = 0; i < 16; ++i) {
    in_ptr[i] = i;        // INPUT0 at offset 0
    in_ptr[16 + i] = 1;   // INPUT1 at offset kTensorBytes
  }

  FAIL_IF_ERR(
      client->RegisterSystemSharedMemory(
          "grpc_shm_example_in", in_key, 2 * kTensorBytes),
      "register input region");
  FAIL_IF_ERR(
      client->RegisterSystemSharedMemory(
          "grpc_shm_example_out", out_key, 2 * kTensorBytes),
      "register output region");

  tc::InferInput in0("INPUT0", {1, 16}, "INT32");
  tc::InferInput in1("INPUT1", {1, 16}, "INT32");
  in0.SetSharedMemory("grpc_shm_example_in", kTensorBytes, 0);
  in1.SetSharedMemory("grpc_shm_example_in", kTensorBytes, kTensorBytes);
  tc::InferRequestedOutput out0("OUTPUT0"), out1("OUTPUT1");
  out0.SetSharedMemory("grpc_shm_example_out", kTensorBytes, 0);
  out1.SetSharedMemory("grpc_shm_example_out", kTensorBytes, kTensorBytes);

  tc::InferOptions options("simple");
  tc::InferResult* result = nullptr;
  FAIL_IF_ERR(
      client->Infer(&result, options, {&in0, &in1}, {&out0, &out1}),
      "inference failed");
  std::unique_ptr<tc::InferResult> owner(result);

  const int32_t* sum = static_cast<int32_t*>(out_addr);
  const int32_t* diff = sum + 16;
  for (int i = 0; i < 16; ++i) {
    std::cout << in_ptr[i] << " + " << in_ptr[16 + i] << " = " << sum[i]
              << std::endl;
    if (sum[i] != in_ptr[i] + in_ptr[16 + i] ||
        diff[i] != in_ptr[i] - in_ptr[16 + i]) {
      std::cerr << "error: incorrect result in shared memory" << std::endl;
      return 1;
    }
  }

  FAIL_IF_ERR(
      client->UnregisterSystemSharedMemory("grpc_shm_example_in"),
      "unregister input");
  FAIL_IF_ERR(
      client->UnregisterSystemSharedMemory("grpc_shm_example_out"),
      "unregister output");
  tc::UnmapSharedMemory(in_addr, 2 * kTensorBytes);
  tc::UnmapSharedMemory(out_addr, 2 * kTensorBytes);
  tc::CloseSharedMemory(in_fd);
  tc::CloseSharedMemory(out_fd);
  tc::UnlinkSharedMemoryRegion(in_key);
  tc::UnlinkSharedMemoryRegion(out_key);

  std::cout << "PASS: simple_grpc_shm_client (native)" << std::endl;
  return 0;
}
