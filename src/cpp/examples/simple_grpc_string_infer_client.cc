// Native gRPC BYTES-tensor example: string-encoded integers in, string sums
// and differences out, using the KServe binary BYTES encoding (4-byte LE
// length prefix per element — parity with reference
// src/c++/examples/simple_grpc_string_infer_client.cc).
//
// Usage: simple_grpc_string_infer_client [-u host:port]
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <iostream>
#include <string>
#include <vector>

#include "grpc_client.h"

namespace tc = ctpu;

#define FAIL_IF_ERR(X, MSG)                                 \
  do {                                                      \
    tc::Error err__ = (X);                                  \
    if (!err__.IsOk()) {                                    \
      fprintf(stderr, "error: %s: %s\n", (MSG),            \
              err__.Message().c_str());                     \
      return 1;                                             \
    }                                                       \
  } while (false)

// KServe BYTES wire form: for each element, uint32 LE length then the bytes.
static std::string
SerializeStrings(const std::vector<std::string>& values)
{
  std::string out;
  for (const auto& v : values) {
    const uint32_t len = static_cast<uint32_t>(v.size());
    out.push_back(static_cast<char>(len & 0xff));
    out.push_back(static_cast<char>((len >> 8) & 0xff));
    out.push_back(static_cast<char>((len >> 16) & 0xff));
    out.push_back(static_cast<char>((len >> 24) & 0xff));
    out += v;
  }
  return out;
}

static bool
DeserializeStrings(
    const uint8_t* data, size_t size, std::vector<std::string>* values)
{
  size_t off = 0;
  while (off + 4 <= size) {
    const uint32_t len = uint32_t(data[off]) | (uint32_t(data[off + 1]) << 8) |
                         (uint32_t(data[off + 2]) << 16) |
                         (uint32_t(data[off + 3]) << 24);
    off += 4;
    if (off + len > size) return false;
    values->emplace_back(reinterpret_cast<const char*>(data) + off, len);
    off += len;
  }
  return off == size;
}

int
main(int argc, char** argv)
{
  std::string url = "localhost:8001";
  for (int i = 1; i < argc - 1; ++i) {
    if (!std::strcmp(argv[i], "-u")) url = argv[++i];
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url), "create client");

  std::vector<std::string> in0_vals, in1_vals;
  for (int i = 0; i < 16; ++i) {
    in0_vals.push_back(std::to_string(i));
    in1_vals.push_back(std::to_string(1));
  }
  const std::string in0_raw = SerializeStrings(in0_vals);
  const std::string in1_raw = SerializeStrings(in1_vals);

  tc::InferInput in0("INPUT0", {1, 16}, "BYTES");
  tc::InferInput in1("INPUT1", {1, 16}, "BYTES");
  in0.AppendRaw(
      reinterpret_cast<const uint8_t*>(in0_raw.data()), in0_raw.size());
  in1.AppendRaw(
      reinterpret_cast<const uint8_t*>(in1_raw.data()), in1_raw.size());

  tc::InferOptions options("simple_string");
  tc::InferResult* result = nullptr;
  FAIL_IF_ERR(
      client->Infer(&result, options, {&in0, &in1}), "inference failed");
  std::unique_ptr<tc::InferResult> owner(result);

  const uint8_t* data = nullptr;
  size_t size = 0;
  FAIL_IF_ERR(result->RawData("OUTPUT0", &data, &size), "OUTPUT0");
  std::vector<std::string> sums;
  if (!DeserializeStrings(data, size, &sums) || sums.size() != 16) {
    std::cerr << "error: malformed BYTES output" << std::endl;
    return 1;
  }
  for (int i = 0; i < 16; ++i) {
    std::cout << in0_vals[i] << " + " << in1_vals[i] << " = " << sums[i]
              << std::endl;
    if (std::stoi(sums[i]) != i + 1) {
      std::cerr << "error: incorrect string sum" << std::endl;
      return 1;
    }
  }
  std::cout << "PASS: simple_grpc_string_infer_client (native)" << std::endl;
  return 0;
}
