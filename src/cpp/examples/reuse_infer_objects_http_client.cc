// Native HTTP object-reuse example — the HTTP twin of
// reuse_infer_objects_grpc_client.cc (reference
// src/c++/examples/reuse_infer_objects_client.cc): one InferInput set
// serves many requests via Reset + AppendRaw, including a switch to
// shared-memory payloads and back.
//
// Usage: reuse_infer_objects_http_client [-u host:port]
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <iostream>
#include <string>
#include <vector>

#include "http_client.h"
#include "shm_utils.h"

namespace tc = ctpu;

#define FAIL_IF_ERR(X, MSG)                                 \
  do {                                                      \
    tc::Error err__ = (X);                                  \
    if (!err__.IsOk()) {                                    \
      fprintf(stderr, "error: %s: %s\n", (MSG),            \
              err__.Message().c_str());                     \
      return 1;                                             \
    }                                                       \
  } while (false)

int
main(int argc, char** argv)
{
  std::string url = "localhost:8000";
  for (int i = 1; i < argc - 1; ++i) {
    if (!std::strcmp(argv[i], "-u")) url = argv[++i];
  }

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerHttpClient::Create(&client, url), "create client");

  tc::InferInput in0("INPUT0", {1, 16}, "INT32");
  tc::InferInput in1("INPUT1", {1, 16}, "INT32");
  tc::InferOptions options("simple");

  // rounds 0-2: raw buffers through the same objects
  for (int round = 0; round < 3; ++round) {
    std::vector<int32_t> input0(16), input1(16);
    for (int i = 0; i < 16; ++i) {
      input0[i] = round * 10 + i;
      input1[i] = round;
    }
    FAIL_IF_ERR(in0.Reset(), "reset INPUT0");
    FAIL_IF_ERR(in1.Reset(), "reset INPUT1");
    in0.AppendRaw(
        reinterpret_cast<const uint8_t*>(input0.data()),
        input0.size() * sizeof(int32_t));
    in1.AppendRaw(
        reinterpret_cast<const uint8_t*>(input1.data()),
        input1.size() * sizeof(int32_t));
    tc::InferResultPtr result;
    FAIL_IF_ERR(client->Infer(&result, options, {&in0, &in1}), "infer");
    const uint8_t* data = nullptr;
    size_t size = 0;
    FAIL_IF_ERR(result->RawData("OUTPUT0", &data, &size), "OUTPUT0");
    const int32_t* sum = reinterpret_cast<const int32_t*>(data);
    for (int i = 0; i < 16; ++i) {
      if (sum[i] != input0[i] + input1[i]) {
        std::cerr << "error: wrong sum in round " << round << std::endl;
        return 1;
      }
    }
    std::cout << "raw round " << round << " ok" << std::endl;
  }

  // final round: the SAME objects switch to a shared-memory payload
  constexpr size_t kTensorBytes = 16 * sizeof(int32_t);
  const std::string key = "/reuse_http_in";
  tc::UnlinkSharedMemoryRegion(key);
  client->UnregisterSystemSharedMemory("reuse_http_in");
  int fd = -1;
  void* addr = nullptr;
  FAIL_IF_ERR(
      tc::CreateSharedMemoryRegion(key, 2 * kTensorBytes, &fd), "create shm");
  FAIL_IF_ERR(tc::MapSharedMemory(fd, 0, 2 * kTensorBytes, &addr), "map shm");
  int32_t* p = static_cast<int32_t*>(addr);
  for (int i = 0; i < 16; ++i) {
    p[i] = 1000 + i;
    p[16 + i] = 1;
  }
  FAIL_IF_ERR(
      client->RegisterSystemSharedMemory(
          "reuse_http_in", key, 2 * kTensorBytes),
      "register shm");
  FAIL_IF_ERR(in0.Reset(), "reset INPUT0");
  FAIL_IF_ERR(in1.Reset(), "reset INPUT1");
  in0.SetSharedMemory("reuse_http_in", kTensorBytes, 0);
  in1.SetSharedMemory("reuse_http_in", kTensorBytes, kTensorBytes);
  tc::InferResultPtr result;
  FAIL_IF_ERR(client->Infer(&result, options, {&in0, &in1}), "shm infer");
  const uint8_t* data = nullptr;
  size_t size = 0;
  FAIL_IF_ERR(result->RawData("OUTPUT0", &data, &size), "OUTPUT0");
  const int32_t* sum = reinterpret_cast<const int32_t*>(data);
  for (int i = 0; i < 16; ++i) {
    if (sum[i] != 1000 + i + 1) {
      std::cerr << "error: wrong shm-round sum" << std::endl;
      return 1;
    }
  }
  std::cout << "shm round ok (same objects)" << std::endl;
  client->UnregisterSystemSharedMemory("reuse_http_in");
  tc::UnmapSharedMemory(addr, 2 * kTensorBytes);
  tc::CloseSharedMemory(fd);
  tc::UnlinkSharedMemoryRegion(key);
  std::cout << "PASS: reuse_infer_objects_http_client (native)" << std::endl;
  return 0;
}
