// Native decoupled-model streaming example — one request, N streamed
// responses over the bidi ModelStreamInfer stream (reference
// src/c++/examples's decoupled/repeat pattern; the LLM token-streaming
// shape).  The repeat_int32 model yields values 0..n-1 for input n.
//
// Usage: simple_grpc_decoupled_repeat_client [-u host:port] [-n count]
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <iostream>
#include <mutex>
#include <string>
#include <vector>

#include "grpc_client.h"

namespace tc = ctpu;

#define FAIL_IF_ERR(X, MSG)                                 \
  do {                                                      \
    tc::Error err__ = (X);                                  \
    if (!err__.IsOk()) {                                    \
      fprintf(stderr, "error: %s: %s\n", (MSG),            \
              err__.Message().c_str());                     \
      return 1;                                             \
    }                                                       \
  } while (false)

int
main(int argc, char** argv)
{
  std::string url = "localhost:8001";
  int n = 8;
  for (int i = 1; i < argc - 1; ++i) {
    if (!std::strcmp(argv[i], "-u")) url = argv[++i];
    if (!std::strcmp(argv[i], "-n")) n = std::atoi(argv[++i]);
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url), "create client");

  std::mutex mu;
  std::condition_variable cv;
  std::vector<int32_t> received;
  bool failed = false;
  FAIL_IF_ERR(
      client->StartStream([&](tc::InferResultPtr result) {
        std::lock_guard<std::mutex> lk(mu);
        const uint8_t* data = nullptr;
        size_t size = 0;
        if (result->RequestStatus().IsOk() &&
            result->RawData("OUT", &data, &size).IsOk() &&
            size == sizeof(int32_t)) {
          received.push_back(*reinterpret_cast<const int32_t*>(data));
        } else {
          failed = true;
        }
        cv.notify_all();
      }),
      "start stream");

  int32_t count = n;
  tc::InferInput input("IN", {1}, "INT32");
  input.AppendRaw(reinterpret_cast<const uint8_t*>(&count), sizeof(count));
  tc::InferOptions options("repeat_int32");
  FAIL_IF_ERR(client->AsyncStreamInfer(options, {&input}), "stream infer");

  {
    std::unique_lock<std::mutex> lk(mu);
    cv.wait_for(lk, std::chrono::seconds(30), [&] {
      return failed || static_cast<int>(received.size()) >= n;
    });
  }
  FAIL_IF_ERR(client->StopStream(), "stop stream");

  if (failed || static_cast<int>(received.size()) != n) {
    std::cerr << "error: expected " << n << " streamed responses, got "
              << received.size() << std::endl;
    return 1;
  }
  for (int i = 0; i < n; ++i) {
    std::cout << "response " << i << ": " << received[i] << std::endl;
    if (received[i] != i) {
      std::cerr << "error: out-of-order or wrong streamed value"
                << std::endl;
      return 1;
    }
  }
  std::cout << "PASS: simple_grpc_decoupled_repeat_client (native)"
            << std::endl;
  return 0;
}
