// Native HTTP ensemble example — the HTTP twin of
// simple_grpc_ensemble_client.cc: one request drives the server-side DAG;
// composing-model execution is proven via the statistics endpoint.
//
// Usage: simple_http_ensemble_client [-u host:port]
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <iostream>
#include <map>
#include <string>
#include <vector>

#include "http_client.h"

namespace tc = ctpu;

#define FAIL_IF_ERR(X, MSG)                                 \
  do {                                                      \
    tc::Error err__ = (X);                                  \
    if (!err__.IsOk()) {                                    \
      fprintf(stderr, "error: %s: %s\n", (MSG),            \
              err__.Message().c_str());                     \
      return 1;                                             \
    }                                                       \
  } while (false)

static std::map<std::string, int64_t>
SuccessCounts(tc::InferenceServerHttpClient* client)
{
  std::map<std::string, int64_t> counts;
  tc::json::ValuePtr stats;
  if (client->ModelInferenceStatistics(&stats).IsOk()) {
    const tc::json::Value* model_stats = stats->Get("model_stats");
    if (model_stats != nullptr) {
      for (const auto& entry : model_stats->arr) {
        const tc::json::Value* name = entry->Get("name");
        const tc::json::Value* inference = entry->Get("inference_stats");
        if (name == nullptr || inference == nullptr) continue;
        const tc::json::Value* success = inference->Get("success");
        if (success == nullptr) continue;
        const tc::json::Value* count = success->Get("count");
        counts[name->AsString()] = count != nullptr ? count->AsInt() : 0;
      }
    }
  }
  return counts;
}

int
main(int argc, char** argv)
{
  std::string url = "localhost:8000";
  for (int i = 1; i < argc - 1; ++i) {
    if (!std::strcmp(argv[i], "-u")) url = argv[++i];
  }

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerHttpClient::Create(&client, url), "create client");

  auto before = SuccessCounts(client.get());

  std::vector<int32_t> input0(16), input1(16);
  tc::InferInput in0("INPUT0", {1, 16}, "INT32");
  tc::InferInput in1("INPUT1", {1, 16}, "INT32");
  for (int i = 0; i < 16; ++i) {
    input0[i] = 7 * i;
    input1[i] = i;
  }
  in0.AppendRaw(
      reinterpret_cast<const uint8_t*>(input0.data()), 16 * sizeof(int32_t));
  in1.AppendRaw(
      reinterpret_cast<const uint8_t*>(input1.data()), 16 * sizeof(int32_t));

  tc::InferOptions options("simple_ensemble");
  tc::InferResultPtr result;
  FAIL_IF_ERR(
      client->Infer(&result, options, {&in0, &in1}), "inference failed");

  const uint8_t* data = nullptr;
  size_t nbytes = 0;
  FAIL_IF_ERR(result->RawData("OUTPUT0", &data, &nbytes), "OUTPUT0");
  const int32_t* sum = reinterpret_cast<const int32_t*>(data);
  for (int i = 0; i < 16; ++i) {
    std::cout << input0[i] << " + " << input1[i] << " = " << sum[i]
              << std::endl;
    if (sum[i] != input0[i] + input1[i]) {
      std::cerr << "error: ensemble result incorrect" << std::endl;
      return 1;
    }
  }

  auto after = SuccessCounts(client.get());
  for (const char* composing : {"simple", "identity_int32"}) {
    if (after[composing] <= before[composing]) {
      std::cerr << "error: composing model '" << composing
                << "' did not execute server-side" << std::endl;
      return 1;
    }
  }
  std::cout << "composing models executed server-side" << std::endl;
  std::cout << "PASS: simple_http_ensemble_client (native)" << std::endl;
  return 0;
}
