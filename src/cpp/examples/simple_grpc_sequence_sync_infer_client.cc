// Native gRPC stateful-sequence example over UNARY calls: two interleaved
// sequences — one with a numeric correlation id, one with a string id —
// each accumulating independently on the server (parity with reference
// src/c++/examples/simple_grpc_sequence_sync_infer_client.cc; the string id
// exercises InferOptions::sequence_id_str).
//
// Usage: simple_grpc_sequence_sync_infer_client [-u host:port]
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <iostream>
#include <string>
#include <vector>

#include "grpc_client.h"

namespace tc = ctpu;

#define FAIL_IF_ERR(X, MSG)                                 \
  do {                                                      \
    tc::Error err__ = (X);                                  \
    if (!err__.IsOk()) {                                    \
      fprintf(stderr, "error: %s: %s\n", (MSG),            \
              err__.Message().c_str());                     \
      return 1;                                             \
    }                                                       \
  } while (false)

static int
SendStep(
    tc::InferenceServerGrpcClient* client, uint64_t seq_id,
    const std::string& seq_id_str, int step, int last_step, int32_t value,
    int32_t* accumulated)
{
  tc::InferInput input("INPUT", {1}, "INT32");
  input.AppendRaw(reinterpret_cast<const uint8_t*>(&value), sizeof(value));
  tc::InferOptions options("simple_sequence");
  options.sequence_id = seq_id;
  options.sequence_id_str = seq_id_str;
  options.sequence_start = (step == 0);
  options.sequence_end = (step == last_step);
  tc::InferResult* result = nullptr;
  tc::Error err = client->Infer(&result, options, {&input});
  if (!err.IsOk()) {
    fprintf(stderr, "error: sequence step: %s\n", err.Message().c_str());
    return -1;
  }
  std::unique_ptr<tc::InferResult> owner(result);
  const uint8_t* data = nullptr;
  size_t size = 0;
  err = result->RawData("OUTPUT", &data, &size);
  if (!err.IsOk() || size != sizeof(int32_t)) {
    fprintf(stderr, "error: sequence output\n");
    return -1;
  }
  *accumulated = *reinterpret_cast<const int32_t*>(data);
  return 0;
}

int
main(int argc, char** argv)
{
  std::string url = "localhost:8001";
  for (int i = 1; i < argc - 1; ++i) {
    if (!std::strcmp(argv[i], "-u")) url = argv[++i];
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url), "create client");

  const int32_t values[3] = {11, 7, 300};
  int32_t sum_numeric = 0, sum_string = 0;
  int32_t acc = 0;
  for (int step = 0; step < 3; ++step) {
    // numeric-id sequence accumulates values[step]
    sum_numeric += values[step];
    if (SendStep(client.get(), 12345, "", step, 2, values[step], &acc) != 0)
      return 1;
    std::cout << "seq 12345 step " << step << ": " << acc << std::endl;
    if (acc != sum_numeric) {
      std::cerr << "error: numeric-id accumulator mismatch" << std::endl;
      return 1;
    }
    // interleaved string-id sequence accumulates the negatives — state must
    // stay separate
    sum_string -= values[step];
    if (SendStep(
            client.get(), 0, "seq-example-str", step, 2, -values[step],
            &acc) != 0)
      return 1;
    std::cout << "seq 'seq-example-str' step " << step << ": " << acc
              << std::endl;
    if (acc != sum_string) {
      std::cerr << "error: string-id accumulator mismatch" << std::endl;
      return 1;
    }
  }
  std::cout << "PASS: simple_grpc_sequence_sync_infer_client (native)"
            << std::endl;
  return 0;
}
