// Health + metadata surface over the native gRPC client (parity with
// reference src/c++/examples/simple_grpc_health_metadata.cc).
//
// Usage: simple_grpc_health_metadata [-u host:port]
#include <cstdio>
#include <cstring>
#include <string>

#include "grpc_client.h"

namespace tc = ctpu;

#define FAIL_IF_ERR(X, MSG)                                                \
  do {                                                                     \
    tc::Error err__ = (X);                                                 \
    if (!err__.IsOk()) {                                                   \
      fprintf(stderr, "error: %s: %s\n", (MSG), err__.Message().c_str());  \
      return 1;                                                            \
    }                                                                      \
  } while (false)

int
main(int argc, char** argv)
{
  std::string url = "localhost:8001";
  for (int i = 1; i < argc - 1; ++i)
    if (!std::strcmp(argv[i], "-u")) url = argv[++i];
  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(tc::InferenceServerGrpcClient::Create(&client, url), "create");

  bool live = false, ready = false, model_ready = false;
  FAIL_IF_ERR(client->IsServerLive(&live), "live");
  FAIL_IF_ERR(client->IsServerReady(&ready), "ready");
  FAIL_IF_ERR(client->IsModelReady(&model_ready, "simple"), "model ready");
  printf("live=%d ready=%d model_ready=%d\n", live, ready, model_ready);
  if (!live || !ready || !model_ready) {
    fprintf(stderr, "error: server/model not healthy\n");
    return 1;
  }
  inference::ServerMetadataResponse server_meta;
  FAIL_IF_ERR(client->ServerMetadata(&server_meta), "server metadata");
  printf("server: %s %s\n", server_meta.name().c_str(),
         server_meta.version().c_str());
  inference::ModelMetadataResponse model_meta;
  FAIL_IF_ERR(client->ModelMetadata(&model_meta, "simple"), "model metadata");
  printf("model '%s': %d inputs, %d outputs\n", model_meta.name().c_str(),
         model_meta.inputs_size(), model_meta.outputs_size());
  inference::ModelConfigResponse config;
  FAIL_IF_ERR(client->ModelConfig(&config, "simple"), "model config");
  printf("max_batch_size: %d\n", config.config().max_batch_size());
  printf("PASS : grpc_health_metadata\n");
  return 0;
}
