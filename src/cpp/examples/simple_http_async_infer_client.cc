// Native HTTP async example: a burst of AsyncInfer requests rides the
// client's single epoll reactor thread — many in-flight keep-alive
// connections, no thread-per-request (the reference's curl-multi model,
// reference src/c++/examples/simple_http_async_infer_client.cc).
//
// Usage: simple_http_async_infer_client [-u host:port]
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <iostream>
#include <mutex>
#include <string>
#include <vector>

#include "http_client.h"

namespace tc = ctpu;

#define FAIL_IF_ERR(X, MSG)                                 \
  do {                                                      \
    tc::Error err__ = (X);                                  \
    if (!err__.IsOk()) {                                    \
      fprintf(stderr, "error: %s: %s\n", (MSG),            \
              err__.Message().c_str());                     \
      return 1;                                             \
    }                                                       \
  } while (false)

int
main(int argc, char** argv)
{
  std::string url = "localhost:8000";
  for (int i = 1; i < argc - 1; ++i) {
    if (!std::strcmp(argv[i], "-u")) url = argv[++i];
  }

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerHttpClient::Create(&client, url), "create client");

  std::vector<int32_t> input0(16), input1(16);
  for (int i = 0; i < 16; ++i) {
    input0[i] = i;
    input1[i] = 2 * i;
  }
  tc::InferInput in0("INPUT0", {1, 16}, "INT32");
  tc::InferInput in1("INPUT1", {1, 16}, "INT32");
  in0.AppendRaw(
      reinterpret_cast<const uint8_t*>(input0.data()),
      input0.size() * sizeof(int32_t));
  in1.AppendRaw(
      reinterpret_cast<const uint8_t*>(input1.data()),
      input1.size() * sizeof(int32_t));
  tc::InferOptions options("simple");

  constexpr int kRequests = 32;
  std::mutex mu;
  std::condition_variable cv;
  int done = 0, good = 0;
  for (int r = 0; r < kRequests; ++r) {
    FAIL_IF_ERR(
        client->AsyncInfer(
            [&](tc::InferResultPtr result, tc::Error err) {
              bool ok = err.IsOk() && result != nullptr &&
                        result->RequestStatus().IsOk();
              if (ok) {
                const uint8_t* data = nullptr;
                size_t size = 0;
                ok = result->RawData("OUTPUT0", &data, &size).IsOk() &&
                     size == 16 * sizeof(int32_t) &&
                     reinterpret_cast<const int32_t*>(data)[5] == 3 * 5;
              }
              std::lock_guard<std::mutex> lk(mu);
              ++done;
              if (ok) ++good;
              cv.notify_all();
            },
            options, {&in0, &in1}),
        "async infer");
  }
  {
    std::unique_lock<std::mutex> lk(mu);
    cv.wait_for(
        lk, std::chrono::seconds(60), [&] { return done == kRequests; });
  }
  std::cout << good << "/" << kRequests << " async responses ok" << std::endl;
  if (good != kRequests) {
    std::cerr << "error: async burst incomplete" << std::endl;
    return 1;
  }
  std::cout << "PASS: simple_http_async_infer_client (native)" << std::endl;
  return 0;
}
