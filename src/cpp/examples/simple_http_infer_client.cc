// Native HTTP inference example — parity with the reference's C++
// simple_http_infer_client.cc: INT32 add/sub on the 'simple' model via the
// binary tensor protocol.  Usage: simple_http_infer_client [-u host:port]
#include <cstdint>
#include <cstring>
#include <iostream>
#include <vector>

#include "../client/http_client.h"

namespace tc = ctpu;

#define FAIL_IF_ERR(X, MSG)                              \
  do {                                                   \
    tc::Error err__ = (X);                               \
    if (!err__.IsOk()) {                                 \
      std::cerr << "error: " << (MSG) << ": "            \
                << err__.Message() << std::endl;         \
      return 1;                                          \
    }                                                    \
  } while (false)

int
main(int argc, char** argv)
{
  std::string url = "localhost:8000";
  for (int i = 1; i < argc - 1; i++) {
    if (std::string(argv[i]) == "-u") url = argv[i + 1];
  }

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerHttpClient::Create(&client, url),
      "unable to create client");

  bool live = false;
  FAIL_IF_ERR(client->IsServerLive(&live), "server liveness");
  if (!live) {
    std::cerr << "error: server not live" << std::endl;
    return 1;
  }

  std::vector<int32_t> input0_data(16), input1_data(16);
  for (int i = 0; i < 16; i++) {
    input0_data[i] = i;
    input1_data[i] = 1;
  }

  tc::InferInput input0("INPUT0", {1, 16}, "INT32");
  tc::InferInput input1("INPUT1", {1, 16}, "INT32");
  input0.AppendRaw(
      reinterpret_cast<const uint8_t*>(input0_data.data()),
      input0_data.size() * sizeof(int32_t));
  input1.AppendRaw(
      reinterpret_cast<const uint8_t*>(input1_data.data()),
      input1_data.size() * sizeof(int32_t));

  tc::InferRequestedOutput output0("OUTPUT0");
  tc::InferRequestedOutput output1("OUTPUT1");

  tc::InferOptions options("simple");
  tc::InferResultPtr result;
  FAIL_IF_ERR(
      client->Infer(&result, options, {&input0, &input1}, {&output0, &output1}),
      "inference failed");

  const uint8_t* out0 = nullptr;
  const uint8_t* out1 = nullptr;
  size_t size0 = 0, size1 = 0;
  FAIL_IF_ERR(result->RawData("OUTPUT0", &out0, &size0), "OUTPUT0");
  FAIL_IF_ERR(result->RawData("OUTPUT1", &out1, &size1), "OUTPUT1");
  if (size0 != 16 * sizeof(int32_t) || size1 != 16 * sizeof(int32_t)) {
    std::cerr << "error: unexpected output sizes" << std::endl;
    return 1;
  }
  const int32_t* sum = reinterpret_cast<const int32_t*>(out0);
  const int32_t* diff = reinterpret_cast<const int32_t*>(out1);
  for (int i = 0; i < 16; i++) {
    std::cout << input0_data[i] << " + " << input1_data[i] << " = " << sum[i]
              << std::endl;
    if (sum[i] != input0_data[i] + input1_data[i] ||
        diff[i] != input0_data[i] - input1_data[i]) {
      std::cerr << "error: incorrect result" << std::endl;
      return 1;
    }
  }
  std::cout << "PASS: simple_http_infer_client (native)" << std::endl;
  return 0;
}
