// Native gRPC client-timeout example: a generous deadline succeeds; a
// microscopic one must surface DEADLINE_EXCEEDED as a clean Error, and the
// connection must remain usable afterwards (the reference's
// client_timeout test behavior in cc_client_test.cc).
//
// Usage: simple_grpc_timeout_client [-u host:port]
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <iostream>
#include <string>
#include <vector>

#include "grpc_client.h"

namespace tc = ctpu;

#define FAIL_IF_ERR(X, MSG)                                 \
  do {                                                      \
    tc::Error err__ = (X);                                  \
    if (!err__.IsOk()) {                                    \
      fprintf(stderr, "error: %s: %s\n", (MSG),            \
              err__.Message().c_str());                     \
      return 1;                                             \
    }                                                       \
  } while (false)

static tc::Error
DoInfer(
    tc::InferenceServerGrpcClient* client, uint64_t client_timeout_us,
    tc::InferResult** result)
{
  // slow_identity sleeps 50ms server-side — the deterministic way to make
  // a deadline race winnable (the reference uses delay models the same way)
  static std::vector<int32_t> values(8, 7);
  tc::InferInput in0("INPUT0", {8}, "INT32");
  in0.AppendRaw(
      reinterpret_cast<const uint8_t*>(values.data()), 8 * sizeof(int32_t));
  tc::InferOptions options("slow_identity");
  options.client_timeout_us = client_timeout_us;
  return client->Infer(result, options, {&in0});
}

int
main(int argc, char** argv)
{
  std::string url = "localhost:8001";
  for (int i = 1; i < argc - 1; ++i) {
    if (!std::strcmp(argv[i], "-u")) url = argv[++i];
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url), "create client");

  // generous deadline: must succeed
  tc::InferResult* result = nullptr;
  FAIL_IF_ERR(DoInfer(client.get(), 30 * 1000 * 1000, &result), "generous");
  delete result;
  std::cout << "30s deadline on 50ms model: ok" << std::endl;

  // 5ms against a 50ms model: must fail with a deadline error, not hang
  result = nullptr;
  tc::Error err = DoInfer(client.get(), 5 * 1000, &result);
  if (err.IsOk()) {
    std::cerr << "error: 5ms deadline never expired on the 50ms model"
              << std::endl;
    delete result;
    return 1;
  }
  std::cout << "5ms deadline on 50ms model: failed as expected ("
            << err.Message() << ")" << std::endl;

  // the connection stays usable after the deadline error
  result = nullptr;
  FAIL_IF_ERR(DoInfer(client.get(), 0, &result), "post-timeout request");
  delete result;
  std::cout << "connection usable after timeout" << std::endl;
  std::cout << "PASS: simple_grpc_timeout_client (native)" << std::endl;
  return 0;
}
