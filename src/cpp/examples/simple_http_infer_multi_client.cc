// Native HTTP batched-convenience example: InferMulti sends N requests
// from one call (options broadcast across requests), AsyncInferMulti
// returns them through one completion callback (reference
// grpc_client.h:441-494 InferMulti/AsyncInferMulti surface).
//
// Usage: simple_http_infer_multi_client [-u host:port]
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <iostream>
#include <mutex>
#include <string>
#include <vector>

#include "http_client.h"

namespace tc = ctpu;

#define FAIL_IF_ERR(X, MSG)                                 \
  do {                                                      \
    tc::Error err__ = (X);                                  \
    if (!err__.IsOk()) {                                    \
      fprintf(stderr, "error: %s: %s\n", (MSG),            \
              err__.Message().c_str());                     \
      return 1;                                             \
    }                                                       \
  } while (false)

int
main(int argc, char** argv)
{
  std::string url = "localhost:8000";
  for (int i = 1; i < argc - 1; ++i) {
    if (!std::strcmp(argv[i], "-u")) url = argv[++i];
  }

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerHttpClient::Create(&client, url), "create client");

  constexpr int kRequests = 4;
  // distinct payload per request so results are distinguishable
  std::vector<std::vector<int32_t>> payload0(kRequests),
      payload1(kRequests);
  std::vector<std::unique_ptr<tc::InferInput>> owned;
  std::vector<std::vector<tc::InferInput*>> inputs(kRequests);
  for (int r = 0; r < kRequests; ++r) {
    payload0[r].resize(16);
    payload1[r].resize(16);
    for (int i = 0; i < 16; ++i) {
      payload0[r][i] = r * 100 + i;
      payload1[r][i] = r;
    }
    auto i0 = std::make_unique<tc::InferInput>(
        "INPUT0", std::vector<int64_t>{1, 16}, "INT32");
    auto i1 = std::make_unique<tc::InferInput>(
        "INPUT1", std::vector<int64_t>{1, 16}, "INT32");
    i0->AppendRaw(
        reinterpret_cast<const uint8_t*>(payload0[r].data()),
        16 * sizeof(int32_t));
    i1->AppendRaw(
        reinterpret_cast<const uint8_t*>(payload1[r].data()),
        16 * sizeof(int32_t));
    inputs[r] = {i0.get(), i1.get()};
    owned.push_back(std::move(i0));
    owned.push_back(std::move(i1));
  }

  auto check = [&](const std::vector<tc::InferResultPtr>& results) -> bool {
    if (static_cast<int>(results.size()) != kRequests) return false;
    for (int r = 0; r < kRequests; ++r) {
      const uint8_t* data = nullptr;
      size_t size = 0;
      if (!results[r]->RawData("OUTPUT0", &data, &size).IsOk()) return false;
      const int32_t* sum = reinterpret_cast<const int32_t*>(data);
      for (int i = 0; i < 16; ++i)
        if (sum[i] != payload0[r][i] + payload1[r][i]) return false;
    }
    return true;
  };

  // one InferOptions broadcast across all requests
  std::vector<tc::InferOptions> options = {tc::InferOptions("simple")};
  std::vector<tc::InferResultPtr> results;
  FAIL_IF_ERR(client->InferMulti(&results, options, inputs), "InferMulti");
  if (!check(results)) {
    std::cerr << "error: InferMulti results incorrect" << std::endl;
    return 1;
  }
  std::cout << "InferMulti: " << results.size() << " results ok" << std::endl;

  std::mutex mu;
  std::condition_variable cv;
  bool done = false, ok = false;
  FAIL_IF_ERR(
      client->AsyncInferMulti(
          [&](std::vector<tc::InferResultPtr> rs, tc::Error err) {
            std::lock_guard<std::mutex> lk(mu);
            ok = err.IsOk() && check(rs);
            done = true;
            cv.notify_all();
          },
          options, inputs),
      "AsyncInferMulti");
  {
    std::unique_lock<std::mutex> lk(mu);
    cv.wait_for(lk, std::chrono::seconds(60), [&] { return done; });
  }
  if (!ok) {
    std::cerr << "error: AsyncInferMulti results incorrect" << std::endl;
    return 1;
  }
  std::cout << "AsyncInferMulti: all results ok" << std::endl;
  std::cout << "PASS: simple_http_infer_multi_client (native)" << std::endl;
  return 0;
}
