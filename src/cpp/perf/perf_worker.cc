// Native load-generation worker — the C++ engine behind the perf harness
// (the role of the reference's perf_analyzer core: perf_analyzer.cc:56-424
// concurrency manager + concurrency_worker.cc hot loop + async
// InferContext slots, infer_context.cc:103-150), re-shaped for this
// framework: N outstanding AsyncInfer contexts multiplexed on ONE
// HTTP/2 connection and completed by its reactor thread — no GIL, no
// thread-per-request.  The Python CLI drives it as a subprocess
// (client_tpu/perf/native_worker.py) and merges its records.
//
//   perf_worker -u host:port -m model -c concurrency -d seconds
//               [-w warmup_seconds]
//               [--wire-input NAME:DTYPE:d1,d2,...]...
//               [--shm-input NAME:DTYPE:d1,d2:REGION:NBYTES]...
//               [--shm-output NAME:REGION:NBYTES]...
//
// Prints ONE JSON line:
//   {"ok": N, "errors": N, "elapsed_s": F, "throughput": F,
//    "p50_us": F, "p90_us": F, "p95_us": F, "p99_us": F, "avg_us": F}
#include <algorithm>
#include <cmath>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <memory>
#include <mutex>
#include <random>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

#include "grpc_client.h"

namespace tc = ctpu;

namespace {

using Clock = std::chrono::steady_clock;

struct TensorArg {
  std::string name;
  std::string datatype;
  std::vector<int64_t> shape;
  std::string region;  // shm variants
  size_t nbytes = 0;
};

std::vector<std::string>
Split(const std::string& s, char sep)
{
  std::vector<std::string> out;
  std::istringstream in(s);
  std::string part;
  while (std::getline(in, part, sep)) out.push_back(part);
  return out;
}

bool
ParseTensorArg(const std::string& text, bool shm, bool output, TensorArg* out)
{
  const auto parts = Split(text, ':');
  if (output) {  // NAME:REGION:NBYTES
    if (parts.size() != 3) return false;
    out->name = parts[0];
    out->region = parts[1];
    out->nbytes = std::stoull(parts[2]);
    return true;
  }
  if (parts.size() != (shm ? 5u : 3u)) return false;
  out->name = parts[0];
  out->datatype = parts[1];
  for (const auto& d : Split(parts[2], ',')) out->shape.push_back(std::stoll(d));
  if (shm) {
    out->region = parts[3];
    out->nbytes = std::stoull(parts[4]);
  }
  return true;
}

size_t
DtypeSize(const std::string& datatype)
{
  if (datatype == "FP64" || datatype == "INT64" || datatype == "UINT64")
    return 8;
  if (datatype == "FP32" || datatype == "INT32" || datatype == "UINT32")
    return 4;
  if (datatype == "FP16" || datatype == "BF16" || datatype == "INT16" ||
      datatype == "UINT16")
    return 2;
  return 1;
}

struct Record {
  int64_t start_ns;
  int64_t end_ns;
  bool ok;
};

class Driver {
 public:
  Driver(tc::InferenceServerGrpcClient* client, tc::InferOptions options,
         std::vector<tc::InferInput*> inputs,
         std::vector<const tc::InferRequestedOutput*> outputs)
      : client_(client), options_(std::move(options)),
        inputs_(std::move(inputs)), outputs_(std::move(outputs))
  {
  }

  // Returns false when the drain timed out with requests still in flight
  // (the caller must not destroy this Driver: the reactor can still fire).
  bool Run(int concurrency, double warmup_s, double duration_s)
  {
    stop_.store(false);
    const auto t_warm_end =
        Clock::now() + std::chrono::duration<double>(warmup_s);
    // ALL submissions run on this pump thread, never on the connection's
    // reactor thread: a completion callback that re-armed inline would run
    // SendData on the reader — which must stay free to process the
    // WINDOW_UPDATE frames SendData waits for (self-deadlock for any body
    // larger than the h2 flow-control window).
    {
      std::lock_guard<std::mutex> lk(mu_);
      rearm_pending_ = concurrency;
    }
    pump_ = std::thread([this] { PumpLoop(); });
    pump_cv_.notify_all();
    std::this_thread::sleep_until(t_warm_end);
    {
      std::lock_guard<std::mutex> lk(mu_);
      records_.clear();  // warmup requests don't count
    }
    window_start_ = Now();
    std::this_thread::sleep_for(std::chrono::duration<double>(duration_s));
    stop_.store(true);
    window_end_ = Now();
    // stop the pump first: after it joins, nothing submits anymore ...
    pump_cv_.notify_all();
    if (pump_.joinable()) pump_.join();
    // ... then drain every outstanding context.  Completions touch members
    // only under mu_, and the final unlock happens-before this wait
    // observes outstanding_ == 0, so returning (and destroying the Driver)
    // after a successful drain is safe.
    std::unique_lock<std::mutex> lk(mu_);
    return drained_.wait_for(
        lk, std::chrono::seconds(60), [&] { return outstanding_ == 0; });
  }

  void Report()
  {
    std::vector<Record> records;
    {
      std::lock_guard<std::mutex> lk(mu_);
      records = records_;
    }
    std::vector<double> lat_us;
    size_t ok = 0, errors = 0;
    for (const auto& r : records) {
      // count only requests completing inside the window (the profiler's
      // ValidLatencyMeasurement clip)
      if (r.end_ns < window_start_ || r.end_ns > window_end_) continue;
      if (!r.ok) {
        errors++;
        continue;
      }
      ok++;
      lat_us.push_back((r.end_ns - r.start_ns) / 1e3);
    }
    std::sort(lat_us.begin(), lat_us.end());
    const double elapsed_s = (window_end_ - window_start_) / 1e9;
    const auto pct = [&](double p) -> double {
      if (lat_us.empty()) return 0.0;
      // nearest-rank: ceil(p/100 * N) - 1, clamped
      const double rank =
          std::ceil(p / 100.0 * static_cast<double>(lat_us.size()));
      const size_t idx = rank >= 1.0 ? static_cast<size_t>(rank) - 1 : 0;
      return lat_us[std::min(idx, lat_us.size() - 1)];
    };
    double avg = 0;
    for (const double v : lat_us) avg += v;
    if (!lat_us.empty()) avg /= lat_us.size();
    std::printf(
        "{\"ok\": %zu, \"errors\": %zu, \"elapsed_s\": %.3f, "
        "\"throughput\": %.2f, \"p50_us\": %.1f, \"p90_us\": %.1f, "
        "\"p95_us\": %.1f, \"p99_us\": %.1f, \"avg_us\": %.1f}\n",
        ok, errors, elapsed_s, elapsed_s > 0 ? ok / elapsed_s : 0.0,
        pct(50), pct(90), pct(95), pct(99), avg);
  }

 private:
  static int64_t Now()
  {
    return std::chrono::duration_cast<std::chrono::nanoseconds>(
               Clock::now().time_since_epoch())
        .count();
  }

  // Pump thread: arms a slot whenever a completion (or startup) leaves one
  // empty.  A synchronous AsyncInfer failure (server died, reconnects keep
  // failing) records the error and retries after a backoff — iteratively,
  // on this thread, never on the reactor.
  void PumpLoop()
  {
    while (true) {
      {
        std::unique_lock<std::mutex> lk(mu_);
        pump_cv_.wait(lk, [&] {
          return rearm_pending_ > 0 || stop_.load();
        });
        if (stop_.load()) return;
        rearm_pending_--;
        outstanding_++;
      }
      const int64_t start = Now();
      tc::Error err = client_->AsyncInfer(
          [this, start](tc::InferResultPtr result) {
            Complete(start, result->RequestStatus().IsOk());
          },
          options_, inputs_, outputs_);
      if (err.IsOk()) continue;
      {
        std::lock_guard<std::mutex> lk(mu_);
        records_.push_back({start, Now(), false});
        outstanding_--;
        rearm_pending_++;  // the slot still needs arming
        if (outstanding_ == 0) drained_.notify_all();
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(2));
    }
  }

  void Complete(int64_t start, bool ok)
  {
    std::lock_guard<std::mutex> lk(mu_);
    records_.push_back({start, Now(), ok});
    outstanding_--;
    if (!stop_.load()) {
      // hand the empty slot to the pump thread (concurrency_worker.cc's
      // hot loop, minus the reactor-thread re-arm hazard)
      rearm_pending_++;
      pump_cv_.notify_one();
    }
    if (outstanding_ == 0) drained_.notify_all();
  }

  tc::InferenceServerGrpcClient* client_;
  tc::InferOptions options_;
  std::vector<tc::InferInput*> inputs_;
  std::vector<const tc::InferRequestedOutput*> outputs_;
  std::mutex mu_;
  std::condition_variable drained_;
  std::condition_variable pump_cv_;
  std::thread pump_;
  std::vector<Record> records_;
  int outstanding_ = 0;
  int rearm_pending_ = 0;
  std::atomic<bool> stop_{false};
  int64_t window_start_ = 0;
  int64_t window_end_ = 0;
};

}  // namespace

int
main(int argc, char** argv)
{
  std::string url = "localhost:8001";
  std::string model;
  int concurrency = 1;
  double duration_s = 5.0, warmup_s = 1.0;
  std::vector<TensorArg> wire_inputs, shm_inputs, shm_outputs;
  for (int i = 1; i < argc; ++i) {
    const std::string arg = argv[i];
    const auto next = [&]() -> const char* {
      return i + 1 < argc ? argv[++i] : "";
    };
    if (arg == "-u") {
      url = next();
    } else if (arg == "-m") {
      model = next();
    } else if (arg == "-c") {
      concurrency = std::stoi(next());
    } else if (arg == "-d") {
      duration_s = std::stod(next());
    } else if (arg == "-w") {
      warmup_s = std::stod(next());
    } else if (arg == "--wire-input" || arg == "--shm-input" ||
               arg == "--shm-output") {
      TensorArg tensor;
      const bool shm = arg != "--wire-input";
      const bool output = arg == "--shm-output";
      if (!ParseTensorArg(next(), shm, output, &tensor)) {
        std::fprintf(stderr, "malformed %s\n", arg.c_str());
        return 2;
      }
      (output ? shm_outputs : (shm ? shm_inputs : wire_inputs))
          .push_back(std::move(tensor));
    } else {
      std::fprintf(stderr, "unknown argument %s\n", arg.c_str());
      return 2;
    }
  }
  if (model.empty()) {
    std::fprintf(stderr, "usage: perf_worker -u url -m model -c N -d secs\n");
    return 2;
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  tc::Error err = tc::InferenceServerGrpcClient::Create(&client, url);
  if (!err.IsOk()) {
    std::fprintf(stderr, "create failed: %s\n", err.Message().c_str());
    return 1;
  }

  // prepared request objects, reused for every send (the reference prepares
  // infer data once per context)
  std::vector<std::unique_ptr<tc::InferInput>> owned_inputs;
  std::vector<std::string> payloads;
  std::vector<tc::InferInput*> inputs;
  std::mt19937 rng(42);
  for (const auto& tensor : wire_inputs) {
    size_t elems = 1;
    for (const int64_t d : tensor.shape) elems *= static_cast<size_t>(d);
    payloads.emplace_back();
    std::string& payload = payloads.back();
    payload.resize(elems * DtypeSize(tensor.datatype));
    for (char& b : payload) b = static_cast<char>(rng() & 0x3f);
    auto input = std::make_unique<tc::InferInput>(
        tensor.name, tensor.shape, tensor.datatype);
    input->AppendRaw(
        reinterpret_cast<const uint8_t*>(payload.data()), payload.size());
    inputs.push_back(input.get());
    owned_inputs.push_back(std::move(input));
  }
  for (const auto& tensor : shm_inputs) {
    auto input = std::make_unique<tc::InferInput>(
        tensor.name, tensor.shape, tensor.datatype);
    input->SetSharedMemory(tensor.region, tensor.nbytes);
    inputs.push_back(input.get());
    owned_inputs.push_back(std::move(input));
  }
  std::vector<std::unique_ptr<tc::InferRequestedOutput>> owned_outputs;
  std::vector<const tc::InferRequestedOutput*> outputs;
  for (const auto& tensor : shm_outputs) {
    auto output = std::make_unique<tc::InferRequestedOutput>(tensor.name);
    output->SetSharedMemory(tensor.region, tensor.nbytes);
    outputs.push_back(output.get());
    owned_outputs.push_back(std::move(output));
  }
  if (inputs.empty()) {
    std::fprintf(stderr, "no inputs configured\n");
    return 2;
  }

  tc::InferOptions options(model);
  Driver driver(client.get(), options, inputs, outputs);
  const bool drained = driver.Run(concurrency, warmup_s, duration_s);
  driver.Report();
  if (!drained) {
    // requests still in flight: the reactor may yet fire completions that
    // touch the Driver — skip destructors entirely rather than free state
    // under a live callback (and signal the partial drain to the caller)
    std::fprintf(stderr, "warning: drain timed out; exiting hard\n");
    std::fflush(stdout);
    std::_Exit(3);
  }
  return 0;
}
