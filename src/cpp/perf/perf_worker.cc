// Native load-generation worker — the C++ engine behind the perf harness
// (the role of the reference's perf_analyzer core: perf_analyzer.cc:56-424
// concurrency manager + concurrency_worker.cc hot loop + async
// InferContext slots; request_rate_worker.h:51-118 schedule generation),
// re-shaped for this framework: N outstanding AsyncInfer contexts
// multiplexed on ONE HTTP/2 connection and completed by its reactor
// thread — no GIL, no thread-per-request.  The Python CLI drives it as a
// subprocess (client_tpu/perf/native_worker.py) and merges its records.
//
//   perf_worker -u host:port -m model -c concurrency -d seconds
//               [-w warmup_seconds]
//               [-r rate_per_sec] [--distribution constant|poisson]
//               [--window-interval seconds]      (per-window JSON lines)
//               [--completion-sync]              (wire outputs: latency
//                                                 covers compute + D2H)
//               [--sequences N] [--seq-steps M]  (bidi sequence streaming)
//               [--decoupled]                    (N-responses-per-request
//                                                 streaming: TTFT latency,
//                                                 final-marker completion)
//               [--wire-input NAME:DTYPE:d1,d2,...[=VALUE]]...
//               [--shm-input NAME:DTYPE:d1,d2:REGION:NBYTES]...
//               [--shm-output NAME:REGION:NBYTES]...
//
// Per-window lines (only with --window-interval): the Python profiler's
// stability loop (inference_profiler.h:365-399 shape) consumes these live:
//   {"window": K, "ok": N, "errors": N, "throughput": F,
//    "p50_us": F, "p99_us": F}
// Final line:
//   {"ok": N, "errors": N, "delayed": N, "elapsed_s": F, "throughput": F,
//    "p50_us": F, "p90_us": F, "p95_us": F, "p99_us": F, "avg_us": F,
//    "mode": "concurrency|rate|sequence"}
#include <algorithm>
#include <cmath>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <random>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

#include "grpc_client.h"

namespace tc = ctpu;

namespace {

using Clock = std::chrono::steady_clock;

struct TensorArg {
  std::string name;
  std::string datatype;
  std::vector<int64_t> shape;
  std::string region;  // shm variants
  size_t nbytes = 0;
  bool has_fill = false;  // --wire-input NAME:DTYPE:dims=VALUE
  int64_t fill_value = 0;
};

std::vector<std::string>
Split(const std::string& s, char sep)
{
  std::vector<std::string> out;
  std::istringstream in(s);
  std::string part;
  while (std::getline(in, part, sep)) out.push_back(part);
  return out;
}

bool
ParseTensorArg(const std::string& text, bool shm, bool output, TensorArg* out)
{
  const auto parts = Split(text, ':');
  if (output) {  // NAME:REGION:NBYTES
    if (parts.size() != 3) return false;
    out->name = parts[0];
    out->region = parts[1];
    out->nbytes = std::stoull(parts[2]);
    return true;
  }
  if (parts.size() != (shm ? 5u : 3u)) return false;
  out->name = parts[0];
  out->datatype = parts[1];
  std::string dims = parts[2];
  if (!shm) {
    // optional "=VALUE" suffix: constant fill instead of random bytes
    // (decoupled models read a response count from the input, so the
    // payload must be a controlled value)
    const auto eq = dims.find('=');
    if (eq != std::string::npos) {
      out->has_fill = true;
      out->fill_value = std::stoll(dims.substr(eq + 1));
      dims = dims.substr(0, eq);
    }
  }
  for (const auto& d : Split(dims, ',')) out->shape.push_back(std::stoll(d));
  if (shm) {
    out->region = parts[3];
    out->nbytes = std::stoull(parts[4]);
  }
  return true;
}

size_t
DtypeSize(const std::string& datatype)
{
  if (datatype == "FP64" || datatype == "INT64" || datatype == "UINT64")
    return 8;
  if (datatype == "FP32" || datatype == "INT32" || datatype == "UINT32")
    return 4;
  if (datatype == "FP16" || datatype == "BF16" || datatype == "INT16" ||
      datatype == "UINT16")
    return 2;
  return 1;
}

struct Record {
  int64_t start_ns;
  int64_t end_ns;
  bool ok;
};

int64_t
Now()
{
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             Clock::now().time_since_epoch())
      .count();
}

double
Percentile(const std::vector<double>& sorted, double p)
{
  if (sorted.empty()) return 0.0;
  // nearest-rank: ceil(p/100 * N) - 1, clamped
  const double rank =
      std::ceil(p / 100.0 * static_cast<double>(sorted.size()));
  const size_t idx = rank >= 1.0 ? static_cast<size_t>(rank) - 1 : 0;
  return sorted[std::min(idx, sorted.size() - 1)];
}

// Shared measurement state: completion records plus the optional
// per-window reporter thread (the profiler's Measure-window feed).
class Recorder {
 public:
  void Push(int64_t start, int64_t end, bool ok)
  {
    std::lock_guard<std::mutex> lk(mu_);
    records_.push_back({start, end, ok});
  }

  void ClearForMeasurement()
  {
    std::lock_guard<std::mutex> lk(mu_);
    records_.clear();
    reported_idx_ = 0;
  }

  void StartWindows(double interval_s)
  {
    if (interval_s <= 0) return;
    windows_stop_.store(false);
    reporter_ = std::thread([this, interval_s] {
      int window = 0;
      auto next = Clock::now() + std::chrono::duration<double>(interval_s);
      std::unique_lock<std::mutex> lk(mu_);
      while (!windows_cv_.wait_until(
                 lk, next, [&] { return windows_stop_.load(); })) {
        next += std::chrono::duration<double>(interval_s);
        std::vector<double> lat_us;
        size_t ok = 0, errors = 0;
        for (size_t i = reported_idx_; i < records_.size(); ++i) {
          const Record& r = records_[i];
          if (!r.ok) {
            errors++;
            continue;
          }
          ok++;
          lat_us.push_back((r.end_ns - r.start_ns) / 1e3);
        }
        reported_idx_ = records_.size();
        std::sort(lat_us.begin(), lat_us.end());
        // print outside the lock so a slow pipe cannot stall completions
        lk.unlock();
        std::printf(
            "{\"window\": %d, \"ok\": %zu, \"errors\": %zu, "
            "\"throughput\": %.2f, \"p50_us\": %.1f, \"p99_us\": %.1f}\n",
            window++, ok, errors, ok / interval_s, Percentile(lat_us, 50),
            Percentile(lat_us, 99));
        std::fflush(stdout);
        lk.lock();
      }
    });
  }

  void StopWindows()
  {
    {
      // store+notify under mu_: a notify between the reporter's predicate
      // check and its block would otherwise be lost, stalling join until
      // the next window tick
      std::lock_guard<std::mutex> lk(mu_);
      windows_stop_.store(true);
    }
    windows_cv_.notify_all();
    if (reporter_.joinable()) reporter_.join();
  }

  void Report(
      int64_t window_start, int64_t window_end, size_t delayed,
      const char* mode, const std::string& extra_json = "")
  {
    std::vector<Record> records;
    {
      std::lock_guard<std::mutex> lk(mu_);
      records = records_;
    }
    std::vector<double> lat_us;
    size_t ok = 0, errors = 0;
    for (const auto& r : records) {
      // count only requests completing inside the window (the profiler's
      // ValidLatencyMeasurement clip)
      if (r.end_ns < window_start || r.end_ns > window_end) continue;
      if (!r.ok) {
        errors++;
        continue;
      }
      ok++;
      lat_us.push_back((r.end_ns - r.start_ns) / 1e3);
    }
    std::sort(lat_us.begin(), lat_us.end());
    const double elapsed_s = (window_end - window_start) / 1e9;
    double avg = 0;
    for (const double v : lat_us) avg += v;
    if (!lat_us.empty()) avg /= lat_us.size();
    std::printf(
        "{\"ok\": %zu, \"errors\": %zu, \"delayed\": %zu, "
        "\"elapsed_s\": %.3f, \"throughput\": %.2f, \"p50_us\": %.1f, "
        "\"p90_us\": %.1f, \"p95_us\": %.1f, \"p99_us\": %.1f, "
        "\"avg_us\": %.1f, %s\"mode\": \"%s\"}\n",
        ok, errors, delayed, elapsed_s,
        elapsed_s > 0 ? ok / elapsed_s : 0.0, Percentile(lat_us, 50),
        Percentile(lat_us, 90), Percentile(lat_us, 95),
        Percentile(lat_us, 99), avg, extra_json.c_str(), mode);
  }

 private:
  std::mutex mu_;
  std::vector<Record> records_;
  size_t reported_idx_ = 0;
  std::thread reporter_;
  std::condition_variable windows_cv_;
  std::atomic<bool> windows_stop_{false};
};

class Driver {
 public:
  // rate <= 0: closed-loop fixed concurrency (concurrency_worker.cc's
  // shape).  rate > 0: open-loop schedule at `rate` req/s with constant or
  // poisson inter-arrivals (request_rate_worker.h:51-118); `concurrency`
  // then caps outstanding requests, and sends falling behind schedule are
  // counted as delayed (reference --max-trials delayed accounting).
  Driver(tc::InferenceServerGrpcClient* client, tc::InferOptions options,
         std::vector<tc::InferInput*> inputs,
         std::vector<const tc::InferRequestedOutput*> outputs, double rate,
         bool poisson, double window_interval_s)
      : client_(client), options_(std::move(options)),
        inputs_(std::move(inputs)), outputs_(std::move(outputs)),
        rate_(rate), poisson_(poisson),
        window_interval_s_(window_interval_s), rng_(12345)
  {
  }

  // Returns false when the drain timed out with requests still in flight
  // (the caller must not destroy this Driver: the reactor can still fire).
  bool Run(int concurrency, double warmup_s, double duration_s)
  {
    stop_.store(false);
    const auto t_warm_end =
        Clock::now() + std::chrono::duration<double>(warmup_s);
    // ALL submissions run on this pump thread, never on the connection's
    // reactor thread: a completion callback that re-armed inline would run
    // SendData on the reader — which must stay free to process the
    // WINDOW_UPDATE frames SendData waits for (self-deadlock for any body
    // larger than the h2 flow-control window).
    {
      std::lock_guard<std::mutex> lk(mu_);
      slots_free_ = concurrency;
    }
    pump_ = std::thread([this] { PumpLoop(); });
    pump_cv_.notify_all();
    std::this_thread::sleep_until(t_warm_end);
    recorder_.ClearForMeasurement();
    delayed_.store(0);
    window_start_ = Now();
    recorder_.StartWindows(window_interval_s_);
    std::this_thread::sleep_for(std::chrono::duration<double>(duration_s));
    {
      // store under mu_ so the pump can't lose the wakeup between its
      // predicate check and blocking
      std::lock_guard<std::mutex> lk(mu_);
      stop_.store(true);
    }
    window_end_ = Now();
    recorder_.StopWindows();
    // stop the pump first: after it joins, nothing submits anymore ...
    pump_cv_.notify_all();
    if (pump_.joinable()) pump_.join();
    // ... then drain every outstanding context.  Completions touch members
    // only under mu_, and the final unlock happens-before this wait
    // observes outstanding_ == 0, so returning (and destroying the Driver)
    // after a successful drain is safe.
    std::unique_lock<std::mutex> lk(mu_);
    return drained_.wait_for(
        lk, std::chrono::seconds(60), [&] { return outstanding_ == 0; });
  }

  void Report(const char* mode)
  {
    recorder_.Report(window_start_, window_end_, delayed_.load(), mode);
  }

 private:
  // Closed loop: send whenever a slot frees.  Open loop (rate mode): wait
  // for the next schedule tick AND a free slot; a tick that finds no free
  // slot (or fires late) counts the request as delayed but still sends it,
  // so the achieved rate degrades visibly instead of silently re-timing.
  void PumpLoop()
  {
    auto next_send = Clock::now();
    std::exponential_distribution<double> exp_dist(rate_ > 0 ? rate_ : 1.0);
    while (true) {
      if (rate_ > 0) {
        // open-loop schedule: wait to the tick even with slots free —
        // interruptibly, so a stop at measurement end doesn't block join
        // for a full inter-arrival interval at low rates
        {
          std::unique_lock<std::mutex> lk(mu_);
          pump_cv_.wait_until(lk, next_send, [&] { return stop_.load(); });
        }
        if (stop_.load()) return;
        const auto behind =
            std::chrono::duration_cast<std::chrono::milliseconds>(
                Clock::now() - next_send)
                .count();
        bool slot_waited = false;
        {
          std::unique_lock<std::mutex> lk(mu_);
          if (slots_free_ == 0) slot_waited = true;
          pump_cv_.wait(lk, [&] { return slots_free_ > 0 || stop_.load(); });
          if (stop_.load()) return;
          slots_free_--;
          outstanding_++;
        }
        if (behind > 1 || slot_waited) delayed_.fetch_add(1);
        next_send += std::chrono::duration_cast<Clock::duration>(
            std::chrono::duration<double>(
                poisson_ ? exp_dist(rng_) : 1.0 / rate_));
      } else {
        std::unique_lock<std::mutex> lk(mu_);
        pump_cv_.wait(lk, [&] { return slots_free_ > 0 || stop_.load(); });
        if (stop_.load()) return;
        slots_free_--;
        outstanding_++;
      }
      const int64_t start = Now();
      tc::Error err = client_->AsyncInfer(
          [this, start](tc::InferResultPtr result) {
            Complete(start, result->RequestStatus().IsOk());
          },
          options_, inputs_, outputs_);
      if (err.IsOk()) continue;
      recorder_.Push(start, Now(), false);
      {
        std::lock_guard<std::mutex> lk(mu_);
        outstanding_--;
        slots_free_++;  // the slot still needs arming
        if (outstanding_ == 0) drained_.notify_all();
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(2));
    }
  }

  void Complete(int64_t start, bool ok)
  {
    recorder_.Push(start, Now(), ok);
    std::lock_guard<std::mutex> lk(mu_);
    outstanding_--;
    if (!stop_.load()) {
      // hand the empty slot to the pump thread (concurrency_worker.cc's
      // hot loop, minus the reactor-thread re-arm hazard)
      slots_free_++;
      pump_cv_.notify_one();
    }
    if (outstanding_ == 0) drained_.notify_all();
  }

  tc::InferenceServerGrpcClient* client_;
  tc::InferOptions options_;
  std::vector<tc::InferInput*> inputs_;
  std::vector<const tc::InferRequestedOutput*> outputs_;
  Recorder recorder_;
  std::mutex mu_;
  std::condition_variable drained_;
  std::condition_variable pump_cv_;
  std::thread pump_;
  int outstanding_ = 0;
  int slots_free_ = 0;
  std::atomic<bool> stop_{false};
  std::atomic<size_t> delayed_{0};
  double rate_ = 0.0;
  bool poisson_ = false;
  double window_interval_s_ = 0.0;
  std::mt19937 rng_;
  int64_t window_start_ = 0;
  int64_t window_end_ = 0;
};

// Sequence streaming over the bidi ModelStreamInfer stream (the reference's
// sequence workload: sequence_manager.h:46-132 id allocation + the
// simple_grpc_sequence_stream_infer_client shape).  N stateful sequences
// run closed-loop: each response re-arms that sequence's next step via the
// pump thread (stream writes must never run on the reactor).  A sequence
// reaching seq_steps sends sequence_end and restarts under a fresh id.
class SequenceRunner {
 public:
  SequenceRunner(tc::InferenceServerGrpcClient* client,
                 const std::string& model,
                 std::vector<tc::InferInput*> inputs,
                 std::vector<const tc::InferRequestedOutput*> outputs,
                 int n_sequences, int seq_steps, double window_interval_s)
      : client_(client), model_(model), inputs_(std::move(inputs)),
        outputs_(std::move(outputs)), n_sequences_(n_sequences),
        seq_steps_(seq_steps), window_interval_s_(window_interval_s)
  {
  }

  // 0 = measured and drained; 1 = stream never started (no measurement);
  // 3 = measured but the drain timed out (in-flight callbacks may fire).
  int Run(double warmup_s, double duration_s)
  {
    stop_.store(false);
    tc::Error err = client_->StartStream(
        [this](tc::InferResultPtr result) { OnResponse(std::move(result)); });
    if (!err.IsOk()) {
      std::fprintf(stderr, "stream start failed: %s\n",
                   err.Message().c_str());
      return 1;
    }
    {
      std::lock_guard<std::mutex> lk(mu_);
      next_seq_id_ = 1;
      for (int i = 0; i < n_sequences_; ++i) {
        ready_.push_back(SeqState{next_seq_id_++, 0});
      }
    }
    pump_ = std::thread([this] { PumpLoop(); });
    pump_cv_.notify_all();
    std::this_thread::sleep_for(std::chrono::duration<double>(warmup_s));
    recorder_.ClearForMeasurement();
    window_start_ = Now();
    recorder_.StartWindows(window_interval_s_);
    std::this_thread::sleep_for(std::chrono::duration<double>(duration_s));
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_.store(true);
    }
    window_end_ = Now();
    recorder_.StopWindows();
    pump_cv_.notify_all();
    if (pump_.joinable()) pump_.join();
    // drain: every in-flight step either completes or the stream errors out
    bool drained;
    {
      std::unique_lock<std::mutex> lk(mu_);
      drained = drained_.wait_for(
          lk, std::chrono::seconds(60), [&] { return in_flight_.empty(); });
    }
    client_->StopStream();
    return drained ? 0 : 3;
  }

  void Report() { recorder_.Report(window_start_, window_end_, 0, "sequence"); }

 private:
  struct SeqState {
    uint64_t id;
    int step;
  };

  void PumpLoop()
  {
    while (true) {
      SeqState st;
      {
        std::unique_lock<std::mutex> lk(mu_);
        pump_cv_.wait(lk, [&] { return !ready_.empty() || stop_.load(); });
        if (stop_.load()) return;
        st = ready_.front();
        ready_.pop_front();
      }
      tc::InferOptions options(model_);
      options.sequence_id = st.id;
      options.sequence_start = (st.step == 0);
      options.sequence_end = (st.step == seq_steps_ - 1);
      options.request_id =
          std::to_string(st.id) + "-" + std::to_string(st.step);
      const int64_t start = Now();
      {
        std::lock_guard<std::mutex> lk(mu_);
        in_flight_[options.request_id] = {start, st};
      }
      tc::Error err = client_->AsyncStreamInfer(options, inputs_, outputs_);
      if (!err.IsOk()) {
        {
          std::lock_guard<std::mutex> lk(mu_);
          in_flight_.erase(options.request_id);
          recorder_.Push(start, Now(), false);
          if (!stop_.load()) ready_.push_back(SeqState{next_seq_id_++, 0});
          if (in_flight_.empty()) drained_.notify_all();
        }
        // a dead stream fails instantly: back off so the rest of the run
        // degrades gracefully instead of busy-spinning error records
        std::this_thread::sleep_for(std::chrono::milliseconds(2));
      }
    }
  }

  void OnResponse(tc::InferResultPtr result)
  {
    const bool ok = result->RequestStatus().IsOk();
    // error results still carry the request id when the failure was
    // per-request (grpc_client fills it); only id-less stream-level
    // errors fall back to charging an arbitrary in-flight entry
    const std::string id = result->Id();
    SeqState st{0, 0};
    int64_t start = 0;
    bool matched = false;
    {
      std::lock_guard<std::mutex> lk(mu_);
      auto it = id.empty() ? in_flight_.end() : in_flight_.find(id);
      if (it == in_flight_.end() && !in_flight_.empty() && !ok) {
        it = in_flight_.begin();  // id-less stream error: charge any entry
      }
      if (it != in_flight_.end()) {
        start = it->second.first;
        st = it->second.second;
        matched = true;
        in_flight_.erase(it);
      }
      if (matched) {
        recorder_.Push(start, Now(), ok);
        if (!stop_.load()) {
          // re-arm: next step of this sequence, or a fresh sequence
          if (ok && st.step + 1 < seq_steps_) {
            ready_.push_back(SeqState{st.id, st.step + 1});
          } else {
            ready_.push_back(SeqState{next_seq_id_++, 0});
          }
          pump_cv_.notify_one();
        }
      }
      if (in_flight_.empty()) drained_.notify_all();
    }
  }

  tc::InferenceServerGrpcClient* client_;
  std::string model_;
  std::vector<tc::InferInput*> inputs_;
  std::vector<const tc::InferRequestedOutput*> outputs_;
  int n_sequences_;
  int seq_steps_;
  double window_interval_s_;
  Recorder recorder_;
  std::mutex mu_;
  std::condition_variable pump_cv_;
  std::condition_variable drained_;
  std::thread pump_;
  std::deque<SeqState> ready_;
  std::map<std::string, std::pair<int64_t, SeqState>> in_flight_;
  uint64_t next_seq_id_ = 1;
  std::atomic<bool> stop_{false};
  int64_t window_start_ = 0;
  int64_t window_end_ = 0;
};

// Decoupled (N-responses-per-request) streaming load: LLM token-stream
// shape (reference measures FIRST-response latency for decoupled models,
// perf_analyzer.cc:334-337; completion detection rides the
// triton_final_response marker requested via enable_empty_final_response).
// `concurrency` decoupled requests stay outstanding; each final marker
// re-arms its slot through the pump thread.  Recorded latency per request
// is time-to-first-response; ok counts completed requests; the report
// carries the total (token) response count.
class DecoupledRunner {
 public:
  DecoupledRunner(tc::InferenceServerGrpcClient* client,
                  const std::string& model,
                  std::vector<tc::InferInput*> inputs, int concurrency,
                  double window_interval_s)
      : client_(client), model_(model), inputs_(std::move(inputs)),
        concurrency_(concurrency), window_interval_s_(window_interval_s)
  {
  }

  // 0 = measured and drained; 1 = stream never started (no measurement);
  // 3 = measured but the drain timed out (in-flight callbacks may fire).
  int Run(double warmup_s, double duration_s)
  {
    stop_.store(false);
    tc::Error err = client_->StartStream(
        [this](tc::InferResultPtr result) { OnResponse(std::move(result)); });
    if (!err.IsOk()) {
      std::fprintf(stderr, "stream start failed: %s\n",
                   err.Message().c_str());
      return 1;
    }
    {
      std::lock_guard<std::mutex> lk(mu_);
      slots_free_ = concurrency_;
    }
    pump_ = std::thread([this] { PumpLoop(); });
    pump_cv_.notify_all();
    std::this_thread::sleep_for(std::chrono::duration<double>(warmup_s));
    recorder_.ClearForMeasurement();
    responses_.store(0);
    window_start_ = Now();
    recorder_.StartWindows(window_interval_s_);
    std::this_thread::sleep_for(std::chrono::duration<double>(duration_s));
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_.store(true);
    }
    window_end_ = Now();
    recorder_.StopWindows();
    pump_cv_.notify_all();
    if (pump_.joinable()) pump_.join();
    bool drained;
    {
      std::unique_lock<std::mutex> lk(mu_);
      drained = drained_.wait_for(
          lk, std::chrono::seconds(60), [&] { return in_flight_.empty(); });
    }
    client_->StopStream();
    return drained ? 0 : 3;
  }

  void Report()
  {
    recorder_.Report(
        window_start_, window_end_, 0, "decoupled",
        "\"responses\": " + std::to_string(responses_.load()) + ", ");
  }

 private:
  struct Flight {
    int64_t start_ns;
    int64_t first_response_ns = 0;
  };

  void PumpLoop()
  {
    while (true) {
      {
        std::unique_lock<std::mutex> lk(mu_);
        pump_cv_.wait(lk, [&] { return slots_free_ > 0 || stop_.load(); });
        if (stop_.load()) return;
        slots_free_--;
      }
      tc::InferOptions options(model_);
      options.enable_empty_final_response = true;
      options.request_id = "d-" + std::to_string(next_id_++);
      const int64_t start = Now();
      {
        std::lock_guard<std::mutex> lk(mu_);
        in_flight_[options.request_id] = Flight{start, 0};
      }
      tc::Error err = client_->AsyncStreamInfer(options, inputs_);
      if (!err.IsOk()) {
        {
          std::lock_guard<std::mutex> lk(mu_);
          in_flight_.erase(options.request_id);
          recorder_.Push(start, Now(), false);
          if (!stop_.load()) slots_free_++;
          if (in_flight_.empty()) drained_.notify_all();
        }
        std::this_thread::sleep_for(std::chrono::milliseconds(2));
      }
    }
  }

  void OnResponse(tc::InferResultPtr result)
  {
    const bool ok = result->RequestStatus().IsOk();
    // per-request errors keep their id (grpc_client fills it); only
    // id-less stream-level errors fall back to an arbitrary entry
    const std::string id = result->Id();
    std::lock_guard<std::mutex> lk(mu_);
    auto it = id.empty() ? in_flight_.end() : in_flight_.find(id);
    if (it == in_flight_.end() && !in_flight_.empty() && !ok) {
      it = in_flight_.begin();  // id-less stream error: charge any entry
    }
    if (it == in_flight_.end()) return;
    if (ok && !result->IsFinalResponse()) {
      responses_.fetch_add(1);  // content responses only, not the marker
    }
    if (it->second.first_response_ns == 0) {
      it->second.first_response_ns = Now();
    }
    if (result->IsFinalResponse() || !ok) {
      // latency sample = time to FIRST response (reference decoupled
      // semantics); the final marker closes the request
      recorder_.Push(
          it->second.start_ns, it->second.first_response_ns, ok);
      in_flight_.erase(it);
      if (!stop_.load()) {
        slots_free_++;
        pump_cv_.notify_one();
      }
      if (in_flight_.empty()) drained_.notify_all();
    }
  }

  tc::InferenceServerGrpcClient* client_;
  std::string model_;
  std::vector<tc::InferInput*> inputs_;
  int concurrency_;
  double window_interval_s_;
  Recorder recorder_;
  std::mutex mu_;
  std::condition_variable pump_cv_;
  std::condition_variable drained_;
  std::thread pump_;
  std::map<std::string, Flight> in_flight_;
  int slots_free_ = 0;
  uint64_t next_id_ = 1;
  std::atomic<bool> stop_{false};
  std::atomic<size_t> responses_{0};
  int64_t window_start_ = 0;
  int64_t window_end_ = 0;
};

}  // namespace

int
main(int argc, char** argv)
{
  std::string url = "localhost:8001";
  std::string model;
  int concurrency = 1;
  double duration_s = 5.0, warmup_s = 1.0;
  double rate = 0.0;
  bool poisson = false;
  double window_interval_s = 0.0;
  bool completion_sync = false;
  bool decoupled = false;
  int sequences = 0, seq_steps = 8;
  std::vector<TensorArg> wire_inputs, shm_inputs, shm_outputs;
  for (int i = 1; i < argc; ++i) {
    const std::string arg = argv[i];
    const auto next = [&]() -> const char* {
      return i + 1 < argc ? argv[++i] : "";
    };
    if (arg == "-u") {
      url = next();
    } else if (arg == "-m") {
      model = next();
    } else if (arg == "-c") {
      concurrency = std::stoi(next());
    } else if (arg == "-d") {
      duration_s = std::stod(next());
    } else if (arg == "-w") {
      warmup_s = std::stod(next());
    } else if (arg == "-r") {
      rate = std::stod(next());
    } else if (arg == "--distribution") {
      const std::string d = next();
      if (d != "constant" && d != "poisson") {
        std::fprintf(stderr, "unknown distribution %s\n", d.c_str());
        return 2;
      }
      poisson = (d == "poisson");
    } else if (arg == "--window-interval") {
      window_interval_s = std::stod(next());
    } else if (arg == "--completion-sync") {
      completion_sync = true;
    } else if (arg == "--sequences") {
      sequences = std::stoi(next());
    } else if (arg == "--seq-steps") {
      seq_steps = std::stoi(next());
    } else if (arg == "--decoupled") {
      decoupled = true;
    } else if (arg == "--wire-input" || arg == "--shm-input" ||
               arg == "--shm-output") {
      TensorArg tensor;
      const bool shm = arg != "--wire-input";
      const bool output = arg == "--shm-output";
      if (!ParseTensorArg(next(), shm, output, &tensor)) {
        std::fprintf(stderr, "malformed %s\n", arg.c_str());
        return 2;
      }
      (output ? shm_outputs : (shm ? shm_inputs : wire_inputs))
          .push_back(std::move(tensor));
    } else {
      std::fprintf(stderr, "unknown argument %s\n", arg.c_str());
      return 2;
    }
  }
  if (model.empty()) {
    std::fprintf(stderr, "usage: perf_worker -u url -m model -c N -d secs\n");
    return 2;
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  tc::Error err = tc::InferenceServerGrpcClient::Create(&client, url);
  if (!err.IsOk()) {
    std::fprintf(stderr, "create failed: %s\n", err.Message().c_str());
    return 1;
  }

  // prepared request objects, reused for every send (the reference prepares
  // infer data once per context)
  std::vector<std::unique_ptr<tc::InferInput>> owned_inputs;
  std::vector<std::string> payloads;
  std::vector<tc::InferInput*> inputs;
  std::mt19937 rng(42);
  for (const auto& tensor : wire_inputs) {
    size_t elems = 1;
    for (const int64_t d : tensor.shape) elems *= static_cast<size_t>(d);
    payloads.emplace_back();
    std::string& payload = payloads.back();
    const size_t elem_size = DtypeSize(tensor.datatype);
    payload.resize(elems * elem_size);
    if (tensor.has_fill) {
      // little-endian constant per element, truncated to the dtype width
      for (size_t e = 0; e < elems; ++e) {
        for (size_t b = 0; b < elem_size; ++b) {
          payload[e * elem_size + b] = static_cast<char>(
              (static_cast<uint64_t>(tensor.fill_value) >> (8 * b)) & 0xff);
        }
      }
    } else {
      for (char& b : payload) b = static_cast<char>(rng() & 0x3f);
    }
    auto input = std::make_unique<tc::InferInput>(
        tensor.name, tensor.shape, tensor.datatype);
    input->AppendRaw(
        reinterpret_cast<const uint8_t*>(payload.data()), payload.size());
    inputs.push_back(input.get());
    owned_inputs.push_back(std::move(input));
  }
  for (const auto& tensor : shm_inputs) {
    auto input = std::make_unique<tc::InferInput>(
        tensor.name, tensor.shape, tensor.datatype);
    input->SetSharedMemory(tensor.region, tensor.nbytes);
    inputs.push_back(input.get());
    owned_inputs.push_back(std::move(input));
  }
  std::vector<std::unique_ptr<tc::InferRequestedOutput>> owned_outputs;
  std::vector<const tc::InferRequestedOutput*> outputs;
  for (const auto& tensor : shm_outputs) {
    auto output = std::make_unique<tc::InferRequestedOutput>(tensor.name);
    if (completion_sync) {
      // wire output: the server must materialize (device compute + D2H)
      // before responding, so the recorded latency is COMPLETION latency —
      // the RequestTimers-true number (reference common.h:521-601) — not a
      // dispatch ack into a shm region
    } else {
      output->SetSharedMemory(tensor.region, tensor.nbytes);
    }
    outputs.push_back(output.get());
    owned_outputs.push_back(std::move(output));
  }
  if (inputs.empty()) {
    std::fprintf(stderr, "no inputs configured\n");
    return 2;
  }

  if (sequences > 0) {
    SequenceRunner runner(
        client.get(), model, inputs, outputs, sequences, seq_steps,
        window_interval_s);
    const int rc = runner.Run(warmup_s, duration_s);
    if (rc == 1) return 1;  // stream never started: no report to print
    runner.Report();
    if (rc == 3) {
      std::fprintf(stderr, "warning: sequence drain timed out\n");
      std::fflush(stdout);
      std::_Exit(3);
    }
    return 0;
  }

  if (decoupled) {
    DecoupledRunner runner(
        client.get(), model, inputs, concurrency, window_interval_s);
    const int rc = runner.Run(warmup_s, duration_s);
    if (rc == 1) return 1;
    runner.Report();
    if (rc == 3) {
      std::fprintf(stderr, "warning: decoupled drain timed out\n");
      std::fflush(stdout);
      std::_Exit(3);
    }
    return 0;
  }

  tc::InferOptions options(model);
  Driver driver(
      client.get(), options, inputs, outputs, rate, poisson,
      window_interval_s);
  const bool drained = driver.Run(concurrency, warmup_s, duration_s);
  driver.Report(rate > 0 ? "rate" : "concurrency");
  if (!drained) {
    // requests still in flight: the reactor may yet fire completions that
    // touch the Driver — skip destructors entirely rather than free state
    // under a live callback (and signal the partial drain to the caller)
    std::fprintf(stderr, "warning: drain timed out; exiting hard\n");
    std::fflush(stdout);
    std::_Exit(3);
  }
  return 0;
}
