#include "json.h"

#include <cmath>
#include <cstdio>

namespace ctpu {
namespace json {

std::string
Quote(const std::string& s)
{
  std::string out = "\"";
  for (unsigned char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          char hex[8];
          std::snprintf(hex, sizeof(hex), "\\u%04x", c);
          out += hex;
        } else {
          out += static_cast<char>(c);
        }
    }
  }
  out += '"';
  return out;
}

void
Writer::Double(double v)
{
  Sep();
  if (std::isfinite(v)) {
    char tmp[32];
    std::snprintf(tmp, sizeof(tmp), "%.17g", v);
    buf_ += tmp;
  } else {
    buf_ += "null";  // JSON cannot carry inf/nan
  }
}

namespace {

class Parser {
 public:
  Parser(const std::string& text) : text_(text) {}

  ValuePtr Run(std::string* err)
  {
    ValuePtr v = ParseValue();
    SkipWs();
    if (v == nullptr || pos_ != text_.size()) {
      if (err_msg_.empty()) err_msg_ = "trailing characters";
      *err = err_msg_ + " at offset " + std::to_string(pos_);
      return nullptr;
    }
    return v;
  }

 private:
  void SkipWs()
  {
    while (pos_ < text_.size() &&
           (text_[pos_] == ' ' || text_[pos_] == '\t' || text_[pos_] == '\n' ||
            text_[pos_] == '\r'))
      pos_++;
  }

  bool Consume(char c)
  {
    SkipWs();
    if (pos_ < text_.size() && text_[pos_] == c) {
      pos_++;
      return true;
    }
    return false;
  }

  ValuePtr Fail(const std::string& msg)
  {
    if (err_msg_.empty()) err_msg_ = msg;
    return nullptr;
  }

  ValuePtr ParseValue()
  {
    SkipWs();
    if (pos_ >= text_.size()) return Fail("unexpected end of input");
    char c = text_[pos_];
    if (c == '{') return ParseObject();
    if (c == '[') return ParseArray();
    if (c == '"') return ParseString();
    if (c == 't' || c == 'f') return ParseBool();
    if (c == 'n') return ParseNull();
    return ParseNumber();
  }

  ValuePtr ParseObject()
  {
    pos_++;  // '{'
    auto v = std::make_shared<Value>();
    v->type = Type::Object;
    SkipWs();
    if (Consume('}')) return v;
    while (true) {
      SkipWs();
      ValuePtr key = ParseString();
      if (key == nullptr) return Fail("expected object key");
      if (!Consume(':')) return Fail("expected ':'");
      ValuePtr val = ParseValue();
      if (val == nullptr) return nullptr;
      v->obj[key->s] = val;
      if (Consume(',')) continue;
      if (Consume('}')) return v;
      return Fail("expected ',' or '}'");
    }
  }

  ValuePtr ParseArray()
  {
    pos_++;  // '['
    auto v = std::make_shared<Value>();
    v->type = Type::Array;
    SkipWs();
    if (Consume(']')) return v;
    while (true) {
      ValuePtr item = ParseValue();
      if (item == nullptr) return nullptr;
      v->arr.push_back(item);
      if (Consume(',')) continue;
      if (Consume(']')) return v;
      return Fail("expected ',' or ']'");
    }
  }

  ValuePtr ParseString()
  {
    SkipWs();
    if (pos_ >= text_.size() || text_[pos_] != '"')
      return Fail("expected string");
    pos_++;
    auto v = std::make_shared<Value>();
    v->type = Type::String;
    while (pos_ < text_.size()) {
      char c = text_[pos_++];
      if (c == '"') return v;
      if (c == '\\') {
        if (pos_ >= text_.size()) return Fail("bad escape");
        char e = text_[pos_++];
        switch (e) {
          case '"': v->s += '"'; break;
          case '\\': v->s += '\\'; break;
          case '/': v->s += '/'; break;
          case 'b': v->s += '\b'; break;
          case 'f': v->s += '\f'; break;
          case 'n': v->s += '\n'; break;
          case 'r': v->s += '\r'; break;
          case 't': v->s += '\t'; break;
          case 'u': {
            if (pos_ + 4 > text_.size()) return Fail("bad \\u escape");
            unsigned code = 0;
            for (int k = 0; k < 4; k++) {
              char h = text_[pos_ + k];
              unsigned digit;
              if (h >= '0' && h <= '9') digit = h - '0';
              else if (h >= 'a' && h <= 'f') digit = h - 'a' + 10;
              else if (h >= 'A' && h <= 'F') digit = h - 'A' + 10;
              else return Fail("bad \\u escape");
              code = (code << 4) | digit;
            }
            pos_ += 4;
            // UTF-8 encode the BMP code point (surrogate pairs unhandled —
            // KServe bodies never carry them)
            if (code < 0x80) {
              v->s += static_cast<char>(code);
            } else if (code < 0x800) {
              v->s += static_cast<char>(0xC0 | (code >> 6));
              v->s += static_cast<char>(0x80 | (code & 0x3F));
            } else {
              v->s += static_cast<char>(0xE0 | (code >> 12));
              v->s += static_cast<char>(0x80 | ((code >> 6) & 0x3F));
              v->s += static_cast<char>(0x80 | (code & 0x3F));
            }
            break;
          }
          default: return Fail("bad escape");
        }
      } else {
        v->s += c;
      }
    }
    return Fail("unterminated string");
  }

  ValuePtr ParseBool()
  {
    auto v = std::make_shared<Value>();
    v->type = Type::Bool;
    if (text_.compare(pos_, 4, "true") == 0) {
      v->b = true;
      pos_ += 4;
      return v;
    }
    if (text_.compare(pos_, 5, "false") == 0) {
      v->b = false;
      pos_ += 5;
      return v;
    }
    return Fail("bad literal");
  }

  ValuePtr ParseNull()
  {
    if (text_.compare(pos_, 4, "null") == 0) {
      pos_ += 4;
      return std::make_shared<Value>();
    }
    return Fail("bad literal");
  }

  ValuePtr ParseNumber()
  {
    size_t start = pos_;
    bool is_double = false;
    if (pos_ < text_.size() && (text_[pos_] == '-' || text_[pos_] == '+'))
      pos_++;
    while (pos_ < text_.size()) {
      char c = text_[pos_];
      if (c >= '0' && c <= '9') {
        pos_++;
      } else if (c == '.' || c == 'e' || c == 'E' || c == '-' || c == '+') {
        is_double = true;
        pos_++;
      } else {
        break;
      }
    }
    if (pos_ == start) return Fail("expected value");
    auto v = std::make_shared<Value>();
    std::string num = text_.substr(start, pos_ - start);
    try {
      if (is_double) {
        v->type = Type::Double;
        v->d = std::stod(num);
      } else {
        v->type = Type::Int;
        v->i = std::stoll(num);
      }
    }
    catch (...) {
      return Fail("bad number");
    }
    return v;
  }

  const std::string& text_;
  size_t pos_ = 0;
  std::string err_msg_;
};

}  // namespace

ValuePtr
Parse(const std::string& text, std::string* err)
{
  return Parser(text).Run(err);
}

}  // namespace json
}  // namespace ctpu
