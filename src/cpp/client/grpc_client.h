// Native gRPC client for the KServe-v2 inference protocol — parity with the
// reference C++ gRPC client (reference src/c++/library/grpc_client.h:100-570:
// management surface, Infer, AsyncInfer via a completion-queue thread,
// StartStream/AsyncStreamInfer bidi streaming), built on this framework's
// own HTTP/2 transport (src/cpp/grpc/h2.h) and protoc-generated KServe
// protos instead of libgrpc++.
//
// The per-connection reactor thread plays the role of the reference's
// completion-queue thread (grpc_client.cc:1484): one thread drives every
// in-flight async request and the stream reader, so hundreds of requests
// can be outstanding with no thread-per-request.
#pragma once

#include <condition_variable>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "common.h"
#include "inference.pb.h"
#include "transport.h"

namespace ctpu {
namespace h2 {
class H2Connection;
}

// Channel TLS options (reference grpc_client.cc:119-129 SSL credentials).
// Declared for API parity; the TLS Create overload is gated exactly like
// HttpSslOptions (no OpenSSL headers in this toolchain).
struct GrpcSslOptions {
  std::string root_certificates;  // PEM path
  std::string private_key;        // PEM path
  std::string certificate_chain;  // PEM path
};

// Channel keepalive (reference grpc_client.h:62-82 KeepAliveOptions): h2
// PING probes every keepalive_time_ms; an unacked probe after
// keepalive_timeout_ms fails the connection so every pending request
// surfaces the failure instead of hanging on a dead peer.
struct KeepAliveOptions {
  int64_t keepalive_time_ms = INT32_MAX;  // INT32_MAX = disabled (reference default)
  int64_t keepalive_timeout_ms = 20000;
  bool keepalive_permit_without_calls = false;
};

// Per-call message compression (reference grpc_client.h:411 passes
// grpc_compression_algorithm): the LPM payload is compressed and flagged,
// with the matching grpc-encoding header.
enum class GrpcCompression { NONE, DEFLATE, GZIP };

class InferenceServerGrpcClient {
 public:
  using OnCompleteFn = std::function<void(InferResultPtr)>;

  // url is "host:port" (no scheme) or "grpc://host:port".
  static Error Create(
      std::unique_ptr<InferenceServerGrpcClient>* client,
      const std::string& url, bool verbose = false);
  // Keepalive + channel-cache variant (reference grpc_client.cc:79-120
  // NewGrpcChannel: shared channels per url with a share count).  With
  // use_cached_channel, clients for the same url multiplex cached
  // H2Connections, at most CLIENT_TPU_GRPC_CHANNEL_MAX_SHARE_COUNT clients
  // per connection (env var, default 6 — the reference's
  // TRITON_CLIENT_GRPC_CHANNEL_MAX_SHARE_COUNT analog); each connection
  // closes when its last user is destroyed.
  static Error Create(
      std::unique_ptr<InferenceServerGrpcClient>* client,
      const std::string& url, const KeepAliveOptions& keepalive,
      bool use_cached_channel, bool verbose = false);
  // TLS channel variant; see GrpcSslOptions for the gating note.
  static Error Create(
      std::unique_ptr<InferenceServerGrpcClient>* client,
      const std::string& url, const GrpcSslOptions& ssl_options,
      bool verbose = false);
  ~InferenceServerGrpcClient();

  // -- server / model management (grpc_client.h:118-259) -------------------
  Error IsServerLive(bool* live);
  Error IsServerReady(bool* ready);
  Error IsModelReady(
      bool* ready, const std::string& model_name,
      const std::string& model_version = "");
  Error ServerMetadata(inference::ServerMetadataResponse* response);
  Error ModelMetadata(
      inference::ModelMetadataResponse* response, const std::string& name,
      const std::string& version = "");
  Error ModelConfig(
      inference::ModelConfigResponse* response, const std::string& name,
      const std::string& version = "");
  Error ModelRepositoryIndex(inference::RepositoryIndexResponse* response);
  Error LoadModel(
      const std::string& name, const std::string& config_json = "");
  Error UnloadModel(const std::string& name);
  Error ModelInferenceStatistics(
      inference::ModelStatisticsResponse* response,
      const std::string& name = "", const std::string& version = "");

  // -- trace / log settings (reference grpc_client.h:291-309) --------------
  Error UpdateTraceSettings(
      inference::TraceSettingResponse* response,
      const std::string& model_name = "",
      const std::map<std::string, std::vector<std::string>>& settings = {});
  Error GetTraceSettings(
      inference::TraceSettingResponse* response,
      const std::string& model_name = "");
  Error UpdateLogSettings(
      inference::LogSettingsResponse* response,
      const std::map<std::string, std::string>& settings = {});
  Error GetLogSettings(inference::LogSettingsResponse* response);

  // -- shared memory verbs (grpc_client.h:263-321) -------------------------
  Error SystemSharedMemoryStatus(
      inference::SystemSharedMemoryStatusResponse* response,
      const std::string& region_name = "");
  Error RegisterSystemSharedMemory(
      const std::string& name, const std::string& key, size_t byte_size,
      size_t offset = 0);
  Error UnregisterSystemSharedMemory(const std::string& name = "");
  // TPU device-buffer regions: the framework's CUDA-shm replacement rides
  // its own Tpu* RPC set (proto/inference.proto:50-55 — SURVEY §2.2 north
  // star).
  Error TpuSharedMemoryStatus(
      inference::TpuSharedMemoryStatusResponse* response,
      const std::string& region_name = "");
  Error RegisterTpuSharedMemory(
      const std::string& name, const std::string& raw_handle, int device_id,
      size_t byte_size);
  Error UnregisterTpuSharedMemory(const std::string& name = "");

  // -- inference ------------------------------------------------------------
  Error Infer(
      InferResult** result, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs = {},
      const std::vector<std::pair<std::string, std::string>>& headers = {},
      GrpcCompression compression = GrpcCompression::NONE);
  Error AsyncInfer(
      OnCompleteFn callback, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs = {},
      const std::vector<std::pair<std::string, std::string>>& headers = {},
      GrpcCompression compression = GrpcCompression::NONE);

  // -- batched multi-request variants (reference grpc_client.h:455-494) ----
  // Issue one request per options/inputs row.  InferMulti returns on the
  // first failure (already-returned results stay owned by the caller);
  // AsyncInferMulti fires `callback` once with all results (error results
  // included) after every request completes.
  using OnMultiCompleteFn = std::function<void(std::vector<InferResultPtr>)>;
  Error InferMulti(
      std::vector<InferResult*>* results,
      const std::vector<InferOptions>& options,
      const std::vector<std::vector<InferInput*>>& inputs,
      const std::vector<std::vector<const InferRequestedOutput*>>& outputs =
          {},
      const std::vector<std::pair<std::string, std::string>>& headers = {});
  Error AsyncInferMulti(
      OnMultiCompleteFn callback, const std::vector<InferOptions>& options,
      const std::vector<std::vector<InferInput*>>& inputs,
      const std::vector<std::vector<const InferRequestedOutput*>>& outputs =
          {},
      const std::vector<std::pair<std::string, std::string>>& headers = {});

  // -- decoupled / sequence streaming (grpc_client.h:414-504) ---------------
  // One bidi ModelStreamInfer stream per client.  Responses (and stream
  // errors, delivered as error-message results) arrive on `callback`.
  Error StartStream(
      OnCompleteFn callback, uint64_t stream_timeout_us = 0,
      const std::vector<std::pair<std::string, std::string>>& headers = {});
  Error AsyncStreamInfer(
      const InferOptions& options, const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs = {});
  Error StopStream();

  // Per-client aggregate of request timers (ctpu::InferStat in common.h;
  // the nested name is kept for source compatibility).
  using InferStat = ctpu::InferStat;
  Error ClientInferStat(InferStat* stat);

 private:
  InferenceServerGrpcClient(const std::string& host, int port, bool verbose);
  Error Connected();
  // One unary gRPC exchange: serialize request, LPM-frame, wait for the
  // response message + trailers, check grpc-status.
  Error Call(
      const std::string& method, const google::protobuf::Message& request,
      google::protobuf::Message* response, uint64_t timeout_us = 0,
      const std::vector<std::pair<std::string, std::string>>& headers = {},
      GrpcCompression compression = GrpcCompression::NONE);
  Error BuildInferRequest(
      const InferOptions& options, const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs,
      inference::ModelInferRequest* request);
  void UpdateStat(const RequestTimers& timers);

  std::string host_;
  int port_;
  bool verbose_;
  bool shared_channel_ = false;  // cached-channel clients never Close()
  bool attached_ = false;  // holds a share count in the channel cache
  KeepAliveOptions keepalive_;
  bool keepalive_enabled_ = false;
  bool tls_enabled_ = false;  // connections ride MakeTlsTransport
  TlsConfig tls_config_;
  // shared_ptr: a reconnect swaps conn_ while requests may still be blocked
  // inside (or async callbacks may still reference) the old connection —
  // each call path pins its own reference.
  std::shared_ptr<h2::H2Connection> conn_;
  std::mutex conn_mu_;
  std::shared_ptr<h2::H2Connection> Conn();
  // Cached-channel bookkeeping: decrement this url's share count; the last
  // user (or a holder of a stale pre-reconnect connection) closes it.
  void DropCachedUser(const std::shared_ptr<h2::H2Connection>& conn);

  // streaming state
  std::mutex stream_mu_;
  std::shared_ptr<h2::H2Connection> stream_conn_;  // owns stream_sid_
  int32_t stream_sid_ = 0;
  OnCompleteFn stream_callback_;
  std::string stream_rx_;  // partial length-prefixed message bytes
  uint64_t stream_timeout_us_ = 0;

  std::mutex stat_mu_;
  InferStat stat_;
};

// Convenience mirrors of the reference's free helpers.
Error ParseGrpcInferResult(
    const inference::ModelInferResponse& response, InferResult** result);

// Number of cached-channel slots currently held for "host:port" — test
// observability for the share-count distribution policy; not a public API.
int CachedChannelCountForTesting(const std::string& host_port);

}  // namespace ctpu
