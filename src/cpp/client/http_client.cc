#include "http_client.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <zlib.h>

#include <chrono>
#include <cstring>
#include <sstream>
#include <thread>

#include "http_reactor.h"

namespace ctpu {

namespace {

std::string
UrlEncode(const std::string& s)
{
  std::string out;
  for (unsigned char c : s) {
    if (isalnum(c) || c == '-' || c == '_' || c == '.' || c == '~') {
      out += static_cast<char>(c);
    } else {
      char hex[8];
      std::snprintf(hex, sizeof(hex), "%%%02X", c);
      out += hex;
    }
  }
  return out;
}

std::string
LowerCase(std::string s)
{
  for (auto& c : s) c = static_cast<char>(tolower(c));
  return s;
}

}  // namespace

Error
InferenceServerHttpClient::Create(
    std::unique_ptr<InferenceServerHttpClient>* client,
    const std::string& server_url, bool verbose)
{
  client->reset(new InferenceServerHttpClient(server_url, verbose));
  if ((*client)->port_ == 0) {
    client->reset();
    return Error("malformed server url '" + server_url + "' (want host:port)");
  }
  if (server_url.rfind("https://", 0) == 0) {
    // https scheme on the plain overload: default TLS options
    return EnableTls(client, HttpSslOptions());
  }
  return Error::Success();
}

Error
InferenceServerHttpClient::Create(
    std::unique_ptr<InferenceServerHttpClient>* client,
    const std::string& server_url, const HttpSslOptions& ssl_options,
    bool verbose)
{
  client->reset(new InferenceServerHttpClient(server_url, verbose));
  if ((*client)->port_ == 0) {
    client->reset();
    return Error("malformed server url '" + server_url + "' (want host:port)");
  }
  return EnableTls(client, ssl_options);
}

Error
InferenceServerHttpClient::EnableTls(
    std::unique_ptr<InferenceServerHttpClient>* client,
    const HttpSslOptions& ssl_options)
{
  // Same fail-fast shape as the gRPC client: resolve a transport NOW so a
  // build/deployment without any TLS provider errors at Create, not on the
  // first request (reference fails at channel creation too).
  TlsConfig config;
  config.root_certificates = ssl_options.ca_info;
  config.private_key = ssl_options.key;
  config.certificate_chain = ssl_options.cert;
  config.insecure_skip_verify =
      !(ssl_options.verify_peer || ssl_options.verify_host);
  std::unique_ptr<ByteTransport> probe;
  Error err = MakeTlsTransport(config, &probe);
  if (!err.IsOk()) {
    client->reset();
    return err;
  }
  (*client)->tls_enabled_ = true;
  (*client)->tls_config_ = config;
  return Error::Success();
}

InferenceServerHttpClient::InferenceServerHttpClient(
    const std::string& url, bool verbose)
    : verbose_(verbose)
{
  std::string stripped = url;
  auto scheme = stripped.find("://");
  if (scheme != std::string::npos) stripped = stripped.substr(scheme + 3);
  std::string port_str;
  if (!stripped.empty() && stripped.front() == '[') {
    // RFC 3986 bracketed IPv6 literal: [::1]:8000
    auto close = stripped.find(']');
    if (close == std::string::npos) return;
    host_ = stripped.substr(1, close - 1);
    if (close + 1 >= stripped.size() || stripped[close + 1] != ':') return;
    port_str = stripped.substr(close + 2);
  } else {
    auto colon = stripped.rfind(':');
    if (colon == std::string::npos) return;
    // a second ':' means an unbracketed IPv6 literal — ambiguous, reject
    if (stripped.find(':') != colon) return;
    host_ = stripped.substr(0, colon);
    port_str = stripped.substr(colon + 1);
  }
  try {
    port_ = std::stoi(port_str);
  }
  catch (...) {
    port_ = 0;
  }
}

InferenceServerHttpClient::~InferenceServerHttpClient()
{
  CloseSocket();
}

void
InferenceServerHttpClient::CloseSocket()
{
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
  if (transport_ != nullptr) {
    transport_->Close();
    transport_.reset();
  }
}

bool
InferenceServerHttpClient::Connected() const
{
  return tls_enabled_ ? transport_ != nullptr : fd_ >= 0;
}

ssize_t
InferenceServerHttpClient::IoSend(const void* buf, size_t len)
{
  if (tls_enabled_) return transport_->Write(buf, len);
  return ::send(fd_, buf, len, MSG_NOSIGNAL);
}

ssize_t
InferenceServerHttpClient::IoRecv(void* buf, size_t len)
{
  if (tls_enabled_) return transport_->Read(buf, len);
  return ::recv(fd_, buf, len, 0);
}

Error
InferenceServerHttpClient::EnsureConnected()
{
  if (Connected()) return Error::Success();
  if (tls_enabled_) {
    std::unique_ptr<ByteTransport> t;
    Error err = MakeTlsTransport(tls_config_, &t);
    if (!err.IsOk()) return err;
    err = t->Connect(host_, port_, /*timeout_ms=*/30000);
    if (!err.IsOk()) return err;
    transport_ = std::move(t);
    return Error::Success();
  }
  struct addrinfo hints = {};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  std::string port = std::to_string(port_);
  int rc = ::getaddrinfo(host_.c_str(), port.c_str(), &hints, &res);
  if (rc != 0) {
    return Error(
        "failed to resolve " + host_ + ": " + std::string(gai_strerror(rc)));
  }
  Error err("failed to connect to " + host_ + ":" + port);
  for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    int fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) {
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      fd_ = fd;
      err = Error::Success();
      break;
    }
    ::close(fd);
  }
  ::freeaddrinfo(res);
  return err;
}

Error
InferenceServerHttpClient::Request(
    HttpResponse* response, const std::string& method, const std::string& uri,
    const std::string& body, const std::map<std::string, std::string>& headers,
    RequestTimers* timers, uint64_t timeout_us)
{
  // Whole-exchange deadline (the reference's CURLOPT_TIMEOUT_MS shape):
  // every socket op gets only the REMAINING budget, so a server dripping
  // bytes cannot stretch one request past client_timeout_us.
  const auto deadline = std::chrono::steady_clock::now() +
                        std::chrono::microseconds(timeout_us);
  const auto set_socket_timeout = [&]() -> bool {
    if (tls_enabled_) {
      // TLS path: the remaining budget reaches the transport's socket via
      // SetIoTimeout, so a peer that accepts then stalls times the read
      // out (errno EAGAIN, same as the plain-TCP SO_RCVTIMEO path) instead
      // of hanging Infer() forever.  Factory transports without deadline
      // support no-op and keep the old between-ops granularity.
      if (timeout_us == 0) {
        transport_->SetIoTimeout(0);
        return true;
      }
      const auto remaining =
          std::chrono::duration_cast<std::chrono::microseconds>(
              deadline - std::chrono::steady_clock::now())
              .count();
      if (remaining <= 0) return false;  // budget exhausted
      transport_->SetIoTimeout(remaining);
      return true;
    }
    struct timeval tv;
    if (timeout_us == 0) {
      tv.tv_sec = 0;
      tv.tv_usec = 0;  // zero timeval = wait forever
      setsockopt(fd_, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
      setsockopt(fd_, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
      return true;
    }
    {
      const auto remaining =
          std::chrono::duration_cast<std::chrono::microseconds>(
              deadline - std::chrono::steady_clock::now())
              .count();
      if (remaining <= 0) return false;  // budget exhausted
      tv.tv_sec = static_cast<time_t>(remaining / 1000000);
      tv.tv_usec = static_cast<suseconds_t>(remaining % 1000000);
    }
    setsockopt(fd_, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(fd_, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    return true;
  };
  const auto rearm_or_timeout = [&]() -> bool {
    // keep the remaining-budget invariant between partial socket ops;
    // no-op (single redundant-free path) when no deadline is set
    return timeout_us == 0 || set_socket_timeout();
  };
  const auto timed_out = [] {
    return errno == EAGAIN || errno == EWOULDBLOCK;
  };
  for (int attempt = 0; attempt < 2; ++attempt) {
    // A request may only be retried when it was written to a REUSED
    // keep-alive connection and ZERO response bytes arrived: then the server
    // closed the idle connection before reading our request, so it cannot
    // have executed.  A drop on a fresh connection, or after any response
    // byte, may mean the request already ran — retrying would double-infer.
    const bool reused_connection = Connected();
    Error err = EnsureConnected();
    if (!err.IsOk()) return err;
    // client_timeout_us bounds the WHOLE exchange; 0 restores "wait
    // forever" (the fd is a reused keep-alive socket, so set it per request)
    if (!set_socket_timeout()) {
      CloseSocket();
      return Error("client timeout exceeded");
    }

    std::ostringstream req;
    req << method << " " << uri << " HTTP/1.1\r\n";
    req << "Host: " << host_ << ":" << port_ << "\r\n";
    req << "Content-Length: " << body.size() << "\r\n";
    req << "Connection: keep-alive\r\n";
    for (const auto& kv : headers) {
      req << kv.first << ": " << kv.second << "\r\n";
    }
    req << "\r\n";
    std::string head = req.str();

    if (timers != nullptr) timers->Capture(RequestTimers::Kind::SEND_START);
    bool write_failed = false;
    const std::string* parts[2] = {&head, &body};
    for (const std::string* part : parts) {
      size_t sent = 0;
      while (sent < part->size()) {
        ssize_t n = IoSend(part->data() + sent, part->size() - sent);
        if (n <= 0) {
          if (n < 0 && timed_out()) {
            CloseSocket();
            return Error("client timeout exceeded while sending request");
          }
          write_failed = true;
          break;
        }
        sent += static_cast<size_t>(n);
        if (!rearm_or_timeout()) {
          CloseSocket();
          return Error("client timeout exceeded while sending request");
        }
      }
      if (write_failed) break;
    }
    if (write_failed) {
      CloseSocket();
      if (reused_connection && attempt == 0) {
        continue;  // stale keep-alive: request was never read, safe to resend
      }
      return Error("failed to send request to " + host_);
    }

    if (timers != nullptr) {
      timers->Capture(RequestTimers::Kind::SEND_END);
      timers->Capture(RequestTimers::Kind::RECV_START);
    }
    // read response: status line + headers, then Content-Length body
    std::string buf;
    size_t header_end = std::string::npos;
    char chunk[8192];
    bool read_closed = false;
    while (header_end == std::string::npos) {
      ssize_t n = IoRecv(chunk, sizeof(chunk));
      if (n <= 0) {
        if (n < 0 && timed_out()) {
          CloseSocket();
          return Error("client timeout exceeded waiting for response");
        }
        CloseSocket();
        read_closed = true;
        break;
      }
      if (!rearm_or_timeout()) {
        CloseSocket();
        return Error("client timeout exceeded waiting for response");
      }
      buf.append(chunk, static_cast<size_t>(n));
      header_end = buf.find("\r\n\r\n");
    }
    if (read_closed) {
      if (buf.empty() && reused_connection && attempt == 0) {
        continue;  // idle keep-alive closed under us with nothing received
      }
      return Error(
          buf.empty() ? "connection closed by server"
                      : "connection closed mid-response");
    }

    response->headers.clear();
    std::istringstream hs(buf.substr(0, header_end));
    std::string line;
    std::getline(hs, line);
    {
      auto sp1 = line.find(' ');
      response->status = 0;
      if (sp1 != std::string::npos) {
        try {
          response->status = std::stoi(line.substr(sp1 + 1));
        }
        catch (...) {
          CloseSocket();
          return Error("malformed status line: " + line);
        }
      }
    }
    while (std::getline(hs, line)) {
      if (!line.empty() && line.back() == '\r') line.pop_back();
      auto colon = line.find(':');
      if (colon == std::string::npos) continue;
      std::string key = LowerCase(line.substr(0, colon));
      std::string val = line.substr(colon + 1);
      while (!val.empty() && val.front() == ' ') val.erase(val.begin());
      response->headers[key] = val;
    }

    size_t content_length = 0;
    auto cl = response->headers.find("content-length");
    if (cl != response->headers.end()) {
      try {
        content_length = std::stoull(cl->second);
      }
      catch (...) {
        CloseSocket();
        return Error("malformed Content-Length: " + cl->second);
      }
    }
    response->body = buf.substr(header_end + 4);
    while (response->body.size() < content_length) {
      ssize_t n = IoRecv(chunk, sizeof(chunk));
      if (n <= 0) {
        const bool was_timeout = n < 0 && timed_out();  // before close clobbers errno
        CloseSocket();
        if (was_timeout) {
          return Error("client timeout exceeded reading response body");
        }
        return Error("connection closed mid-body");
      }
      if (!rearm_or_timeout()) {
        CloseSocket();
        return Error("client timeout exceeded reading response body");
      }
      response->body.append(chunk, static_cast<size_t>(n));
    }
    if (verbose_) {
      fprintf(
          stderr, "[ctpu] %s %s -> %d (%zu bytes)\n", method.c_str(),
          uri.c_str(), response->status, response->body.size());
    }
    if (timers != nullptr) timers->Capture(RequestTimers::Kind::RECV_END);
    auto conn = response->headers.find("connection");
    if (conn != response->headers.end() &&
        LowerCase(conn->second) == "close") {
      CloseSocket();
    }
    return Error::Success();
  }
  return Error("request failed after reconnect");
}

namespace {

Error
ErrorFromResponse(const HttpResponse& r)
{
  std::string err;
  auto parsed = json::Parse(r.body, &err);
  if (parsed != nullptr && parsed->Get("error") != nullptr) {
    return Error(parsed->Get("error")->AsString());
  }
  return Error("HTTP " + std::to_string(r.status) + ": " + r.body);
}

}  // namespace

Error
InferenceServerHttpClient::GetJson(const std::string& uri, json::ValuePtr* out)
{
  HttpResponse r;
  Error err = Request(&r, "GET", uri, "");
  if (!err.IsOk()) return err;
  if (r.status != 200) return ErrorFromResponse(r);
  if (out != nullptr) {
    std::string perr;
    *out = json::Parse(r.body.empty() ? "{}" : r.body, &perr);
    if (*out == nullptr) return Error("malformed response JSON: " + perr);
  }
  return Error::Success();
}

Error
InferenceServerHttpClient::PostJson(
    const std::string& uri, const std::string& body, json::ValuePtr* out)
{
  HttpResponse r;
  Error err = Request(
      &r, "POST", uri, body, {{"Content-Type", "application/json"}});
  if (!err.IsOk()) return err;
  if (r.status != 200) return ErrorFromResponse(r);
  if (out != nullptr) {
    std::string perr;
    *out = json::Parse(r.body.empty() ? "{}" : r.body, &perr);
    if (*out == nullptr) return Error("malformed response JSON: " + perr);
  }
  return Error::Success();
}

Error
InferenceServerHttpClient::IsServerLive(bool* live)
{
  HttpResponse r;
  Error err = Request(&r, "GET", "/v2/health/live", "");
  if (!err.IsOk()) return err;
  *live = (r.status == 200);
  return Error::Success();
}

Error
InferenceServerHttpClient::IsServerReady(bool* ready)
{
  HttpResponse r;
  Error err = Request(&r, "GET", "/v2/health/ready", "");
  if (!err.IsOk()) return err;
  *ready = (r.status == 200);
  return Error::Success();
}

Error
InferenceServerHttpClient::IsModelReady(
    bool* ready, const std::string& model_name,
    const std::string& model_version)
{
  std::string uri = "/v2/models/" + UrlEncode(model_name);
  if (!model_version.empty()) uri += "/versions/" + model_version;
  uri += "/ready";
  HttpResponse r;
  Error err = Request(&r, "GET", uri, "");
  if (!err.IsOk()) return err;
  *ready = (r.status == 200);
  return Error::Success();
}

Error
InferenceServerHttpClient::ServerMetadata(json::ValuePtr* metadata)
{
  return GetJson("/v2", metadata);
}

Error
InferenceServerHttpClient::ModelMetadata(
    json::ValuePtr* metadata, const std::string& model_name,
    const std::string& model_version)
{
  std::string uri = "/v2/models/" + UrlEncode(model_name);
  if (!model_version.empty()) uri += "/versions/" + model_version;
  return GetJson(uri, metadata);
}

Error
InferenceServerHttpClient::ModelConfig(
    json::ValuePtr* config, const std::string& model_name,
    const std::string& model_version)
{
  std::string uri = "/v2/models/" + UrlEncode(model_name);
  if (!model_version.empty()) uri += "/versions/" + model_version;
  uri += "/config";
  return GetJson(uri, config);
}

Error
InferenceServerHttpClient::ModelRepositoryIndex(json::ValuePtr* index)
{
  return PostJson("/v2/repository/index", "{}", index);
}

Error
InferenceServerHttpClient::LoadModel(const std::string& model_name)
{
  return PostJson(
      "/v2/repository/models/" + UrlEncode(model_name) + "/load", "{}");
}

Error
InferenceServerHttpClient::UnloadModel(const std::string& model_name)
{
  return PostJson(
      "/v2/repository/models/" + UrlEncode(model_name) + "/unload", "{}");
}

Error
InferenceServerHttpClient::ModelInferenceStatistics(
    json::ValuePtr* stats, const std::string& model_name,
    const std::string& model_version)
{
  std::string uri = "/v2/models";
  if (!model_name.empty()) {
    uri += "/" + UrlEncode(model_name);
    if (!model_version.empty()) uri += "/versions/" + model_version;
  }
  uri += "/stats";
  return GetJson(uri, stats);
}

Error
InferenceServerHttpClient::RegisterSystemSharedMemory(
    const std::string& name, const std::string& key, size_t byte_size,
    size_t offset)
{
  json::Writer w;
  w.BeginObject();
  w.Key("key");
  w.String(key);
  w.Key("offset");
  w.Int(static_cast<int64_t>(offset));
  w.Key("byte_size");
  w.Int(static_cast<int64_t>(byte_size));
  w.EndObject();
  return PostJson(
      "/v2/systemsharedmemory/region/" + UrlEncode(name) + "/register",
      w.str());
}

Error
InferenceServerHttpClient::UnregisterSystemSharedMemory(const std::string& name)
{
  std::string uri = "/v2/systemsharedmemory";
  if (!name.empty()) uri += "/region/" + UrlEncode(name);
  return PostJson(uri + "/unregister", "{}");
}

Error
InferenceServerHttpClient::SystemSharedMemoryStatus(json::ValuePtr* status)
{
  return GetJson("/v2/systemsharedmemory/status", status);
}

Error
InferenceServerHttpClient::RegisterTpuSharedMemory(
    const std::string& name, const std::string& raw_handle, int device_id,
    size_t byte_size)
{
  // raw handle travels base64 over HTTP like the CUDA path (reference
  // cencode.c / cuda_shared_memory __init__.py:76-77); ours is JSON-safe
  // already, so b64 here is purely wire-format parity
  static const char* b64 =
      "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";
  std::string encoded;
  size_t i = 0;
  while (i + 2 < raw_handle.size()) {
    uint32_t v = (static_cast<uint8_t>(raw_handle[i]) << 16) |
                 (static_cast<uint8_t>(raw_handle[i + 1]) << 8) |
                 static_cast<uint8_t>(raw_handle[i + 2]);
    encoded += b64[(v >> 18) & 63];
    encoded += b64[(v >> 12) & 63];
    encoded += b64[(v >> 6) & 63];
    encoded += b64[v & 63];
    i += 3;
  }
  if (i + 1 == raw_handle.size()) {
    uint32_t v = static_cast<uint8_t>(raw_handle[i]) << 16;
    encoded += b64[(v >> 18) & 63];
    encoded += b64[(v >> 12) & 63];
    encoded += "==";
  } else if (i + 2 == raw_handle.size()) {
    uint32_t v = (static_cast<uint8_t>(raw_handle[i]) << 16) |
                 (static_cast<uint8_t>(raw_handle[i + 1]) << 8);
    encoded += b64[(v >> 18) & 63];
    encoded += b64[(v >> 12) & 63];
    encoded += b64[(v >> 6) & 63];
    encoded += '=';
  }
  json::Writer w;
  w.BeginObject();
  w.Key("raw_handle");
  w.BeginObject();
  w.Key("b64");
  w.String(encoded);
  w.EndObject();
  w.Key("device_id");
  w.Int(device_id);
  w.Key("byte_size");
  w.Int(static_cast<int64_t>(byte_size));
  w.EndObject();
  return PostJson(
      "/v2/tpusharedmemory/region/" + UrlEncode(name) + "/register", w.str());
}

Error
InferenceServerHttpClient::UnregisterTpuSharedMemory(const std::string& name)
{
  std::string uri = "/v2/tpusharedmemory";
  if (!name.empty()) uri += "/region/" + UrlEncode(name);
  return PostJson(uri + "/unregister", "{}");
}

Error
InferenceServerHttpClient::TpuSharedMemoryStatus(json::ValuePtr* status)
{
  return GetJson("/v2/tpusharedmemory/status", status);
}

Error
InferenceServerHttpClient::GenerateRequestBody(
    std::string* body, size_t* header_length, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs)
{
  json::Writer w;
  w.BeginObject();
  if (!options.request_id.empty()) {
    w.Key("id");
    w.String(options.request_id);
  }
  const bool has_sequence =
      options.sequence_id != 0 || !options.sequence_id_str.empty();
  if (has_sequence || options.priority != 0 || options.timeout_us != 0 ||
      outputs.empty()) {
    w.Key("parameters");
    w.BeginObject();
    if (outputs.empty()) {
      // no explicit outputs: ask for all of them in binary form (reference
      // http_client.cc sets binary_data_output for this case)
      w.Key("binary_data_output");
      w.Bool(true);
    }
    if (has_sequence) {
      w.Key("sequence_id");
      if (!options.sequence_id_str.empty()) {
        w.String(options.sequence_id_str);
      } else {
        w.Int(static_cast<int64_t>(options.sequence_id));
      }
      w.Key("sequence_start");
      w.Bool(options.sequence_start);
      w.Key("sequence_end");
      w.Bool(options.sequence_end);
    }
    if (options.priority != 0) {
      w.Key("priority");
      w.Int(static_cast<int64_t>(options.priority));
    }
    if (options.timeout_us != 0) {
      w.Key("timeout");
      w.Int(static_cast<int64_t>(options.timeout_us));
    }
    w.EndObject();
  }
  w.Key("inputs");
  w.BeginArray();
  for (const InferInput* input : inputs) {
    w.BeginObject();
    w.Key("name");
    w.String(input->Name());
    w.Key("shape");
    w.BeginArray();
    for (int64_t d : input->Shape()) w.Int(d);
    w.EndArray();
    w.Key("datatype");
    w.String(input->Datatype());
    w.Key("parameters");
    w.BeginObject();
    if (input->IsSharedMemory()) {
      w.Key("shared_memory_region");
      w.String(input->SharedMemoryName());
      w.Key("shared_memory_byte_size");
      w.Int(static_cast<int64_t>(input->SharedMemoryByteSize()));
      if (input->SharedMemoryOffset() != 0) {
        w.Key("shared_memory_offset");
        w.Int(static_cast<int64_t>(input->SharedMemoryOffset()));
      }
    } else {
      w.Key("binary_data_size");
      w.Int(static_cast<int64_t>(input->TotalByteSize()));
    }
    w.EndObject();
    w.EndObject();
  }
  w.EndArray();
  if (!outputs.empty()) {
    w.Key("outputs");
    w.BeginArray();
    for (const InferRequestedOutput* output : outputs) {
      w.BeginObject();
      w.Key("name");
      w.String(output->Name());
      w.Key("parameters");
      w.BeginObject();
      if (output->IsSharedMemory()) {
        w.Key("shared_memory_region");
        w.String(output->SharedMemoryName());
        w.Key("shared_memory_byte_size");
        w.Int(static_cast<int64_t>(output->SharedMemoryByteSize()));
        if (output->SharedMemoryOffset() != 0) {
          w.Key("shared_memory_offset");
          w.Int(static_cast<int64_t>(output->SharedMemoryOffset()));
        }
      } else if (output->ClassCount() > 0) {
        w.Key("classification");
        w.Int(static_cast<int64_t>(output->ClassCount()));
      } else {
        w.Key("binary_data");
        w.Bool(true);
      }
      w.EndObject();
      w.EndObject();
    }
    w.EndArray();
  }
  w.EndObject();

  *body = w.str();
  *header_length = body->size();
  for (const InferInput* input : inputs) {
    for (const auto& buf : input->Buffers()) {
      body->append(reinterpret_cast<const char*>(buf.first), buf.second);
    }
  }
  return Error::Success();
}

Error
InferenceServerHttpClient::ParseResponseBody(
    InferResultPtr* result, std::string&& body, size_t header_length)
{
  auto res = std::make_shared<InferResult>();
  res->body_ = std::move(body);
  size_t json_len =
      (header_length == 0) ? res->body_.size() : header_length;
  std::string perr;
  auto parsed = json::Parse(res->body_.substr(0, json_len), &perr);
  if (parsed == nullptr) {
    return Error("malformed inference response: " + perr);
  }
  if (parsed->Get("model_name") != nullptr) {
    res->model_name_ = parsed->Get("model_name")->AsString();
  }
  if (parsed->Get("id") != nullptr) res->id_ = parsed->Get("id")->AsString();

  size_t binary_offset = json_len;
  const json::Value* outputs = parsed->Get("outputs");
  if (outputs != nullptr) {
    for (const auto& out : outputs->arr) {
      InferResult::Output o;
      if (out->Get("name") == nullptr) {
        return Error("response output entry missing 'name'");
      }
      std::string name = out->Get("name")->AsString();
      if (out->Get("datatype") != nullptr) {
        o.datatype = out->Get("datatype")->AsString();
      }
      if (out->Get("shape") != nullptr) {
        for (const auto& d : out->Get("shape")->arr) {
          o.shape.push_back(d->AsInt());
        }
      }
      const json::Value* params = out->Get("parameters");
      if (params != nullptr && params->Get("binary_data_size") != nullptr) {
        o.byte_size =
            static_cast<size_t>(params->Get("binary_data_size")->AsInt());
        if (binary_offset + o.byte_size > res->body_.size()) {
          return Error("binary section underrun for output '" + name + "'");
        }
        o.data = reinterpret_cast<const uint8_t*>(res->body_.data()) +
                 binary_offset;
        binary_offset += o.byte_size;
      } else if (
          params != nullptr &&
          params->Get("shared_memory_region") != nullptr) {
        o.in_shared_memory = true;
      } else if (out->Get("data") != nullptr) {
        for (const auto& v : out->Get("data")->arr) {
          if (v->type == json::Type::String) {
            o.json_values.push_back(v->AsString());
          } else if (v->type == json::Type::Double) {
            o.json_values.push_back(std::to_string(v->AsDouble()));
          } else {
            o.json_values.push_back(std::to_string(v->AsInt()));
          }
        }
      }
      res->outputs_[name] = std::move(o);
    }
  }
  *result = res;
  return Error::Success();
}

namespace {

// gzip = zlib with the RFC-1952 wrapper (windowBits 15+16); deflate = the
// RFC-1950 zlib stream browsers and servers actually speak for
// "Content-Encoding: deflate".
Error
ZCompress(
    const std::string& in,
    InferenceServerHttpClient::CompressionType type, std::string* out)
{
  z_stream zs = {};
  const int window =
      type == InferenceServerHttpClient::CompressionType::GZIP ? 15 + 16 : 15;
  if (deflateInit2(&zs, Z_DEFAULT_COMPRESSION, Z_DEFLATED, window, 8,
                   Z_DEFAULT_STRATEGY) != Z_OK) {
    return Error("deflateInit2 failed");
  }
  out->resize(deflateBound(&zs, in.size()));
  zs.next_in = reinterpret_cast<Bytef*>(const_cast<char*>(in.data()));
  zs.avail_in = in.size();
  zs.next_out = reinterpret_cast<Bytef*>(&(*out)[0]);
  zs.avail_out = out->size();
  const int rc = deflate(&zs, Z_FINISH);
  deflateEnd(&zs);
  if (rc != Z_STREAM_END) return Error("deflate failed");
  out->resize(out->size() - zs.avail_out);
  return Error::Success();
}

Error
ZDecompress(
    const std::string& in,
    InferenceServerHttpClient::CompressionType type, std::string* out)
{
  z_stream zs = {};
  const int window =
      type == InferenceServerHttpClient::CompressionType::GZIP ? 15 + 16 : 15;
  if (inflateInit2(&zs, window) != Z_OK) return Error("inflateInit2 failed");
  zs.next_in = reinterpret_cast<Bytef*>(const_cast<char*>(in.data()));
  zs.avail_in = in.size();
  char chunk[65536];
  int rc = Z_OK;
  while (rc != Z_STREAM_END) {
    zs.next_out = reinterpret_cast<Bytef*>(chunk);
    zs.avail_out = sizeof(chunk);
    rc = inflate(&zs, Z_NO_FLUSH);
    if (rc != Z_OK && rc != Z_STREAM_END) {
      inflateEnd(&zs);
      return Error("inflate failed (corrupt compressed response)");
    }
    out->append(chunk, sizeof(chunk) - zs.avail_out);
    if (rc != Z_STREAM_END && zs.avail_in == 0) {
      inflateEnd(&zs);
      return Error("truncated compressed response");
    }
  }
  inflateEnd(&zs);
  return Error::Success();
}

}  // namespace

Error
InferenceServerHttpClient::Infer(
    InferResultPtr* result, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs,
    CompressionType request_compression, CompressionType response_compression)
{
  RequestTimers timers;
  timers.Capture(RequestTimers::Kind::REQUEST_START);
  std::string body;
  size_t header_length = 0;
  Error err = GenerateRequestBody(&body, &header_length, options, inputs,
                                  outputs);
  if (!err.IsOk()) return err;

  std::string uri = "/v2/models/" + UrlEncode(options.model_name);
  if (!options.model_version.empty()) {
    uri += "/versions/" + options.model_version;
  }
  uri += "/infer";

  std::map<std::string, std::string> headers = {
      {"Content-Type", "application/octet-stream"},
      {"Inference-Header-Content-Length", std::to_string(header_length)},
  };
  if (request_compression != CompressionType::NONE) {
    std::string compressed;
    err = ZCompress(body, request_compression, &compressed);
    if (!err.IsOk()) return err;
    body.swap(compressed);
    headers["Content-Encoding"] =
        request_compression == CompressionType::GZIP ? "gzip" : "deflate";
  }
  if (response_compression != CompressionType::NONE) {
    headers["Accept-Encoding"] =
        response_compression == CompressionType::GZIP ? "gzip" : "deflate";
  }
  HttpResponse r;
  err = Request(&r, "POST", uri, body, headers, &timers,
                options.client_timeout_us);
  if (!err.IsOk()) return err;
  if (r.status != 200) return ErrorFromResponse(r);
  const auto enc = r.headers.find("content-encoding");
  if (enc != r.headers.end() && !enc->second.empty() &&
      enc->second != "identity") {
    std::string plain;
    err = ZDecompress(
        r.body,
        enc->second == "gzip" ? CompressionType::GZIP
                              : CompressionType::DEFLATE,
        &plain);
    if (!err.IsOk()) return err;
    r.body.swap(plain);
  }

  size_t resp_header_len = 0;
  auto it = r.headers.find("inference-header-content-length");
  if (it != r.headers.end()) {
    try {
      resp_header_len = std::stoull(it->second);
    }
    catch (...) {
      return Error("malformed Inference-Header-Content-Length: " + it->second);
    }
  }
  err = ParseResponseBody(result, std::move(r.body), resp_header_len);
  if (err.IsOk()) {
    timers.Capture(RequestTimers::Kind::REQUEST_END);
    UpdateStat(timers);
  }
  return err;
}

void
InferenceServerHttpClient::UpdateStat(const RequestTimers& timers)
{
  std::lock_guard<std::mutex> lk(stat_mu_);
  stat_.completed_request_count++;
  stat_.cumulative_total_request_time_ns += timers.Duration(
      RequestTimers::Kind::REQUEST_START, RequestTimers::Kind::REQUEST_END);
  stat_.cumulative_send_time_ns += timers.Duration(
      RequestTimers::Kind::SEND_START, RequestTimers::Kind::SEND_END);
  stat_.cumulative_receive_time_ns += timers.Duration(
      RequestTimers::Kind::RECV_START, RequestTimers::Kind::RECV_END);
}

Error
InferenceServerHttpClient::ClientInferStat(InferStat* stat)
{
  std::lock_guard<std::mutex> lk(stat_mu_);
  *stat = stat_;
  return Error::Success();
}

Error
InferenceServerHttpClient::InferMulti(
    std::vector<InferResultPtr>* results,
    const std::vector<InferOptions>& options,
    const std::vector<std::vector<InferInput*>>& inputs,
    const std::vector<std::vector<const InferRequestedOutput*>>& outputs)
{
  if (options.size() != 1 && options.size() != inputs.size()) {
    return Error("options count must be 1 or match request count");
  }
  if (!outputs.empty() && outputs.size() != 1 &&
      outputs.size() != inputs.size()) {
    return Error("outputs count must be 0, 1, or match request count");
  }
  results->clear();
  static const std::vector<const InferRequestedOutput*> kNoOutputs;
  for (size_t i = 0; i < inputs.size(); i++) {
    const InferOptions& opt = options.size() == 1 ? options[0] : options[i];
    const auto& outs = outputs.empty()
                           ? kNoOutputs
                           : (outputs.size() == 1 ? outputs[0] : outputs[i]);
    InferResultPtr result;
    Error err = Infer(&result, opt, inputs[i], outs);
    if (!err.IsOk()) return err;
    results->push_back(result);
  }
  return Error::Success();
}

Error
InferenceServerHttpClient::AsyncInferMulti(
    std::function<void(std::vector<InferResultPtr>, Error)> callback,
    const std::vector<InferOptions>& options,
    const std::vector<std::vector<InferInput*>>& inputs,
    const std::vector<std::vector<const InferRequestedOutput*>>& outputs)
{
  if (options.size() != 1 && options.size() != inputs.size()) {
    return Error("options count must be 1 or match request count");
  }
  if (!outputs.empty() && outputs.size() != 1 &&
      outputs.size() != inputs.size()) {
    return Error("outputs count must be 0, 1, or match request count");
  }
  // fan out on the reactor; gather in order; one callback when all land
  struct Gather {
    std::mutex mu;
    std::vector<InferResultPtr> results;
    Error first_error;
    size_t remaining;
    std::function<void(std::vector<InferResultPtr>, Error)> callback;
  };
  if (inputs.empty()) {  // the callback must fire exactly once, even empty
    callback({}, Error::Success());
    return Error::Success();
  }
  auto gather = std::make_shared<Gather>();
  gather->results.resize(inputs.size());
  gather->remaining = inputs.size();
  gather->callback = std::move(callback);
  static const std::vector<const InferRequestedOutput*> kNoOutputs;
  for (size_t i = 0; i < inputs.size(); i++) {
    const InferOptions& opt = options.size() == 1 ? options[0] : options[i];
    const auto& outs = outputs.empty()
                           ? kNoOutputs
                           : (outputs.size() == 1 ? outputs[0] : outputs[i]);
    auto complete = [gather, i](InferResultPtr result, Error status) {
      bool last = false;
      {
        std::lock_guard<std::mutex> lk(gather->mu);
        gather->results[i] = result;
        if (!status.IsOk() && gather->first_error.IsOk())
          gather->first_error = status;
        last = (--gather->remaining == 0);
      }
      if (last) gather->callback(gather->results, gather->first_error);
    };
    Error err = AsyncInfer(complete, opt, inputs[i], outs);
    // A mid-batch submission failure cannot return an error: earlier
    // requests are already in flight (a caller retrying the batch would
    // double-execute them).  Route it through the gather as this request's
    // completion instead — the one batch callback reports it.
    if (!err.IsOk()) complete(nullptr, err);
  }
  return Error::Success();
}

Error
InferenceServerHttpClient::AsyncInfer(
    std::function<void(InferResultPtr, Error)> callback,
    const InferOptions& options, const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs)
{
  if (callback == nullptr)
    return Error("AsyncInfer requires a completion callback");
  if (tls_enabled_) {
    return Error(
        "AsyncInfer is not supported on TLS connections (the epoll reactor "
        "is fd-based); use Infer, or terminate TLS in a local proxy");
  }
  {
    std::lock_guard<std::mutex> lk(reactor_mu_);
    if (reactor_ == nullptr) {
      auto reactor =
          std::unique_ptr<HttpReactor>(new HttpReactor(host_, port_));
      Error err = reactor->Start();
      if (!err.IsOk()) return err;
      reactor_ = std::move(reactor);
    }
  }
  std::string body;
  size_t header_length = 0;
  Error err = GenerateRequestBody(&body, &header_length, options, inputs,
                                  outputs);
  if (!err.IsOk()) return err;
  std::string uri = "/v2/models/" + UrlEncode(options.model_name);
  if (!options.model_version.empty()) {
    uri += "/versions/" + options.model_version;
  }
  uri += "/infer";
  std::ostringstream req;
  req << "POST " << uri << " HTTP/1.1\r\n";
  req << "Host: " << host_ << ":" << port_ << "\r\n";
  req << "Content-Length: " << body.size() << "\r\n";
  req << "Connection: keep-alive\r\n";
  req << "Content-Type: application/octet-stream\r\n";
  req << "Inference-Header-Content-Length: " << header_length << "\r\n";
  req << "\r\n";
  std::string framed = req.str() + body;
  uint64_t deadline_ns = 0;
  if (options.client_timeout_us > 0) {
    deadline_ns =
        std::chrono::duration_cast<std::chrono::nanoseconds>(
            std::chrono::steady_clock::now().time_since_epoch())
            .count() +
        options.client_timeout_us * 1000ull;
  }
  reactor_->Submit(
      std::move(framed),
      [callback](HttpResponse response, Error status) {
        if (!status.IsOk()) {
          callback(nullptr, status);
          return;
        }
        if (response.status != 200) {
          callback(nullptr, ErrorFromResponse(response));
          return;
        }
        size_t resp_header_len = 0;
        auto it = response.headers.find("inference-header-content-length");
        if (it != response.headers.end()) {
          try {
            resp_header_len = std::stoull(it->second);
          }
          catch (...) {
            callback(nullptr,
                     Error("malformed Inference-Header-Content-Length"));
            return;
          }
        }
        InferResultPtr result;
        Error perr = ParseResponseBody(
            &result, std::move(response.body), resp_header_len);
        callback(result, perr);
      },
      deadline_ns);
  return Error::Success();
}

}  // namespace ctpu
