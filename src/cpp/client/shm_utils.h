// POSIX system shared-memory helpers — parity with the reference shm_utils
// (reference src/c++/library/shm_utils.h:38-64): create/map/close/unlink
// regions used with RegisterSystemSharedMemory.
#pragma once

#include <cstddef>

#include "common.h"

namespace ctpu {

// shm_open(O_CREAT|O_RDWR) + ftruncate; returns the fd.
Error CreateSharedMemoryRegion(
    const std::string& shm_key, size_t byte_size, int* shm_fd);

// mmap a window of the region.
Error MapSharedMemory(
    int shm_fd, size_t offset, size_t byte_size, void** shm_addr);

Error CloseSharedMemory(int shm_fd);
Error UnlinkSharedMemoryRegion(const std::string& shm_key);
Error UnmapSharedMemory(void* shm_addr, size_t byte_size);

}  // namespace ctpu
