#include "grpc_client.h"

#include <zlib.h>

#include <atomic>
#include <cstdlib>
#include <cstring>
#include <sstream>
#include <vector>

#include "../grpc/h2.h"

namespace ctpu {

namespace {

constexpr const char* kService = "/inference.GRPCInferenceService/";

std::string
LpmFrame(const std::string& message, bool compressed = false)
{
  std::string out;
  out.reserve(message.size() + 5);
  out.push_back(compressed ? 1 : 0);
  out.push_back(static_cast<char>((message.size() >> 24) & 0xff));
  out.push_back(static_cast<char>((message.size() >> 16) & 0xff));
  out.push_back(static_cast<char>((message.size() >> 8) & 0xff));
  out.push_back(static_cast<char>(message.size() & 0xff));
  out += message;
  return out;
}

// Pulls one complete length-prefixed message out of *buf (erasing it).
// Returns false when the buffer does not yet hold a complete message.
// *compressed reports the LPM compression flag — the caller must reject it
// unless it negotiated grpc-encoding (this client never advertises
// grpc-accept-encoding, so a flagged response is a protocol violation).
bool
TakeLpm(std::string* buf, std::string* message, bool* compressed = nullptr)
{
  if (buf->size() < 5) return false;
  const uint8_t* p = reinterpret_cast<const uint8_t*>(buf->data());
  if (compressed != nullptr) *compressed = p[0] != 0;
  const uint32_t len = (uint32_t(p[1]) << 24) | (uint32_t(p[2]) << 16) |
                       (uint32_t(p[3]) << 8) | uint32_t(p[4]);
  if (buf->size() < 5u + len) return false;
  message->assign(*buf, 5, len);
  buf->erase(0, 5 + len);
  return true;
}

// zlib-compress for the gRPC message encodings: "gzip" (RFC 1952 wrapper,
// windowBits 15+16) or "deflate" (RFC 1950 zlib stream).
Error
CompressMessage(const std::string& in, bool gzip, std::string* out)
{
  z_stream zs;
  std::memset(&zs, 0, sizeof(zs));
  const int window = gzip ? 15 + 16 : 15;
  if (deflateInit2(&zs, Z_DEFAULT_COMPRESSION, Z_DEFLATED, window, 8,
                   Z_DEFAULT_STRATEGY) != Z_OK)
    return Error("deflateInit2 failed");
  out->resize(deflateBound(&zs, in.size()));
  zs.next_in = reinterpret_cast<Bytef*>(const_cast<char*>(in.data()));
  zs.avail_in = in.size();
  zs.next_out = reinterpret_cast<Bytef*>(&(*out)[0]);
  zs.avail_out = out->size();
  const int rc = deflate(&zs, Z_FINISH);
  deflateEnd(&zs);
  if (rc != Z_STREAM_END) return Error("message compression failed");
  out->resize(out->size() - zs.avail_out);
  return Error::Success();
}

// Compress *body* in place per the requested algorithm and append the
// matching grpc-encoding header; *compressed reports whether the LPM flag
// must be set.  Shared by the sync and async infer paths.
Error
ApplyCompression(
    GrpcCompression compression, std::string* body,
    std::vector<h2::Header>* hdrs, bool* compressed)
{
  *compressed = false;
  if (compression == GrpcCompression::NONE) return Error::Success();
  std::string packed;
  Error err = CompressMessage(
      *body, compression == GrpcCompression::GZIP, &packed);
  if (!err.IsOk()) return err;
  body->swap(packed);
  hdrs->emplace_back(
      "grpc-encoding",
      compression == GrpcCompression::GZIP ? "gzip" : "deflate");
  *compressed = true;
  return Error::Success();
}

// Shared channel cache (reference grpc_client.cc:79-120: channels per url
// with an explicit max share count).  Each url maps to a LIST of channel
// slots; a slot is shared by at most MaxChannelShareCount() clients (env
// CLIENT_TPU_GRPC_CHANNEL_MAX_SHARE_COUNT, default 6 — the reference's
// TRITON_CLIENT_GRPC_CHANNEL_MAX_SHARE_COUNT analog), so heavy fan-out
// spreads over several real connections instead of serializing on one
// h2 session.  The map holds STRONG references and the count tracks
// clients created with use_cached_channel for that url; the last departing
// client of a slot Closes the connection from its own thread.
// (Async completion lambdas hold only weak refs — see AsyncInfer — so a
// connection's final strong reference is never dropped on its own reader
// thread, where ~H2Connection's reader join would be a self-join.)
struct CachedChannel {
  std::shared_ptr<h2::H2Connection> conn;
  int users = 0;
};
std::mutex g_channel_mu;
std::map<std::string, std::vector<CachedChannel>> g_channels;

int
MaxChannelShareCount()
{
  // read per call (not latched): cheap next to a connect, and lets tests
  // and long-lived processes adjust the fan-out policy
  const char* v = std::getenv("CLIENT_TPU_GRPC_CHANNEL_MAX_SHARE_COUNT");
  if (v != nullptr) {
    const int n = std::atoi(v);
    if (n > 0) return n;
  }
  return 6;  // reference default (grpc_client.cc:89-91)
}

std::string
PercentDecode(const std::string& in)
{
  std::string out;
  for (size_t i = 0; i < in.size(); ++i) {
    if (in[i] == '%' && i + 2 < in.size()) {
      const auto hex = [](char c) -> int {
        if (c >= '0' && c <= '9') return c - '0';
        if (c >= 'a' && c <= 'f') return c - 'a' + 10;
        if (c >= 'A' && c <= 'F') return c - 'A' + 10;
        return -1;
      };
      const int hi = hex(in[i + 1]), lo = hex(in[i + 2]);
      if (hi >= 0 && lo >= 0) {
        out.push_back(static_cast<char>(hi * 16 + lo));
        i += 2;
        continue;
      }
    }
    out.push_back(in[i]);
  }
  return out;
}

std::string
GrpcTimeoutValue(uint64_t timeout_us)
{
  // gRPC wire format: int + unit.  Microsecond resolution is plenty.
  return std::to_string(timeout_us) + "u";
}

// grpc-status / grpc-message from trailers (falling back to the initial
// headers for trailers-only responses).
Error
GrpcStatus(const h2::Stream& stream)
{
  const std::vector<h2::Header>* sets[2] = {&stream.trailers,
                                            &stream.headers};
  for (const auto* headers : sets) {
    std::string status, message;
    for (const auto& h : *headers) {
      if (h.first == "grpc-status") status = h.second;
      if (h.first == "grpc-message") message = h.second;
    }
    if (status.empty()) continue;
    if (status == "0") return Error::Success();
    std::string msg = PercentDecode(message);
    if (msg.empty()) msg = "request failed";
    return Error("[grpc-status " + status + "] " + msg);
  }
  return Error("response carried no grpc-status");
}

void
SetParam(
    google::protobuf::Map<std::string, inference::InferParameter>* params,
    const std::string& key, int64_t value)
{
  (*params)[key].set_int64_param(value);
}

void
SetParam(
    google::protobuf::Map<std::string, inference::InferParameter>* params,
    const std::string& key, const std::string& value)
{
  (*params)[key].set_string_param(value);
}

void
SetParam(
    google::protobuf::Map<std::string, inference::InferParameter>* params,
    const std::string& key, bool value)
{
  (*params)[key].set_bool_param(value);
}

}  // namespace

int
CachedChannelCountForTesting(const std::string& host_port)
{
  std::lock_guard<std::mutex> clk(g_channel_mu);
  auto it = g_channels.find(host_port);
  return it == g_channels.end() ? 0 : static_cast<int>(it->second.size());
}

Error
ParseGrpcInferResult(
    const inference::ModelInferResponse& response, InferResult** result)
{
  auto* res = new InferResult();
  res->model_name_ = response.model_name();
  res->id_ = response.id();
  {
    const auto it = response.parameters().find("triton_final_response");
    if (it != response.parameters().end())
      res->is_final_response_ = it->second.bool_param();
  }
  // Raw output bytes move into body_; Output.data points into it.
  size_t total = 0;
  for (const auto& raw : response.raw_output_contents()) total += raw.size();
  res->body_.reserve(total);
  std::vector<std::pair<size_t, size_t>> spans;
  for (const auto& raw : response.raw_output_contents()) {
    spans.emplace_back(res->body_.size(), raw.size());
    res->body_ += raw;
  }
  for (int i = 0; i < response.outputs_size(); ++i) {
    const auto& out = response.outputs(i);
    InferResult::Output o;
    o.datatype = out.datatype();
    o.shape.assign(out.shape().begin(), out.shape().end());
    if (i < static_cast<int>(spans.size())) {
      o.data = reinterpret_cast<const uint8_t*>(res->body_.data()) +
               spans[i].first;
      o.byte_size = spans[i].second;
    }
    const auto shm = out.parameters().find("shared_memory_region");
    if (shm != out.parameters().end()) o.in_shared_memory = true;
    // classification-extension string values ride typed contents
    for (const auto& s : out.contents().bytes_contents())
      o.json_values.push_back(s);
    res->outputs_.emplace(out.name(), std::move(o));
  }
  *result = res;
  return Error::Success();
}

Error
InferenceServerGrpcClient::Create(
    std::unique_ptr<InferenceServerGrpcClient>* client, const std::string& url,
    bool verbose)
{
  std::string hostport = url;
  const size_t scheme = hostport.find("://");
  if (scheme != std::string::npos) hostport = hostport.substr(scheme + 3);
  std::string host = hostport;
  int port = 8001;
  if (!hostport.empty() && hostport[0] == '[') {  // [v6-literal]:port
    const size_t close = hostport.find(']');
    if (close == std::string::npos) return Error("malformed IPv6 url");
    host = hostport.substr(1, close - 1);
    if (close + 1 < hostport.size() && hostport[close + 1] == ':')
      port = std::stoi(hostport.substr(close + 2));
  } else {
    const size_t colon = hostport.rfind(':');
    if (colon != std::string::npos) {
      host = hostport.substr(0, colon);
      port = std::stoi(hostport.substr(colon + 1));
    }
  }
  client->reset(new InferenceServerGrpcClient(host, port, verbose));
  return Error::Success();
}

InferenceServerGrpcClient::InferenceServerGrpcClient(
    const std::string& host, int port, bool verbose)
    : host_(host), port_(port), verbose_(verbose)
{
}

Error
InferenceServerGrpcClient::Create(
    std::unique_ptr<InferenceServerGrpcClient>* client, const std::string& url,
    const KeepAliveOptions& keepalive, bool use_cached_channel, bool verbose)
{
  Error err = Create(client, url, verbose);
  if (!err.IsOk()) return err;
  (*client)->keepalive_ = keepalive;
  (*client)->keepalive_enabled_ =
      keepalive.keepalive_time_ms > 0 &&
      keepalive.keepalive_time_ms < INT32_MAX;
  (*client)->shared_channel_ = use_cached_channel;
  return Error::Success();
}

Error
InferenceServerGrpcClient::Create(
    std::unique_ptr<InferenceServerGrpcClient>* client, const std::string& url,
    const GrpcSslOptions& ssl_options, bool verbose)
{
  // Probe the TLS transport seam up front so a misconfigured build fails at
  // Create (the reference fails at channel creation too) instead of on the
  // first request.  The per-connection transport is made in Connected().
  TlsConfig probe;
  probe.root_certificates = ssl_options.root_certificates;
  probe.private_key = ssl_options.private_key;
  probe.certificate_chain = ssl_options.certificate_chain;
  std::unique_ptr<ByteTransport> transport;
  Error err = MakeTlsTransport(probe, &transport);
  if (!err.IsOk()) {
    client->reset();
    return err;
  }
  err = Create(client, url, verbose);
  if (!err.IsOk()) return err;
  (*client)->tls_enabled_ = true;
  (*client)->tls_config_ = probe;
  return Error::Success();
}

InferenceServerGrpcClient::~InferenceServerGrpcClient()
{
  StopStream();
  if (conn_ == nullptr) {
    return;  // never connected: nothing attached, no share count held
  }
  if (!shared_channel_) {
    conn_->Close();
    return;
  }
  // Cached channel: decrement the share count; the LAST user closes the
  // connection (from this client thread — never the reader's).
  if (attached_) DropCachedUser(conn_);
}

void
InferenceServerGrpcClient::DropCachedUser(
    const std::shared_ptr<h2::H2Connection>& conn)
{
  const std::string key = host_ + ":" + std::to_string(port_);
  std::shared_ptr<h2::H2Connection> to_close;
  {
    std::lock_guard<std::mutex> clk(g_channel_mu);
    auto it = g_channels.find(key);
    if (it == g_channels.end()) {
      to_close = conn;  // entry replaced after a reconnect; ours to close
    } else {
      auto& slots = it->second;
      bool found = false;
      for (auto sit = slots.begin(); sit != slots.end(); ++sit) {
        if (sit->conn == conn) {
          found = true;
          if (--sit->users <= 0) {
            to_close = sit->conn;
            slots.erase(sit);
          }
          break;
        }
      }
      if (!found) to_close = conn;  // our slot was pruned after a reconnect
      if (slots.empty()) g_channels.erase(it);
    }
  }
  if (to_close != nullptr) to_close->Close();
}

Error
InferenceServerGrpcClient::Connected()
{
  std::lock_guard<std::mutex> lk(conn_mu_);
  if (conn_ != nullptr && conn_->IsOpen()) return Error::Success();
  // The old connection object (if any) stays alive for as long as any
  // in-flight call or async callback still holds its shared_ptr.
  if (shared_channel_) {
    const std::string key = host_ + ":" + std::to_string(port_);
    if (conn_ != nullptr && attached_) {
      // Reconnect: leave the dead slot first (closing it if we were its
      // last user) so share counts stay exact before re-attaching below.
      auto dead = conn_;
      conn_ = nullptr;
      attached_ = false;
      DropCachedUser(dead);
    }
    const int max_share = MaxChannelShareCount();
    {
      std::lock_guard<std::mutex> clk(g_channel_mu);
      auto it = g_channels.find(key);
      if (it != g_channels.end()) {
        for (auto& slot : it->second) {
          if (slot.conn->IsOpen() && slot.users < max_share) {
            slot.users++;
            attached_ = true;
            conn_ = slot.conn;
            // a later client's keepalive request applies to the shared
            // channel (first effective enabler's interval wins)
            if (keepalive_enabled_)
              conn_->EnableKeepAlive(
                  keepalive_.keepalive_time_ms,
                  keepalive_.keepalive_timeout_ms);
            return Error::Success();
          }
        }
      }
    }
    // No attachable slot (none yet, all dead, or all at the share cap):
    // connect a new channel OUTSIDE the cache lock — a slow/unroutable
    // host must not stall every cached-channel client process-wide.
    auto fresh = std::make_shared<h2::H2Connection>();
    Error err = fresh->Connect(host_, port_);
    if (!err.IsOk()) return err;
    if (keepalive_enabled_)
      fresh->EnableKeepAlive(
          keepalive_.keepalive_time_ms, keepalive_.keepalive_timeout_ms);
    std::shared_ptr<h2::H2Connection> lost_race;
    {
      std::lock_guard<std::mutex> clk(g_channel_mu);
      auto& slots = g_channels[key];
      // prune slots nobody holds whose connection died meanwhile
      for (auto sit = slots.begin(); sit != slots.end();) {
        if (sit->users <= 0 && !sit->conn->IsOpen()) {
          sit = slots.erase(sit);
        } else {
          ++sit;
        }
      }
      // another thread may have opened an attachable slot while we
      // connected; adopt it and discard ours to keep the channel count low
      for (auto& slot : slots) {
        if (slot.conn->IsOpen() && slot.users < max_share) {
          slot.users++;
          attached_ = true;
          conn_ = slot.conn;
          lost_race = fresh;
          break;
        }
      }
      if (lost_race == nullptr) {
        slots.push_back(CachedChannel{fresh, 1});
        attached_ = true;
        conn_ = fresh;
      }
    }
    if (lost_race != nullptr) lost_race->Close();
    return Error::Success();
  }
  // Close the dead connection BEFORE replacing it: Close joins its reader
  // thread, so no in-flight async callback can end up holding its last
  // strong reference on that thread (where ~H2Connection's join would be a
  // self-join).  Its failure callbacks have all fired by now.
  if (conn_ != nullptr) conn_->Close();
  conn_ = std::make_shared<h2::H2Connection>();
  Error err;
  if (tls_enabled_) {
    std::unique_ptr<ByteTransport> transport;
    err = MakeTlsTransport(tls_config_, &transport);
    if (err.IsOk())
      err = conn_->ConnectWith(std::move(transport), host_, port_);
  } else {
    err = conn_->Connect(host_, port_);
  }
  if (err.IsOk() && keepalive_enabled_)
    conn_->EnableKeepAlive(
        keepalive_.keepalive_time_ms, keepalive_.keepalive_timeout_ms);
  return err;
}

std::shared_ptr<h2::H2Connection>
InferenceServerGrpcClient::Conn()
{
  std::lock_guard<std::mutex> lk(conn_mu_);
  return conn_;
}

Error
InferenceServerGrpcClient::Call(
    const std::string& method, const google::protobuf::Message& request,
    google::protobuf::Message* response, uint64_t timeout_us,
    const std::vector<std::pair<std::string, std::string>>& headers,
    GrpcCompression compression)
{
  Error err = Connected();
  if (!err.IsOk()) return err;
  auto conn = Conn();  // pin across the call (reconnects swap conn_)

  std::string body;
  if (!request.SerializeToString(&body))
    return Error("failed to serialize " + method + " request");

  std::vector<h2::Header> hdrs = {
      {":method", "POST"},
      {":scheme", "http"},
      {":path", std::string(kService) + method},
      {":authority", host_ + ":" + std::to_string(port_)},
      {"content-type", "application/grpc"},
      {"te", "trailers"},
      {"user-agent", "ctpu-grpc-client/1.0"},
  };
  bool compressed = false;
  err = ApplyCompression(compression, &body, &hdrs, &compressed);
  if (!err.IsOk()) return err;
  if (timeout_us > 0)
    hdrs.emplace_back("grpc-timeout", GrpcTimeoutValue(timeout_us));
  for (const auto& h : headers) hdrs.emplace_back(h.first, h.second);

  int32_t sid = 0;
  err = conn->StartStream(hdrs, false, &sid);
  if (!err.IsOk()) return err;
  const std::string framed = LpmFrame(body, compressed);
  const int64_t deadline_ms =
      timeout_us > 0 ? static_cast<int64_t>(timeout_us / 1000) + 1 : 0;
  err = conn->SendData(
      sid, reinterpret_cast<const uint8_t*>(framed.data()), framed.size(),
      true, deadline_ms);
  if (err.IsOk()) err = conn->WaitEndStream(sid, deadline_ms);
  if (!err.IsOk()) {
    conn->ResetStream(sid, 0x8 /* CANCEL */);
    conn->ForgetStream(sid);
    return err;
  }
  auto stream = conn->GetStream(sid);
  std::string wire;
  wire.swap(stream->data);
  conn->ForgetStream(sid);
  err = GrpcStatus(*stream);
  if (!err.IsOk()) return err;
  std::string message;
  bool rx_compressed = false;
  if (!TakeLpm(&wire, &message, &rx_compressed))
    return Error(method + " response carried no message");
  if (rx_compressed)
    return Error(
        "compressed gRPC response messages are not supported (this client "
        "sends no grpc-accept-encoding)");
  if (!response->ParseFromString(message))
    return Error("failed to parse " + method + " response");
  if (verbose_) {
    std::ostringstream oss;
    oss << method << " OK: " << response->ShortDebugString();
    fprintf(stderr, "%s\n", oss.str().c_str());
  }
  return Error::Success();
}

// ---------------------------------------------------------------------------
// management surface
// ---------------------------------------------------------------------------

Error
InferenceServerGrpcClient::IsServerLive(bool* live)
{
  inference::ServerLiveRequest request;
  inference::ServerLiveResponse response;
  Error err = Call("ServerLive", request, &response);
  *live = err.IsOk() && response.live();
  return err;
}

Error
InferenceServerGrpcClient::IsServerReady(bool* ready)
{
  inference::ServerReadyRequest request;
  inference::ServerReadyResponse response;
  Error err = Call("ServerReady", request, &response);
  *ready = err.IsOk() && response.ready();
  return err;
}

Error
InferenceServerGrpcClient::IsModelReady(
    bool* ready, const std::string& model_name,
    const std::string& model_version)
{
  inference::ModelReadyRequest request;
  request.set_name(model_name);
  request.set_version(model_version);
  inference::ModelReadyResponse response;
  Error err = Call("ModelReady", request, &response);
  *ready = err.IsOk() && response.ready();
  return err;
}

Error
InferenceServerGrpcClient::ServerMetadata(
    inference::ServerMetadataResponse* response)
{
  inference::ServerMetadataRequest request;
  return Call("ServerMetadata", request, response);
}

Error
InferenceServerGrpcClient::ModelMetadata(
    inference::ModelMetadataResponse* response, const std::string& name,
    const std::string& version)
{
  inference::ModelMetadataRequest request;
  request.set_name(name);
  request.set_version(version);
  return Call("ModelMetadata", request, response);
}

Error
InferenceServerGrpcClient::ModelConfig(
    inference::ModelConfigResponse* response, const std::string& name,
    const std::string& version)
{
  inference::ModelConfigRequest request;
  request.set_name(name);
  request.set_version(version);
  return Call("ModelConfig", request, response);
}

Error
InferenceServerGrpcClient::ModelRepositoryIndex(
    inference::RepositoryIndexResponse* response)
{
  inference::RepositoryIndexRequest request;
  return Call("RepositoryIndex", request, response);
}

Error
InferenceServerGrpcClient::LoadModel(
    const std::string& name, const std::string& config_json)
{
  inference::RepositoryModelLoadRequest request;
  request.set_model_name(name);
  if (!config_json.empty())
    (*request.mutable_parameters())["config"].set_string_param(config_json);
  inference::RepositoryModelLoadResponse response;
  return Call("RepositoryModelLoad", request, &response);
}

Error
InferenceServerGrpcClient::UnloadModel(const std::string& name)
{
  inference::RepositoryModelUnloadRequest request;
  request.set_model_name(name);
  inference::RepositoryModelUnloadResponse response;
  return Call("RepositoryModelUnload", request, &response);
}

Error
InferenceServerGrpcClient::ModelInferenceStatistics(
    inference::ModelStatisticsResponse* response, const std::string& name,
    const std::string& version)
{
  inference::ModelStatisticsRequest request;
  request.set_name(name);
  request.set_version(version);
  return Call("ModelStatistics", request, response);
}

Error
InferenceServerGrpcClient::UpdateTraceSettings(
    inference::TraceSettingResponse* response, const std::string& model_name,
    const std::map<std::string, std::vector<std::string>>& settings)
{
  inference::TraceSettingRequest request;
  request.set_model_name(model_name);
  for (const auto& kv : settings) {
    auto& value = (*request.mutable_settings())[kv.first];
    for (const auto& v : kv.second) value.add_value(v);
  }
  return Call("TraceSetting", request, response);
}

Error
InferenceServerGrpcClient::GetTraceSettings(
    inference::TraceSettingResponse* response, const std::string& model_name)
{
  return UpdateTraceSettings(response, model_name, {});
}

Error
InferenceServerGrpcClient::UpdateLogSettings(
    inference::LogSettingsResponse* response,
    const std::map<std::string, std::string>& settings)
{
  inference::LogSettingsRequest request;
  for (const auto& kv : settings) {
    auto& value = (*request.mutable_settings())[kv.first];
    // bool and uint32 settings ride their natural types; the rest strings
    // (mirror of the python client's log_settings plumbing)
    if (kv.second == "true" || kv.second == "false") {
      value.set_bool_param(kv.second == "true");
    } else if (!kv.second.empty() && kv.second.size() <= 9 &&
               kv.second.find_first_not_of("0123456789") ==
                   std::string::npos) {
      // <= 9 digits always fits uint32; longer numerics ride as strings
      // rather than throwing or truncating
      value.set_uint32_param(
          static_cast<uint32_t>(std::stoul(kv.second)));
    } else {
      value.set_string_param(kv.second);
    }
  }
  return Call("LogSettings", request, response);
}

Error
InferenceServerGrpcClient::GetLogSettings(
    inference::LogSettingsResponse* response)
{
  return UpdateLogSettings(response, {});
}

Error
InferenceServerGrpcClient::SystemSharedMemoryStatus(
    inference::SystemSharedMemoryStatusResponse* response,
    const std::string& region_name)
{
  inference::SystemSharedMemoryStatusRequest request;
  request.set_name(region_name);
  return Call("SystemSharedMemoryStatus", request, response);
}

Error
InferenceServerGrpcClient::RegisterSystemSharedMemory(
    const std::string& name, const std::string& key, size_t byte_size,
    size_t offset)
{
  inference::SystemSharedMemoryRegisterRequest request;
  request.set_name(name);
  request.set_key(key);
  request.set_offset(offset);
  request.set_byte_size(byte_size);
  inference::SystemSharedMemoryRegisterResponse response;
  return Call("SystemSharedMemoryRegister", request, &response);
}

Error
InferenceServerGrpcClient::UnregisterSystemSharedMemory(
    const std::string& name)
{
  inference::SystemSharedMemoryUnregisterRequest request;
  request.set_name(name);
  inference::SystemSharedMemoryUnregisterResponse response;
  return Call("SystemSharedMemoryUnregister", request, &response);
}

Error
InferenceServerGrpcClient::TpuSharedMemoryStatus(
    inference::TpuSharedMemoryStatusResponse* response,
    const std::string& region_name)
{
  inference::TpuSharedMemoryStatusRequest request;
  request.set_name(region_name);
  return Call("TpuSharedMemoryStatus", request, response);
}

Error
InferenceServerGrpcClient::RegisterTpuSharedMemory(
    const std::string& name, const std::string& raw_handle, int device_id,
    size_t byte_size)
{
  inference::TpuSharedMemoryRegisterRequest request;
  request.set_name(name);
  request.set_raw_handle(raw_handle);
  request.set_device_id(device_id);
  request.set_byte_size(byte_size);
  inference::TpuSharedMemoryRegisterResponse response;
  return Call("TpuSharedMemoryRegister", request, &response);
}

Error
InferenceServerGrpcClient::UnregisterTpuSharedMemory(const std::string& name)
{
  inference::TpuSharedMemoryUnregisterRequest request;
  request.set_name(name);
  inference::TpuSharedMemoryUnregisterResponse response;
  return Call("TpuSharedMemoryUnregister", request, &response);
}

// ---------------------------------------------------------------------------
// inference
// ---------------------------------------------------------------------------

Error
InferenceServerGrpcClient::BuildInferRequest(
    const InferOptions& options, const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs,
    inference::ModelInferRequest* request)
{
  request->set_model_name(options.model_name);
  request->set_model_version(options.model_version);
  request->set_id(options.request_id);
  auto* params = request->mutable_parameters();
  if (!options.sequence_id_str.empty()) {
    SetParam(params, "sequence_id", options.sequence_id_str);
    SetParam(params, "sequence_start", options.sequence_start);
    SetParam(params, "sequence_end", options.sequence_end);
  } else if (options.sequence_id != 0) {
    SetParam(params, "sequence_id",
             static_cast<int64_t>(options.sequence_id));
    SetParam(params, "sequence_start", options.sequence_start);
    SetParam(params, "sequence_end", options.sequence_end);
  }
  if (options.priority != 0)
    SetParam(params, "priority", static_cast<int64_t>(options.priority));
  if (options.timeout_us != 0)
    SetParam(params, "timeout", static_cast<int64_t>(options.timeout_us));
  if (options.enable_empty_final_response)
    SetParam(params, "triton_enable_empty_final_response", true);

  for (const InferInput* input : inputs) {
    auto* tensor = request->add_inputs();
    tensor->set_name(input->Name());
    tensor->set_datatype(input->Datatype());
    for (const int64_t d : input->Shape()) tensor->add_shape(d);
    if (input->IsSharedMemory()) {
      auto* tp = tensor->mutable_parameters();
      SetParam(tp, "shared_memory_region", input->SharedMemoryName());
      SetParam(tp, "shared_memory_byte_size",
               static_cast<int64_t>(input->SharedMemoryByteSize()));
      if (input->SharedMemoryOffset() != 0)
        SetParam(tp, "shared_memory_offset",
                 static_cast<int64_t>(input->SharedMemoryOffset()));
    } else {
      std::string* raw = request->add_raw_input_contents();
      raw->reserve(input->TotalByteSize());
      for (const auto& buf : input->Buffers())
        raw->append(reinterpret_cast<const char*>(buf.first), buf.second);
    }
  }
  for (const InferRequestedOutput* output : outputs) {
    auto* tensor = request->add_outputs();
    tensor->set_name(output->Name());
    auto* tp = tensor->mutable_parameters();
    if (output->ClassCount() > 0)
      SetParam(tp, "classification",
               static_cast<int64_t>(output->ClassCount()));
    if (output->IsSharedMemory()) {
      SetParam(tp, "shared_memory_region", output->SharedMemoryName());
      SetParam(tp, "shared_memory_byte_size",
               static_cast<int64_t>(output->SharedMemoryByteSize()));
      if (output->SharedMemoryOffset() != 0)
        SetParam(tp, "shared_memory_offset",
                 static_cast<int64_t>(output->SharedMemoryOffset()));
    }
  }
  return Error::Success();
}

void
InferenceServerGrpcClient::UpdateStat(const RequestTimers& timers)
{
  std::lock_guard<std::mutex> lk(stat_mu_);
  stat_.completed_request_count++;
  stat_.cumulative_total_request_time_ns += timers.Duration(
      RequestTimers::Kind::REQUEST_START, RequestTimers::Kind::REQUEST_END);
  stat_.cumulative_send_time_ns += timers.Duration(
      RequestTimers::Kind::SEND_START, RequestTimers::Kind::SEND_END);
  stat_.cumulative_receive_time_ns += timers.Duration(
      RequestTimers::Kind::RECV_START, RequestTimers::Kind::RECV_END);
}

Error
InferenceServerGrpcClient::ClientInferStat(InferStat* stat)
{
  std::lock_guard<std::mutex> lk(stat_mu_);
  *stat = stat_;
  return Error::Success();
}

Error
InferenceServerGrpcClient::Infer(
    InferResult** result, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs,
    const std::vector<std::pair<std::string, std::string>>& headers,
    GrpcCompression compression)
{
  RequestTimers timers;
  timers.Capture(RequestTimers::Kind::REQUEST_START);
  inference::ModelInferRequest request;
  Error err = BuildInferRequest(options, inputs, outputs, &request);
  if (!err.IsOk()) return err;
  inference::ModelInferResponse response;
  timers.Capture(RequestTimers::Kind::SEND_START);
  err = Call("ModelInfer", request, &response, options.client_timeout_us,
             headers, compression);
  timers.Capture(RequestTimers::Kind::SEND_END);
  timers.Capture(RequestTimers::Kind::RECV_START);
  if (!err.IsOk()) return err;
  err = ParseGrpcInferResult(response, result);
  timers.Capture(RequestTimers::Kind::RECV_END);
  timers.Capture(RequestTimers::Kind::REQUEST_END);
  UpdateStat(timers);
  return err;
}

Error
InferenceServerGrpcClient::AsyncInfer(
    OnCompleteFn callback, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs,
    const std::vector<std::pair<std::string, std::string>>& headers,
    GrpcCompression compression)
{
  if (callback == nullptr)
    return Error("AsyncInfer requires a completion callback");
  Error err = Connected();
  if (!err.IsOk()) return err;

  inference::ModelInferRequest request;
  err = BuildInferRequest(options, inputs, outputs, &request);
  if (!err.IsOk()) return err;
  std::string body;
  if (!request.SerializeToString(&body))
    return Error("failed to serialize ModelInfer request");

  std::vector<h2::Header> hdrs = {
      {":method", "POST"},
      {":scheme", "http"},
      {":path", std::string(kService) + "ModelInfer"},
      {":authority", host_ + ":" + std::to_string(port_)},
      {"content-type", "application/grpc"},
      {"te", "trailers"},
      {"user-agent", "ctpu-grpc-client/1.0"},
  };
  bool compressed = false;
  err = ApplyCompression(compression, &body, &hdrs, &compressed);
  if (!err.IsOk()) return err;
  if (options.client_timeout_us > 0)
    hdrs.emplace_back("grpc-timeout",
                      GrpcTimeoutValue(options.client_timeout_us));
  for (const auto& h : headers) hdrs.emplace_back(h.first, h.second);

  // The reactor thread completes the request: on end-of-stream, parse and
  // fire the user callback (the reference's AsyncReqRepr + cq thread,
  // grpc_client.cc:1407-1504).  StartStream needs the callback before the
  // stream id exists, so the lambda reads it from a shared holder.  The
  // lambda holds only a WEAK connection reference: it runs on the reader
  // thread, and an owning capture could make that thread drop the last
  // strong reference — ~H2Connection would then self-join its own reader.
  // While the callback runs the connection is alive by construction (the
  // reader thread is inside it), and every strong holder (client, channel
  // cache) Closes before releasing.
  auto conn_sp = Conn();
  auto* conn = conn_sp.get();
  std::weak_ptr<h2::H2Connection> conn_wp = conn_sp;
  auto done = std::make_shared<std::atomic<bool>>(false);
  int32_t sid = 0;
  auto sid_holder = std::make_shared<std::atomic<int32_t>>(0);
  auto user_cb = std::make_shared<OnCompleteFn>(std::move(callback));
  err = conn->StartStream(
      hdrs, false, &sid, [this, conn_wp, done, sid_holder, user_cb]() {
        auto pinned = conn_wp.lock();
        if (pinned == nullptr) return;  // connection already torn down
        auto* conn = pinned.get();
        const int32_t s = sid_holder->load();
        if (s == 0) return;
        auto stream = conn->GetStream(s);
        if (stream == nullptr || !stream->end_stream) return;
        if (done->exchange(true)) return;  // single completion
        InferResult* raw = nullptr;
        Error status = conn->ConnectionError();
        if (status.IsOk() && stream->reset)
          status = Error("h2 stream reset (code " +
                         std::to_string(stream->rst_code) + ")");
        if (status.IsOk()) status = GrpcStatus(*stream);
        if (status.IsOk()) {
          std::string wire;
          wire.swap(stream->data);
          std::string message;
          bool rx_compressed = false;
          inference::ModelInferResponse response;
          if (!TakeLpm(&wire, &message, &rx_compressed))
            status = Error("ModelInfer response carried no message");
          else if (rx_compressed)
            status = Error("compressed gRPC response messages are not supported");
          else if (!response.ParseFromString(message))
            status = Error("failed to parse ModelInfer response");
          else
            status = ParseGrpcInferResult(response, &raw);
        }
        conn->ForgetStream(s);
        if (raw == nullptr) raw = new InferResult();
        raw->error_ = status;
        (*user_cb)(InferResultPtr(raw));
      });
  if (!err.IsOk()) return err;
  sid_holder->store(sid);
  const std::string framed = LpmFrame(body, compressed);
  // From here on the request is owned by the callback path: a send failure
  // surfaces through the stream/connection event (reset or FailConnection),
  // which fires the completion — returning the error too would double-report
  // one request (a retry loop would double-submit).
  const int64_t send_deadline_ms =
      options.client_timeout_us > 0
          ? static_cast<int64_t>(options.client_timeout_us / 1000) + 1
          : 0;
  Error send_err = conn->SendData(
      sid, reinterpret_cast<const uint8_t*>(framed.data()), framed.size(),
      true, send_deadline_ms);
  if (!send_err.IsOk()) conn->ResetStream(sid, 0x8 /* CANCEL */);
  // The stream may already have completed before sid_holder was set (tiny
  // responses) or via the reset above: nudge once.
  auto stream = conn->GetStream(sid);
  if (stream != nullptr && stream->end_stream && stream->on_event)
    stream->on_event();
  return Error::Success();
}

// ---------------------------------------------------------------------------
// batched multi-request variants (reference grpc_client.h:455-494)
// ---------------------------------------------------------------------------

Error
InferenceServerGrpcClient::InferMulti(
    std::vector<InferResult*>* results, const std::vector<InferOptions>& options,
    const std::vector<std::vector<InferInput*>>& inputs,
    const std::vector<std::vector<const InferRequestedOutput*>>& outputs,
    const std::vector<std::pair<std::string, std::string>>& headers)
{
  // The reference permits a single shared options/outputs row for N inputs.
  if (inputs.empty()) return Error("InferMulti needs at least one request");
  if (options.size() != 1 && options.size() != inputs.size())
    return Error("InferMulti options must be size 1 or match inputs");
  if (!outputs.empty() && outputs.size() != 1 &&
      outputs.size() != inputs.size())
    return Error("InferMulti outputs must be empty, size 1, or match inputs");
  results->clear();
  for (size_t i = 0; i < inputs.size(); ++i) {
    const InferOptions& opt = options.size() == 1 ? options[0] : options[i];
    static const std::vector<const InferRequestedOutput*> kNoOutputs;
    const auto& outs = outputs.empty()
                           ? kNoOutputs
                           : (outputs.size() == 1 ? outputs[0] : outputs[i]);
    InferResult* result = nullptr;
    Error err = Infer(&result, opt, inputs[i], outs, headers);
    if (!err.IsOk()) return err;
    results->push_back(result);
  }
  return Error::Success();
}

Error
InferenceServerGrpcClient::AsyncInferMulti(
    OnMultiCompleteFn callback, const std::vector<InferOptions>& options,
    const std::vector<std::vector<InferInput*>>& inputs,
    const std::vector<std::vector<const InferRequestedOutput*>>& outputs,
    const std::vector<std::pair<std::string, std::string>>& headers)
{
  if (callback == nullptr)
    return Error("AsyncInferMulti requires a completion callback");
  if (inputs.empty())
    return Error("AsyncInferMulti needs at least one request");
  if (options.size() != 1 && options.size() != inputs.size())
    return Error("AsyncInferMulti options must be size 1 or match inputs");
  if (!outputs.empty() && outputs.size() != 1 &&
      outputs.size() != inputs.size())
    return Error(
        "AsyncInferMulti outputs must be empty, size 1, or match inputs");

  // All requests fly concurrently on the multiplexed connection; the last
  // completion fires the user callback with results in request order.
  struct MultiState {
    std::mutex mu;
    std::vector<InferResultPtr> results;
    size_t pending;
    OnMultiCompleteFn callback;
  };
  auto state = std::make_shared<MultiState>();
  state->results.resize(inputs.size());
  state->pending = inputs.size();
  state->callback = std::move(callback);
  for (size_t i = 0; i < inputs.size(); ++i) {
    const InferOptions& opt = options.size() == 1 ? options[0] : options[i];
    static const std::vector<const InferRequestedOutput*> kNoOutputs;
    const auto& outs = outputs.empty()
                           ? kNoOutputs
                           : (outputs.size() == 1 ? outputs[0] : outputs[i]);
    Error err = AsyncInfer(
        [state, i](InferResultPtr result) {
          bool fire = false;
          {
            std::lock_guard<std::mutex> lk(state->mu);
            state->results[i] = std::move(result);
            fire = (--state->pending == 0);
          }
          if (fire) state->callback(std::move(state->results));
        },
        opt, inputs[i], outs, headers);
    if (!err.IsOk()) {
      // submission failed: deliver an error result for this slot
      auto* res = new InferResult();
      res->error_ = err;
      bool fire = false;
      {
        std::lock_guard<std::mutex> lk(state->mu);
        state->results[i] = InferResultPtr(res);
        fire = (--state->pending == 0);
      }
      if (fire) state->callback(std::move(state->results));
    }
  }
  return Error::Success();
}

// ---------------------------------------------------------------------------
// bidi streaming
// ---------------------------------------------------------------------------

Error
InferenceServerGrpcClient::StartStream(
    OnCompleteFn callback, uint64_t stream_timeout_us,
    const std::vector<std::pair<std::string, std::string>>& headers)
{
  if (callback == nullptr)
    return Error("StartStream requires a completion callback");
  std::lock_guard<std::mutex> lk(stream_mu_);
  if (stream_sid_ != 0) return Error("stream already active");
  Error err = Connected();
  if (!err.IsOk()) return err;

  std::vector<h2::Header> hdrs = {
      {":method", "POST"},
      {":scheme", "http"},
      {":path", std::string(kService) + "ModelStreamInfer"},
      {":authority", host_ + ":" + std::to_string(port_)},
      {"content-type", "application/grpc"},
      {"te", "trailers"},
      {"user-agent", "ctpu-grpc-client/1.0"},
  };
  if (stream_timeout_us > 0)
    hdrs.emplace_back("grpc-timeout", GrpcTimeoutValue(stream_timeout_us));
  for (const auto& h : headers) hdrs.emplace_back(h.first, h.second);

  stream_callback_ = std::move(callback);
  stream_rx_.clear();
  stream_timeout_us_ = stream_timeout_us;
  auto conn_sp = Conn();
  auto* conn = conn_sp.get();
  std::weak_ptr<h2::H2Connection> conn_wp = conn_sp;
  int32_t sid = 0;
  err = conn->StartStream(hdrs, false, &sid, [this, conn_wp]() {
    // Reactor thread: drain complete stream messages, deliver results.
    // Weak capture: an owning capture could drop the connection's last
    // strong reference on its own reader thread (see AsyncInfer).
    auto pinned = conn_wp.lock();
    if (pinned == nullptr) return;
    auto* conn = pinned.get();
    std::vector<InferResultPtr> ready;
    OnCompleteFn cb;
    {
      std::lock_guard<std::mutex> lk(stream_mu_);
      // ignore events from a stale stream (client restarted streaming,
      // possibly on a new connection)
      if (stream_sid_ == 0 || stream_conn_.get() != conn) return;
      auto stream = conn->GetStream(stream_sid_);
      if (stream == nullptr) return;
      cb = stream_callback_;
      // Take everything buffered (min_bytes=0 returns immediately).
      conn->ReadData(stream_sid_, 0, &stream_rx_, 1);
      std::string message;
      bool rx_compressed = false;
      while (TakeLpm(&stream_rx_, &message, &rx_compressed)) {
        inference::ModelStreamInferResponse response;
        auto* res = new InferResult();
        if (rx_compressed) {
          res->error_ =
              Error("compressed gRPC response messages are not supported");
        } else if (!response.ParseFromString(message)) {
          res->error_ = Error("failed to parse stream response");
        } else if (!response.error_message().empty()) {
          res->error_ = Error(response.error_message());
          res->id_ = response.infer_response().id();
        } else {
          InferResult* parsed = nullptr;
          Error perr =
              ParseGrpcInferResult(response.infer_response(), &parsed);
          if (perr.IsOk()) {
            delete res;
            res = parsed;
          } else {
            res->error_ = perr;
          }
        }
        ready.emplace_back(res);
      }
      if (stream->end_stream && stream->reset) {
        auto* res = new InferResult();
        res->error_ = Error("stream closed (reset " +
                            std::to_string(stream->rst_code) + ")");
        ready.emplace_back(res);
      }
    }
    if (cb)
      for (auto& r : ready) cb(r);
  });
  if (!err.IsOk()) {
    stream_callback_ = nullptr;
    return err;
  }
  stream_conn_ = conn_sp;
  stream_sid_ = sid;
  return Error::Success();
}

Error
InferenceServerGrpcClient::AsyncStreamInfer(
    const InferOptions& options, const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs)
{
  int32_t sid;
  std::shared_ptr<h2::H2Connection> conn;
  {
    std::lock_guard<std::mutex> lk(stream_mu_);
    if (stream_sid_ == 0)
      return Error("no active stream (call StartStream first)");
    sid = stream_sid_;
    conn = stream_conn_;
  }
  inference::ModelInferRequest request;
  Error err = BuildInferRequest(options, inputs, outputs, &request);
  if (!err.IsOk()) return err;
  std::string body;
  if (!request.SerializeToString(&body))
    return Error("failed to serialize stream request");
  const std::string framed = LpmFrame(body);
  const int64_t deadline_ms =
      stream_timeout_us_ > 0
          ? static_cast<int64_t>(stream_timeout_us_ / 1000) + 1
          : 0;
  return conn->SendData(
      sid, reinterpret_cast<const uint8_t*>(framed.data()), framed.size(),
      false, deadline_ms);
}

Error
InferenceServerGrpcClient::StopStream()
{
  int32_t sid;
  std::shared_ptr<h2::H2Connection> conn;
  {
    std::lock_guard<std::mutex> lk(stream_mu_);
    if (stream_sid_ == 0) return Error::Success();
    sid = stream_sid_;
    conn = stream_conn_;
    stream_sid_ = 0;
  }
  // half-close, wait for server to finish, then drop state
  const int64_t deadline_ms =
      stream_timeout_us_ > 0
          ? static_cast<int64_t>(stream_timeout_us_ / 1000) + 1
          : 10000;
  Error err = conn->SendData(sid, nullptr, 0, true, deadline_ms);
  if (err.IsOk()) {
    conn->WaitEndStream(sid, deadline_ms);
  }
  conn->ForgetStream(sid);
  {
    std::lock_guard<std::mutex> lk(stream_mu_);
    stream_callback_ = nullptr;
    stream_conn_.reset();
    stream_rx_.clear();
  }
  return Error::Success();
}

}  // namespace ctpu
