// Native client value types — parity with the reference C++ library's
// common.h (reference src/c++/library/common.h:62-626: Error,
// InferOptions, InferInput with zero-copy AppendRaw buffer list,
// InferRequestedOutput, InferResult, RequestTimers, InferStat), re-built
// for the TPU framework with no external dependencies.
//
// Deliberate divergence: the reference's InferenceServerClient base class
// owns a worker thread + condition variable that each transport's async
// path feeds (common.h:120-154).  Here there is no shared base — each
// client owns an event-loop reactor (http_reactor.h epoll loop;
// grpc_client.h per-connection HTTP/2 reactor thread), which is the model
// the reference itself uses for HTTP (curl-multi) and gRPC (completion
// queue); the extra base-class thread would be a third mechanism with no
// consumer.  The shared pieces that ARE cross-transport (InferStat
// aggregation, RequestTimers) live in this header.
#pragma once

#include <chrono>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <string>
#include <vector>

namespace ctpu {

class Error {
 public:
  Error() = default;
  explicit Error(const std::string& msg) : msg_(msg), ok_(false) {}
  static Error Success() { return Error(); }
  bool IsOk() const { return ok_; }
  const std::string& Message() const { return msg_; }

 private:
  std::string msg_;
  bool ok_ = true;
};

// Per-request options (reference common.h:159-220).
struct InferOptions {
  explicit InferOptions(const std::string& model_name)
      : model_name(model_name)
  {
  }
  std::string model_name;
  std::string model_version;
  std::string request_id;
  // Correlation id for stateful sequences: numeric or string form
  // (reference common.h supports both; a non-empty sequence_id_str wins).
  uint64_t sequence_id = 0;
  std::string sequence_id_str;
  bool sequence_start = false;
  bool sequence_end = false;
  uint64_t priority = 0;
  uint64_t timeout_us = 0;       // server-side request timeout
  uint64_t client_timeout_us = 0;  // client-side socket deadline
  // Decoupled streams: ask the server to append one EMPTY response marked
  // triton_final_response=true when the request's stream completes, so the
  // client detects completion without model-specific EOS knowledge
  // (reference triton_enable_empty_final_response parameter).
  bool enable_empty_final_response = false;
};

// Per-client aggregate of request timers (reference common.h:94-115
// InferStat); both protocol clients expose it via ClientInferStat.
struct InferStat {
  uint64_t completed_request_count = 0;
  uint64_t cumulative_total_request_time_ns = 0;
  uint64_t cumulative_send_time_ns = 0;
  uint64_t cumulative_receive_time_ns = 0;
};

// One named input tensor.  AppendRaw keeps caller-owned buffer pointers (the
// zero-copy list of reference common.h:226-365); SetSharedMemory switches the
// payload to a region reference.
class InferInput {
 public:
  InferInput(
      const std::string& name, const std::vector<int64_t>& shape,
      const std::string& datatype)
      : name_(name), shape_(shape), datatype_(datatype)
  {
  }

  const std::string& Name() const { return name_; }
  const std::string& Datatype() const { return datatype_; }
  const std::vector<int64_t>& Shape() const { return shape_; }
  void SetShape(const std::vector<int64_t>& shape) { shape_ = shape; }

  Error AppendRaw(const uint8_t* input, size_t input_byte_size)
  {
    bufs_.emplace_back(input, input_byte_size);
    total_byte_size_ += input_byte_size;
    return Error::Success();
  }
  Error AppendRaw(const std::vector<uint8_t>& input)
  {
    return AppendRaw(input.data(), input.size());
  }

  Error SetSharedMemory(
      const std::string& region_name, size_t byte_size, size_t offset = 0)
  {
    shm_name_ = region_name;
    shm_byte_size_ = byte_size;
    shm_offset_ = offset;
    bufs_.clear();
    total_byte_size_ = 0;
    return Error::Success();
  }

  Error Reset()
  {
    bufs_.clear();
    total_byte_size_ = 0;
    shm_name_.clear();
    shm_byte_size_ = 0;
    shm_offset_ = 0;
    return Error::Success();
  }

  bool IsSharedMemory() const { return !shm_name_.empty(); }
  const std::string& SharedMemoryName() const { return shm_name_; }
  size_t SharedMemoryByteSize() const { return shm_byte_size_; }
  size_t SharedMemoryOffset() const { return shm_offset_; }
  size_t TotalByteSize() const { return total_byte_size_; }
  const std::vector<std::pair<const uint8_t*, size_t>>& Buffers() const
  {
    return bufs_;
  }

 private:
  std::string name_;
  std::vector<int64_t> shape_;
  std::string datatype_;
  std::vector<std::pair<const uint8_t*, size_t>> bufs_;
  size_t total_byte_size_ = 0;
  std::string shm_name_;
  size_t shm_byte_size_ = 0;
  size_t shm_offset_ = 0;
};

// One requested output (reference common.h:371-443).
class InferRequestedOutput {
 public:
  explicit InferRequestedOutput(
      const std::string& name, size_t class_count = 0)
      : name_(name), class_count_(class_count)
  {
  }

  const std::string& Name() const { return name_; }
  size_t ClassCount() const { return class_count_; }

  Error SetSharedMemory(
      const std::string& region_name, size_t byte_size, size_t offset = 0)
  {
    shm_name_ = region_name;
    shm_byte_size_ = byte_size;
    shm_offset_ = offset;
    return Error::Success();
  }

  bool IsSharedMemory() const { return !shm_name_.empty(); }
  const std::string& SharedMemoryName() const { return shm_name_; }
  size_t SharedMemoryByteSize() const { return shm_byte_size_; }
  size_t SharedMemoryOffset() const { return shm_offset_; }

 private:
  std::string name_;
  size_t class_count_ = 0;
  std::string shm_name_;
  size_t shm_byte_size_ = 0;
  size_t shm_offset_ = 0;
};

// Result view over a parsed response (reference common.h:449-516).  Owns the
// response body; RawData returns views into it.
class InferResult {
 public:
  struct Output {
    std::string datatype;
    std::vector<int64_t> shape;
    const uint8_t* data = nullptr;  // into body_ (binary outputs)
    size_t byte_size = 0;
    std::vector<std::string> json_values;  // non-binary / BYTES-from-JSON
    bool in_shared_memory = false;
  };

  const std::string& ModelName() const { return model_name_; }
  const std::string& Id() const { return id_; }

  Error Shape(const std::string& name, std::vector<int64_t>* shape) const
  {
    auto it = outputs_.find(name);
    if (it == outputs_.end()) return Error("unknown output '" + name + "'");
    *shape = it->second.shape;
    return Error::Success();
  }

  Error Datatype(const std::string& name, std::string* datatype) const
  {
    auto it = outputs_.find(name);
    if (it == outputs_.end()) return Error("unknown output '" + name + "'");
    *datatype = it->second.datatype;
    return Error::Success();
  }

  Error RawData(
      const std::string& name, const uint8_t** buf, size_t* byte_size) const
  {
    auto it = outputs_.find(name);
    if (it == outputs_.end()) return Error("unknown output '" + name + "'");
    if (it->second.data == nullptr)
      return Error("output '" + name + "' has no binary data");
    *buf = it->second.data;
    *byte_size = it->second.byte_size;
    return Error::Success();
  }

  // Classification-extension / BYTES values.  Typed-contents responses fill
  // json_values directly; raw binary BYTES payloads carry the 4-byte-LE
  // length framing, deserialized here exactly like the reference's
  // InferResult::StringData.
  Error StringData(
      const std::string& name, std::vector<std::string>* values) const
  {
    auto it = outputs_.find(name);
    if (it == outputs_.end()) return Error("unknown output '" + name + "'");
    if (!it->second.json_values.empty() || it->second.data == nullptr ||
        it->second.datatype != "BYTES") {
      // deframing only applies to BYTES payloads; typed tensors keep the
      // pre-existing empty-vector behavior
      *values = it->second.json_values;
      return Error::Success();
    }
    values->clear();
    const uint8_t* p = it->second.data;
    size_t off = 0;
    const size_t size = it->second.byte_size;
    while (off + 4 <= size) {
      const uint32_t len = uint32_t(p[off]) | (uint32_t(p[off + 1]) << 8) |
                           (uint32_t(p[off + 2]) << 16) |
                           (uint32_t(p[off + 3]) << 24);
      off += 4;
      if (off + len > size)
        return Error("malformed BYTES framing in output '" + name + "'");
      values->emplace_back(reinterpret_cast<const char*>(p) + off, len);
      off += len;
    }
    if (off != size)
      return Error("malformed BYTES framing in output '" + name + "'");
    return Error::Success();
  }

  const std::map<std::string, Output>& Outputs() const { return outputs_; }

  // Overall request status — meaningful for async/stream results, where the
  // failure arrives with the result instead of a return value (reference
  // common.h InferResult::RequestStatus).
  const Error& RequestStatus() const { return error_; }

  // Decoupled streams: true on the final marker response
  // (triton_final_response=true; see InferOptions
  // enable_empty_final_response).
  bool IsFinalResponse() const { return is_final_response_; }

  std::string model_name_;
  std::string id_;
  std::map<std::string, Output> outputs_;
  std::string body_;  // owns the raw response bytes
  Error error_;
  bool is_final_response_ = false;
};
using InferResultPtr = std::shared_ptr<InferResult>;

// Six-timestamp request timer (reference common.h:521-601).
struct RequestTimers {
  enum class Kind { REQUEST_START, SEND_START, SEND_END, RECV_START, RECV_END,
                    REQUEST_END };
  uint64_t ts[6] = {0, 0, 0, 0, 0, 0};
  void Capture(Kind k)
  {
    ts[static_cast<int>(k)] =
        std::chrono::duration_cast<std::chrono::nanoseconds>(
            std::chrono::steady_clock::now().time_since_epoch())
            .count();
  }
  uint64_t Duration(Kind a, Kind b) const
  {
    return ts[static_cast<int>(b)] - ts[static_cast<int>(a)];
  }
};

}  // namespace ctpu
