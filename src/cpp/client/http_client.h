// Native KServe-v2 HTTP client — parity with the reference
// InferenceServerHttpClient (reference src/c++/library/http_client.h:106-652)
// over raw POSIX sockets with keep-alive instead of libcurl: the image ships
// no curl/ssl headers and the KServe HTTP surface needs only HTTP/1.1 with
// Content-Length framing.  Implements the binary-tensor extension
// (Inference-Header-Content-Length) and the shared-memory verbs including
// the TPU region registration this framework adds.
#pragma once

#include <functional>
#include <future>
#include <string>
#include <vector>

#include "common.h"
#include "json.h"
#include "transport.h"

#include <mutex>

namespace ctpu {

class HttpReactor;

struct HttpResponse {
  int status = 0;
  std::map<std::string, std::string> headers;
  std::string body;
};

// TLS options (reference http_client.h:46-87 HttpSslOptions).  TLS rides
// the ByteTransport seam (transport.h): Create resolves a transport via
// MakeTlsTransport — a factory registered with SetTlsTransportFactory, or
// the built-in OpenSSL transport on CLIENT_TPU_ENABLE_TLS builds — and
// errors helpfully when neither exists.  Sync requests run over the TLS
// transport; the epoll-reactor async path is fd-based, so AsyncInfer on a
// TLS client returns a descriptive error (use Infer, or terminate TLS in a
// local proxy for async workloads).  client_timeout_us is enforced per
// socket op on TLS connections too: the remaining budget reaches the
// transport through ByteTransport::SetIoTimeout (SO_RCVTIMEO on the
// built-in transports), so a peer that accepts then stalls times out
// instead of hanging Infer().  Factory-registered transports that leave
// SetIoTimeout a no-op degrade to between-ops granularity.
struct HttpSslOptions {
  bool verify_peer = true;
  bool verify_host = true;
  std::string ca_info;    // CA bundle path
  std::string cert;       // client certificate path (PEM)
  std::string key;        // client private key path (PEM)
};

class InferenceServerHttpClient {
 public:
  static Error Create(
      std::unique_ptr<InferenceServerHttpClient>* client,
      const std::string& server_url, bool verbose = false);
  // HTTPS variant (also selected by an "https://" url on the plain Create);
  // see HttpSslOptions for the transport-seam note.
  static Error Create(
      std::unique_ptr<InferenceServerHttpClient>* client,
      const std::string& server_url, const HttpSslOptions& ssl_options,
      bool verbose = false);
  ~InferenceServerHttpClient();

  Error IsServerLive(bool* live);
  Error IsServerReady(bool* ready);
  Error IsModelReady(
      bool* ready, const std::string& model_name,
      const std::string& model_version = "");

  Error ServerMetadata(json::ValuePtr* metadata);
  Error ModelMetadata(
      json::ValuePtr* metadata, const std::string& model_name,
      const std::string& model_version = "");
  Error ModelConfig(
      json::ValuePtr* config, const std::string& model_name,
      const std::string& model_version = "");
  Error ModelRepositoryIndex(json::ValuePtr* index);
  Error LoadModel(const std::string& model_name);
  Error UnloadModel(const std::string& model_name);
  Error ModelInferenceStatistics(
      json::ValuePtr* stats, const std::string& model_name = "",
      const std::string& model_version = "");

  Error RegisterSystemSharedMemory(
      const std::string& name, const std::string& key, size_t byte_size,
      size_t offset = 0);
  Error UnregisterSystemSharedMemory(const std::string& name = "");
  Error SystemSharedMemoryStatus(json::ValuePtr* status);
  Error RegisterTpuSharedMemory(
      const std::string& name, const std::string& raw_handle, int device_id,
      size_t byte_size);
  Error UnregisterTpuSharedMemory(const std::string& name = "");
  Error TpuSharedMemoryStatus(json::ValuePtr* status);

  // Compression algorithms for the infer body (reference http_client.h
  // Infer(..., request_compression_algorithm, response_compression_algorithm)
  // — gzip/deflate via zlib).
  enum class CompressionType { NONE, DEFLATE, GZIP };

  Error Infer(
      InferResultPtr* result, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs = {},
      CompressionType request_compression = CompressionType::NONE,
      CompressionType response_compression = CompressionType::NONE);

  // Event-loop async: requests ride the client's epoll reactor (one thread,
  // many in-flight keep-alive connections — the reference's curl-multi
  // AsyncTransfer, http_client.cc:1882-1956).  The callback runs on the
  // reactor thread; do not block in it.
  Error AsyncInfer(
      std::function<void(InferResultPtr, Error)> callback,
      const InferOptions& options, const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs = {});

  // Batched convenience calls (reference grpc_client.h:441-494 InferMulti /
  // AsyncInferMulti): one options+inputs+outputs tuple per request; an
  // options/outputs vector of size 1 is broadcast across all requests.
  Error InferMulti(
      std::vector<InferResultPtr>* results,
      const std::vector<InferOptions>& options,
      const std::vector<std::vector<InferInput*>>& inputs,
      const std::vector<std::vector<const InferRequestedOutput*>>& outputs =
          {});
  Error AsyncInferMulti(
      std::function<void(std::vector<InferResultPtr>, Error)> callback,
      const std::vector<InferOptions>& options,
      const std::vector<std::vector<InferInput*>>& inputs,
      const std::vector<std::vector<const InferRequestedOutput*>>& outputs =
          {});

  // Per-client aggregate of request timers (reference InferStat; the gRPC
  // client exposes the same surface).
  Error ClientInferStat(InferStat* stat);

  // Request/response pipelining helpers (reference http_client.h:122-138).
  static Error GenerateRequestBody(
      std::string* body, size_t* header_length, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs);
  static Error ParseResponseBody(
      InferResultPtr* result, std::string&& body, size_t header_length);

 private:
  InferenceServerHttpClient(const std::string& url, bool verbose);
  // timeout_us > 0 bounds the whole exchange via socket send/recv timeouts
  // (InferOptions.client_timeout_us); an expiry closes the connection (the
  // response could still arrive later) and surfaces a timeout error.
  Error Request(
      HttpResponse* response, const std::string& method,
      const std::string& uri, const std::string& body,
      const std::map<std::string, std::string>& headers = {},
      RequestTimers* timers = nullptr, uint64_t timeout_us = 0);
  Error EnsureConnected();
  void CloseSocket();
  void UpdateStat(const RequestTimers& timers);
  Error GetJson(const std::string& uri, json::ValuePtr* out);
  Error PostJson(
      const std::string& uri, const std::string& body,
      json::ValuePtr* out = nullptr);

  static Error EnableTls(
      std::unique_ptr<InferenceServerHttpClient>* client,
      const HttpSslOptions& ssl_options);
  // raw send/recv over fd_ (plain TCP) or transport_ (TLS)
  ssize_t IoSend(const void* buf, size_t len);
  ssize_t IoRecv(void* buf, size_t len);
  bool Connected() const;

  std::string host_;
  int port_ = 0;
  int fd_ = -1;
  bool verbose_ = false;
  bool tls_enabled_ = false;
  TlsConfig tls_config_;
  std::unique_ptr<ByteTransport> transport_;  // TLS connections only
  std::mutex reactor_mu_;
  std::unique_ptr<HttpReactor> reactor_;  // created on first AsyncInfer

  std::mutex stat_mu_;
  InferStat stat_;
};

}  // namespace ctpu
