#include "transport.h"

#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <mutex>

#ifdef CLIENT_TPU_ENABLE_TLS
#include <openssl/err.h>
#include <openssl/ssl.h>
#endif

namespace ctpu {

namespace {

class TcpTransport : public ByteTransport {
 public:
  ~TcpTransport() override { Close(); }

  Error Connect(
      const std::string& host, int port, int64_t timeout_ms) override
  {
    struct addrinfo hints;
    memset(&hints, 0, sizeof(hints));
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    struct addrinfo* res = nullptr;
    const std::string port_s = std::to_string(port);
    if (getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) != 0 ||
        res == nullptr) {
      return Error("failed to resolve host '" + host + "'");
    }
    int fd = -1;
    for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
      fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
      if (fd < 0) continue;
      const int fl = fcntl(fd, F_GETFL, 0);
      fcntl(fd, F_SETFL, fl | O_NONBLOCK);
      int rc = connect(fd, ai->ai_addr, ai->ai_addrlen);
      if (rc != 0 && errno == EINPROGRESS) {
        struct pollfd pfd = {fd, POLLOUT, 0};
        rc = poll(&pfd, 1, static_cast<int>(timeout_ms));
        int soerr = 0;
        socklen_t slen = sizeof(soerr);
        if (rc == 1 &&
            getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &slen) == 0 &&
            soerr == 0) {
          rc = 0;
        } else {
          rc = -1;
        }
      }
      if (rc == 0) {
        fcntl(fd, F_SETFL, fl);  // back to blocking
        break;
      }
      close(fd);
      fd = -1;
    }
    freeaddrinfo(res);
    if (fd < 0) {
      return Error("failed to connect to '" + host + ":" + port_s + "'");
    }
    const int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    fd_ = fd;
    return Error::Success();
  }

  ssize_t Read(void* buf, size_t len) override
  {
    while (true) {
      const ssize_t n = recv(fd_, buf, len, 0);
      if (n < 0 && errno == EINTR) continue;
      return n;
    }
  }

  ssize_t Write(const void* buf, size_t len) override
  {
    while (true) {
      const ssize_t n = send(fd_, buf, len, MSG_NOSIGNAL);
      if (n < 0 && errno == EINTR) continue;
      return n;
    }
  }

  void SetIoTimeout(int64_t timeout_us) override
  {
    if (fd_ < 0) return;
    struct timeval tv;
    tv.tv_sec = static_cast<time_t>(timeout_us / 1000000);
    tv.tv_usec = static_cast<suseconds_t>(timeout_us % 1000000);
    // zero timeval = wait forever (the SO_RCVTIMEO contract)
    setsockopt(fd_, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(fd_, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  }

  void Shutdown() override
  {
    if (fd_ >= 0) shutdown(fd_, SHUT_RDWR);
  }

  void Close() override
  {
    if (fd_ >= 0) {
      close(fd_);
      fd_ = -1;
    }
  }

  int fd() const { return fd_; }

 private:
  int fd_ = -1;
};

std::mutex g_factory_mu;
TlsTransportFactory g_tls_factory;

#ifdef CLIENT_TPU_ENABLE_TLS
// Built-in OpenSSL transport: a TLS session over a TcpTransport-owned
// socket.  Compiled only on OpenSSL-equipped toolchains — this image ships
// no OpenSSL headers, so the path is validated there, not here.
class OpenSslTransport : public ByteTransport {
 public:
  explicit OpenSslTransport(const TlsConfig& config) : config_(config) {}

  ~OpenSslTransport() override
  {
    Close();
    if (ssl_ != nullptr) SSL_free(ssl_);
    if (ctx_ != nullptr) SSL_CTX_free(ctx_);
  }

  Error Connect(
      const std::string& host, int port, int64_t timeout_ms) override
  {
    Error err = tcp_.Connect(host, port, timeout_ms);
    if (!err.IsOk()) return err;
    SSL_library_init();
    ctx_ = SSL_CTX_new(TLS_client_method());
    if (ctx_ == nullptr) return Error("SSL_CTX_new failed");
    if (!config_.root_certificates.empty()) {
      if (SSL_CTX_load_verify_locations(
              ctx_, config_.root_certificates.c_str(), nullptr) != 1)
        return Error("failed to load root certificates");
    } else {
      SSL_CTX_set_default_verify_paths(ctx_);
    }
    if (!config_.certificate_chain.empty() &&
        SSL_CTX_use_certificate_chain_file(
            ctx_, config_.certificate_chain.c_str()) != 1)
      return Error("failed to load certificate chain");
    if (!config_.private_key.empty() &&
        SSL_CTX_use_PrivateKey_file(
            ctx_, config_.private_key.c_str(), SSL_FILETYPE_PEM) != 1)
      return Error("failed to load private key");
    SSL_CTX_set_verify(
        ctx_,
        config_.insecure_skip_verify ? SSL_VERIFY_NONE : SSL_VERIFY_PEER,
        nullptr);
    ssl_ = SSL_new(ctx_);
    if (ssl_ == nullptr) return Error("SSL_new failed");
    const std::string sni =
        config_.server_name.empty() ? host : config_.server_name;
    SSL_set_tlsext_host_name(ssl_, sni.c_str());
    if (!config_.insecure_skip_verify &&
        SSL_set1_host(ssl_, sni.c_str()) != 1) {
      return Error("failed to pin TLS verification hostname");
    }
    // bound the handshake too: the TCP connect timeout only covers connect()
    if (timeout_ms > 0) {
      struct timeval tv;
      tv.tv_sec = static_cast<time_t>(timeout_ms / 1000);
      tv.tv_usec = static_cast<suseconds_t>((timeout_ms % 1000) * 1000);
      setsockopt(tcp_.fd(), SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
      setsockopt(tcp_.fd(), SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    }
    SSL_set_fd(ssl_, tcp_.fd());
    const int hs = SSL_connect(ssl_);
    if (timeout_ms > 0) {
      struct timeval tv;
      tv.tv_sec = 0;
      tv.tv_usec = 0;
      setsockopt(tcp_.fd(), SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
      setsockopt(tcp_.fd(), SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    }
    if (hs != 1) {
      return Error(
          "TLS handshake with '" + host + "' failed: " +
          std::string(ERR_error_string(ERR_get_error(), nullptr)));
    }
    return Error::Success();
  }

  ssize_t Read(void* buf, size_t len) override
  {
    const int n = SSL_read(ssl_, buf, static_cast<int>(len));
    if (n > 0) return n;
    const int e = SSL_get_error(ssl_, n);
    return e == SSL_ERROR_ZERO_RETURN ? 0 : -1;
  }

  ssize_t Write(const void* buf, size_t len) override
  {
    const int n = SSL_write(ssl_, buf, static_cast<int>(len));
    return n > 0 ? n : -1;
  }

  void SetIoTimeout(int64_t timeout_us) override
  {
    // the deadline lives on the underlying socket: a timed-out SSL_read
    // fails with SSL_ERROR_SYSCALL and errno EAGAIN intact, which Read
    // returns as -1 — exactly the plain-TCP timeout shape
    tcp_.SetIoTimeout(timeout_us);
  }

  void Shutdown() override { tcp_.Shutdown(); }
  void Close() override { tcp_.Close(); }

 private:
  TlsConfig config_;
  TcpTransport tcp_;
  SSL_CTX* ctx_ = nullptr;
  SSL* ssl_ = nullptr;
};
#endif  // CLIENT_TPU_ENABLE_TLS

}  // namespace

std::unique_ptr<ByteTransport>
MakeTcpTransport()
{
  return std::make_unique<TcpTransport>();
}

void
SetTlsTransportFactory(TlsTransportFactory factory)
{
  std::lock_guard<std::mutex> lk(g_factory_mu);
  g_tls_factory = std::move(factory);
}

Error
MakeTlsTransport(const TlsConfig& config, std::unique_ptr<ByteTransport>* out)
{
  {
    std::lock_guard<std::mutex> lk(g_factory_mu);
    if (g_tls_factory) {
      *out = g_tls_factory(config);
      if (*out != nullptr) return Error::Success();
      return Error("registered TLS transport factory returned null");
    }
  }
#ifdef CLIENT_TPU_ENABLE_TLS
  *out = std::make_unique<OpenSslTransport>(config);
  return Error::Success();
#else
  return Error(
      "TLS support is not compiled in: this toolchain ships no OpenSSL "
      "headers; rebuild with -DCLIENT_TPU_ENABLE_TLS against an "
      "OpenSSL-equipped toolchain, register a transport with "
      "SetTlsTransportFactory, or terminate TLS in a local proxy");
#endif
}

}  // namespace ctpu
