// Byte-transport seam for the native clients (reference http_client.h:46-87
// HttpSslOptions / grpc_client.cc:119-129 SSL credentials).
//
// The clients speak to the wire through ByteTransport, so the TLS question
// becomes "which transport?":
//  - MakeTcpTransport(): the default plain-TCP transport (always built).
//  - SetTlsTransportFactory(): the INJECTABLE seam — tests and deployments
//    register a factory producing a TLS-wrapping transport (e.g. around a
//    local TLS-terminating proxy, a vendored TLS library, or a corporate
//    mTLS stack) without rebuilding this library.
//  - CLIENT_TPU_ENABLE_TLS: an OpenSSL-backed transport compiled in when
//    the toolchain has OpenSSL headers (this image's does not; the code
//    path is exercised on OpenSSL-equipped rebuilds).
// MakeTlsTransport resolves in that order: registered factory, then the
// built-in OpenSSL transport, then a descriptive error.
#pragma once

#include <functional>
#include <memory>
#include <string>

#include "common.h"

namespace ctpu {

// TLS parameters (superset of GrpcSslOptions/HttpSslOptions fields).
struct TlsConfig {
  std::string root_certificates;   // PEM path ("" = system default)
  std::string private_key;         // PEM path (mTLS)
  std::string certificate_chain;   // PEM path (mTLS)
  bool insecure_skip_verify = false;
  std::string server_name;         // SNI/verification override ("" = host)
};

class ByteTransport {
 public:
  virtual ~ByteTransport() = default;
  // Establish the connection (TCP connect + any handshake).
  virtual Error Connect(
      const std::string& host, int port, int64_t timeout_ms) = 0;
  // Blocking read; >0 bytes, 0 on orderly EOF, -1 on error (EINTR retried
  // internally).
  virtual ssize_t Read(void* buf, size_t len) = 0;
  // Blocking write of up to len bytes; -1 on error.
  virtual ssize_t Write(const void* buf, size_t len) = 0;
  // Bound every subsequent Read/Write to timeout_us (0 = wait forever).
  // A timed-out op returns -1 with errno EAGAIN/EWOULDBLOCK, like a plain
  // socket under SO_RCVTIMEO — this is how client_timeout_us reaches TLS
  // connections (a peer that accepts then stalls must not hang Infer()
  // forever).  Default no-op: a factory-registered transport that cannot
  // enforce deadlines degrades to the old between-ops granularity.
  virtual void SetIoTimeout(int64_t timeout_us) { (void)timeout_us; }
  // Wake any blocked Read/Write (both directions); idempotent.
  virtual void Shutdown() = 0;
  virtual void Close() = 0;
};

std::unique_ptr<ByteTransport> MakeTcpTransport();

using TlsTransportFactory =
    std::function<std::unique_ptr<ByteTransport>(const TlsConfig&)>;

// Register (or clear, with nullptr) the process-wide TLS transport factory.
void SetTlsTransportFactory(TlsTransportFactory factory);

// TLS transport: registered factory > built-in OpenSSL (when compiled with
// CLIENT_TPU_ENABLE_TLS) > error explaining how to get one.
Error MakeTlsTransport(
    const TlsConfig& config, std::unique_ptr<ByteTransport>* out);

}  // namespace ctpu
