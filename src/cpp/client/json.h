// Minimal JSON layer for the native client: a string-building writer and a
// recursive-descent parser.  The image ships no rapidjson (the reference's
// JSON dep — reference src/c++/library/json_utils.h), so the client carries
// its own ~300-line implementation; KServe-v2 bodies are small and simple.
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <string>
#include <vector>

namespace ctpu {
namespace json {

class Value;
using ValuePtr = std::shared_ptr<Value>;

enum class Type { Null, Bool, Int, Double, String, Array, Object };

class Value {
 public:
  Type type = Type::Null;
  bool b = false;
  int64_t i = 0;
  double d = 0.0;
  std::string s;
  std::vector<ValuePtr> arr;
  std::map<std::string, ValuePtr> obj;

  bool IsNull() const { return type == Type::Null; }
  bool AsBool() const { return type == Type::Bool ? b : false; }
  int64_t AsInt() const
  {
    if (type == Type::Int) return i;
    if (type == Type::Double) return static_cast<int64_t>(d);
    if (type == Type::String) return std::stoll(s);
    return 0;
  }
  double AsDouble() const
  {
    if (type == Type::Double) return d;
    if (type == Type::Int) return static_cast<double>(i);
    return 0.0;
  }
  const std::string& AsString() const { return s; }
  const Value* Get(const std::string& key) const
  {
    auto it = obj.find(key);
    return it == obj.end() ? nullptr : it->second.get();
  }
  bool Has(const std::string& key) const { return obj.count(key) != 0; }
};

// Parse `text`; returns nullptr and sets `err` on failure.
ValuePtr Parse(const std::string& text, std::string* err);

// Escape and quote a string literal.
std::string Quote(const std::string& s);

// Incremental writer for request bodies.
class Writer {
 public:
  void BeginObject() { Sep(); buf_ += '{'; stack_.push_back(kFirst); }
  void EndObject() { buf_ += '}'; Pop(); }
  void BeginArray() { Sep(); buf_ += '['; stack_.push_back(kFirst); }
  void EndArray() { buf_ += ']'; Pop(); }
  void Key(const std::string& k)
  {
    Sep();
    buf_ += Quote(k);
    buf_ += ':';
    pending_value_ = true;
  }
  void String(const std::string& v) { Sep(); buf_ += Quote(v); }
  void Int(int64_t v) { Sep(); buf_ += std::to_string(v); }
  void Double(double v);
  void Bool(bool v) { Sep(); buf_ += v ? "true" : "false"; }
  void Raw(const std::string& v) { Sep(); buf_ += v; }
  const std::string& str() const { return buf_; }

 private:
  static constexpr int kFirst = 0, kNext = 1;
  void Sep()
  {
    if (pending_value_) {
      pending_value_ = false;
      return;
    }
    if (!stack_.empty()) {
      if (stack_.back() == kNext) buf_ += ',';
      stack_.back() = kNext;
    }
  }
  void Pop()
  {
    if (!stack_.empty()) stack_.pop_back();
    if (!stack_.empty()) stack_.back() = kNext;
  }
  std::string buf_;
  std::vector<int> stack_;
  bool pending_value_ = false;
};

}  // namespace json
}  // namespace ctpu
