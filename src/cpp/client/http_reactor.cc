#include "http_reactor.h"

#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cctype>
#include <cerrno>
#include <cstdlib>
#include <chrono>
#include <cstring>

namespace ctpu {

namespace {

uint64_t
NowNs()
{
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

std::string
Lower(std::string s)
{
  for (char& c : s) c = static_cast<char>(std::tolower(c));
  return s;
}

}  // namespace

HttpReactor::HttpReactor(
    const std::string& host, int port, size_t max_connections)
    : host_(host), port_(port), max_connections_(max_connections)
{
}

HttpReactor::~HttpReactor()
{
  {
    std::lock_guard<std::mutex> lk(mu_);
    shutdown_ = true;
  }
  if (wake_fd_ >= 0) {
    uint64_t one = 1;
    ssize_t n = write(wake_fd_, &one, sizeof(one));
    (void)n;
  }
  if (thread_.joinable()) thread_.join();
  for (auto& kv : conns_) {
    if (kv.second->active != nullptr) {
      kv.second->active->callback(
          HttpResponse(), Error("reactor shut down"));
    }
    close(kv.second->fd);
  }
  conns_.clear();
  // fail anything never assigned
  std::deque<std::unique_ptr<Request>> leftover;
  {
    std::lock_guard<std::mutex> lk(mu_);
    leftover.swap(pending_);
  }
  for (auto& r : leftover) {
    r->callback(HttpResponse(), Error("reactor shut down"));
  }
  if (epoll_fd_ >= 0) close(epoll_fd_);
  if (wake_fd_ >= 0) close(wake_fd_);
}

Error
HttpReactor::Start()
{
  epoll_fd_ = epoll_create1(EPOLL_CLOEXEC);
  if (epoll_fd_ < 0) return Error("epoll_create1 failed");
  wake_fd_ = eventfd(0, EFD_CLOEXEC | EFD_NONBLOCK);
  if (wake_fd_ < 0) return Error("eventfd failed");
  struct epoll_event ev = {};
  ev.events = EPOLLIN;
  ev.data.fd = wake_fd_;
  epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, wake_fd_, &ev);
  running_ = true;
  thread_ = std::thread(&HttpReactor::Loop, this);
  return Error::Success();
}

void
HttpReactor::Submit(std::string request, Callback callback, uint64_t deadline)
{
  auto req = std::unique_ptr<Request>(new Request{
      std::move(request), std::move(callback), deadline});
  {
    std::lock_guard<std::mutex> lk(mu_);
    pending_.push_back(std::move(req));
  }
  uint64_t one = 1;
  ssize_t n = write(wake_fd_, &one, sizeof(one));
  (void)n;
}

void
HttpReactor::Loop()
{
  struct epoll_event events[64];
  while (true) {
    const int n = epoll_wait(epoll_fd_, events, 64, 50 /* ms */);
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (shutdown_) return;
    }
    for (int i = 0; i < n; ++i) {
      if (events[i].data.fd == wake_fd_) {
        uint64_t drain;
        while (read(wake_fd_, &drain, sizeof(drain)) > 0) {
        }
        continue;
      }
      auto it = conns_.find(events[i].data.fd);
      if (it == conns_.end()) continue;
      Conn* conn = it->second.get();
      if (events[i].events & (EPOLLERR | EPOLLHUP)) {
        FailConn(conn, "connection error");
        continue;
      }
      if (events[i].events & EPOLLOUT) HandleWritable(conn);
      // FailConn inside HandleWritable may have erased the conn
      it = conns_.find(events[i].data.fd);
      if (it == conns_.end()) continue;
      if (events[i].events & EPOLLIN) HandleReadable(conn);
    }
    DrainSubmissions();
    CheckDeadlines();
  }
}

void
HttpReactor::DrainSubmissions()
{
  // hand queued requests to idle connections, then open new ones up to cap.
  // Iterate over an fd snapshot: AssignRequest can fail the write and erase
  // the connection from conns_ mid-walk.
  std::vector<int> fds;
  fds.reserve(conns_.size());
  for (const auto& kv : conns_) fds.push_back(kv.first);
  for (const int fd : fds) {
    auto it = conns_.find(fd);
    if (it == conns_.end() || it->second->state != Conn::IDLE) continue;
    if (!AssignRequest(it->second.get())) return;
  }
  size_t queued;
  {
    std::lock_guard<std::mutex> lk(mu_);
    queued = pending_.size();
  }
  // Connections still connecting (or idle) will serve the queue when ready:
  // they count against demand, or a single slow connect would spawn a new
  // socket every loop tick for the same request.
  size_t available = 0;
  for (const auto& kv : conns_) {
    if (kv.second->state == Conn::CONNECTING ||
        kv.second->state == Conn::IDLE) {
      ++available;
    }
  }
  while (queued > available && conns_.size() < max_connections_) {
    StartConnection();
    ++available;
  }
}

bool
HttpReactor::AssignRequest(Conn* conn)
{
  std::unique_ptr<Request> req;
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (pending_.empty()) return false;
    req = std::move(pending_.front());
    pending_.pop_front();
  }
  conn->active = std::move(req);
  conn->out = conn->active->bytes;
  conn->out_off = 0;
  conn->in.clear();
  conn->header_end = std::string::npos;
  conn->content_length = std::string::npos;
  conn->response = HttpResponse();
  conn->state = Conn::WRITING;
  struct epoll_event ev = {};
  ev.events = EPOLLOUT | EPOLLIN;
  ev.data.fd = conn->fd;
  epoll_ctl(epoll_fd_, EPOLL_CTL_MOD, conn->fd, &ev);
  HandleWritable(conn);
  return true;
}

void
HttpReactor::StartConnection()
{
  if (!resolved_) {
    struct addrinfo hints = {};
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    struct addrinfo* res = nullptr;
    const std::string port = std::to_string(port_);
    if (getaddrinfo(host_.c_str(), port.c_str(), &hints, &res) != 0 ||
        res == nullptr) {
      // fail one pending request so the queue cannot stall silently
      std::unique_ptr<Request> req;
      {
        std::lock_guard<std::mutex> lk(mu_);
        if (!pending_.empty()) {
          req = std::move(pending_.front());
          pending_.pop_front();
        }
      }
      if (req != nullptr)
        req->callback(HttpResponse(), Error("failed to resolve " + host_));
      return;
    }
    for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
      Addr a;
      a.family = ai->ai_family;
      a.socktype = ai->ai_socktype;
      a.protocol = ai->ai_protocol;
      std::memcpy(&a.addr, ai->ai_addr, ai->ai_addrlen);
      a.addrlen = ai->ai_addrlen;
      addrs_.push_back(a);
    }
    freeaddrinfo(res);
    resolved_ = true;
  }
  int fd = -1;
  for (const Addr& a : addrs_) {
    fd = socket(a.family, a.socktype | SOCK_NONBLOCK | SOCK_CLOEXEC,
                a.protocol);
    if (fd < 0) continue;
    const int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    if (connect(fd, reinterpret_cast<const struct sockaddr*>(&a.addr),
                a.addrlen) == 0 ||
        errno == EINPROGRESS) {
      break;
    }
    close(fd);
    fd = -1;
  }
  if (fd < 0) return;
  auto conn = std::unique_ptr<Conn>(new Conn());
  conn->fd = fd;
  conn->state = Conn::CONNECTING;
  struct epoll_event ev = {};
  ev.events = EPOLLOUT;
  ev.data.fd = fd;
  epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, fd, &ev);
  conns_[fd] = std::move(conn);
}

void
HttpReactor::HandleWritable(Conn* conn)
{
  if (conn->state == Conn::CONNECTING) {
    int soerr = 0;
    socklen_t slen = sizeof(soerr);
    if (getsockopt(conn->fd, SOL_SOCKET, SO_ERROR, &soerr, &slen) != 0 ||
        soerr != 0) {
      FailConn(conn, "connect failed");
      return;
    }
    conn->state = Conn::IDLE;
    if (!AssignRequest(conn)) {
      struct epoll_event ev = {};
      ev.events = EPOLLIN;  // watch for server-side close while idle
      ev.data.fd = conn->fd;
      epoll_ctl(epoll_fd_, EPOLL_CTL_MOD, conn->fd, &ev);
    }
    return;
  }
  if (conn->state != Conn::WRITING) return;
  while (conn->out_off < conn->out.size()) {
    const ssize_t n =
        send(conn->fd, conn->out.data() + conn->out_off,
             conn->out.size() - conn->out_off, MSG_NOSIGNAL);
    if (n > 0) {
      conn->out_off += static_cast<size_t>(n);
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return;
    FailConn(conn, "request write failed");
    return;
  }
  conn->state = Conn::READING;
  struct epoll_event ev = {};
  ev.events = EPOLLIN;
  ev.data.fd = conn->fd;
  epoll_ctl(epoll_fd_, EPOLL_CTL_MOD, conn->fd, &ev);
}

void
HttpReactor::HandleReadable(Conn* conn)
{
  if (conn->state == Conn::IDLE) {
    // Data or EOF on an idle keep-alive connection: either way it is
    // unusable (a server pushing bytes outside a request desynced it).
    // Consuming nothing would leave the level-triggered EPOLLIN firing
    // every tick — a busy-spin — so always close.
    CloseConn(conn);
    return;
  }
  if (conn->state != Conn::READING && conn->state != Conn::WRITING) return;
  char chunk[16384];
  while (true) {
    const ssize_t n = recv(conn->fd, chunk, sizeof(chunk), 0);
    if (n > 0) {
      conn->in.append(chunk, static_cast<size_t>(n));
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
    FailConn(conn, n == 0 ? "connection closed mid-response"
                          : "response read failed");
    return;
  }
  if (conn->header_end == std::string::npos) {
    conn->header_end = conn->in.find("\r\n\r\n");
    if (conn->header_end == std::string::npos) return;
    // parse status line + headers
    const std::string head = conn->in.substr(0, conn->header_end);
    size_t line_end = head.find("\r\n");
    const std::string status_line =
        head.substr(0, line_end == std::string::npos ? head.size() : line_end);
    const size_t sp = status_line.find(' ');
    if (sp != std::string::npos)
      conn->response.status = std::atoi(status_line.c_str() + sp + 1);
    size_t pos = line_end == std::string::npos ? head.size() : line_end + 2;
    while (pos < head.size()) {
      size_t eol = head.find("\r\n", pos);
      if (eol == std::string::npos) eol = head.size();
      const std::string line = head.substr(pos, eol - pos);
      const size_t colon = line.find(':');
      if (colon != std::string::npos) {
        std::string key = Lower(line.substr(0, colon));
        size_t vstart = colon + 1;
        while (vstart < line.size() && line[vstart] == ' ') ++vstart;
        conn->response.headers[key] = line.substr(vstart);
      }
      pos = eol + 2;
    }
    const auto cl = conn->response.headers.find("content-length");
    if (cl != conn->response.headers.end()) {
      char* end = nullptr;
      errno = 0;
      const unsigned long long v =
          std::strtoull(cl->second.c_str(), &end, 10);
      // reject non-numeric, trailing junk, and absurd sizes (also guards
      // the body_start + content_length overflow below)
      if (end == cl->second.c_str() || (end != nullptr && *end != '\0') ||
          errno == ERANGE || v > (1ull << 40)) {
        FailConn(conn, "malformed Content-Length: " + cl->second);
        return;
      }
      conn->content_length = static_cast<size_t>(v);
    } else {
      conn->content_length = 0;  // KServe responses always carry a length
    }
  }
  const size_t body_start = conn->header_end + 4;
  if (conn->in.size() >= body_start &&
      conn->in.size() - body_start >= conn->content_length) {
    conn->response.body =
        conn->in.substr(body_start, conn->content_length);
    FinishResponse(conn);
  }
}

void
HttpReactor::FinishResponse(Conn* conn)
{
  std::unique_ptr<Request> done = std::move(conn->active);
  HttpResponse response = std::move(conn->response);
  if (conn->out_off < conn->out.size()) {
    // Early response (e.g. 400/413) while our body was still in flight:
    // the stream is desynced — the server still expects the old body's
    // tail — so this connection must not be reused.
    CloseConn(conn);
  } else {
    conn->ever_used = true;
    conn->state = Conn::IDLE;
    if (!AssignRequest(conn)) {
      struct epoll_event ev = {};
      ev.events = EPOLLIN;
      ev.data.fd = conn->fd;
      epoll_ctl(epoll_fd_, EPOLL_CTL_MOD, conn->fd, &ev);
    }
  }
  done->callback(std::move(response), Error::Success());
}

void
HttpReactor::FailConn(Conn* conn, const std::string& msg)
{
  std::unique_ptr<Request> active = std::move(conn->active);
  const bool connecting = (conn->state == Conn::CONNECTING);
  const bool retryable =
      conn->ever_used && active != nullptr && conn->in.empty();
  if (active != nullptr) {
    if (retryable) {
      // stale keep-alive closed before reading our request: it cannot have
      // executed — requeue at the front (same rule as the sync client)
      std::lock_guard<std::mutex> lk(mu_);
      pending_.push_front(std::move(active));
    } else {
      active->callback(HttpResponse(), Error(msg));
    }
  } else if (connecting) {
    // A failed connect must surface: fail one queued request per doomed
    // connection, otherwise an unreachable server leaves every AsyncInfer
    // callback pending forever while the loop retries connects.
    std::unique_ptr<Request> victim;
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (!pending_.empty()) {
        victim = std::move(pending_.front());
        pending_.pop_front();
      }
    }
    if (victim != nullptr) {
      victim->callback(
          HttpResponse(),
          Error("failed to connect to " + host_ + ":" +
                std::to_string(port_)));
    }
  }
  CloseConn(conn);
}

void
HttpReactor::CloseConn(Conn* conn)
{
  const int fd = conn->fd;
  epoll_ctl(epoll_fd_, EPOLL_CTL_DEL, fd, nullptr);
  close(fd);
  conns_.erase(fd);
}

void
HttpReactor::CheckDeadlines()
{
  const uint64_t now = NowNs();
  std::vector<Conn*> expired;
  for (auto& kv : conns_) {
    Conn* conn = kv.second.get();
    if (conn->active != nullptr && conn->active->deadline_ns != 0 &&
        now > conn->active->deadline_ns) {
      expired.push_back(conn);
    }
  }
  for (Conn* conn : expired) {
    std::unique_ptr<Request> active = std::move(conn->active);
    active->callback(HttpResponse(), Error("request timed out"));
    CloseConn(conn);  // mid-request connection state is unusable
  }
  // expired requests still queued
  std::vector<std::unique_ptr<Request>> timed_out;
  {
    std::lock_guard<std::mutex> lk(mu_);
    for (auto it = pending_.begin(); it != pending_.end();) {
      if ((*it)->deadline_ns != 0 && now > (*it)->deadline_ns) {
        timed_out.push_back(std::move(*it));
        it = pending_.erase(it);
      } else {
        ++it;
      }
    }
  }
  for (auto& r : timed_out)
    r->callback(HttpResponse(), Error("request timed out in queue"));
}

}  // namespace ctpu
