// Event-loop HTTP/1.1 client engine for AsyncInfer — the native analog of
// the reference's curl-multi reactor (reference
// src/c++/library/http_client.cc:1882-1956 AsyncTransfer): one thread, an
// epoll set of non-blocking keep-alive connections, hundreds of in-flight
// requests with no thread-per-request.
#pragma once

#include <sys/socket.h>

#include <cstdint>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "common.h"
#include "http_client.h"  // HttpResponse

namespace ctpu {

class HttpReactor {
 public:
  // Callback runs on the reactor thread — do not block in it.
  using Callback = std::function<void(HttpResponse, Error)>;

  HttpReactor(
      const std::string& host, int port, size_t max_connections = 64);
  ~HttpReactor();
  HttpReactor(const HttpReactor&) = delete;
  HttpReactor& operator=(const HttpReactor&) = delete;

  Error Start();
  // Queue one fully-framed HTTP/1.1 request (must carry Content-Length and
  // Connection: keep-alive).  deadline: monotonic ns, 0 = none.
  void Submit(std::string request, Callback callback, uint64_t deadline_ns = 0);

 private:
  struct Request {
    std::string bytes;
    Callback callback;
    uint64_t deadline_ns;
  };
  struct Conn {
    int fd = -1;
    enum State { CONNECTING, WRITING, READING, IDLE } state = CONNECTING;
    std::string out;
    size_t out_off = 0;
    std::string in;
    size_t header_end = std::string::npos;
    size_t content_length = std::string::npos;
    HttpResponse response;
    std::unique_ptr<Request> active;
    bool ever_used = false;  // reused keep-alive vs fresh connection
  };

  void Loop();
  void DrainSubmissions();
  bool AssignRequest(Conn* conn);  // pop queue -> start writing; false if empty
  void StartConnection();
  void HandleWritable(Conn* conn);
  void HandleReadable(Conn* conn);
  void FailConn(Conn* conn, const std::string& msg);
  void FinishResponse(Conn* conn);
  void CloseConn(Conn* conn);
  void CheckDeadlines();

  std::string host_;
  int port_;
  size_t max_connections_;
  int epoll_fd_ = -1;
  int wake_fd_ = -1;  // eventfd: submissions + shutdown
  std::thread thread_;
  bool running_ = false;

  std::mutex mu_;  // guards pending_ (+running_ flag flips)
  std::deque<std::unique_ptr<Request>> pending_;
  bool shutdown_ = false;

  std::map<int, std::unique_ptr<Conn>> conns_;  // by fd

  // The target is fixed for the reactor's lifetime: resolve once (lazily,
  // on the loop thread) and reuse — a slow resolver must not stall every
  // in-flight request on each new connection.
  struct Addr {
    int family, socktype, protocol;
    struct sockaddr_storage addr;
    socklen_t addrlen;
  };
  std::vector<Addr> addrs_;
  bool resolved_ = false;
};

}  // namespace ctpu
