// Client-timeout behavior across both native clients — the analog of
// reference src/c++/tests/client_timeout_test.cc: a microscopic
// client_timeout on a deliberately slow model must surface a clean timeout
// error (sync AND async paths), a generous timeout must succeed, and the
// client must remain fully usable afterwards.
//   client_timeout_test <http_host:port> <grpc_host:port>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <iostream>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

#include "grpc_client.h"
#include "http_client.h"
#include "transport.h"

namespace tc = ctpu;

static int g_failures = 0;
static int g_checks = 0;

#define CHECK(cond)                                                         \
  do {                                                                      \
    g_checks++;                                                             \
    if (!(cond)) {                                                          \
      g_failures++;                                                         \
      std::cerr << "FAIL " << __FILE__ << ":" << __LINE__ << "  " << #cond  \
                << std::endl;                                               \
    }                                                                       \
  } while (false)

// slow_identity takes ~50ms per request; 5ms must time out, 5s must not.
static constexpr uint64_t kTinyUs = 5 * 1000;
static constexpr uint64_t kAmpleUs = 5 * 1000 * 1000;

static tc::InferInput
MakeInput()
{
  static int32_t value = 42;
  tc::InferInput input("INPUT0", {1}, "INT32");
  input.AppendRaw(reinterpret_cast<const uint8_t*>(&value), sizeof(value));
  return input;
}

static tc::Error
SyncInfer(tc::InferenceServerGrpcClient* client, uint64_t timeout_us)
{
  tc::InferInput input = MakeInput();
  tc::InferRequestedOutput output("OUTPUT0");
  tc::InferOptions options("slow_identity");
  options.client_timeout_us = timeout_us;
  tc::InferResult* result = nullptr;
  tc::Error err = client->Infer(&result, options, {&input}, {&output});
  delete result;
  return err;
}

static tc::Error
SyncInfer(tc::InferenceServerHttpClient* client, uint64_t timeout_us)
{
  tc::InferInput input = MakeInput();
  tc::InferRequestedOutput output("OUTPUT0");
  tc::InferOptions options("slow_identity");
  options.client_timeout_us = timeout_us;
  tc::InferResultPtr result;
  return client->Infer(&result, options, {&input}, {&output});
}

template <typename ClientT>
static void
TestSyncTimeout(ClientT* client)
{
  // tiny deadline: must fail, and promptly (well under the 5s ample bound)
  const auto t0 = std::chrono::steady_clock::now();
  tc::Error err = SyncInfer(client, kTinyUs);
  const auto elapsed = std::chrono::duration_cast<std::chrono::milliseconds>(
      std::chrono::steady_clock::now() - t0);
  CHECK(!err.IsOk());
  CHECK(elapsed.count() < 2000);
  // ample deadline: same client recovers and succeeds
  CHECK(SyncInfer(client, kAmpleUs).IsOk());
  // no deadline at all still succeeds
  CHECK(SyncInfer(client, 0).IsOk());
}

static void
TestGrpcAsyncTimeout(tc::InferenceServerGrpcClient* client)
{
  std::mutex mu;
  std::condition_variable cv;
  bool done = false;
  tc::Error status;
  tc::InferInput input = MakeInput();
  tc::InferRequestedOutput output("OUTPUT0");
  tc::InferOptions options("slow_identity");
  options.client_timeout_us = kTinyUs;
  tc::Error err = client->AsyncInfer(
      [&](tc::InferResultPtr result) {
        std::lock_guard<std::mutex> lk(mu);
        status = result->RequestStatus();
        done = true;
        cv.notify_all();
      },
      options, {&input}, {&output});
  CHECK(err.IsOk());
  std::unique_lock<std::mutex> lk(mu);
  const bool fired =
      cv.wait_for(lk, std::chrono::seconds(30), [&] { return done; });
  CHECK(fired);
  CHECK(!status.IsOk());  // the tiny deadline must surface as an error
  lk.unlock();
  // the client (and its connection) must still serve after the timeout
  CHECK(SyncInfer(client, kAmpleUs).IsOk());
}

// TLS-path stall: a peer that ACCEPTS the connection and then never sends
// a byte must surface client_timeout_us as a prompt error.  Pre-fix this
// hung forever — the whole-exchange budget was only checked BETWEEN ops on
// TLS connections, and transport_->Read had no socket deadline
// (ByteTransport::SetIoTimeout is what closes that hole).  The factory
// transport is plain TCP (same seam the TlsTransportSeam tests use), so
// the test runs on toolchains without OpenSSL.
static void
TestTlsStallTimeout()
{
  int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
  CHECK(lfd >= 0);
  struct sockaddr_in addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = 0;
  CHECK(::bind(lfd, reinterpret_cast<struct sockaddr*>(&addr),
               sizeof(addr)) == 0);
  CHECK(::listen(lfd, 1) == 0);
  socklen_t alen = sizeof(addr);
  CHECK(::getsockname(
            lfd, reinterpret_cast<struct sockaddr*>(&addr), &alen) == 0);
  const int port = ntohs(addr.sin_port);

  std::atomic<bool> stop{false};
  std::thread acceptor([lfd, &stop]() {
    // accept and HOLD the connection open without ever writing a byte
    int cfd = ::accept(lfd, nullptr, nullptr);
    while (!stop.load()) {
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
    }
    if (cfd >= 0) ::close(cfd);
  });

  tc::SetTlsTransportFactory(
      [](const tc::TlsConfig&) { return tc::MakeTcpTransport(); });
  std::unique_ptr<tc::InferenceServerHttpClient> client;
  tc::HttpSslOptions ssl_options;
  tc::Error err = tc::InferenceServerHttpClient::Create(
      &client, "localhost:" + std::to_string(port), ssl_options, false);
  CHECK(err.IsOk());
  const auto t0 = std::chrono::steady_clock::now();
  err = SyncInfer(client.get(), 200 * 1000);  // 200ms budget
  const auto elapsed = std::chrono::duration_cast<std::chrono::milliseconds>(
      std::chrono::steady_clock::now() - t0);
  CHECK(!err.IsOk());
  CHECK(elapsed.count() < 5000);  // pre-fix: blocked in Read forever

  tc::SetTlsTransportFactory(nullptr);
  stop.store(true);
  acceptor.join();
  ::close(lfd);
}

int
main(int argc, char** argv)
{
  const std::string http_url = argc > 1 ? argv[1] : "localhost:8000";
  const std::string grpc_url = argc > 2 ? argv[2] : "localhost:8001";

  std::unique_ptr<tc::InferenceServerHttpClient> http_client;
  if (!tc::InferenceServerHttpClient::Create(&http_client, http_url)
           .IsOk()) {
    std::cerr << "http create failed" << std::endl;
    return 1;
  }
  std::unique_ptr<tc::InferenceServerGrpcClient> grpc_client;
  if (!tc::InferenceServerGrpcClient::Create(&grpc_client, grpc_url)
           .IsOk()) {
    std::cerr << "grpc create failed" << std::endl;
    return 1;
  }

  TestSyncTimeout(http_client.get());
  TestSyncTimeout(grpc_client.get());
  TestGrpcAsyncTimeout(grpc_client.get());
  TestTlsStallTimeout();

  std::cout << g_checks << " checks, " << g_failures << " failures"
            << std::endl;
  if (g_failures == 0) {
    std::cout << "PASS: client_timeout_test" << std::endl;
    return 0;
  }
  return 1;
}
