// Native gRPC client integration suite against a live in-process server —
// the gRPC half of the reference's typed cc_client_test.cc (reference
// src/c++/tests/cc_client_test.cc:1626-1627 instantiates the suite for both
// protocols; here each protocol binary shares the same check list, driven
// together by tests/test_cpp_client.py).
//   cc_grpc_client_test <host:port>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <iostream>
#include <mutex>
#include <thread>
#include <vector>

#include "grpc_client.h"

namespace tc = ctpu;

static int g_failures = 0;
static int g_checks = 0;

#define CHECK(cond)                                                         \
  do {                                                                      \
    g_checks++;                                                             \
    if (!(cond)) {                                                          \
      g_failures++;                                                         \
      std::cerr << "FAIL " << __FILE__ << ":" << __LINE__ << "  " << #cond  \
                << std::endl;                                               \
    }                                                                       \
  } while (false)

#define CHECK_OK(expr)                                                      \
  do {                                                                      \
    g_checks++;                                                             \
    tc::Error e__ = (expr);                                                 \
    if (!e__.IsOk()) {                                                      \
      g_failures++;                                                         \
      std::cerr << "FAIL " << __FILE__ << ":" << __LINE__ << "  " << #expr  \
                << " -> " << e__.Message() << std::endl;                    \
    }                                                                       \
  } while (false)

#define CHECK_ERR(expr)                                                     \
  do {                                                                      \
    g_checks++;                                                             \
    tc::Error e__ = (expr);                                                 \
    if (e__.IsOk()) {                                                       \
      g_failures++;                                                         \
      std::cerr << "FAIL " << __FILE__ << ":" << __LINE__                   \
                << "  expected error from " << #expr << std::endl;          \
    }                                                                       \
  } while (false)

static void
TestHealthAndMetadata(tc::InferenceServerGrpcClient* client)
{
  bool live = false, ready = false, model_ready = false;
  CHECK_OK(client->IsServerLive(&live));
  CHECK(live);
  CHECK_OK(client->IsServerReady(&ready));
  CHECK(ready);
  CHECK_OK(client->IsModelReady(&model_ready, "simple"));
  CHECK(model_ready);
  // missing model: server answers ready=false or NOT_FOUND; both are "not
  // ready", neither may crash the connection
  tc::Error e = client->IsModelReady(&model_ready, "no_such_model");
  CHECK(!e.IsOk() || !model_ready);

  inference::ServerMetadataResponse server_meta;
  CHECK_OK(client->ServerMetadata(&server_meta));
  CHECK(!server_meta.name().empty());

  inference::ModelMetadataResponse model_meta;
  CHECK_OK(client->ModelMetadata(&model_meta, "simple"));
  CHECK(model_meta.name() == "simple");
  CHECK(model_meta.inputs_size() == 2);
  CHECK(model_meta.outputs_size() == 2);

  inference::ModelConfigResponse config;
  CHECK_OK(client->ModelConfig(&config, "simple"));
  CHECK(config.config().name() == "simple");

  inference::RepositoryIndexResponse index;
  CHECK_OK(client->ModelRepositoryIndex(&index));
  bool found = false;
  for (const auto& m : index.models())
    if (m.name() == "simple") found = true;
  CHECK(found);
}

static tc::Error
DoInfer(
    tc::InferenceServerGrpcClient* client, const std::string& model,
    tc::InferResult** result, uint64_t client_timeout_us = 0)
{
  std::vector<int32_t> input0(16), input1(16);
  for (int i = 0; i < 16; ++i) {
    input0[i] = i;
    input1[i] = 2 * i;
  }
  tc::InferInput in0("INPUT0", {1, 16}, "INT32");
  tc::InferInput in1("INPUT1", {1, 16}, "INT32");
  in0.AppendRaw(
      reinterpret_cast<const uint8_t*>(input0.data()), 16 * sizeof(int32_t));
  in1.AppendRaw(
      reinterpret_cast<const uint8_t*>(input1.data()), 16 * sizeof(int32_t));
  tc::InferRequestedOutput out0("OUTPUT0"), out1("OUTPUT1");
  tc::InferOptions options(model);
  options.request_id = "42";
  options.client_timeout_us = client_timeout_us;
  return client->Infer(result, options, {&in0, &in1}, {&out0, &out1});
}

static void
TestInfer(tc::InferenceServerGrpcClient* client)
{
  tc::InferResult* result = nullptr;
  CHECK_OK(DoInfer(client, "simple", &result));
  if (result == nullptr) return;
  std::unique_ptr<tc::InferResult> owner(result);
  CHECK(result->Id() == "42");
  const uint8_t* data = nullptr;
  size_t nbytes = 0;
  CHECK_OK(result->RawData("OUTPUT0", &data, &nbytes));
  CHECK(nbytes == 16 * sizeof(int32_t));
  const int32_t* sum = reinterpret_cast<const int32_t*>(data);
  bool ok = true;
  for (int i = 0; i < 16; ++i) ok &= (sum[i] == 3 * i);
  CHECK(ok);
  std::vector<int64_t> shape;
  CHECK_OK(result->Shape("OUTPUT0", &shape));
  CHECK(shape.size() == 2 && shape[1] == 16);
  std::string datatype;
  CHECK_OK(result->Datatype("OUTPUT0", &datatype));
  CHECK(datatype == "INT32");
  CHECK_ERR(result->RawData("NO_SUCH_OUTPUT", &data, &nbytes));
}

static void
TestInferErrors(tc::InferenceServerGrpcClient* client)
{
  tc::InferResult* result = nullptr;
  // unknown model -> grpc-status NOT_FOUND surfaced as Error
  tc::Error e = DoInfer(client, "no_such_model", &result);
  CHECK(!e.IsOk());
  CHECK(e.Message().find("grpc-status") != std::string::npos);

  // wrong shape -> INVALID_ARGUMENT
  tc::InferInput bad("INPUT0", {1, 3}, "INT32");
  std::vector<int32_t> values(3, 7);
  bad.AppendRaw(
      reinterpret_cast<const uint8_t*>(values.data()), 3 * sizeof(int32_t));
  tc::InferOptions options("simple");
  e = client->Infer(&result, options, {&bad});
  CHECK(!e.IsOk());
}

static void
TestAsyncInfer(tc::InferenceServerGrpcClient* client)
{
  // A burst of async requests sharing one connection + reactor thread (the
  // reference's completion-queue model) — hundreds in flight, no
  // thread-per-request.
  const int kRequests = 64;
  std::mutex mu;
  std::condition_variable cv;
  int done = 0, good = 0;
  std::vector<int32_t> input0(16), input1(16);
  for (int i = 0; i < 16; ++i) {
    input0[i] = i;
    input1[i] = i;
  }
  tc::InferInput in0("INPUT0", {1, 16}, "INT32");
  tc::InferInput in1("INPUT1", {1, 16}, "INT32");
  in0.AppendRaw(
      reinterpret_cast<const uint8_t*>(input0.data()), 16 * sizeof(int32_t));
  in1.AppendRaw(
      reinterpret_cast<const uint8_t*>(input1.data()), 16 * sizeof(int32_t));
  tc::InferOptions options("simple");
  for (int r = 0; r < kRequests; ++r) {
    CHECK_OK(client->AsyncInfer(
        [&](tc::InferResultPtr result) {
          std::lock_guard<std::mutex> lk(mu);
          ++done;
          if (result->RequestStatus().IsOk()) {
            const uint8_t* data = nullptr;
            size_t nbytes = 0;
            if (result->RawData("OUTPUT0", &data, &nbytes).IsOk() &&
                nbytes == 16 * sizeof(int32_t) &&
                reinterpret_cast<const int32_t*>(data)[5] == 10) {
              ++good;
            }
          }
          cv.notify_all();
        },
        options, {&in0, &in1}));
  }
  std::unique_lock<std::mutex> lk(mu);
  const bool all = cv.wait_for(
      lk, std::chrono::seconds(60), [&] { return done == kRequests; });
  CHECK(all);
  CHECK(good == kRequests);
}

static void
TestSequenceStream(tc::InferenceServerGrpcClient* client)
{
  std::mutex mu;
  std::condition_variable cv;
  std::vector<int32_t> sums;
  CHECK_OK(client->StartStream([&](tc::InferResultPtr result) {
    std::lock_guard<std::mutex> lk(mu);
    const uint8_t* data = nullptr;
    size_t nbytes = 0;
    if (result->RequestStatus().IsOk() &&
        result->RawData("OUTPUT", &data, &nbytes).IsOk()) {
      sums.push_back(*reinterpret_cast<const int32_t*>(data));
    } else {
      sums.push_back(-1);
    }
    cv.notify_all();
  }));
  for (int step = 0; step < 3; ++step) {
    int32_t value = step + 1;
    tc::InferInput input("INPUT", {1}, "INT32");
    input.AppendRaw(
        reinterpret_cast<const uint8_t*>(&value), sizeof(value));
    tc::InferOptions options("simple_sequence");
    options.sequence_id = 7;
    options.sequence_start = (step == 0);
    options.sequence_end = (step == 2);
    CHECK_OK(client->AsyncStreamInfer(options, {&input}));
  }
  {
    std::unique_lock<std::mutex> lk(mu);
    cv.wait_for(
        lk, std::chrono::seconds(30), [&] { return sums.size() >= 3; });
  }
  CHECK_OK(client->StopStream());
  CHECK(sums.size() == 3);
  if (sums.size() == 3) {
    CHECK(sums[0] == 1 && sums[1] == 3 && sums[2] == 6);
  }
  // a second stream on the same client works after StopStream
  std::atomic<int> n2{0};
  CHECK_OK(client->StartStream([&](tc::InferResultPtr) { ++n2; }));
  CHECK_OK(client->StopStream());
}

static void
TestDecoupledFinalResponse(tc::InferenceServerGrpcClient* client)
{
  // Triton's decoupled completion protocol: with
  // enable_empty_final_response the N content responses (marked
  // IsFinalResponse()==false) are followed by one EMPTY response marked
  // true — the model-agnostic stream terminator.
  std::mutex mu;
  std::condition_variable cv;
  std::vector<int32_t> values;
  bool saw_final = false;
  bool final_had_outputs = false;
  CHECK_OK(client->StartStream([&](tc::InferResultPtr result) {
    std::lock_guard<std::mutex> lk(mu);
    if (result->IsFinalResponse()) {
      saw_final = true;
      final_had_outputs = !result->Outputs().empty();
    } else {
      const uint8_t* data = nullptr;
      size_t nbytes = 0;
      if (result->RequestStatus().IsOk() &&
          result->RawData("OUT", &data, &nbytes).IsOk()) {
        values.push_back(*reinterpret_cast<const int32_t*>(data));
      }
    }
    cv.notify_all();
  }));
  int32_t n = 4;
  tc::InferInput input("IN", {1}, "INT32");
  input.AppendRaw(reinterpret_cast<const uint8_t*>(&n), sizeof(n));
  tc::InferOptions options("repeat_int32");
  options.enable_empty_final_response = true;
  CHECK_OK(client->AsyncStreamInfer(options, {&input}));
  {
    std::unique_lock<std::mutex> lk(mu);
    cv.wait_for(lk, std::chrono::seconds(30), [&] { return saw_final; });
  }
  CHECK_OK(client->StopStream());
  CHECK(saw_final);
  CHECK(!final_had_outputs);
  CHECK(values.size() == 4);
  for (int i = 0; i < static_cast<int>(values.size()); ++i) {
    CHECK(values[i] == i);
  }
}

static void
TestStringSequenceId(tc::InferenceServerGrpcClient* client)
{
  // unary infer over the sequence protocol with a string correlation id
  // (string_param in the request parameters map)
  int32_t values[3] = {10, 20, 30};
  int32_t expected = 0;
  for (int step = 0; step < 3; ++step) {
    expected += values[step];
    tc::InferInput input("INPUT", {1}, "INT32");
    input.AppendRaw(
        reinterpret_cast<const uint8_t*>(&values[step]), sizeof(int32_t));
    tc::InferOptions options("simple_sequence");
    options.sequence_id_str = "grpc-corr-xyz";
    options.sequence_start = (step == 0);
    options.sequence_end = (step == 2);
    tc::InferResult* result = nullptr;
    CHECK_OK(client->Infer(&result, options, {&input}));
    if (result == nullptr) return;
    std::unique_ptr<tc::InferResult> owner(result);
    const uint8_t* buf = nullptr;
    size_t size = 0;
    CHECK_OK(result->RawData("OUTPUT", &buf, &size));
    CHECK(*reinterpret_cast<const int32_t*>(buf) == expected);
  }
}

static void
TestStatistics(tc::InferenceServerGrpcClient* client)
{
  inference::ModelStatisticsResponse stats;
  CHECK_OK(client->ModelInferenceStatistics(&stats, "simple"));
  CHECK(stats.model_stats_size() >= 1);
  bool counted = false;
  for (const auto& ms : stats.model_stats())
    if (ms.name() == "simple" && ms.inference_stats().success().count() > 0)
      counted = true;
  CHECK(counted);

  tc::InferenceServerGrpcClient::InferStat client_stat;
  CHECK_OK(client->ClientInferStat(&client_stat));
  CHECK(client_stat.completed_request_count > 0);
}

static void
TestSharedMemoryVerbs(tc::InferenceServerGrpcClient* client)
{
  // Round-trip the system-shm registry (no actual shm mapping needed for
  // the control-plane verbs: register with a key that exists).
  inference::SystemSharedMemoryStatusResponse status;
  CHECK_OK(client->SystemSharedMemoryStatus(&status));
  // Unregister-all must succeed even when empty.
  CHECK_OK(client->UnregisterSystemSharedMemory());
  inference::TpuSharedMemoryStatusResponse tpu_status;
  CHECK_OK(client->TpuSharedMemoryStatus(&tpu_status));
  CHECK_OK(client->UnregisterTpuSharedMemory());
}

static void
TestTraceAndLogSettings(tc::InferenceServerGrpcClient* client)
{
  // reference grpc_client.h:291-309 — get, update, get-back
  inference::TraceSettingResponse trace;
  CHECK_OK(client->GetTraceSettings(&trace));
  CHECK(trace.settings().count("trace_level") == 1);
  CHECK_OK(client->UpdateTraceSettings(
      &trace, "", {{"trace_level", {"TIMESTAMPS"}}, {"trace_rate", {"500"}}}));
  inference::TraceSettingResponse trace2;
  CHECK_OK(client->GetTraceSettings(&trace2));
  bool rate_ok = trace2.settings().count("trace_rate") == 1 &&
                 trace2.settings().at("trace_rate").value_size() == 1 &&
                 trace2.settings().at("trace_rate").value(0) == "500";
  CHECK(rate_ok);

  inference::LogSettingsResponse log;
  CHECK_OK(client->GetLogSettings(&log));
  CHECK(log.settings().count("log_info") == 1);
  CHECK_OK(client->UpdateLogSettings(
      &log, {{"log_verbose_level", "2"}, {"log_info", "true"}}));
  inference::LogSettingsResponse log2;
  CHECK_OK(client->GetLogSettings(&log2));
  bool level_ok = log2.settings().count("log_verbose_level") == 1 &&
                  log2.settings().at("log_verbose_level").uint32_param() == 2;
  CHECK(level_ok);
}

static void
TestInferMulti(tc::InferenceServerGrpcClient* client)
{
  // reference grpc_client.h:455-494 — N independent requests, one call
  const int kN = 4;
  std::vector<std::vector<int32_t>> data0(kN), data1(kN);
  std::vector<std::unique_ptr<tc::InferInput>> owned;
  std::vector<std::vector<tc::InferInput*>> inputs;
  for (int r = 0; r < kN; ++r) {
    data0[r].assign(16, r);
    data1[r].assign(16, 10 * r);
    auto in0 = std::make_unique<tc::InferInput>(
        "INPUT0", std::vector<int64_t>{1, 16}, "INT32");
    auto in1 = std::make_unique<tc::InferInput>(
        "INPUT1", std::vector<int64_t>{1, 16}, "INT32");
    in0->AppendRaw(
        reinterpret_cast<const uint8_t*>(data0[r].data()),
        16 * sizeof(int32_t));
    in1->AppendRaw(
        reinterpret_cast<const uint8_t*>(data1[r].data()),
        16 * sizeof(int32_t));
    inputs.push_back({in0.get(), in1.get()});
    owned.push_back(std::move(in0));
    owned.push_back(std::move(in1));
  }
  std::vector<tc::InferOptions> options(1, tc::InferOptions("simple"));
  std::vector<tc::InferResult*> results;
  CHECK_OK(client->InferMulti(&results, options, inputs));
  CHECK(results.size() == kN);
  for (int r = 0; r < static_cast<int>(results.size()); ++r) {
    std::unique_ptr<tc::InferResult> owner(results[r]);
    const uint8_t* buf = nullptr;
    size_t size = 0;
    CHECK_OK(results[r]->RawData("OUTPUT0", &buf, &size));
    CHECK(size == 16 * sizeof(int32_t));
    CHECK(reinterpret_cast<const int32_t*>(buf)[3] == 11 * r);
  }

  // async variant: one callback with all results, request order preserved
  std::mutex mu;
  std::condition_variable cv;
  bool fired = false;
  int good = 0;
  CHECK_OK(client->AsyncInferMulti(
      [&](std::vector<tc::InferResultPtr> multi) {
        std::lock_guard<std::mutex> lk(mu);
        for (int r = 0; r < static_cast<int>(multi.size()); ++r) {
          const uint8_t* buf = nullptr;
          size_t size = 0;
          if (multi[r] && multi[r]->RequestStatus().IsOk() &&
              multi[r]->RawData("OUTPUT0", &buf, &size).IsOk() &&
              reinterpret_cast<const int32_t*>(buf)[0] == 11 * r) {
            ++good;
          }
        }
        fired = true;
        cv.notify_all();
      },
      options, inputs));
  std::unique_lock<std::mutex> lk(mu);
  cv.wait_for(lk, std::chrono::seconds(30), [&] { return fired; });
  CHECK(fired);
  CHECK(good == kN);
}

static void
TestCompression(tc::InferenceServerGrpcClient* client)
{
  // per-call gzip/deflate message compression (reference grpc_client.h:411);
  // the python gRPC server transparently decompresses both encodings
  for (const auto algo :
       {tc::GrpcCompression::GZIP, tc::GrpcCompression::DEFLATE}) {
    tc::InferResult* result = nullptr;
    std::vector<int32_t> input0(16), input1(16);
    for (int i = 0; i < 16; ++i) {
      input0[i] = i;
      input1[i] = i;
    }
    tc::InferInput in0("INPUT0", {1, 16}, "INT32");
    tc::InferInput in1("INPUT1", {1, 16}, "INT32");
    in0.AppendRaw(
        reinterpret_cast<const uint8_t*>(input0.data()),
        16 * sizeof(int32_t));
    in1.AppendRaw(
        reinterpret_cast<const uint8_t*>(input1.data()),
        16 * sizeof(int32_t));
    tc::InferOptions options("simple");
    CHECK_OK(client->Infer(&result, options, {&in0, &in1}, {}, {}, algo));
    if (result == nullptr) continue;
    std::unique_ptr<tc::InferResult> owner(result);
    const uint8_t* buf = nullptr;
    size_t size = 0;
    CHECK_OK(result->RawData("OUTPUT0", &buf, &size));
    CHECK(reinterpret_cast<const int32_t*>(buf)[7] == 14);
  }
}

static void
TestTlsTransportSeam(const std::string& url)
{
  // Without a TLS transport (no OpenSSL in this toolchain, no factory
  // registered), the SSL Create must fail with the descriptive diagnostic.
  tc::GrpcSslOptions ssl;
  std::unique_ptr<tc::InferenceServerGrpcClient> tls_client;
  tc::Error e = tc::InferenceServerGrpcClient::Create(&tls_client, url, ssl);
  CHECK(!e.IsOk());
  CHECK(e.Message().find("TLS") != std::string::npos);

  // Injectable seam: register a transport factory (here a pass-through TCP
  // transport standing in for a TLS library / TLS-terminating proxy hop)
  // and the SAME Create + request path works end to end — proving the ssl
  // option plumbing and the per-connection transport wiring, which is
  // everything an OpenSSL-equipped rebuild adds code to.
  tc::SetTlsTransportFactory(
      [](const tc::TlsConfig&) { return tc::MakeTcpTransport(); });
  e = tc::InferenceServerGrpcClient::Create(&tls_client, url, ssl);
  CHECK_OK(e);
  if (e.IsOk()) {
    tc::InferResult* result = nullptr;
    CHECK_OK(DoInfer(tls_client.get(), "simple", &result));
    delete result;
  }
  tc::SetTlsTransportFactory(nullptr);
}

static void
TestKeepAliveAndChannelCache(const std::string& url)
{
  // keepalive: pings every 200ms must not disturb request traffic
  tc::KeepAliveOptions keepalive;
  keepalive.keepalive_time_ms = 200;
  keepalive.keepalive_timeout_ms = 5000;
  std::unique_ptr<tc::InferenceServerGrpcClient> ka_client;
  CHECK_OK(tc::InferenceServerGrpcClient::Create(
      &ka_client, url, keepalive, /*use_cached_channel=*/false));
  bool live = false;
  CHECK_OK(ka_client->IsServerLive(&live));
  std::this_thread::sleep_for(std::chrono::milliseconds(700));  // >2 pings
  tc::InferResult* result = nullptr;
  CHECK_OK(DoInfer(ka_client.get(), "simple", &result));
  delete result;

  // channel cache: two clients share one connection; destroying the first
  // must not break the second (shared_ptr refcount is the share count)
  std::unique_ptr<tc::InferenceServerGrpcClient> c1, c2;
  CHECK_OK(tc::InferenceServerGrpcClient::Create(
      &c1, url, tc::KeepAliveOptions(), /*use_cached_channel=*/true));
  CHECK_OK(tc::InferenceServerGrpcClient::Create(
      &c2, url, tc::KeepAliveOptions(), /*use_cached_channel=*/true));
  tc::InferResult* r1 = nullptr;
  CHECK_OK(DoInfer(c1.get(), "simple", &r1));
  delete r1;
  c1.reset();  // drops one reference; the shared channel stays open
  tc::InferResult* r2 = nullptr;
  CHECK_OK(DoInfer(c2.get(), "simple", &r2));
  delete r2;
  // channel attach is lazy (first RPC); by now only c2 holds its slot
  CHECK(tc::CachedChannelCountForTesting(url) == 1);
  c2.reset();
  CHECK(tc::CachedChannelCountForTesting(url) == 0);  // last user closed it

  // share-count policy (reference TRITON_CLIENT_GRPC_CHANNEL_MAX_SHARE_COUNT
  // analog): with the cap at 2, three cached clients must spread over two
  // real connections; all stay usable; teardown drains every slot
  setenv("CLIENT_TPU_GRPC_CHANNEL_MAX_SHARE_COUNT", "2", 1);
  std::unique_ptr<tc::InferenceServerGrpcClient> s1, s2, s3;
  CHECK_OK(tc::InferenceServerGrpcClient::Create(
      &s1, url, tc::KeepAliveOptions(), /*use_cached_channel=*/true));
  CHECK_OK(tc::InferenceServerGrpcClient::Create(
      &s2, url, tc::KeepAliveOptions(), /*use_cached_channel=*/true));
  CHECK_OK(tc::InferenceServerGrpcClient::Create(
      &s3, url, tc::KeepAliveOptions(), /*use_cached_channel=*/true));
  for (auto* c : {s1.get(), s2.get(), s3.get()}) {
    tc::InferResult* r = nullptr;
    CHECK_OK(DoInfer(c, "simple", &r));
    delete r;
  }
  CHECK(tc::CachedChannelCountForTesting(url) == 2);
  s1.reset();
  s2.reset();
  s3.reset();
  CHECK(tc::CachedChannelCountForTesting(url) == 0);
  unsetenv("CLIENT_TPU_GRPC_CHANNEL_MAX_SHARE_COUNT");
}

int
main(int argc, char** argv)
{
  std::string url = argc > 1 ? argv[1] : "localhost:8001";
  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  tc::Error err = tc::InferenceServerGrpcClient::Create(&client, url);
  if (!err.IsOk()) {
    std::cerr << "create failed: " << err.Message() << std::endl;
    return 1;
  }
  TestHealthAndMetadata(client.get());
  TestInfer(client.get());
  TestInferErrors(client.get());
  TestAsyncInfer(client.get());
  TestSequenceStream(client.get());
  TestDecoupledFinalResponse(client.get());
  TestStringSequenceId(client.get());
  TestStatistics(client.get());
  TestSharedMemoryVerbs(client.get());
  TestTraceAndLogSettings(client.get());
  TestInferMulti(client.get());
  TestCompression(client.get());
  TestKeepAliveAndChannelCache(url);
  TestTlsTransportSeam(url);

  std::cout << g_checks << " checks, " << g_failures << " failures"
            << std::endl;
  if (g_failures == 0) {
    std::cout << "PASS: cc_grpc_client_test" << std::endl;
    return 0;
  }
  return 1;
}
