// Native gRPC client integration suite against a live in-process server —
// the gRPC half of the reference's typed cc_client_test.cc (reference
// src/c++/tests/cc_client_test.cc:1626-1627 instantiates the suite for both
// protocols; here each protocol binary shares the same check list, driven
// together by tests/test_cpp_client.py).
//   cc_grpc_client_test <host:port>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <iostream>
#include <mutex>
#include <thread>
#include <vector>

#include "grpc_client.h"

namespace tc = ctpu;

static int g_failures = 0;
static int g_checks = 0;

#define CHECK(cond)                                                         \
  do {                                                                      \
    g_checks++;                                                             \
    if (!(cond)) {                                                          \
      g_failures++;                                                         \
      std::cerr << "FAIL " << __FILE__ << ":" << __LINE__ << "  " << #cond  \
                << std::endl;                                               \
    }                                                                       \
  } while (false)

#define CHECK_OK(expr)                                                      \
  do {                                                                      \
    g_checks++;                                                             \
    tc::Error e__ = (expr);                                                 \
    if (!e__.IsOk()) {                                                      \
      g_failures++;                                                         \
      std::cerr << "FAIL " << __FILE__ << ":" << __LINE__ << "  " << #expr  \
                << " -> " << e__.Message() << std::endl;                    \
    }                                                                       \
  } while (false)

#define CHECK_ERR(expr)                                                     \
  do {                                                                      \
    g_checks++;                                                             \
    tc::Error e__ = (expr);                                                 \
    if (e__.IsOk()) {                                                       \
      g_failures++;                                                         \
      std::cerr << "FAIL " << __FILE__ << ":" << __LINE__                   \
                << "  expected error from " << #expr << std::endl;          \
    }                                                                       \
  } while (false)

static void
TestHealthAndMetadata(tc::InferenceServerGrpcClient* client)
{
  bool live = false, ready = false, model_ready = false;
  CHECK_OK(client->IsServerLive(&live));
  CHECK(live);
  CHECK_OK(client->IsServerReady(&ready));
  CHECK(ready);
  CHECK_OK(client->IsModelReady(&model_ready, "simple"));
  CHECK(model_ready);
  // missing model: server answers ready=false or NOT_FOUND; both are "not
  // ready", neither may crash the connection
  tc::Error e = client->IsModelReady(&model_ready, "no_such_model");
  CHECK(!e.IsOk() || !model_ready);

  inference::ServerMetadataResponse server_meta;
  CHECK_OK(client->ServerMetadata(&server_meta));
  CHECK(!server_meta.name().empty());

  inference::ModelMetadataResponse model_meta;
  CHECK_OK(client->ModelMetadata(&model_meta, "simple"));
  CHECK(model_meta.name() == "simple");
  CHECK(model_meta.inputs_size() == 2);
  CHECK(model_meta.outputs_size() == 2);

  inference::ModelConfigResponse config;
  CHECK_OK(client->ModelConfig(&config, "simple"));
  CHECK(config.config().name() == "simple");

  inference::RepositoryIndexResponse index;
  CHECK_OK(client->ModelRepositoryIndex(&index));
  bool found = false;
  for (const auto& m : index.models())
    if (m.name() == "simple") found = true;
  CHECK(found);
}

static tc::Error
DoInfer(
    tc::InferenceServerGrpcClient* client, const std::string& model,
    tc::InferResult** result, uint64_t client_timeout_us = 0)
{
  std::vector<int32_t> input0(16), input1(16);
  for (int i = 0; i < 16; ++i) {
    input0[i] = i;
    input1[i] = 2 * i;
  }
  tc::InferInput in0("INPUT0", {1, 16}, "INT32");
  tc::InferInput in1("INPUT1", {1, 16}, "INT32");
  in0.AppendRaw(
      reinterpret_cast<const uint8_t*>(input0.data()), 16 * sizeof(int32_t));
  in1.AppendRaw(
      reinterpret_cast<const uint8_t*>(input1.data()), 16 * sizeof(int32_t));
  tc::InferRequestedOutput out0("OUTPUT0"), out1("OUTPUT1");
  tc::InferOptions options(model);
  options.request_id = "42";
  options.client_timeout_us = client_timeout_us;
  return client->Infer(result, options, {&in0, &in1}, {&out0, &out1});
}

static void
TestInfer(tc::InferenceServerGrpcClient* client)
{
  tc::InferResult* result = nullptr;
  CHECK_OK(DoInfer(client, "simple", &result));
  if (result == nullptr) return;
  std::unique_ptr<tc::InferResult> owner(result);
  CHECK(result->Id() == "42");
  const uint8_t* data = nullptr;
  size_t nbytes = 0;
  CHECK_OK(result->RawData("OUTPUT0", &data, &nbytes));
  CHECK(nbytes == 16 * sizeof(int32_t));
  const int32_t* sum = reinterpret_cast<const int32_t*>(data);
  bool ok = true;
  for (int i = 0; i < 16; ++i) ok &= (sum[i] == 3 * i);
  CHECK(ok);
  std::vector<int64_t> shape;
  CHECK_OK(result->Shape("OUTPUT0", &shape));
  CHECK(shape.size() == 2 && shape[1] == 16);
  std::string datatype;
  CHECK_OK(result->Datatype("OUTPUT0", &datatype));
  CHECK(datatype == "INT32");
  CHECK_ERR(result->RawData("NO_SUCH_OUTPUT", &data, &nbytes));
}

static void
TestInferErrors(tc::InferenceServerGrpcClient* client)
{
  tc::InferResult* result = nullptr;
  // unknown model -> grpc-status NOT_FOUND surfaced as Error
  tc::Error e = DoInfer(client, "no_such_model", &result);
  CHECK(!e.IsOk());
  CHECK(e.Message().find("grpc-status") != std::string::npos);

  // wrong shape -> INVALID_ARGUMENT
  tc::InferInput bad("INPUT0", {1, 3}, "INT32");
  std::vector<int32_t> values(3, 7);
  bad.AppendRaw(
      reinterpret_cast<const uint8_t*>(values.data()), 3 * sizeof(int32_t));
  tc::InferOptions options("simple");
  e = client->Infer(&result, options, {&bad});
  CHECK(!e.IsOk());
}

static void
TestAsyncInfer(tc::InferenceServerGrpcClient* client)
{
  // A burst of async requests sharing one connection + reactor thread (the
  // reference's completion-queue model) — hundreds in flight, no
  // thread-per-request.
  const int kRequests = 64;
  std::mutex mu;
  std::condition_variable cv;
  int done = 0, good = 0;
  std::vector<int32_t> input0(16), input1(16);
  for (int i = 0; i < 16; ++i) {
    input0[i] = i;
    input1[i] = i;
  }
  tc::InferInput in0("INPUT0", {1, 16}, "INT32");
  tc::InferInput in1("INPUT1", {1, 16}, "INT32");
  in0.AppendRaw(
      reinterpret_cast<const uint8_t*>(input0.data()), 16 * sizeof(int32_t));
  in1.AppendRaw(
      reinterpret_cast<const uint8_t*>(input1.data()), 16 * sizeof(int32_t));
  tc::InferOptions options("simple");
  for (int r = 0; r < kRequests; ++r) {
    CHECK_OK(client->AsyncInfer(
        [&](tc::InferResultPtr result) {
          std::lock_guard<std::mutex> lk(mu);
          ++done;
          if (result->RequestStatus().IsOk()) {
            const uint8_t* data = nullptr;
            size_t nbytes = 0;
            if (result->RawData("OUTPUT0", &data, &nbytes).IsOk() &&
                nbytes == 16 * sizeof(int32_t) &&
                reinterpret_cast<const int32_t*>(data)[5] == 10) {
              ++good;
            }
          }
          cv.notify_all();
        },
        options, {&in0, &in1}));
  }
  std::unique_lock<std::mutex> lk(mu);
  const bool all = cv.wait_for(
      lk, std::chrono::seconds(60), [&] { return done == kRequests; });
  CHECK(all);
  CHECK(good == kRequests);
}

static void
TestSequenceStream(tc::InferenceServerGrpcClient* client)
{
  std::mutex mu;
  std::condition_variable cv;
  std::vector<int32_t> sums;
  CHECK_OK(client->StartStream([&](tc::InferResultPtr result) {
    std::lock_guard<std::mutex> lk(mu);
    const uint8_t* data = nullptr;
    size_t nbytes = 0;
    if (result->RequestStatus().IsOk() &&
        result->RawData("OUTPUT", &data, &nbytes).IsOk()) {
      sums.push_back(*reinterpret_cast<const int32_t*>(data));
    } else {
      sums.push_back(-1);
    }
    cv.notify_all();
  }));
  for (int step = 0; step < 3; ++step) {
    int32_t value = step + 1;
    tc::InferInput input("INPUT", {1}, "INT32");
    input.AppendRaw(
        reinterpret_cast<const uint8_t*>(&value), sizeof(value));
    tc::InferOptions options("simple_sequence");
    options.sequence_id = 7;
    options.sequence_start = (step == 0);
    options.sequence_end = (step == 2);
    CHECK_OK(client->AsyncStreamInfer(options, {&input}));
  }
  {
    std::unique_lock<std::mutex> lk(mu);
    cv.wait_for(
        lk, std::chrono::seconds(30), [&] { return sums.size() >= 3; });
  }
  CHECK_OK(client->StopStream());
  CHECK(sums.size() == 3);
  if (sums.size() == 3) {
    CHECK(sums[0] == 1 && sums[1] == 3 && sums[2] == 6);
  }
  // a second stream on the same client works after StopStream
  std::atomic<int> n2{0};
  CHECK_OK(client->StartStream([&](tc::InferResultPtr) { ++n2; }));
  CHECK_OK(client->StopStream());
}

static void
TestStringSequenceId(tc::InferenceServerGrpcClient* client)
{
  // unary infer over the sequence protocol with a string correlation id
  // (string_param in the request parameters map)
  int32_t values[3] = {10, 20, 30};
  int32_t expected = 0;
  for (int step = 0; step < 3; ++step) {
    expected += values[step];
    tc::InferInput input("INPUT", {1}, "INT32");
    input.AppendRaw(
        reinterpret_cast<const uint8_t*>(&values[step]), sizeof(int32_t));
    tc::InferOptions options("simple_sequence");
    options.sequence_id_str = "grpc-corr-xyz";
    options.sequence_start = (step == 0);
    options.sequence_end = (step == 2);
    tc::InferResult* result = nullptr;
    CHECK_OK(client->Infer(&result, options, {&input}));
    if (result == nullptr) return;
    std::unique_ptr<tc::InferResult> owner(result);
    const uint8_t* buf = nullptr;
    size_t size = 0;
    CHECK_OK(result->RawData("OUTPUT", &buf, &size));
    CHECK(*reinterpret_cast<const int32_t*>(buf) == expected);
  }
}

static void
TestStatistics(tc::InferenceServerGrpcClient* client)
{
  inference::ModelStatisticsResponse stats;
  CHECK_OK(client->ModelInferenceStatistics(&stats, "simple"));
  CHECK(stats.model_stats_size() >= 1);
  bool counted = false;
  for (const auto& ms : stats.model_stats())
    if (ms.name() == "simple" && ms.inference_stats().success().count() > 0)
      counted = true;
  CHECK(counted);

  tc::InferenceServerGrpcClient::InferStat client_stat;
  CHECK_OK(client->ClientInferStat(&client_stat));
  CHECK(client_stat.completed_request_count > 0);
}

static void
TestSharedMemoryVerbs(tc::InferenceServerGrpcClient* client)
{
  // Round-trip the system-shm registry (no actual shm mapping needed for
  // the control-plane verbs: register with a key that exists).
  inference::SystemSharedMemoryStatusResponse status;
  CHECK_OK(client->SystemSharedMemoryStatus(&status));
  // Unregister-all must succeed even when empty.
  CHECK_OK(client->UnregisterSystemSharedMemory());
  inference::TpuSharedMemoryStatusResponse tpu_status;
  CHECK_OK(client->TpuSharedMemoryStatus(&tpu_status));
  CHECK_OK(client->UnregisterTpuSharedMemory());
}

int
main(int argc, char** argv)
{
  std::string url = argc > 1 ? argv[1] : "localhost:8001";
  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  tc::Error err = tc::InferenceServerGrpcClient::Create(&client, url);
  if (!err.IsOk()) {
    std::cerr << "create failed: " << err.Message() << std::endl;
    return 1;
  }
  TestHealthAndMetadata(client.get());
  TestInfer(client.get());
  TestInferErrors(client.get());
  TestAsyncInfer(client.get());
  TestSequenceStream(client.get());
  TestStringSequenceId(client.get());
  TestStatistics(client.get());
  TestSharedMemoryVerbs(client.get());

  std::cout << g_checks << " checks, " << g_failures << " failures"
            << std::endl;
  if (g_failures == 0) {
    std::cout << "PASS: cc_grpc_client_test" << std::endl;
    return 0;
  }
  return 1;
}
