// Native client integration suite — the reference cc_client_test.cc pattern
// (reference src/c++/tests/cc_client_test.cc: typed suite against a live
// server) with a self-contained CHECK harness instead of gtest (not in the
// image).  Run against the Python in-process server:
//   cc_client_test <host:port>
#include <atomic>
#include <condition_variable>
#include <cstring>
#include <iostream>
#include <mutex>

#include "../client/http_client.h"
#include "../client/shm_utils.h"

namespace tc = ctpu;

static int g_failures = 0;
static int g_checks = 0;

#define CHECK(cond)                                                         \
  do {                                                                      \
    g_checks++;                                                             \
    if (!(cond)) {                                                          \
      g_failures++;                                                         \
      std::cerr << "FAIL " << __FILE__ << ":" << __LINE__ << "  " << #cond  \
                << std::endl;                                               \
    }                                                                       \
  } while (false)

#define CHECK_OK(expr)                                                      \
  do {                                                                      \
    g_checks++;                                                             \
    tc::Error e__ = (expr);                                                 \
    if (!e__.IsOk()) {                                                      \
      g_failures++;                                                         \
      std::cerr << "FAIL " << __FILE__ << ":" << __LINE__ << "  " << #expr  \
                << " -> " << e__.Message() << std::endl;                    \
    }                                                                       \
  } while (false)

static void
TestHealthAndMetadata(tc::InferenceServerHttpClient* client)
{
  bool live = false, ready = false, model_ready = false;
  CHECK_OK(client->IsServerLive(&live));
  CHECK(live);
  CHECK_OK(client->IsServerReady(&ready));
  CHECK(ready);
  CHECK_OK(client->IsModelReady(&model_ready, "simple"));
  CHECK(model_ready);
  CHECK_OK(client->IsModelReady(&model_ready, "no_such_model"));
  CHECK(!model_ready);

  ctpu::json::ValuePtr meta;
  CHECK_OK(client->ServerMetadata(&meta));
  CHECK(meta->Get("name") != nullptr);

  CHECK_OK(client->ModelMetadata(&meta, "simple"));
  CHECK(meta->Get("name")->AsString() == "simple");
  CHECK(meta->Get("inputs")->arr.size() == 2);

  CHECK_OK(client->ModelConfig(&meta, "simple"));
  CHECK(meta->Has("max_batch_size") || meta->Has("name"));

  // HTTP repository index is a bare JSON array (Triton HTTP format)
  CHECK_OK(client->ModelRepositoryIndex(&meta));
  CHECK(meta->type == ctpu::json::Type::Array && !meta->arr.empty());

  tc::Error err = client->ModelMetadata(&meta, "no_such_model");
  CHECK(!err.IsOk());
}

static void
FillInputs(
    std::vector<int32_t>& in0, std::vector<int32_t>& in1, tc::InferInput& i0,
    tc::InferInput& i1)
{
  for (int i = 0; i < 16; i++) {
    in0[i] = i;
    in1[i] = 2;
  }
  i0.AppendRaw(
      reinterpret_cast<const uint8_t*>(in0.data()),
      in0.size() * sizeof(int32_t));
  i1.AppendRaw(
      reinterpret_cast<const uint8_t*>(in1.data()),
      in1.size() * sizeof(int32_t));
}

static void
TestInfer(tc::InferenceServerHttpClient* client)
{
  std::vector<int32_t> in0(16), in1(16);
  tc::InferInput i0("INPUT0", {1, 16}, "INT32");
  tc::InferInput i1("INPUT1", {1, 16}, "INT32");
  FillInputs(in0, in1, i0, i1);
  tc::InferRequestedOutput o0("OUTPUT0"), o1("OUTPUT1");

  tc::InferOptions options("simple");
  options.request_id = "42";
  tc::InferResultPtr result;
  CHECK_OK(client->Infer(&result, options, {&i0, &i1}, {&o0, &o1}));
  CHECK(result->ModelName() == "simple");
  CHECK(result->Id() == "42");

  std::vector<int64_t> shape;
  CHECK_OK(result->Shape("OUTPUT0", &shape));
  CHECK(shape.size() == 2 && shape[0] == 1 && shape[1] == 16);
  std::string datatype;
  CHECK_OK(result->Datatype("OUTPUT0", &datatype));
  CHECK(datatype == "INT32");

  const uint8_t* buf = nullptr;
  size_t size = 0;
  CHECK_OK(result->RawData("OUTPUT0", &buf, &size));
  CHECK(size == 16 * sizeof(int32_t));
  const int32_t* sum = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; i++) CHECK(sum[i] == in0[i] + in1[i]);
}

static void
TestInferClassification(tc::InferenceServerHttpClient* client)
{
  std::vector<float> scores = {0.1f, 0.7f, 0.15f, 0.05f};
  tc::InferInput input("INPUT0", {1, 4}, "FP32");
  input.AppendRaw(
      reinterpret_cast<const uint8_t*>(scores.data()),
      scores.size() * sizeof(float));
  tc::InferRequestedOutput output("OUTPUT0", /*class_count=*/2);
  tc::InferOptions options("classifier");
  tc::InferResultPtr result;
  CHECK_OK(client->Infer(&result, options, {&input}, {&output}));
  std::vector<std::string> values;
  CHECK_OK(result->StringData("OUTPUT0", &values));
  CHECK(values.size() == 2);
  // best class is index 1 ("dog") per the builtin classifier's labels
  CHECK(values[0].find(":1:dog") != std::string::npos);
}

static void
TestAsyncInfer(tc::InferenceServerHttpClient* client)
{
  std::vector<int32_t> in0(16), in1(16);
  tc::InferInput i0("INPUT0", {1, 16}, "INT32");
  tc::InferInput i1("INPUT1", {1, 16}, "INT32");
  FillInputs(in0, in1, i0, i1);
  tc::InferOptions options("simple");

  std::mutex mu;
  std::condition_variable cv;
  bool done = false;
  tc::InferResultPtr result;
  tc::Error async_err;
  CHECK_OK(client->AsyncInfer(
      [&](tc::InferResultPtr r, tc::Error e) {
        std::lock_guard<std::mutex> lk(mu);
        result = r;
        async_err = e;
        done = true;
        cv.notify_one();
      },
      options, {&i0, &i1}));
  {
    std::unique_lock<std::mutex> lk(mu);
    cv.wait_for(lk, std::chrono::seconds(30), [&] { return done; });
  }
  CHECK(done);
  CHECK_OK(async_err);
  const uint8_t* buf = nullptr;
  size_t size = 0;
  CHECK_OK(result->RawData("OUTPUT0", &buf, &size));
  const int32_t* sum = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; i++) CHECK(sum[i] == in0[i] + in1[i]);
}

static void
TestInferCompressed(tc::InferenceServerHttpClient* client)
{
  // request body gzip-compressed, response requested as deflate (zlib)
  std::vector<int32_t> in0(16), in1(16);
  tc::InferInput i0("INPUT0", {1, 16}, "INT32");
  tc::InferInput i1("INPUT1", {1, 16}, "INT32");
  FillInputs(in0, in1, i0, i1);
  tc::InferOptions options("simple");
  using CT = tc::InferenceServerHttpClient::CompressionType;
  for (const auto mode : {CT::GZIP, CT::DEFLATE}) {
    tc::InferResultPtr result;
    CHECK_OK(client->Infer(&result, options, {&i0, &i1}, {}, mode, mode));
    const uint8_t* buf = nullptr;
    size_t size = 0;
    CHECK_OK(result->RawData("OUTPUT0", &buf, &size));
    const int32_t* sum = reinterpret_cast<const int32_t*>(buf);
    for (int i = 0; i < 16; i++) CHECK(sum[i] == in0[i] + in1[i]);
  }
}

static void
TestAsyncInferBurst(tc::InferenceServerHttpClient* client)
{
  // 64 requests in flight on the client's epoll reactor — one event-loop
  // thread, a handful of keep-alive connections, no thread-per-request.
  const int kRequests = 64;
  std::vector<int32_t> in0(16), in1(16);
  tc::InferInput i0("INPUT0", {1, 16}, "INT32");
  tc::InferInput i1("INPUT1", {1, 16}, "INT32");
  FillInputs(in0, in1, i0, i1);
  tc::InferOptions options("simple");

  std::mutex mu;
  std::condition_variable cv;
  int done = 0, good = 0;
  for (int r = 0; r < kRequests; ++r) {
    CHECK_OK(client->AsyncInfer(
        [&](tc::InferResultPtr result, tc::Error e) {
          std::lock_guard<std::mutex> lk(mu);
          ++done;
          const uint8_t* buf = nullptr;
          size_t size = 0;
          if (e.IsOk() && result != nullptr &&
              result->RawData("OUTPUT0", &buf, &size).IsOk() &&
              size == 16 * sizeof(int32_t)) {
            ++good;
          }
          cv.notify_all();
        },
        options, {&i0, &i1}));
  }
  std::unique_lock<std::mutex> lk(mu);
  const bool all = cv.wait_for(
      lk, std::chrono::seconds(60), [&] { return done == kRequests; });
  CHECK(all);
  CHECK(good == kRequests);
}

static void
TestSystemSharedMemory(tc::InferenceServerHttpClient* client)
{
  const char* key = "/cc_test_shm";
  const size_t region_size = 2 * 16 * sizeof(int32_t);
  int fd = -1;
  CHECK_OK(tc::CreateSharedMemoryRegion(key, region_size, &fd));
  void* addr = nullptr;
  CHECK_OK(tc::MapSharedMemory(fd, 0, region_size, &addr));
  int32_t* in_region = static_cast<int32_t*>(addr);
  for (int i = 0; i < 16; i++) {
    in_region[i] = i;
    in_region[16 + i] = 3;
  }

  CHECK_OK(client->RegisterSystemSharedMemory("cc_in", key, region_size));
  // HTTP shm status is a bare array of region entries (Triton HTTP format)
  ctpu::json::ValuePtr status;
  CHECK_OK(client->SystemSharedMemoryStatus(&status));
  bool found = false;
  for (const auto& region : status->arr) {
    if (region->Get("name") != nullptr &&
        region->Get("name")->AsString() == "cc_in") {
      found = true;
    }
  }
  CHECK(found);

  tc::InferInput i0("INPUT0", {1, 16}, "INT32");
  tc::InferInput i1("INPUT1", {1, 16}, "INT32");
  i0.SetSharedMemory("cc_in", 16 * sizeof(int32_t), 0);
  i1.SetSharedMemory("cc_in", 16 * sizeof(int32_t), 16 * sizeof(int32_t));
  tc::InferRequestedOutput o0("OUTPUT0");
  tc::InferOptions options("simple");
  tc::InferResultPtr result;
  CHECK_OK(client->Infer(&result, options, {&i0, &i1}, {&o0}));
  const uint8_t* buf = nullptr;
  size_t size = 0;
  CHECK_OK(result->RawData("OUTPUT0", &buf, &size));
  const int32_t* sum = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; i++) CHECK(sum[i] == i + 3);

  CHECK_OK(client->UnregisterSystemSharedMemory("cc_in"));
  CHECK_OK(tc::UnmapSharedMemory(addr, region_size));
  CHECK_OK(tc::CloseSharedMemory(fd));
  CHECK_OK(tc::UnlinkSharedMemoryRegion(key));
}

static void
TestSequence(tc::InferenceServerHttpClient* client)
{
  // stateful accumulator over the sequence protocol (request parameters)
  int32_t values[3] = {5, 7, 11};
  int32_t expected = 0;
  for (int step = 0; step < 3; step++) {
    expected += values[step];
    tc::InferInput input("INPUT", {1}, "INT32");
    input.AppendRaw(
        reinterpret_cast<const uint8_t*>(&values[step]), sizeof(int32_t));
    tc::InferOptions options("simple_sequence");
    options.sequence_id = 9001;
    options.sequence_start = (step == 0);
    options.sequence_end = (step == 2);
    tc::InferResultPtr result;
    CHECK_OK(client->Infer(&result, options, {&input}));
    const uint8_t* buf = nullptr;
    size_t size = 0;
    CHECK_OK(result->RawData("OUTPUT", &buf, &size));
    CHECK(*reinterpret_cast<const int32_t*>(buf) == expected);
  }
}

static void
TestStringSequenceId(tc::InferenceServerHttpClient* client)
{
  // same protocol, string correlation id (reference InferOptions supports
  // both forms); a distinct id must start a distinct accumulator
  int32_t values[3] = {2, 3, 4};
  int32_t expected = 0;
  for (int step = 0; step < 3; step++) {
    expected += values[step];
    tc::InferInput input("INPUT", {1}, "INT32");
    input.AppendRaw(
        reinterpret_cast<const uint8_t*>(&values[step]), sizeof(int32_t));
    tc::InferOptions options("simple_sequence");
    options.sequence_id_str = "corr-abc";
    options.sequence_start = (step == 0);
    options.sequence_end = (step == 2);
    tc::InferResultPtr result;
    CHECK_OK(client->Infer(&result, options, {&input}));
    const uint8_t* buf = nullptr;
    size_t size = 0;
    CHECK_OK(result->RawData("OUTPUT", &buf, &size));
    CHECK(*reinterpret_cast<const int32_t*>(buf) == expected);
  }
}

static void
TestClientInferStat(tc::InferenceServerHttpClient* client)
{
  tc::InferStat before;
  CHECK_OK(client->ClientInferStat(&before));
  std::vector<int32_t> in0(16), in1(16);
  tc::InferInput i0("INPUT0", {1, 16}, "INT32");
  tc::InferInput i1("INPUT1", {1, 16}, "INT32");
  FillInputs(in0, in1, i0, i1);
  tc::InferResultPtr result;
  CHECK_OK(client->Infer(&result, tc::InferOptions("simple"), {&i0, &i1}));
  tc::InferStat after;
  CHECK_OK(client->ClientInferStat(&after));
  CHECK(after.completed_request_count == before.completed_request_count + 1);
  CHECK(
      after.cumulative_total_request_time_ns >
      before.cumulative_total_request_time_ns);
  CHECK(after.cumulative_send_time_ns >= before.cumulative_send_time_ns);
  CHECK(after.cumulative_receive_time_ns > before.cumulative_receive_time_ns);
}

static void
TestInferMulti(tc::InferenceServerHttpClient* client)
{
  std::vector<int32_t> in0(16), in1(16);
  tc::InferInput i0("INPUT0", {1, 16}, "INT32");
  tc::InferInput i1("INPUT1", {1, 16}, "INT32");
  FillInputs(in0, in1, i0, i1);
  std::vector<tc::InferOptions> options = {tc::InferOptions("simple")};
  std::vector<std::vector<tc::InferInput*>> inputs = {
      {&i0, &i1}, {&i0, &i1}, {&i0, &i1}};
  std::vector<tc::InferResultPtr> results;
  CHECK_OK(client->InferMulti(&results, options, inputs));
  CHECK(results.size() == 3);
  for (const auto& result : results) {
    const uint8_t* buf = nullptr;
    size_t size = 0;
    CHECK_OK(result->RawData("OUTPUT0", &buf, &size));
    const int32_t* sum = reinterpret_cast<const int32_t*>(buf);
    for (int i = 0; i < 16; i++) CHECK(sum[i] == in0[i] + in1[i]);
  }
}

static void
TestModelControl(tc::InferenceServerHttpClient* client)
{
  bool ready = false;
  CHECK_OK(client->UnloadModel("simple"));
  CHECK_OK(client->IsModelReady(&ready, "simple"));
  CHECK(!ready);
  CHECK_OK(client->LoadModel("simple"));
  CHECK_OK(client->IsModelReady(&ready, "simple"));
  CHECK(ready);
}

static void
TestStatistics(tc::InferenceServerHttpClient* client)
{
  ctpu::json::ValuePtr stats;
  CHECK_OK(client->ModelInferenceStatistics(&stats, "simple"));
  CHECK(stats->Get("model_stats") != nullptr);
}

static void
TestTlsTransportSeam(const std::string& url)
{
  // Without a TLS transport (no OpenSSL in this toolchain, no factory
  // registered), the SSL Create must fail with the descriptive diagnostic —
  // at Create, not on the first request.
  tc::HttpSslOptions ssl;
  std::unique_ptr<tc::InferenceServerHttpClient> tls_client;
  tc::Error e = tc::InferenceServerHttpClient::Create(&tls_client, url, ssl);
  CHECK(!e.IsOk());
  CHECK(e.Message().find("TLS") != std::string::npos);

  // https:// scheme on the plain Create takes the same gate
  e = tc::InferenceServerHttpClient::Create(&tls_client, "https://" + url);
  CHECK(!e.IsOk());

  // Injectable seam (mirror of the gRPC suite's TestTlsTransportSeam):
  // register a pass-through TCP transport standing in for a TLS library —
  // the SAME Create + sync request path must then work end to end.
  tc::SetTlsTransportFactory(
      [](const tc::TlsConfig&) { return tc::MakeTcpTransport(); });
  e = tc::InferenceServerHttpClient::Create(&tls_client, url, ssl);
  CHECK_OK(e);
  if (e.IsOk()) {
    TestInfer(tls_client.get());
    // async on a TLS client is rejected with a helpful error, not a hang
    tc::InferInput i0("INPUT0", {1, 16}, "INT32");
    tc::InferInput i1("INPUT1", {1, 16}, "INT32");
    std::vector<int32_t> in0(16), in1(16);
    FillInputs(in0, in1, i0, i1);
    tc::InferOptions options("simple");
    e = tls_client->AsyncInfer(
        [](tc::InferResultPtr, tc::Error) {}, options, {&i0, &i1});
    CHECK(!e.IsOk());
    CHECK(e.Message().find("TLS") != std::string::npos);
  }
  tc::SetTlsTransportFactory(nullptr);
}

int
main(int argc, char** argv)
{
  std::string url = (argc > 1) ? argv[1] : "localhost:8000";
  std::unique_ptr<tc::InferenceServerHttpClient> client;
  tc::Error err = tc::InferenceServerHttpClient::Create(&client, url);
  if (!err.IsOk()) {
    std::cerr << "error: " << err.Message() << std::endl;
    return 1;
  }
  TestHealthAndMetadata(client.get());
  TestInfer(client.get());
  TestInferClassification(client.get());
  TestAsyncInfer(client.get());
  TestAsyncInferBurst(client.get());
  TestInferCompressed(client.get());
  TestSystemSharedMemory(client.get());
  TestSequence(client.get());
  TestStringSequenceId(client.get());
  TestClientInferStat(client.get());
  TestInferMulti(client.get());
  TestModelControl(client.get());
  TestStatistics(client.get());
  TestTlsTransportSeam(url);

  std::cout << (g_checks - g_failures) << "/" << g_checks << " checks passed"
            << std::endl;
  if (g_failures == 0) {
    std::cout << "PASS: cc_client_test" << std::endl;
    return 0;
  }
  return 1;
}
