// HPACK unit test: RFC 7541 Appendix C vectors (Huffman literals, header
// blocks with dynamic-table evolution) + roundtrips of this implementation.
// The Huffman table itself is init-verified (Kraft sum, EOS code) in hpack.cc.
#include "hpack.h"
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>
using namespace ctpu::h2;

static bool huff(const char* hex, const char* want) {
  std::string bytes;
  for (const char* p = hex; *p; p += 2) {
    unsigned v; sscanf(p, "%2x", &v); bytes.push_back((char)v);
  }
  std::string out;
  bool ok = Huffman::Get().Decode((const uint8_t*)bytes.data(), bytes.size(), &out);
  if (!ok || out != want) { printf("FAIL %s -> '%s' (want '%s', ok=%d)\n", hex, out.c_str(), want, ok); return false; }
  return true;
}

int main() {
  // RFC 7541 Appendix C Huffman-coded literals
  bool ok = true;
  ok &= huff("f1e3c2e5f23a6ba0ab90f4ff", "www.example.com");        // C.4.1
  ok &= huff("a8eb10649cbf", "no-cache");                             // C.4.2
  ok &= huff("25a849e95ba97d7f", "custom-key");                       // C.4.3
  ok &= huff("25a849e95bb8e8b4bf", "custom-value");                   // C.4.3
  ok &= huff("6402", "302");                                          // C.6.1
  ok &= huff("aec3771a4b", "private");                                // C.6.1
  ok &= huff("d07abe941054d444a8200595040b8166e082a62d1bff",
             "Mon, 21 Oct 2013 20:13:21 GMT");                        // C.6.1
  ok &= huff("9d29ad171863c78f0b97c8e9ae82ae43d3",
             "https://www.example.com");                              // C.6.1
  ok &= huff("94e7821dd7f2e6c7b335dfdfcd5b3960d5af27087f3672c1ab270fb5291f9587316065c003ed4ee5b1063d5007",
             "foo=ASDJKHQKBZXOQWEOPIUAXQWEOIU; max-age=3600; version=1"); // C.6.3
  // Roundtrip our own encoder through the decoder over all byte values
  std::string all;
  for (int i = 0; i < 256; ++i) all.push_back((char)i);
  std::string enc, dec;
  Huffman::Get().Encode(all, &enc);
  if (!Huffman::Get().Decode((const uint8_t*)enc.data(), enc.size(), &dec) || dec != all) {
    printf("FAIL roundtrip\n"); ok = false;
  }
  // HPACK block: encoder -> decoder roundtrip
  HpackEncoder e;
  HpackDecoder d;
  std::vector<Header> in = {{":method", "POST"}, {":path", "/inference.GRPCInferenceService/ModelInfer"},
                            {":scheme", "http"}, {":authority", "localhost:8001"},
                            {"content-type", "application/grpc"}, {"te", "trailers"},
                            {"grpc-timeout", "5S"}};
  std::string block; e.Encode(in, &block);
  std::vector<Header> got;
  if (!d.Decode((const uint8_t*)block.data(), block.size(), &got) || got != in) {
    printf("FAIL hpack roundtrip (%zu)\n", got.size()); ok = false;
  }
  // RFC C.3.1 request block (no Huffman, incremental indexing w/ dyn table)
  {
    const uint8_t block1[] = {0x82, 0x86, 0x84, 0x41, 0x0f, 'w','w','w','.','e','x','a','m','p','l','e','.','c','o','m'};
    HpackDecoder d2;
    std::vector<Header> h1;
    if (!d2.Decode(block1, sizeof(block1), &h1) || h1 != std::vector<Header>{
          {":method","GET"},{":scheme","http"},{":path","/"},{":authority","www.example.com"}}) {
      printf("FAIL C.3.1\n"); ok = false;
    }
    // C.3.2 second request reuses dynamic entry 62
    const uint8_t block2[] = {0x82, 0x86, 0x84, 0xbe, 0x58, 0x08, 'n','o','-','c','a','c','h','e'};
    std::vector<Header> h2v;
    if (!d2.Decode(block2, sizeof(block2), &h2v) || h2v != std::vector<Header>{
          {":method","GET"},{":scheme","http"},{":path","/"},{":authority","www.example.com"},
          {"cache-control","no-cache"}}) {
      printf("FAIL C.3.2\n"); ok = false;
    }
  }
  printf(ok ? "ALL OK\n" : "FAILURES\n");
  return ok ? 0 : 1;
}
