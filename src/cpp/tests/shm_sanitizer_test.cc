// Native shared-memory library exercise driver, built and run under
// AddressSanitizer by `make asan` (SURVEY §5.2 prescribed sanitizer CI;
// the byte-window code is exactly where ASAN pays off).  Covers the happy
// paths, the overflow-guarded range checks, and error paths of BOTH C ABIs
// (libcshm_tpu: system shm; libctpushm: TPU host-window regions).
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include "../shm/ctpushm.h"

extern "C" {
// libcshm_tpu (src/cpp/shm/cshm.cc)
const char* TpuShmLastError();
void* TpuShmCreate(const char* key, uint64_t byte_size);
void* TpuShmOpen(const char* key, uint64_t byte_size, uint64_t offset);
int TpuShmWrite(void* handle, uint64_t offset, const void* data, uint64_t n);
int TpuShmRead(void* handle, uint64_t offset, void* dst, uint64_t n);
void* TpuShmBaseAddr(void* handle);
uint64_t TpuShmByteSize(void* handle);
int TpuShmClose(void* handle, int keep_key);

}

static int g_failures = 0;

#define CHECK(cond)                                                     \
  do {                                                                  \
    if (!(cond)) {                                                      \
      ++g_failures;                                                     \
      std::fprintf(stderr, "FAIL %s:%d  %s\n", __FILE__, __LINE__,      \
                   #cond);                                              \
    }                                                                   \
  } while (false)

static void
TestSystemShm()
{
  const char* key = "/asan_shm_test";
  void* region = TpuShmCreate(key, 256);
  CHECK(region != nullptr);
  CHECK(TpuShmByteSize(region) == 256);

  uint8_t src[64];
  std::memset(src, 0xAB, sizeof(src));
  CHECK(TpuShmWrite(region, 0, src, 64) == 0);
  CHECK(TpuShmWrite(region, 192, src, 64) == 0);
  uint8_t dst[64] = {0};
  CHECK(TpuShmRead(region, 192, dst, 64) == 0);
  CHECK(std::memcmp(src, dst, 64) == 0);

  // range violations must be refused, including offset+size wraparound
  CHECK(TpuShmWrite(region, 224, src, 64) != 0);
  CHECK(TpuShmWrite(region, UINT64_MAX - 8, src, 64) != 0);
  CHECK(TpuShmRead(region, UINT64_MAX - 8, dst, 64) != 0);
  CHECK(TpuShmLastError() != nullptr);

  // a second mapping of the same key sees the first mapping's bytes
  void* view = TpuShmOpen(key, 64, 192);
  CHECK(view != nullptr);
  std::memset(dst, 0, sizeof(dst));
  CHECK(TpuShmRead(view, 0, dst, 64) == 0);
  CHECK(std::memcmp(src, dst, 64) == 0);
  CHECK(TpuShmClose(view, 1) == 0);
  CHECK(TpuShmClose(region, 0) == 0);
}

static void
TestTpuHbmWindow()
{
  void* region = TpuHbmRegionCreate(128, 3);
  CHECK(region != nullptr);
  CHECK(TpuHbmByteSize(region) == 128);
  CHECK(TpuHbmDeviceId(region) == 3);
  CHECK(TpuHbmBaseAddr(region) != nullptr);

  uint8_t src[32];
  for (int i = 0; i < 32; ++i) src[i] = static_cast<uint8_t>(i);
  CHECK(TpuHbmWrite(region, 96, src, 32) == 0);
  uint8_t dst[32] = {0};
  CHECK(TpuHbmRead(region, 96, dst, 32) == 0);
  CHECK(std::memcmp(src, dst, 32) == 0);

  // overflow-guarded range checks (ADVICE r02: huge offset must not wrap)
  CHECK(TpuHbmWrite(region, UINT64_MAX - 4, src, 32) != 0);
  CHECK(TpuHbmRead(region, UINT64_MAX - 4, dst, 32) != 0);
  CHECK(TpuHbmWrite(region, 100, src, 32) != 0);  // tail overrun

  // raw-handle JSON round trip into a second handle on the same window
  // (returns the JSON length on success, a negative code on error)
  char raw[512];
  CHECK(TpuHbmGetRawHandle(region, raw, sizeof(raw)) > 0);
  void* opened = TpuHbmRegionOpen(raw);
  CHECK(opened != nullptr);
  std::memset(dst, 0, sizeof(dst));
  CHECK(TpuHbmRead(opened, 96, dst, 32) == 0);
  CHECK(std::memcmp(src, dst, 32) == 0);
  CHECK(TpuHbmRegionDestroy(opened) == 0);
  CHECK(TpuHbmRegionDestroy(region) == 0);

  // malformed handle JSON is an error, not a crash
  CHECK(TpuHbmRegionOpen("{not json") == nullptr);
  CHECK(TpuHbmRegionOpen("{}") == nullptr);

  // undersized raw-handle buffer reports range error without overflow
  void* r2 = TpuHbmRegionCreate(16, 0);
  CHECK(r2 != nullptr);
  char tiny[4];
  CHECK(TpuHbmGetRawHandle(r2, tiny, sizeof(tiny)) != 0);
  CHECK(TpuHbmRegionDestroy(r2) == 0);
}

int
main()
{
  TestSystemShm();
  TestTpuHbmWindow();
  if (g_failures == 0) {
    std::printf("PASS: shm_sanitizer_test\n");
    return 0;
  }
  std::printf("%d failures\n", g_failures);
  return 1;
}
