// Long-run memory-stability check for the native clients — the analog of
// reference src/c++/tests/memory_leak_test.cc: loop inference through both
// protocols in two modes (reused client; fresh client per iteration, the
// shape that catches leaked connections/reactors), then compare RSS before
// and after.  Growth beyond the tolerance fails the run.
//   memory_leak_test <http_host:port> <grpc_host:port> [iterations]
#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <fstream>
#include <iostream>
#include <memory>
#include <string>
#include <vector>

#include "grpc_client.h"
#include "http_client.h"

namespace tc = ctpu;

static long
RssBytes()
{
  std::ifstream statm("/proc/self/statm");
  long pages = 0, rss = 0;
  statm >> pages >> rss;
  return rss * sysconf(_SC_PAGESIZE);
}

static tc::Error
DoInfer(tc::InferenceServerHttpClient* http,
        tc::InferenceServerGrpcClient* grpc)
{
  std::vector<int32_t> input0(16), input1(16);
  for (int i = 0; i < 16; ++i) {
    input0[i] = i;
    input1[i] = 1;
  }
  tc::InferInput in0("INPUT0", {1, 16}, "INT32");
  tc::InferInput in1("INPUT1", {1, 16}, "INT32");
  in0.AppendRaw(
      reinterpret_cast<const uint8_t*>(input0.data()), 16 * sizeof(int32_t));
  in1.AppendRaw(
      reinterpret_cast<const uint8_t*>(input1.data()), 16 * sizeof(int32_t));
  tc::InferRequestedOutput out0("OUTPUT0");
  tc::InferOptions options("simple");
  tc::Error err;
  const uint8_t* data = nullptr;
  size_t nbytes = 0;
  if (http != nullptr) {
    tc::InferResultPtr result;
    err = http->Infer(&result, options, {&in0, &in1}, {&out0});
    if (err.IsOk()) err = result->RawData("OUTPUT0", &data, &nbytes);
  } else {
    tc::InferResult* raw = nullptr;
    err = grpc->Infer(&raw, options, {&in0, &in1}, {&out0});
    std::unique_ptr<tc::InferResult> owner(raw);
    if (err.IsOk()) err = raw->RawData("OUTPUT0", &data, &nbytes);
    if (err.IsOk() && (nbytes != 16 * sizeof(int32_t) ||
                       reinterpret_cast<const int32_t*>(data)[5] != 6)) {
      err = tc::Error("wrong result");
    }
    return err;
  }
  if (err.IsOk() && (nbytes != 16 * sizeof(int32_t) ||
                     reinterpret_cast<const int32_t*>(data)[5] != 6)) {
    err = tc::Error("wrong result");
  }
  return err;
}

int
main(int argc, char** argv)
{
  const std::string http_url = argc > 1 ? argv[1] : "localhost:8000";
  const std::string grpc_url = argc > 2 ? argv[2] : "localhost:8001";
  const int iterations = argc > 3 ? std::stoi(argv[3]) : 200;

  // warm both stacks (allocator pools, HPACK tables, reactor threads)
  {
    std::unique_ptr<tc::InferenceServerHttpClient> http;
    std::unique_ptr<tc::InferenceServerGrpcClient> grpc;
    if (!tc::InferenceServerHttpClient::Create(&http, http_url).IsOk() ||
        !tc::InferenceServerGrpcClient::Create(&grpc, grpc_url).IsOk()) {
      std::cerr << "create failed" << std::endl;
      return 1;
    }
    for (int i = 0; i < 20; ++i) {
      if (!DoInfer(http.get(), nullptr).IsOk() ||
          !DoInfer(nullptr, grpc.get()).IsOk()) {
        std::cerr << "warmup infer failed" << std::endl;
        return 1;
      }
    }
  }

  const long before = RssBytes();

  // mode 1: one long-lived client per protocol
  {
    std::unique_ptr<tc::InferenceServerHttpClient> http;
    std::unique_ptr<tc::InferenceServerGrpcClient> grpc;
    tc::InferenceServerHttpClient::Create(&http, http_url);
    tc::InferenceServerGrpcClient::Create(&grpc, grpc_url);
    for (int i = 0; i < iterations; ++i) {
      if (!DoInfer(http.get(), nullptr).IsOk() ||
          !DoInfer(nullptr, grpc.get()).IsOk()) {
        std::cerr << "reused-client infer failed at " << i << std::endl;
        return 1;
      }
    }
  }

  // mode 2: fresh client (connection, reactor thread, HPACK state) per
  // iteration — leaked per-connection state shows up here
  for (int i = 0; i < iterations / 4; ++i) {
    std::unique_ptr<tc::InferenceServerHttpClient> http;
    std::unique_ptr<tc::InferenceServerGrpcClient> grpc;
    tc::InferenceServerHttpClient::Create(&http, http_url);
    tc::InferenceServerGrpcClient::Create(&grpc, grpc_url);
    if (!DoInfer(http.get(), nullptr).IsOk() ||
        !DoInfer(nullptr, grpc.get()).IsOk()) {
      std::cerr << "fresh-client infer failed at " << i << std::endl;
      return 1;
    }
  }

  const long after = RssBytes();
  const long growth = after - before;
  std::printf(
      "iterations=%d rss_before=%ld rss_after=%ld growth=%ld bytes\n",
      iterations, before, after, growth);
  // glibc arenas wobble a few hundred KB; a real per-request or
  // per-connection leak at this iteration count clears 16MB easily
  if (growth > 16L * 1024 * 1024) {
    std::cerr << "FAIL: rss grew by " << growth << " bytes" << std::endl;
    return 1;
  }
  std::cout << "PASS: memory_leak_test" << std::endl;
  return 0;
}
