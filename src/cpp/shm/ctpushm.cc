// libctpushm.so — native TPU shared-memory region component.
//
// Role parity with the reference wheel's native libccudashm.so
// (/root/reference/src/python/library/tritonclient/utils/cuda_shared_memory/
// cuda_shared_memory.cc: cudaMalloc + cudaIpcGetMemHandle + host<->device
// copies).  PJRT has no cudaIpc-style cross-process HBM export, so the TPU
// design splits a region into two coupled faces:
//
//   * an HBM face: jax.Array slots managed by the Python layer (device_put /
//     dlpack at the edges) — the zero-copy path when client and server share
//     a process;
//   * a host window (this library): a POSIX-shm-backed, byte-addressable
//     buffer that is the region's process-portable face.  Any byte range can
//     be read or written at any offset; a server in another process attaches
//     it by key from the raw handle.
//
// The raw handle (the cudaIpcMemHandle_t analog) is JSON:
//   {"uuid", "pid", "device_id", "byte_size", "staging_key"}
// generated here so every language binding shares one implementation.

#include "ctpushm.h"
#include <errno.h>
#include <fcntl.h>
#include <stdio.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdint>
#include <cstdlib>
#include <ctime>
#include <string>

namespace {

struct TpuHbmRegion {
  std::string uuid;
  std::string shm_key;
  void* base = nullptr;
  uint64_t byte_size = 0;
  int device_id = 0;
  int fd = -1;
  bool owner = false;  // created (vs attached) — owner unlinks on destroy
};

thread_local std::string g_last_error;

void set_errno_error(const std::string& msg) {
  g_last_error = msg + ": " + strerror(errno);
}

std::string gen_uuid() {
  unsigned char buf[16];
  FILE* f = fopen("/dev/urandom", "rb");
  if (f != nullptr) {
    size_t got = fread(buf, 1, sizeof(buf), f);
    fclose(f);
    if (got != sizeof(buf)) f = nullptr;
  }
  if (f == nullptr) {
    // extremely unlikely; fall back to pid+clock entropy
    uint64_t a = static_cast<uint64_t>(getpid());
    uint64_t b = static_cast<uint64_t>(clock());
    memcpy(buf, &a, 8);
    memcpy(buf + 8, &b, 8);
  }
  char out[33];
  for (int i = 0; i < 16; ++i) snprintf(out + 2 * i, 3, "%02x", buf[i]);
  return std::string(out, 32);
}

// Minimal extraction of "key": "value" / "key": number from the raw-handle
// JSON (emitted by this library or re-serialized by a language binding, so
// whitespace after the colon must be tolerated).
size_t json_value_start(const std::string& js, const char* key) {
  std::string pat = std::string("\"") + key + "\"";
  size_t at = js.find(pat);
  if (at == std::string::npos) return std::string::npos;
  at += pat.size();
  while (at < js.size() && (js[at] == ' ' || js[at] == '\t')) ++at;
  if (at >= js.size() || js[at] != ':') return std::string::npos;
  ++at;
  while (at < js.size() && (js[at] == ' ' || js[at] == '\t')) ++at;
  return at < js.size() ? at : std::string::npos;
}

bool json_string_field(const std::string& js, const char* key,
                       std::string* out) {
  size_t at = json_value_start(js, key);
  if (at == std::string::npos || js[at] != '"') return false;
  ++at;
  size_t end = js.find('"', at);
  if (end == std::string::npos) return false;
  *out = js.substr(at, end - at);
  return true;
}

bool json_uint_field(const std::string& js, const char* key, uint64_t* out) {
  size_t at = json_value_start(js, key);
  if (at == std::string::npos) return false;
  char* endp = nullptr;
  *out = strtoull(js.c_str() + at, &endp, 10);
  return endp != js.c_str() + at;
}

}  // namespace

extern "C" {

const char* TpuHbmLastError() { return g_last_error.c_str(); }

// Create an HBM region's host window: a fresh shm segment keyed by uuid.
void* TpuHbmRegionCreate(uint64_t byte_size, int device_id) {
  std::string uuid = gen_uuid();
  std::string key = "/tpushm-" + uuid;
  int fd = shm_open(key.c_str(), O_RDWR | O_CREAT | O_EXCL, S_IRUSR | S_IWUSR);
  if (fd < 0) {
    set_errno_error("shm_open failed for '" + key + "'");
    return nullptr;
  }
  if (ftruncate(fd, static_cast<off_t>(byte_size)) < 0) {
    set_errno_error("ftruncate failed for '" + key + "'");
    close(fd);
    shm_unlink(key.c_str());
    return nullptr;
  }
  void* base =
      mmap(nullptr, byte_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    set_errno_error("mmap failed for '" + key + "'");
    close(fd);
    shm_unlink(key.c_str());
    return nullptr;
  }
  auto* region = new TpuHbmRegion();
  region->uuid = uuid;
  region->shm_key = key;
  region->base = base;
  region->byte_size = byte_size;
  region->device_id = device_id;
  region->fd = fd;
  region->owner = true;
  return region;
}

// Attach the host window of a region created elsewhere, from its raw handle.
void* TpuHbmRegionOpen(const char* raw_handle_json) {
  std::string js(raw_handle_json != nullptr ? raw_handle_json : "");
  std::string key, uuid;
  uint64_t byte_size = 0;
  uint64_t device_id = 0;
  if (!json_string_field(js, "staging_key", &key) ||
      !json_uint_field(js, "byte_size", &byte_size)) {
    g_last_error = "raw handle missing staging_key/byte_size: " + js;
    return nullptr;
  }
  json_string_field(js, "uuid", &uuid);
  json_uint_field(js, "device_id", &device_id);
  int fd = shm_open(key.c_str(), O_RDWR, S_IRUSR | S_IWUSR);
  if (fd < 0) {
    set_errno_error("shm_open failed for '" + key + "'");
    return nullptr;
  }
  // Reject descriptors whose claimed byte_size exceeds the real segment:
  // mmap past EOF would succeed but any access beyond it is a SIGBUS.
  struct stat st;
  if (fstat(fd, &st) != 0 ||
      static_cast<uint64_t>(st.st_size) < byte_size) {
    g_last_error = "region '" + key + "' is smaller than the descriptor's " +
                   "byte_size claims";
    close(fd);
    return nullptr;
  }
  void* base =
      mmap(nullptr, byte_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    set_errno_error("mmap failed for '" + key + "'");
    close(fd);
    return nullptr;
  }
  auto* region = new TpuHbmRegion();
  region->uuid = uuid;
  region->shm_key = key;
  region->base = base;
  region->byte_size = byte_size;
  region->device_id = static_cast<int>(device_id);
  region->fd = fd;
  region->owner = false;
  return region;
}

int TpuHbmWrite(void* handle, uint64_t offset, const void* src,
                uint64_t size) {
  auto* region = static_cast<TpuHbmRegion*>(handle);
  if (region == nullptr || region->base == nullptr) return TPU_HBM_ERR_HANDLE;
  // overflow-safe: offset + size can wrap uint64
  if (size > region->byte_size || offset > region->byte_size - size) {
    g_last_error = "write overruns TPU region window";
    return TPU_HBM_ERR_RANGE;
  }
  memcpy(static_cast<char*>(region->base) + offset, src, size);
  return TPU_HBM_OK;
}

int TpuHbmRead(void* handle, uint64_t offset, void* dst, uint64_t size) {
  auto* region = static_cast<TpuHbmRegion*>(handle);
  if (region == nullptr || region->base == nullptr) return TPU_HBM_ERR_HANDLE;
  // overflow-safe: offset + size can wrap uint64
  if (size > region->byte_size || offset > region->byte_size - size) {
    g_last_error = "read overruns TPU region window";
    return TPU_HBM_ERR_RANGE;
  }
  memcpy(dst, static_cast<char*>(region->base) + offset, size);
  return TPU_HBM_OK;
}

void* TpuHbmBaseAddr(void* handle) {
  auto* region = static_cast<TpuHbmRegion*>(handle);
  return region != nullptr ? region->base : nullptr;
}

uint64_t TpuHbmByteSize(void* handle) {
  auto* region = static_cast<TpuHbmRegion*>(handle);
  return region != nullptr ? region->byte_size : 0;
}

int TpuHbmDeviceId(void* handle) {
  auto* region = static_cast<TpuHbmRegion*>(handle);
  return region != nullptr ? region->device_id : -1;
}

// Raw handle JSON into caller buffer; returns bytes written (excl. NUL) or
// negative error.
int TpuHbmGetRawHandle(void* handle, char* out, uint64_t capacity) {
  auto* region = static_cast<TpuHbmRegion*>(handle);
  if (region == nullptr) return TPU_HBM_ERR_HANDLE;
  char buf[512];
  int n = snprintf(buf, sizeof(buf),
                   "{\"uuid\":\"%s\",\"pid\":%d,\"device_id\":%d,"
                   "\"byte_size\":%llu,\"staging_key\":\"%s\"}",
                   region->uuid.c_str(), static_cast<int>(getpid()),
                   region->device_id,
                   static_cast<unsigned long long>(region->byte_size),
                   region->shm_key.c_str());
  if (n < 0 || static_cast<uint64_t>(n) >= capacity) {
    g_last_error = "raw handle buffer too small";
    return TPU_HBM_ERR_RANGE;
  }
  memcpy(out, buf, n + 1);
  return n;
}

int TpuHbmRegionDestroy(void* handle) {
  auto* region = static_cast<TpuHbmRegion*>(handle);
  if (region == nullptr) return TPU_HBM_ERR_HANDLE;
  if (region->base != nullptr) munmap(region->base, region->byte_size);
  if (region->fd >= 0) close(region->fd);
  int rc = TPU_HBM_OK;
  if (region->owner) {
    if (shm_unlink(region->shm_key.c_str()) < 0) {
      set_errno_error("shm_unlink failed for '" + region->shm_key + "'");
      rc = TPU_HBM_ERR_OPEN;
    }
  }
  delete region;
  return rc;
}

}  // extern "C"
