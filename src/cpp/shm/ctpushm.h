// C ABI of libctpushm — TPU device-buffer regions with a POSIX-shm host
// window (the framework's CUDA-shm replacement; Python wrapper:
// client_tpu/utils/tpu_shared_memory).  One shared declaration set so every
// consumer (the .so's own TU, native examples, sanitizer tests, non-Python
// language bindings) drifts into a compile error instead of a runtime one.
#pragma once

#include <cstdint>

extern "C" {

enum TpuHbmStatus {
  TPU_HBM_OK = 0,
  TPU_HBM_ERR_OPEN = -1,
  TPU_HBM_ERR_MAP = -2,
  TPU_HBM_ERR_RANGE = -3,
  TPU_HBM_ERR_HANDLE = -4,
  TPU_HBM_ERR_PARSE = -5,
};

// Thread-local message for the most recent failure.
const char* TpuHbmLastError();

// Create a region (fresh uuid-keyed shm window); NULL on failure.
void* TpuHbmRegionCreate(uint64_t byte_size, int device_id);
// Attach a region created elsewhere from its raw JSON handle.
void* TpuHbmRegionOpen(const char* raw_handle_json);
// Byte-window IO; TpuHbmStatus return codes.
int TpuHbmWrite(void* handle, uint64_t offset, const void* src,
                uint64_t size);
int TpuHbmRead(void* handle, uint64_t offset, void* dst, uint64_t size);
void* TpuHbmBaseAddr(void* handle);
uint64_t TpuHbmByteSize(void* handle);
int TpuHbmDeviceId(void* handle);
// Serialize the region's raw JSON handle into out (NUL-terminated).
// Returns the JSON length (> 0) on success, a TpuHbmStatus (< 0) on error.
int TpuHbmGetRawHandle(void* handle, char* out, uint64_t capacity);
int TpuHbmRegionDestroy(void* handle);

}  // extern "C"
