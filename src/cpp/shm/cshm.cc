// libcshm_tpu.so — POSIX shared-memory primitives for the client_tpu Python
// package (ctypes-loaded by client_tpu/utils/shared_memory).
//
// Role parity with the reference wheel's native libcshm.so
// (/root/reference/src/python/library/tritonclient/utils/shared_memory/
// shared_memory.cc): create/attach/read/write/destroy POSIX shm regions that
// KServe-v2 servers map by key. The C ABI here is client_tpu's own design:
// opaque region handles with explicit error codes, plus attach-only open so
// the same library serves both producer (client) and consumer (in-process
// server / tpu staging) roles.

#include <errno.h>
#include <fcntl.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdint>
#include <cstdlib>
#include <string>

namespace {

struct ShmRegion {
  std::string key;
  void* base = nullptr;
  size_t byte_size = 0;
  int fd = -1;
  bool owner = false;  // created (vs attached) — owner may unlink
};

thread_local std::string g_last_error;

void set_error(const std::string& msg) {
  g_last_error = msg + ": " + strerror(errno);
}

}  // namespace

extern "C" {

// Error codes returned by the int-returning entry points.
enum TpuShmStatus {
  TPU_SHM_OK = 0,
  TPU_SHM_ERR_OPEN = -1,
  TPU_SHM_ERR_MAP = -2,
  TPU_SHM_ERR_RANGE = -3,
  TPU_SHM_ERR_HANDLE = -4,
};

const char* TpuShmLastError() { return g_last_error.c_str(); }

// Create (or open existing) a region of byte_size under /dev/shm/<key> and map
// it read-write. Returns an opaque handle or nullptr.
void* TpuShmCreate(const char* key, uint64_t byte_size) {
  int fd = shm_open(key, O_RDWR | O_CREAT, S_IRUSR | S_IWUSR);
  if (fd < 0) {
    set_error(std::string("shm_open failed for '") + key + "'");
    return nullptr;
  }
  if (ftruncate(fd, static_cast<off_t>(byte_size)) < 0) {
    set_error(std::string("ftruncate failed for '") + key + "'");
    close(fd);
    return nullptr;
  }
  void* base =
      mmap(nullptr, byte_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    set_error(std::string("mmap failed for '") + key + "'");
    close(fd);
    return nullptr;
  }
  auto* region = new ShmRegion();
  region->key = key;
  region->base = base;
  region->byte_size = byte_size;
  region->fd = fd;
  region->owner = true;
  return region;
}

// Attach to an existing region (no create, no resize).
void* TpuShmOpen(const char* key, uint64_t byte_size, uint64_t offset) {
  int fd = shm_open(key, O_RDWR, S_IRUSR | S_IWUSR);
  if (fd < 0) {
    set_error(std::string("shm_open failed for '") + key + "'");
    return nullptr;
  }
  void* base = mmap(nullptr, byte_size + offset, PROT_READ | PROT_WRITE,
                    MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    set_error(std::string("mmap failed for '") + key + "'");
    close(fd);
    return nullptr;
  }
  auto* region = new ShmRegion();
  region->key = key;
  region->base = static_cast<char*>(base) + offset;
  region->byte_size = byte_size;
  region->fd = fd;
  region->owner = false;
  return region;
}

int TpuShmWrite(void* handle, uint64_t offset, const void* data,
                uint64_t size) {
  auto* region = static_cast<ShmRegion*>(handle);
  if (region == nullptr || region->base == nullptr) return TPU_SHM_ERR_HANDLE;
  // overflow-safe: offset + size can wrap uint64 for adversarial offsets
  if (size > region->byte_size || offset > region->byte_size - size) {
    g_last_error = "write overruns region '" + region->key + "'";
    return TPU_SHM_ERR_RANGE;
  }
  memcpy(static_cast<char*>(region->base) + offset, data, size);
  return TPU_SHM_OK;
}

int TpuShmRead(void* handle, uint64_t offset, void* dst, uint64_t size) {
  auto* region = static_cast<ShmRegion*>(handle);
  if (region == nullptr || region->base == nullptr) return TPU_SHM_ERR_HANDLE;
  // overflow-safe: offset + size can wrap uint64 for adversarial offsets
  if (size > region->byte_size || offset > region->byte_size - size) {
    g_last_error = "read overruns region '" + region->key + "'";
    return TPU_SHM_ERR_RANGE;
  }
  memcpy(dst, static_cast<char*>(region->base) + offset, size);
  return TPU_SHM_OK;
}

// Zero-copy view for numpy frombuffer over the mapping.
void* TpuShmBaseAddr(void* handle) {
  auto* region = static_cast<ShmRegion*>(handle);
  return region != nullptr ? region->base : nullptr;
}

uint64_t TpuShmByteSize(void* handle) {
  auto* region = static_cast<ShmRegion*>(handle);
  return region != nullptr ? region->byte_size : 0;
}

// Unmap and close; owner regions also shm_unlink unless keep_key is set.
int TpuShmClose(void* handle, int keep_key) {
  auto* region = static_cast<ShmRegion*>(handle);
  if (region == nullptr) return TPU_SHM_ERR_HANDLE;
  if (region->base != nullptr) {
    munmap(region->base, region->byte_size);
  }
  if (region->fd >= 0) close(region->fd);
  int rc = TPU_SHM_OK;
  if (region->owner && !keep_key) {
    if (shm_unlink(region->key.c_str()) < 0) {
      set_error("shm_unlink failed for '" + region->key + "'");
      rc = TPU_SHM_ERR_OPEN;
    }
  }
  delete region;
  return rc;
}

}  // extern "C"
