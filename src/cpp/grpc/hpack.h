// HPACK (RFC 7541) header compression for the native gRPC client's HTTP/2
// transport.  The decoder is complete (static + dynamic table, Huffman,
// table-size updates) because the peer chooses the encoding; the encoder
// stays in the always-safe subset (indexed static entries + literals
// without indexing, no Huffman) — every compliant decoder accepts it.
//
// Parity note: this replaces the HPACK engine the reference client gets for
// free from libgrpc (reference src/c++/library/grpc_client.cc links grpc++;
// this framework's native stack speaks the wire format directly).
#pragma once

#include <cstdint>
#include <string>
#include <utility>
#include <vector>

namespace ctpu {
namespace h2 {

using Header = std::pair<std::string, std::string>;

// Canonical Huffman code for header strings (RFC 7541 Appendix B).  The
// table is generated at static-init from the per-symbol code lengths: the
// RFC's code is canonical (within a length, symbols ascend; first code of a
// longer length is (last+1) shifted), so lengths fully determine it.  Init
// verifies the Kraft sum is exactly 1 and the EOS symbol lands on the
// all-ones 30-bit code — any transcription error in the lengths trips it.
class Huffman {
 public:
  static const Huffman& Get();

  // Decoded string, or false on a malformed sequence (bad EOS padding).
  bool Decode(const uint8_t* data, size_t len, std::string* out) const;
  void Encode(const std::string& in, std::string* out) const;
  size_t EncodedSize(const std::string& in) const;

 private:
  Huffman();
  struct Node {
    int16_t next[2];  // node index, or -1
    int16_t sym;      // emitted symbol, or -1 for interior
  };
  std::vector<Node> nodes_;
  uint32_t code_[257];
  uint8_t len_[257];
};

// Decoding side of one HPACK connection context (one per h2 connection
// direction; holds the peer-driven dynamic table).
class HpackDecoder {
 public:
  explicit HpackDecoder(size_t max_table_size = 4096);

  // Parse one complete header block.  Appends to *out.  Returns false on a
  // malformed block (connection error per RFC 7541 §5.2/§6.3).
  bool Decode(const uint8_t* data, size_t len, std::vector<Header>* out);

  void SetMaxTableSize(size_t n);  // from peer SETTINGS

 private:
  struct Entry {
    std::string name, value;
  };
  bool Lookup(uint64_t index, Entry* out) const;
  void Insert(const std::string& name, const std::string& value);
  void EvictFor(size_t need);

  std::vector<Entry> dynamic_;  // newest at front
  size_t dynamic_size_ = 0;     // RFC 7541 §4.1 size (bytes + 32/entry)
  size_t max_size_;             // current limit (table-size updates)
  size_t settings_cap_;         // upper bound from SETTINGS
};

// Encoding side: static-table exact/name matches + literal-without-indexing.
class HpackEncoder {
 public:
  void Encode(const std::vector<Header>& headers, std::string* out) const;
};

}  // namespace h2
}  // namespace ctpu
