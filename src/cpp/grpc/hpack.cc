#include "hpack.h"

#include <cassert>
#include <cstring>
#include <stdexcept>

namespace ctpu {
namespace h2 {

// ---------------------------------------------------------------------------
// Huffman
// ---------------------------------------------------------------------------

// RFC 7541 Appendix B code lengths, symbols 0..255 + EOS(256).  The code
// itself is derived canonically in the constructor.
static const uint8_t kHuffLen[257] = {
    /*   0- 15 */ 13, 23, 28, 28, 28, 28, 28, 28, 28, 24, 30, 28, 28, 30, 28, 28,
    /*  16- 31 */ 28, 28, 28, 28, 28, 28, 30, 28, 28, 28, 28, 28, 28, 28, 28, 28,
    /*  32- 47 */ 6, 10, 10, 12, 13, 6, 8, 11, 10, 10, 8, 11, 8, 6, 6, 6,
    /*  48- 63 */ 5, 5, 5, 6, 6, 6, 6, 6, 6, 6, 7, 8, 15, 6, 12, 10,
    /*  64- 79 */ 13, 6, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7,
    /*  80- 95 */ 7, 7, 7, 7, 7, 7, 7, 7, 8, 7, 8, 13, 19, 13, 14, 6,
    /*  96-111 */ 15, 5, 6, 5, 6, 5, 6, 6, 6, 5, 7, 7, 6, 6, 6, 5,
    /* 112-127 */ 6, 7, 6, 5, 5, 6, 7, 7, 7, 7, 7, 15, 11, 14, 13, 28,
    /* 128-143 */ 20, 22, 20, 20, 22, 22, 22, 23, 22, 23, 23, 23, 23, 23, 24, 23,
    /* 144-159 */ 24, 24, 22, 23, 24, 23, 23, 23, 23, 21, 22, 23, 22, 23, 23, 24,
    /* 160-175 */ 22, 21, 20, 22, 22, 23, 23, 21, 23, 22, 22, 24, 21, 22, 23, 23,
    /* 176-191 */ 21, 21, 22, 21, 23, 22, 23, 23, 20, 22, 22, 22, 23, 22, 22, 23,
    /* 192-207 */ 26, 26, 20, 19, 22, 23, 22, 25, 26, 26, 26, 27, 27, 26, 24, 25,
    /* 208-223 */ 19, 21, 26, 27, 27, 26, 27, 24, 21, 21, 26, 26, 28, 27, 27, 27,
    /* 224-239 */ 20, 24, 20, 21, 22, 21, 21, 23, 22, 22, 25, 25, 24, 24, 26, 23,
    /* 240-255 */ 26, 27, 26, 26, 27, 27, 27, 27, 27, 28, 27, 27, 27, 27, 27, 26,
    /* EOS 256 */ 30,
};

Huffman::Huffman()
{
  // Canonical code assignment: walk lengths ascending; within a length,
  // symbols ascend and codes increment.
  uint64_t kraft = 0;  // in units of 2^-30
  uint32_t code = 0;
  uint8_t prev_len = 0;
  for (uint8_t bits = 1; bits <= 30; ++bits) {
    for (int sym = 0; sym <= 256; ++sym) {
      if (kHuffLen[sym] != bits) continue;
      if (prev_len != 0) code = (code + 1) << (bits - prev_len);
      // first assignment: code stays 0 at the smallest length
      if (prev_len == 0) code = 0;
      prev_len = bits;
      code_[sym] = code;
      len_[sym] = bits;
      kraft += 1ull << (30 - bits);
    }
  }
  if (kraft != (1ull << 30) || code_[256] != 0x3fffffff)
    throw std::logic_error("HPACK Huffman length table is corrupt");

  // Binary decode tree (513 nodes max for a complete code over 257 syms).
  nodes_.push_back({{-1, -1}, -1});
  for (int sym = 0; sym <= 256; ++sym) {
    int n = 0;
    for (int b = len_[sym] - 1; b >= 0; --b) {
      int bit = (code_[sym] >> b) & 1;
      if (nodes_[n].next[bit] < 0) {
        nodes_[n].next[bit] = static_cast<int16_t>(nodes_.size());
        nodes_.push_back({{-1, -1}, -1});
      }
      n = nodes_[n].next[bit];
    }
    nodes_[n].sym = static_cast<int16_t>(sym);
  }
}

const Huffman&
Huffman::Get()
{
  static const Huffman instance;
  return instance;
}

bool
Huffman::Decode(const uint8_t* data, size_t len, std::string* out) const
{
  int n = 0;
  int depth = 0;  // bits consumed since last emit (for padding validation)
  bool all_ones = true;
  for (size_t i = 0; i < len; ++i) {
    for (int b = 7; b >= 0; --b) {
      int bit = (data[i] >> b) & 1;
      if (!bit) all_ones = false;
      n = nodes_[n].next[bit];
      ++depth;
      if (n < 0) return false;  // walked past a leaf: corrupt
      if (nodes_[n].sym >= 0) {
        if (nodes_[n].sym == 256) return false;  // explicit EOS is an error
        out->push_back(static_cast<char>(nodes_[n].sym));
        n = 0;
        depth = 0;
        all_ones = true;
      }
    }
  }
  // Residual bits must be a prefix of EOS (all ones), < 8 bits.
  return depth < 8 && all_ones;
}

size_t
Huffman::EncodedSize(const std::string& in) const
{
  size_t bits = 0;
  for (unsigned char c : in) bits += len_[c];
  return (bits + 7) / 8;
}

void
Huffman::Encode(const std::string& in, std::string* out) const
{
  uint64_t acc = 0;
  int nbits = 0;
  for (unsigned char c : in) {
    acc = (acc << len_[c]) | code_[c];
    nbits += len_[c];
    while (nbits >= 8) {
      nbits -= 8;
      out->push_back(static_cast<char>((acc >> nbits) & 0xff));
    }
  }
  if (nbits > 0) {  // pad with EOS prefix (all ones)
    acc = (acc << (8 - nbits)) | ((1u << (8 - nbits)) - 1);
    out->push_back(static_cast<char>(acc & 0xff));
  }
}

// ---------------------------------------------------------------------------
// Static table (RFC 7541 Appendix A)
// ---------------------------------------------------------------------------

static const Header kStaticTable[61] = {
    {":authority", ""},
    {":method", "GET"},
    {":method", "POST"},
    {":path", "/"},
    {":path", "/index.html"},
    {":scheme", "http"},
    {":scheme", "https"},
    {":status", "200"},
    {":status", "204"},
    {":status", "206"},
    {":status", "304"},
    {":status", "400"},
    {":status", "404"},
    {":status", "500"},
    {"accept-charset", ""},
    {"accept-encoding", "gzip, deflate"},
    {"accept-language", ""},
    {"accept-ranges", ""},
    {"accept", ""},
    {"access-control-allow-origin", ""},
    {"age", ""},
    {"allow", ""},
    {"authorization", ""},
    {"cache-control", ""},
    {"content-disposition", ""},
    {"content-encoding", ""},
    {"content-language", ""},
    {"content-length", ""},
    {"content-location", ""},
    {"content-range", ""},
    {"content-type", ""},
    {"cookie", ""},
    {"date", ""},
    {"etag", ""},
    {"expect", ""},
    {"expires", ""},
    {"from", ""},
    {"host", ""},
    {"if-match", ""},
    {"if-modified-since", ""},
    {"if-none-match", ""},
    {"if-range", ""},
    {"if-unmodified-since", ""},
    {"last-modified", ""},
    {"link", ""},
    {"location", ""},
    {"max-forwards", ""},
    {"proxy-authenticate", ""},
    {"proxy-authorization", ""},
    {"range", ""},
    {"referer", ""},
    {"refresh", ""},
    {"retry-after", ""},
    {"server", ""},
    {"set-cookie", ""},
    {"strict-transport-security", ""},
    {"transfer-encoding", ""},
    {"user-agent", ""},
    {"vary", ""},
    {"via", ""},
    {"www-authenticate", ""},
};

// ---------------------------------------------------------------------------
// Primitive integer / string codecs (RFC 7541 §5)
// ---------------------------------------------------------------------------

static void
EncodeInt(uint64_t value, uint8_t prefix_bits, uint8_t first_byte_flags,
          std::string* out)
{
  const uint64_t max_prefix = (1u << prefix_bits) - 1;
  if (value < max_prefix) {
    out->push_back(static_cast<char>(first_byte_flags | value));
    return;
  }
  out->push_back(static_cast<char>(first_byte_flags | max_prefix));
  value -= max_prefix;
  while (value >= 128) {
    out->push_back(static_cast<char>(0x80 | (value & 0x7f)));
    value >>= 7;
  }
  out->push_back(static_cast<char>(value));
}

static bool
DecodeInt(const uint8_t* data, size_t len, size_t* pos, uint8_t prefix_bits,
          uint64_t* value)
{
  if (*pos >= len) return false;
  const uint64_t max_prefix = (1u << prefix_bits) - 1;
  uint64_t v = data[(*pos)++] & max_prefix;
  if (v < max_prefix) {
    *value = v;
    return true;
  }
  int shift = 0;
  while (true) {
    if (*pos >= len || shift > 56) return false;
    uint8_t b = data[(*pos)++];
    v += static_cast<uint64_t>(b & 0x7f) << shift;
    shift += 7;
    if (!(b & 0x80)) break;
  }
  *value = v;
  return true;
}

static bool
DecodeString(const uint8_t* data, size_t len, size_t* pos, std::string* out)
{
  if (*pos >= len) return false;
  const bool huffman = (data[*pos] & 0x80) != 0;
  uint64_t slen;
  if (!DecodeInt(data, len, pos, 7, &slen)) return false;
  if (*pos + slen > len) return false;
  out->clear();
  bool ok = true;
  if (huffman) {
    ok = Huffman::Get().Decode(data + *pos, slen, out);
  } else {
    out->assign(reinterpret_cast<const char*>(data + *pos), slen);
  }
  *pos += slen;
  return ok;
}

static void
EncodeString(const std::string& s, std::string* out)
{
  EncodeInt(s.size(), 7, 0x00, out);  // plain, never Huffman on send
  out->append(s);
}

// ---------------------------------------------------------------------------
// HpackDecoder
// ---------------------------------------------------------------------------

HpackDecoder::HpackDecoder(size_t max_table_size)
    : max_size_(max_table_size), settings_cap_(max_table_size)
{
}

void
HpackDecoder::SetMaxTableSize(size_t n)
{
  settings_cap_ = n;
  if (max_size_ > n) {
    max_size_ = n;
    EvictFor(0);
  }
}

bool
HpackDecoder::Lookup(uint64_t index, Entry* out) const
{
  if (index == 0) return false;
  if (index <= 61) {
    out->name = kStaticTable[index - 1].first;
    out->value = kStaticTable[index - 1].second;
    return true;
  }
  const size_t d = index - 62;
  if (d >= dynamic_.size()) return false;
  *out = dynamic_[d];
  return true;
}

void
HpackDecoder::EvictFor(size_t need)
{
  while (!dynamic_.empty() && dynamic_size_ + need > max_size_) {
    const Entry& e = dynamic_.back();
    dynamic_size_ -= e.name.size() + e.value.size() + 32;
    dynamic_.pop_back();
  }
}

void
HpackDecoder::Insert(const std::string& name, const std::string& value)
{
  const size_t sz = name.size() + value.size() + 32;
  EvictFor(sz);
  if (sz > max_size_) return;  // too large: table drains empty (RFC §4.4)
  dynamic_.insert(dynamic_.begin(), {name, value});
  dynamic_size_ += sz;
}

bool
HpackDecoder::Decode(const uint8_t* data, size_t len, std::vector<Header>* out)
{
  size_t pos = 0;
  bool field_seen = false;  // §4.2: size updates only at block start
  while (pos < len) {
    const uint8_t b = data[pos];
    if (b & 0x80) {  // indexed header field (§6.1)
      uint64_t index;
      if (!DecodeInt(data, len, &pos, 7, &index)) return false;
      Entry e;
      if (!Lookup(index, &e)) return false;
      out->emplace_back(std::move(e.name), std::move(e.value));
      field_seen = true;
    } else if ((b & 0xe0) == 0x20) {  // dynamic table size update (§6.3)
      if (field_seen) return false;  // RFC 7541 §4.2: must precede fields
      uint64_t sz;
      if (!DecodeInt(data, len, &pos, 5, &sz)) return false;
      if (sz > settings_cap_) return false;
      max_size_ = sz;
      EvictFor(0);
    } else {
      // Literal: incremental indexing (01xxxxxx, 6-bit name index),
      // without indexing (0000xxxx), never indexed (0001xxxx).
      const bool incremental = (b & 0xc0) == 0x40;
      const uint8_t prefix = incremental ? 6 : 4;
      uint64_t name_index;
      if (!DecodeInt(data, len, &pos, prefix, &name_index)) return false;
      std::string name;
      if (name_index > 0) {
        Entry e;
        if (!Lookup(name_index, &e)) return false;
        name = std::move(e.name);
      } else {
        if (!DecodeString(data, len, &pos, &name)) return false;
      }
      std::string value;
      if (!DecodeString(data, len, &pos, &value)) return false;
      if (incremental) Insert(name, value);
      out->emplace_back(std::move(name), std::move(value));
      field_seen = true;
    }
  }
  return true;
}

// ---------------------------------------------------------------------------
// HpackEncoder
// ---------------------------------------------------------------------------

void
HpackEncoder::Encode(const std::vector<Header>& headers, std::string* out) const
{
  for (const Header& h : headers) {
    int exact = 0, name_only = 0;
    for (int i = 0; i < 61; ++i) {
      if (kStaticTable[i].first != h.first) continue;
      if (name_only == 0) name_only = i + 1;
      if (kStaticTable[i].second == h.second) {
        exact = i + 1;
        break;
      }
    }
    if (exact) {
      EncodeInt(exact, 7, 0x80, out);  // indexed (§6.1)
    } else {
      // literal without indexing (§6.2.2), static name ref when available
      EncodeInt(name_only, 4, 0x00, out);
      if (name_only == 0) EncodeString(h.first, out);
      EncodeString(h.second, out);
    }
  }
}

}  // namespace h2
}  // namespace ctpu
