// Minimal-but-correct HTTP/2 (RFC 7540) client connection for the native
// gRPC transport: h2c prior-knowledge over TCP, full HPACK, flow control,
// and stream multiplexing driven by one reactor thread per connection.
//
// Threading model (reference grpc_client.cc:1484's completion-queue thread,
// re-shaped): a single reader thread owns the socket's receive side and
// wakes waiters per stream; writers serialize on a write mutex.  Sync calls
// are "start stream + wait"; async calls register a completion callback.
// Hundreds of in-flight requests share one connection and one thread — no
// thread-per-request (the weakness VERDICT r02 called out in the HTTP
// client's AsyncInfer pool).
#pragma once

#include <condition_variable>
#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "../client/common.h"
#include "../client/transport.h"
#include "hpack.h"

namespace ctpu {
namespace h2 {

// One HTTP/2 stream's receive-side state.  Guarded by the connection mutex.
struct Stream {
  int32_t id = 0;
  std::vector<Header> headers;      // initial HEADERS block
  std::vector<Header> trailers;     // trailing HEADERS block
  std::string data;                 // DATA bytes as received
  size_t consumed = 0;              // bytes the user has taken from `data`
  bool headers_done = false;
  bool end_stream = false;          // peer half-closed
  bool reset = false;               // RST_STREAM received
  uint32_t rst_code = 0;
  int64_t send_window = 0;          // stream-level credit for our DATA
  // Fires (under no locks) whenever receive-side state advances; used by the
  // async gRPC layer to re-examine the stream.
  std::function<void()> on_event;
};

class H2Connection {
 public:
  H2Connection() = default;
  ~H2Connection();
  H2Connection(const H2Connection&) = delete;
  H2Connection& operator=(const H2Connection&) = delete;

  // TCP connect + h2c preface/SETTINGS exchange; spawns the reader thread.
  Error Connect(
      const std::string& host, int port, int64_t connect_timeout_ms = 10000);
  // Same, over a caller-supplied byte transport (the TLS seam —
  // src/cpp/client/transport.h): the transport's Connect is called here.
  Error ConnectWith(
      std::unique_ptr<ByteTransport> transport, const std::string& host,
      int port, int64_t connect_timeout_ms = 10000);
  void Close();
  bool IsOpen();

  // Open a stream with the given request headers.  Returns the stream id.
  Error StartStream(
      const std::vector<Header>& headers, bool end_stream, int32_t* sid,
      std::function<void()> on_event = nullptr);
  // Write DATA respecting both flow-control windows; blocks until window
  // opens (reader thread keeps running, so this cannot self-deadlock).
  // deadline_ms <= 0 waits forever; on expiry the send fails (caller resets
  // the stream) so a stalled peer cannot hang a deadline-bearing request.
  Error SendData(
      int32_t sid, const uint8_t* buf, size_t len, bool end_stream,
      int64_t deadline_ms = 0);
  // Abort one stream.
  void ResetStream(int32_t sid, uint32_t error_code);

  // Blocking waits, all driven by the reader thread.  deadline_ms <= 0 means
  // wait forever.  They return the failure when the stream/connection dies.
  Error WaitHeaders(int32_t sid, int64_t deadline_ms);
  // Blocks until at least `min_bytes` are available, the peer half-closes,
  // or the deadline passes; appends what is available to *out.
  Error ReadData(
      int32_t sid, size_t min_bytes, std::string* out, int64_t deadline_ms);
  Error WaitEndStream(int32_t sid, int64_t deadline_ms);

  // Non-blocking state peeks for the async layer (mutex-guarded copies).
  std::shared_ptr<Stream> GetStream(int32_t sid);
  void ForgetStream(int32_t sid);  // release finished stream state
  Error ConnectionError();

  // PING keepalive (reference grpc_client.h:62-82 KeepAliveOptions): a
  // probe thread sends PING every interval_ms; a probe unacked for
  // timeout_ms fails the connection (every waiter wakes with the error).
  void EnableKeepAlive(int64_t interval_ms, int64_t timeout_ms);
  // One synchronous PING round trip — liveness check / RTT probe.
  Error Ping(int64_t timeout_ms);

 private:
  Error WriteAll(const uint8_t* buf, size_t len);
  Error WriteFrame(
      uint8_t type, uint8_t flags, int32_t sid, const std::string& payload);
  void ReaderLoop();
  void HandleFrame(
      uint8_t type, uint8_t flags, int32_t sid, std::string payload);
  void FailConnection(const std::string& msg);
  std::shared_ptr<Stream> StreamLocked(int32_t sid);

  std::unique_ptr<ByteTransport> transport_;
  std::thread reader_;
  std::thread keepalive_;
  std::mutex mu_;                  // stream table + windows + hpack_rx_
  std::condition_variable cv_;
  std::mutex write_mu_;            // serializes socket writes + hpack_tx_
  std::map<int32_t, std::shared_ptr<Stream>> streams_;
  HpackDecoder hpack_rx_;
  HpackEncoder hpack_tx_;
  // Header-block accumulation (HEADERS..CONTINUATION run).
  int32_t hdr_stream_ = 0;
  std::string hdr_block_;
  bool hdr_end_stream_ = false;
  // RFC 7540 §6.10: between a HEADERS/CONTINUATION without END_HEADERS and
  // the block's end, only CONTINUATION for the same stream is legal.
  bool expect_continuation_ = false;
  uint64_t ping_acks_ = 0;  // PING ACK count (guarded by mu_)
  int64_t keepalive_interval_ms_ = 0;
  int64_t keepalive_timeout_ms_ = 0;
  bool keepalive_stop_ = false;

  int64_t conn_send_window_ = 65535;
  uint32_t peer_max_frame_ = 16384;
  uint32_t peer_initial_window_ = 65535;
  int32_t next_stream_id_ = 1;
  bool open_ = false;
  bool goaway_ = false;
  Error conn_err_;
};

}  // namespace h2
}  // namespace ctpu
