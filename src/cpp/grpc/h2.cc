#include "h2.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstring>

namespace ctpu {
namespace h2 {

namespace {

constexpr uint8_t kData = 0x0;
constexpr uint8_t kHeaders = 0x1;
constexpr uint8_t kRstStream = 0x3;
constexpr uint8_t kSettings = 0x4;
constexpr uint8_t kPushPromise = 0x5;
constexpr uint8_t kPing = 0x6;
constexpr uint8_t kGoaway = 0x7;
constexpr uint8_t kWindowUpdate = 0x8;
constexpr uint8_t kContinuation = 0x9;

constexpr uint8_t kFlagEndStream = 0x1;
constexpr uint8_t kFlagAck = 0x1;
constexpr uint8_t kFlagEndHeaders = 0x4;
constexpr uint8_t kFlagPadded = 0x8;
constexpr uint8_t kFlagPriority = 0x20;

// Our receive-side windows.  We buffer in user space and replenish
// immediately, so these just need to cover the bandwidth-delay product of
// large tensor responses.
constexpr uint32_t kInitialWindow = 8 * 1024 * 1024;
constexpr uint32_t kConnWindowBoost = 64 * 1024 * 1024;

const char kPreface[] = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";

void
Put24(std::string* s, uint32_t v)
{
  s->push_back(static_cast<char>((v >> 16) & 0xff));
  s->push_back(static_cast<char>((v >> 8) & 0xff));
  s->push_back(static_cast<char>(v & 0xff));
}

void
Put32(std::string* s, uint32_t v)
{
  s->push_back(static_cast<char>((v >> 24) & 0xff));
  s->push_back(static_cast<char>((v >> 16) & 0xff));
  s->push_back(static_cast<char>((v >> 8) & 0xff));
  s->push_back(static_cast<char>(v & 0xff));
}

void
Put16(std::string* s, uint16_t v)
{
  s->push_back(static_cast<char>((v >> 8) & 0xff));
  s->push_back(static_cast<char>(v & 0xff));
}

uint32_t
Get32(const uint8_t* p)
{
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

std::chrono::steady_clock::time_point
Deadline(int64_t deadline_ms)
{
  if (deadline_ms <= 0)
    return std::chrono::steady_clock::time_point::max();
  return std::chrono::steady_clock::now() +
         std::chrono::milliseconds(deadline_ms);
}

}  // namespace

H2Connection::~H2Connection() { Close(); }

Error
H2Connection::Connect(
    const std::string& host, int port, int64_t connect_timeout_ms)
{
  return ConnectWith(MakeTcpTransport(), host, port, connect_timeout_ms);
}

Error
H2Connection::ConnectWith(
    std::unique_ptr<ByteTransport> transport, const std::string& host,
    int port, int64_t connect_timeout_ms)
{
  Error cerr = transport->Connect(host, port, connect_timeout_ms);
  if (!cerr.IsOk()) return cerr;
  transport_ = std::move(transport);

  // Client preface: magic + SETTINGS (push off, big stream windows), then a
  // connection-level WINDOW_UPDATE so large responses never stall.
  std::string settings;
  Put16(&settings, 0x2);  // ENABLE_PUSH
  Put32(&settings, 0);
  Put16(&settings, 0x4);  // INITIAL_WINDOW_SIZE
  Put32(&settings, kInitialWindow);
  std::string buf(kPreface, sizeof(kPreface) - 1);
  Put24(&buf, settings.size());
  buf.push_back(kSettings);
  buf.push_back(0);
  Put32(&buf, 0);
  buf += settings;
  Put24(&buf, 4);
  buf.push_back(kWindowUpdate);
  buf.push_back(0);
  Put32(&buf, 0);
  Put32(&buf, kConnWindowBoost - 65535);
  Error err =
      WriteAll(reinterpret_cast<const uint8_t*>(buf.data()), buf.size());
  if (!err.IsOk()) {
    transport_->Close();
    transport_.reset();
    return err;
  }
  open_ = true;
  reader_ = std::thread(&H2Connection::ReaderLoop, this);
  return Error::Success();
}

bool
H2Connection::IsOpen()
{
  std::lock_guard<std::mutex> lk(mu_);
  return open_ && conn_err_.IsOk();
}

void
H2Connection::EnableKeepAlive(int64_t interval_ms, int64_t timeout_ms)
{
  std::lock_guard<std::mutex> lk(mu_);
  if (keepalive_.joinable() || !open_) return;
  keepalive_interval_ms_ = interval_ms > 0 ? interval_ms : 10000;
  keepalive_timeout_ms_ = timeout_ms > 0 ? timeout_ms : 20000;
  keepalive_ = std::thread([this] {
    while (true) {
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait_for(
            lk, std::chrono::milliseconds(keepalive_interval_ms_),
            [&] { return keepalive_stop_ || !conn_err_.IsOk(); });
        if (keepalive_stop_ || !conn_err_.IsOk() || !open_) return;
      }
      Error err = Ping(keepalive_timeout_ms_);
      if (!err.IsOk()) {
        FailConnection("keepalive ping timed out: " + err.Message());
        return;
      }
    }
  });
}

Error
H2Connection::Ping(int64_t timeout_ms)
{
  uint64_t acked_before;
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (!conn_err_.IsOk()) return conn_err_;
    if (!open_) return Error("h2 connection closed");
    acked_before = ping_acks_;
  }
  std::string payload(8, '\0');
  Error err = WriteFrame(kPing, 0, 0, payload);
  if (!err.IsOk()) return err;
  std::unique_lock<std::mutex> lk(mu_);
  const bool got = cv_.wait_for(
      lk, std::chrono::milliseconds(timeout_ms > 0 ? timeout_ms : 20000),
      [&] {
        return ping_acks_ != acked_before || !conn_err_.IsOk() ||
               keepalive_stop_;
      });
  if (!conn_err_.IsOk()) return conn_err_;
  if (keepalive_stop_) return Error("h2 connection closing");
  if (!got) return Error("timeout waiting for PING ack");
  return Error::Success();
}

void
H2Connection::Close()
{
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (!open_ && transport_ == nullptr) return;
    open_ = false;
    keepalive_stop_ = true;
  }
  cv_.notify_all();
  if (keepalive_.joinable()) keepalive_.join();
  if (transport_ != nullptr) {
    // GOAWAY then hard shutdown; the reader thread unblocks on EOF/EPIPE.
    std::string payload;
    Put32(&payload, 0);  // last stream id
    Put32(&payload, 0);  // NO_ERROR
    WriteFrame(kGoaway, 0, 0, payload);
    transport_->Shutdown();
  }
  if (reader_.joinable()) reader_.join();
  if (transport_ != nullptr) {
    transport_->Close();
    transport_.reset();
  }
}

Error
H2Connection::WriteAll(const uint8_t* buf, size_t len)
{
  size_t off = 0;
  while (off < len) {
    if (transport_ == nullptr) return Error("h2 connection closed");
    const ssize_t n = transport_->Write(buf + off, len - off);
    if (n <= 0) {
      return Error("h2 connection write failed: " +
                   std::string(std::strerror(errno)));
    }
    off += static_cast<size_t>(n);
  }
  return Error::Success();
}

Error
H2Connection::WriteFrame(
    uint8_t type, uint8_t flags, int32_t sid, const std::string& payload)
{
  std::string hdr;
  Put24(&hdr, payload.size());
  hdr.push_back(type);
  hdr.push_back(flags);
  Put32(&hdr, static_cast<uint32_t>(sid));
  std::lock_guard<std::mutex> lk(write_mu_);
  Error err =
      WriteAll(reinterpret_cast<const uint8_t*>(hdr.data()), hdr.size());
  if (!err.IsOk()) return err;
  if (payload.empty()) return Error::Success();
  return WriteAll(
      reinterpret_cast<const uint8_t*>(payload.data()), payload.size());
}

std::shared_ptr<Stream>
H2Connection::StreamLocked(int32_t sid)
{
  auto it = streams_.find(sid);
  return it == streams_.end() ? nullptr : it->second;
}

std::shared_ptr<Stream>
H2Connection::GetStream(int32_t sid)
{
  std::lock_guard<std::mutex> lk(mu_);
  return StreamLocked(sid);
}

void
H2Connection::ForgetStream(int32_t sid)
{
  std::lock_guard<std::mutex> lk(mu_);
  streams_.erase(sid);
}

Error
H2Connection::ConnectionError()
{
  std::lock_guard<std::mutex> lk(mu_);
  return conn_err_;
}

Error
H2Connection::StartStream(
    const std::vector<Header>& headers, bool end_stream, int32_t* sid,
    std::function<void()> on_event)
{
  auto stream = std::make_shared<Stream>();
  stream->on_event = std::move(on_event);
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (!open_) return Error("h2 connection is closed");
    if (!conn_err_.IsOk()) return conn_err_;
    if (goaway_) return Error("h2 connection is draining (GOAWAY)");
    stream->id = next_stream_id_;
    next_stream_id_ += 2;
    stream->send_window = peer_initial_window_;
    streams_[stream->id] = stream;
  }
  *sid = stream->id;

  // HPACK encoding shares write_mu_ with the frame writes: header blocks
  // must land on the wire in encode order.
  std::string block;
  std::lock_guard<std::mutex> lk(write_mu_);
  hpack_tx_.Encode(headers, &block);
  size_t off = 0;
  bool first = true;
  do {
    const size_t n = std::min<size_t>(block.size() - off, peer_max_frame_);
    const bool last = (off + n == block.size());
    std::string hdr;
    Put24(&hdr, n);
    hdr.push_back(first ? kHeaders : kContinuation);
    uint8_t flags = last ? kFlagEndHeaders : 0;
    if (first && end_stream) flags |= kFlagEndStream;
    hdr.push_back(flags);
    Put32(&hdr, static_cast<uint32_t>(stream->id));
    Error err =
        WriteAll(reinterpret_cast<const uint8_t*>(hdr.data()), hdr.size());
    if (err.IsOk() && n > 0) {
      err = WriteAll(
          reinterpret_cast<const uint8_t*>(block.data() + off), n);
    }
    if (!err.IsOk()) return err;
    off += n;
    first = false;
  } while (off < block.size());
  return Error::Success();
}

Error
H2Connection::SendData(
    int32_t sid, const uint8_t* buf, size_t len, bool end_stream,
    int64_t deadline_ms)
{
  const auto dl = Deadline(deadline_ms);
  size_t off = 0;
  while (off < len || (end_stream && len == 0)) {
    size_t budget;
    {
      std::unique_lock<std::mutex> lk(mu_);
      auto stream = StreamLocked(sid);
      if (stream == nullptr) return Error("h2 stream closed");
      if (!cv_.wait_until(lk, dl, [&] {
            return !conn_err_.IsOk() || stream->reset ||
                   stream->end_stream ||
                   (conn_send_window_ > 0 && stream->send_window > 0) ||
                   (end_stream && len == 0);
          })) {
        return Error("timeout waiting for send window");
      }
      if (!conn_err_.IsOk()) return conn_err_;
      if (stream->reset)
        return Error(
            "h2 stream reset by peer (code " +
            std::to_string(stream->rst_code) + ")");
      if (stream->end_stream && off < len) {
        // Peer half-closed without RST (e.g. a trailers-only early
        // response, auth reject, RESOURCE_EXHAUSTED): the RPC is decided
        // and the rest of the body is moot.  Stop sending and report
        // success for the sent prefix so the caller reads the REAL
        // grpc-status from the trailers already buffered on the stream —
        // erroring here would mask it (and a deadline-less caller whose
        // window never reopens would otherwise block forever).
        return Error::Success();
      }
      budget = std::min<size_t>(
          {len - off, static_cast<size_t>(std::max<int64_t>(
                          0, std::min(conn_send_window_,
                                      stream->send_window))),
           peer_max_frame_});
      if (len != 0) {
        conn_send_window_ -= budget;
        stream->send_window -= budget;
      }
    }
    const bool last = (off + budget == len);
    std::string payload(
        reinterpret_cast<const char*>(buf + off), budget);
    Error err = WriteFrame(
        kData, (last && end_stream) ? kFlagEndStream : 0, sid, payload);
    if (!err.IsOk()) return err;
    off += budget;
    if (last) break;
  }
  return Error::Success();
}

void
H2Connection::ResetStream(int32_t sid, uint32_t error_code)
{
  std::string payload;
  Put32(&payload, error_code);
  WriteFrame(kRstStream, 0, sid, payload);
  std::lock_guard<std::mutex> lk(mu_);
  auto stream = StreamLocked(sid);
  if (stream != nullptr) {
    stream->reset = true;
    stream->rst_code = error_code;
    stream->end_stream = true;
  }
  cv_.notify_all();
}

Error
H2Connection::WaitHeaders(int32_t sid, int64_t deadline_ms)
{
  const auto dl = Deadline(deadline_ms);
  std::unique_lock<std::mutex> lk(mu_);
  auto stream = StreamLocked(sid);
  if (stream == nullptr) return Error("h2 stream closed");
  if (!cv_.wait_until(lk, dl, [&] {
        return stream->headers_done || stream->end_stream || stream->reset ||
               !conn_err_.IsOk();
      })) {
    return Error("timeout waiting for response headers");
  }
  if (!conn_err_.IsOk()) return conn_err_;
  if (stream->reset)
    return Error(
        "h2 stream reset by peer (code " + std::to_string(stream->rst_code) +
        ")");
  return Error::Success();
}

Error
H2Connection::ReadData(
    int32_t sid, size_t min_bytes, std::string* out, int64_t deadline_ms)
{
  const auto dl = Deadline(deadline_ms);
  std::unique_lock<std::mutex> lk(mu_);
  auto stream = StreamLocked(sid);
  if (stream == nullptr) return Error("h2 stream closed");
  if (!cv_.wait_until(lk, dl, [&] {
        return stream->data.size() - stream->consumed >= min_bytes ||
               stream->end_stream || stream->reset || !conn_err_.IsOk();
      })) {
    return Error("timeout waiting for response data");
  }
  if (!conn_err_.IsOk()) return conn_err_;
  if (stream->reset)
    return Error(
        "h2 stream reset by peer (code " + std::to_string(stream->rst_code) +
        ")");
  out->append(stream->data, stream->consumed, std::string::npos);
  stream->consumed = stream->data.size();
  return Error::Success();
}

Error
H2Connection::WaitEndStream(int32_t sid, int64_t deadline_ms)
{
  const auto dl = Deadline(deadline_ms);
  std::unique_lock<std::mutex> lk(mu_);
  auto stream = StreamLocked(sid);
  if (stream == nullptr) return Error("h2 stream closed");
  if (!cv_.wait_until(lk, dl, [&] {
        return stream->end_stream || stream->reset || !conn_err_.IsOk();
      })) {
    return Error("timeout waiting for response");
  }
  if (!conn_err_.IsOk()) return conn_err_;
  if (stream->reset)
    return Error(
        "h2 stream reset by peer (code " + std::to_string(stream->rst_code) +
        ")");
  return Error::Success();
}

void
H2Connection::FailConnection(const std::string& msg)
{
  std::vector<std::function<void()>> callbacks;
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (conn_err_.IsOk()) conn_err_ = Error(msg);
    for (auto& kv : streams_) {
      kv.second->end_stream = true;
      if (kv.second->on_event) callbacks.push_back(kv.second->on_event);
    }
  }
  cv_.notify_all();
  for (auto& cb : callbacks) cb();
}

void
H2Connection::ReaderLoop()
{
  std::string buf;
  uint8_t hdr[9];
  while (true) {
    // frame header
    size_t got = 0;
    while (got < 9) {
      const ssize_t n = transport_->Read(hdr + got, 9 - got);
      if (n <= 0) {
        FailConnection(
            got == 0 && n == 0 ? "h2 connection closed by peer"
                               : "h2 connection read failed");
        return;
      }
      got += static_cast<size_t>(n);
    }
    const uint32_t len =
        (uint32_t(hdr[0]) << 16) | (uint32_t(hdr[1]) << 8) | uint32_t(hdr[2]);
    const uint8_t type = hdr[3];
    const uint8_t flags = hdr[4];
    const int32_t sid = static_cast<int32_t>(Get32(hdr + 5) & 0x7fffffff);
    if (len > 16 * 1024 * 1024) {
      FailConnection("h2 frame exceeds sane size");
      return;
    }
    buf.resize(len);
    size_t off = 0;
    while (off < len) {
      const ssize_t n = transport_->Read(&buf[off], len - off);
      if (n <= 0) {
        FailConnection("h2 connection read failed mid-frame");
        return;
      }
      off += static_cast<size_t>(n);
    }
    HandleFrame(type, flags, sid, std::move(buf));
    buf.clear();
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (!conn_err_.IsOk()) return;
    }
  }
}

void
H2Connection::HandleFrame(
    uint8_t type, uint8_t flags, int32_t sid, std::string payload)
{
  // RFC 7540 §6.10: an unterminated header block admits ONLY CONTINUATION
  // frames for the same stream; anything else is a connection error.  (A
  // CONTINUATION for a different stream is also caught below.)
  if (expect_continuation_ && type != kContinuation)
    return FailConnection("frame interleaved in header block (§6.10)");
  switch (type) {
    case kData: {
      size_t start = 0, end = payload.size();
      if (flags & kFlagPadded) {
        if (payload.empty()) return FailConnection("malformed DATA");
        const uint8_t pad = payload[0];
        if (1u + pad > payload.size())
          return FailConnection("malformed DATA padding");
        start = 1;
        end = payload.size() - pad;
      }
      std::function<void()> cb;
      {
        std::lock_guard<std::mutex> lk(mu_);
        auto stream = StreamLocked(sid);
        if (stream != nullptr) {
          stream->data.append(payload, start, end - start);
          if (flags & kFlagEndStream) stream->end_stream = true;
          cb = stream->on_event;
        }
      }
      // Replenish both windows for the whole frame (padding included).
      if (!payload.empty()) {
        std::string wu;
        Put32(&wu, payload.size());
        WriteFrame(kWindowUpdate, 0, 0, wu);
        bool stream_open;
        {
          std::lock_guard<std::mutex> lk(mu_);
          auto stream = StreamLocked(sid);
          stream_open = stream != nullptr && !stream->end_stream;
        }
        if (stream_open) WriteFrame(kWindowUpdate, 0, sid, wu);
      }
      cv_.notify_all();
      if (cb) cb();
      break;
    }
    case kHeaders:
    case kContinuation: {
      size_t start = 0, end = payload.size();
      if (type == kHeaders) {
        if (flags & kFlagPadded) {
          if (payload.empty()) return FailConnection("malformed HEADERS");
          const uint8_t pad = payload[0];
          if (1u + pad > payload.size())
            return FailConnection("malformed HEADERS padding");
          start = 1;
          end = payload.size() - pad;
        }
        if (flags & kFlagPriority) {
          if (start + 5 > end)
            return FailConnection("malformed HEADERS priority");
          start += 5;
        }
        hdr_stream_ = sid;
        hdr_block_.clear();
        hdr_end_stream_ = (flags & kFlagEndStream) != 0;
      } else if (sid != hdr_stream_) {
        return FailConnection("CONTINUATION for wrong stream");
      }
      hdr_block_.append(payload, start, end - start);
      if (!(flags & kFlagEndHeaders)) {
        expect_continuation_ = true;
        break;
      }
      expect_continuation_ = false;
      std::vector<Header> decoded;
      std::function<void()> cb;
      {
        std::lock_guard<std::mutex> lk(mu_);
        if (!hpack_rx_.Decode(
                reinterpret_cast<const uint8_t*>(hdr_block_.data()),
                hdr_block_.size(), &decoded)) {
          conn_err_ = Error("HPACK decode failed (COMPRESSION_ERROR)");
          cv_.notify_all();
          return;
        }
        auto stream = StreamLocked(hdr_stream_);
        if (stream != nullptr) {
          if (!stream->headers_done) {
            stream->headers = std::move(decoded);
            stream->headers_done = true;
          } else {
            stream->trailers = std::move(decoded);
          }
          if (hdr_end_stream_) stream->end_stream = true;
          cb = stream->on_event;
        }
      }
      hdr_block_.clear();
      cv_.notify_all();
      if (cb) cb();
      break;
    }
    case kSettings: {
      if (flags & kFlagAck) break;
      std::vector<std::function<void()>> callbacks;
      {
        std::lock_guard<std::mutex> lk(mu_);
        for (size_t i = 0; i + 6 <= payload.size(); i += 6) {
          const uint16_t id =
              (uint16_t(uint8_t(payload[i])) << 8) | uint8_t(payload[i + 1]);
          const uint32_t value =
              Get32(reinterpret_cast<const uint8_t*>(payload.data()) + i + 2);
          switch (id) {
            case 0x1:
              // HEADER_TABLE_SIZE constrains the *encoder* toward the peer
              // (RFC 7540 §6.5.2); ours never uses the dynamic table, so
              // nothing to do.  Our decoder's cap is OUR advertised value
              // (default 4096), not the peer's.
              break;
            case 0x4: {  // INITIAL_WINDOW_SIZE: delta applies to open streams
              const int64_t delta =
                  int64_t(value) - int64_t(peer_initial_window_);
              peer_initial_window_ = value;
              for (auto& kv : streams_) kv.second->send_window += delta;
              break;
            }
            case 0x5:  // MAX_FRAME_SIZE
              peer_max_frame_ = value;
              break;
            default:
              break;
          }
        }
      }
      WriteFrame(kSettings, kFlagAck, 0, "");
      cv_.notify_all();
      break;
    }
    case kPing:
      if (!(flags & kFlagAck) && payload.size() == 8) {
        WriteFrame(kPing, kFlagAck, 0, payload);
      } else if (flags & kFlagAck) {
        std::lock_guard<std::mutex> lk(mu_);
        ping_acks_++;
        cv_.notify_all();
      }
      break;
    case kWindowUpdate: {
      if (payload.size() != 4) return FailConnection("malformed WINDOW_UPDATE");
      const uint32_t inc = Get32(
          reinterpret_cast<const uint8_t*>(payload.data())) & 0x7fffffff;
      {
        std::lock_guard<std::mutex> lk(mu_);
        if (sid == 0) {
          conn_send_window_ += inc;
        } else {
          auto stream = StreamLocked(sid);
          if (stream != nullptr) stream->send_window += inc;
        }
      }
      cv_.notify_all();
      break;
    }
    case kRstStream: {
      if (payload.size() != 4) return FailConnection("malformed RST_STREAM");
      std::function<void()> cb;
      {
        std::lock_guard<std::mutex> lk(mu_);
        auto stream = StreamLocked(sid);
        if (stream != nullptr) {
          stream->reset = true;
          stream->end_stream = true;
          stream->rst_code =
              Get32(reinterpret_cast<const uint8_t*>(payload.data()));
          cb = stream->on_event;
        }
      }
      cv_.notify_all();
      if (cb) cb();
      break;
    }
    case kGoaway: {
      std::vector<std::function<void()>> callbacks;
      {
        std::lock_guard<std::mutex> lk(mu_);
        goaway_ = true;
        const int32_t last =
            payload.size() >= 4
                ? static_cast<int32_t>(
                      Get32(reinterpret_cast<const uint8_t*>(payload.data())) &
                      0x7fffffff)
                : 0;
        // Streams the server never processed die now; processed ones finish.
        for (auto& kv : streams_) {
          if (kv.first > last && !kv.second->end_stream) {
            kv.second->reset = true;
            kv.second->rst_code = 0x7;  // REFUSED_STREAM
            kv.second->end_stream = true;
            if (kv.second->on_event) callbacks.push_back(kv.second->on_event);
          }
        }
      }
      cv_.notify_all();
      for (auto& cb : callbacks) cb();
      break;
    }
    case kPushPromise:
      FailConnection("unexpected PUSH_PROMISE (push is disabled)");
      break;
    default:
      break;  // unknown frame types are ignored (RFC 7540 §4.1)
  }
}

}  // namespace h2
}  // namespace ctpu
