// Java bindings for the framework's C shared-memory ABI — the analog of the
// reference's java-api-bindings (which JavaCPP-binds the server's C API,
// src/java-api-bindings/scripts/install_dependencies_and_build.sh).  This
// framework's bindable C seam is the shared-memory transport
// (src/cpp/shm/cshm.cc `TpuShm*` exports in libcshm_tpu.so): a JVM process
// maps the same POSIX region a client_tpu server/client uses and exchanges
// tensors zero-copy, then references the region by name over the Java HTTP
// client (src/java/clienttpu).
//
// Implemented with java.lang.foreign (FFM, finalized in JDK 22) — no JNI
// compile step, no JavaCPP dependency.  Compile with `make java-bindings`
// (skipped automatically on older JDKs).
package clienttpu.bindings;

import java.lang.foreign.Arena;
import java.lang.foreign.FunctionDescriptor;
import java.lang.foreign.Linker;
import java.lang.foreign.MemorySegment;
import java.lang.foreign.SymbolLookup;
import java.lang.foreign.ValueLayout;
import java.lang.invoke.MethodHandle;
import java.nio.file.Path;

public final class TpuShm {
  private final Linker linker = Linker.nativeLinker();
  private final MethodHandle create;
  private final MethodHandle open;
  private final MethodHandle write;
  private final MethodHandle read;
  private final MethodHandle byteSize;
  private final MethodHandle close;
  private final MethodHandle lastError;

  public TpuShm(Path library) {
    SymbolLookup lib = SymbolLookup.libraryLookup(library, Arena.global());
    create = handle(lib, "TpuShmCreate",
        FunctionDescriptor.of(ValueLayout.ADDRESS, ValueLayout.ADDRESS,
            ValueLayout.JAVA_LONG));
    open = handle(lib, "TpuShmOpen",
        FunctionDescriptor.of(ValueLayout.ADDRESS, ValueLayout.ADDRESS,
            ValueLayout.JAVA_LONG, ValueLayout.JAVA_LONG));
    write = handle(lib, "TpuShmWrite",
        FunctionDescriptor.of(ValueLayout.JAVA_INT, ValueLayout.ADDRESS,
            ValueLayout.JAVA_LONG, ValueLayout.ADDRESS,
            ValueLayout.JAVA_LONG));
    read = handle(lib, "TpuShmRead",
        FunctionDescriptor.of(ValueLayout.JAVA_INT, ValueLayout.ADDRESS,
            ValueLayout.JAVA_LONG, ValueLayout.ADDRESS,
            ValueLayout.JAVA_LONG));
    byteSize = handle(lib, "TpuShmByteSize",
        FunctionDescriptor.of(ValueLayout.JAVA_LONG, ValueLayout.ADDRESS));
    close = handle(lib, "TpuShmClose",
        FunctionDescriptor.of(ValueLayout.JAVA_INT, ValueLayout.ADDRESS,
            ValueLayout.JAVA_INT));
    lastError = handle(lib, "TpuShmLastError",
        FunctionDescriptor.of(ValueLayout.ADDRESS));
  }

  private MethodHandle handle(
      SymbolLookup lib, String name, FunctionDescriptor descriptor) {
    return linker.downcallHandle(
        lib.find(name).orElseThrow(
            () -> new IllegalStateException("missing symbol " + name)),
        descriptor);
  }

  private String lastError() {
    try {
      MemorySegment msg = (MemorySegment) lastError.invoke();
      return msg.reinterpret(4096).getString(0);
    } catch (Throwable t) {
      return "(error message unavailable: " + t + ")";
    }
  }

  /** One mapped region; close() unmaps (keeping the key for other users). */
  public final class Region implements AutoCloseable {
    private MemorySegment handle;

    private Region(MemorySegment handle) {
      this.handle = handle;
    }

    public long byteSize() {
      try {
        return (long) byteSize.invoke(handle);
      } catch (Throwable t) {
        throw new IllegalStateException(t);
      }
    }

    public void write(long offset, byte[] data) {
      try (Arena arena = Arena.ofConfined()) {
        MemorySegment src = arena.allocate(data.length);
        MemorySegment.copy(data, 0, src, ValueLayout.JAVA_BYTE, 0,
            data.length);
        int rc = (int) TpuShm.this.write.invoke(
            handle, offset, src, (long) data.length);
        if (rc != 0) {
          throw new IllegalStateException("TpuShmWrite: " + lastError());
        }
      } catch (Throwable t) {
        throw asRuntime(t);
      }
    }

    public byte[] read(long offset, int length) {
      try (Arena arena = Arena.ofConfined()) {
        MemorySegment dst = arena.allocate(length);
        int rc = (int) TpuShm.this.read.invoke(
            handle, offset, dst, (long) length);
        if (rc != 0) {
          throw new IllegalStateException("TpuShmRead: " + lastError());
        }
        byte[] out = new byte[length];
        MemorySegment.copy(dst, ValueLayout.JAVA_BYTE, 0, out, 0, length);
        return out;
      } catch (Throwable t) {
        throw asRuntime(t);
      }
    }

    /** Unmap; keepKey leaves the shm key linked for other processes. */
    public void close(boolean keepKey) {
      if (handle == null) {
        return;
      }
      try {
        int rc = (int) TpuShm.this.close.invoke(handle, keepKey ? 1 : 0);
        if (rc != 0) {
          throw new IllegalStateException("TpuShmClose: " + lastError());
        }
      } catch (Throwable t) {
        throw asRuntime(t);
      } finally {
        handle = null;
      }
    }

    @Override
    public void close() {
      close(true);
    }
  }

  public Region create(String key, long byteSizeBytes) {
    return regionFrom(invokeFactory(create, key, byteSizeBytes, null),
        "TpuShmCreate");
  }

  public Region open(String key, long byteSizeBytes, long offset) {
    return regionFrom(invokeFactory(open, key, byteSizeBytes, offset),
        "TpuShmOpen");
  }

  private MemorySegment invokeFactory(
      MethodHandle factory, String key, long size, Long offset) {
    try (Arena arena = Arena.ofConfined()) {
      MemorySegment ckey = arena.allocateFrom(key);
      return offset == null
          ? (MemorySegment) factory.invoke(ckey, size)
          : (MemorySegment) factory.invoke(ckey, size, (long) offset);
    } catch (Throwable t) {
      throw asRuntime(t);
    }
  }

  private Region regionFrom(MemorySegment handle, String what) {
    if (handle == null || handle.equals(MemorySegment.NULL)) {
      throw new IllegalStateException(what + ": " + lastError());
    }
    return new Region(handle);
  }

  private static RuntimeException asRuntime(Throwable t) {
    return t instanceof RuntimeException
        ? (RuntimeException) t
        : new IllegalStateException(t);
  }
}
