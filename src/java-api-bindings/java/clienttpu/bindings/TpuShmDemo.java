// Exercises the FFM bindings (TpuShm.java) against libcshm_tpu.so.
//
//   java clienttpu.bindings.TpuShmDemo <libcshm_tpu.so> selftest
//   java clienttpu.bindings.TpuShmDemo <libcshm_tpu.so> exchange <key> <size>
//
// selftest: create a region, write a pattern, read it back, destroy.
// exchange: open an EXISTING region (created by the Python side in
// tests/test_java_client.py), print its contents as hex, then overwrite
// every byte with (byte XOR 0x5A) — the Python side then verifies the
// transform, proving both directions cross the JVM/native boundary and the
// two runtimes really shared one mapping.
package clienttpu.bindings;

import java.nio.file.Path;

public final class TpuShmDemo {
  public static void main(String[] args) {
    if (args.length < 2) {
      System.err.println("usage: TpuShmDemo <lib> selftest|exchange ...");
      System.exit(2);
    }
    TpuShm shm = new TpuShm(Path.of(args[0]));
    switch (args[1]) {
      case "selftest" -> selftest(shm);
      case "exchange" -> exchange(shm, args[2], Long.parseLong(args[3]));
      default -> {
        System.err.println("unknown mode " + args[1]);
        System.exit(2);
      }
    }
  }

  private static void selftest(TpuShm shm) {
    String key = "/jffm-selftest-" + ProcessHandle.current().pid();
    try (TpuShm.Region region = shm.create(key, 256)) {
      byte[] pattern = new byte[256];
      for (int i = 0; i < pattern.length; i++) {
        pattern[i] = (byte) (i * 7);
      }
      region.write(0, pattern);
      byte[] back = region.read(0, pattern.length);
      if (region.byteSize() != 256 || !java.util.Arrays.equals(pattern, back)) {
        System.out.println("FAIL selftest: readback mismatch");
        System.exit(1);
      }
      region.close(false);  // drop the key: nothing else uses it
    }
    System.out.println("PASS: java ffm shm selftest");
  }

  private static void exchange(TpuShm shm, String key, long size) {
    try (TpuShm.Region region = shm.open(key, size, 0)) {
      byte[] data = region.read(0, (int) size);
      StringBuilder hex = new StringBuilder();
      for (byte b : data) {
        hex.append(String.format("%02x", b));
      }
      System.out.println("read-hex " + hex);
      byte[] transformed = new byte[data.length];
      for (int i = 0; i < data.length; i++) {
        transformed[i] = (byte) (data[i] ^ 0x5A);
      }
      region.write(0, transformed);
    }
    System.out.println("PASS: java ffm shm exchange");
  }
}
