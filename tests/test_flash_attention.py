"""Pallas flash attention (client_tpu.ops): numerical equivalence with the
plain einsum formulation, gradients through the custom VJP, padding edges,
and the transformer's attn_impl="flash" path.  On CPU the kernel runs in
Pallas interpret mode — the same code path the chip compiles."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from client_tpu.ops import flash_attention
from client_tpu.parallel.ring_attention import plain_attention
from client_tpu.serve.models import transformer as tfm


def _qkv(key, b, t, h, d, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (b, t, h, d), dtype) for k in ks)


@pytest.mark.parametrize(
    "b,t,h,d,causal",
    [
        (2, 128, 4, 64, True),
        (1, 256, 2, 64, True),
        (2, 100, 4, 64, True),   # t not divisible by blocks → padded path
        (2, 64, 4, 64, False),
        (1, 75, 2, 32, False),   # non-causal padded → reference fallback
    ],
)
def test_matches_plain_attention(b, t, h, d, causal):
    q, k, v = _qkv(jax.random.PRNGKey(0), b, t, h, d)
    ref = np.asarray(plain_attention(q, k, v, causal=causal))
    out = np.asarray(
        flash_attention(q, k, v, causal=causal, block_q=64, block_k=32)
    )
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=1e-4)


def test_gradients_match_reference():
    q, k, v = _qkv(jax.random.PRNGKey(1), 1, 64, 2, 32)

    def loss(fn):
        return lambda a, b_, c: jnp.sum(fn(a, b_, c) ** 2)

    gf = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss(plain_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), atol=1e-4, rtol=1e-3
        )


def test_bf16_inputs():
    q, k, v = _qkv(jax.random.PRNGKey(2), 2, 128, 4, 64, jnp.bfloat16)
    out = flash_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = plain_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=3e-2, rtol=3e-2,
    )


def test_transformer_flash_impl_matches_plain():
    cfg = tfm.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, max_seq=64, dtype="float32",
    )
    params = tfm.init_params(jax.random.PRNGKey(3), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 48), 0, cfg.vocab_size)
    plain = np.asarray(tfm.forward(params, tokens, cfg))
    flash = np.asarray(tfm.forward(params, tokens, cfg, attn_impl="flash"))
    np.testing.assert_allclose(flash, plain, atol=1e-4, rtol=1e-3)


def test_flash_train_step_reduces_loss():
    """custom_vjp backward: training through the kernel converges."""
    cfg = tfm.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=64, max_seq=64, dtype="float32",
    )
    params = tfm.init_params(jax.random.PRNGKey(5), cfg)
    opt, step = tfm.make_train_step(cfg, attn_impl="flash", learning_rate=1e-2)
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(6), (4, 33), 0, cfg.vocab_size)
    first = None
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, tokens)
        if first is None:
            first = float(loss)
    assert float(loss) < first
