"""Shared-memory transports end-to-end: system shm (native lib) and TPU
device-buffer regions (in-process zero-copy + staging fallback).

Mirrors the reference's simple_grpc_shm_client / simple_grpc_cudashm_client
flows (SURVEY.md §3.5) against the hermetic server.
"""

import json
import os

import numpy as np
import pytest

import client_tpu.grpc as grpcclient
from client_tpu.serve import Server
from client_tpu.utils import InferenceServerException
from client_tpu.utils import shared_memory as sysshm
from client_tpu.utils import tpu_shared_memory as tpushm


@pytest.fixture(scope="module")
def server():
    with Server(grpc_port=0) as s:
        yield s


@pytest.fixture()
def client(server):
    with grpcclient.InferenceServerClient(server.grpc_address) as c:
        yield c


_NATIVE_BUILT = os.path.exists(
    os.path.join(os.path.dirname(sysshm.__file__), "libcshm_tpu.so")
)
needs_native = pytest.mark.skipif(
    not _NATIVE_BUILT, reason="libcshm_tpu.so not built (make native)"
)


@needs_native
class TestSystemShm:
    def test_round_trip_local(self):
        h = sysshm.create_shared_memory_region("reg0", "/cl_tpu_test0", 256)
        try:
            data = np.arange(16, dtype=np.int32)
            sysshm.set_shared_memory_region(h, [data])
            back = sysshm.get_contents_as_numpy(h, np.int32, [16])
            np.testing.assert_array_equal(back, data)
            assert "reg0" in sysshm.mapped_shared_memory_regions()
        finally:
            sysshm.destroy_shared_memory_region(h)
        assert "reg0" not in sysshm.mapped_shared_memory_regions()

    def test_infer_via_system_shm(self, client):
        i0 = np.arange(16, dtype=np.int32).reshape(1, 16)
        i1 = np.full((1, 16), 3, dtype=np.int32)
        byte_size = i0.nbytes + i1.nbytes
        h_in = sysshm.create_shared_memory_region("input_sys", "/cl_in0", byte_size)
        h_out = sysshm.create_shared_memory_region("output_sys", "/cl_out0", byte_size)
        try:
            sysshm.set_shared_memory_region(h_in, [i0, i1])
            client.register_system_shared_memory("input_sys", "/cl_in0", byte_size)
            client.register_system_shared_memory("output_sys", "/cl_out0", byte_size)

            status = client.get_system_shared_memory_status(as_json=True)
            names = set(status.get("regions", {}))
            assert {"input_sys", "output_sys"} <= names

            inputs = [
                grpcclient.InferInput("INPUT0", [1, 16], "INT32"),
                grpcclient.InferInput("INPUT1", [1, 16], "INT32"),
            ]
            inputs[0].set_shared_memory("input_sys", i0.nbytes)
            inputs[1].set_shared_memory("input_sys", i1.nbytes, offset=i0.nbytes)
            outputs = [
                grpcclient.InferRequestedOutput("OUTPUT0"),
                grpcclient.InferRequestedOutput("OUTPUT1"),
            ]
            outputs[0].set_shared_memory("output_sys", i0.nbytes)
            outputs[1].set_shared_memory("output_sys", i1.nbytes, offset=i0.nbytes)

            result = client.infer("simple", inputs, outputs=outputs)
            out0 = result.get_output("OUTPUT0")
            assert out0 is not None
            sum_ = sysshm.get_contents_as_numpy(h_out, np.int32, [1, 16])
            diff = sysshm.get_contents_as_numpy(
                h_out, np.int32, [1, 16], offset=i0.nbytes
            )
            np.testing.assert_array_equal(sum_, i0 + i1)
            np.testing.assert_array_equal(diff, i0 - i1)
        finally:
            client.unregister_system_shared_memory()
            sysshm.destroy_shared_memory_region(h_in)
            sysshm.destroy_shared_memory_region(h_out)

    def test_register_unknown_key_errors(self, client):
        with pytest.raises(InferenceServerException):
            client.register_system_shared_memory("bad", "/does_not_exist_key", 64)


class TestTpuShm:
    def test_local_round_trip(self):
        h = tpushm.create_shared_memory_region("tpu0", 1024)
        try:
            data = np.linspace(0, 1, 32, dtype=np.float32).reshape(4, 8)
            tpushm.set_shared_memory_region(h, [data])
            back = tpushm.get_contents_as_numpy(h, "FP32", [4, 8])
            np.testing.assert_allclose(back, data)
            live = tpushm.get_contents_as_jax(h)
            import jax

            assert isinstance(live, jax.Array)
            assert "tpu0" in tpushm.allocated_shared_memory_regions()
        finally:
            tpushm.destroy_shared_memory_region(h)
        assert "tpu0" not in tpushm.allocated_shared_memory_regions()

    def test_infer_via_tpu_shm_zero_copy(self, client):
        i0 = np.arange(16, dtype=np.int32).reshape(1, 16)
        i1 = np.full((1, 16), 5, dtype=np.int32)
        h_in = tpushm.create_shared_memory_region("tpu_in", 256)
        h_out = tpushm.create_shared_memory_region("tpu_out", 256)
        try:
            tpushm.set_shared_memory_region(h_in, [i0, i1])
            client.register_tpu_shared_memory(
                "tpu_in", tpushm.get_raw_handle(h_in), 0, 256
            )
            client.register_tpu_shared_memory(
                "tpu_out", tpushm.get_raw_handle(h_out), 0, 256
            )
            status = client.get_tpu_shared_memory_status(as_json=True)
            names = set(status.get("regions", {}))
            assert {"tpu_in", "tpu_out"} <= names

            inputs = [
                grpcclient.InferInput("INPUT0", [1, 16], "INT32"),
                grpcclient.InferInput("INPUT1", [1, 16], "INT32"),
            ]
            inputs[0].set_shared_memory("tpu_in", i0.nbytes)
            inputs[1].set_shared_memory("tpu_in", i1.nbytes, offset=i0.nbytes)
            outputs = [
                grpcclient.InferRequestedOutput("OUTPUT0"),
                grpcclient.InferRequestedOutput("OUTPUT1"),
            ]
            outputs[0].set_shared_memory("tpu_out", i0.nbytes)
            outputs[1].set_shared_memory("tpu_out", i1.nbytes, offset=i0.nbytes)

            client.infer("simple", inputs, outputs=outputs)

            sum_ = tpushm.get_contents_as_numpy(h_out, "INT32", [1, 16])
            diff = tpushm.get_contents_as_numpy(
                h_out, "INT32", [1, 16], offset=i0.nbytes
            )
            np.testing.assert_array_equal(sum_, i0 + i1)
            np.testing.assert_array_equal(diff, i0 - i1)
        finally:
            client.unregister_tpu_shared_memory()
            tpushm.destroy_shared_memory_region(h_in)
            tpushm.destroy_shared_memory_region(h_out)

    def test_cross_process_requires_window(self, client):
        """A foreign-process handle whose descriptor lost its host window key
        must be rejected with a clear error (PJRT has no cross-process
        buffer export)."""
        h = tpushm.create_shared_memory_region("tpu_other", 64)
        try:
            desc = json.loads(tpushm.get_raw_handle(h))
            desc["pid"] = desc["pid"] + 1  # simulate foreign process
            del desc["staging_key"]
            with pytest.raises(InferenceServerException, match="staging|window"):
                client.register_tpu_shared_memory(
                    "tpu_other", json.dumps(desc).encode(), 0, 64
                )
        finally:
            tpushm.destroy_shared_memory_region(h)

    @needs_native
    def test_staging_fallback_cross_process(self, client):
        """Foreign-pid handle WITH staging: server reads via the host mirror."""
        i0 = np.arange(16, dtype=np.int32).reshape(1, 16)
        i1 = np.ones((1, 16), dtype=np.int32)
        h_in = tpushm.create_shared_memory_region(
            "tpu_staged", 256, staging_key="/cl_tpu_stage0"
        )
        try:
            tpushm.set_shared_memory_region(h_in, [i0, i1])
            desc = json.loads(tpushm.get_raw_handle(h_in))
            desc["pid"] = desc["pid"] + 1  # force the staging path
            client.register_tpu_shared_memory(
                "tpu_staged", json.dumps(desc).encode(), 0, 256
            )
            inputs = [
                grpcclient.InferInput("INPUT0", [1, 16], "INT32"),
                grpcclient.InferInput("INPUT1", [1, 16], "INT32"),
            ]
            inputs[0].set_shared_memory("tpu_staged", i0.nbytes)
            inputs[1].set_shared_memory("tpu_staged", i1.nbytes, offset=i0.nbytes)
            result = client.infer("simple", inputs)
            np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), i0 + i1)
        finally:
            client.unregister_tpu_shared_memory()
            tpushm.destroy_shared_memory_region(h_in)


class TestTpuRegionByteSemantics:
    """The native host window makes regions byte-addressable at any offset
    (VERDICT r01 weak #5: reads previously had to hit an exact prior-write
    offset, and overlapping writes silently dropped bytes)."""

    def test_arbitrary_offset_read(self):
        h = tpushm.create_shared_memory_region("tpu_bytes0", 256)
        try:
            data = np.arange(32, dtype=np.int32)  # 128 bytes at offset 0
            tpushm.set_shared_memory_region(h, [data])
            # read 8 ints starting mid-tensor (offset 40 bytes = element 10)
            back = tpushm.get_contents_as_numpy(h, np.int32, [8], offset=40)
            np.testing.assert_array_equal(back, data[10:18])
        finally:
            tpushm.destroy_shared_memory_region(h)

    def test_overlapping_writes_preserve_bytes(self):
        h = tpushm.create_shared_memory_region("tpu_bytes1", 256)
        try:
            a = np.arange(16, dtype=np.int32)  # bytes [0, 64)
            b = np.full(4, 99, dtype=np.int32)  # bytes [32, 48)
            tpushm.set_shared_memory_region(h, [a])
            tpushm.set_shared_memory_region(h, [b], offset=32)
            merged = tpushm.get_contents_as_numpy(h, np.int32, [16])
            expect = a.copy()
            expect[8:12] = 99
            np.testing.assert_array_equal(merged, expect)
        finally:
            tpushm.destroy_shared_memory_region(h)

    def test_device_write_syncs_lazily(self):
        import jax

        h = tpushm.create_shared_memory_region("tpu_bytes2", 256)
        try:
            dev = jax.device_put(np.float32([1.5, 2.5, 3.5, 4.5]))
            h.write_array(16, dev)
            # live device array, no sync
            live = tpushm.get_contents_as_jax(h, offset=16)
            assert hasattr(live, "devices")
            # byte read forces the D2H sync into the window
            back = tpushm.get_contents_as_numpy(h, np.float32, [4], offset=16)
            np.testing.assert_array_equal(
                back, np.float32([1.5, 2.5, 3.5, 4.5])
            )
            # ...and a partial-range read also works
            tail = tpushm.get_contents_as_numpy(h, np.float32, [2], offset=24)
            np.testing.assert_array_equal(tail, np.float32([3.5, 4.5]))
        finally:
            tpushm.destroy_shared_memory_region(h)

    def test_partial_overlap_of_dirty_device_slot_flushes_first(self):
        """ADVICE r2 (medium): a byte write overlapping a *dirty* device slot
        must flush the slot's bytes to the window first, so the slot's
        non-overlapped bytes survive the overlay."""
        import jax

        h = tpushm.create_shared_memory_region("tpu_bytes4", 256)
        try:
            dev = jax.device_put(np.arange(16, dtype=np.float32))  # 64B dirty
            h.write_array(0, dev)
            h.write(32, np.full(8, 9, dtype=np.float32).tobytes())
            head = tpushm.get_contents_as_numpy(h, np.float32, [8], offset=0)
            np.testing.assert_array_equal(head, np.arange(8, dtype=np.float32))
            tail = tpushm.get_contents_as_numpy(h, np.float32, [8], offset=32)
            np.testing.assert_array_equal(tail, np.full(8, 9, dtype=np.float32))
        finally:
            tpushm.destroy_shared_memory_region(h)

    def test_partial_overlap_by_device_write_flushes_first(self):
        """Same contract when the overlapping write is itself a device write."""
        import jax

        h = tpushm.create_shared_memory_region("tpu_bytes5", 256)
        try:
            h.write_array(0, jax.device_put(np.arange(16, dtype=np.float32)))
            h.write_array(32, jax.device_put(np.full(8, 5, dtype=np.float32)))
            head = tpushm.get_contents_as_numpy(h, np.float32, [8], offset=0)
            np.testing.assert_array_equal(head, np.arange(8, dtype=np.float32))
            mid = tpushm.get_contents_as_numpy(h, np.float32, [8], offset=32)
            np.testing.assert_array_equal(mid, np.full(8, 5, dtype=np.float32))
        finally:
            tpushm.destroy_shared_memory_region(h)

    def test_full_overwrite_of_dirty_slot_skips_flush(self):
        """The hot serving path: every request's output fully overwrites the
        previous device slot at the same offset.  That must NOT trigger a
        hidden D2H flush (it cost 27x throughput when it did)."""
        import jax

        h = tpushm.create_shared_memory_region("tpu_bytes7", 256)
        try:
            h.write_array(0, jax.device_put(np.arange(8, dtype=np.float32)))
            calls = []
            orig = h._window.write
            h._window.write = lambda *a: calls.append(a) or orig(*a)
            h.write_array(0, jax.device_put(np.full(8, 2, dtype=np.float32)))
            assert calls == [], "full overwrite must not sync the old slot"
            h._window.write = orig
            back = tpushm.get_contents_as_numpy(h, np.float32, [8])
            np.testing.assert_array_equal(back, np.full(8, 2, dtype=np.float32))
        finally:
            tpushm.destroy_shared_memory_region(h)

    def test_bytearray_write_accepted(self):
        """ADVICE r2 (low): bytearray input must not raise ctypes.ArgumentError."""
        h = tpushm.create_shared_memory_region("tpu_bytes6", 64)
        try:
            h.write(0, bytearray(np.arange(8, dtype=np.int32).tobytes()))
            back = tpushm.get_contents_as_numpy(h, np.int32, [8])
            np.testing.assert_array_equal(back, np.arange(8, dtype=np.int32))
        finally:
            tpushm.destroy_shared_memory_region(h)

    def test_raw_handle_fields(self):
        h = tpushm.create_shared_memory_region("tpu_bytes3", 128, device_id=0)
        try:
            desc = json.loads(tpushm.get_raw_handle(h))
            assert desc["byte_size"] == 128
            assert desc["device_id"] == 0
            assert desc["pid"] == os.getpid()
            assert desc["staging_key"].startswith("/tpushm-")
            assert len(desc["uuid"]) == 32
        finally:
            tpushm.destroy_shared_memory_region(h)

    def test_cross_process_window_attach(self):
        """A real second process attaches the region by raw handle and both
        reads our bytes and writes bytes we observe (the cudaIpc-analog
        round trip, via the native libctpushm.so window)."""
        import subprocess
        import sys

        h = tpushm.create_shared_memory_region("tpu_xproc", 64)
        try:
            tpushm.set_shared_memory_region(
                h, [np.arange(8, dtype=np.int32)]
            )
            handle_json = tpushm.get_raw_handle(h).decode()
            code = (
                "import json, sys, numpy as np\n"
                "sys.path.insert(0, %r)\n"
                "from client_tpu.utils.tpu_shared_memory import TpuWindowRegion\n"
                "region = TpuWindowRegion(json.loads(%r))\n"
                "got = np.frombuffer(region.read(0, 32), dtype=np.int32)\n"
                "assert (got == np.arange(8)).all(), got\n"
                "region.write(32, np.full(4, 7, dtype=np.int32).tobytes())\n"
                "region.close()\n"
                "print('child-ok')\n"
            ) % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 handle_json)
            out = subprocess.run(
                [sys.executable, "-c", code], capture_output=True, text=True,
                timeout=60,
            )
            assert out.returncode == 0, out.stderr
            assert "child-ok" in out.stdout
            back = tpushm.get_contents_as_numpy(h, np.int32, [4], offset=32)
            np.testing.assert_array_equal(back, np.full(4, 7, dtype=np.int32))
        finally:
            tpushm.destroy_shared_memory_region(h)
