"""Non-KServe client backends: TorchServe + TF-Serving against the hermetic
fake endpoints — proves the L4 pluggable-backend abstraction over a second
and third protocol family (reference client_backend.h:134-139;
torchserve_http_client.cc, tfserve_grpc_client.cc)."""

import subprocess
import sys

import numpy as np
import pytest

from client_tpu.perf import (
    BackendKind,
    ClientBackendFactory,
    ConcurrencyManager,
    DataLoader,

)
from client_tpu.perf.infer_data import InferDataManager
from client_tpu.perf.fake_endpoints import fake_tfserving, fake_torchserve
from client_tpu.utils import InferenceServerException


@pytest.fixture(scope="module")
def torchserve():
    with fake_torchserve(["resnet"]) as s:
        yield s


@pytest.fixture(scope="module")
def tfserving():
    with fake_tfserving(["half_plus_two"]) as s:
        yield s


class TestTorchServeBackend:
    def _backend(self, srv):
        return ClientBackendFactory.create(
            BackendKind.TORCHSERVE, url=srv.url, input_shape=[1, 8]
        )

    def test_live_and_metadata(self, torchserve):
        be = self._backend(torchserve)
        assert be.server_live()
        meta = be.model_metadata("resnet")
        assert meta["inputs"][0]["shape"] == [1, 8]
        cfg = be.model_config("resnet")
        assert cfg["name"] == "resnet"

    def test_infer_value_roundtrip(self, torchserve):
        be = self._backend(torchserve)
        arr = np.arange(8, dtype=np.float32).reshape(1, 8)
        inp = be.infer_input_cls("data", [1, 8], "FP32")
        inp.set_data_from_numpy(arr)
        result = be.infer("resnet", [inp])
        # fake computes sum of the f32 payload — ground truth for validation
        np.testing.assert_allclose(
            result.as_numpy("predictions"), [arr.sum()], rtol=1e-6
        )

    def test_unknown_model_is_error(self, torchserve):
        be = self._backend(torchserve)
        inp = be.infer_input_cls("data", [1, 8], "FP32")
        inp.set_data_from_numpy(np.zeros((1, 8), np.float32))
        with pytest.raises(InferenceServerException, match="404"):
            be.infer("nope", [inp])

    def test_load_engine_runs_over_torchserve(self, torchserve):
        def factory():
            return ClientBackendFactory.create(
                BackendKind.TORCHSERVE, url=torchserve.url, input_shape=[1, 8]
            )

        be = factory()
        meta = be.model_metadata("resnet")
        loader = DataLoader(meta["inputs"], batch_size=1)
        loader.generate_data()
        dm = InferDataManager(be, loader, meta["inputs"], meta["outputs"])
        dm.init()
        mgr = ConcurrencyManager(
            backend_factory=factory, data_loader=loader, data_manager=dm,
            model_name="resnet", max_threads=4,
        )
        try:
            before = torchserve.request_count
            mgr.change_concurrency_level(2)
            import time

            time.sleep(0.4)
            records = mgr.swap_timestamps()
            assert len(records) > 20
            assert all(r.ok for r in records)
            assert torchserve.request_count > before
        finally:
            mgr.cleanup()


class TestTfServeGrpcBackend:
    """The TFSERVE kind speaks gRPC PredictionService (reference
    tfserve_grpc_client.cc) against the hermetic fake service."""

    @pytest.fixture()
    def tfs_grpc(self):
        from client_tpu.perf.fake_endpoints import fake_tfserving_grpc

        with fake_tfserving_grpc(["half_plus_two"]) as s:
            yield s

    def _backend(self, service):
        return ClientBackendFactory.create(
            BackendKind.TFSERVE, url=service.url, input_shape=[1, 4]
        )

    def test_status_and_metadata(self, tfs_grpc):
        be = self._backend(tfs_grpc)
        assert be.model_ready("half_plus_two")
        assert not be.model_ready("nope")
        meta = be.model_metadata("half_plus_two")
        assert meta["platform"] == "tensorflow_serving"
        assert meta["versions"] == ["1"]
        be.close()

    def test_predict_roundtrip(self, tfs_grpc):
        be = self._backend(tfs_grpc)
        arr = np.array([[1.0, 2.0, 3.0, 4.0]], dtype=np.float32)
        inp = be.infer_input_cls("input", [1, 4], "FP32")
        inp.set_data_from_numpy(arr)
        result = be.infer("half_plus_two", [inp])
        np.testing.assert_allclose(
            result.as_numpy("output"), [[10.0]], rtol=1e-6
        )
        assert tfs_grpc.request_count == 1
        be.close()

    def test_unknown_model_is_error(self, tfs_grpc):
        be = self._backend(tfs_grpc)
        inp = be.infer_input_cls("input", [1, 4], "FP32")
        inp.set_data_from_numpy(np.zeros((1, 4), np.float32))
        with pytest.raises(InferenceServerException, match="Servable"):
            be.infer("nope", [inp])
        be.close()


def test_perf_cli_tfserve_grpc_hermetic_sweep():
    """`--service-kind tfserve --hermetic` drives the gRPC PredictionService
    fake end-to-end through the full harness."""
    proc = subprocess.run(
        [sys.executable, "-m", "client_tpu.perf", "-m", "half_plus_two",
         "--service-kind", "tfserve", "--hermetic",
         "--shape", "input:1,8", "--concurrency-range", "1:1:1",
         "--measurement-interval", "400", "--max-trials", "4"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "Best: concurrency=" in proc.stdout


class TestTfServeBackend:
    def _backend(self, tfserving):
        return ClientBackendFactory.create(
            BackendKind.TFSERVE_REST, url=tfserving.url, input_shape=[1, 4]
        )

    def test_metadata(self, tfserving):
        be = self._backend(tfserving)
        meta = be.model_metadata("half_plus_two")
        assert meta["platform"] == "tensorflow_serving"
        cfg = be.model_config("half_plus_two")
        assert cfg["tfserving"]["model_version_status"][0]["state"] == "AVAILABLE"

    def test_predict_instances_roundtrip(self, tfserving):
        be = self._backend(tfserving)
        arr = np.array([[1.0, 2.0, 3.0, 4.0]], dtype=np.float32)
        inp = be.infer_input_cls("instances", [1, 4], "FP32")
        inp.set_data_from_numpy(arr)
        result = be.infer("half_plus_two", [inp])
        np.testing.assert_allclose(
            result.as_numpy("predictions"), [[10.0]], rtol=1e-6
        )

    def test_unknown_model_is_error(self, tfserving):
        be = self._backend(tfserving)
        inp = be.infer_input_cls("instances", [1, 4], "FP32")
        inp.set_data_from_numpy(np.zeros((1, 4), np.float32))
        with pytest.raises(InferenceServerException, match="404"):
            be.infer("nope", [inp])


def test_perf_cli_torchserve_hermetic_sweep():
    """`python -m client_tpu.perf --service-kind torchserve --hermetic`
    end-to-end (the VERDICT r02 acceptance command)."""
    proc = subprocess.run(
        [sys.executable, "-m", "client_tpu.perf", "-m", "resnet",
         "--service-kind", "torchserve", "--hermetic",
         "--shape", "data:1,8", "--concurrency-range", "1:2:1",
         "--measurement-interval", "400", "--max-trials", "4"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "Best: concurrency=" in proc.stdout
