"""Java client acceptance: compile src/java and run its mains against a
live in-process server (reference src/java/library + examples —
SimpleInferClient, MemoryGrowthTest, SimpleInferPerf).  Skipped when no JDK
is on PATH (this image ships none); on a JDK-equipped machine the suite
compiles and exercises the sync + async transports end to end.
"""

import os
import shutil
import subprocess

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CLASSES = os.path.join(_REPO, "build", "java", "classes")

pytestmark = pytest.mark.skipif(
    shutil.which("javac") is None or shutil.which("java") is None,
    reason="no JDK on PATH",
)


@pytest.fixture(scope="module")
def java_classes():
    proc = subprocess.run(
        ["make", "java"], cwd=_REPO, capture_output=True, text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr
    assert os.path.isdir(_CLASSES)
    return _CLASSES


@pytest.fixture(scope="module")
def server():
    from client_tpu.serve import Server

    with Server(http_port=0) as srv:
        yield srv


def _run_main(classes, main, *args, timeout=120):
    return subprocess.run(
        ["java", "-cp", classes, main, *args],
        capture_output=True, text=True, timeout=timeout,
    )


def test_simple_infer(java_classes, server):
    proc = _run_main(
        java_classes, "clienttpu.SimpleInferClient", server.http_address
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "PASS: java simple infer" in proc.stdout


def test_memory_growth(java_classes, server):
    proc = _run_main(
        java_classes, "clienttpu.examples.MemoryGrowthTest",
        server.http_address, "200",
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "PASS: MemoryGrowthTest" in proc.stdout


def test_async_infer_perf(java_classes, server):
    proc = _run_main(
        java_classes, "clienttpu.examples.SimpleInferPerf",
        server.http_address, "100", "8",
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "PASS: SimpleInferPerf" in proc.stdout
    assert "infer/sec" in proc.stdout


def _javac_major():
    out = subprocess.run(
        ["javac", "--version"], capture_output=True, text=True
    ).stdout
    digits = "".join(c for c in out.split()[-1].split(".")[0] if c.isdigit())
    return int(digits or 0)


@pytest.fixture(scope="module")
def java_bindings_classes():
    if _javac_major() < 22:
        pytest.skip("java FFM bindings need JDK >= 22")
    proc = subprocess.run(
        ["make", "java-bindings"], cwd=_REPO, capture_output=True, text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr
    classes = os.path.join(_REPO, "build", "java-bindings", "classes")
    assert os.path.isdir(classes)
    return classes


_CSHM = os.path.join(
    _REPO, "client_tpu", "utils", "shared_memory", "libcshm_tpu.so"
)


def test_ffm_shm_selftest(java_bindings_classes):
    """The java.lang.foreign bindings (src/java-api-bindings/java) drive the
    C shm ABI end to end in-process: create, write, readback, destroy."""
    proc = _run_main(
        java_bindings_classes, "clienttpu.bindings.TpuShmDemo",
        _CSHM, "selftest",
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "PASS: java ffm shm selftest" in proc.stdout


def test_ffm_shm_cross_language_exchange(java_bindings_classes):
    """Python creates a POSIX shm region; the JVM opens the SAME region via
    the FFM bindings, reads the pattern, and writes back each byte XOR 0x5A;
    Python verifies the transform — both directions crossed the
    JVM<->native boundary on one shared mapping."""
    import numpy as np

    from client_tpu.utils import shared_memory as cshm

    key = f"/jffm-x-{os.getpid()}"
    pattern = np.arange(64, dtype=np.uint8)
    handle = cshm.create_shared_memory_region("jffm", key, 64)
    try:
        cshm.set_shared_memory_region(handle, [pattern])
        proc = _run_main(
            java_bindings_classes, "clienttpu.bindings.TpuShmDemo",
            _CSHM, "exchange", key, "64",
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "read-hex " + pattern.tobytes().hex() in proc.stdout
        assert "PASS: java ffm shm exchange" in proc.stdout
        back = cshm.get_contents_as_numpy(handle, np.uint8, [64])
        np.testing.assert_array_equal(back, pattern ^ 0x5A)
    finally:
        cshm.destroy_shared_memory_region(handle)


def test_golden_wire(java_classes):
    """No server needed: the Java client's encoding is asserted against the
    Python-generated golden bytes (tests/golden/, kept current by
    tests/test_golden_wire.py) — request binary section byte-identical,
    header JSON canonically equal, response parsed to exact values."""
    proc = _run_main(
        java_classes, "clienttpu.GoldenWireTest",
        os.path.join(_REPO, "tests", "golden"),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "PASS: java golden wire" in proc.stdout
