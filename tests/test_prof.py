"""The continuous profiler (serve/prof.py) and its surfaces.

Covers:
- ring semantics: bounded capacity, idle-run coalescing, disarmed
  no-op handles, and thread-safety under the Eraser race witness,
- reconciliation: bracketed phase sums stay within the tick wall time
  and the dispatch/compute/host/idle attribution sums to ~100,
- the ctpu_prof_* series reaching a Registry through the batched
  flush path (and the metrics-manager prefix whitelist),
- the server surfaces: GET /v2/debug/prof, prof_tick records in
  flight dumps, and the profview CLI (text / json / exit codes),
- the always-on budget: one armed commit costs <= 2% of a headline
  in-process request (same ratio bench.py records as
  prof_overhead_pct).
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import client_tpu.http as httpclient
from client_tpu import profview
from client_tpu.serve import Model, Server, TensorSpec
from client_tpu.serve.metrics import Registry
from client_tpu.serve.prof import (
    NULL_TICK,
    PhaseProfiler,
    attribute_phases,
    device_peak_tflops,
)


def _commit_n(prof, n, kind="unary", model=None):
    for i in range(n):
        prof.commit(
            kind, 1e-3,
            phases={"host": 2e-4, "compute": 6e-4, "render": 2e-4},
            model=model, items=1 if model else 0,
        )


class TestRing:
    def test_ring_is_bounded(self):
        p = PhaseProfiler(name="t", capacity=8)
        _commit_n(p, 50)
        assert len(p.snapshot()) == 8
        assert p.ticks_noted == 50  # lifetime counters keep counting

    def test_idle_runs_coalesce_in_place(self):
        p = PhaseProfiler(name="t", capacity=8)
        p.commit("unary", 1e-3, phases={"compute": 1e-3})
        for _ in range(20):
            p.commit("idle", 5e-2, phases={"idle": 5e-2})
        records = p.snapshot()
        assert len(records) == 2  # the idle run is ONE record
        idle = records[-1]
        assert idle["kind"] == "idle" and idle["ticks"] == 20
        assert idle["dur_s"] == pytest.approx(20 * 5e-2)
        # ...but the rollup still counts every coalesced tick
        assert p.rollup(window_s=0)["kinds"]["idle"] == 20

    def test_disarmed_is_a_no_op(self):
        p = PhaseProfiler(name="t")
        p.arm(False)
        assert p.start_tick("sched") is NULL_TICK
        with p.start_tick("sched") as tick:
            with tick.phase("schedule"):
                pass
            tick.relabel("idle")
            tick.compute("m", 1, 1e6)
        p.commit("unary", 1e-3, phases={"compute": 1e-3})
        assert p.snapshot() == [] and p.ticks_noted == 0
        p.arm(True)
        p.commit("unary", 1e-3, phases={"compute": 1e-3})
        assert p.ticks_noted == 1

    def test_commits_are_race_free_under_witness(self):
        """Concurrent commits, snapshots, and rollups on one profiler:
        the Eraser witness instruments @witness_shared(PhaseProfiler)
        and must stay green."""
        from client_tpu.analysis.witness import RaceWitness

        w = RaceWitness()
        with w.installed():
            p = PhaseProfiler(name="t", capacity=64)
            errors = []

            def writer():
                try:
                    _commit_n(p, 200, model="m")
                except Exception as e:  # pragma: no cover
                    errors.append(e)

            def reader():
                try:
                    for _ in range(50):
                        p.snapshot()
                        p.rollup(window_s=0)
                except Exception as e:  # pragma: no cover
                    errors.append(e)

            threads = [threading.Thread(target=fn)
                       for fn in (writer, writer, reader)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        assert not errors
        assert p.ticks_noted == 400
        assert w.assert_race_free() > 0  # it watched, and stayed green


class TestReconciliation:
    def test_phase_sum_stays_within_wall(self):
        p = PhaseProfiler(name="t")
        tick = p.start_tick("sched")
        try:
            with tick.phase("schedule"):
                time.sleep(0.005)
            with tick.phase("decode_dispatch"):
                time.sleep(0.01)
        finally:
            p.finish(tick)
        roll = p.rollup(window_s=0)
        assert roll["ticks"] == 1
        # bracketed phases can never exceed the tick's wall time...
        assert roll["covered_s"] <= roll["wall_s"]
        # ...and here they bracket nearly all of it
        assert roll["covered_s"] >= 0.8 * roll["wall_s"]

    def test_attribution_sums_to_100(self):
        split = attribute_phases(
            {"compute": 0.6, "schedule": 0.1, "host": 0.2},
            wall_s=1.0,  # 0.1s uncovered -> idle
        )
        assert split["compute_pct"] == pytest.approx(60.0, abs=0.1)
        assert split["dispatch_pct"] == pytest.approx(10.0, abs=0.1)
        assert split["host_pct"] == pytest.approx(20.0, abs=0.1)
        assert split["idle_pct"] == pytest.approx(10.0, abs=0.1)
        assert sum(split.values()) == pytest.approx(100.0, abs=0.5)

    def test_attribution_empty_is_none(self):
        assert attribute_phases({}) is None

    def test_report_covers_adopted_children(self):
        parent = PhaseProfiler(name="serve")
        child = PhaseProfiler(name="lm")
        parent.adopt(child)
        _commit_n(parent, 2)
        _commit_n(child, 3, kind="decode")
        report = parent.report(window_s=0)
        assert report["kind"] == "prof_report"
        by_name = {e["engine"]: e for e in report["engines"]}
        assert by_name["serve"]["ticks"] == 2
        assert by_name["lm"]["ticks"] == 3
        # recent() tags each record with its engine for flight dumps
        engines = {r["engine"] for r in parent.recent(last=8)}
        assert engines == {"serve", "lm"}


class TestMetricsExport:
    def test_batched_flush_reaches_registry(self):
        reg = Registry()
        p = PhaseProfiler(name="t", registry=reg)
        _commit_n(p, 10, model="m")
        p.flush_metrics()
        lines = []
        reg.render_into(lines)
        text = "\n".join(lines)
        assert 'ctpu_prof_ticks_total{engine="t",kind="unary"} 10' in text
        assert "ctpu_prof_phase_seconds_total" in text
        assert "ctpu_prof_compute_share_pct" in text

    def test_mfu_uses_measured_peak(self):
        reg = Registry()
        p = PhaseProfiler(name="t", registry=reg)
        p.commit("unary", 1e-3, phases={"compute": 1e-3},
                 model="m", items=1, flops_per_item=1e6)
        p.flush_metrics()
        peak, kind = device_peak_tflops()
        assert peak > 0 and kind in ("tpu", "cpu_fallback")
        roll = p.rollup(window_s=0)
        assert roll["peak_kind"] == kind
        assert roll["models"]["m"]["mfu_pct"] > 0

    def test_prof_prefix_is_whitelisted(self):
        from client_tpu.perf.metrics_manager import MetricsManager

        assert "ctpu_prof_" in MetricsManager.SERIES_PREFIXES


def _infer_simple(client, n=1):
    inputs = [
        httpclient.InferInput("INPUT0", [1, 16], "INT32"),
        httpclient.InferInput("INPUT1", [1, 16], "INT32"),
    ]
    inputs[0].set_data_from_numpy(np.ones((1, 16), np.int32))
    inputs[1].set_data_from_numpy(np.ones((1, 16), np.int32))
    for _ in range(n):
        client.infer("simple", inputs)


class TestServerSurfaces:
    def test_debug_prof_endpoint(self):
        with Server(http_port=0) as server:
            with httpclient.InferenceServerClient(server.http_address) as c:
                _infer_simple(c, n=3)
            body = urllib.request.urlopen(
                f"http://{server.http_address}/v2/debug/prof?window=0"
            ).read()
            report = json.loads(body)
            assert report["kind"] == "prof_report"
            by_name = {e["engine"]: e for e in report["engines"]}
            serve = by_name["serve"]
            assert serve["kinds"]["unary"] == 3
            split = serve["attribution"]
            assert sum(split.values()) == pytest.approx(100.0, abs=0.5)
            # the HTTP frontend's wire ticks land in the wire engine
            wire = by_name["wire"]
            assert wire["kinds"]["http"] == 3
            for phase in ("deserialize", "wait", "serialize", "send"):
                assert phase in wire["phases"]

    def test_flight_dump_carries_prof_ticks(self):
        with Server(http_port=0) as server:
            with httpclient.InferenceServerClient(server.http_address) as c:
                _infer_simple(c, n=2)
            body = urllib.request.urlopen(
                f"http://{server.http_address}/v2/debug/flight"
            ).read().decode()
            lines = [json.loads(line) for line in body.splitlines()]
            prof_ticks = [r for r in lines if r["kind"] == "prof_tick"]
            assert any(r.get("tick_kind") == "unary" for r in prof_ticks)
            assert all("engine" in r for r in prof_ticks)


class TestProfview:
    def _report_file(self, tmp_path):
        p = PhaseProfiler(name="serve")
        _commit_n(p, 4, model="m")
        path = tmp_path / "prof.json"
        path.write_text(json.dumps(p.report(window_s=0)))
        return path

    def test_text_output(self, tmp_path, capsys):
        path = self._report_file(tmp_path)
        assert profview.main([str(path)]) == 0
        out = capsys.readouterr().out
        assert "engine serve" in out and "ticks=4" in out
        assert "attribution:" in out and "compute" in out
        assert "model m" in out

    def test_json_output(self, tmp_path, capsys):
        path = self._report_file(tmp_path)
        assert profview.main([str(path), "--format", "json"]) == 0
        rollups = [json.loads(line)
                   for line in capsys.readouterr().out.splitlines()]
        assert rollups[0]["engine"] == "serve"
        assert rollups[0]["ticks"] == 4

    def test_flight_dump_input_rerolls(self, tmp_path, capsys):
        p = PhaseProfiler(name="serve")
        _commit_n(p, 3, model="m")
        dump = tmp_path / "flight.jsonl"
        lines = []
        for record in p.recent(last=8):
            tagged = dict(record)
            tagged["tick_kind"] = tagged.pop("kind", None)
            tagged["kind"] = "prof_tick"
            lines.append(json.dumps(tagged))
        dump.write_text("\n".join(lines) + "\n")
        assert profview.main([str(dump)]) == 0
        out = capsys.readouterr().out
        assert "engine serve" in out and "ticks=3" in out

    def test_exit_codes(self, tmp_path, capsys):
        assert profview.main([str(tmp_path / "missing.json")]) == 2
        empty = tmp_path / "empty.json"
        empty.write_text(json.dumps(
            PhaseProfiler(name="quiet").report(window_s=0)
        ))
        assert profview.main([str(empty)]) == 1
        err = capsys.readouterr().err
        assert "no prof data found" in err

    def test_engine_filter(self, tmp_path, capsys):
        parent = PhaseProfiler(name="serve")
        child = PhaseProfiler(name="lm")
        parent.adopt(child)
        _commit_n(parent, 1)
        _commit_n(child, 1, kind="decode")
        path = tmp_path / "prof.json"
        path.write_text(json.dumps(parent.report(window_s=0)))
        assert profview.main([str(path), "--engine", "lm"]) == 0
        out = capsys.readouterr().out
        assert "engine lm" in out and "engine serve" not in out


class TestOverheadBudget:
    def test_armed_commit_within_2pct_of_headline_request(self):
        """The always-on budget: one armed commit (the unary path adds
        exactly one per request) costs <= 2% of an in-process headline
        request — same ratio bench.py records as prof_overhead_pct."""
        work = np.ones((384, 384), np.float32) * 1e-3

        def fn(inputs, params, ctx):
            acc = work
            for _ in range(6):
                acc = acc @ work
            return {"OUT": inputs["IN"] + acc[0, 0]}

        from client_tpu.serve.model_runtime import InferenceEngine
        from client_tpu.utils import to_wire_bytes

        engine = InferenceEngine(models=[Model(
            "probe",
            inputs=[TensorSpec("IN", "FP32", [-1, 8])],
            outputs=[TensorSpec("OUT", "FP32", [-1, 8])],
            fn=fn,
        )])
        try:
            arr = np.zeros((1, 8), np.float32)
            raw = to_wire_bytes(arr, "FP32")
            request = {
                "id": "",
                "inputs": [{
                    "name": "IN", "datatype": "FP32", "shape": [1, 8],
                    "parameters": {"binary_data_size": len(raw)},
                }],
                "outputs": [
                    {"name": "OUT", "parameters": {"binary_data": True}}
                ],
            }

            def run(n=20):
                t0 = time.perf_counter()
                for _ in range(n):
                    engine.execute("probe", "", dict(request), raw)
                return (time.perf_counter() - t0) / n

            run(5)  # warm imports / BLAS threads
            request_s = min(run(), run())

            prof = engine.prof
            phases = {"host": 2e-5, "compute": 9e-3, "render": 1e-5}
            iters = 5000
            t0 = time.perf_counter()
            for _ in range(iters):
                prof.commit("unary", 9.1e-3, phases=phases,
                            model="probe", items=1, flops_per_item=1e6)
            commit_s = (time.perf_counter() - t0) / iters
            overhead_pct = 100.0 * commit_s / request_s
            assert overhead_pct <= 2.0, (
                f"armed commit {commit_s * 1e6:.1f}us on a "
                f"{request_s * 1e3:.2f}ms request = {overhead_pct:.2f}%"
            )
        finally:
            engine.close()
