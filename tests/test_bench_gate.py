"""The bench SLO regression gate (`bench._slo_gate` / `_slo_block`):
round-over-round capacity ratchet semantics, including the zero-capacity
case and the link-drift escape hatch."""

import importlib.util
import os

import pytest


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location(
        "bench",
        os.path.join(os.path.dirname(__file__), "..", "bench.py"),
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def test_regression_past_tolerance_fails(bench):
    gate = bench._slo_gate(
        {"value": 100.0}, {"value": 200.0}, tolerance_pct=20.0
    )
    assert not gate["pass"]
    assert gate["regressions"][0]["key"] == "value"
    assert gate["checked"]["value"] == -50.0


def test_within_tolerance_passes(bench):
    gate = bench._slo_gate({"value": 170.0}, {"value": 200.0})
    assert gate["pass"] and not gate["regressions"]
    assert gate["checked"]["value"] == -15.0


def test_zero_capacity_is_the_loudest_regression(bench):
    """slo_qps_under_p99 drops to exactly 0.0 when the measured p99
    misses the objective — the gate must fire on it, not skip a falsy
    figure."""
    cur = {"slo": {"slo_qps_under_p99": 0.0}}
    prev = {"slo": {"slo_qps_under_p99": 900.0}}
    gate = bench._slo_gate(cur, prev)
    assert not gate["pass"]
    assert gate["regressions"][0]["key"] == "slo_qps_under_p99"
    assert gate["regressions"][0]["delta_pct"] == -100.0


def test_unmeasured_keys_are_skipped(bench):
    gate = bench._slo_gate({"value": None}, {"value": 100.0})
    assert gate["pass"] and "value" not in gate["checked"]
    gate = bench._slo_gate({}, {"value": 100.0})
    assert gate["pass"]


def test_link_drift_skips_with_reason(bench):
    gate = bench._slo_gate(
        {"value": 100.0, "mp_link_drift_pct": -22.0}, {"value": 200.0}
    )
    assert gate["pass"]
    assert "value" in gate["skipped"]
    assert "drift" in gate["skipped"]["value"]


def test_slo_block_zeroes_qps_on_missed_objective(bench, monkeypatch):
    monkeypatch.setenv("BENCH_SLO_P99_MS", "10")
    block = bench._slo_block({"value": 500.0, "p99_ms": 50.0}, {})
    assert block["slo_qps_under_p99"] == 0.0
    block = bench._slo_block({"value": 500.0, "p99_ms": 5.0}, {})
    assert block["slo_qps_under_p99"] == 500.0
    monkeypatch.delenv("BENCH_SLO_P99_MS")
    block = bench._slo_block({"value": 500.0, "p99_ms": 50.0}, {"m|": {}})
    assert block["slo_qps_under_p99"] == 500.0
    assert block["slo_series"] == {"m|": {}}


def test_link_drift_floor_blocks_the_escape_hatch(bench):
    """Sub-millisecond baseline RTTs turn microsecond jitter into huge
    drift percentages — below the 1 ms floor the drift escape hatch
    stays shut and real regressions still fail the gate."""
    gate = bench._slo_gate(
        {"value": 100.0, "mp_link_drift_pct": 143.7, "link_rtt_ms": 0.1},
        {"value": 200.0},
    )
    assert not gate["pass"]
    assert gate["drift_floor_applied"]
    assert not gate["skipped"]
    assert gate["regressions"][0]["key"] == "value"


def test_link_drift_above_floor_still_skips(bench):
    gate = bench._slo_gate(
        {"value": 100.0, "mp_link_drift_pct": -22.0, "link_rtt_ms": 8.0},
        {"value": 200.0},
    )
    assert gate["pass"]
    assert "value" in gate["skipped"]
    assert not gate["drift_floor_applied"]


def test_prof_block_attributes_only_ticked_engines(bench):
    split = {"compute_pct": 60.0, "dispatch_pct": 10.0,
             "host_pct": 25.0, "idle_pct": 5.0}
    report = {"engines": [
        {"engine": "serve", "ticks": 12, "attribution": split},
        {"engine": "lm", "ticks": 0, "attribution": None},
    ]}
    block = bench._prof_block(report, 0.4, "cpu_fallback")
    assert block["cnn224"] == split
    assert block["lm"] is None          # no ticks -> no made-up split
    assert block["wire"] is None
    assert block["prof_overhead_pct"] == 0.4
    assert block["peak_kind"] == "cpu_fallback"
    assert abs(sum(split.values()) - 100.0) < 0.5
