"""Resilience layer under real injected faults, on both transports.

Every scenario drives a real client through a real failure: the chaos TCP
proxy (client_tpu.testing.faults.FaultProxy) injects transport faults on
live sockets, and the server-side hooks inject application-level overload
and slowness.  Covered fault scenarios:

1. connect delay (retry under a deadline still succeeds)
2. error-N-times-then-succeed (connection resets, HTTP + gRPC, sync + aio)
3. persistent connection refusal (attempts and wall time bounded by Deadline)
4. mid-stream disconnect (gRPC streaming callback gets the error, no hung
   reader thread)
5. response byte truncation (HTTP mid-body cut is retried)
6. overload 503 shedding (engine admission + batcher queue depth), and its
   composition with client retries
7. circuit-open fast-fail
8. drain-while-busy (ready flips false, in-flight finishes, new work shed)
"""

import asyncio
import threading
import time

import numpy as np
import pytest

import client_tpu.grpc as grpcclient
import client_tpu.http as httpclient
from client_tpu.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    Deadline,
    RetryPolicy,
    call_with_retry,
)
from client_tpu.serve import Model, Server, TensorSpec
from client_tpu.testing.faults import FailNTimes, FaultProxy, GatedFn
from client_tpu.utils import InferenceServerException

# a port from the dynamic range with nothing listening (bound-and-released
# ports are not reused immediately by the kernel)
def _closed_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _echo_model(name="echo", fn=None, width=4):
    def echo(inputs, params, ctx):
        return {"OUT": inputs["IN"]}

    return Model(
        name,
        inputs=[TensorSpec("IN", "INT32", [-1, width])],
        outputs=[TensorSpec("OUT", "INT32", [-1, width])],
        fn=fn or echo,
        max_batch_size=8,
    )


def _echo_inputs(mod):
    data = np.arange(4, dtype=np.int32).reshape(1, 4)
    inp = mod.InferInput("IN", [1, 4], "INT32")
    inp.set_data_from_numpy(data)
    return [inp], data


def _fast_policy(**kw):
    kw.setdefault("max_attempts", 5)
    kw.setdefault("initial_backoff_s", 0.02)
    kw.setdefault("max_backoff_s", 0.1)
    return RetryPolicy(**kw)


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


@pytest.fixture(scope="module")
def server():
    models = [_echo_model(), _echo_model("echo_big", width=1024)]
    with Server(models=models, grpc_port=0) as s:
        yield s


# -- policy unit behavior ---------------------------------------------------


class TestPolicyUnits:
    def test_deadline_bounds_attempts_and_wall_time(self):
        """Acceptance: under a persistent failure, total attempts and wall
        time stay bounded by the configured Deadline — no retry storm."""
        calls = []

        def always_down(timeout_s):
            calls.append(timeout_s)
            raise ConnectionRefusedError("injected: endpoint down")

        policy = RetryPolicy(
            max_attempts=100,  # deliberately generous: the deadline must bind
            initial_backoff_s=0.05,
            backoff_multiplier=2.0,
            max_backoff_s=0.2,
            jitter=False,
            deadline_s=0.5,
        )
        t0 = time.monotonic()
        with pytest.raises(ConnectionRefusedError):
            call_with_retry(always_down, policy)
        elapsed = time.monotonic() - t0
        # backoffs 0.05+0.1+0.2+0.2... within a 0.5s budget allow at most
        # a handful of attempts, and the loop never sleeps past the budget
        assert elapsed < 1.0
        assert 2 <= len(calls) <= 6
        # each attempt's timeout was derived from the remaining budget
        assert all(t is not None and t <= 0.5 + 1e-6 for t in calls)
        assert calls[0] > calls[-1]

    def test_retry_after_hint_is_honored_and_capped(self):
        policy = _fast_policy(max_attempts=2, max_backoff_s=0.05)
        exc = InferenceServerException("busy", status="503")
        exc.retry_after_s = 30.0  # hostile hint: capped at max_backoff_s
        assert policy.delay_for(exc, 0) == 0.05
        exc.retry_after_s = 0.01
        assert policy.delay_for(exc, 0) == 0.01

    def test_non_retryable_fails_immediately(self):
        calls = []

        def bad_request(timeout_s):
            calls.append(1)
            raise InferenceServerException("no such model", status="400")

        with pytest.raises(InferenceServerException, match="no such model"):
            call_with_retry(bad_request, _fast_policy())
        assert len(calls) == 1

    def test_circuit_breaker_transitions(self):
        breaker = CircuitBreaker(failure_threshold=2, reset_timeout_s=0.1)
        assert breaker.state == CircuitBreaker.CLOSED
        breaker.record_failure()
        breaker.before_attempt()  # still closed below threshold
        breaker.record_failure()
        assert breaker.state == CircuitBreaker.OPEN
        with pytest.raises(CircuitOpenError):
            breaker.before_attempt()
        time.sleep(0.12)
        breaker.before_attempt()  # half-open probe allowed
        assert breaker.state == CircuitBreaker.HALF_OPEN
        breaker.record_failure()  # probe failed: straight back to open
        assert breaker.state == CircuitBreaker.OPEN
        time.sleep(0.12)
        breaker.before_attempt()
        breaker.record_success()
        assert breaker.state == CircuitBreaker.CLOSED

    def test_non_retryable_errors_do_not_trip_breaker(self):
        """A 4xx application error proves the endpoint answered: it must
        not open the circuit against a healthy server."""
        breaker = CircuitBreaker(failure_threshold=2, reset_timeout_s=60.0)
        policy = _fast_policy(max_attempts=1, circuit_breaker=breaker)

        def bad_request(timeout_s):
            raise InferenceServerException("no such model", status="400")

        for _ in range(5):
            with pytest.raises(InferenceServerException, match="no such model"):
                call_with_retry(bad_request, policy)
        assert breaker.state == CircuitBreaker.CLOSED

    def test_half_open_admits_single_probe(self):
        """Concurrent callers keep fast-failing while the one half-open
        probe is in flight — no herd onto a recovering endpoint."""
        breaker = CircuitBreaker(failure_threshold=1, reset_timeout_s=0.05)
        breaker.record_failure()
        assert breaker.state == CircuitBreaker.OPEN
        time.sleep(0.06)
        breaker.before_attempt()  # the probe passes
        with pytest.raises(CircuitOpenError):
            breaker.before_attempt()  # a concurrent caller does not
        breaker.record_success()
        breaker.before_attempt()
        assert breaker.state == CircuitBreaker.CLOSED

    def test_deadline_expiry(self):
        d = Deadline(0.05)
        assert not d.expired()
        assert 0 < d.attempt_timeout() <= 0.05
        time.sleep(0.06)
        assert d.expired()
        assert d.attempt_timeout() == 0.0


class TestDeadlineAttemptTimeout:
    """Direct coverage of the per-attempt timeout derivation — previously
    only exercised indirectly through client e2e retries."""

    def test_cap_below_remaining_wins(self):
        d = Deadline(10.0)
        assert d.attempt_timeout(cap=0.5) == 0.5

    def test_remaining_wins_when_cap_above_budget(self):
        d = Deadline(0.2)
        t = d.attempt_timeout(cap=5.0)
        assert 0 < t <= 0.2

    def test_no_cap_returns_remaining(self):
        d = Deadline(0.5)
        t = d.attempt_timeout()
        assert 0 < t <= 0.5

    def test_expired_budget_clamps_to_zero(self):
        d = Deadline(0.01)
        time.sleep(0.02)
        # an expired budget must never produce a negative transport
        # timeout (urllib3/aiohttp/grpc all reject those)
        assert d.attempt_timeout() == 0.0
        assert d.attempt_timeout(cap=3.0) == 0.0
        assert d.attempt_timeout(cap=0.0) == 0.0

    def test_zero_or_negative_budget_rejected_at_construction(self):
        for bad in (0, -1, -0.5, None):
            with pytest.raises(ValueError):
                Deadline(bad)


class TestHalfOpenSingleProbeRace:
    """The half-open gate under real thread contention: exactly one of N
    simultaneous callers may probe a cooled-down open circuit."""

    def _race(self, breaker, n=8):
        barrier = threading.Barrier(n)
        outcomes = []
        lock = threading.Lock()

        def contender():
            barrier.wait()
            try:
                breaker.before_attempt()
            except CircuitOpenError:
                with lock:
                    outcomes.append("rejected")
            else:
                with lock:
                    outcomes.append("admitted")

        threads = [threading.Thread(target=contender) for _ in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert not any(t.is_alive() for t in threads)
        return outcomes

    def test_exactly_one_contender_probes(self):
        breaker = CircuitBreaker(failure_threshold=1, reset_timeout_s=0.05)
        breaker.record_failure()
        assert breaker.state == CircuitBreaker.OPEN
        time.sleep(0.06)
        outcomes = self._race(breaker)
        assert outcomes.count("admitted") == 1
        assert outcomes.count("rejected") == len(outcomes) - 1
        assert breaker.state == CircuitBreaker.HALF_OPEN

    def test_failed_probe_reopens_and_regates_next_herd(self):
        breaker = CircuitBreaker(failure_threshold=1, reset_timeout_s=0.05)
        breaker.record_failure()
        time.sleep(0.06)
        assert self._race(breaker).count("admitted") == 1
        breaker.record_failure()  # the probe failed: straight back to open
        assert breaker.state == CircuitBreaker.OPEN
        with pytest.raises(CircuitOpenError):
            breaker.before_attempt()  # cooldown restarted
        time.sleep(0.06)
        assert self._race(breaker).count("admitted") == 1  # one new probe
        breaker.record_success()
        assert breaker.state == CircuitBreaker.CLOSED
        # closed circuit admits everyone again
        outcomes = self._race(breaker)
        assert outcomes.count("admitted") == len(outcomes)


class TestSerialDeliverer:
    """The lock-free observer-delivery queue behind pool/breaker
    notifications: ordered, re-entrant, and never latched by a raising
    callback."""

    def test_raising_delivery_does_not_latch_the_drainer(self):
        from client_tpu.resilience import _SerialDeliverer

        d = _SerialDeliverer()
        delivered = []
        with pytest.raises(RuntimeError):
            d.post(lambda: (_ for _ in ()).throw(RuntimeError("boom")))
        # the deliverer must have unlatched: later posts still deliver
        d.post(lambda: delivered.append("after"))
        assert delivered == ["after"]

    def test_reentrant_post_delivers_in_order(self):
        from client_tpu.resilience import _SerialDeliverer

        d = _SerialDeliverer()
        delivered = []

        def first():
            delivered.append("first")
            d.post(lambda: delivered.append("nested"))  # from inside

        d.post(first)
        d.post(lambda: delivered.append("second"))
        assert delivered == ["first", "nested", "second"]

    def test_accept_vetoes_stale_delivery(self):
        from client_tpu.resilience import _SerialDeliverer

        d = _SerialDeliverer()
        delivered = []
        d.post(lambda: delivered.append("kept"), accept=lambda: True)
        d.post(lambda: delivered.append("dropped"), accept=lambda: False)
        assert delivered == ["kept"]


# -- scenario 1+2: delay and error-then-succeed over HTTP -------------------


class TestHttpFaults:
    def test_error_then_succeed(self, server):
        with FaultProxy(server.http_address) as proxy:
            proxy.reset_next_connections(2)
            with httpclient.InferenceServerClient(
                proxy.address, retry_policy=_fast_policy()
            ) as client:
                inputs, data = _echo_inputs(httpclient)
                result = client.infer("echo", inputs)
                np.testing.assert_array_equal(result.as_numpy("OUT"), data)
            assert proxy.connections >= 3  # two resets + the success

    def test_connect_delay_within_deadline(self, server):
        with FaultProxy(server.http_address) as proxy:
            proxy.set_delay(0.1)
            with httpclient.InferenceServerClient(
                proxy.address, retry_policy=_fast_policy(deadline_s=5.0)
            ) as client:
                inputs, data = _echo_inputs(httpclient)
                result = client.infer("echo", inputs)
                np.testing.assert_array_equal(result.as_numpy("OUT"), data)

    def test_truncated_response_is_retried(self, server):
        with FaultProxy(server.http_address) as proxy:
            # Cut the first connection's response mid-BODY (the 4 KiB binary
            # tensor guarantees the cut lands past the HTTP headers, where
            # the Content-Length mismatch is a hard transport error —
            # truncating inside the headers can parse as an empty 200);
            # the retry's fresh connection passes through intact.
            proxy.cut_responses_after(600, times=1)
            with httpclient.InferenceServerClient(
                proxy.address, retry_policy=_fast_policy()
            ) as client:
                data = np.arange(1024, dtype=np.int32).reshape(1, 1024)
                inp = httpclient.InferInput("IN", [1, 1024], "INT32")
                inp.set_data_from_numpy(data)
                outputs = [httpclient.InferRequestedOutput("OUT", binary_data=True)]
                result = client.infer("echo_big", [inp], outputs=outputs)
                np.testing.assert_array_equal(result.as_numpy("OUT"), data)
            assert proxy.connections >= 2

    def test_persistent_refusal_bounded_by_deadline(self, server):
        with FaultProxy(server.http_address) as proxy:
            proxy.refuse_connections(True)
            policy = _fast_policy(max_attempts=50, deadline_s=0.6)
            with httpclient.InferenceServerClient(
                proxy.address, retry_policy=policy
            ) as client:
                inputs, _ = _echo_inputs(httpclient)
                t0 = time.monotonic()
                with pytest.raises(InferenceServerException):
                    client.infer("echo", inputs)
                elapsed = time.monotonic() - t0
            assert elapsed < 2.0  # deadline bound, not 50 attempts' worth
            assert proxy.connections <= 30

    def test_without_policy_behavior_unchanged(self, server):
        with FaultProxy(server.http_address) as proxy:
            proxy.reset_next_connections(1)
            with httpclient.InferenceServerClient(proxy.address) as client:
                inputs, _ = _echo_inputs(httpclient)
                with pytest.raises(InferenceServerException):
                    client.infer("echo", inputs)  # single attempt: fails
            assert proxy.connections == 1


# -- scenario 2 over gRPC (sync + aio) --------------------------------------

# After a connection failure the channel sits in TRANSIENT_FAILURE for its
# own reconnect backoff; shrink it so the retry policy's attempts map to
# real reconnects instead of burning against the cached channel state.
_FAST_RECONNECT = [
    ("grpc.initial_reconnect_backoff_ms", 50),
    ("grpc.min_reconnect_backoff_ms", 50),
    ("grpc.max_reconnect_backoff_ms", 100),
]


def _grpc_policy():
    return RetryPolicy(
        max_attempts=6, initial_backoff_s=0.1, max_backoff_s=0.2, jitter=False
    )


class TestGrpcFaults:
    def test_error_then_succeed(self, server):
        with FaultProxy(server.grpc_address) as proxy:
            proxy.reset_next_connections(1)
            with grpcclient.InferenceServerClient(
                proxy.address,
                retry_policy=_grpc_policy(),
                channel_args=_FAST_RECONNECT,
            ) as client:
                inputs, data = _echo_inputs(grpcclient)
                result = client.infer("echo", inputs)
                np.testing.assert_array_equal(result.as_numpy("OUT"), data)

    def test_aio_error_then_succeed(self, server):
        import client_tpu.grpc.aio as aiogrpc

        async def flow(proxy):
            proxy.reset_next_connections(1)
            async with aiogrpc.InferenceServerClient(
                proxy.address,
                retry_policy=_grpc_policy(),
                channel_args=_FAST_RECONNECT,
            ) as client:
                inputs, data = _echo_inputs(aiogrpc)
                result = await client.infer("echo", inputs)
                np.testing.assert_array_equal(result.as_numpy("OUT"), data)

        with FaultProxy(server.grpc_address) as proxy:
            _run(flow(proxy))

    def test_midstream_disconnect_reaches_stream_callback(self, server):
        """Satellite: a mid-stream disconnect must surface to the stream
        callback as an error and leave no hung reader thread."""
        with FaultProxy(server.grpc_address) as proxy:
            client = grpcclient.InferenceServerClient(proxy.address)
            events = []
            got_event = threading.Event()

            def callback(result, error):
                events.append((result, error))
                got_event.set()

            client.start_stream(callback)
            inputs, data = _echo_inputs(grpcclient)
            client.async_stream_infer("echo", inputs)
            assert got_event.wait(timeout=10)  # first response arrived
            result, error = events[0]
            assert error is None
            np.testing.assert_array_equal(result.as_numpy("OUT"), data)

            got_event.clear()
            proxy.kill_active()  # mid-stream disconnect
            assert got_event.wait(timeout=10)
            result, error = events[-1]
            assert error is not None
            assert isinstance(error, InferenceServerException)

            handler = client._stream._handler
            client.stop_stream()
            handler.join(timeout=5)
            assert not handler.is_alive()  # no hung reader thread
            client.close()


# -- aio HTTP ---------------------------------------------------------------


class TestHttpAioFaults:
    def test_error_then_succeed(self, server):
        import client_tpu.http.aio as aiohttpclient

        async def flow(proxy):
            proxy.reset_next_connections(2)
            async with aiohttpclient.InferenceServerClient(
                proxy.address, retry_policy=_fast_policy()
            ) as client:
                inputs, data = _echo_inputs(aiohttpclient)
                result = await client.infer("echo", inputs)
                np.testing.assert_array_equal(result.as_numpy("OUT"), data)

        with FaultProxy(server.http_address) as proxy:
            _run(flow(proxy))


# -- scenario 6: overload shedding + composition with retries ---------------


class TestOverload:
    def test_engine_admission_sheds_with_retryable_503(self):
        gated = GatedFn(lambda inputs, params, ctx: {"OUT": inputs["IN"]})
        with Server(
            models=[_echo_model("gated", fn=gated)],
            with_default_models=False,
            max_inflight=1,
        ) as server:
            with httpclient.InferenceServerClient(
                server.http_address, concurrency=2
            ) as client:
                inputs, _ = _echo_inputs(httpclient)
                first = client.async_infer("gated", inputs)
                assert gated.entered.wait(timeout=10)
                # capacity is taken: the second request is shed retryably
                with pytest.raises(InferenceServerException) as exc_info:
                    client.infer("gated", inputs)
                assert exc_info.value.status() == "503"
                assert "overloaded" in str(exc_info.value)
                gated.release()
                first.get_result(timeout=10)  # in-flight work completed

    def test_client_retries_compose_with_server_shedding(self):
        gated = GatedFn(lambda inputs, params, ctx: {"OUT": inputs["IN"]})
        with Server(
            models=[_echo_model("gated", fn=gated)],
            with_default_models=False,
            max_inflight=1,
        ) as server:
            with httpclient.InferenceServerClient(
                server.http_address,
                concurrency=2,
                retry_policy=_fast_policy(max_attempts=40, max_backoff_s=0.05),
            ) as client:
                inputs, data = _echo_inputs(httpclient)
                first = client.async_infer("gated", inputs)
                assert gated.entered.wait(timeout=10)
                # the retrying client keeps backing off while the slot is
                # held, and lands once it frees
                releaser = threading.Timer(0.2, gated.release)
                releaser.start()
                try:
                    result = client.infer("gated", inputs)
                finally:
                    releaser.cancel()
                np.testing.assert_array_equal(result.as_numpy("OUT"), data)
                first.get_result(timeout=10)

    def test_batcher_queue_depth_sheds(self):
        gated = GatedFn(lambda inputs, params, ctx: {"OUT": inputs["IN"]})
        model = _echo_model("batched", fn=gated)
        model.dynamic_batching = True
        model.max_queue_depth = 1
        with Server(models=[model], with_default_models=False) as server:
            with httpclient.InferenceServerClient(
                server.http_address, concurrency=4
            ) as client:
                inputs, _ = _echo_inputs(httpclient)
                # wave 1 occupies the batcher thread inside model.fn ...
                first = client.async_infer("batched", inputs)
                assert gated.entered.wait(timeout=10)
                # ... so of wave 2, exactly one fits the depth-1 queue and
                # the rest shed with the retryable 503
                wave = [client.async_infer("batched", inputs) for _ in range(4)]
                # shed responses return immediately; wait until the three
                # rejections are in before releasing the gate (releasing
                # early would let the batcher drain the queue under them)
                deadline = time.monotonic() + 10
                while (
                    sum(w._future.done() for w in wave) < 3
                    and time.monotonic() < deadline
                ):
                    time.sleep(0.01)
                gated.release()
                outcomes = []
                for p in [first] + wave:
                    try:
                        p.get_result(timeout=15)
                        outcomes.append("ok")
                    except InferenceServerException as e:
                        outcomes.append(e.status())
                assert outcomes[0] == "ok"  # dispatched work completed
                assert "503" in outcomes[1:]
                assert "ok" in outcomes[1:]  # the queued one landed too


# -- scenario 7: circuit breaker fast-fail ----------------------------------


class TestCircuitBreaker:
    def test_open_circuit_fast_fails_without_network(self):
        port = _closed_port()
        breaker = CircuitBreaker(failure_threshold=2, reset_timeout_s=60.0)
        policy = _fast_policy(max_attempts=1, circuit_breaker=breaker)
        with httpclient.InferenceServerClient(
            f"127.0.0.1:{port}", retry_policy=policy
        ) as client:
            inputs, _ = _echo_inputs(httpclient)
            for _ in range(2):  # trip the breaker
                with pytest.raises(InferenceServerException):
                    client.infer("echo", inputs)
            assert breaker.state == CircuitBreaker.OPEN
            t0 = time.monotonic()
            with pytest.raises(CircuitOpenError, match="circuit breaker"):
                client.infer("echo", inputs)
            # fast-fail: no connect attempt, no backoff sleep
            assert time.monotonic() - t0 < 0.05


# -- scenario 8: graceful drain ---------------------------------------------


class TestDrain:
    def test_drain_while_busy(self):
        gated = GatedFn(lambda inputs, params, ctx: {"OUT": inputs["IN"]})
        server = Server(
            models=[_echo_model("gated", fn=gated)],
            with_default_models=False,
            grpc_port=0,
        ).start()
        http = httpclient.InferenceServerClient(server.http_address, concurrency=2)
        grpc_client = grpcclient.InferenceServerClient(server.grpc_address)
        try:
            assert http.is_server_ready()
            assert grpc_client.is_server_ready()
            inputs, data = _echo_inputs(httpclient)
            inflight = http.async_infer("gated", inputs)
            assert gated.entered.wait(timeout=10)

            drained = []
            drainer = threading.Thread(
                target=lambda: drained.append(server.engine.drain(timeout_s=20))
            )
            drainer.start()
            deadline = time.monotonic() + 5
            while http.is_server_ready() and time.monotonic() < deadline:
                time.sleep(0.01)
            # readiness flipped on BOTH frontends while work is in flight
            assert not http.is_server_ready()
            assert not grpc_client.is_server_ready()
            assert http.is_server_live()  # live stays true: process is up

            # new work is shed with the retryable 503/UNAVAILABLE
            with pytest.raises(InferenceServerException) as http_exc:
                http.infer("gated", inputs)
            assert http_exc.value.status() == "503"
            g_inputs, _ = _echo_inputs(grpcclient)
            with pytest.raises(InferenceServerException) as grpc_exc:
                grpc_client.infer("gated", g_inputs)
            assert grpc_exc.value.status() == "UNAVAILABLE"

            gated.release()
            drainer.join(timeout=20)
            assert drained == [True]  # fully drained within budget
            result = inflight.get_result(timeout=10)  # in-flight completed
            np.testing.assert_array_equal(result.as_numpy("OUT"), data)
        finally:
            http.close()
            grpc_client.close()
            server.stop()

    def test_unary_decoupled_rejection_does_not_leak_inflight(self):
        """A decoupled model called over unary RPC is rejected before its
        response stream is iterated; the admission slot must be released
        anyway (a leak here wedges max_inflight and hangs drain)."""

        def gen_fn(inputs, params, ctx):
            yield {"OUT": inputs["IN"]}

        model = _echo_model("dec", fn=gen_fn)
        model.decoupled = True
        with Server(
            models=[model],
            with_default_models=False,
            grpc_port=0,
            max_inflight=1,
        ) as server:
            with grpcclient.InferenceServerClient(server.grpc_address) as client:
                inputs, _ = _echo_inputs(grpcclient)
                for _ in range(3):  # with a leak, call 2+ would 503
                    with pytest.raises(
                        InferenceServerException, match="decoupled"
                    ):
                        client.infer("dec", inputs)
            assert server.engine.drain(timeout_s=2.0) is True

    def test_drain_timeout_reports_false(self):
        gated = GatedFn(lambda inputs, params, ctx: {"OUT": inputs["IN"]})
        with Server(
            models=[_echo_model("gated", fn=gated)], with_default_models=False
        ) as server:
            with httpclient.InferenceServerClient(
                server.http_address, concurrency=2
            ) as client:
                inputs, _ = _echo_inputs(httpclient)
                inflight = client.async_infer("gated", inputs)
                assert gated.entered.wait(timeout=10)
                t0 = time.monotonic()
                assert server.engine.drain(timeout_s=0.2) is False
                assert time.monotonic() - t0 < 2.0
                gated.release()
                inflight.get_result(timeout=10)


# -- satellite: health verbs answer False against a dead endpoint -----------


class TestHealthParity:
    def test_http_sync_health_false_on_closed_port(self):
        url = f"127.0.0.1:{_closed_port()}"
        with httpclient.InferenceServerClient(url) as client:
            assert client.is_server_live() is False
            assert client.is_server_ready() is False
            assert client.is_model_ready("echo") is False

    def test_grpc_sync_health_false_on_closed_port(self):
        url = f"127.0.0.1:{_closed_port()}"
        with grpcclient.InferenceServerClient(url) as client:
            assert client.is_server_live() is False
            assert client.is_server_ready() is False
            assert client.is_model_ready("echo") is False

    def test_http_aio_health_false_on_closed_port(self):
        import client_tpu.http.aio as aiohttpclient

        async def flow():
            url = f"127.0.0.1:{_closed_port()}"
            async with aiohttpclient.InferenceServerClient(url) as client:
                assert await client.is_server_live() is False
                assert await client.is_server_ready() is False
                assert await client.is_model_ready("echo") is False

        _run(flow())

    def test_grpc_aio_health_false_on_closed_port(self):
        import client_tpu.grpc.aio as aiogrpc

        async def flow():
            url = f"127.0.0.1:{_closed_port()}"
            async with aiogrpc.InferenceServerClient(url) as client:
                assert await client.is_server_live() is False
                assert await client.is_server_ready() is False
                assert await client.is_model_ready("echo") is False

        _run(flow())
