"""Fleet-scale resilience acceptance (client_tpu/serve/fleet.py + the
balance-layer routing half): the cross-replica cache tier, prefix-aware
routing, fleet-wide tenant accounting, the degraded-tier guarantee, and
the three-replica kill-mid-stream chaos scenario.

The chaos acceptance runs the replica set in-process (three LmEngines
sharing one model's weights, each with its own FleetTier peer) so the
whole scenario — mixed-tenant shared-prefix load, one replica killed
mid-stream, byte-exact resume on a survivor from the shared tier — fits
the tier-1 budget; ``make soak`` repeats the slow-marked scaled variant.
"""

import queue
import socket
import threading
import time
import types

import numpy as np
import pytest

import jax

from client_tpu.balance.policy import PrefixAware, make_policy
from client_tpu.balance.pool import Endpoint, EndpointPool
from client_tpu.serve.fleet import FleetTier, chain_digests, fetch_summary
from client_tpu.serve.frontdoor import TenantQoS
from client_tpu.serve.lm import LmEngine
from client_tpu.serve.metrics import Registry
from client_tpu.serve.models import transformer as tfm
from client_tpu.utils import SERVER_READY

CLOSE = LmEngine.CLOSE

CFG = tfm.TransformerConfig(
    vocab_size=128,
    d_model=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    d_ff=128,
    max_seq=96,
    dtype="float32",
)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(jax.random.PRNGKey(0), CFG)


def _serial(params, prompt, n):
    return list(tfm.generate(params, CFG, prompt, n, readback_depth=0))


def _collect(q, timeout=120):
    out = []
    while True:
        tok = q.get(timeout=timeout)
        if tok is CLOSE:
            return out
        out.append(tok)


def _tier(**kwargs):
    kwargs.setdefault("gossip_interval_s", 0)  # tests gossip explicitly
    return FleetTier(**kwargs).start()


def _peer_up(tiers):
    for tier in tiers:
        tier.set_peers([t.address for t in tiers if t is not tier])


def _engine(params, fleet=None, registry=None, **kwargs):
    kwargs.setdefault("max_slots", 2)
    kwargs.setdefault("lane_counts", (2,))
    kwargs.setdefault("block_size", 8)
    kwargs.setdefault("prefill_chunk", 16)
    kwargs.setdefault("min_bucket", 4)
    return LmEngine(params, CFG, registry=registry or Registry(),
                    fleet=fleet, **kwargs)


# -- units: digests, store, transport --------------------------------------

def test_chain_digests_cumulative_and_block_aligned():
    row = list(range(40))
    digs = chain_digests(row, 8)
    assert len(digs) == 5  # full blocks only
    assert chain_digests(row, 8, max_blocks=2) == digs[:2]
    # cumulative: a different earlier block changes every later digest
    other = [99] + row[1:]
    assert chain_digests(other, 8)[0] != digs[0]
    assert chain_digests(other, 8)[4] != digs[4]
    # a shared prefix shares the digest chain exactly
    assert chain_digests(row[:16] + [7] * 24, 8)[:2] == digs[:2]
    assert len(chain_digests(row[:7], 8)) == 0  # no full block, no digest


def test_prefix_store_roundtrip_and_lru_bound():
    tier = FleetTier(max_store_blocks=4, gossip_interval_s=0)
    row = np.arange(32)
    host_k = [np.random.rand(4, 8, 2, 4).astype(np.float32)
              for _ in range(CFG.n_layers)]
    host_v = [np.random.rand(4, 8, 2, 4).astype(np.float32)
              for _ in range(CFG.n_layers)]
    tier.export_prefix(row, 4, 8, host_k, host_v)
    got = tier.store.lookup(row, 8, 4)
    assert got is not None and got[0] == 4
    np.testing.assert_array_equal(got[1][0], host_k[0])
    np.testing.assert_array_equal(got[2][1], host_v[1])
    # partial walk stops at the first missing chain link
    assert tier.store.lookup(np.arange(16), 8, 2)[0] == 2
    assert tier.store.lookup(np.concatenate([np.arange(8), [99] * 8]),
                             8, 2)[0] == 1
    # LRU bound: inserting a second chain evicts the oldest blocks
    tier.export_prefix(np.arange(100, 132), 4, 8, host_k, host_v)
    assert tier.store.blocks == 4
    assert tier.store.lookup(np.arange(100, 132), 8, 4)[0] == 4


def test_peer_prefix_and_summary_roundtrip():
    a, b = _tier(), _tier()
    try:
        _peer_up([a, b])
        row = np.arange(24)
        host_k = [np.random.rand(3, 8, 2, 4).astype(np.float32)
                  for _ in range(CFG.n_layers)]
        host_v = [np.random.rand(3, 8, 2, 4).astype(np.float32)
                  for _ in range(CFG.n_layers)]
        b.export_prefix(row, 3, 8, host_k, host_v)
        got = a.prefix_lookup(row, 8, 3)
        assert got is not None and got[0] == 3
        np.testing.assert_array_equal(got[1][0], host_k[0])
        assert a.stats()["peer_hits"] == 1
        # served counts AFTER the reply is sent: the client can observe
        # the answer a beat before the handler bumps the counter
        deadline = time.monotonic() + 5
        while b.stats()["served"] < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert b.stats()["served"] >= 1
        # the gossip/probe summary carries b's chain digests
        summary = fetch_summary(b.address)
        assert summary["prefix_digests"] == chain_digests(row, 8, 3)
        # total miss: every peer answers, nobody has it
        assert a.prefix_lookup(np.arange(50, 74), 8, 3) is None
        assert a.stats()["peer_misses"] == 1
    finally:
        a.close()
        b.close()


def test_prefix_lookup_start_offset_transfers_only_the_tail():
    """``start_blocks`` keeps locally-held blocks off the wire: the
    response covers chain blocks [start, covered) only, and a peer whose
    chain ends at or before the asker's local match is a miss."""
    a, b = _tier(), _tier()
    try:
        _peer_up([a, b])
        row = np.arange(32)
        host_k = [np.random.rand(4, 8, 2, 4).astype(np.float32)
                  for _ in range(CFG.n_layers)]
        host_v = [np.random.rand(4, 8, 2, 4).astype(np.float32)
                  for _ in range(CFG.n_layers)]
        b.export_prefix(row, 4, 8, host_k, host_v)
        got = a.prefix_lookup(row, 8, 4, start_blocks=1)
        assert got is not None
        covered, k_layers, _v_layers, start = got
        assert (covered, start) == (4, 1)
        assert k_layers[0].shape[0] == 3  # blocks [1, 4): the tail only
        np.testing.assert_array_equal(k_layers[0], host_k[0][1:])
        # the asker already holds everything the peer has: miss, not an
        # empty payload
        assert a.prefix_lookup(row, 8, 4, start_blocks=4) is None
    finally:
        a.close()
        b.close()


def test_partial_local_match_fetches_and_installs_the_remote_tail(params):
    """Engine-level start-offset path: replica B already holds the FIRST
    block of a prompt locally (shorter shared prefix served earlier);
    the longer prompt's admission matches 1 block in the trie, fetches
    only blocks [1, covered) from the peer, and stays byte-exact."""
    tier_a, tier_b = _tier(), _tier()
    eng_a = eng_b = None
    try:
        _peer_up([tier_a, tier_b])
        eng_a = _engine(params, fleet=tier_a)
        eng_b = _engine(params, fleet=tier_b)
        long_prompt = list(range(1, 30))       # 3 full blocks of 8 + tail
        short_prompt = long_prompt[:9]         # 1 full block + 1 token
        # A serves the LONG prompt: exports 3 chain blocks to its store
        assert _collect(eng_a.submit(long_prompt, 6)[0]) == \
            _serial(params, long_prompt, 6)
        assert tier_a.stats()["store_blocks"] >= 3
        # B serves the SHORT prompt: its local trie now holds block 0
        # (that block itself may arrive over the tier — A has the chain)
        _collect(eng_b.submit(short_prompt, 2)[0])
        assert eng_b.prefix_stats()["cached_blocks"] >= 1
        before = eng_b.fleet_stats()["remote_blocks"]
        # B serves the LONG prompt: local match = 1 block, remote tail =
        # blocks [1, 3) fetched with start_blocks=1 and installed
        got = _collect(eng_b.submit(long_prompt, 6)[0])
        assert got == _serial(params, long_prompt, 6)
        assert eng_b.fleet_stats()["remote_blocks"] - before == 2
        assert eng_b.prefix_stats()["hits"] >= 1  # the local block
    finally:
        for engine in (eng_a, eng_b):
            if engine is not None:
                engine.close()
        tier_a.close()
        tier_b.close()
    assert eng_b.kv.used_blocks == 0, eng_b.kv.ref_counts()


# -- the degraded-tier guarantee -------------------------------------------

def test_degraded_tier_is_never_slower_than_no_tier(params):
    """With every peer unreachable, the tier must cost (almost) nothing:
    dead peers strike their circuit breakers open, later lookups return
    without touching the network, and end-to-end serving stays within
    noise of the no-tier baseline."""
    row = np.arange(33)

    # (1) transport level: a refused peer never blocks past the bounded
    # fan-out, and an OPEN breaker short-circuits to local-only
    tier = FleetTier(peers=["127.0.0.1:9", "127.0.0.1:11"], fan_out=2,
                     lookup_timeout_s=0.2, failure_threshold=2,
                     gossip_interval_s=0)
    try:
        for _ in range(4):  # drive both breakers past their threshold
            tier.prefix_lookup(row, 8, 4)
        t0 = time.monotonic()
        assert tier.prefix_lookup(row, 8, 4) is None
        assert time.monotonic() - t0 < 0.05  # breaker-open: no dial at all
        stats = tier.stats()
        assert stats["peer_errors"] >= 2 and stats["peer_skips"] >= 2
    finally:
        tier.close()

    # (2) a BLACKHOLE peer (accepts, never answers) is the worst case:
    # the read timeout bounds it, per peer, once — then the breaker opens
    blackhole = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    blackhole.bind(("127.0.0.1", 0))
    blackhole.listen(8)
    addr = "%s:%d" % blackhole.getsockname()[:2]
    tier = FleetTier(peers=[addr], lookup_timeout_s=0.15,
                     failure_threshold=1, gossip_interval_s=0)
    try:
        t0 = time.monotonic()
        assert tier.prefix_lookup(row, 8, 4) is None
        first = time.monotonic() - t0
        assert first < 1.0  # one peer x one bounded timeout
        t0 = time.monotonic()
        assert tier.prefix_lookup(row, 8, 4) is None
        assert time.monotonic() - t0 < 0.05  # breaker open after 1 strike
    finally:
        tier.close()
        blackhole.close()

    # (3) end to end: p99 submit->stream-complete latency and total
    # throughput with a dead-peer tier attached stay within noise of the
    # no-tier engine (generous 2.5x bound — CI scheduling jitter, not
    # the tier, is the variance here; the REAL guarantee is the breaker
    # math above: past the first strikes the tier adds ~zero per call)
    def run(fleet):
        eng = _engine(params, fleet=fleet)
        lat = []
        try:
            warm = eng.submit([5, 6, 7], 2)[0]
            _collect(warm)
            t_start = time.monotonic()
            for i in range(6):
                prompt = [i + 1] * 17  # distinct prompts: every submit
                t0 = time.monotonic()  # triggers a (possible) lookup
                _collect(eng.submit(prompt, 4)[0])
                lat.append(time.monotonic() - t0)
            total = time.monotonic() - t_start
        finally:
            eng.close()
        return total, max(lat)

    base_total, base_p99 = run(None)
    # STARTED tier: the anti-entropy replication thread is live too, so
    # this re-proves the guarantee with proactive replication enabled —
    # replication is off the request path and its failed pushes strike
    # the same breakers
    dead_tier = FleetTier(peers=["127.0.0.1:9"], lookup_timeout_s=0.1,
                          failure_threshold=1, gossip_interval_s=0,
                          hot_hits=1).start()
    try:
        assert dead_tier._repl_thread is not None  # replication armed
        degraded_total, degraded_p99 = run(dead_tier)
        assert dead_tier.stats()["peer_skips"] >= 1  # breaker did its job
    finally:
        dead_tier.close()
    assert degraded_total < base_total * 2.5 + 0.5, (
        degraded_total, base_total
    )
    assert degraded_p99 < base_p99 * 2.5 + 0.5, (degraded_p99, base_p99)


# -- prefix-aware routing ---------------------------------------------------

def test_prefix_aware_policy_picks_longest_cached_prefix():
    policy = PrefixAware(fallback="least-inflight")
    a, b, c = Endpoint("a:1"), Endpoint("b:1"), Endpoint("c:1")
    digs = ["d0", "d1", "d2", "d3"]
    a.summary = frozenset(digs[:1])
    b.summary = frozenset(digs[:3])
    c.summary = frozenset()
    ctx = {"prefix_digests": digs}
    assert policy.pick([a, b, c], ctx) is b  # longest cached prefix wins
    # ties break by load through the fallback
    a.summary = frozenset(digs[:3])
    a.inflight, b.inflight = 5, 1
    assert policy.pick([a, b, c], ctx) is b
    # no digests / no summaries: pure fallback (stale gossip degrades to
    # load balancing, never errors)
    c.inflight = 0
    assert policy.pick([a, b, c], {}) is c
    a.summary = b.summary = frozenset()
    assert policy.pick([a, b, c], ctx) is c
    assert make_policy("prefix-aware").name == "prefix-aware"


def test_probe_piggybacks_summary_into_pool_routing():
    """Health probes returning (state, digests) feed EndpointPool
    summaries — cache-aware routing costs no extra probe traffic — and
    the prefix-aware policy routes on them end to end."""
    pool = EndpointPool(["a:1", "b:1"], policy="prefix-aware")
    summaries = {
        "a:1": ["d0"],
        "b:1": ["d0", "d1"],
    }
    pool.start_probes(lambda url: (SERVER_READY, summaries[url]),
                      interval_s=0.05)
    try:
        deadline = time.monotonic() + 10
        while (set(map(len, pool.summaries().values())) != {1, 2}
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert pool.summaries()["b:1"] == frozenset(["d0", "d1"])
        ctx = {"prefix_digests": ["d0", "d1"]}
        lease = pool.lease(request_ctx=ctx)
        assert lease.url == "b:1"  # holds the longer prefix
        lease.release()
        # a plain-state probe keeps working unchanged
        pool.set_summary("a:1", ["d0", "d1", "d2"])
        lease = pool.lease(request_ctx={"prefix_digests": ["d0", "d1", "d2"]})
        assert lease.url == "a:1"
        lease.release()
    finally:
        pool.close()


def test_probe_piggybacks_pressure_into_pool_introspection():
    """Probes returning (state, digests, pressure) feed the autoscaling
    gauges: EndpointPool.pressures() surfaces the per-replica queue
    depth + prefix-affinity pressure a discovery source scales on, and
    the observer exports ctpu_fleet_pressure_* per endpoint."""
    from client_tpu.serve.metrics import BalancerMetricsObserver

    registry = Registry()
    pool = EndpointPool(
        ["a:1", "b:1"], policy="least-inflight",
        observer=BalancerMetricsObserver(registry),
    )
    feeds = {
        "a:1": {"queue_depth": 7, "prefix_hot": 2},
        "b:1": {"queue_depth": 1, "prefix_hot": 0},
    }
    pool.start_probes(
        lambda url: (SERVER_READY, [], feeds[url]), interval_s=0.05,
    )
    try:
        deadline = time.monotonic() + 10
        while (
            any(not p for p in pool.pressures().values())
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        assert pool.pressures()["a:1"] == feeds["a:1"]
        assert pool.pressures()["b:1"] == feeds["b:1"]
        assert registry.get(
            "ctpu_fleet_pressure_queue_depth", {"endpoint": "a:1"}
        ) == 7
        assert registry.get(
            "ctpu_fleet_pressure_prefix", {"endpoint": "a:1"}
        ) == 2
    finally:
        pool.close()


def test_fetch_summary_carries_pressure():
    """fetch_summary — the payload pool probes piggyback — now carries
    the replica's pressure block alongside its digests."""
    import types

    tier = _tier()
    try:
        tier.attach(types.SimpleNamespace(
            qos=None, metrics=None, response_cache=None,
            pressure=lambda: {"queue_depth": 5, "inflight": 2},
        ))
        summary = fetch_summary(tier.address)
        assert summary["pressure"]["queue_depth"] == 5
        assert "prefix_hot" in summary["pressure"]
    finally:
        tier.close()


def test_pressure_entries_age_out_behind_a_dead_replica():
    """A pressure entry not refreshed within PRESSURE_FRESH_INTERVALS
    probe intervals reads as {} — same as never-gossiped — so a dead
    replica's final numbers cannot steer the autoscaler forever.  The
    url key stays present (membership is the pool's concern; freshness
    only blanks the signals)."""
    pool = EndpointPool(["a:1", "b:1"], policy="least-inflight")
    try:
        pool._probe_interval_s = 0.05  # what start_probes would stamp
        pool.set_pressure("a:1", {"queue_depth": 9})
        pool.set_pressure("b:1", {"queue_depth": 1})
        assert pool.pressures()["a:1"] == {"queue_depth": 9}
        horizon = pool.PRESSURE_FRESH_INTERVALS * 0.05
        with pool._lock:
            for endpoint in pool._endpoints:
                if endpoint.url == "a:1":
                    endpoint.pressure_at -= horizon + 0.01
        got = pool.pressures()
        assert got["a:1"] == {}  # aged out; key still present
        assert got["b:1"] == {"queue_depth": 1}  # fresh peer unaffected
        # without an armed prober there is no staleness horizon at all
        pool._probe_interval_s = 0.0
        assert pool.pressures()["a:1"] == {"queue_depth": 9}
    finally:
        pool.close()


def test_pressure_carries_kv_occupancy_fraction():
    """FleetTier.pressure() surfaces paged-KV occupancy (used / total
    blocks) from the gauges the KV pool publishes — the earliest LM
    scale-up signal — and 0.0 when no LM model is bound, so the key is
    always present and comparable."""
    registry = Registry()
    tier = _tier(registry=registry)
    try:
        assert tier.pressure()["kv_used_fraction"] == 0.0
        registry.set("ctpu_lm_kv_blocks_used", None, 3,
                     help_="KV blocks in use")
        registry.set("ctpu_lm_kv_blocks_free", None, 1,
                     help_="KV blocks free")
        assert tier.pressure()["kv_used_fraction"] == 0.75
        assert tier.local_summary()["pressure"]["kv_used_fraction"] == 0.75
    finally:
        tier.close()


def test_replicated_client_stamps_prefix_digests_from_tokens():
    """ROADMAP fleet follow-up 3: the prefix-aware policy's
    prefix_digests request-ctx is now stamped by the replicated client
    itself — from an explicit prefix_tokens kwarg or a tokenizer-aware
    prefix_fn hook — instead of hand-built by tests/operators."""
    from client_tpu.balance.replicated import ReplicatedClient
    from client_tpu.serve import Server

    tokens = list(range(32))
    server_a, server_b = Server().start(), Server().start()
    seen = []

    class _SpyPolicy(PrefixAware):
        def pick(self, candidates, request_ctx=None):
            seen.append(dict(request_ctx or {}))
            return super().pick(candidates, request_ctx)

    pool = EndpointPool(
        [server_a.http_address, server_b.http_address],
        policy=_SpyPolicy(),
    )
    pool.set_summary(server_b.http_address, chain_digests(tokens, 16))
    client = ReplicatedClient(
        pool, transport="http", probe_interval_s=None,
        prefix_fn=lambda model, inputs: tokens, prefix_block_size=16,
    )
    try:
        from client_tpu.http import InferInput

        def infer(**kwargs):
            inputs = [InferInput("INPUT0", [1, 16], "INT32"),
                      InferInput("INPUT1", [1, 16], "INT32")]
            inputs[0].set_data_from_numpy(
                np.arange(16, dtype=np.int32).reshape(1, 16))
            inputs[1].set_data_from_numpy(np.ones((1, 16), dtype=np.int32))
            return client.infer("simple", inputs, **kwargs)

        infer()  # prefix_fn path: digests computed from the tokens
        assert seen[-1]["prefix_digests"] == chain_digests(tokens, 16)
        # the digest-holding replica won the pick (cache affinity)
        infer()
        # explicit prefix_tokens / prefix_digests kwargs override the fn
        infer(prefix_tokens=tokens[:16])
        assert seen[-1]["prefix_digests"] == chain_digests(tokens[:16], 16)
        infer(prefix_digests=["d0", "d1"])
        assert seen[-1]["prefix_digests"] == ["d0", "d1"]
    finally:
        client.close()
        server_a.stop()
        server_b.stop()


# -- fleet-wide tenant accounting ------------------------------------------

def test_tenant_quota_accounts_fleet_wide_via_gossip():
    """A flooder spraying N replicas must converge on ~1x its quota, not
    N x: each replica's admissions gossip to peers, whose buckets drain
    by the remote consumption."""
    def make_qos():
        return TenantQoS(tenants={"flood": {"rate_per_s": 0.001,
                                            "burst": 10.0}})

    # without gossip: the flooder gets the full burst on EACH replica
    qos_a, qos_b = make_qos(), make_qos()
    admitted_a = admitted_b = 0
    for _ in range(10):
        try:
            qos_a.admit("flood")()
            admitted_a += 1
        except Exception:  # noqa: BLE001
            break
    assert admitted_a == 10  # full burst locally

    # with gossip: A's consumption lands in B's bucket before the spray
    # moves over — B sheds at (burst - remote), not at its full burst
    tier_a, tier_b = _tier(), _tier()
    try:
        _peer_up([tier_a, tier_b])
        tier_a.attach(types.SimpleNamespace(qos=qos_a, metrics=None,
                                            response_cache=None))
        tier_b.attach(types.SimpleNamespace(qos=qos_b, metrics=None,
                                            response_cache=None))
        assert tier_a.gossip_now() == 1  # pushed {"flood": 10} to B
        shed = None
        for i in range(12):
            try:
                qos_b.admit("flood")()
                admitted_b += 1
            except Exception:  # noqa: BLE001
                shed = i
                break
        assert shed == 0 and admitted_b == 0, (shed, admitted_b)
        snapshot = qos_b.snapshot()
        assert snapshot["flood"]["shed"] >= 1
        # unknown tenants in a gossip payload never fabricate state
        qos_b.absorb_remote({"martian": 999})
        assert "martian" not in qos_b.snapshot()
    finally:
        tier_a.close()
        tier_b.close()


# -- response-cache tier over real servers ---------------------------------

def test_response_cache_spans_replicas_over_http():
    from client_tpu.http import InferenceServerClient
    from client_tpu.serve import Server
    from client_tpu.serve.frontdoor import ResponseCache

    def make_server():
        fleet = _tier()
        server = Server(response_cache=ResponseCache(), coalescing=True,
                        fleet=fleet)
        server.start()
        return server, fleet

    server_a, fleet_a = make_server()
    server_b, fleet_b = make_server()
    try:
        _peer_up([fleet_a, fleet_b])
        from client_tpu.http import InferInput

        def infer(server):
            with InferenceServerClient(server.http_address) as client:
                inputs = [InferInput("INPUT0", [1, 16], "INT32"),
                          InferInput("INPUT1", [1, 16], "INT32")]
                inputs[0].set_data_from_numpy(
                    np.arange(16, dtype=np.int32).reshape(1, 16))
                inputs[1].set_data_from_numpy(
                    np.ones((1, 16), dtype=np.int32))
                return client.infer("simple", inputs).as_numpy("OUTPUT0")

        out_a = infer(server_a)  # executes on A, fills A's cache
        out_b = infer(server_b)  # B misses locally, hits A's cache
        np.testing.assert_array_equal(out_a, out_b)
        assert server_b.engine.metrics.get(
            "ctpu_fleet_cache_hits_total") == 1
        # the peer hit also filled B's LOCAL cache: the next identical
        # request is a plain local hit, no peer round trip
        infer(server_b)
        assert server_b.engine.response_cache.stats()["hits"] == 1
        assert fleet_b.stats()["peer_hits"] == 1  # still just the one RPC
    finally:
        server_a.stop()
        server_b.stop()
        fleet_a.close()
        fleet_b.close()


# -- the three-replica chaos acceptance ------------------------------------

class _LmSession:
    """Client-side resumable LM session over a set of replica engines:
    tracks delivered tokens; if the serving replica dies mid-stream the
    session resubmits prompt + delivered tokens (remaining budget) on a
    survivor — the fleet tier makes that replay cheap, determinism makes
    it byte-exact, and the position arithmetic makes double-delivery
    structurally impossible to miss (duplicated positions would break
    the length/content assertions)."""

    def __init__(self, prompt, budget, tenant=""):
        self.prompt = list(prompt)
        self.budget = int(budget)
        self.tenant = tenant
        self.delivered = []
        self.hops = 0

    def run_on(self, engine):
        """Serve (or resume) on *engine*; True when the budget is met."""
        remaining = self.budget - len(self.delivered)
        if remaining <= 0:
            return True
        q, _ = engine.submit(self.prompt + self.delivered, remaining,
                             tenant=self.tenant)
        got = _collect(q)
        self.delivered.extend(got)
        self.hops += 1
        return len(self.delivered) >= self.budget


def _run_fleet_chaos(params, n_sessions, budget):
    """Three replicas under mixed-tenant shared-prefix load; replica 0
    is killed mid-stream; every session must complete byte-exact with
    zero errors, and the shared tier must add hits a single replica
    would not have had.  Expressed on the chaos-matrix harness
    (client_tpu/testing/chaos.py): the schedule is one declarative kill,
    the thread/error/wedge plumbing is the harness's."""
    from client_tpu.testing.chaos import (
        ChaosScenario,
        FaultSpec,
        run_scenario,
    )

    shared = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7, 9, 3]  # 2 blocks
    tiers = [_tier() for _ in range(3)]
    _peer_up(tiers)
    engines = [
        _engine(params, fleet=tier, max_slots=4, lane_counts=(4,))
        for tier in tiers
    ]
    # warm the shared system prefix on ONE replica (the production
    # shape: some replica served it first); the other replicas' first
    # admissions fetch it over the tier instead of recomputing
    _collect(engines[1].submit(shared + [99], 2)[0])
    assert tiers[1].stats()["store_blocks"] >= 2
    sessions = [
        _LmSession(shared + [10 + i] * 3, budget,
                   tenant="gold" if i % 2 else "bronze")
        for i in range(n_sessions)
    ]
    killed = threading.Event()

    def kill(_target):
        # kill replica 0 mid-stream: its active streams close early and
        # their sessions resume on survivors from the shared tier
        killed.set()
        engines[0].close()

    def drive(i, session):
        # sessions spread over the fleet; survivors carry the dead
        # replica's sessions to completion
        order = [engines[i % 3], engines[(i + 1) % 3], engines[(i + 2) % 3]]
        for _attempt in range(8):
            engine = next(
                e for e in order
                if not (e is engines[0] and killed.is_set())
            )
            if session.run_on(engine):
                return
        raise AssertionError("budget never met")

    scenario = ChaosScenario(
        "fleet-kill-mid-stream",
        [FaultSpec("kill_replica", at_s=0.3, target=0)],
    )
    try:
        result = run_scenario(
            scenario, lambda fault: kill(fault.target),
            [
                (lambda i=i, s=s: drive(i, s))
                for i, s in enumerate(sessions)
            ],
            join_timeout_s=600,
        )
        result.assert_clean()
        hops = sum(s.hops for s in sessions)
        for session in sessions:
            reference = _serial(params, session.prompt, session.budget)
            # byte-exact = every position delivered exactly once in
            # order: duplicates/replays would duplicate positions and
            # fail here
            assert session.delivered == reference, (
                session.prompt, session.hops
            )
        # the fleet tier contributed hits a single replica could not:
        # fleet hit rate strictly exceeds the local-trie-only rate
        local_hits = local_misses = remote_blocks = 0
        for engine in engines:
            stats = engine.prefix_stats()
            local_hits += stats.get("hits", 0)
            local_misses += stats.get("misses", 0)
            remote_blocks += engine.fleet_stats()["remote_blocks"]
        looked = local_hits + local_misses
        assert looked > 0 and remote_blocks > 0
        single_pct = 100.0 * local_hits / looked
        fleet_pct = 100.0 * min(local_hits + remote_blocks, looked) / looked
        assert fleet_pct > single_pct, (fleet_pct, single_pct)
        return hops
    finally:
        for engine in engines:
            engine.close()
        for tier in tiers:
            tier.close()
        for engine in engines[1:]:
            assert engine.kv.used_blocks == 0, engine.kv.ref_counts()


def test_three_replica_kill_mid_stream_chaos(params):
    hops = _run_fleet_chaos(params, n_sessions=4, budget=24)
    assert hops >= 4  # every session served at least once


@pytest.mark.slow
def test_three_replica_chaos_soak(params):
    """Scaled chaos repetition for `make soak`: more sessions and longer
    budgets widen the kill window so mid-stream deaths actually land."""
    _run_fleet_chaos(params, n_sessions=8, budget=40)
