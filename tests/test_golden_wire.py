"""Golden-wire guard: the KServe HTTP binary protocol, pinned as bytes.

tests/golden/ holds a canonical infer request (Python client encoding) and
the in-process server's response to it.  This suite keeps the goldens
current — any wire-format drift in the Python client or the server fails
here loudly — and the JDK-gated Java side (GoldenWireTest, run from
test_java_client.py) asserts the Java client speaks the same bytes, so the
~900-LoC Java client is machine-checked even though this image ships no
JDK.  Reference protocol: src/java/.../InferenceServerClient.java:59-221
and the HTTP binary extension (http/__init__.py:82-139 analog).
"""

import json
import os
import urllib.request

import numpy as np
import pytest

import client_tpu.http as httpclient

_GOLDEN = os.path.join(os.path.dirname(__file__), "golden")


def _meta():
    with open(os.path.join(_GOLDEN, "kserve_infer.meta.json")) as f:
        return json.load(f)


def _golden_bytes(name):
    with open(os.path.join(_GOLDEN, name), "rb") as f:
        return f.read()


def _build_request():
    in0 = np.arange(16, dtype=np.int32).reshape(1, 16)
    in1 = (np.arange(16, dtype=np.int32) + 1).reshape(1, 16)
    i0 = httpclient.InferInput("INPUT0", [1, 16], "INT32")
    i0.set_data_from_numpy(in0, binary_data=True)
    i1 = httpclient.InferInput("INPUT1", [1, 16], "INT32")
    i1.set_data_from_numpy(in1, binary_data=True)
    o0 = httpclient.InferRequestedOutput("OUTPUT0", binary_data=True)
    o1 = httpclient.InferRequestedOutput("OUTPUT1", binary_data=True)
    return httpclient.InferenceServerClient.generate_request_body(
        [i0, i1], outputs=[o0, o1], request_id="golden-1"
    )


def test_request_golden_current():
    """The Python client must reproduce the committed request bytes exactly
    (regenerate tests/golden/ via this builder if the protocol legitimately
    changes — and expect the Java assertions to need the same look)."""
    body, header_len = _build_request()
    assert header_len == _meta()["request_header_length"]
    assert bytes(body) == _golden_bytes("kserve_infer_request.bin")


def test_response_golden_current():
    """Posting the golden request bytes raw must yield the golden response
    bytes from the in-process server (wire drift on either side fails)."""
    from client_tpu.serve import Server

    meta = _meta()
    body = _golden_bytes("kserve_infer_request.bin")
    with Server(http_port=0) as srv:
        req = urllib.request.Request(
            f"http://{srv.http_address}/v2/models/simple/infer", data=body,
            headers={
                "Inference-Header-Content-Length": str(
                    meta["request_header_length"]
                ),
                "Content-Type": "application/octet-stream",
            },
        )
        with urllib.request.urlopen(req) as r:
            resp = r.read()
            resp_hlen = int(r.headers["Inference-Header-Content-Length"])
    assert resp_hlen == meta["response_header_length"]
    assert resp == _golden_bytes("kserve_infer_response.bin")


def test_response_golden_values():
    """The golden response decodes to the expected tensors (simple model:
    OUTPUT0 = INPUT0+INPUT1, OUTPUT1 = INPUT0-INPUT1) — the semantic
    anchor the Java GoldenWireTest asserts against the same file."""
    resp = _golden_bytes("kserve_infer_response.bin")
    result = httpclient.InferResult.from_response_body(
        resp, header_length=_meta()["response_header_length"]
    )
    in0 = np.arange(16, dtype=np.int32)
    in1 = in0 + 1
    np.testing.assert_array_equal(
        result.as_numpy("OUTPUT0").reshape(-1), in0 + in1
    )
    np.testing.assert_array_equal(
        result.as_numpy("OUTPUT1").reshape(-1), in0 - in1
    )
