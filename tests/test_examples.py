"""Every example is an acceptance test (the reference treats its examples
corpus as the de-facto acceptance suite — reference src/python/examples/*,
SURVEY §2.5): run each against one shared in-process server over real
sockets and require its PASS line."""

import os
import subprocess
import sys

import pytest

from client_tpu.serve import Server

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EXAMPLES = os.path.join(_REPO, "examples")

# example -> which address it takes (grpc/http).  Excludes only the
# interactive / special-setup ones covered elsewhere (image_client's file
# inputs, llm_streaming's language model set).
GRPC_EXAMPLES = [
    "simple_grpc_infer_client.py",
    "simple_grpc_async_infer_client.py",
    "simple_grpc_aio_infer_client.py",
    "simple_grpc_string_infer_client.py",
    "simple_grpc_model_control.py",
    "simple_grpc_sequence_stream_infer_client.py",
    "simple_grpc_sequence_sync_infer_client.py",
    "simple_grpc_aio_sequence_stream_infer_client.py",
    "simple_grpc_shm_client.py",
    "simple_grpc_shm_string_client.py",
    "simple_grpc_tpushm_client.py",
    "simple_grpc_health_metadata.py",
    "simple_grpc_keepalive_client.py",
    "simple_grpc_custom_args_client.py",
    "simple_grpc_custom_repeat.py",
    "simple_grpc_replicated_client.py",
    "simple_grpc_discovery_client.py",
    "ensemble_client.py",
    "ensemble_image_client.py",
    "reuse_infer_objects_client.py",
    "grpc_client.py",
    "grpc_image_client.py",
    "grpc_explicit_int_content_client.py",
    "grpc_explicit_int8_content_client.py",
    "grpc_explicit_byte_content_client.py",
    "memory_growth_test.py",
]
HTTP_EXAMPLES = [
    "simple_http_infer_client.py",
    "simple_http_async_infer_client.py",
    "simple_http_aio_infer_client.py",
    "simple_http_string_infer_client.py",
    "simple_http_health_metadata.py",
    "simple_http_model_control.py",
    "simple_http_sequence_sync_infer_client.py",
    "simple_http_replicated_client.py",
    "simple_http_shm_client.py",
    "simple_http_shm_string_client.py",
    "simple_http_tpushm_client.py",
]


@pytest.fixture(scope="module")
def server():
    with Server(grpc_port=0, http_port=0) as s:
        yield s


def _run_example(name, url):
    proc = subprocess.run(
        [sys.executable, os.path.join(_EXAMPLES, name), "-u", url],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, f"{name}: {proc.stdout}\n{proc.stderr}"
    assert "PASS" in proc.stdout, f"{name}: no PASS line\n{proc.stdout}"


@pytest.mark.parametrize("name", GRPC_EXAMPLES)
def test_grpc_example(server, name):
    _run_example(name, server.grpc_address)


@pytest.mark.parametrize("name", HTTP_EXAMPLES)
def test_http_example(server, name):
    _run_example(name, server.http_address)


def test_example_corpus_size():
    """VERDICT r02 acceptance: >=25 Python examples, all runnable."""
    names = [n for n in os.listdir(_EXAMPLES) if n.endswith(".py")]
    assert len(names) >= 25, sorted(names)
