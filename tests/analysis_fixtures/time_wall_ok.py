"""TIME-WALL clean twin: monotonic deadlines; wall clock only as data.

``time.time()`` is fine for *timestamps* (metrics, log fields) — the
rule keys on deadline semantics, not on the call itself.
"""

import time


def wait_for(predicate, timeout_s):
    deadline = time.monotonic() + timeout_s  # monotonic budget
    while not predicate():
        if time.monotonic() > deadline:
            return False
        time.sleep(0.01)
    return True


def scrape_metrics(stats):
    # wall-clock timestamps are data, not deadlines
    last_inference_ms = int(time.time() * 1000)
    return {"last_inference": last_inference_ms, "count": stats}
