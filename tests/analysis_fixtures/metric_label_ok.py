"""METRIC-LABEL clean twin: label values pass through escape_label();
interpolations OUTSIDE label positions (sample values, metric suffixes)
are untouched by the rule."""

from client_tpu.serve.metrics import escape_label


def render_model_lines(model, version, count):
    lines = []
    labels = f'{{model="{escape_label(model)}",version="{escape_label(version)}"}}'
    # value position (after the closing brace) needs no escaping
    lines.append(f"ctpu_inference_request_success{labels} {count}")
    return lines


def render_plain(name, value):
    # no label position at all: plain interpolation stays clean
    return f"ctpu_{name}_total {value}"
