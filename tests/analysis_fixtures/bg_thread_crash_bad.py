"""BG-THREAD-CRASH fixtures — the silently-dying background thread.

Freezes the endpoint-pool prober incident shape: a service loop spawned
as a ``threading.Thread`` target whose body can raise (here: tuple
unpack of an arbitrary probe result) with no top-level guard.  One
malformed result ends the thread; probing stops forever; nothing
surfaces anywhere.
"""

import threading


class Prober:
    def __init__(self, probe, interval_s=1.0):
        self._probe = probe
        self._interval_s = interval_s
        self._stop = threading.Event()
        self.states = {}

    def start(self):
        threading.Thread(target=self._probe_loop, daemon=True).start()

    def _probe_loop(self):
        while not self._stop.is_set():  # BAD: unpack can raise; loop dies
            state, summary = self._probe("replica")
            self.states["replica"] = state
            self.states["summary"] = summary
            if self._stop.wait(self._interval_s):
                return


def serve_forever(sock, handle):
    while True:  # BAD: a bad frame kills the accept loop silently
        conn, _ = sock.accept()
        handle(conn)


def start_server(sock, handle):
    thread = threading.Thread(target=serve_forever, args=(sock, handle))
    thread.start()
    return thread
