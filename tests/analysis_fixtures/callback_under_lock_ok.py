"""Post-fix twin of callback_under_lock_bad.py: state mutates under the
lock, the observer snapshot is delivered after it is released (the
``_SerialDeliverer`` discipline resilience.py/pool.py now use)."""

import threading


def _notify(observer, method, *args):
    if observer is None:
        return
    fn = getattr(observer, method, None)
    if fn is None:
        return
    try:
        fn(*args)
    except Exception:
        pass


class Pool:
    def __init__(self, observer):
        self.observer = observer
        self._lock = threading.Lock()
        self._states = {}

    def _deliver_events(self, events):
        # no lock held: observers run free to look back at the pool
        for method, args in events:
            _notify(self.observer, method, *args)

    def set_state(self, url, state):
        events = []
        with self._lock:
            if self._states.get(url) != state:
                self._states[url] = state
                events.append(("on_endpoint_state", (url, state)))
        self._deliver_events(events)
