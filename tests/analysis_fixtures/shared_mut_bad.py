"""SHARED-MUT violation: state the worker thread iterates is reassigned
from the request side without taking the lock — the thread can read a
half-updated view or lose the write entirely."""

import threading


class Batcher:
    def __init__(self):
        self._cv = threading.Condition()
        self._backlog = []
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        while True:
            try:
                with self._cv:
                    while not self._backlog:
                        self._cv.wait()
                    batch, self._backlog = self._backlog, []
                self._dispatch(batch)
            except Exception:
                pass

    def _dispatch(self, batch):
        pass

    def reset(self):
        self._backlog = []  # races the worker: no lock held
