"""NPY-TRUTH violations, modeled on the a2654c4 cancel() crash: entries
holding numpy prompts hit list membership / remove, which compare
elementwise and raise "truth value of an array is ambiguous"."""

import queue

import numpy as np


class Scheduler:
    def __init__(self):
        self._pending = []

    def submit_and_dedup(self, prompt_tokens, max_tokens):
        prompt = np.asarray(prompt_tokens, np.int32).reshape(1, -1)
        entry = [prompt, max_tokens, queue.Queue()]
        if entry in self._pending:  # elementwise compare -> ValueError
            self._pending.remove(entry)  # same crash on the remove
        self._pending.append(entry)
        return entry

    def cancel(self, handle):
        # the EXACT pre-a2654c4 shape: the numpy-bearing handle arrives as
        # a parameter; only submit_and_dedup above shows the taint, so the
        # class-level pass must connect them
        if handle in self._pending:
            self._pending.remove(handle)

    def has_tokens(self, prompt_tokens):
        arr = np.asarray(prompt_tokens, np.int32)
        if arr:  # ambiguous truth: raises for size != 1
            return True
        return bool(arr)  # same crash, spelled explicitly

    def wait_until_nonempty(self, prompt_tokens):
        arr = np.array(prompt_tokens)
        while not arr:  # ambiguous truth in the loop predicate
            arr = np.array(prompt_tokens)
        assert arr  # and in the assert
