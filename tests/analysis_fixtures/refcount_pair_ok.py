"""REFCOUNT-PAIR clean twin — every increment has a paired decrement.

The serve/lm/kv.py shape: ``retain`` increments, ``release`` decrements
and frees at zero; plain counters that are not refcount-ish (request
tallies, follower counts) increment freely without tripping the rule.
"""

import threading


class RefcountedBlockPool:
    def __init__(self, n_blocks):
        self._lock = threading.Lock()
        self._free = list(range(1, n_blocks + 1))
        self._refs = {}

    def alloc(self, n):
        with self._lock:
            if n > len(self._free):
                return None
            taken = self._free[:n]
            del self._free[:n]
            for block in taken:
                self._refs[block] = 1
            return taken

    def retain(self, blocks):
        with self._lock:
            for block in blocks:
                self._refs[block] += 1

    def release(self, blocks):
        with self._lock:
            for block in blocks:
                left = self._refs[block] - 1
                if left > 0:
                    self._refs[block] = left
                else:
                    del self._refs[block]
                    self._free.append(block)


class PlainTally:
    """Non-refcount counters increment without a paired decrement."""

    def __init__(self):
        self.requests = 0
        self.followers = 0

    def note(self):
        self.requests += 1
        self.followers += 1
