"""RESP-PARAM-OVERWRITE clean twins: the sanctioned merge via setdefault,
marker stamps onto responses freshly BUILT in the same function (nothing
to lose), and non-marker parameter dicts (no boolean constants — request
construction, shm rendering)."""


def stream_markers_merged(render):
    rendered = render()
    # merge, don't overwrite: model-set response parameters survive
    rendered[0].setdefault("parameters", {})["triton_final_response"] = False
    return rendered


def build_final_response(model_name):
    # fresh construction: the dict literal IS the response being built
    final = {
        "model_name": model_name,
        "outputs": [],
    }
    final["parameters"] = {"triton_final_response": True}
    return final


def render_shm_output(entry_params, region, nbytes):
    # non-marker dict (no boolean constants): tensor-entry bookkeeping,
    # not a completion stamp
    out = fetch_entry()
    out["parameters"] = {
        "shared_memory_region": region,
        "shared_memory_byte_size": nbytes,
    }
    return out


def fetch_entry():
    return {}
