"""Lock-order inversion: ``credit`` takes audit-then-write, ``debit``
takes write-then-(via a helper)-audit.  Each function is individually
fine — the deadlock only exists in the composition, with one edge hidden
behind a call, which is why no per-function rule can ever see it.  Two
threads, one in each method, each holding one lock and waiting for the
other: classic ABBA."""

import threading


class Ledger:
    def __init__(self):
        self._audit_lock = threading.Lock()
        self._write_lock = threading.Lock()
        self.entries = []

    def credit(self, amount):
        with self._audit_lock:
            with self._write_lock:  # edge: audit -> write
                self.entries.append(amount)

    def debit(self, amount):
        with self._write_lock:
            self.entries.append(-amount)
            self._audit()  # edge: write -> audit, one call down

    def _audit(self):
        with self._audit_lock:
            return sum(self.entries)
