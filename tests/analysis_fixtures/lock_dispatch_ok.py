"""LOCK-DISPATCH clean samples: the post-fix admission shape — slot
bookkeeping under the lock, every device dispatch outside it."""

import functools
import threading

import jax
import jax.numpy as jnp

from some_model import prefill  # noqa: F401 (fixture only)


class Scheduler:
    def __init__(self, params, cfg):
        self.params = params
        self._cv = threading.Condition()
        self._pending = []
        # binding jit is not dispatch; only calling the bound name is
        self._prefill = jax.jit(functools.partial(prefill, cfg=cfg))

    def _admit(self):
        with self._cv:
            if not self._pending:
                return None
            entry = self._pending.pop(0)
        # dispatch happens with the lock dropped
        logits, cache = self._prefill(self.params, jnp.asarray(entry[0]))
        with self._cv:
            entry[3] = logits
        return cache
