"""CV-WAIT-LOOP clean samples: predicate loops, wait_for, and Event.wait
(events latch, so the loop rule does not apply to them)."""

import threading


class Batcher:
    def __init__(self):
        self._cv = threading.Condition()
        self._cond = threading.Condition()
        self._queue = []
        self._stop = threading.Event()

    def take(self):
        with self._cv:
            while not self._queue:
                self._cv.wait()
            return self._queue.pop(0)

    def take_with_timeout(self, timeout):
        with self._cond:
            self._cond.wait_for(lambda: self._queue, timeout=timeout)
            return self._queue.pop(0) if self._queue else None

    def join(self):
        self._stop.wait()  # Event receiver: not cv-like, out of scope
