"""Suppression samples: real violations waived in place with
`# tpulint: disable=RULE -- reason` — same-line and comment-line-above
forms.  Every waiver carries its reason; reason-less waivers are
BARE-SUPPRESS findings (see bare_suppress_bad.py)."""

import threading
import time

import numpy as np


class Scheduler:
    def __init__(self):
        self._cv = threading.Condition()

    def has_tokens(self, prompt_tokens):
        arr = np.asarray(prompt_tokens, np.int32)
        if arr:  # tpulint: disable=NPY-TRUTH -- scalar array by contract
            return True
        # tpulint: disable=CV-WAIT-LOOP -- single waiter, latched predicate
        self._cv.wait()
        return False

    async def blanket_waiver(self):
        # tpulint: disable -- fixture exercising the all-rules waiver form
        time.sleep(0.1)
