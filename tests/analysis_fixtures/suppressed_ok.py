"""Suppression samples: real violations waived in place with
`# tpulint: disable=RULE` — same-line and comment-line-above forms."""

import threading
import time

import numpy as np


class Scheduler:
    def __init__(self):
        self._cv = threading.Condition()

    def has_tokens(self, prompt_tokens):
        arr = np.asarray(prompt_tokens, np.int32)
        if arr:  # tpulint: disable=NPY-TRUTH
            return True
        # single-waiter cv with a latched predicate; loop not needed here
        # tpulint: disable=CV-WAIT-LOOP
        self._cv.wait()
        return False

    async def blanket_waiver(self):
        time.sleep(0.1)  # tpulint: disable
