"""STALE-SUPPRESS fixtures: waivers whose rules no longer fire.

Three stale shapes: a waiver left behind after the hazard was fixed
(monotonic deadline, TIME-WALL long gone), a multi-rule waiver where
only one rule still fires (the other id is dead weight), and a blanket
reasoned waiver on a line where nothing fires at all.
"""

import time


def fixed_long_ago():
    # the code moved to monotonic; the waiver outlived the hazard
    deadline = time.monotonic() + 5  # tpulint: disable=TIME-WALL -- wall clock mandated (no longer true)
    return deadline


def half_stale():
    # TIME-WALL still fires (and is waived); NPY-TRUTH never did
    deadline = time.time() + 5  # tpulint: disable=TIME-WALL,NPY-TRUTH -- protocol deadline
    return deadline


def blanket_over_nothing():
    value = 1  # tpulint: disable -- defensive waiver nobody needed
    return value
