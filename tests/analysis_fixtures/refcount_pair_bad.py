"""REFCOUNT-PAIR fixture — the leaked-shared-block shape.

A block pool whose ``retain`` adds references that no method of the
class ever drops: every adoption permanently shrinks the pool (the
block is never freed and, once its owner retires, never read again).
This is the bug-class the prefix cache's refcounted sharing must never
reintroduce; the clean twin pairs the increment with ``release``.
"""

import threading


class LeakyBlockPool:
    def __init__(self, n_blocks):
        self._lock = threading.Lock()
        self._free = list(range(1, n_blocks + 1))
        self._refs = {}

    def alloc(self, n):
        with self._lock:
            if n > len(self._free):
                return None
            taken = self._free[:n]
            del self._free[:n]
            for block in taken:
                self._refs[block] = 1
            return taken

    def retain(self, blocks):
        # BAD: adds a reference no exit path of this class ever drops
        with self._lock:
            for block in blocks:
                self._refs[block] += 1

    def free_count(self):
        with self._lock:
            return len(self._free)


class LeakyCounter:
    """Same shape on a scalar attribute (``*_refcount`` spelling)."""

    def __init__(self):
        self.block_refcount = 0

    def acquire(self):
        # BAD: incremented, never decremented anywhere in the class
        self.block_refcount = self.block_refcount + 1
