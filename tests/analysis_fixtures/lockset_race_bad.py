"""LOCKSET-RACE fixtures: the pre-fix shapes of this PR's live catches.

Each class freezes one real bug the Eraser-style lockset pass surfaced
in the tree (and this PR fixed):

- ``ScrapeLoop`` — perf/metrics_manager.py's ``scrape_errors``: both
  thread roots bump a counter lock-free (read-modify-write lost update).
- ``TickEngine`` — serve/lm/engine.py's ``_tick_jits``: the scheduler
  memoizes into a dict lock-free while the caller side iterates it.
- ``Publisher`` — the pre-fix ``set_registry`` shape: a late-bound
  reference rebound with NO lock while the loop dereferences it (the
  post-fix guarded rebind is the sanctioned safe-publication pattern,
  see lockset_race_ok.py).
- ``SplitGuard`` — writes under one lock, reads under a DIFFERENT one:
  lexically every access is "under a lock", so SHARED-MUT stays silent;
  only the lockset intersection sees the empty guard set.  The write
  side is two calls deep to prove the interprocedural chain.
"""

import threading


class ScrapeLoop:
    def __init__(self):
        self._lock = threading.Lock()
        self._snapshots = []
        self.scrape_errors = 0
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def scrape(self):
        try:
            return {"up": 1}
        except Exception:
            self.scrape_errors += 1  # racy: no lock, both roots reach it
            raise

    def _loop(self):
        while True:
            try:
                snap = self.scrape()
                with self._lock:
                    self._snapshots.append(snap)
            except Exception:
                self.scrape_errors += 1  # racy: loop side, still no lock


class TickEngine:
    def __init__(self):
        self._cv = threading.Condition()
        self._jits = {}
        self._pending = []
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def submit(self, n):
        with self._cv:
            self._pending.append(n)
            self._cv.notify()

    def executables(self):
        return sum(1 for _ in self._jits.values())  # iterates lock-free

    def _loop(self):
        while True:
            try:
                with self._cv:
                    while not self._pending:
                        self._cv.wait()
                    n = self._pending.pop()
                if self._jits.get(n) is None:
                    self._jits[n] = object()  # racy: insert outside _cv
            except Exception:
                return


class Publisher:
    def __init__(self):
        self.registry = None
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def set_registry(self, registry):
        self.registry = registry  # racy: unguarded late-bind rebind

    def _loop(self):
        while True:
            try:
                registry = self.registry
                if registry is not None:
                    registry.inc("tick")
            except Exception:
                return


class SplitGuard:
    def __init__(self):
        self._lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self._inflight = {}
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def note(self, key):
        self._note_stats(key)

    def _note_stats(self, key):
        with self._stats_lock:
            self._bump(key)

    def _bump(self, key):
        self._inflight[key] = 1  # "under a lock" — the WRONG lock

    def _loop(self):
        while True:
            try:
                with self._lock:
                    for key in self._inflight:  # reader holds the other
                        _ = key
            except Exception:
                return
