"""TIME-WALL fixture: deadlines derived from the wall clock.

The shape that breaks under NTP adjustment: a deadline computed from
``time.time()`` can expire instantly (clock steps forward) or never
(clock steps back) — every timed wait keyed on it misbehaves.
"""

import time


def wait_for(predicate, timeout_s):
    deadline = time.time() + timeout_s  # BAD: wall-clock deadline
    while not predicate():
        if time.time() > deadline:  # BAD: wall-clock comparison
            return False
        time.sleep(0.01)
    return True


class Drainer:
    def drain(self, timeout_s):
        self._expires = time.time() + timeout_s  # BAD: wall-clock expiry
        return self._expires

    def schedule(self, timeout_s):
        deadline: float = time.time() + timeout_s  # BAD: annotated form
        return deadline
