"""QUEUE-SENTINEL clean samples: every deactivation closes the stream
queue in the same branch; constructor initialization is exempt."""

_CLOSE = object()


class _Slot:
    def __init__(self):
        self.active = False  # initialization, not a deactivation
        self.queue = None


class Scheduler:
    def __init__(self):
        self._slots = []

    def finish(self, slot):
        slot.queue.put(_CLOSE)
        slot.active = False
        slot.gen += 1

    def release_all(self):
        for slot in self._slots:
            if slot.active:
                slot.active = False
                slot.gen += 1
                slot.queue.put(_CLOSE)
