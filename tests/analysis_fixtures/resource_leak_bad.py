"""RESOURCE-LEAK fixture: handles that can go out of scope unreleased.

Freezes the four leak shapes the interprocedural pass exists for: a
lease whose strike path forgets ``failure()`` (released only on some
branches), a KV reservation dropped by an early return between acquire
and release, a socket that is simply never closed, and — the shape no
per-file pass can see — a reservation acquired through a WRAPPER whose
summary returns a fresh ``alloc``.  These are the pre-fix shapes of the
balance/engine lifecycle bugs the rule guards against reintroducing.
"""

import socket


def probe(pool, payload):
    lease = pool.lease(())  # BAD: released only when the reply is ok
    reply = send_probe(lease.url, payload)
    if reply.ok:
        lease.success()
        return reply
    return None  # strike path forgets lease.failure()


class Admitter:
    def reserve(self, pool, n):
        blocks = pool.alloc(n)
        if blocks is None:
            return None  # fine: nothing was acquired (backpressure)
        if blocks[0] < 0:
            return None  # BAD: early return drops the reservation
        pool.release(blocks)
        return n


def open_feed(host):
    conn = socket.create_connection((host, 9100))  # BAD: never closed
    banner = conn.recv(64)
    return banner


class PoolFronted:
    def _fresh(self, n):
        return self.kv.alloc(n)

    def admit(self, n):
        blocks = self._fresh(n)  # BAD: wrapper-acquired, never released
        if blocks is None:
            return None
        blocks.sort()


def send_probe(url, payload):
    raise NotImplementedError
