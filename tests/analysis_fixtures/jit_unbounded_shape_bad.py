"""JIT-UNBOUNDED-SHAPE fixture: the pre-fix per-prompt-length prefill
recompile shape (serve/models/continuous.py before serve/lm) — a jitted
callable fed an array whose leading shape derives from request data,
with no bucketing/padding on the path.  One distinct prompt length =
one fresh XLA executable, unbounded by anything but client behavior."""

import functools

import numpy as np

import jax
import jax.numpy as jnp


def prefill(params, tokens, cache=None):
    return tokens, cache


class Scheduler:
    def __init__(self, params, cfg):
        self.params = params
        self._prefill = jax.jit(functools.partial(prefill, cfg=cfg))

    def admit(self, prompt_tokens):
        # ragged reshape: the resulting [1, T] shape follows the request
        prompt = np.asarray(prompt_tokens, np.int32).reshape(1, -1)
        logits, _ = self._prefill(self.params, jnp.asarray(prompt))
        return logits

    def admit_unsanitized_rebind(self, prompt_tokens):
        # last assignment wins the other way: a ragged reshape AFTER a
        # sanitizer re-taints the name before the jitted dispatch
        prompt = pad_prompt(np.asarray(prompt_tokens, np.int32), 64)
        prompt = np.asarray(prompt_tokens, np.int32).reshape(1, -1)
        logits, _ = self._prefill(self.params, jnp.asarray(prompt))
        return logits
