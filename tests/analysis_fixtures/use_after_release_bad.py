"""USE-AFTER-RELEASE fixture: the handle touched after its release.

Released block indices spliced into a lane table scatter new KV writes
into blocks the free list already handed to another request; a read on
a closed file raises at best.  Both uses sit on the same sequential
path as the release.
"""


class Splice:
    def finish(self, pool, table, n):
        blocks = pool.alloc(n)
        if blocks is None:
            return
        pool.release(blocks)
        table[0] = blocks[0]  # BAD: freed index spliced into the table


def tail(path):
    fh = open(path)
    head = fh.read(1024)
    fh.close()
    return head + fh.read()  # BAD: read on the closed handle
