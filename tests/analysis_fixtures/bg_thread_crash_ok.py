"""BG-THREAD-CRASH clean fixtures — guarded service loops.

Every shape here must stay finding-free: a loop whose whole body is one
``try``, a loop nested inside a ``try``, the ``if stop.wait(): return``
sleep shape beside a ``try``, a bounded ``for`` driver, and a loop-less
one-shot worker.
"""

import threading


class GuardedProber:
    def __init__(self, probe, interval_s=1.0):
        self._probe = probe
        self._interval_s = interval_s
        self._stop = threading.Event()
        self.states = {}

    def start(self):
        threading.Thread(target=self._probe_loop, daemon=True).start()

    def _probe_loop(self):
        # OK: the whole body is one try; a broken probe result degrades
        # instead of killing the thread
        while not self._stop.is_set():
            try:
                state, summary = self._probe("replica")
                self.states["replica"] = state
                self.states["summary"] = summary
            except Exception:
                pass
            if self._stop.wait(self._interval_s):
                return


class OuterGuard:
    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        try:
            while True:  # OK: the loop itself sits under a try
                self._tick()
        except Exception:
            self._closed = True

    def _tick(self):
        pass


class BoundedDriver:
    def start(self):
        threading.Thread(target=self._drive, daemon=True).start()

    def _drive(self):
        for i in range(100):  # OK: bounded for-driver, not a service loop
            self._step(i)

    def _step(self, i):
        pass


def one_shot(conn):
    data = conn.recv(1024)  # OK: no loop at all
    conn.sendall(data)


def spawn(conn):
    threading.Thread(target=one_shot, args=(conn,), daemon=True).start()
