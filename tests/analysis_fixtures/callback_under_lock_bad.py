"""PRE-FIX observer delivery (the balance/pool.py + resilience.py shape
this PR fixed): ``_notify`` — a dynamic getattr-derived callback — runs
while the private ``_notify_lock`` is held, and a metrics observer is
invoked directly under the pool lock.  An observer that looks back at
the pool (snapshot/states) or triggers another transition re-enters a
non-reentrant private lock and deadlocks; one that blocks parks every
state transition behind third-party code."""

import threading


def _notify(observer, method, *args):
    if observer is None:
        return
    fn = getattr(observer, method, None)
    if fn is None:
        return
    try:
        fn(*args)
    except Exception:
        pass


class Pool:
    def __init__(self, observer):
        self.observer = observer
        self._lock = threading.Lock()
        self._notify_lock = threading.Lock()
        self._states = {}

    def _deliver_events(self, events):
        # BAD: the callback chain runs under the private delivery lock
        with self._notify_lock:
            for method, args in events:
                _notify(self.observer, method, *args)

    def set_state(self, url, state):
        with self._lock:
            self._states[url] = state
            # BAD: observer invoked directly under the pool lock
            self.observer.on_endpoint_state(url, state)
