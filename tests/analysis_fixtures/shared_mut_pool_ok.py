"""SHARED-MUT clean twin of the balancer fixture: every endpoint-state
write the prober thread can observe happens under the pool lock (the
shape client_tpu/balance/pool.py ships)."""

import threading


class EndpointPool:
    def __init__(self, urls):
        self._lock = threading.Lock()
        self._states = {url: "READY" for url in urls}
        self._draining = False
        self._prober = threading.Thread(target=self._probe_loop, daemon=True)

    def _probe_loop(self):
        while True:
            try:
                with self._lock:
                    if self._draining:
                        return
                    snapshot = dict(self._states)
                self._refresh(snapshot)
            except Exception:
                pass

    def _refresh(self, snapshot):
        pass

    def mark_drained(self, url):
        with self._lock:
            self._states = {**self._states, url: "NOT_READY"}

    def shutdown(self):
        with self._lock:
            self._draining = True
