"""PRE-FIX cancel() from serve/models/continuous.py (this PR's ADVICE
low finding): the active-slot branch frees the lane but never enqueues
the close sentinel, stranding any public-API reader on queue.get().
QUEUE-SENTINEL must flag the `slot.active = False` in cancel(); the
close()/_release_all_locked path (put in the same branch) must stay
clean.  Kept verbatim-shaped so the rule is proven against the real bug.
"""

_CLOSE = object()


class Scheduler:
    def __init__(self):
        self._pending = []
        self._slots = []
        self._cv = None

    def cancel(self, handle):
        """Release a stream early (consumer went away)."""
        if handle is None:
            return
        with self._cv:
            for i, entry in enumerate(self._pending):
                if entry is handle:
                    entry[2].put(_CLOSE)
                    del self._pending[i]
                    return
            placed = handle[3]
            if placed is None:
                return
            slot_idx, gen = placed
            slot = self._slots[slot_idx]
            if slot.active and slot.gen == gen:
                slot.active = False
                slot.gen += 1  # in-flight ticks for this lane drop on drain

    def _release_all_locked(self):
        """Close every pending and active stream queue (caller holds _cv)."""
        for entry in self._pending:
            entry[2].put(_CLOSE)
        self._pending.clear()
        for slot in self._slots:
            if slot.active:
                slot.active = False
                slot.gen += 1
                slot.queue.put(_CLOSE)
