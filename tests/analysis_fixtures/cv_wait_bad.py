"""CV-WAIT-LOOP violation: a condition wait with no predicate re-check
loop — spurious wakeups and consumed predicates act on stale state."""

import threading


class Batcher:
    def __init__(self):
        self._cv = threading.Condition()
        self._queue = []

    def take(self):
        with self._cv:
            if not self._queue:
                self._cv.wait()  # woken with the queue still empty
            return self._queue.pop(0)
