"""SHARED-MUT violation, discovery-shaped: live membership mutated IN
PLACE outside the pool lock while the prober thread iterates it — the
prober can see a torn list (endpoint half-added, or skip one during a
remove) and probe/route against membership that never existed."""

import threading


class EndpointPool:
    def __init__(self, urls):
        self._lock = threading.Lock()
        self._endpoints = list(urls)
        self._prober = threading.Thread(target=self._probe_loop, daemon=True)

    def _probe_loop(self):
        while True:
            try:
                with self._lock:
                    members = list(self._endpoints)
                for url in members:
                    self._probe(url)
            except Exception:
                pass

    def _probe(self, url):
        pass

    def update_endpoints(self, urls):
        # races the prober's snapshot copy: in-place mutation, no lock
        for url in urls:
            if url not in self._endpoints:
                self._endpoints.append(url)
        for url in list(self._endpoints):
            if url not in urls:
                self._endpoints.remove(url)
