"""SHARED-MUT clean twin of the discovery fixture: every in-place
membership mutation the prober thread can observe happens under the pool
lock (the shape client_tpu/balance/pool.py update_endpoints ships)."""

import threading


class EndpointPool:
    def __init__(self, urls):
        self._lock = threading.Lock()
        self._endpoints = list(urls)
        self._prober = threading.Thread(target=self._probe_loop, daemon=True)

    def _probe_loop(self):
        while True:
            try:
                with self._lock:
                    members = list(self._endpoints)
                for url in members:
                    self._probe(url)
            except Exception:
                pass

    def _probe(self, url):
        pass

    def update_endpoints(self, urls):
        with self._lock:
            for url in urls:
                if url not in self._endpoints:
                    self._endpoints.append(url)
            for url in list(self._endpoints):
                if url not in urls:
                    self._endpoints.remove(url)
