"""JIT-UNBOUNDED-SHAPE clean fixture: the fixed shape — the ragged
request array passes through a pad/bucket sanitizer before any jitted
dispatch, so the compiled-executable set is bounded by the bucket set
(serve/lm/policy.pad_prompt + geometric buckets)."""

import functools

import numpy as np

import jax
import jax.numpy as jnp

BUCKETS = (16, 32, 64)


def prefill(params, tokens, cache=None):
    return tokens, cache


def pad_prompt(prompt, width, pad_id=0):
    out = np.full((1, width), pad_id, np.int32)
    out[0, : prompt.shape[1]] = prompt[0]
    return out


def bucket_for(n):
    for width in BUCKETS:
        if n <= width:
            return width
    return BUCKETS[-1]


class Scheduler:
    def __init__(self, params, cfg):
        self.params = params
        self._prefill = jax.jit(functools.partial(prefill, cfg=cfg))

    def admit(self, prompt_tokens):
        prompt = np.asarray(prompt_tokens, np.int32).reshape(1, -1)
        # the sanitizer fixes the dispatch shape to a bucket member
        chunk = pad_prompt(prompt, bucket_for(prompt.shape[1]))
        logits, _ = self._prefill(self.params, jnp.asarray(chunk))
        return logits

    def admit_inline(self, prompt_tokens):
        prompt = np.asarray(prompt_tokens, np.int32).reshape(1, -1)
        # inline sanitizer call inside the argument list is also fixed
        logits, _ = self._prefill(
            self.params, jnp.asarray(pad_prompt(prompt, 64))
        )
        return logits

    def admit_rebind(self, prompt_tokens):
        # sanitize-in-place: the LAST assignment to the name is the
        # sanitizer, which clears the earlier ragged-reshape taint
        prompt = np.asarray(prompt_tokens, np.int32).reshape(1, -1)
        prompt = pad_prompt(prompt, bucket_for(prompt.shape[1]))
        logits, _ = self._prefill(self.params, jnp.asarray(prompt))
        return logits
