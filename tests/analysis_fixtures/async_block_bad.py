"""ASYNC-BLOCK violations: blocking calls inside async bodies — each one
stalls every coroutine sharing the event loop (the aio clients multiplex
all in-flight infers on one loop)."""

import queue
import time

import requests


class AioClient:
    def __init__(self):
        self._results = queue.Queue()

    async def infer_with_backoff(self, request):
        time.sleep(0.5)  # stalls the loop; use asyncio.sleep
        return request

    async def fetch_metadata(self, url):
        return requests.get(url)  # sync HTTP inside async

    async def next_result(self):
        return self._results.get()  # timeout-less queue get on the loop

    async def local_queue_roundtrip(self, item):
        q = queue.Queue()
        q.put(item)  # unbounded put never blocks: NOT flagged
        return q.get()  # blocks the loop if racing producers

    async def explicit_blocking_put(self, item):
        q = queue.Queue(maxsize=1)
        q.put(item, True)  # bounded + positional block=True: blocks
