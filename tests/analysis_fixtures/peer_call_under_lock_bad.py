"""Peer RPC under an engine/pool lock — the fleet-tier stall shape.

A peer lookup is timeout-bounded, so no blocking classifier fires; but
hundreds of milliseconds under the scheduler's condition lock stalls
every decode tick (and under a pool lock, every route).  Three shapes:
a direct fleet call inside the ``with``, one reached through a call
chain (invisible to any lexical rule), and a rendezvous collective
under a pool lock.
"""

import threading

from some_fleet import FleetTier  # noqa: F401 (fixture only)


class Scheduler:
    def __init__(self, fleet):
        self.fleet = fleet
        self._cv = threading.Condition()
        self._pending = []

    def submit(self, prompt):
        with self._cv:
            # BAD: peer RPC directly inside the critical section — every
            # submit/cancel/tick waiter stalls behind one slow peer
            remote = self.fleet.prefix_lookup(prompt, 8, 4)
            self._pending.append((prompt, remote))

    def admit(self):
        with self._cv:
            # BAD: the peer call is one frame below the lock — same
            # stall, invisible to any per-function rule
            self._fetch_remote()

    def _fetch_remote(self):
        return self.fleet.cache_lookup("digest")


class Pool:
    def __init__(self, rendezvous):
        self.rendezvous = rendezvous
        self._lock = threading.Lock()
        self._stable = False

    def converge(self):
        with self._lock:
            # BAD: a rendezvous collective under the pool lock — every
            # route waits on the slowest rank
            self._stable = all(self.rendezvous.all_gather(True))
