"""PRE-FIX _admit_locked from serve/models/continuous.py (this PR's
ADVICE medium finding): the jit prefill + adopt dispatches run while the
caller holds the scheduler condition lock — a novel-length prompt holds
_cv for the full XLA compile and head-of-line-blocks every submit(),
cancel(), and decode tick.  LOCK-DISPATCH must flag both dispatches via
the *_locked method-name convention AND the inline `with self._cv:`
variant below.
"""

import functools
import threading

import jax
import jax.numpy as jnp

from some_model import adopt, prefill, tick  # noqa: F401 (fixture only)


class Scheduler:
    def __init__(self, params, cfg):
        self.params = params
        self._cv = threading.Condition()
        self._pending = []
        self._slots = []
        self._prefill = jax.jit(functools.partial(prefill, cfg=cfg))
        self._adopt = jax.jit(adopt)
        self._tick = jax.jit(tick)

    def _admit_locked(self):
        """Move pending requests into free lanes (prefill + splice)."""
        admitted = False
        for slot_idx, slot in enumerate(self._slots):
            if not self._pending or slot.active:
                continue
            prompt, max_tokens, q, _ = entry = self._pending.pop(0)
            single = {}
            logits, single = self._prefill(self.params, jnp.asarray(prompt),
                                           cache=single)
            first = jnp.argmax(logits, axis=-1).astype(jnp.int32)[0]
            self._cache, self._tokens = self._adopt(
                self._cache, single, self._tokens, slot_idx, first
            )
            slot.active = True
            slot.queue = q
            admitted = True
        return admitted

    def _loop_inner(self):
        while True:
            with self._cv:
                if self._closed:
                    break
                # inline variant: tick dispatched under the lock
                self._tokens, self._cache = self._tick(
                    self.params, self._tokens, self._cache
                )
