"""SHARED-MUT violation, balancer-shaped: the endpoint pool's health
prober thread reads shared routing state that request-side methods write
without the pool lock — a probe can observe (or clobber) a half-applied
drain mark, and the router keeps sending traffic at a replica the admin
just pulled."""

import threading


class EndpointPool:
    def __init__(self, urls):
        self._lock = threading.Lock()
        self._states = {url: "READY" for url in urls}
        self._draining = False
        self._prober = threading.Thread(target=self._probe_loop, daemon=True)

    def _probe_loop(self):
        while True:
            try:
                with self._lock:
                    if self._draining:
                        return
                    snapshot = dict(self._states)
                self._refresh(snapshot)
            except Exception:
                pass

    def _refresh(self, snapshot):
        pass

    def mark_drained(self, url):
        # races the prober's snapshot copy: no lock held
        self._states = {**self._states, url: "NOT_READY"}

    def shutdown(self):
        self._draining = True  # races the prober's exit check: no lock
