"""RESP-PARAM-OVERWRITE violations: stamping a decoupled-completion marker
with a dict-literal ASSIGNMENT replaces whatever response-level parameters
the model (or the render step) already set — they vanish silently (the
ADVICE round-5 _stream_execute finding).  Both the subscript-chain shape
(rendered[0]) and the bare-name shape on a response that was NOT built in
this function must hit."""


def stream_markers(render):
    rendered = render()
    # rendered came from a call: its parameters may carry model-set keys
    rendered[0]["parameters"] = {"triton_final_response": False}
    return rendered


def stamp_final(response):
    # response is a caller's object; assignment clobbers its parameters
    response["parameters"] = {"final": True, "count": 3}
    return response
