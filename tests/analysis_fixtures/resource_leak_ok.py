"""RESOURCE-LEAK ok fixture: the exception-safe and transfer shapes.

Post-fix counterparts of resource_leak_bad.py: try/finally release, a
``with`` acquisition, success/failure on every arm of a try, ownership
transferred to a callee whose summary stores the handle, the
``if handle is None: return`` backpressure guard, and the two thread
shapes that never leak (daemon fire-and-forget, started-then-joined).
Every function here must scan clean through every rule family.
"""

import socket
import threading


def probe(pool, payload):
    lease = pool.lease(())
    try:
        reply = send_probe(lease.url, payload)
        lease.success()
        return reply
    except Exception as exc:
        lease.failure(exc, retryable=True)
        raise


class Admitter:
    def reserve(self, pool, n):
        blocks = pool.alloc(n)
        if blocks is None:
            return None  # backpressure: nothing acquired, nothing leaked
        try:
            if blocks[0] < 0:
                return None
            return n
        finally:
            pool.release(blocks)


def fetch_banner(host):
    with socket.create_connection((host, 9100)) as conn:
        return conn.recv(64)


class Handoff:
    def admit(self, pool, n):
        blocks = pool.alloc(n)
        if blocks is None:
            return None
        self._commit(blocks)  # ownership transferred: _commit stores it

    def _commit(self, blocks):
        self._table = blocks


def spawn_daemon(work):
    t = threading.Thread(target=work, daemon=True)  # dies with process
    t.start()


def run_joined(work):
    t = threading.Thread(target=work)
    t.start()
    t.join()


def send_probe(url, payload):
    raise NotImplementedError
