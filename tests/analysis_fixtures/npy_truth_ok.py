"""NPY-TRUTH clean samples: the post-a2654c4 idioms — identity scans for
membership, explicit .size/.any()/len() for truthiness."""

import numpy as np


class Scheduler:
    def __init__(self):
        self._pending = []

    def cancel(self, handle):
        # identity scan: entries hold numpy prompts, so `in`/`remove`
        # would compare element-wise
        for i, entry in enumerate(self._pending):
            if entry is handle:
                del self._pending[i]
                return

    def has_tokens(self, prompt_tokens):
        arr = np.asarray(prompt_tokens, np.int32)
        if arr.size:  # explicit emptiness
            return True
        if len(arr):
            return True
        return bool(arr.any())  # explicit reduction

    def scalar_flags_are_fine(self, n):
        count = int(n)
        if count:  # plain int: not numpy-tainted
            return True
        flags = [True, False]
        return count in [1, 2] and flags  # plain containers: fine
