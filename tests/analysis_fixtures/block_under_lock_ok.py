"""Post-fix twin of block_under_lock_bad.py: the lock covers only the
pending-pop bookkeeping; the jit dispatch and the host sleep run with
the lock released (the real serve/models/continuous.py ``_admit``
structure)."""

import functools
import threading
import time

import jax
import jax.numpy as jnp

from some_model import prefill  # noqa: F401 (fixture only)


class Scheduler:
    def __init__(self, params, cfg):
        self.params = params
        self._cv = threading.Condition()
        self._pending = []
        self._prefill = jax.jit(functools.partial(prefill, cfg=cfg))

    def _loop(self):
        while True:
            with self._cv:
                if not self._pending:
                    return
                entry = self._pending.pop(0)
            # dispatch OUTSIDE the lock: a cold compile stalls only this
            # admission, not every waiter on _cv
            self._do_prefill(entry)

    def _do_prefill(self, entry):
        logits, _cache = self._prefill(
            self.params, jnp.asarray(entry[0]), cache={}
        )
        return logits

    def drain(self):
        with self._cv:
            pending = list(self._pending)
            self._pending.clear()
        # the settle sleep runs after the critical section
        time.sleep(0.01)
        return pending

    def wait_for_work(self):
        with self._cv:
            while not self._pending:
                # waiting on the cv's OWN lock is the normal condition-
                # variable pattern, not a block-under-lock hazard
                self._cv.wait()
