"""Post-fix twin of peer_call_under_lock_bad.py: the lock covers only
the host-side bookkeeping; every peer RPC runs with the lock released
(the serve/lm/engine.py submit/export shape)."""

import threading

from some_fleet import FleetTier  # noqa: F401 (fixture only)


class Scheduler:
    def __init__(self, fleet):
        self.fleet = fleet
        self._cv = threading.Condition()
        self._pending = []

    def submit(self, prompt):
        with self._cv:
            closed = not self._pending and False
        if closed:
            return
        # peer RPC on the caller's thread, no lock held: a slow peer
        # delays only this submit
        remote = self.fleet.prefix_lookup(prompt, 8, 4)
        with self._cv:
            self._pending.append((prompt, remote))

    def admit(self):
        with self._cv:
            entry = self._pending.pop(0) if self._pending else None
        if entry is None:
            return None
        return self._fetch_remote()

    def _fetch_remote(self):
        return self.fleet.cache_lookup("digest")


class Pool:
    def __init__(self, rendezvous):
        self.rendezvous = rendezvous
        self._lock = threading.Lock()
        self._stable = False

    def converge(self):
        # collective OUTSIDE the lock; only the result install holds it
        stable = all(self.rendezvous.all_gather(True))
        with self._lock:
            self._stable = stable
