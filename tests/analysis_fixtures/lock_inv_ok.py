"""Consistent global lock order (audit before write on every path):
same structure as lock_inv_bad.py, no cycle, no finding."""

import threading


class Ledger:
    def __init__(self):
        self._audit_lock = threading.Lock()
        self._write_lock = threading.Lock()
        self.entries = []

    def credit(self, amount):
        with self._audit_lock:
            with self._write_lock:  # audit -> write
                self.entries.append(amount)

    def debit(self, amount):
        with self._audit_lock:  # same order: audit first, then write
            self._write(-amount)

    def _write(self, amount):
        with self._write_lock:
            self.entries.append(amount)
