"""USE-AFTER-RELEASE ok fixture: either-or hand-off and finally close.

Releasing in one arm and using in the other is the normal hand-off
shape (exactly one runs); a use inside a try whose finally closes the
handle is the canonical safe bracket.  Neither may pair as a
use-after-release.
"""


class Splice:
    def finish(self, pool, table, n, keep):
        blocks = pool.alloc(n)
        if blocks is None:
            return
        if keep:
            table[0] = blocks[0]  # hand-off arm: reservation still held
        else:
            pool.release(blocks)  # release arm: exclusive with the use


def read_all(path):
    fh = open(path)
    try:
        return fh.read()
    finally:
        fh.close()
