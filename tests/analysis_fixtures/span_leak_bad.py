"""SPAN-LEAK fixture: spans started without a finish on every exit path.

Freezes the two leak shapes the rule exists for: a sampled span whose
completion sits on the happy path only (any raise between start and
finish loses the record), and a started timer that is never finished at
all.  Pre-fix shape of the tracing brackets before they grew their
try/finally.
"""


def handle_request(tracer, engine, request):
    trace = tracer.sample(request.model)  # BAD: completed outside finally
    trace.event("REQUEST_START")
    response = engine.execute(request.model, request.body)
    trace.event("RESPONSE_SENT")
    tracer.complete(trace)  # never runs when execute() raises
    return response


def time_tick(metrics, fn):
    timer = metrics.start_timer("tick")  # BAD: never finished at all
    result = fn()
    metrics.observe("tick_result", result)
    return result


def profile_pass(prof, sched):
    ptick = prof.start_tick("sched")  # BAD: finish not on the raise path
    ptick.add("schedule", sched.admit())
    alive = sched.step()
    prof.finish(ptick)  # never runs when step() raises
    return alive
