"""ASYNC-BLOCK clean samples: awaited primitives, bounded queue ops, and
blocking calls that live in *sync* helpers (fine — they run on worker
threads)."""

import asyncio
import queue
import time


class AioClient:
    def __init__(self):
        self._results = queue.Queue()

    async def infer_with_backoff(self, request):
        await asyncio.sleep(0.5)
        return request

    async def next_result(self):
        # bounded wait: worst case surfaces as queue.Empty, not a wedge
        return self._results.get(timeout=30)

    async def poll_result(self):
        return self._results.get_nowait()

    async def poll_result_positional(self):
        return self._results.get(False)  # block=False never blocks

    async def put_with_timeout(self, item):
        self._results.put(item, True, 5)  # positional timeout bounds it

    async def unbounded_put(self, item):
        self._results.put(item)  # queue.Queue() without maxsize: no block

    async def bounded_put_with_timeout(self, item):
        q = queue.Queue(maxsize=4)
        q.put(item, timeout=5)

    def sync_helper(self):
        time.sleep(0.01)  # sync context: allowed
        return self._results.get()
