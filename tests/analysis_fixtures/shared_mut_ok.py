"""SHARED-MUT clean samples: every cross-thread write happens under the
condition lock, in __init__ (before the thread exists), or in a
*_locked method whose caller holds the lock by convention."""

import threading


class Batcher:
    def __init__(self):
        self._cv = threading.Condition()
        self._backlog = []
        self._closed = False
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        while True:
            try:
                with self._cv:
                    while not self._backlog and not self._closed:
                        self._cv.wait()
                    if self._closed:
                        return
                    batch, self._backlog = self._backlog, []
                self._dispatch(batch)
            except Exception:
                pass

    def _dispatch(self, batch):
        pass

    def reset(self):
        with self._cv:
            self._backlog = []
            self._cv.notify_all()

    def _drain_locked(self):
        self._backlog = []  # caller holds _cv (naming convention)

    def close(self):
        with self._cv:
            self._closed = True
            self._cv.notify_all()
