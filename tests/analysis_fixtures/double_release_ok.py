"""DOUBLE-RELEASE ok fixture: either-or releases are one release.

A release in each exclusive arm — if/else, reject-vs-accept, except vs
the no-raise path — is the normal shape: exactly one runs.  The rule's
path algebra must never pair them.
"""


class Retire:
    def commit(self, pool, n):
        blocks = pool.alloc(n)
        if blocks is None:
            return None
        try:
            if blocks[0] < 0:
                pool.release(blocks)  # reject arm
                return None
            pool.release(blocks)  # accept arm: exclusive with reject
            return n
        except Exception:
            pool.release(blocks)  # exception arm: exclusive with both
            raise
