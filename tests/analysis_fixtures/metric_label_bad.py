"""METRIC-LABEL fixture: the pre-fix serve/metrics.py label rendering —
model/version names interpolated into label positions unescaped, so a
model named ``evil"name`` corrupts the whole /metrics payload."""


def render_model_lines(model, version, count):
    lines = []
    labels = f'{{model="{model}",version="{version}"}}'
    lines.append(f"ctpu_inference_request_success{labels} {count}")
    return lines


def render_device_line(device_id, used):
    return f'ctpu_tpu_memory_used_bytes{{device="{device_id}"}} {used}'
