"""Peer replies counted as durability acks unchecked — the
acks-then-loses shape ACK-BEFORE-STORE exists to catch.

Every reachable peer answers with a frame; the frame's ``stored`` field
says whether the payload was actually kept (a stale snapshot is
REJECTED with ``{"stored": false}``).  Bumping the ack counter on mere
arrival counts reachability: a fleet of rejecting peers still 'reaches
quorum' and the client holds an ack a SIGKILL can lose.
"""


class QuorumWriter:
    def __init__(self, transport, peers):
        self.transport = transport
        self.peers = peers

    def publish(self, snapshot):
        acks = 0
        for addr in self.peers:
            try:
                reply = self.transport._peer_call(
                    addr, {"op": "seq_put", "snapshot": snapshot}
                )
            except OSError:
                continue
            # BAD: the reply proves the peer is reachable, nothing more
            # — it may have rejected the snapshot as stale
            acks += 1
            del reply
        return acks

    def rebalance(self, payload):
        acked = 0
        for _addr, reply in self._ask({"op": "seq_put", "p": payload}):
            if reply.get("ok"):
                # BAD: 'ok' is transport success; durability lives in
                # the (never consulted) 'stored' field
                acked += 1
        return acked

    def _ask(self, payload):
        for addr in self.peers:
            yield addr, self.transport._peer_call(addr, payload)
