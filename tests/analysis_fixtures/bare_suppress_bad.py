"""Reason-less suppressions: the waiver still silences its rule (the
suppression machinery is unchanged) but becomes a BARE-SUPPRESS finding
itself — a waiver nobody can audit is debt, not a decision.  Both the
targeted and the blanket form, same-line and comment-line-above."""

import time


class Poller:
    def tick(self):
        deadline = time.time() + 5  # tpulint: disable=TIME-WALL
        return deadline

    async def nap(self):
        # tpulint: disable
        time.sleep(0.1)
