"""DOUBLE-RELEASE fixture: two releases reachable on one path.

For a refcounted handle the second release decrements SOMEONE ELSE'S
reference — the pool frees a block another request still maps.  Freezes
the two shapes: a plain sequential double release, and the
release-in-body-plus-release-in-finally shape (the finally also runs on
the no-raise path, so both releases execute back to back).
"""


class Retire:
    def drain(self, pool, n):
        blocks = pool.alloc(n)
        if blocks is None:
            return
        pool.release(blocks)
        self.note_free(n)
        pool.release(blocks)  # BAD: second release on the same path

    def retire(self, pool, n):
        blocks = pool.alloc(n)
        if blocks is None:
            return
        try:
            pool.release(blocks)
        finally:
            pool.release(blocks)  # BAD: finally re-runs on no-raise path
