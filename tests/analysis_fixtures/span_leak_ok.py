"""SPAN-LEAK fixture (clean): every started span finishes on every exit
path — the try/finally bracket, the context-manager form, and the two
ownership-transfer shapes (returned / handed to a callee) the rule must
stay silent on.
"""


def handle_request(tracer, engine, request):
    trace = tracer.sample(request.model)  # OK: completed in finally
    trace.event("REQUEST_START")
    try:
        response = engine.execute(request.model, request.body)
        trace.event("RESPONSE_SENT")
        return response
    finally:
        tracer.complete(trace)


def time_tick(metrics, fn):
    with metrics.start_timer("tick"):  # OK: context manager closes it
        return fn()


def sample_for_caller(tracer, model):
    trace = tracer.sample(model)  # OK: ownership transfers via return
    return trace


def sample_and_delegate(tracer, engine, request):
    trace = tracer.sample(request.model)  # OK: handed to the engine,
    engine.execute(request, trace=trace)  # which owns completion


def profile_pass(prof, sched):
    ptick = prof.start_tick("sched")  # OK: finished in finally
    try:
        return sched.step()
    finally:
        prof.finish(ptick)


def profile_request(prof, engine, request):
    with prof.start_tick("unary"):  # OK: the handle closes itself
        return engine.execute(request)
