"""PRE-FIX prefill-under-_cv, the INTERPROCEDURAL shape (ADVICE round
5's incident one refactor later): the jit prefill dispatch no longer
sits lexically inside the ``with self._cv:`` body — it is two calls
below it — so the lexical LOCK-DISPATCH rule cannot fire.  Only the
call-graph pass sees that ``_loop`` carries the scheduler lock into
``_admit_one -> _do_prefill`` where the compile-on-novel-shape dispatch
runs.  Also covers direct host-blocking (``time.sleep``) under a lock,
same-function and through a call.
"""

import functools
import threading
import time

import jax
import jax.numpy as jnp

from some_model import prefill  # noqa: F401 (fixture only)


class Scheduler:
    def __init__(self, params, cfg):
        self.params = params
        self._cv = threading.Condition()
        self._pending = []
        self._prefill = jax.jit(functools.partial(prefill, cfg=cfg))

    def _loop(self):
        while True:
            with self._cv:
                # BAD: this call chain reaches the jit dispatch while _cv
                # is held — a novel-length prompt compiles for seconds
                # with every submit()/cancel() blocked behind it
                self._admit_one()

    def _admit_one(self):
        entry = self._pending.pop(0)
        return self._do_prefill(entry)

    def _do_prefill(self, entry):
        logits, _cache = self._prefill(
            self.params, jnp.asarray(entry[0]), cache={}
        )
        return logits

    def drain(self):
        with self._cv:
            # BAD: host sleep directly inside the critical section
            time.sleep(0.01)

    def flush(self):
        with self._cv:
            # BAD: the sleep is one call away — same stall, invisible to
            # any per-function rule
            self._settle()

    def _settle(self):
        time.sleep(0.05)
