"""The compliant shapes ACK-BEFORE-STORE must NOT flag: ack counters
gated on the reply's ``stored`` field, transport-level delivery
counters under a non-ack name, and ack arithmetic in functions that
never touch the peer transport.
"""


class QuorumWriter:
    def __init__(self, transport, peers):
        self.transport = transport
        self.peers = peers
        self.seq_quorum_acks = 0

    def publish(self, snapshot):
        acks = 0
        for addr in self.peers:
            try:
                reply = self.transport._peer_call(
                    addr, {"op": "seq_put", "snapshot": snapshot}
                )
            except OSError:
                continue
            # OK: durability is the peer's 'stored' verdict, not its
            # reachability
            if reply.get("stored"):
                acks += 1
        return acks

    def gossip(self, payload):
        # OK: transport delivery counted under a non-ack name — gossip
        # has no stored semantics to check
        delivered = 0
        for addr in self.peers:
            try:
                self.transport._peer_call(addr, payload)
            except OSError:
                continue
            delivered += 1
        return delivered

    def note_quorum(self, ok):
        # OK: pure ack bookkeeping — no peer reply is bound here, the
        # decision was made by a caller that checked 'stored'
        if ok:
            self.seq_quorum_acks += 1
        return self.seq_quorum_acks
