"""STALE-SUPPRESS clean twin: every reasoned waiver still matches a
live finding on its line — used waivers are decisions, not debt."""

import time


def protocol_deadline():
    # TIME-WALL fires here and the waiver absorbs it: not stale
    deadline = time.time() + 5  # tpulint: disable=TIME-WALL -- wire protocol requires wall-clock budget
    return deadline


def rationale_above():
    # tpulint: disable=TIME-WALL -- server compares against epoch stamps
    expiry = time.time() + 60
    return expiry
