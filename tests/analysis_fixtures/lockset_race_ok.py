"""LOCKSET-RACE clean twins: the post-fix shapes plus every documented
exemption.

- ``FixedScrapeLoop`` / ``FixedTickEngine`` / ``FixedSplitGuard`` — the
  bad fixtures' races fixed with one consistent guard.
- ``FixedPublisher`` — the safe-publication pattern: every write is a
  pure reference rebind under one lock, reads are GIL-atomic reference
  loads (the post-fix ``set_registry``/``fleet.attach`` shape).
- ``InitOnly`` — fields written only in ``__init__``/the spawning
  method (the virgin phase: the thread does not exist yet).
- ``LoopLocal`` — a field only the loop root touches (single-threaded).
- ``Convention`` — the ``*_locked`` caller-holds-the-lock convention
  vouches for the helper's writes.
"""

import threading


class FixedScrapeLoop:
    def __init__(self):
        self._lock = threading.Lock()
        self._snapshots = []
        self.scrape_errors = 0
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def scrape(self):
        try:
            return {"up": 1}
        except Exception:
            with self._lock:
                self.scrape_errors += 1
            raise

    def _loop(self):
        while True:
            try:
                snap = self.scrape()
                with self._lock:
                    self._snapshots.append(snap)
            except Exception:
                with self._lock:
                    self.scrape_errors += 1


class FixedTickEngine:
    def __init__(self):
        self._cv = threading.Condition()
        self._jits = {}
        self._pending = []
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def submit(self, n):
        with self._cv:
            self._pending.append(n)
            self._cv.notify()

    def executables(self):
        with self._cv:
            return sum(1 for _ in self._jits.values())

    def _loop(self):
        while True:
            try:
                with self._cv:
                    while not self._pending:
                        self._cv.wait()
                    n = self._pending.pop()
                    if self._jits.get(n) is None:
                        self._jits[n] = object()
            except Exception:
                return


class FixedPublisher:
    def __init__(self):
        self._lock = threading.Lock()
        self.registry = None
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def set_registry(self, registry):
        with self._lock:  # guarded rebind: safe publication
            self.registry = registry

    def _loop(self):
        while True:
            try:
                registry = self.registry  # atomic reference load
                if registry is not None:
                    registry.inc("tick")
            except Exception:
                return


class FixedSplitGuard:
    def __init__(self):
        self._lock = threading.Lock()
        self._inflight = {}
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def note(self, key):
        self._note_stats(key)

    def _note_stats(self, key):
        with self._lock:
            self._bump(key)

    def _bump(self, key):
        self._inflight[key] = 1  # under the SAME lock the reader holds

    def _loop(self):
        while True:
            try:
                with self._lock:
                    for key in self._inflight:
                        _ = key
            except Exception:
                return


class InitOnly:
    def __init__(self):
        # virgin phase: no second thread exists yet (the spawning
        # method enjoys the same exemption — unit-tested directly)
        self.block_size = 16
        self.limit = self.block_size * 8

    def start(self):
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def describe(self):
        return (self.block_size, self.limit)

    def _loop(self):
        while True:
            try:
                if self.block_size > self.limit:
                    return
            except Exception:
                return


class LoopLocal:
    def __init__(self):
        self._ticks = 0
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def ping(self):
        return True

    def _loop(self):
        while True:
            try:
                self._ticks += 1  # only this root ever touches it
            except Exception:
                return


class Convention:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def put(self, key):
        with self._lock:
            self._put_locked(key)

    def _put_locked(self, key):
        self._entries[key] = 1  # caller holds the lock by convention

    def _loop(self):
        while True:
            try:
                with self._lock:
                    for key in self._entries:
                        _ = key
            except Exception:
                return
