"""Self-healing replica set under real injected chaos.

Unit layers: resolvers + the discovery loop's last-known-good error
containment, live membership (probation / graceful retire / eviction /
the last-healthy safety valve), jittered readiness probes, and the
sticky sequence policy's restart contract.  Streaming: the sync and aio
resilient streams reconnect across a mid-stream replica kill, replaying
only unacknowledged requests and deduping duplicate responses by request
id.  The churn acceptance scenario drives all of it at once — add a
replica, retire a replica, kill the stream-pinned replica, flap the
resolver — under sustained load with zero client-visible errors.
"""

import asyncio
import random
import threading
import time

import numpy as np
import pytest

import client_tpu.grpc as grpcclient
from client_tpu.balance import (
    CallableResolver,
    ConfigFileResolver,
    DiscoveryLoop,
    EndpointPool,
    ReplicatedClient,
    AsyncReplicatedClient,
    SequenceRestartError,
    SrvResolver,
    StaticResolver,
    Sticky,
    make_policy,
    make_resolver,
)
from client_tpu.balance.pool import (
    Endpoint,
    PHASE_ACTIVE,
    PHASE_PROBATION,
    PHASE_RETIRING,
)
from client_tpu.resilience import NoHealthyEndpointError, RetryPolicy
from client_tpu.serve import Model, Server, TensorSpec
from client_tpu.serve.metrics import BalancerMetricsObserver, Registry
from client_tpu.testing.faults import FaultProxy
from client_tpu.tracing import ClientTracer
from client_tpu.utils import (
    SERVER_NOT_READY,
    SERVER_READY,
    SERVER_UNREACHABLE,
    InferenceServerException,
)

_FAST_RECONNECT = [
    ("grpc.initial_reconnect_backoff_ms", 50),
    ("grpc.min_reconnect_backoff_ms", 50),
    ("grpc.max_reconnect_backoff_ms", 100),
]

# input-value markers the recording model reacts to
_SLEEPY = 1000  # >= this: hold the request ~100ms (in-flight at kill time)
_BAD = -1       # exactly this: answered application error (status 400)


def _recording_model(name, log, lock):
    """Echo model that records (sequence_id, value) per application —
    the double-apply detector the churn acceptance asserts over."""

    def fn(inputs, params, ctx):
        val = int(np.asarray(inputs["IN"]).reshape(-1)[0])
        if val == _BAD:
            raise InferenceServerException(
                "injected bad request", status="400"
            )
        if val >= _SLEEPY:
            time.sleep(0.1)
        with lock:
            log.append((params.get("sequence_id", 0), val))
        return {"OUT": inputs["IN"]}

    return Model(
        name,
        inputs=[TensorSpec("IN", "INT32", [-1, 4])],
        outputs=[TensorSpec("OUT", "INT32", [-1, 4])],
        fn=fn,
        max_batch_size=8,
    )


def _val_inputs(val):
    data = np.full((1, 4), val, dtype=np.int32)
    inp = grpcclient.InferInput("IN", [1, 4], "INT32")
    inp.set_data_from_numpy(data)
    return [inp]


def _start_servers(n, model_name="echo"):
    """n gRPC servers, each with its own application log."""
    servers, logs = [], []
    for _ in range(n):
        log, lock = [], threading.Lock()
        server = Server(
            models=[_recording_model(model_name, log, lock)],
            with_default_models=False,
            grpc_port=0,
        ).start()
        servers.append(server)
        logs.append(log)
    return servers, logs


def _fast_policy(**kw):
    kw.setdefault("max_attempts", 6)
    kw.setdefault("initial_backoff_s", 0.02)
    kw.setdefault("max_backoff_s", 0.1)
    return RetryPolicy(**kw)


def _wait_for(predicate, timeout_s=5.0, interval_s=0.01):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


# -- resolvers ---------------------------------------------------------------


class TestResolvers:
    def test_static_resolver(self):
        r = StaticResolver(["a", ("b", 2.0)])
        assert r.resolve() == ["a", ("b", 2.0)]
        assert r.resolve() == r.resolve()  # stable

    def test_callable_resolver(self):
        calls = []

        def lookup():
            calls.append(1)
            return ["a", "b"]

        r = CallableResolver(lookup)
        assert r.resolve() == ["a", "b"]
        assert len(calls) == 1

    def test_config_file_resolver_text(self, tmp_path):
        path = tmp_path / "fleet.conf"
        path.write_text(
            "# the fleet\nhost1:8001\nhost2:8001 2.5\n\nhost3:8001  # canary\n"
        )
        r = ConfigFileResolver(str(path))
        assert r.resolve() == [
            "host1:8001", ("host2:8001", 2.5), "host3:8001",
        ]
        # edits are picked up on the next resolve (no stale cache)
        path.write_text("host9:8001\n")
        assert r.resolve() == ["host9:8001"]

    def test_config_file_resolver_json(self, tmp_path):
        path = tmp_path / "fleet.json"
        path.write_text('["h1:8001", ["h2:8001", 3]]')
        assert ConfigFileResolver(str(path)).resolve() == [
            "h1:8001", ("h2:8001", 3.0),
        ]
        path.write_text('{"endpoints": ["h1:8001"]}')
        assert ConfigFileResolver(str(path)).resolve() == ["h1:8001"]

    def test_config_file_resolver_missing_raises(self, tmp_path):
        with pytest.raises(OSError):
            ConfigFileResolver(str(tmp_path / "absent.conf")).resolve()

    def test_make_resolver_dispatch(self, tmp_path):
        assert isinstance(make_resolver(["a"]), StaticResolver)
        assert isinstance(make_resolver(lambda: ["a"]), CallableResolver)
        assert isinstance(
            make_resolver(str(tmp_path / "f.conf")), ConfigFileResolver
        )
        r = StaticResolver(["a"])
        assert make_resolver(r) is r


class TestSrvResolver:
    """DNS SRV-style resolution honoring record TTLs (the PR 5
    carry-over): cached until the smallest TTL expires, re-resolved
    after, last-known-good on lookup failure."""

    def _clock(self):
        state = {"now": 100.0}

        def advance(dt):
            state["now"] += dt

        return (lambda: state["now"]), advance

    def test_ttl_caches_until_expiry_then_re_resolves(self):
        time_fn, advance = self._clock()
        calls = []

        def lookup():
            calls.append(1)
            return [("h1:8001", 1.0, 5.0), ("h2:8001", 2.0, 9.0)]

        r = SrvResolver(lookup, time_fn=time_fn)
        assert r.resolve() == [("h1:8001", 1.0), ("h2:8001", 2.0)]
        # inside the smallest record TTL (5s): served from cache
        advance(4.9)
        assert r.resolve() == [("h1:8001", 1.0), ("h2:8001", 2.0)]
        assert len(calls) == 1
        # past it: re-resolved
        advance(0.2)
        r.resolve()
        assert len(calls) == 2
        assert r.resolutions == 2

    def test_records_without_ttl_use_default(self):
        time_fn, advance = self._clock()
        calls = []

        def lookup():
            calls.append(1)
            return ["h1:8001", ("h2:8001", 2.0)]

        r = SrvResolver(lookup, default_ttl_s=30.0, time_fn=time_fn)
        assert r.resolve() == ["h1:8001", ("h2:8001", 2.0)]
        advance(29.0)
        r.resolve()
        assert len(calls) == 1
        advance(2.0)
        r.resolve()
        assert len(calls) == 2

    def test_zero_ttl_floored_not_a_hot_loop(self):
        time_fn, _advance = self._clock()
        calls = []

        def lookup():
            calls.append(1)
            return [("h1:8001", 1.0, 0.0)]  # misconfigured zone

        r = SrvResolver(lookup, min_ttl_s=1.0, time_fn=time_fn)
        r.resolve()
        r.resolve()  # same instant: still cached (TTL floored to 1s)
        assert len(calls) == 1

    def test_lookup_failure_serves_last_known_good(self):
        time_fn, advance = self._clock()
        answers = [["h1:8001"], RuntimeError("registry down"), ["h2:8001"]]

        def lookup():
            answer = answers.pop(0)
            if isinstance(answer, Exception):
                raise answer
            return answer

        r = SrvResolver(lookup, default_ttl_s=5.0, min_ttl_s=1.0,
                        time_fn=time_fn)
        assert r.resolve() == ["h1:8001"]
        advance(6.0)  # TTL expired, lookup now fails
        assert r.resolve() == ["h1:8001"]  # stale-on-error
        assert r.errors == 1 and "registry down" in str(r.last_error)
        # the outage re-probes after the floor, not the full TTL
        advance(1.1)
        assert r.resolve() == ["h2:8001"]

    def test_initial_failure_raises(self):
        def lookup():
            raise RuntimeError("cold start, registry down")

        r = SrvResolver(lookup)
        with pytest.raises(RuntimeError):
            r.resolve()
        # DiscoveryLoop contains it like any resolver error
        pool = EndpointPool(["seed:8001"])
        loop = DiscoveryLoop(pool, r, interval_s=3600)
        assert loop.refresh_now() is None
        assert pool.urls() == ["seed:8001"]  # last-known-good membership

    def test_feeds_discovery_loop_on_ttl_churn(self):
        time_fn, advance = self._clock()
        membership = [["a:8001", "b:8001"], ["b:8001", "c:8001"]]

        def lookup():
            return [(u, 1.0, 2.0) for u in membership[0]]

        r = SrvResolver(lookup, time_fn=time_fn)
        pool = EndpointPool(["a:8001"])
        loop = DiscoveryLoop(pool, r, interval_s=3600)
        assert loop.refresh_now() is not None
        assert sorted(pool.urls()) == ["a:8001", "b:8001"]
        membership.pop(0)
        advance(3.0)  # TTL expiry picks up the new records
        summary = loop.refresh_now()
        assert summary["added"] == ["c:8001"]
        assert "a:8001" in summary["retired"]


class TestDiscoveryLoop:
    def test_refresh_applies_membership(self):
        pool = EndpointPool(["a", "b"])
        members = [["a", "b", "c"]]
        loop = DiscoveryLoop(pool, CallableResolver(lambda: members[0]))
        summary = loop.refresh_now()
        assert summary["added"] == ["c"]
        assert sorted(pool.urls()) == ["a", "b", "c"]
        assert loop.updates == 1 and loop.errors == 0

    def test_resolver_error_keeps_last_known_good(self):
        pool = EndpointPool(["a", "b"])

        def flaky():
            raise RuntimeError("registry outage")

        loop = DiscoveryLoop(pool, CallableResolver(flaky))
        assert loop.refresh_now() is None
        assert sorted(pool.urls()) == ["a", "b"]  # membership untouched
        assert loop.errors == 1
        assert isinstance(loop.last_error, RuntimeError)

    def test_empty_membership_refused(self):
        pool = EndpointPool(["a"])
        loop = DiscoveryLoop(pool, CallableResolver(lambda: []))
        assert loop.refresh_now() is None
        assert pool.urls() == ["a"]
        assert loop.errors == 1

    def test_background_polling(self):
        pool = EndpointPool(["a"])
        members = [["a"]]
        with DiscoveryLoop(
            pool, CallableResolver(lambda: members[0]), interval_s=0.02
        ).start():
            members[0] = ["a", "b"]
            assert _wait_for(lambda: "b" in pool.urls())


# -- live membership ---------------------------------------------------------


class TestMembership:
    def test_add_without_prober_is_immediately_routable(self):
        pool = EndpointPool(["a"])
        summary = pool.update_endpoints(["a", "b"])
        assert summary["added"] == ["b"]
        assert pool.phases() == {"a": PHASE_ACTIVE, "b": PHASE_ACTIVE}
        seen = set()
        for _ in range(6):
            lease = pool.lease()
            seen.add(lease.url)
            lease.success()
        assert seen == {"a", "b"}

    def test_add_with_prober_enters_probation(self):
        states = {"a": SERVER_READY, "b": SERVER_NOT_READY}
        pool = EndpointPool(["a"])
        pool.start_probes(lambda url: states[url], interval_s=0.02)
        try:
            pool.update_endpoints(["a", "b"])
            assert pool.phases()["b"] == PHASE_PROBATION
            # unproven: never takes traffic while its probe says not-ready
            for _ in range(8):
                lease = pool.lease()
                assert lease.url == "a"
                lease.success()
            # first READY probe promotes it
            states["b"] = SERVER_READY
            assert _wait_for(lambda: pool.phases()["b"] == PHASE_ACTIVE)
            seen = set()
            for _ in range(8):
                lease = pool.lease()
                seen.add(lease.url)
                lease.success()
            assert "b" in seen
        finally:
            pool.close()

    def test_retire_waits_for_inflight_then_evicts(self):
        pool = EndpointPool(["a", "b"])
        held = pool.lease(excluded=("b",))
        assert held.url == "a"
        summary = pool.update_endpoints(["b"])
        assert summary["retired"] == ["a"]
        assert summary["evicted"] == []
        assert pool.phases()["a"] == PHASE_RETIRING
        # no NEW leases on the retiring endpoint, in-flight finishes
        for _ in range(6):
            lease = pool.lease()
            assert lease.url == "b"
            lease.success()
        held.success()  # the in-flight lease finishes -> eviction
        assert pool.urls() == ["b"]

    def test_idle_retiree_evicted_immediately(self):
        pool = EndpointPool(["a", "b"])
        summary = pool.update_endpoints(["b"])
        assert summary["retired"] == ["a"]
        assert summary["evicted"] == ["a"]
        assert pool.urls() == ["b"]

    def test_unretire_on_flap_back(self):
        pool = EndpointPool(["a", "b"])
        held = pool.lease(excluded=("b",))
        pool.update_endpoints(["b"])
        assert pool.phases()["a"] == PHASE_RETIRING
        summary = pool.update_endpoints(["a", "b"])
        assert summary["unretired"] == ["a"]
        assert pool.phases()["a"] == PHASE_ACTIVE
        held.success()
        assert sorted(pool.urls()) == ["a", "b"]

    def test_last_healthy_endpoint_is_never_evicted(self):
        pool = EndpointPool(["a", "b"])
        pool.set_state("b", SERVER_UNREACHABLE)
        # resolver flap says "only b" — but b is dead and a is the last
        # healthy endpoint: the safety valve retains it
        summary = pool.update_endpoints(["b"])
        assert summary["retained"] == ["a"]
        assert summary["retired"] == []
        assert pool.phases()["a"] == PHASE_ACTIVE
        lease = pool.lease()
        assert lease.url == "a"
        lease.success()

    def test_safety_valve_releases_once_replacement_is_healthy(self):
        states = {"a": SERVER_READY, "b": SERVER_NOT_READY}
        pool = EndpointPool(["a"])
        pool.start_probes(lambda url: states[url], interval_s=0.02)
        try:
            pool.update_endpoints(["b"])  # b unproven: a retained
            assert pool.phases()["a"] == PHASE_ACTIVE
            states["b"] = SERVER_READY
            assert _wait_for(lambda: pool.phases().get("b") == PHASE_ACTIVE)
            summary = pool.update_endpoints(["b"])  # now a can retire
            assert summary["retired"] == ["a"] or summary["evicted"] == ["a"]
            assert _wait_for(lambda: pool.urls() == ["b"])
        finally:
            pool.close()

    def test_update_rejects_empty_and_duplicates(self):
        pool = EndpointPool(["a"])
        with pytest.raises(ValueError, match="empty"):
            pool.update_endpoints([])
        with pytest.raises(ValueError, match="duplicate"):
            pool.update_endpoints(["b", "b"])
        assert pool.urls() == ["a"]  # both rejections left the pool intact

    def test_update_applies_weights(self):
        pool = EndpointPool([("a", 1.0)])
        pool.update_endpoints([("a", 3.0), ("b", 0.5)])
        weights = {s["url"]: s["weight"] for s in pool.snapshot()}
        assert weights == {"a": 3.0, "b": 0.5}

    def test_membership_metrics(self):
        registry = Registry()
        pool = EndpointPool(
            ["a", "b"], observer=BalancerMetricsObserver(registry)
        )
        pool.update_endpoints(["a", "c"])  # add c, retire+evict b (idle)

        def changes(op, url):
            return registry.get(
                "ctpu_client_membership_changes_total",
                {"op": op, "endpoint": url},
            )

        assert changes("add", "c") == 1
        assert changes("retire", "b") == 1
        assert changes("evict", "b") == 1
        assert registry.get(
            "ctpu_client_pool_endpoints", {"phase": "active"}
        ) == 2
        assert registry.get(
            "ctpu_client_endpoint_phase", {"endpoint": "c"}
        ) == 0  # active (no prober -> no probation)
        # an evicted endpoint's gauges are dropped, not parked at their
        # last value forever (counters remain: they are history)
        assert registry.get(
            "ctpu_client_endpoint_phase", {"endpoint": "b"}
        ) is None
        assert registry.get(
            "ctpu_client_endpoint_state", {"endpoint": "b"}
        ) is None


# -- probation ramp-up / slow start (satellite) ------------------------------


class TestProbationRampup:
    def test_ramp_fraction_math(self):
        e = Endpoint("a")
        assert e.ramp_fraction() == 1.0  # never promoted: full share
        e.ramp_started, e.ramp_span, e.ramp_floor = 100.0, 10.0, 0.1
        assert e.ramp_fraction(now=100.0) == pytest.approx(0.1)  # floored
        assert e.ramp_fraction(now=105.0) == pytest.approx(0.5)
        assert e.ramp_fraction(now=107.0) == pytest.approx(0.7)
        assert e.ramp_fraction(now=110.5) == 1.0
        assert e.ramp_started is None  # completed ramp clears itself

    def test_weighted_policy_ramp_not_double_applied(self):
        """The ramp lives in the pool's candidate thinning ONLY: a ramping
        replica at fraction f must get ~f of its fair share under the
        weighted policy, not ~f^2 (thinning AND weight-scaling would
        compound)."""
        pool = EndpointPool(
            ["a", "b"], policy="weighted", rampup_s=600.0,
            rng=random.Random(5),
        )
        try:
            b = next(e for e in pool.endpoints() if e.url == "b")
            b.ramp_started = time.monotonic()  # fraction pinned at floor
            b.ramp_span, b.ramp_floor = 600.0, 0.2
            policy = pool._policy
            policy._rng = random.Random(11)
            counts = {"a": 0, "b": 0}
            n = 1000
            for _ in range(n):
                lease = pool.lease()
                counts[lease.url] += 1
                lease.success()
            share = counts["b"] / n
            # expected: survives thinning w.p. 0.2, then equal-weight pick
            # among {a,b} -> ~0.1; the f^2 bug would give ~0.02
            assert 0.05 < share < 0.18, counts
        finally:
            pool.close()

    def test_promoted_replica_slow_starts_then_ramps_to_full(self):
        states = {"a": SERVER_READY, "b": SERVER_NOT_READY}
        pool = EndpointPool(
            ["a"], rampup_s=60.0, rng=random.Random(7)
        )
        pool.start_probes(lambda url: states[url], interval_s=0.02)
        try:
            pool.update_endpoints(["a", "b"])
            states["b"] = SERVER_READY
            assert _wait_for(lambda: pool.phases()["b"] == PHASE_ACTIVE)
            b = next(e for e in pool.endpoints() if e.url == "b")
            assert b.ramp_started is not None  # promote stamped the ramp

            def share(n=400):
                counts = {"a": 0, "b": 0}
                for _ in range(n):
                    lease = pool.lease()
                    counts[lease.url] += 1
                    lease.success()
                return counts["b"] / n

            # freshly promoted: thinning holds b well under its fair 50%
            assert share() < 0.25
            # mid-window: share grows but stays below fair
            b.ramp_started = time.monotonic() - 24.0  # 40% through
            assert 0.05 < share() < 0.45
            # past the window: full fair share again (round-robin ~50%)
            b.ramp_started = time.monotonic() - 120.0
            assert share() > 0.4
            assert b.ramp_started is None  # ramp state self-cleared
        finally:
            pool.close()

    def test_thinning_exempts_sticky_sequences(self):
        """A ramping replica must never be thinned out from under the
        sequences pinned to it: the sticky policy reads a missing pinned
        candidate as replica death and forces a SequenceRestartError —
        a fabricated restart on a perfectly healthy replica."""
        pool = EndpointPool(
            ["a", "b"], policy="sticky", rampup_s=600.0,
            rng=random.Random(3),
        )
        try:
            b = next(e for e in pool.endpoints() if e.url == "b")
            # force b deep into a ramp window (fraction at the floor)
            b.ramp_started = time.monotonic()
            b.ramp_span, b.ramp_floor = 600.0, 0.1
            ctx = {"sequence_id": 42}
            pinned = pool.lease(request_ctx=ctx)
            pinned_url = pinned.url
            pinned.success()
            for _ in range(100):
                lease = pool.lease(request_ctx=ctx)  # must never raise
                assert lease.url == pinned_url
                lease.success()
        finally:
            pool.close()

    def test_rampup_disabled_promotes_at_full_share(self):
        states = {"a": SERVER_READY, "b": SERVER_NOT_READY}
        pool = EndpointPool(["a"])  # rampup_s=0: no slow start
        pool.start_probes(lambda url: states[url], interval_s=0.02)
        try:
            pool.update_endpoints(["a", "b"])
            states["b"] = SERVER_READY
            assert _wait_for(lambda: pool.phases()["b"] == PHASE_ACTIVE)
            b = next(e for e in pool.endpoints() if e.url == "b")
            assert b.ramp_started is None
            counts = {"a": 0, "b": 0}
            for _ in range(100):
                lease = pool.lease()
                counts[lease.url] += 1
                lease.success()
            assert counts["b"] > 30  # instant full rotation share
        finally:
            pool.close()


# -- probe jitter (satellite) ------------------------------------------------


class TestProbeJitter:
    def test_probe_times_spread(self):
        """A fleet's first probes must not land in lockstep: per-endpoint
        full jitter spreads them across the probe interval."""
        urls = [f"ep{i}" for i in range(8)]
        times = {}
        lock = threading.Lock()
        t0 = time.monotonic()

        def probe(url):
            with lock:
                times.setdefault(url, time.monotonic() - t0)
            return SERVER_READY

        pool = EndpointPool(urls)
        interval = 0.4
        pool.start_probes(probe, interval_s=interval,
                          rng=random.Random(42))
        try:
            assert _wait_for(lambda: len(times) == len(urls), timeout_s=5)
        finally:
            pool.close()
        first = sorted(times.values())
        # not a synchronized burst: the first probes span a real fraction
        # of the interval, and no two fire at the same instant
        assert first[-1] - first[0] > 0.2 * interval
        gaps = [b - a for a, b in zip(first, first[1:])]
        assert max(gaps) > 0.02

    def test_probes_cover_discovered_endpoints(self):
        probed = set()
        lock = threading.Lock()

        def probe(url):
            with lock:
                probed.add(url)
            return SERVER_READY

        pool = EndpointPool(["a"])
        pool.start_probes(probe, interval_s=0.02)
        try:
            pool.update_endpoints(["a", "b"])
            assert _wait_for(lambda: "b" in probed)
            assert _wait_for(lambda: pool.phases()["b"] == PHASE_ACTIVE)
        finally:
            pool.close()


# -- sticky sequence routing -------------------------------------------------


def _eps(n):
    return [Endpoint(f"ep{i}") for i in range(n)]


class TestStickyPolicy:
    def test_sequence_pins_one_endpoint(self):
        eps = _eps(3)
        policy = Sticky()
        first = policy.pick(eps, {"sequence_id": 7})
        for _ in range(5):
            assert policy.pick(eps, {"sequence_id": 7}) is first

    def test_sequences_spread_via_fallback(self):
        eps = _eps(3)
        policy = Sticky()
        picked = {
            policy.pick(eps, {"sequence_id": seq}).url
            for seq in range(1, 7)
        }
        assert len(picked) == 3  # round-robin fallback spreads sequences

    def test_stateless_requests_fall_through(self):
        eps = _eps(2)
        policy = Sticky()
        urls = {policy.pick(eps, {}).url for _ in range(4)}
        assert urls == {"ep0", "ep1"}
        assert policy.sequences() == {}

    def test_sequence_end_drops_mapping(self):
        eps = _eps(2)
        policy = Sticky()
        policy.pick(eps, {"sequence_id": 9})
        assert 9 in policy.sequences()
        policy.pick(eps, {"sequence_id": 9, "sequence_end": True})
        assert 9 not in policy.sequences()

    def test_dead_endpoint_raises_restart_and_remaps(self):
        eps = _eps(3)
        policy = Sticky()
        pinned = policy.pick(eps, {"sequence_id": 5})
        survivors = [e for e in eps if e is not pinned]
        with pytest.raises(SequenceRestartError) as exc_info:
            policy.pick(survivors, {"sequence_id": 5})
        err = exc_info.value
        assert err.sequence_id == 5
        assert err.dead_endpoint == pinned.url
        assert err.new_endpoint in {e.url for e in survivors}
        # the restart error is NOT blind-retryable: replaying one
        # mid-sequence request is the state split it exists to prevent
        assert not RetryPolicy().retryable(err)
        # the remap is already installed: the restarted sequence sticks —
        # including the restart request itself (sequence_start honors it)
        restart = policy.pick(
            survivors, {"sequence_id": 5, "sequence_start": True}
        )
        assert restart.url == err.new_endpoint
        again = policy.pick(survivors, {"sequence_id": 5})
        assert again.url == err.new_endpoint

    def test_durable_sequence_remaps_silently(self):
        """A durable sequence's replica death never surfaces: its
        server-side state replicates through the fleet tier's sequence
        lane, so the remap is silent — the survivor rebuilds the context
        from a peer snapshot on first touch instead of forcing the
        client to restart (SequenceRestartError stays the non-durable
        contract)."""
        eps = _eps(3)
        policy = Sticky()
        ctx = {"sequence_id": 6, "sequence_durable": True}
        pinned = policy.pick(eps, ctx)
        survivors = [e for e in eps if e is not pinned]
        remapped = policy.pick(survivors, ctx)  # no raise
        assert remapped in survivors
        # the remap sticks for the rest of the sequence
        for _ in range(3):
            assert policy.pick(survivors, ctx) is remapped
        # the same death without the durable marker still raises
        bare = Sticky()
        pinned = bare.pick(eps, {"sequence_id": 7})
        survivors = [e for e in eps if e is not pinned]
        with pytest.raises(SequenceRestartError):
            bare.pick(survivors, {"sequence_id": 7})

    def test_sequence_start_keeps_healthy_mapping(self):
        eps = _eps(3)
        policy = Sticky()
        pinned = policy.pick(eps, {"sequence_id": 8})
        # a client restarting a sequence whose replica is alive stays put
        for _ in range(3):
            assert policy.pick(
                eps, {"sequence_id": 8, "sequence_start": True}
            ) is pinned

    def test_sequence_start_remaps_without_error(self):
        eps = _eps(2)
        policy = Sticky()
        pinned = policy.pick(eps, {"sequence_id": 3})
        survivors = [e for e in eps if e is not pinned]
        # an explicit restart never raises — the caller is already
        # rebuilding the sequence from its start
        fresh = policy.pick(
            survivors, {"sequence_id": 3, "sequence_start": True}
        )
        assert fresh in survivors

    def test_lru_bound(self):
        eps = _eps(2)
        policy = Sticky(max_sequences=3)
        for seq in range(1, 6):
            policy.pick(eps, {"sequence_id": seq})
        assert len(policy.sequences()) == 3
        assert set(policy.sequences()) == {3, 4, 5}

    def test_make_policy_knows_sticky(self):
        assert make_policy("sticky").name == "sticky"

    def test_replicated_client_sticky_end_to_end(self):
        """Sequences stick to one replica; killing it surfaces the
        retryable sequence-restart error instead of silently splitting
        state, and the restarted sequence lands whole on a survivor."""
        servers, logs = _start_servers(2)
        urls = [s.grpc_address for s in servers]
        client = ReplicatedClient(
            urls, transport="grpc", policy="sticky",
            probe_interval_s=None,
            retry_policy=_fast_policy(jitter=False),
            channel_args=_FAST_RECONNECT,
        )
        try:
            for step in range(4):
                client.infer(
                    "echo", _val_inputs(step), sequence_id=11,
                    sequence_start=(step == 0),
                )
            seq_counts = [
                sum(1 for seq, _ in log if seq == 11) for log in logs
            ]
            assert sorted(seq_counts) == [0, 4]  # one replica took it all
            pinned_index = seq_counts.index(4)
            servers[pinned_index].stop()
            with pytest.raises(SequenceRestartError):
                client.infer("echo", _val_inputs(4), sequence_id=11)
            # restart per the contract: the sequence rebuilds on the
            # survivor, whole
            for step in range(3):
                client.infer(
                    "echo", _val_inputs(100 + step), sequence_id=11,
                    sequence_start=(step == 0),
                )
            survivor_log = logs[1 - pinned_index]
            assert [
                val for seq, val in survivor_log if seq == 11
            ] == [100, 101, 102]
        finally:
            client.close()
            for s in servers:
                s.stop()


# -- resilient streaming -----------------------------------------------------


class TestResilientStreamSync:
    def _pin_to(self, pool, url):
        """Deterministic pinning: mark every other endpoint not-ready."""
        for other in pool.urls():
            if other != url:
                pool.set_state(other, SERVER_NOT_READY)

    def test_reconnect_replays_unacked_and_dedupes(self):
        servers, logs = _start_servers(2)
        proxy = FaultProxy(servers[0].grpc_address)
        url_a, url_b = proxy.address, servers[1].grpc_address
        registry = Registry()
        pool = EndpointPool(
            [url_a, url_b], observer=BalancerMetricsObserver(registry)
        )
        tracer = ClientTracer()
        client = ReplicatedClient(
            pool, transport="grpc", probe_interval_s=None,
            tracer=tracer, retry_policy=_fast_policy(jitter=False),
            channel_args=_FAST_RECONNECT,
        )
        events = []
        got = threading.Event()
        lock = threading.Lock()

        def callback(result, error):
            with lock:
                events.append((result, error))
            got.set()

        self._pin_to(pool, url_a)
        stream = client.resilient_stream(callback)
        try:
            assert stream.url == url_a
            pool.set_state(url_b, SERVER_READY)
            rid0 = stream.async_stream_infer("echo", _val_inputs(0))
            assert _wait_for(lambda: len(events) == 1, timeout_s=10)
            # queue sleepy requests so the kill catches them in flight
            rids = [
                stream.async_stream_infer("echo", _val_inputs(_SLEEPY + i))
                for i in range(3)
            ]
            time.sleep(0.05)
            proxy.refuse_connections(True)
            proxy.kill_active()
            assert _wait_for(lambda: len(events) == 4, timeout_s=15)
            rid_after = stream.async_stream_infer("echo", _val_inputs(7))
            assert _wait_for(lambda: len(events) == 5, timeout_s=10)

            with lock:
                assert all(err is None for _, err in events)
                answered = [r.get_response().id for r, _ in events]
            # exactly-once to the callback: every request id answered once
            assert sorted(answered) == sorted([rid0] + rids + [rid_after])
            assert stream.reconnects == 1
            assert stream.replayed >= 1
            assert stream.url == url_b
            # the hop and the replay are on the metrics surface
            assert registry.get(
                "ctpu_client_stream_reconnects_total", {"endpoint": url_a}
            ) == 1
            assert registry.get(
                "ctpu_client_stream_replayed_requests_total",
                {"endpoint": url_b},
            ) >= 1
            # ... and on one trace: consecutive endpoint-tagged attempts
            # under a single trace id
            hops = stream.trace.attempt_endpoints()
            assert hops[0] == url_a and hops[-1] == url_b
        finally:
            stream.close()
            client.close()
            proxy.close()
            for s in servers:
                s.stop()
        # closing released every inflight slot
        assert all(s["inflight"] == 0 for s in pool.snapshot())

    def test_app_error_propagates_without_reconnect(self):
        servers, logs = _start_servers(1)
        client = ReplicatedClient(
            [servers[0].grpc_address], transport="grpc",
            probe_interval_s=None,
            retry_policy=_fast_policy(jitter=False),
        )
        events = []
        lock = threading.Lock()

        def callback(result, error):
            with lock:
                events.append((result, error))

        stream = client.resilient_stream(callback)
        try:
            stream.async_stream_infer("echo", _val_inputs(_BAD))
            stream.async_stream_infer("echo", _val_inputs(1))
            assert _wait_for(lambda: len(events) == 2, timeout_s=10)
            with lock:
                errors = [err for _, err in events if err is not None]
            assert len(errors) == 1
            assert errors[0].status() == "400"
            assert stream.reconnects == 0  # answered error: no failover
        finally:
            stream.close()
            client.close()
            servers[0].stop()

    def test_independent_of_pinned_stream_slot(self):
        """A ResilientStream must coexist with the pinned start_stream on
        the SAME endpoint (it owns its transport client, so the one-
        stream-per-client limit never collides)."""
        servers, _ = _start_servers(1)
        client = ReplicatedClient(
            [servers[0].grpc_address], transport="grpc",
            probe_interval_s=None,
            retry_policy=_fast_policy(jitter=False),
        )
        pinned_events, resilient_events = [], []
        pinned_got = threading.Event()

        def pinned_cb(result, error):
            pinned_events.append((result, error))
            pinned_got.set()

        client.start_stream(pinned_cb)  # occupies the per-endpoint slot
        stream = client.resilient_stream(
            lambda result, error: resilient_events.append((result, error))
        )
        try:
            client.async_stream_infer("echo", _val_inputs(1))
            stream.async_stream_infer("echo", _val_inputs(2))
            assert pinned_got.wait(timeout=10)
            assert _wait_for(lambda: len(resilient_events) == 1,
                             timeout_s=10)
            assert pinned_events[0][1] is None
            assert resilient_events[0][1] is None
        finally:
            stream.close()
            client.close()
            servers[0].stop()

    def test_terminal_when_no_replica_left(self):
        servers, _ = _start_servers(1)
        proxy = FaultProxy(servers[0].grpc_address)
        client = ReplicatedClient(
            [proxy.address], transport="grpc", probe_interval_s=None,
            retry_policy=_fast_policy(
                max_attempts=2, jitter=False, initial_backoff_s=0.01
            ),
            channel_args=_FAST_RECONNECT,
        )
        events = []
        done = threading.Event()

        def callback(result, error):
            events.append((result, error))
            if error is not None:
                done.set()

        stream = client.resilient_stream(callback)
        try:
            stream.async_stream_infer("echo", _val_inputs(_SLEEPY))
            time.sleep(0.05)
            proxy.refuse_connections(True)
            proxy.kill_active()
            assert done.wait(timeout=15)
            terminal = [e for _, e in events if e is not None]
            assert terminal  # non-recoverable death reached the caller
        finally:
            stream.close()
            client.close()
            proxy.close()
            servers[0].stop()


class TestResilientStreamAio:
    def test_reconnect_replays_and_dedupes(self):
        servers, logs = _start_servers(2)
        proxy = FaultProxy(servers[0].grpc_address)
        url_a, url_b = proxy.address, servers[1].grpc_address

        class Feed:
            def __init__(self):
                self.queue = asyncio.Queue()

            def __aiter__(self):
                return self

            async def __anext__(self):
                item = await self.queue.get()
                if item is None:
                    raise StopAsyncIteration
                return item

        async def flow():
            pool = EndpointPool([url_a, url_b])
            client = AsyncReplicatedClient(
                pool, transport="grpc",
                retry_policy=_fast_policy(jitter=False),
                channel_args=_FAST_RECONNECT,
            )
            pool.set_state(url_b, SERVER_NOT_READY)  # pin to the proxy
            feed = Feed()
            stream = client.resilient_stream_infer(feed)
            results = []
            try:
                await feed.queue.put(
                    {"model_name": "echo", "inputs": _val_inputs(0),
                     "request_id": "r0"}
                )
                results.append(await stream.__anext__())
                pool.set_state(url_b, SERVER_READY)
                for i in range(3):
                    await feed.queue.put({
                        "model_name": "echo",
                        "inputs": _val_inputs(_SLEEPY + i),
                        "request_id": f"r{i + 1}",
                    })
                await asyncio.sleep(0.1)  # let them reach the wire
                proxy.refuse_connections(True)
                proxy.kill_active()
                await feed.queue.put(
                    {"model_name": "echo", "inputs": _val_inputs(9),
                     "request_id": "r4"}
                )
                await feed.queue.put(None)
                async for pair in stream:
                    results.append(pair)
                assert all(err is None for _, err in results)
                answered = [r.get_response().id for r, _ in results]
                # exactly-once per request id, across the reconnect
                assert sorted(answered) == ["r0", "r1", "r2", "r3", "r4"]
            finally:
                await stream.aclose()
                await client.close()
            assert all(s["inflight"] == 0 for s in pool.snapshot())

        loop = asyncio.new_event_loop()
        try:
            loop.run_until_complete(flow())
        finally:
            loop.close()
            proxy.close()
            for s in servers:
                s.stop()

    def test_duplicate_request_id_rejected(self):
        """A reused request id would clobber the replay buffer and eat
        the second response — the aio path rejects it like the sync one."""
        servers, _ = _start_servers(1)

        async def flow():
            client = AsyncReplicatedClient(
                [servers[0].grpc_address], transport="grpc",
                retry_policy=_fast_policy(jitter=False),
            )

            async def feed():
                for _ in range(2):
                    yield {"model_name": "echo", "inputs": _val_inputs(1),
                           "request_id": "dup"}

            stream = client.resilient_stream_infer(feed())
            try:
                with pytest.raises(InferenceServerException,
                                   match="duplicate request id"):
                    async for _pair in stream:
                        pass
            finally:
                await stream.aclose()
                await client.close()
            assert all(
                s["inflight"] == 0 for s in client.pool.snapshot()
            )

        loop = asyncio.new_event_loop()
        try:
            loop.run_until_complete(flow())
        finally:
            loop.close()
            servers[0].stop()


# -- churn chaos acceptance --------------------------------------------------


def _run_churn_scenario():
    """Sustained load while the fleet churns: add a replica, retire a
    replica, kill the stream-pinned replica, flap the resolver.  Zero
    client-visible errors, exactly-once responses per stream request, no
    request applied twice to a sequence on any replica, the last healthy
    endpoint never evicted, and metrics + a shared-trace-id timeline
    prove the reconnect hop."""
    servers, logs = _start_servers(3)
    proxies = [FaultProxy(s.grpc_address) for s in servers]
    urls = [p.address for p in proxies]
    by_url = dict(zip(urls, range(3)))

    membership = {"urls": list(urls), "flap": False}
    membership_lock = threading.Lock()

    def resolve():
        with membership_lock:
            if membership["flap"]:
                raise RuntimeError("resolver flap")
            return list(membership["urls"])

    registry = Registry()
    pool = EndpointPool(
        urls, policy="round-robin",
        observer=BalancerMetricsObserver(registry),
        failure_threshold=3, reset_timeout_s=60.0,
    )
    tracer = ClientTracer(max_traces=10000)
    client = ReplicatedClient(
        pool, transport="grpc",
        probe_interval_s=0.05,
        resolver=CallableResolver(resolve), discovery_interval_s=0.05,
        tracer=tracer,
        retry_policy=RetryPolicy(
            max_attempts=8, initial_backoff_s=0.02, max_backoff_s=0.2,
            deadline_s=20.0,
        ),
        channel_args=_FAST_RECONNECT,
    )

    # watcher: the pool must never go empty of healthy routable replicas
    min_healthy = [99]
    watch_stop = threading.Event()

    def watcher():
        while not watch_stop.is_set():
            snapshot = client.pool.snapshot()
            healthy = sum(
                1 for s in snapshot
                if s["phase"] == PHASE_ACTIVE and s["state"] == SERVER_READY
            )
            min_healthy[0] = min(min_healthy[0], healthy)
            time.sleep(0.01)

    # unary load
    errors = []
    load_lock = threading.Lock()

    def unary_worker(worker_id):
        for i in range(40):
            try:
                client.infer("echo", _val_inputs(10000 * worker_id + i))
            except Exception as exc:  # noqa: BLE001 - recorded, asserted
                with load_lock:
                    errors.append(exc)
            time.sleep(0.005)

    # resilient stream carrying a sequence
    stream_events = []
    stream_lock = threading.Lock()

    def stream_callback(result, error):
        with stream_lock:
            stream_events.append((result, error))

    threads = [
        threading.Thread(target=unary_worker, args=(w,)) for w in range(3)
    ]
    watch_thread = threading.Thread(target=watcher)
    new_server = None
    stream = None
    try:
        watch_thread.start()
        for t in threads:
            t.start()
        stream = client.resilient_stream(stream_callback)
        victim_url = stream.url
        assert victim_url in urls
        victim_proxy = proxies[by_url[victim_url]]
        retire_url = next(u for u in urls if u != victim_url)

        sent = []
        for step in range(10):
            sent.append(stream.async_stream_infer(
                "echo", _val_inputs(step), sequence_id=7,
                sequence_start=(step == 0),
            ))
        assert _wait_for(
            lambda: len(stream_events) == len(sent), timeout_s=30
        )

        # (1) grow the fleet: a new replica joins through discovery,
        # passes probation, and starts taking traffic
        log_d, lock_d = [], threading.Lock()
        new_server = Server(
            models=[_recording_model("echo", log_d, lock_d)],
            with_default_models=False, grpc_port=0,
        ).start()
        with membership_lock:
            membership["urls"].append(new_server.grpc_address)
        assert _wait_for(
            lambda: client.pool.phases().get(new_server.grpc_address)
            == PHASE_ACTIVE,
            timeout_s=10,
        )

        # (2) retire a replica gracefully
        with membership_lock:
            membership["urls"].remove(retire_url)
        assert _wait_for(
            lambda: retire_url not in client.pool.urls(), timeout_s=10
        )

        # (3) kill the stream-pinned replica mid-stream, with requests
        # in flight (sleepy values), and keep the sequence going
        burst = [
            stream.async_stream_infer(
                "echo", _val_inputs(_SLEEPY + step), sequence_id=7
            )
            for step in range(10, 14)
        ]
        sent.extend(burst)
        time.sleep(0.05)
        victim_proxy.refuse_connections(True)
        victim_proxy.kill_active()
        for step in range(14, 18):
            sent.append(stream.async_stream_infer(
                "echo", _val_inputs(step), sequence_id=7
            ))

        # (4) flap the resolver: errors keep last-known-good membership
        with membership_lock:
            membership["flap"] = True
            flap_urls = set(client.pool.urls())
        time.sleep(0.2)
        with membership_lock:
            membership["flap"] = False
        assert set(client.pool.urls()) == flap_urls
        assert client.discovery.errors > 0

        for t in threads:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in threads)
        assert _wait_for(
            lambda: len(stream_events) == len(sent), timeout_s=40
        )

        # zero client-visible errors, unary and stream
        assert errors == []
        with stream_lock:
            assert all(err is None for _, err in stream_events)
            answered = [r.get_response().id for r, _ in stream_events]
        # exactly-once responses per request id across the reconnect
        assert sorted(answered) == sorted(sent)

        # no request applied twice to the sequence on ANY replica
        all_logs = logs + [log_d]
        for log in all_logs:
            seq_vals = [val for seq, val in log if seq == 7]
            assert len(seq_vals) == len(set(seq_vals))

        # the last healthy endpoint was never evicted (pool never empty)
        assert min_healthy[0] >= 1

        # membership metrics prove the churn
        def changes(op, url):
            return registry.get(
                "ctpu_client_membership_changes_total",
                {"op": op, "endpoint": url},
            )

        assert changes("add", new_server.grpc_address) == 1
        assert changes("promote", new_server.grpc_address) == 1
        assert changes("retire", retire_url) == 1
        assert changes("evict", retire_url) == 1
        # reconnect + replay metrics prove the stream hop
        assert registry.get(
            "ctpu_client_stream_reconnects_total", {"endpoint": victim_url}
        ) == 1
        assert stream.reconnects == 1 and stream.replayed >= 1
        new_home = stream.url
        assert registry.get(
            "ctpu_client_stream_replayed_requests_total",
            {"endpoint": new_home},
        ) >= 1
        # shared-trace-id timeline: the stream is ONE span whose
        # endpoint-tagged attempts hop from the victim to the new home
        hops = stream.trace.attempt_endpoints()
        assert hops[0] == victim_url
        assert hops[-1] == new_home
        assert len(set(hops)) > 1
    finally:
        watch_stop.set()
        watch_thread.join(timeout=5)
        if stream is not None:
            stream.close()
        client.close()
        for p in proxies:
            p.close()
        for s in servers:
            s.stop()
        if new_server is not None:
            new_server.stop()


class TestChurnChaos:
    def test_churn_under_load(self):
        _run_churn_scenario()

    @pytest.mark.slow
    def test_churn_soak(self):
        """`make soak`: the same scenario, repeated — churn bugs are
        timing bugs, and repetition is how they surface."""
        for _ in range(3):
            _run_churn_scenario()
