"""Unit tests for client_tpu.utils — dtype mapping and serialization.

Mirrors the behavior contract of tritonclient.utils
(reference utils/__init__.py:128-345).
"""

import numpy as np
import pytest

from client_tpu import utils


class TestDtypeMapping:
    @pytest.mark.parametrize(
        "np_dtype,triton",
        [
            (np.bool_, "BOOL"),
            (np.int8, "INT8"),
            (np.int16, "INT16"),
            (np.int32, "INT32"),
            (np.int64, "INT64"),
            (np.uint8, "UINT8"),
            (np.uint16, "UINT16"),
            (np.uint32, "UINT32"),
            (np.uint64, "UINT64"),
            (np.float16, "FP16"),
            (np.float32, "FP32"),
            (np.float64, "FP64"),
            (np.object_, "BYTES"),
            (np.bytes_, "BYTES"),
        ],
    )
    def test_np_to_triton(self, np_dtype, triton):
        assert utils.np_to_triton_dtype(np_dtype) == triton

    def test_bf16_native(self):
        import ml_dtypes

        assert utils.np_to_triton_dtype(ml_dtypes.bfloat16) == "BF16"
        assert utils.triton_to_np_dtype("BF16") == np.dtype(ml_dtypes.bfloat16)

    def test_roundtrip(self):
        for t in ["BOOL", "INT32", "INT64", "UINT8", "FP16", "FP32", "FP64"]:
            assert utils.np_to_triton_dtype(utils.triton_to_np_dtype(t)) == t

    def test_unknown(self):
        assert utils.triton_to_np_dtype("NOPE") is None

    def test_element_size(self):
        assert utils.triton_dtype_element_size("FP32") == 4
        assert utils.triton_dtype_element_size("BF16") == 2
        assert utils.triton_dtype_element_size("BYTES") is None


class TestByteTensor:
    def test_roundtrip_bytes(self):
        arr = np.array([b"hello", b"", b"tpu \x00 world"], dtype=np.object_)
        wire = utils.serialize_byte_tensor(arr)
        out = utils.deserialize_bytes_tensor(wire.tobytes())
        assert list(out) == [b"hello", b"", b"tpu \x00 world"]

    def test_roundtrip_str(self):
        arr = np.array(["alpha", "beta"], dtype=np.object_)
        wire = utils.serialize_byte_tensor(arr)
        out = utils.deserialize_bytes_tensor(wire.tobytes())
        assert list(out) == [b"alpha", b"beta"]

    def test_row_major_order(self):
        arr = np.array([[b"a", b"b"], [b"c", b"d"]], dtype=np.object_)
        wire = utils.serialize_byte_tensor(arr).tobytes()
        out = utils.deserialize_bytes_tensor(wire)
        assert list(out) == [b"a", b"b", b"c", b"d"]

    def test_empty(self):
        arr = np.array([], dtype=np.object_)
        assert utils.serialize_byte_tensor(arr).size == 0

    def test_serialized_byte_size(self):
        arr = np.array([b"abc", b"de"], dtype=np.object_)
        assert utils.serialized_byte_size(arr) == (4 + 3) + (4 + 2)
        fixed = np.zeros((2, 3), dtype=np.float32)
        assert utils.serialized_byte_size(fixed) == 24


class TestBF16:
    def test_roundtrip(self):
        import ml_dtypes

        arr = np.array([1.0, -2.5, 3.25], dtype=np.float32)
        wire = utils.serialize_bf16_tensor(arr)
        assert wire.nbytes == 6
        out = utils.deserialize_bf16_tensor(wire.tobytes())
        assert out.dtype == np.dtype(ml_dtypes.bfloat16)
        np.testing.assert_allclose(out.astype(np.float32), arr, rtol=1e-2)

    def test_native_bf16_input(self):
        import ml_dtypes

        arr = np.array([0.5, 1.5], dtype=ml_dtypes.bfloat16)
        wire = utils.serialize_bf16_tensor(arr)
        out = utils.deserialize_bf16_tensor(wire.tobytes())
        np.testing.assert_array_equal(out.astype(np.float32), [0.5, 1.5])

    def test_rejects_int(self):
        with pytest.raises(utils.InferenceServerException):
            utils.serialize_bf16_tensor(np.array([1, 2], dtype=np.int32))


class TestWireBridge:
    def test_fixed_roundtrip(self):
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        buf = utils.to_wire_bytes(arr, "FP32")
        out = utils.from_wire_bytes(buf, "FP32", [3, 4])
        np.testing.assert_array_equal(out, arr)

    def test_bytes_roundtrip(self):
        arr = np.array([b"x", b"yz"], dtype=np.object_)
        buf = utils.to_wire_bytes(arr, "BYTES")
        out = utils.from_wire_bytes(buf, "BYTES", [2])
        assert list(out) == [b"x", b"yz"]

    def test_jax_array(self):
        import jax.numpy as jnp

        arr = jnp.ones((2, 2), dtype=jnp.float32)
        buf = utils.to_wire_bytes(arr, "FP32")
        assert len(buf) == 16

    def test_dtype_mismatch(self):
        with pytest.raises(utils.InferenceServerException):
            utils.to_wire_bytes(np.ones(2, dtype=np.int64), "FP32")


class TestException:
    def test_fields(self):
        e = utils.InferenceServerException("boom", status="400", debug_details="d")
        assert e.message() == "boom"
        assert e.status() == "400"
        assert e.debug_details() == "d"
        assert "[400] boom" == str(e)

    def test_raise_error(self):
        with pytest.raises(utils.InferenceServerException):
            utils.raise_error("nope")


class TestProto:
    def test_infer_request_roundtrip(self):
        from client_tpu._proto import inference_pb2 as pb

        req = pb.ModelInferRequest(model_name="m", model_version="2", id="abc")
        t = req.inputs.add()
        t.name, t.datatype = "INPUT0", "FP32"
        t.shape.extend([2, 2])
        req.raw_input_contents.append(b"\x00" * 16)
        req.parameters["sequence_id"].int64_param = 7
        g = pb.ModelInferRequest()
        g.ParseFromString(req.SerializeToString())
        assert g.model_name == "m"
        assert g.parameters["sequence_id"].int64_param == 7
        assert len(g.raw_input_contents[0]) == 16

    def test_model_config(self):
        from client_tpu._proto import model_config_pb2 as mc

        c = mc.ModelConfig(name="llama", backend="jax", max_batch_size=4)
        c.model_transaction_policy.decoupled = True
        i = c.input.add()
        i.name, i.data_type = "tokens", mc.TYPE_INT32
        i.dims.extend([-1])
        g = mc.ModelConfig()
        g.ParseFromString(c.SerializeToString())
        assert g.model_transaction_policy.decoupled
        assert g.input[0].data_type == mc.TYPE_INT32
