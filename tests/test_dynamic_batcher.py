"""Dynamic micro-batching: concurrent requests fuse into one padded forward.

The server-side analog of the batching the reference's model configs opt into
via ``dynamic_batching`` (normalized by model_parser.h:59-193); here it is a
first-class engine feature (client_tpu/serve/dynamic_batcher.py).
"""

import threading

import numpy as np
import pytest

from client_tpu.serve.dynamic_batcher import _bucket, _buckets_up_to, batchable_request
from client_tpu.serve.model_runtime import InferenceEngine, Model, TensorSpec
from client_tpu.utils import to_wire_bytes


def _echo_model(record, **kwargs):
    """Model that doubles its input and records every executed batch size."""

    def fn(inputs, params, ctx):
        record.append(int(inputs["IN"].shape[0]))
        return {"OUT": inputs["IN"] * 2.0}

    defaults = dict(
        max_batch_size=8,
        dynamic_batching=True,
        max_queue_delay_us=20000,
    )
    defaults.update(kwargs)
    return Model(
        "echo2x",
        inputs=[TensorSpec("IN", "FP32", [-1, 4])],
        outputs=[TensorSpec("OUT", "FP32", [-1, 4])],
        fn=fn,
        **defaults,
    )


def _request(arr, shm_output=None):
    raw = to_wire_bytes(arr, "FP32")
    req = {
        "id": "",
        "parameters": {},
        "inputs": [
            {
                "name": "IN",
                "datatype": "FP32",
                "shape": list(arr.shape),
                "parameters": {"binary_data_size": len(raw)},
            }
        ],
        "outputs": [{"name": "OUT", "parameters": {"binary_data": True}}],
    }
    if shm_output:
        req["outputs"][0]["parameters"] = {
            "shared_memory_region": shm_output,
            "shared_memory_byte_size": arr.nbytes,
        }
    return req, raw


def test_bucket_shapes():
    assert [_bucket(n, 64) for n in (1, 2, 3, 5, 7, 9, 13, 20, 40, 50)] == [
        1, 2, 3, 6, 8, 12, 16, 24, 48, 64,
    ]
    assert _bucket(100, 64) == 64
    buckets = _buckets_up_to(64)
    assert buckets == [1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64]
    # every _bucket output is a warmed bucket
    for n in range(1, 65):
        assert _bucket(n, 64) in buckets


def test_concurrent_requests_fuse_and_split_correctly():
    record = []
    engine = InferenceEngine(models=[_echo_model(record)])
    n_threads = 8
    arrays = [
        np.full((1, 4), float(i), dtype=np.float32) for i in range(n_threads)
    ]
    results = [None] * n_threads
    barrier = threading.Barrier(n_threads)

    def run(i):
        req, raw = _request(arrays[i])
        barrier.wait()
        response, blobs = engine.execute("echo2x", "", req, raw)
        results[i] = np.frombuffer(blobs[0], dtype=np.float32).reshape(1, 4)

    threads = [threading.Thread(target=run, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in range(n_threads):
        np.testing.assert_array_equal(results[i], arrays[i] * 2.0)
    # fewer executions than requests proves fusion happened
    assert sum(record) >= n_threads  # padded rows included
    assert len(record) < n_threads
    # every executed batch size is a warmable bucket (padding applied)
    for b in record:
        assert b in _buckets_up_to(8)
    stats = engine.statistics("echo2x")[0]["inference_stats"]
    assert stats["success"]["count"] == n_threads
    engine.close()


def test_batched_model_response_parameters_replicate():
    """A batched model's reserved "__parameters__" result key is
    batch-wide: the split replicates it to every request instead of
    row-slicing the dict (which raised and 500'd the whole group)."""
    record = []

    def fn(inputs, params, ctx):
        record.append(int(inputs["IN"].shape[0]))
        return {
            "OUT": inputs["IN"] * 2.0,
            "__parameters__": {"engine_pass": 1, "batched": True},
        }

    model = Model(
        "echo2x",
        inputs=[TensorSpec("IN", "FP32", [-1, 4])],
        outputs=[TensorSpec("OUT", "FP32", [-1, 4])],
        fn=fn,
        max_batch_size=8,
        dynamic_batching=True,
        max_queue_delay_us=20000,
    )
    engine = InferenceEngine(models=[model])
    n_threads = 4
    arrays = [
        np.full((1, 4), float(i), dtype=np.float32) for i in range(n_threads)
    ]
    responses = [None] * n_threads
    blobs_out = [None] * n_threads
    barrier = threading.Barrier(n_threads)

    def run(i):
        req, raw = _request(arrays[i])
        barrier.wait()
        responses[i], blobs_out[i] = engine.execute("echo2x", "", req, raw)

    threads = [
        threading.Thread(target=run, args=(i,)) for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in range(n_threads):
        got = np.frombuffer(blobs_out[i][0], dtype=np.float32).reshape(1, 4)
        np.testing.assert_array_equal(got, arrays[i] * 2.0)
        assert responses[i]["parameters"] == {
            "engine_pass": 1, "batched": True,
        }
        # the reserved key never leaks as an output tensor
        assert [o["name"] for o in responses[i]["outputs"]] == ["OUT"]
    engine.close()


def test_fused_group_fn_drops_response_parameters():
    """fused_batching traces the model fn, so a "__parameters__" dict
    would be a trace-time constant; the fused splitter drops it instead
    of crashing the whole group in jnp.split."""
    import jax.numpy as jnp

    from client_tpu.serve.dynamic_batcher import _fused_group_fn

    def fn(inputs, params, ctx):
        return {"OUT": inputs["IN"] * 2.0, "__parameters__": {"n": 1}}

    fused = _fused_group_fn(fn)
    parts = {"IN": (jnp.ones((1, 4)), jnp.full((1, 4), 2.0))}
    out = fused(parts)
    assert set(out) == {"OUT"}
    np.testing.assert_array_equal(np.asarray(out["OUT"][0]), np.full((1, 4), 2.0))
    np.testing.assert_array_equal(np.asarray(out["OUT"][1]), np.full((1, 4), 4.0))


def test_multi_row_requests_batch():
    record = []
    engine = InferenceEngine(models=[_echo_model(record)])
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    req, raw = _request(arr)
    response, blobs = engine.execute("echo2x", "", req, raw)
    out = np.frombuffer(blobs[0], dtype=np.float32).reshape(3, 4)
    np.testing.assert_array_equal(out, arr * 2.0)
    assert response["outputs"][0]["shape"] == [3, 4]
    engine.close()


def test_oversize_request_takes_direct_path():
    record = []
    engine = InferenceEngine(models=[_echo_model(record)])
    arr = np.zeros((9, 4), dtype=np.float32)  # > max_batch_size=8
    req, raw = _request(arr)
    response, blobs = engine.execute("echo2x", "", req, raw)
    assert np.frombuffer(blobs[0], dtype=np.float32).size == 36
    assert record == [9]  # executed unbatched, unpadded
    engine.close()


def test_shm_output_bypasses_batcher():
    model = _echo_model([])
    arr = np.zeros((1, 4), dtype=np.float32)
    req, _ = _request(arr, shm_output="region0")
    inputs = {"IN": arr}
    assert not batchable_request(model, inputs, {}, None, req)


def test_sequence_and_device_inputs_bypass_batcher():
    model = _echo_model([])
    arr = np.zeros((1, 4), dtype=np.float32)
    req, _ = _request(arr)
    assert not batchable_request(
        model, {"IN": arr}, {"sequence_id": 7}, None, req
    )

    class FakeDeviceArray:
        ndim = 2
        shape = (1, 4)

    assert not batchable_request(model, {"IN": FakeDeviceArray()}, {}, None, req)
    # plain numpy wire request IS batchable
    assert batchable_request(model, {"IN": arr}, {}, None, req)


def test_device_requests_fuse_on_device_with_shm_outputs():
    """TPU-shm requests (device-resident inputs, shm outputs) batch on the
    device path: one fused forward, outputs split as live device slices and
    written to regions without any D2H on the dispatch path."""
    from client_tpu.utils import tpu_shared_memory as tpushm

    record = []
    engine = InferenceEngine(
        models=[_echo_model(record, batch_device_inputs=True)]
    )
    n_threads = 4
    handles = []
    try:
        for i in range(n_threads):
            h_in = tpushm.create_shared_memory_region(f"dev_in{i}", 16)
            tpushm.set_shared_memory_region(
                h_in, [np.full((1, 4), float(i + 1), dtype=np.float32)]
            )
            h_out = tpushm.create_shared_memory_region(f"dev_out{i}", 16)
            engine.shm.register_tpu(
                f"dev_in{i}", tpushm.get_raw_handle(h_in), 0, 16
            )
            engine.shm.register_tpu(
                f"dev_out{i}", tpushm.get_raw_handle(h_out), 0, 16
            )
            handles.append((h_in, h_out))

        barrier = threading.Barrier(n_threads)
        errors = []

        def run(i):
            req = {
                "id": "",
                "parameters": {},
                "inputs": [
                    {
                        "name": "IN",
                        "datatype": "FP32",
                        "shape": [1, 4],
                        "parameters": {
                            "shared_memory_region": f"dev_in{i}",
                            "shared_memory_byte_size": 16,
                        },
                    }
                ],
                "outputs": [
                    {
                        "name": "OUT",
                        "parameters": {
                            "shared_memory_region": f"dev_out{i}",
                            "shared_memory_byte_size": 16,
                        },
                    }
                ],
            }
            barrier.wait()
            try:
                engine.execute("echo2x", "", req, b"")
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append(e)

        threads = [
            threading.Thread(target=run, args=(i,)) for i in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        # fewer executions than requests proves device-side fusion happened
        assert len(record) < n_threads
        for i, (h_in, h_out) in enumerate(handles):
            got = tpushm.get_contents_as_numpy(h_out, np.float32, [1, 4])
            np.testing.assert_array_equal(
                got, np.full((1, 4), 2.0 * (i + 1), dtype=np.float32)
            )
    finally:
        engine.close()
        for h_in, h_out in handles:
            tpushm.destroy_shared_memory_region(h_in)
            tpushm.destroy_shared_memory_region(h_out)


def test_fused_device_groups_one_dispatch_correct_splits():
    """fused_batching: a device group runs concat+forward+split inside ONE
    jitted call — per-request outputs come back already split, values exact."""
    from client_tpu.serve.dynamic_batcher import ModelBatcher
    import jax

    record = []
    model = _echo_model(
        record, batch_device_inputs=True, fused_batching=True
    )

    class _Stats:
        def record_batched(self, **kw):
            record.append(("batched", kw["rows"]))

    batcher = ModelBatcher(model, _Stats(), max_queue_delay_s=0.05)
    try:
        results = [None] * 4
        def run(i):
            x = jax.device_put(
                np.full((1, 4), float(i + 1), dtype=np.float32)
            )
            results[i] = batcher.submit({"IN": x})
        threads = [
            threading.Thread(target=run, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, res in enumerate(results):
            np.testing.assert_array_equal(
                np.asarray(res["OUT"]),
                np.full((1, 4), 2.0 * (i + 1), dtype=np.float32),
            )
        rows = [r[1] for r in record if isinstance(r, tuple)]
        assert sum(rows) == 4 and len(rows) < 4  # fused, not per-request
        # mixed row counts retrace but stay correct
        a = jax.device_put(np.ones((2, 4), dtype=np.float32))
        out = batcher.submit({"IN": a})
        np.testing.assert_array_equal(
            np.asarray(out["OUT"]), 2.0 * np.ones((2, 4), dtype=np.float32)
        )
    finally:
        batcher.close()


def test_device_request_batchable_and_mixed_rejected():
    import jax

    model = _echo_model([], batch_device_inputs=True)
    req_shm_out = {
        "outputs": [
            {
                "name": "OUT",
                "parameters": {
                    "shared_memory_region": "r",
                    "shared_memory_byte_size": 16,
                },
            }
        ]
    }
    dev = jax.device_put(np.zeros((1, 4), dtype=np.float32))
    host = np.zeros((1, 4), dtype=np.float32)
    # all-device inputs batch, even with shm outputs
    assert batchable_request(model, {"IN": dev}, {}, None, req_shm_out)
    # ... but only when the model opts in: by default device-resident
    # requests dispatch directly (zero-copy, no assemble/split overhead)
    assert not batchable_request(
        _echo_model([]), {"IN": dev}, {}, None, req_shm_out
    )
    # host inputs with shm outputs keep the direct path
    assert not batchable_request(model, {"IN": host}, {}, None, req_shm_out)
    # mixed host/device inputs keep the direct path
    model2 = Model(
        "echo2",
        inputs=[TensorSpec("A", "FP32", [-1, 4]), TensorSpec("B", "FP32", [-1, 4])],
        outputs=[TensorSpec("OUT", "FP32", [-1, 4])],
        fn=lambda i, p, c: {"OUT": i["A"]},
        max_batch_size=8,
        dynamic_batching=True,
    )
    assert not batchable_request(
        model2, {"A": dev, "B": host}, {}, None, {"outputs": []}
    )


def test_batcher_error_propagates_per_request():
    def fn(inputs, params, ctx):
        raise ValueError("boom")

    model = Model(
        "boom",
        inputs=[TensorSpec("IN", "FP32", [-1, 4])],
        outputs=[TensorSpec("OUT", "FP32", [-1, 4])],
        fn=fn,
        max_batch_size=8,
        dynamic_batching=True,
    )
    engine = InferenceEngine(models=[model])
    req, raw = _request(np.zeros((1, 4), dtype=np.float32))
    from client_tpu.utils import InferenceServerException

    with pytest.raises(InferenceServerException, match="boom"):
        engine.execute("boom", "", req, raw)
    stats = engine.statistics("boom")[0]["inference_stats"]
    assert stats["fail"]["count"] == 1
    engine.close()


def test_unload_closes_batcher_and_reload_works():
    record = []
    engine = InferenceEngine(models=[_echo_model(record)])
    arr = np.ones((1, 4), dtype=np.float32)
    req, raw = _request(arr)
    engine.execute("echo2x", "", req, raw)
    engine.unload_model("echo2x")
    engine.load_model("echo2x")
    response, blobs = engine.execute("echo2x", "", req, raw)
    np.testing.assert_array_equal(
        np.frombuffer(blobs[0], dtype=np.float32).reshape(1, 4), arr * 2.0
    )
    engine.close()


def test_request_parameters_bypass_batcher():
    model = _echo_model([])
    arr = np.zeros((1, 4), dtype=np.float32)
    req, _ = _request(arr)
    # a custom parameter must reach model.fn, so it takes the direct path
    assert not batchable_request(model, {"IN": arr}, {"top_k": 5}, None, req)
    assert batchable_request(
        model, {"IN": arr}, {"binary_data_output": True}, None, req
    )


def test_replacing_model_replaces_batcher():
    record_v1, record_v2 = [], []
    engine = InferenceEngine(models=[_echo_model(record_v1)])
    arr = np.ones((1, 4), dtype=np.float32)
    req, raw = _request(arr)
    engine.execute("echo2x", "", req, raw)
    assert record_v1  # v1 batcher served it

    v2 = _echo_model(record_v2)
    engine.add_model(v2)
    engine.execute("echo2x", "", req, raw)
    assert record_v2  # new batcher bound to the new model fn
    assert len(record_v1) == 1
    engine.close()


def test_warmup_compiles_all_buckets():
    record = []
    engine = InferenceEngine(models=[_echo_model(record, warmup=True)])
    assert sorted(set(record)) == _buckets_up_to(8)
    engine.close()


def test_dynamic_batching_in_model_config():
    model = _echo_model([])
    cfg = model.config()
    assert cfg["dynamic_batching"]["max_queue_delay_microseconds"] == 20000
