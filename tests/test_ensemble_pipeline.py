"""Ensemble DAG scheduler (serve/pipeline.py) acceptance suite.

Covers the ISSUE-8 contract:

- load-time validation: cycles, unknown composing models, unmapped and
  dangling tensors, dtype/shape mismatches, duplicate producers,
  sequence-stateful and decoupled composing models — all 400 at
  ``add_model``/``load_model``, never at infer time,
- parallel-branch concurrency proven BOTH by wall clock and by
  overlapping per-step trace spans,
- nested ensembles recursing through the same scheduler,
- mid-DAG step failure: the rest of the DAG is cancelled, the error
  names the failing step, per-step and ensemble-level failures each
  record exactly once,
- request-params threading to composing models (ensemble-reserved keys
  stripped),
- device-resident intermediates: a jax-backed consumer receives the
  producer's ``jax.Array`` (no ``np.asarray`` host hop), a python
  consumer gets a host array and the hop is counted,
- per-composing-model stats reconciling exactly against the ensemble's
  own ``compute_infer`` total,
- chaos: a composing model unloaded mid-flight surfaces as a clean 4xx
  with no hang.
"""

import threading
import time

import numpy as np
import pytest

from client_tpu.serve.model_runtime import InferenceEngine, Model, TensorSpec
from client_tpu.serve.pipeline import (
    ENSEMBLE_RESERVED_PARAMS,
    build_dag,
    step_params,
)
from client_tpu.tracing import read_trace_file
from client_tpu.utils import InferenceServerException


def _identity(name, dtype="INT32", sleep_s=0.0, record=None, fail=False,
              on_call=None, **model_kwargs):
    """A configurable one-in/one-out python model for DAG shapes."""

    def fn(inputs, params, ctx):
        if record is not None:
            record.append((name, dict(params or {}), time.monotonic()))
        if on_call is not None:
            on_call()
        if sleep_s:
            time.sleep(sleep_s)
        if fail:
            raise InferenceServerException(f"{name} exploded", status="500")
        return {"OUT": inputs["IN"]}

    return Model(
        name,
        inputs=[TensorSpec("IN", dtype, [-1])],
        outputs=[TensorSpec("OUT", dtype, [-1])],
        fn=fn,
        **model_kwargs,
    )


def _ensemble(name, steps, in_dtype="INT32", out_names=("OUT",),
              out_dtype=None, in_names=("IN",)):
    return Model(
        name,
        inputs=[TensorSpec(n, in_dtype, [-1]) for n in in_names],
        outputs=[TensorSpec(n, out_dtype or in_dtype, [-1])
                 for n in out_names],
        fn=None,
        platform="ensemble",
        ensemble_steps=steps,
    )


def _step(model, inp, out):
    return {"model_name": model, "input_map": inp, "output_map": out}


def _infer(engine, name, arrays, params=None):
    request = {
        "id": "t",
        "inputs": [
            {"name": n, "shape": list(a.shape), "datatype": dt,
             "data": a.flatten().tolist()}
            for n, dt, a in arrays
        ],
    }
    if params:
        request["parameters"] = dict(params)
    response, _ = engine.execute(name, "", request, b"")
    return {o["name"]: np.array(o["data"]).reshape(o["shape"])
            for o in response["outputs"]}


def _inference_stats(engine, name):
    return engine.statistics(name)[0]["inference_stats"]


# -- load-time validation ----------------------------------------------------


class TestValidation:
    def _reject(self, models, ensemble, match):
        engine = InferenceEngine(models)
        try:
            with pytest.raises(InferenceServerException, match=match) as ei:
                engine.add_model(ensemble)
            assert ei.value.status() == "400"
        finally:
            engine.close()

    def test_unknown_composing_model_rejected_at_add(self):
        self._reject(
            [_identity("a")],
            _ensemble("e", [_step("ghost", {"IN": "IN"}, {"OUT": "OUT"})]),
            match="unknown composing model 'ghost'",
        )

    def test_cycle_rejected_at_add(self):
        steps = [
            _step("a", {"IN": "t2"}, {"OUT": "t1"}),
            _step("b", {"IN": "t1"}, {"OUT": "t2"}),
        ]
        # t1/t2 feed each other; OUT passes through neither -> make OUT
        # produced so only the cycle trips
        steps.append(_step("a", {"IN": "IN"}, {"OUT": "OUT"}))
        self._reject(
            [_identity("a"), _identity("b")],
            _ensemble("e", steps),
            match="dependency cycle",
        )

    def test_dangling_tensor_rejected_at_add(self):
        self._reject(
            [_identity("a")],
            _ensemble("e", [_step("a", {"IN": "nowhere"}, {"OUT": "OUT"})]),
            match="dangling tensor",
        )

    def test_unmapped_composing_input_rejected_at_add(self):
        self._reject(
            [_identity("a")],
            _ensemble("e", [_step("a", {}, {"OUT": "OUT"})]),
            match="unmapped",
        )

    def test_dtype_mismatch_rejected_at_add(self):
        self._reject(
            [_identity("a", dtype="INT32"), _identity("b", dtype="FP32")],
            _ensemble("e", [
                _step("a", {"IN": "IN"}, {"OUT": "mid"}),
                _step("b", {"IN": "mid"}, {"OUT": "OUT"}),
            ], out_dtype="FP32"),
            match="expects FP32 but tensor 'mid' carries INT32",
        )

    def test_shape_conflict_rejected_at_add(self):
        wide = Model(
            "wide",
            inputs=[TensorSpec("IN", "INT32", [-1, 8])],
            outputs=[TensorSpec("OUT", "INT32", [-1, 8])],
            fn=lambda i, p, c: {"OUT": i["IN"]},
        )
        narrow = Model(
            "narrow",
            inputs=[TensorSpec("IN", "INT32", [-1, 4])],
            outputs=[TensorSpec("OUT", "INT32", [-1, 4])],
            fn=lambda i, p, c: {"OUT": i["IN"]},
        )
        ens = Model(
            "e",
            inputs=[TensorSpec("IN", "INT32", [-1, 8])],
            outputs=[TensorSpec("OUT", "INT32", [-1, 4])],
            fn=None,
            platform="ensemble",
            ensemble_steps=[
                _step("wide", {"IN": "IN"}, {"OUT": "mid"}),
                _step("narrow", {"IN": "mid"}, {"OUT": "OUT"}),
            ],
        )
        self._reject([wide, narrow], ens, match="conflict with tensor 'mid'")

    def test_duplicate_producer_rejected_at_add(self):
        self._reject(
            [_identity("a")],
            _ensemble("e", [
                _step("a", {"IN": "IN"}, {"OUT": "OUT"}),
                _step("a", {"IN": "IN"}, {"OUT": "OUT"}),
            ]),
            match="produced by both step 0 and step 1",
        )

    def test_unproduced_output_rejected_at_add(self):
        self._reject(
            [_identity("a")],
            _ensemble("e", [_step("a", {"IN": "IN"}, {"OUT": "mid"})]),
            match="output tensor 'OUT' is not produced",
        )

    def test_self_reference_rejected_at_add(self):
        self._reject(
            [_identity("a")],
            _ensemble("e", [_step("e", {"IN": "IN"}, {"OUT": "OUT"})]),
            match="refers to the ensemble itself",
        )

    def test_self_cycle_rejected_at_add(self):
        # a step reading its own output is a one-step cycle Kahn never
        # sees (the dep edge would be skipped) — it must still be a 400
        # at add, not an infer-time 500 "tensor not available"
        self._reject(
            [_identity("a")],
            _ensemble("e", [_step("a", {"IN": "t"}, {"OUT": "t"}),
                            _step("a", {"IN": "IN"}, {"OUT": "OUT"})]),
            match="reads its own output tensor 't'",
        )

    def test_sequence_composing_model_rejected_at_add(self):
        self._reject(
            [_identity("seq", stateful=True)],
            _ensemble("e", [_step("seq", {"IN": "IN"}, {"OUT": "OUT"})]),
            match="sequence",
        )

    def test_decoupled_composing_model_rejected_at_add(self):
        self._reject(
            [_identity("dec", decoupled=True)],
            _ensemble("e", [_step("dec", {"IN": "IN"}, {"OUT": "OUT"})]),
            match="decoupled",
        )

    def test_load_revalidates_against_current_repository(self):
        """A composing model swapped for an incompatible one after add must
        fail the ensemble's *load* with a 400, not the next infer."""
        engine = InferenceEngine([_identity("a", dtype="INT32")])
        try:
            engine.add_model(_ensemble(
                "e", [_step("a", {"IN": "IN"}, {"OUT": "OUT"})]
            ))
            engine.add_model(_identity("a", dtype="FP32"))  # swap in place
            with pytest.raises(InferenceServerException) as ei:
                engine.load_model("e")
            assert ei.value.status() == "400"
            assert "expects FP32 but tensor 'IN' carries INT32" in str(
                ei.value
            )
        finally:
            engine.close()

    def test_incompatible_swap_unloads_dependent_ensemble(self):
        """add_model of an incompatible composing-model replacement must
        not leave the loaded ensemble serving stale-typed responses: the
        dependent goes NOT READY (clean 400 at infer), and reloading it
        names the real mismatch."""
        engine = InferenceEngine([_identity("a", dtype="INT32")])
        try:
            engine.add_model(_ensemble(
                "e", [_step("a", {"IN": "IN"}, {"OUT": "OUT"})]
            ))
            assert engine.model_ready("e")
            engine.add_model(_identity("a", dtype="FP32"))  # breaking swap
            assert not engine.model_ready("e")
            with pytest.raises(InferenceServerException) as ei:
                _infer(engine, "e",
                       [("IN", "INT32", np.arange(4, dtype=np.int32))])
            assert ei.value.status() == "400"
            with pytest.raises(InferenceServerException,
                               match="expects FP32"):
                engine.load_model("e")
        finally:
            engine.close()

    def test_compatible_swap_keeps_dependent_ensemble_ready(self):
        engine = InferenceEngine([_identity("a", dtype="INT32")])
        try:
            engine.add_model(_ensemble(
                "e", [_step("a", {"IN": "IN"}, {"OUT": "OUT"})]
            ))
            engine.add_model(_identity("a", dtype="INT32"))  # same specs
            assert engine.model_ready("e")
            x = np.arange(4, dtype=np.int32)
            out = _infer(engine, "e", [("IN", "INT32", x)])
            np.testing.assert_array_equal(out["OUT"], x)
        finally:
            engine.close()

    def test_valid_dag_computes_deps(self):
        a = _identity("a")
        b = _identity("b")
        ens = _ensemble("e", [
            _step("a", {"IN": "IN"}, {"OUT": "mid"}),
            _step("b", {"IN": "mid"}, {"OUT": "OUT"}),
        ])
        dag = build_dag(ens, {"a": a, "b": b}.get)
        assert dag.is_chain
        assert dag.steps[1].deps == {0}
        assert dag.steps[0].consumers == {1}

    def test_parallel_branches_not_a_chain(self):
        a = _identity("a")
        ens = _ensemble("e", [
            _step("a", {"IN": "IN"}, {"OUT": "OUT"}),
            _step("a", {"IN": "IN"}, {"OUT": "OUT1"}),
        ], out_names=("OUT", "OUT1"))
        dag = build_dag(ens, {"a": a}.get)
        assert not dag.is_chain


# -- execution ---------------------------------------------------------------


class TestExecution:
    def test_builtin_simple_ensemble_results(self):
        from client_tpu.serve.builtins import (
            ensemble_model,
            identity_model,
            simple_model,
        )

        engine = InferenceEngine(
            [simple_model(), identity_model("identity_int32", "INT32"),
             ensemble_model()]
        )
        try:
            i0 = np.arange(16, dtype=np.int32).reshape(1, 16)
            i1 = np.full((1, 16), 4, dtype=np.int32)
            out = _infer(engine, "simple_ensemble", [
                ("INPUT0", "INT32", i0), ("INPUT1", "INT32", i1),
            ])
            np.testing.assert_array_equal(out["OUTPUT0"], i0 + i1)
            np.testing.assert_array_equal(out["OUTPUT1"], i0 - i1)
        finally:
            engine.close()

    def test_parallel_branches_overlap(self, tmp_path):
        """Two independent 0.15 s branches: wall clock shows overlap AND
        the per-step trace spans overlap in time (the acceptance proof)."""
        trace_file = str(tmp_path / "trace.jsonl")
        engine = InferenceEngine([
            _identity("slow_a", sleep_s=0.15),
            _identity("slow_b", sleep_s=0.15),
        ])
        try:
            engine.add_model(_ensemble("fork", [
                _step("slow_a", {"IN": "IN"}, {"OUT": "OUT"}),
                _step("slow_b", {"IN": "IN"}, {"OUT": "OUT1"}),
            ], out_names=("OUT", "OUT1")))
            engine.update_trace_settings({
                "trace_level": ["TIMESTAMPS"], "trace_rate": "1",
                "trace_count": "-1", "trace_file": trace_file,
            })
            trace = engine.tracer.sample(None, model_name="fork",
                                         protocol="test")
            trace.event("REQUEST_START")
            x = np.arange(8, dtype=np.int32)
            t0 = time.monotonic()
            request = {"id": "t", "inputs": [
                {"name": "IN", "shape": [8], "datatype": "INT32",
                 "data": x.tolist()}]}
            engine.execute("fork", "", request, b"", trace=trace)
            wall = time.monotonic() - t0
            engine.tracer.complete(trace)
            # serial would be >= 0.30s; overlapped is ~0.15s
            assert wall < 0.26, f"branches ran serially ({wall:.3f}s)"

            spans = {
                r["step"]: {t["name"]: t["ns"] for t in r["timestamps"]}
                for r in read_trace_file(trace_file) if r.get("step")
            }
            assert set(spans) == {"step_0:slow_a", "step_1:slow_b"}
            a, b = spans["step_0:slow_a"], spans["step_1:slow_b"]
            overlap_start = max(a["COMPUTE_START"], b["COMPUTE_START"])
            overlap_end = min(a["COMPUTE_END"], b["COMPUTE_END"])
            assert overlap_end > overlap_start, "step spans do not overlap"
            ensembles = {
                r["ensemble"] for r in read_trace_file(trace_file)
                if r.get("step")
            }
            assert ensembles == {"fork"}
        finally:
            engine.close()

    def test_nested_ensemble_recurses(self):
        engine = InferenceEngine([_identity("leaf")])
        try:
            engine.add_model(_ensemble(
                "inner", [_step("leaf", {"IN": "IN"}, {"OUT": "OUT"})]
            ))
            engine.add_model(_ensemble(
                "outer", [
                    _step("inner", {"IN": "IN"}, {"OUT": "mid"}),
                    _step("leaf", {"IN": "mid"}, {"OUT": "OUT"}),
                ]
            ))
            x = np.arange(6, dtype=np.int32)
            out = _infer(engine, "outer", [("IN", "INT32", x)])
            np.testing.assert_array_equal(out["OUT"], x)
            # the nested ensemble and the leaf both recorded real stats:
            # leaf ran twice (once under inner, once directly)
            assert _inference_stats(engine, "inner")["success"]["count"] == 1
            assert _inference_stats(engine, "leaf")["success"]["count"] == 2
        finally:
            engine.close()

    def test_request_params_thread_to_composing_models(self):
        seen = []
        engine = InferenceEngine([_identity("a", record=seen)])
        try:
            engine.add_model(_ensemble(
                "e", [_step("a", {"IN": "IN"}, {"OUT": "OUT"})]
            ))
            x = np.arange(4, dtype=np.int32)
            _infer(engine, "e", [("IN", "INT32", x)],
                   params={"temperature": 0.5, "timeout": 10,
                           "priority": 3})
            (_, params, _), = seen
            assert params.get("temperature") == 0.5
            # ensemble-reserved keys never reach composing models
            assert not (set(params) & ENSEMBLE_RESERVED_PARAMS)
        finally:
            engine.close()

    def test_step_params_strips_only_reserved_keys(self):
        params = {"sequence_id": 9, "timeout": 1, "seed": 7}
        assert step_params(params) == {"seed": 7}
        assert step_params(None) == {}

    def test_mid_dag_failure_cancels_rest_and_names_step(self):
        ran = []
        engine = InferenceEngine([
            _identity("ok", record=ran),
            _identity("boom", fail=True),
            _identity("never", record=ran),
        ])
        try:
            engine.add_model(_ensemble("chain", [
                _step("ok", {"IN": "IN"}, {"OUT": "t1"}),
                _step("boom", {"IN": "t1"}, {"OUT": "t2"}),
                _step("never", {"IN": "t2"}, {"OUT": "OUT"}),
            ]))
            x = np.arange(4, dtype=np.int32)
            with pytest.raises(InferenceServerException) as ei:
                _infer(engine, "chain", [("IN", "INT32", x)])
            msg = str(ei.value)
            assert "step 1" in msg and "'boom'" in msg
            assert ei.value.status() == "500"
            assert [n for n, _, _ in ran] == ["ok"], "step after failure ran"
            # cancellation is visible in metrics and per-model stats
            assert engine.metrics.get(
                "ctpu_ensemble_cancelled_steps_total", {"model": "chain"}
            ) == 1
            assert _inference_stats(engine, "never")["success"]["count"] == 0
            # the composing failure AND the ensemble-level failure each
            # recorded exactly once (the old double-raise skew)
            assert _inference_stats(engine, "boom")["fail"]["count"] == 1
            assert _inference_stats(engine, "chain")["fail"]["count"] == 1
        finally:
            engine.close()

    def test_parallel_branch_failure_does_not_hang(self):
        engine = InferenceEngine([
            _identity("slow", sleep_s=0.2),
            _identity("boom", fail=True),
        ])
        try:
            engine.add_model(_ensemble("fork", [
                _step("slow", {"IN": "IN"}, {"OUT": "OUT"}),
                _step("boom", {"IN": "IN"}, {"OUT": "OUT1"}),
            ], out_names=("OUT", "OUT1")))
            x = np.arange(4, dtype=np.int32)
            t0 = time.monotonic()
            with pytest.raises(InferenceServerException, match="'boom'"):
                _infer(engine, "fork", [("IN", "INT32", x)])
            # in-flight branch drained, nothing hangs
            assert time.monotonic() - t0 < 2.0
        finally:
            engine.close()

    def test_missing_composing_output_is_500_naming_step(self):
        broken = Model(
            "half",
            inputs=[TensorSpec("IN", "INT32", [-1])],
            outputs=[TensorSpec("OUT", "INT32", [-1])],
            fn=lambda i, p, c: {},  # declares OUT, produces nothing
        )
        engine = InferenceEngine([broken])
        try:
            engine.add_model(_ensemble(
                "e", [_step("half", {"IN": "IN"}, {"OUT": "OUT"})]
            ))
            with pytest.raises(InferenceServerException) as ei:
                _infer(engine, "e", [("IN", "INT32",
                                      np.arange(2, dtype=np.int32))])
            assert ei.value.status() == "500"
            assert "produced no output 'OUT'" in str(ei.value)
        finally:
            engine.close()

    def test_composing_model_unloaded_mid_flight_clean_4xx(self):
        """Chaos case: the second step's model is unloaded while the first
        step runs — the request fails promptly with the engine's 400."""
        engine = InferenceEngine([_identity("b")])
        gate = threading.Event()

        def unload_b():
            engine.unload_model("b")
            gate.set()

        engine.add_model(_identity("a", on_call=unload_b))
        try:
            engine.add_model(_ensemble("chain", [
                _step("a", {"IN": "IN"}, {"OUT": "mid"}),
                _step("b", {"IN": "mid"}, {"OUT": "OUT"}),
            ]))
            t0 = time.monotonic()
            with pytest.raises(InferenceServerException) as ei:
                _infer(engine, "chain", [("IN", "INT32",
                                          np.arange(2, dtype=np.int32))])
            assert gate.is_set()
            assert time.monotonic() - t0 < 2.0, "unload mid-flight hung"
            assert ei.value.status() == "400"
            assert "step 1" in str(ei.value) and "'b'" in str(ei.value)
        finally:
            engine.close()


# -- statistics / metrics reconciliation -------------------------------------


class TestStatsReconcile:
    def test_composing_durations_sum_to_ensemble_compute_infer(self):
        engine = InferenceEngine([_identity("a"), _identity("b")])
        try:
            engine.add_model(_ensemble("e", [
                _step("a", {"IN": "IN"}, {"OUT": "mid"}),
                _step("b", {"IN": "mid"}, {"OUT": "OUT"}),
            ]))
            x = np.arange(8, dtype=np.int32)
            for _ in range(3):
                _infer(engine, "e", [("IN", "INT32", x)])
            ens = _inference_stats(engine, "e")
            total = sum(
                _inference_stats(engine, n)["success"]["ns"]
                for n in ("a", "b")
            )
            assert ens["success"]["count"] == 3
            assert ens["compute_infer"]["ns"] == total
        finally:
            engine.close()

    def test_step_stats_have_real_phase_split(self):
        """The old chain stuffed the whole step into infer_ns with zero
        input/output split; the scheduler records a real one."""
        engine = InferenceEngine([_identity("a")])
        try:
            engine.add_model(_ensemble(
                "e", [_step("a", {"IN": "IN"}, {"OUT": "OUT"})]
            ))
            _infer(engine, "e", [("IN", "INT32",
                                  np.arange(64, dtype=np.int32))])
            sub = _inference_stats(engine, "a")
            assert sub["compute_input"]["ns"] > 0
            assert sub["compute_infer"]["ns"] > 0
            assert sub["success"]["ns"] >= (
                sub["compute_input"]["ns"] + sub["compute_infer"]["ns"]
            )
        finally:
            engine.close()

    def test_ensemble_metric_series(self):
        engine = InferenceEngine([_identity("a"), _identity("b")])
        try:
            engine.add_model(_ensemble("e", [
                _step("a", {"IN": "IN"}, {"OUT": "mid"}),
                _step("b", {"IN": "mid"}, {"OUT": "OUT"}),
            ]))
            x = np.arange(4, dtype=np.int32)
            _infer(engine, "e", [("IN", "INT32", x)])
            m = engine.metrics
            assert m.get("ctpu_ensemble_requests_total",
                         {"model": "e"}) == 1
            assert m.get("ctpu_ensemble_steps_total",
                         {"model": "e", "composing_model": "a"}) == 1
            assert m.get("ctpu_ensemble_steps_total",
                         {"model": "e", "composing_model": "b"}) == 1
        finally:
            engine.close()

    def test_batched_composing_model_records_queue_stats(self):
        """A dynamic-batching composing model rides its batcher from the
        pipeline: executions land under its own name with queue counts."""
        engine = InferenceEngine([
            _identity("batched", max_batch_size=8, dynamic_batching=True),
        ])
        try:
            engine.add_model(Model(
                "e",
                inputs=[TensorSpec("IN", "INT32", [-1, 4])],
                outputs=[TensorSpec("OUT", "INT32", [-1, 4])],
                fn=None,
                platform="ensemble",
                ensemble_steps=[_step("batched", {"IN": "IN"},
                                      {"OUT": "OUT"})],
            ))
            x = np.arange(4, dtype=np.int32).reshape(1, 4)
            _infer(engine, "e", [("IN", "INT32", x)])
            sub = _inference_stats(engine, "batched")
            assert sub["success"]["count"] == 1
            assert sub["queue"]["count"] >= 1
        finally:
            engine.close()


# -- device residency (jax) --------------------------------------------------


class TestDeviceResidency:
    def test_jax_consumer_receives_device_array(self):
        """Between two jax-backed steps the intermediate is handed off as a
        jax.Array — no np.asarray host hop (asserted inside the consumer)."""
        import jax
        import jax.numpy as jnp

        received = []

        def producer_fn(inputs, params, ctx):
            return {"OUT": jnp.asarray(np.asarray(inputs["IN"])) * 2}

        def consumer_fn(inputs, params, ctx):
            received.append(type(inputs["IN"]))
            assert isinstance(inputs["IN"], jax.Array), (
                "device intermediate was materialized to host"
            )
            return {"OUT": inputs["IN"] + 1}

        producer = Model(
            "producer",
            inputs=[TensorSpec("IN", "FP32", [-1])],
            outputs=[TensorSpec("OUT", "FP32", [-1])],
            fn=producer_fn, platform="jax", backend="jax",
        )
        consumer = Model(
            "consumer",
            inputs=[TensorSpec("IN", "FP32", [-1])],
            outputs=[TensorSpec("OUT", "FP32", [-1])],
            fn=consumer_fn, platform="jax", backend="jax",
        )
        engine = InferenceEngine([producer, consumer])
        try:
            engine.add_model(_ensemble("e", [
                _step("producer", {"IN": "IN"}, {"OUT": "mid"}),
                _step("consumer", {"IN": "mid"}, {"OUT": "OUT"}),
            ], in_dtype="FP32"))
            x = np.arange(4, dtype=np.float32)
            out = _infer(engine, "e", [("IN", "FP32", x)])
            np.testing.assert_allclose(out["OUT"], x * 2 + 1)
            assert received, "consumer never ran"
            assert engine.metrics.get(
                "ctpu_ensemble_device_handoffs_total", {"model": "e"}
            ) == 1
            assert not engine.metrics.get(
                "ctpu_ensemble_host_hops_total", {"model": "e"}
            )
        finally:
            engine.close()

    def test_python_consumer_gets_host_array_and_hop_is_counted(self):
        import jax.numpy as jnp

        def producer_fn(inputs, params, ctx):
            return {"OUT": jnp.asarray(np.asarray(inputs["IN"]))}

        def consumer_fn(inputs, params, ctx):
            assert isinstance(inputs["IN"], np.ndarray)
            return {"OUT": inputs["IN"]}

        producer = Model(
            "producer",
            inputs=[TensorSpec("IN", "FP32", [-1])],
            outputs=[TensorSpec("OUT", "FP32", [-1])],
            fn=producer_fn, platform="jax", backend="jax",
        )
        consumer = Model(
            "pyconsumer",
            inputs=[TensorSpec("IN", "FP32", [-1])],
            outputs=[TensorSpec("OUT", "FP32", [-1])],
            fn=consumer_fn,  # python platform: host arrays expected
        )
        engine = InferenceEngine([producer, consumer])
        try:
            engine.add_model(_ensemble("e", [
                _step("producer", {"IN": "IN"}, {"OUT": "mid"}),
                _step("pyconsumer", {"IN": "mid"}, {"OUT": "OUT"}),
            ], in_dtype="FP32"))
            _infer(engine, "e", [("IN", "FP32",
                                  np.arange(4, dtype=np.float32))])
            assert engine.metrics.get(
                "ctpu_ensemble_host_hops_total", {"model": "e"}
            ) == 1
        finally:
            engine.close()

    def test_vision_pipeline_zero_host_hops(self):
        """The builtin tiny vision pipeline: preprocess -> backbone ->
        postprocess with every intermediate device-resident."""
        from client_tpu.serve.models.vision import vision_pipeline_models

        engine = InferenceEngine(vision_pipeline_models())
        try:
            img = np.random.default_rng(0).integers(
                0, 255, (2, 32, 32, 3), dtype=np.uint8
            )
            out = _infer(engine, "vision_pipeline",
                         [("IMAGE", "UINT8", img)])
            scores = out["SCORES"]
            assert scores.shape == (2, 16)
            np.testing.assert_allclose(scores.sum(axis=1), 1.0, atol=1e-5)
            m = engine.metrics
            assert not m.get("ctpu_ensemble_host_hops_total",
                             {"model": "vision_pipeline"})
            assert m.get("ctpu_ensemble_device_handoffs_total",
                         {"model": "vision_pipeline"}) == 2
            # per-composing stats reconcile against the ensemble total
            ens = _inference_stats(engine, "vision_pipeline")
            total = sum(
                _inference_stats(engine, n)["success"]["ns"]
                for n in ("vision_preprocess", "vision_backbone",
                          "vision_postprocess")
            )
            assert ens["compute_infer"]["ns"] == total
        finally:
            engine.close()
