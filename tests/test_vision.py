"""Vision servables: resnet50 structure, FLOP accounting, forward health.

The resnet50 model is BASELINE.md config 3's subject; its flops_per_item
feeds the bench's MFU figures, so the analytic count is cross-checked against
XLA's own cost analysis here.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from client_tpu.serve.models import vision


def test_resnet50_flops_and_params():
    # torchvision resnet50: 4.09 GMACs (= ~8.2e9 FLOPs at 2*MAC), 25.56M params
    flops = vision.resnet50_flops_per_image()
    assert 8.0e9 < flops < 8.4e9
    params = vision._init_resnet_params(jax.random.PRNGKey(0))
    n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
    assert 25.0e6 < n < 26.0e6


def test_resnet50_forward_shape_and_finite():
    params = vision._init_resnet_params(jax.random.PRNGKey(0))
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((2, 3, 64, 64)),
        jnp.float32,
    )
    out = jax.jit(vision._resnet_forward)(params, x)
    assert out.shape == (2, 1000)
    assert out.dtype == jnp.float32
    assert np.isfinite(np.asarray(out)).all()


def test_resnet50_flops_match_xla_cost_analysis():
    """The analytic 2*MAC count must track what XLA actually schedules.
    XLA's own figure moves with compile options (padding accounting,
    elementwise fusion): observed 0.95x-1.10x of analytic across backends —
    the test pins a 0.85x-1.20x band, which still catches any structural
    miscount (a missing stage or doubled block is a >=25% shift)."""
    params = vision._init_resnet_params(jax.random.PRNGKey(0))
    x = jnp.zeros((1, 3, 64, 64), jnp.float32)
    compiled = jax.jit(vision._resnet_forward).lower(params, x).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    xla_flops = float(ca.get("flops", 0.0)) if ca else 0.0
    if not xla_flops:
        pytest.skip("backend exposes no cost analysis")
    analytic = vision.resnet50_flops_per_image(64)
    assert 0.85 <= xla_flops / analytic <= 1.20


def test_resnet50_model_config_carries_flops():
    m = vision.resnet50_model()
    cfg = m.config()
    got = int(cfg["parameters"]["flops_per_item"]["string_value"])
    assert got == vision.resnet50_flops_per_image()
    assert m.flops_per_item == got


def test_cnn_flops_value():
    # the ~0.37 GFLOP figure the round-4 verdict derived independently
    assert 3.6e8 < vision.cnn_flops_per_image() < 3.8e8
