"""Server /metrics endpoint, perf MetricsManager, multi-rank rendezvous."""

import queue
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import client_tpu.http as httpclient
from client_tpu.perf.metrics_manager import MetricsManager, parse_prometheus
from client_tpu.perf.rendezvous import Rendezvous
from client_tpu.serve import Server
from client_tpu.utils import InferenceServerException


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestMetricsEndpoint:
    @pytest.fixture(scope="class")
    def server(self):
        with Server(http_port=0) as s:
            yield s

    def test_scrape_and_counters_advance(self, server):
        url = f"http://{server.http_address}/metrics"
        before = parse_prometheus(
            urllib.request.urlopen(url).read().decode()
        )
        with httpclient.InferenceServerClient(server.http_address) as c:
            inputs = [
                httpclient.InferInput("INPUT0", [1, 16], "INT32"),
                httpclient.InferInput("INPUT1", [1, 16], "INT32"),
            ]
            inputs[0].set_data_from_numpy(np.ones((1, 16), np.int32))
            inputs[1].set_data_from_numpy(np.ones((1, 16), np.int32))
            for _ in range(3):
                c.infer("simple", inputs)
        after = parse_prometheus(
            urllib.request.urlopen(url).read().decode()
        )

        def success_count(snap):
            return sum(
                v for labels, v in snap.get("ctpu_inference_request_success", [])
                if 'model="simple"' in labels
            )

        assert success_count(after) - success_count(before) == 3
        assert "ctpu_scrape_timestamp_seconds" in after

    def test_metrics_manager_collects(self, server):
        mm = MetricsManager(
            f"http://{server.http_address}/metrics", interval_s=0.05
        ).start()
        import time

        time.sleep(0.3)
        mm.stop()
        snaps = mm.swap_snapshots()
        assert len(snaps) >= 2
        assert all("ctpu_inference_request_success" in s for s in snaps)

    def test_summarize_gauges(self):
        snaps = [
            {"ctpu_tpu_memory_used_bytes": [("{}", 100.0)]},
            {"ctpu_tpu_memory_used_bytes": [("{}", 300.0)]},
        ]
        agg = MetricsManager.summarize(snaps)
        assert agg["ctpu_tpu_memory_used_bytes"] == {"avg": 200.0, "max": 300.0}

    def test_local_device_fallback_fills_blind_spot(self, server):
        """A server exposing no TPU gauges (any third-party KServe server)
        still yields device telemetry when the perf process is colocated
        with the chip: scrape() merges the local PJRT snapshot for gauges
        the server response lacks — server-reported values win."""
        mm = MetricsManager(
            f"http://{server.http_address}/metrics",
            include_local_devices=True,
        )
        mm._local_snapshot = lambda: {
            "ctpu_tpu_memory_used_bytes": [('{device="0",source="local"}', 7.0)],
            "ctpu_inference_request_success": [('{source="local"}', -1.0)],
        }
        snap = mm.scrape()
        # blind-spot gauge filled from the local runtime ...
        assert snap["ctpu_tpu_memory_used_bytes"] == [
            ('{device="0",source="local"}', 7.0)
        ]
        # ... but a gauge the server DID report is untouched
        assert all(v >= 0 for _, v in snap["ctpu_inference_request_success"])

    def test_local_device_snapshot_shape(self):
        """local_device_snapshot returns prometheus-shaped entries (or {} on
        runtimes exposing no memory_stats, e.g. the CPU test platform)."""
        from client_tpu.perf.metrics_manager import local_device_snapshot

        snap = local_device_snapshot()
        for name, entries in snap.items():
            assert name.startswith("ctpu_tpu_memory_")
            for labels, value in entries:
                assert labels.startswith("{") and value >= 0

    def test_device_utilization_probe_samples(self):
        """The probe times a real jitted kernel on the local device: idle
        baseline positive, samples well-formed (delay >= 0, busy in {0,1})."""
        from client_tpu.perf.metrics_manager import DeviceUtilizationProbe

        probe = DeviceUtilizationProbe()
        assert probe.baseline_s > 0
        for _ in range(5):
            delay_us, busy = probe.sample()
            assert delay_us >= 0.0
            assert busy in (0.0, 1.0)

    def test_probe_gauges_flow_through_scrape_and_summary(self, server):
        """Probe samples ride every scrape — including the no-/metrics
        fallback path — and summarize() emits ctpu_probe_utilization_pct
        (busy percent) without trusting anything the server reported."""
        from client_tpu.perf.metrics_manager import DeviceUtilizationProbe

        probe = DeviceUtilizationProbe()
        mm = MetricsManager(
            f"http://{server.http_address}/metrics",
            utilization_probe=probe,
        )
        snap = mm.scrape()
        assert "ctpu_probe_queue_delay_us" in snap
        assert "ctpu_probe_busy" in snap
        assert 'source="probe"' in snap["ctpu_probe_busy"][0][0]

        # server with no /metrics endpoint at all: probe still flows
        mm_dead = MetricsManager(
            "http://127.0.0.1:9/metrics", timeout_s=0.2,
            utilization_probe=probe,
        )
        fallback = mm_dead.scrape()
        assert "ctpu_probe_busy" in fallback
        assert mm_dead.scrape_errors == 1

        agg = MetricsManager.summarize([snap, fallback])
        assert "ctpu_probe_utilization_pct" in agg
        assert 0.0 <= agg["ctpu_probe_utilization_pct"]["avg"] <= 100.0
        assert "ctpu_probe_queue_delay_us" in agg


class TestRendezvous:
    def test_all_gather_and_consensus(self):
        addr = f"127.0.0.1:{_free_port()}"
        world = 3
        results = [None] * world
        consensus = [None] * world

        def run(rank):
            rv = Rendezvous(rank, world, addr)
            rv.barrier()
            results[rank] = rv.all_gather(f"rank{rank}")
            consensus[rank] = rv.all_ranks_stable(rank != 1)
            rv.close()

        threads = [threading.Thread(target=run, args=(r,)) for r in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        expected = ["rank0", "rank1", "rank2"]
        assert all(r == expected for r in results)
        assert consensus == [False, False, False]  # rank 1 was unstable

    def test_single_rank_is_local(self):
        rv = Rendezvous(0, 1)
        assert rv.all_gather("x") == ["x"]
        assert rv.all_ranks_stable(True)
        rv.close()

    def test_bad_rank_rejected(self):
        with pytest.raises(InferenceServerException):
            Rendezvous(5, 2)

    def test_duplicate_and_out_of_range_hellos_rejected(self):
        """r1 advisor: rank 0 must reject duplicate / out-of-range / garbage
        hellos instead of silently evicting a legitimate peer or crashing."""
        addr = f"127.0.0.1:{_free_port()}"
        world = 3
        gathered = [None] * world
        rvs = {}

        t0 = threading.Thread(
            target=lambda: rvs.setdefault(
                0, Rendezvous(0, world, addr, connect_timeout_s=30.0)
            )
        )
        t0.start()
        time.sleep(0.2)
        # garbage on the wire: connect-and-close, then a non-frame byte —
        # neither may abort the rendezvous
        port = int(addr.rsplit(":", 1)[1])
        with socket.create_connection(("127.0.0.1", port), timeout=10):
            pass
        with socket.create_connection(("127.0.0.1", port), timeout=10) as gs:
            gs.sendall(b"\x01")
        # out-of-range hello on the wire (bypasses the ctor range check)
        assert _raw_hello(addr, rank=7) == "rejected"
        # real rank 1 joins (ctor returns once its hello is ack'd) ...
        rvs[1] = Rendezvous(1, world, addr, connect_timeout_s=30.0)
        # ... so this duplicate hello must hit the already-joined branch
        assert _raw_hello(addr, rank=1) == "rejected"
        # the final rank completes the world
        rvs[2] = Rendezvous(2, world, addr, connect_timeout_s=30.0)
        t0.join(timeout=30)
        assert 0 in rvs

        def gather(rank):
            gathered[rank] = rvs[rank].all_gather(f"r{rank}")

        threads = [
            threading.Thread(target=gather, args=(r,)) for r in range(world)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        for rv in rvs.values():
            rv.close()
        expected = ["r0", "r1", "r2"]
        assert gathered == [expected] * world


def _raw_hello(addr, rank):
    """Send a hello frame with an arbitrary rank; how rank 0 answered."""
    import json as _json
    import struct

    host, _, port = addr.rpartition(":")
    with socket.create_connection((host, int(port)), timeout=10) as s:
        payload = _json.dumps({"rank": rank}).encode()
        s.sendall(struct.pack("<I", len(payload)) + payload)
        s.settimeout(10)
        hdr = s.recv(4)
        if len(hdr) < 4:
            return "closed"
        (n,) = struct.unpack("<I", hdr)
        resp = _json.loads(s.recv(n).decode())
        return "rejected" if "error" in resp else "accepted"


class TestMultiRankCli:
    def test_two_rank_hermetic_run(self):
        port = _free_port()
        args = [
            sys.executable, "-m", "client_tpu.perf",
            "-m", "simple", "--hermetic",
            "--concurrency-range", "1",
            "--measurement-interval", "100",
            "--max-trials", "3", "-s", "90",
            "--world-size", "2",
            "--rendezvous-addr", f"127.0.0.1:{port}",
        ]
        procs = [
            subprocess.Popen(
                args + ["--rank", str(rank)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
            for rank in range(2)
        ]
        outs = [p.communicate(timeout=180)[0] for p in procs]
        for rank, (proc, out) in enumerate(zip(procs, outs)):
            assert proc.returncode == 0, f"rank {rank}:\n{out}"
        assert "Aggregate across ranks:" in outs[0]
        assert "total:" in outs[0]
        assert "Aggregate" not in outs[1]  # only rank 0 prints the rollup
