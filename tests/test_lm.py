"""Continuous-batching LM engine (client_tpu/serve/lm): the four-pillar
acceptance — bounded prefill compiles (bucketing), chunked prefill
interleaved with decode (head-of-line fix), paged KV accounting, lane
autoscaling + tenant lane quotas — plus per-lane sampling determinism,
the prefix-cache/preemption subsystem (refcounted block sharing,
LRU eviction under pressure, priority swap with byte-exact resume) and
the >=128-stream churn soak (slow tier, `make soak`)."""

import queue
import threading
import time

import numpy as np
import pytest

import jax

from client_tpu.serve.lm import KvBlockPool, LmEngine, PrefixCache
from client_tpu.serve.lm.policy import (
    LaneAutoscaler,
    bucket_for,
    chunk_plan,
    geometric_buckets,
    pad_prompt,
)
from client_tpu.serve.metrics import Registry
from client_tpu.serve.models import transformer as tfm

CLOSE = LmEngine.CLOSE

CFG = tfm.TransformerConfig(
    vocab_size=128,
    d_model=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    d_ff=128,
    max_seq=96,
    dtype="float32",
)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(jax.random.PRNGKey(0), CFG)


def _serial(params, prompt, n):
    return list(tfm.generate(params, CFG, prompt, n, readback_depth=0))


def _collect(q, timeout=120):
    out = []
    while True:
        tok = q.get(timeout=timeout)
        if tok is CLOSE:
            return out
        out.append(tok)


# -- policy units ----------------------------------------------------------

def test_geometric_buckets_and_lookup():
    assert geometric_buckets(16, 64) == (16, 32, 64)
    assert geometric_buckets(16, 48) == (16, 32, 48)
    assert geometric_buckets(64, 64) == (64,)
    assert bucket_for(1, (16, 32)) == 16
    assert bucket_for(17, (16, 32)) == 32
    assert bucket_for(999, (16, 32)) == 32  # multi-chunk prompts


def test_chunk_plan_widths_are_bucket_members():
    buckets = geometric_buckets(4, 16)
    for n in range(1, 60):
        plan = chunk_plan(n, buckets)
        assert all(width in buckets for _, width in plan), (n, plan)
        covered = sum(width for _, width in plan)
        assert covered >= n
        # starts tile the prompt contiguously
        assert [s for s, _ in plan] == [
            i * buckets[-1] for i in range(len(plan))
        ] or len(plan) == 1


def test_pad_prompt_rejects_overflow():
    with pytest.raises(ValueError):
        pad_prompt(np.zeros((1, 8), np.int32), 4)


def test_lane_autoscaler_hysteresis():
    sc = LaneAutoscaler((2, 4, 8), up_after=2, down_after=3)
    assert sc.n_lanes == 2
    assert not sc.note_starved()
    assert sc.note_starved()  # 2 consecutive -> step up
    assert sc.n_lanes == 4
    # ok passes with active work below the lower count start the idle run
    for _ in range(2):
        assert not sc.note_ok(False, 0)
    assert sc.note_ok(False, 0)  # 3rd idle pass -> step down
    assert sc.n_lanes == 2
    # pending work resets the idle run
    sc2 = LaneAutoscaler((2, 4), up_after=1, down_after=2)
    sc2.note_starved()
    assert sc2.n_lanes == 4
    sc2.note_ok(False, -1)
    sc2.note_ok(True, -1)  # pending: reset
    sc2.note_ok(False, -1)
    assert sc2.n_lanes == 4


# -- paged KV pool ---------------------------------------------------------

def test_kv_pool_alloc_release_and_gauges():
    reg = Registry()
    pool = KvBlockPool(CFG, n_blocks=8, block_size=16, registry=reg)
    assert pool.blocks_for(1) == 1
    assert pool.blocks_for(16) == 1
    assert pool.blocks_for(17) == 2
    a = pool.alloc(3)
    assert len(a) == 3 and KvBlockPool.TRASH not in a
    assert pool.used_blocks == 3 and pool.free_blocks == 5
    assert reg.get("ctpu_lm_kv_blocks_used") == 3
    assert reg.get("ctpu_lm_kv_blocks_free") == 5
    assert pool.alloc(6) is None  # over-ask: backpressure, not partial
    pool.release(a)
    assert pool.free_blocks == 8
    assert reg.get("ctpu_lm_kv_blocks_used") == 0


# -- engine: correctness through the paged/chunked path --------------------

def test_streams_match_serial_including_multi_chunk_prefill(params):
    eng = LmEngine(params, CFG, max_slots=4, lane_counts=(4,),
                   block_size=8, prefill_chunk=16, min_bucket=4)
    try:
        prompts = [[1, 2, 3], [7, 9], list(range(1, 41)), [11, 3, 2, 8]]
        lengths = [6, 9, 5, 7]
        qs = [eng.submit(p, n)[0] for p, n in zip(prompts, lengths)]
        got = [_collect(q) for q in qs]
        for p, n, toks in zip(prompts, lengths, got):
            assert toks == _serial(params, p, n), (p, n)
    finally:
        eng.close()


def test_bounded_prefill_compile_over_distinct_lengths(params):
    """THE bounded-compile proof: many distinct prompt lengths compile at
    most len(buckets) prefill executables (jax jit cache-size counter);
    the unbucketed prototype compiled one per distinct length."""
    eng = LmEngine(params, CFG, max_slots=2, lane_counts=(2,),
                   block_size=8, prefill_chunk=16, min_bucket=4)
    try:
        lengths = list(range(1, 15)) + [20, 27, 40]  # 17 distinct lengths
        for n in lengths:
            q, _ = eng.submit(list(range(1, n + 1)), 2)
            _collect(q)
        compiled = eng.prefill_executables()
        assert compiled is not None
        assert compiled <= len(eng.buckets), (compiled, eng.buckets)
        assert eng.decode_executables() <= len(eng.lane_counts)
    finally:
        eng.close()


def test_chunked_prefill_interleaves_with_decode(params):
    """THE head-of-line proof: with active token streams, admitting a
    novel multi-chunk prompt keeps decode ticking BETWEEN its prefill
    chunks (trace-timestamp assertion) — the prototype ran the whole
    prefill (plus its XLA compile) as one stall."""
    eng = LmEngine(params, CFG, max_slots=4, lane_counts=(4,),
                   block_size=8, prefill_chunk=16, min_bucket=4)
    try:
        s1, _ = eng.submit([1, 2, 3], 60)
        s2, _ = eng.submit([9, 4], 60)
        # both streams demonstrably live before the long prompt arrives
        assert s1.get(timeout=60) is not CLOSE
        assert s2.get(timeout=60) is not CLOSE
        t_submit = time.monotonic()
        long_q, _ = eng.submit(list(range(1, 49)), 4)  # 48 tok = 3 chunks
        assert _collect(long_q) == _serial(params, list(range(1, 49)), 4)
        _collect(s1)
        _collect(s2)
        trace = eng.tick_trace()
        chunks = [r for r in trace
                  if r["kind"] == "prefill_chunk" and r["t0"] >= t_submit]
        assert len(chunks) == 3, chunks  # 48 tokens / 16-wide chunks
        decodes = [r for r in trace if r["kind"] == "decode"]
        # structural interleave: >=1 decode tick between consecutive chunks
        for a, b in zip(chunks, chunks[1:]):
            between = [r for r in decodes if a["t1"] <= r["t0"] <= b["t0"]]
            assert between, (a, b)
        # numeric jitter bound: during the prefill window, decode
        # tick-to-tick gaps stay within one chunk budget (chunk + tick +
        # scheduling slack), never the whole-prefill stall
        window = [r for r in decodes
                  if chunks[0]["t0"] <= r["t0"] <= chunks[-1]["t1"]]
        budget = (
            max(r["t1"] - r["t0"] for r in chunks)
            + max(r["t1"] - r["t0"] for r in decodes)
            + 0.5
        )
        for a, b in zip(window, window[1:]):
            assert b["t0"] - a["t0"] <= budget, (a, b, budget)
    finally:
        eng.close()


def test_lane_autoscaling_up_on_queue_depth_then_down(params):
    eng = LmEngine(params, CFG, max_slots=4, lane_counts=(1, 2, 4),
                   block_size=8, prefill_chunk=16, min_bucket=4,
                   scale_up_after=2, scale_down_after=3)
    try:
        qs = [eng.submit([i + 1, i + 2], 25)[0] for i in range(4)]
        got = [_collect(q) for q in qs]
        for i, toks in enumerate(got):
            assert toks == _serial(params, [i + 1, i + 2], 25)
        # sustained queue depth stepped the lane count up to the max
        assert max(r["n_lanes"] for r in eng.tick_trace()) == 4
        # drained + idle: hysteresis steps back down (idle passes tick at
        # the scheduler's wait timeout)
        deadline = time.monotonic() + 10
        while eng._scaler.n_lanes != 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert eng._scaler.n_lanes == 1
    finally:
        eng.close()


def test_kv_pool_exhaustion_backpressures_admission(params):
    """A request that cannot reserve its blocks queues until a completion
    frees them — admission backpressure, not an error and not a partial
    reservation."""
    reg = Registry()
    # pool sized to hold exactly ONE 40-token reservation (3 blocks of 16
    # + the engine floors n_blocks at table_width=6)
    eng = LmEngine(params, CFG, max_slots=2, lane_counts=(2,),
                   block_size=16, pool_tokens=96, prefill_chunk=16,
                   min_bucket=4, registry=reg)
    try:
        q1, _ = eng.submit([1, 2, 3, 4], 60)  # 64 tok -> 4 blocks of 6
        assert q1.get(timeout=60) is not CLOSE
        used_during = reg.get("ctpu_lm_kv_blocks_used")
        assert used_during == 4
        q2, _ = eng.submit([5, 6], 40)  # needs 3 blocks; only 2 free
        got2 = _collect(q2)  # completes AFTER q1 frees its reservation
        assert got2 == _serial(params, [5, 6], 40)
        _collect(q1)
        assert reg.get("ctpu_lm_kv_blocks_used") == 0  # all freed
    finally:
        eng.close()


def test_tenant_lane_quota_admission_policy(params):
    """The quota decision itself, driven deterministically against a
    frozen lane state (the scheduler thread starts lazily, so the locked
    helpers can be exercised race-free): while tenant B waits, tenant A
    at ceil(share * lanes) held lanes is SKIPPED and B's handle is
    picked even though A is first in round-robin order; once B's queue
    drains the quota lifts (work-conserving)."""
    from collections import deque

    from client_tpu.serve.lm.engine import _Handle

    eng = LmEngine(params, CFG, max_slots=4, lane_counts=(4,),
                   block_size=8, prefill_chunk=16, min_bucket=4,
                   tenant_lane_share=0.5)

    def handle(tenant):
        return _Handle(np.zeros((1, 2), np.int32), 4, queue.Queue(),
                       tenant, 0.0, 0, 0)

    ha, hb = handle("a"), handle("b")
    with eng._cv:
        for i in range(2):  # a already holds ceil(0.5 * 4) = 2 lanes
            eng._lanes[i].active = True
            eng._lanes[i].tenant = "a"
        eng._pending["a"] = deque([ha])
        eng._pending["b"] = deque([hb])
        assert eng._tenant_quota_locked("a", 4, others_pending=True) == 2
        assert eng._tenant_quota_locked("a", 4, others_pending=False) == 4
        picked = eng._pick_pending_locked(4)
        assert picked is hb  # a over quota while b waits
        # b's backlog drained: a's quota lifts and its handle is admissible
        assert eng._pick_pending_locked(4) is ha
        for i in range(2):
            eng._lanes[i].active = False


def test_tenant_lane_quota_bounds_flood_integration(params):
    """A tenant flooding the engine with long streams cannot starve a
    late-arriving tenant: B's short stream completes before A's flood
    drains (A is quota-capped to 1 of 2 lanes whenever B waits)."""
    eng = LmEngine(params, CFG, max_slots=2, lane_counts=(2,),
                   block_size=8, prefill_chunk=16, min_bucket=4,
                   tenant_lane_share=0.5)
    try:
        flood = [eng.submit([i + 1, i + 2], 40, tenant="a")[0]
                 for i in range(4)]
        qb, _ = eng.submit([9, 9], 5, tenant="b")
        done = {}

        def drain(name, q):
            _collect(q)
            done[name] = time.monotonic()

        threads = [
            threading.Thread(target=drain, args=(f"a{i}", q), daemon=True)
            for i, q in enumerate(flood)
        ] + [threading.Thread(target=drain, args=("b", qb), daemon=True)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive()
        assert done["b"] < max(v for k, v in done.items() if k != "b")
    finally:
        eng.close()


def test_uncontended_tenant_uses_all_lanes(params):
    """The quota binds only while another tenant waits: a lone tenant's
    two streams run on both lanes concurrently (work-conserving)."""
    eng = LmEngine(params, CFG, max_slots=2, lane_counts=(2,),
                   block_size=8, prefill_chunk=16, min_bucket=4,
                   tenant_lane_share=0.5)
    try:
        q1, _ = eng.submit([1, 2], 20, tenant="a")
        q2, _ = eng.submit([3, 4], 20, tenant="a")
        assert _collect(q1) == _serial(params, [1, 2], 20)
        assert _collect(q2) == _serial(params, [3, 4], 20)
        # both lanes streamed at once at some point
        assert any(
            len(r["lanes"]) == 2 for r in eng.tick_trace()
            if r["kind"] == "decode"
        )
    finally:
        eng.close()


def test_pending_map_evicts_drained_tenants(params):
    """Tenant ids are client-minted (x-tenant-id): a drained tenant's
    _pending entry must be evicted, or a rotating-id flood grows the map
    (and every scheduler pass's scan) without bound."""
    eng = LmEngine(params, CFG, max_slots=2, lane_counts=(2,),
                   block_size=8, prefill_chunk=16, min_bucket=4)
    try:
        qs = [eng.submit([i + 1, 2], 3, tenant=f"t{i}")[0]
              for i in range(6)]
        for q in qs:
            _collect(q)
        # cancel-from-pending also evicts: both lanes held first, so the
        # cancelled handle is still queued when cancel() lands
        busy1, _ = eng.submit([5, 6], 30, tenant="busy")
        busy2, _ = eng.submit([6, 7], 30, tenant="busy")
        assert busy1.get(timeout=60) is not CLOSE
        assert busy2.get(timeout=60) is not CLOSE
        q7, h7 = eng.submit([1, 2], 3, tenant="t-cancel")
        eng.cancel(h7)
        assert _collect(q7) == []
        _collect(busy1)
        _collect(busy2)
        with eng._cv:
            assert not eng._pending, dict(eng._pending)
    finally:
        eng.close()


# -- per-lane sampling -----------------------------------------------------

def test_sampling_seed_deterministic_and_varied(params):
    eng = LmEngine(params, CFG, max_slots=2, lane_counts=(2,),
                   block_size=8, prefill_chunk=16, min_bucket=4)
    try:
        kw = dict(temperature=0.8, top_k=8)
        s1 = _collect(eng.submit([1, 2, 3], 10, seed=42, **kw)[0])
        s2 = _collect(eng.submit([1, 2, 3], 10, seed=42, **kw)[0])
        s3 = _collect(eng.submit([1, 2, 3], 10, seed=7, **kw)[0])
        greedy = _collect(eng.submit([1, 2, 3], 10)[0])
        assert s1 == s2  # same seed, same lane-RNG path
        assert s1 != s3 or s1 != greedy  # sampling actually samples
        assert greedy == _serial(params, [1, 2, 3], 10)
    finally:
        eng.close()


def test_mixed_greedy_and_sampled_lanes_share_one_tick(params):
    """A greedy lane must decode EXACTLY the serial stream while a
    sampled lane shares its batched tick (temperature 0 takes the
    on-device argmax; the executable count does not grow)."""
    eng = LmEngine(params, CFG, max_slots=2, lane_counts=(2,),
                   block_size=8, prefill_chunk=16, min_bucket=4)
    try:
        qg, _ = eng.submit([1, 2, 3], 15)
        qs, _ = eng.submit([4, 5], 15, temperature=1.2, top_k=4, seed=9)
        got_g = _collect(qg)
        got_s = _collect(qs)
        assert got_g == _serial(params, [1, 2, 3], 15)
        assert len(got_s) == 15
        assert eng.decode_executables() <= len(eng.lane_counts)
    finally:
        eng.close()


def test_top_k_above_static_cap_rejected(params):
    """The jitted tick's per-lane top-k filter has a static width: a k
    above it must 400, not silently sample a narrower distribution than
    the client asked for."""
    from client_tpu.serve.lm.engine import _TOPK_CAP
    from client_tpu.serve.models.continuous import BatchedLmRunner
    from client_tpu.utils import InferenceServerException

    runner = BatchedLmRunner(params, CFG, max_slots=1, lane_counts=(1,),
                             block_size=8, prefill_chunk=16, min_bucket=4)
    try:
        with pytest.raises(InferenceServerException) as exc:
            next(runner.stream([1, 2], 4, temperature=1.0,
                               top_k=_TOPK_CAP + 1))
        assert exc.value.status() == "400"
        # at the cap is fine
        assert len(list(
            runner.stream([1, 2], 4, temperature=1.0, top_k=_TOPK_CAP)
        )) == 4
    finally:
        runner.scheduler.close()


def test_top_k_restricts_support(params):
    """top_k=1 IS greedy (the filtered distribution has one atom), at
    any temperature — the tightest sampling-correctness check that needs
    no distribution test."""
    eng = LmEngine(params, CFG, max_slots=1, lane_counts=(1,),
                   block_size=8, prefill_chunk=16, min_bucket=4)
    try:
        got = _collect(
            eng.submit([1, 2, 3], 12, temperature=5.0, top_k=1, seed=3)[0]
        )
        assert got == _serial(params, [1, 2, 3], 12)
    finally:
        eng.close()


# -- prefix cache: refcounted block sharing --------------------------------

def test_kv_pool_refcounts_share_and_release():
    pool = KvBlockPool(CFG, n_blocks=8, block_size=16)
    blocks = pool.alloc(2)
    assert [pool.ref_count(b) for b in blocks] == [1, 1]
    pool.retain(blocks)  # a second holder adopts both
    assert [pool.ref_count(b) for b in blocks] == [2, 2]
    pool.release(blocks)  # first holder exits: blocks stay live
    assert pool.free_blocks == 6
    assert [pool.ref_count(b) for b in blocks] == [1, 1]
    pool.release(blocks)  # last holder exits: blocks free
    assert pool.free_blocks == 8
    assert pool.ref_counts() == {}


def test_prefix_cache_match_adopt_give_back_evict():
    pool = KvBlockPool(CFG, n_blocks=8, block_size=4)
    cache = PrefixCache(pool)
    prompt = np.arange(1, 13, dtype=np.int32)  # 3 full blocks of 4
    blocks = pool.alloc(3)
    # retirement inserts the chain: the holder's references TRANSFER
    cache.give_back(prompt, 3, blocks)
    assert cache.cached_blocks == 3
    assert pool.used_blocks == 3  # cache keeps them live
    # a matching prompt adopts the chain by reference
    matched, nodes = cache.match(prompt, 3)
    assert matched == blocks
    cache.adopt(nodes)
    assert [pool.ref_count(b) for b in blocks] == [2, 2, 2]
    # pinned blocks are NOT evictable; nothing can be freed
    assert cache.evict(3) == 0
    pool.release(matched)  # adopter retires (its prefix re-inserts as hits)
    # a diverging prompt matches only the shared lead
    other = prompt.copy()
    other[4:] = 99
    matched2, nodes2 = cache.match(other, 3)
    assert matched2 == blocks[:1]
    # now unpinned: eviction frees leaves first, LRU order
    assert cache.evict(2) == 2
    assert cache.cached_blocks == 1
    assert pool.used_blocks == 1
    cache.clear()
    assert pool.used_blocks == 0


def test_prefix_cache_min_blocks_hint():
    pool = KvBlockPool(CFG, n_blocks=8, block_size=4)
    cache = PrefixCache(pool, min_prefix_blocks=2)
    prompt = np.arange(1, 9, dtype=np.int32)  # 2 full blocks
    cache.give_back(prompt, 1, pool.alloc(2))  # only 1 block cached
    matched, nodes = cache.match(prompt, 2)
    assert matched == [] and nodes == []  # below the hint: not worth it
    cache.clear()


def test_prefix_adoption_shares_blocks_and_skips_prefill(params):
    """The prefill-savings acceptance at engine level: prompts sharing a
    long prefix decode byte-exact vs serial while the second+ admissions
    adopt the prefix blocks (hits counted, prefill compute reduced, the
    shared blocks' refcounts prove by-reference sharing)."""
    reg = Registry()
    eng = LmEngine(params, CFG, max_slots=2, lane_counts=(2,),
                   block_size=8, prefill_chunk=16, min_bucket=4,
                   registry=reg)
    shared = list(range(1, 25))  # 3 full blocks of 8
    prompts = [shared + [30 + i] for i in range(3)]
    try:
        cold = _collect(eng.submit(prompts[0], 5)[0])
        assert cold == _serial(params, prompts[0], 5)
        computed_cold = reg.get("ctpu_lm_prefill_tokens_total")
        for p in prompts[1:]:
            assert _collect(eng.submit(p, 5)[0]) == _serial(params, p, 5)
        stats = eng.prefix_stats()
        assert stats["hits"] == 6  # 3 blocks adopted by each warm prompt
        assert stats["cached_blocks"] >= 3
        # each warm prompt prefilled only its 1-token tail (padded to the
        # 4-wide min bucket): way below the 25-token cold prefill
        computed_warm = (
            reg.get("ctpu_lm_prefill_tokens_total") - computed_cold
        )
        assert computed_warm == 2  # 1 real token each, pad excluded
        assert reg.get("ctpu_lm_prefill_tokens_saved_total") == 48
    finally:
        eng.close()
    assert eng.kv.used_blocks == 0, eng.kv.ref_counts()


def test_prefix_cache_disabled_knob(params):
    eng = LmEngine(params, CFG, max_slots=2, lane_counts=(2,),
                   block_size=8, prefill_chunk=16, min_bucket=4,
                   prefix_cache=False)
    shared = list(range(1, 25))
    try:
        assert _collect(eng.submit(shared + [30], 4)[0]) == \
            _serial(params, shared + [30], 4)
        assert eng.prefix is None
        assert eng.prefix_stats() == {}
    finally:
        eng.close()
    assert eng.kv.used_blocks == 0


def test_prefix_eviction_under_pool_pressure(params):
    """Warm cache blocks yield to admissions: a pool too small to hold
    the cache AND a new reservation evicts LRU cached blocks instead of
    backpressuring the request forever."""
    reg = Registry()
    # 6 blocks of 16 = 96 tokens: one 40-token stream reserves 3 blocks
    eng = LmEngine(params, CFG, max_slots=2, lane_counts=(2,),
                   block_size=16, pool_tokens=96, prefill_chunk=16,
                   min_bucket=4, registry=reg)
    try:
        p1 = list(range(1, 33))  # 2 full blocks cached at retirement
        assert _collect(eng.submit(p1, 8, seed=1)[0]) == \
            _serial(params, p1, 8)
        assert eng.prefix_stats()["cached_blocks"] == 2
        # a disjoint request needing 5 blocks with only 4 non-cache free:
        # eviction makes room, admission never wedges
        p2 = [90] * 40
        assert _collect(eng.submit(p2, 40)[0]) == _serial(params, p2, 40)
        assert eng.prefix_stats()["evictions"] >= 1
        assert reg.get("ctpu_lm_prefix_evictions_total") >= 1
    finally:
        eng.close()
    assert eng.kv.used_blocks == 0, eng.kv.ref_counts()


def test_prefix_cancel_mid_prefill_keeps_refcounts_balanced(params):
    """Cancels racing multi-chunk prefill of shared prompts must leave
    the ledger balanced: whatever was written may enter the cache, but
    after close every reference is gone (the REFCOUNT-PAIR bug-class,
    exercised dynamically)."""
    eng = LmEngine(params, CFG, max_slots=2, lane_counts=(2,),
                   block_size=8, prefill_chunk=16, min_bucket=4)
    shared = list(range(1, 41))  # 40 tokens = 3 prefill chunks
    try:
        for i in range(6):
            q, handle = eng.submit(shared + [60 + i], 4)
            if i % 2 == 0:
                eng.cancel(handle)  # often lands mid-prefill
                got = _collect(q)
                want = _serial(params, shared + [60 + i], 4)
                assert got == want[: len(got)]
            else:
                assert _collect(q) == _serial(params, shared + [60 + i], 4)
        # drained: only the cache may hold references, every one exactly 1
        refs = eng.kv.ref_counts()
        assert all(v == 1 for v in refs.values()), refs
        assert len(refs) == eng.prefix_stats()["cached_blocks"]
    finally:
        eng.close()
    assert eng.kv.used_blocks == 0, eng.kv.ref_counts()


# -- preemption: priority swap ---------------------------------------------

def _preempt_scenario(params, swap_block_limit):
    """Pool sized so the high-priority admission cannot fit beside the
    low-priority stream: the engine must swap the low lane out, serve
    'hi' first, then resume 'lo' — both byte-exact vs serial greedy."""
    reg = Registry()
    eng = LmEngine(params, CFG, max_slots=2, lane_counts=(2,),
                   block_size=8, pool_tokens=80, prefill_chunk=16,
                   min_bucket=4, registry=reg,
                   tenant_priority={"hi": 10.0},
                   swap_block_limit=swap_block_limit)
    pa, pb = [1, 2, 3], [9, 4]
    try:
        qa, _ = eng.submit(pa, 60, tenant="lo")  # 8 of 10 blocks
        first = qa.get(timeout=120)
        assert first is not CLOSE
        qb, _ = eng.submit(pb, 40, tenant="hi")  # needs 6: must preempt
        done = {}

        def drain(name, q, acc):
            while True:
                tok = q.get(timeout=120)
                if tok is CLOSE:
                    break
                acc.append(tok)
            done[name] = time.monotonic()

        got_a, got_b = [first], []
        threads = [
            threading.Thread(target=drain, args=("a", qa, got_a),
                             daemon=True),
            threading.Thread(target=drain, args=("b", qb, got_b),
                             daemon=True),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive(), "stream wedged across preemption"
        assert got_a == _serial(params, pa, 60)  # byte-exact THROUGH swap
        assert got_b == _serial(params, pb, 40)
        ps = eng.preempt_stats()
        assert ps["preemptions"] >= 1, ps
        assert ps["resumes"] == ps["preemptions"]
        assert ps["swapped_streams"] == 0
        assert all(ms > 0 for ms in ps["resume_ms"])
        assert reg.get("ctpu_lm_preemptions_total") == ps["preemptions"]
        assert (reg.get("ctpu_lm_swapped_blocks") or 0) == 0
    finally:
        eng.close()
    assert eng.kv.used_blocks == 0, eng.kv.ref_counts()


def test_preemption_swap_path_byte_exact(params):
    _preempt_scenario(params, swap_block_limit=None)


def test_preemption_recompute_fallback_byte_exact(params):
    """swap_block_limit=0 forces the recompute path: the preempted KV is
    dropped and rebuilt by replaying prompt + delivered tokens through
    chunked prefill — the stream still resumes and completes exactly."""
    _preempt_scenario(params, swap_block_limit=0)


def test_pick_order_prefers_priority_class_over_rr_head(params):
    """The admission-order half of the preemption guarantee, driven
    race-free against a frozen engine (the scheduler thread starts
    lazily): with the round-robin cursor parked on a low-priority
    tenant, a higher-class tenant's handle is still picked FIRST — the
    shape that makes preemption reachable when a gold request queues
    behind a backpressured bronze head."""
    from collections import deque

    from client_tpu.serve.lm.engine import _Handle

    eng = LmEngine(params, CFG, max_slots=4, lane_counts=(4,),
                   block_size=8, prefill_chunk=16, min_bucket=4,
                   tenant_priority={"hi": 10.0})

    def handle(tenant):
        return _Handle(np.zeros((1, 2), np.int32), 4, queue.Queue(),
                       tenant, 0.0, 0, 0)

    h_lo, h_hi = handle("lo"), handle("hi")
    with eng._cv:
        eng._pending["lo"] = deque([h_lo])
        eng._pending["hi"] = deque([h_hi])
        eng._rr = 0  # cursor on "lo": rotation alone would pick it
        assert eng._pick_pending_locked(4) is h_hi  # class outranks rr
        assert eng._pick_pending_locked(4) is h_lo


def test_high_priority_preempts_past_backpressured_low_head(params):
    """A gold request queued BEHIND another tenant's backpressured
    request must still fire preemption: admission picks priority classes
    first (round-robin only within a class), so pool exhaustion can't
    park the cursor on a low-priority head forever."""
    eng = LmEngine(params, CFG, max_slots=3, lane_counts=(3,),
                   block_size=8, pool_tokens=80, prefill_chunk=16,
                   min_bucket=4, tenant_priority={"hi": 10.0})
    pa = [1, 2, 3]
    try:
        # A's reservation spans the WHOLE pool (blocks_for(3+90) = 12):
        # nothing else admits until A is preempted or fully done, and a
        # 90-token stream cannot finish before the hi submit lands
        q_a, _ = eng.submit(pa, 90, tenant="lo")
        assert q_a.get(timeout=120) is not CLOSE
        q_b, _ = eng.submit([5, 6], 40, tenant="lo2")  # stuck rr head
        q_c, _ = eng.submit([9, 4], 40, tenant="hi")
        got_c = _collect(q_c)
        assert got_c == _serial(params, [9, 4], 40)
        assert eng.preempt_stats()["preemptions"] >= 1
        assert _collect(q_b) == _serial(params, [5, 6], 40)
        got_a = [_serial(params, pa, 90)[0]] + _collect(q_a)
        assert got_a == _serial(params, pa, 90)
    finally:
        eng.close()
    assert eng.kv.used_blocks == 0, eng.kv.ref_counts()


def test_no_preemption_between_equal_priorities(params):
    """Priority ties never preempt: with everyone at the default class,
    pool exhaustion stays plain admission backpressure."""
    eng = LmEngine(params, CFG, max_slots=2, lane_counts=(2,),
                   block_size=8, pool_tokens=80, prefill_chunk=16,
                   min_bucket=4, tenant_priority={})
    try:
        qa, _ = eng.submit([1, 2, 3], 60, tenant="x")
        assert qa.get(timeout=120) is not CLOSE
        qb, _ = eng.submit([9, 4], 40, tenant="y")
        assert _collect(qb) == _serial(params, [9, 4], 40)
        _collect(qa)
        assert eng.preempt_stats()["preemptions"] == 0
    finally:
        eng.close()
    assert eng.kv.used_blocks == 0


def test_cancel_while_swapped_closes_cleanly(params):
    """A parked (preempted) stream cancelled before resume: its queue
    closes, nothing leaks, the engine keeps serving."""
    eng = LmEngine(params, CFG, max_slots=2, lane_counts=(2,),
                   block_size=8, pool_tokens=80, prefill_chunk=16,
                   min_bucket=4, tenant_priority={"hi": 10.0})
    try:
        qa, ha = eng.submit([1, 2, 3], 60, tenant="lo")
        assert qa.get(timeout=120) is not CLOSE
        qb, _ = eng.submit([9, 4], 40, tenant="hi")
        # wait until the low stream is actually parked
        deadline = time.monotonic() + 60
        while (eng.preempt_stats()["swapped_streams"] == 0
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert eng.preempt_stats()["swapped_streams"] == 1
        eng.cancel(ha)
        # the paused queue ends with CLOSE, never an error
        while qa.get(timeout=60) is not CLOSE:
            pass
        assert _collect(qb) == _serial(params, [9, 4], 40)
        ps = eng.preempt_stats()
        assert ps["swapped_streams"] == 0 and ps["resumes"] == 0
    finally:
        eng.close()
    assert eng.kv.used_blocks == 0, eng.kv.ref_counts()


# -- planned retire with parked streams (fleet migration) ------------------

def _park_low_stream(params, fleet=None):
    """Engine with a preempted-and-parked low-priority stream (the PR 10
    swap path).  The caller drains IMMEDIATELY — the 'hi' stream still
    holds the pool, so the parked stream cannot resume first — and reads
    the low stream's delivered-token prefix off its (closed) queue."""
    eng = LmEngine(params, CFG, max_slots=2, lane_counts=(2,),
                   block_size=8, pool_tokens=80, prefill_chunk=16,
                   min_bucket=4, tenant_priority={"hi": 10.0},
                   registry=Registry(), fleet=fleet)
    prompt = [1, 2, 3]
    q_lo, h_lo = eng.submit(prompt, 60, tenant="lo")
    first = q_lo.get(timeout=120)
    assert first is not CLOSE
    q_hi, _ = eng.submit([9, 4], 40, tenant="hi")
    deadline = time.monotonic() + 60
    while (eng.preempt_stats()["swapped_streams"] == 0
           and time.monotonic() < deadline):
        time.sleep(0.005)
    assert eng.preempt_stats()["swapped_streams"] == 1
    return eng, prompt, first, q_lo, q_hi


def test_retire_with_parked_stream_never_leaks_swap_blocks(params):
    """A preempted (swapped-out) LM stream on a retiring engine: drain()
    closes its paused queue cleanly (no error, no strand) and the swap
    store + KV pool end fully free — a parked stream must never leak its
    swap blocks through a planned retire."""
    eng, prompt, first, q_lo, q_hi = _park_low_stream(params)
    migrated = eng.drain()  # no fleet tier: nothing to migrate INTO
    assert migrated == 0
    # both queues end with CLOSE, never an error sentinel
    delivered = [first]
    while True:
        tok = q_lo.get(timeout=60)
        if tok is CLOSE:
            break
        delivered.append(tok)
    while q_hi.get(timeout=60) is not CLOSE:
        pass
    ps = eng.preempt_stats()
    assert ps["swapped_streams"] == 0 and ps["swapped_blocks"] == 0
    assert eng.kv.used_blocks == 0, eng.kv.ref_counts()
    # delivered tokens are a clean prefix of the serial stream (no
    # duplicated or reordered positions across the preemption)
    assert delivered == _serial(params, prompt, 60)[:len(delivered)]


def test_parked_stream_migrates_through_fleet_tier(params):
    """The fleet half of the retire contract: drain() exports the parked
    stream's host-swapped KV chain (prompt AND generated blocks) into
    the shared tier, and a surviving replica resumes it byte-exact with
    the replayed prefill served from peer-fetched blocks."""
    from client_tpu.serve.fleet import FleetTier

    tier_a = FleetTier(gossip_interval_s=0).start()
    tier_b = FleetTier(gossip_interval_s=0).start()
    eng_b = None
    try:
        tier_a.set_peers([tier_b.address])
        tier_b.set_peers([tier_a.address])
        eng, prompt, first, q_lo, _q_hi = _park_low_stream(
            params, fleet=tier_a
        )
        migrated = eng.drain()
        assert migrated == 1
        delivered = [first]
        while True:
            tok = q_lo.get(timeout=60)
            if tok is CLOSE:
                break
            delivered.append(tok)
        assert eng.kv.used_blocks == 0, eng.kv.ref_counts()
        assert eng.preempt_stats()["swapped_blocks"] == 0
        # the surviving replica resumes: prompt + delivered tokens as the
        # new prompt, remaining budget as max_tokens — byte-exact vs the
        # uninterrupted serial stream, prefill fed from the shared tier
        eng_b = LmEngine(params, CFG, max_slots=2, lane_counts=(2,),
                         block_size=8, prefill_chunk=16, min_bucket=4,
                         registry=Registry(), fleet=tier_b)
        resume_prompt = prompt + delivered
        q_r, _ = eng_b.submit(resume_prompt, 60 - len(delivered))
        rest = _collect(q_r)
        assert delivered + rest == _serial(params, prompt, 60)
        fs = eng_b.fleet_stats()
        assert fs["remote_lookups"] >= 1
        assert fs["remote_blocks"] >= 1  # prefill fed from the peer store
    finally:
        if eng_b is not None:
            eng_b.close()
        tier_a.close()
        tier_b.close()
    assert eng_b.kv.used_blocks == 0, eng_b.kv.ref_counts()


# -- engine metrics / spans ------------------------------------------------

def test_engine_metrics_and_tick_spans(params):
    from client_tpu.serve.tracing import Tracer

    reg = Registry()
    settings = {"trace_level": ["TIMESTAMPS"], "trace_rate": "1",
                "trace_count": "1", "trace_file": "", "log_frequency": "0"}
    tracer = Tracer(settings)
    eng = LmEngine(params, CFG, max_slots=2, lane_counts=(2,),
                   block_size=8, prefill_chunk=16, min_bucket=4,
                   registry=reg, tracer=tracer)
    try:
        _collect(eng.submit([1, 2, 3], 6)[0])
        assert reg.get("ctpu_lm_tokens_total") == 6
        assert reg.get("ctpu_lm_prefill_chunks_total") >= 1
        assert reg.get("ctpu_lm_lanes") == 2
        kinds = {t.model_name for t in tracer.tick_completed}
        assert "__lm_decode__" in kinds
        assert "__lm_prefill_chunk__" in kinds
        for t in tracer.tick_completed:
            names = [e["name"] for e in t.timestamps]
            assert names == ["COMPUTE_START", "COMPUTE_END"]
        # tick spans never touch the request-trace budget or deque: a
        # decode loop must not starve/evict real request traces
        assert not any(
            t.model_name.startswith("__lm_") for t in tracer.completed
        )
        assert tracer.sample(model_name="req") is not None
    finally:
        eng.close()


# -- speculative decoding: draft/verify over the paged KV cache ------------

def test_spec_policy_and_drafter_units():
    from client_tpu.serve.lm.policy import verify_widths
    from client_tpu.serve.lm.spec import (
        BigramDrafter,
        Drafter,
        NgramDrafter,
        SpecConfig,
    )

    # verify widths: geometric, capped at k+1, bounded-compile set
    assert verify_widths(4) == (2, 4, 5)
    assert verify_widths(1) == (2,)
    with pytest.raises(ValueError):
        verify_widths(0)

    # config parsing: off / defaults / bare k / dict / injected drafter
    assert SpecConfig.parse(None) is None
    assert SpecConfig.parse(True).k == 4
    assert SpecConfig.parse(2).k == 2
    cfg = SpecConfig.parse({"k": 3, "drafter": "bigram", "window": 4})
    assert cfg.k == 3 and cfg.drafter.name == "bigram" and cfg.window == 4
    inj = SpecConfig.parse({"k": 1, "drafter": Drafter()})
    assert inj.drafter.propose(None, [1, 2], 1) == []
    with pytest.raises(ValueError):
        SpecConfig.parse({"k": 2, "bogus": 1})

    # prompt-lookup: longest-suffix match, most recent occurrence wins
    ng = NgramDrafter(n=3)
    hist = [1, 2, 3, 9, 1, 2, 3, 7, 8, 1, 2, 3]
    assert ng.propose(None, hist, 2) == [7, 8]  # latest [1,2,3] -> 7,8
    assert ng.propose(None, [5, 6], 4) == []  # no prior occurrence

    # bigram table from the prompt, chained greedily
    bg = BigramDrafter()
    state = bg.begin([1, 2, 1, 2, 1, 3])
    assert state[1] == 2  # 1->2 twice beats 1->3 once
    assert bg.propose(state, [9, 1], 3) == [2, 1, 2]


def test_spec_lane_backoff_reprobe_and_growth_units():
    from client_tpu.serve.lm.spec import SpecConfig, LaneSpec

    cfg = SpecConfig.parse({"k": 4, "window": 2, "retry_after": 5})
    lane = LaneSpec(cfg, [1, 2, 3])
    # a fully rejected window disables outright (no signal: walking k
    # down would just waste verifies — the never-slower fast path)
    lane.note(4, 0)
    lane.note(4, 0)
    assert lane.k == 0
    # disabled lane re-probes at k=1 after retry_after plain ticks
    for _ in range(4):
        lane.note_plain()
    assert lane.k == 0
    lane.note_plain()
    assert lane.k == 1
    # low-but-nonzero acceptance halves; high acceptance grows back
    lane.note(1, 1)
    lane.note(1, 1)  # rate 1.0 >= grow_rate -> k doubles
    assert lane.k == 2
    lane.note(2, 0)
    lane.note(2, 1)  # rate 0.25 < min_rate -> halve
    assert lane.k == 1


def test_spec_greedy_byte_exact_across_bucket_boundaries(params):
    """Greedy spec-on output must be byte-identical to spec-off across
    verify-width buckets AND KV block boundaries: repetitive prompts the
    n-gram drafter actually hits (draft lengths bucketing to every
    verify width) decode concurrently, long enough to cross several
    8-token KV blocks; byte-exactness is checked against the serial
    greedy stream (CFG is float32, where verify and decode logits agree
    exactly — see spec.py on the bfloat16 near-tie caveat)."""
    eng = LmEngine(params, CFG, max_slots=2, lane_counts=(2,),
                   block_size=8, prefill_chunk=16, min_bucket=4,
                   speculative={"k": 4}, registry=Registry())
    prompts = [
        [7, 9, 11] * 5,          # period-3 echo: multi-token drafts
        [1, 2] * 7,              # period-2 echo
        [3, 1, 4, 1, 5, 9, 2, 6],  # no structure: short/no drafts
    ]
    try:
        qs = [eng.submit(p, 40)[0] for p in prompts]
        got = [_collect(q) for q in qs]
        for p, g in zip(prompts, got):
            assert g == _serial(params, p, 40)
        stats = eng.spec_stats()
        assert stats["accepted"] > 0  # speculation actually engaged
        assert 0.0 <= stats["acceptance_rate"] <= 1.0
    finally:
        eng.close()
    assert eng.kv.used_blocks == 0


def test_spec_verify_executable_bound(params):
    from client_tpu.serve.lm.policy import verify_widths

    eng = LmEngine(params, CFG, max_slots=2, lane_counts=(2,),
                   block_size=8, prefill_chunk=16, min_bucket=4,
                   speculative={"k": 4})
    try:
        for p in ([5, 6] * 6, [8, 8, 8, 8, 8], [2, 4, 6, 8, 2, 4, 6, 8]):
            _collect(eng.submit(p, 24)[0])
        bound = len(verify_widths(4)) * len(eng.lane_counts)
        assert 1 <= eng.verify_executables() <= bound
    finally:
        eng.close()


def test_spec_temperature_lane_seed_deterministic(params):
    """Temperature lanes under speculation: same seed -> same stream
    (the verify tick's RNG carry is part of lane state, so the
    draft/verify path is seed-deterministic like plain decode), and the
    stream is still an exact draw from the target distribution — not
    byte-equal to the spec-off stream, whose RNG advances once per
    token rather than once per verify round."""
    kw = dict(temperature=0.8, top_k=8)
    prompt = [1, 2] * 6
    eng = LmEngine(params, CFG, max_slots=2, lane_counts=(2,),
                   block_size=8, prefill_chunk=16, min_bucket=4,
                   speculative={"k": 4})
    try:
        s1 = _collect(eng.submit(prompt, 20, seed=42, **kw)[0])
        s2 = _collect(eng.submit(prompt, 20, seed=42, **kw)[0])
        s3 = _collect(eng.submit(prompt, 20, seed=7, **kw)[0])
        greedy = _collect(eng.submit(prompt, 20)[0])
        assert s1 == s2  # same seed, same draft/verify/RNG path
        assert s1 != s3 or s1 != greedy  # sampling actually samples
        assert greedy == _serial(params, prompt, 20)  # greedy unaffected
    finally:
        eng.close()


def test_spec_adversarial_drafter_backs_off_and_never_slower(params):
    """Zero-acceptance adversary: a drafter that always proposes the
    WRONG token (it looks up what greedy will emit next and proposes
    something else).  The engine must (a) stay byte-exact, (b) disable
    the lane after ONE fully rejected window (bounded wasted verifies),
    and (c) sustain >= 0.95x plain-decode throughput with warmed
    executables — the never-slower guarantee."""
    from client_tpu.serve.lm.spec import Drafter

    prompt = [1, 2, 3, 4]
    n_tok = 80
    serial = _serial(params, prompt, n_tok)
    full = prompt + serial

    class Adversary(Drafter):
        name = "adversary"

        def propose(self, state, history, k):
            # history = prompt + delivered tokens; the next greedy
            # token is full[len(history)] — propose anything else
            nxt = full[len(history)] if len(history) < len(full) else 0
            return [(nxt + 1) % CFG.vocab_size] * k

    spec = {"k": 4, "drafter": Adversary()}

    def timed(speculative):
        eng = LmEngine(params, CFG, max_slots=1, lane_counts=(1,),
                       block_size=8, prefill_chunk=16, min_bucket=4,
                       speculative=speculative)
        try:
            _collect(eng.submit(prompt, n_tok)[0])  # warm + compile
            t0 = time.perf_counter()
            got = _collect(eng.submit(prompt, n_tok)[0])
            elapsed = time.perf_counter() - t0
            stats = eng.spec_stats()
        finally:
            eng.close()
        assert got == serial  # byte-exact under total rejection
        return elapsed, stats

    plain_s, _ = timed(None)
    spec_s, stats = timed(spec)
    assert stats["proposed"] > 0 and stats["accepted"] == 0
    # one window (8 rounds) of k=4 drafts per submit before the lane
    # disables; nothing after (n_tok < retry_after blocks the re-probe)
    assert stats["proposed"] <= 2 * 8 * 4
    # throughput ratio, not absolute time: CI boxes are noisy, so give
    # the 0.95x guarantee a small measurement allowance
    assert spec_s <= plain_s / 0.95 + 0.25, (spec_s, plain_s)


def test_spec_tick_kinds_metrics_and_gauge(params):
    from client_tpu.serve.tracing import Tracer

    reg = Registry()
    settings = {"trace_level": ["TIMESTAMPS"], "trace_rate": "1",
                "trace_count": "1", "trace_file": "", "log_frequency": "0"}
    tracer = Tracer(settings)
    eng = LmEngine(params, CFG, max_slots=2, lane_counts=(2,),
                   block_size=8, prefill_chunk=16, min_bucket=4,
                   speculative={"k": 4}, registry=reg, tracer=tracer)
    try:
        _collect(eng.submit([5, 6] * 6, 24)[0])
        kinds = {t.model_name for t in tracer.tick_completed}
        assert "__lm_verify__" in kinds
        assert "__lm_draft__" in kinds
        assert "__lm_prefill_chunk__" in kinds
        proposed = reg.get("ctpu_lm_spec_proposed_tokens_total")
        accepted = reg.get("ctpu_lm_spec_accepted_tokens_total") or 0
        rejected = reg.get("ctpu_lm_spec_rejected_tokens_total") or 0
        assert proposed and proposed == accepted + rejected
        rate = reg.get("ctpu_lm_spec_acceptance_rate")
        assert rate is not None and 0.0 <= rate <= 1.0
        # delivered token accounting includes spec-delivered tokens
        assert reg.get("ctpu_lm_tokens_total") == 24
    finally:
        eng.close()


# -- soak: >=128 concurrent streams under churn (slow tier) ----------------

@pytest.mark.slow
def test_soak_128_streams_submit_cancel_churn(params):
    """The production acceptance: 128 concurrent streams on ONE engine
    through submit/cancel churn — zero client-visible errors (every
    stream terminates; survivors decode EXACTLY their serial greedy
    stream), no stream starved (bounded inter-token gap while the engine
    ran), compiled executables bounded by the bucket/lane-count sets,
    every KV block freed.

    A third of the streams carry SHARED-PREFIX prompts long enough for
    multi-chunk prefill, and some of those are cancelled mid-flight —
    so prefix-cache adoption, publication and give-back churn against
    cancels racing prefill (the refcount-leak bug-class, dynamically).
    At drain every surviving block reference belongs to the cache
    (exactly one each); close() leaves the pool FULLY free.  Runs under
    the lock-order witness in `make soak`."""
    n_streams = 128
    max_tokens = 6
    eng = LmEngine(params, CFG, max_slots=8, lane_counts=(2, 4, 8),
                   block_size=8, prefill_chunk=16, min_bucket=4,
                   scale_up_after=2, registry=Registry())
    lengths = (2, 3, 5)
    shared = [((j * 11) % 120) + 1 for j in range(40)]  # 3 prefill chunks
    prompts = [
        (shared + [((i * 13) % 120) + 1, ((i * 5) % 120) + 1]
         if i % 3 == 0
         else [((i * 7 + j) % 120) + 1 for j in range(lengths[i % 3])])
        for i in range(n_streams)
    ]
    expected = {}
    for p in prompts:
        expected.setdefault(tuple(p), _serial(params, p, max_tokens))
    results = [None] * n_streams
    gaps = [0.0] * n_streams

    def run(i):
        q, handle = eng.submit(prompts[i], max_tokens)
        toks = []
        # i % 9 == 0 cancels after 2 tokens; shared-prefix streams with
        # i % 6 == 3 cancel IMMEDIATELY — those often land mid-prefill
        cancelled = i % 9 == 0 or i % 6 == 3
        cancel_after = 2 if i % 9 == 0 else None
        if i % 6 == 3:
            eng.cancel(handle)
            cancel_after = None
        last = None
        try:
            while True:
                tok = q.get(timeout=300)
                now = time.monotonic()
                if tok is CLOSE:
                    break
                if last is not None:
                    gaps[i] = max(gaps[i], now - last)
                last = now
                toks.append(tok)
                if cancel_after is not None and len(toks) >= cancel_after:
                    eng.cancel(handle)
                    cancel_after = None  # queue still drains to CLOSE
            results[i] = ("cancelled" if cancelled else "done", toks)
        except Exception as e:  # pragma: no cover - failure path
            results[i] = ("error", repr(e))

    try:
        threads = [
            threading.Thread(target=run, args=(i,), daemon=True)
            for i in range(n_streams)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
            assert not t.is_alive(), "stream reader wedged"

        errors = [r for r in results if r is None or r[0] == "error"]
        assert not errors, errors[:5]
        for i, (status, toks) in enumerate(results):
            want = expected[tuple(prompts[i])]
            if status == "done":
                assert toks == want, (i, toks, want)
            else:  # cancelled mid-flight: clean prefix, then CLOSE
                assert toks == want[: len(toks)], (i, toks, want)
        # no starvation: while streaming, no stream waited unboundedly
        # between its own tokens (generous CI bound; the unbounded-stall
        # failure mode is minutes, not seconds)
        assert max(gaps) < 30.0, max(gaps)
        # bounded executable sets survived the churn
        assert eng.prefill_executables() <= len(eng.buckets)
        assert eng.decode_executables() <= len(eng.lane_counts)
        # autoscaling engaged under 128-deep queues
        assert max(r["n_lanes"] for r in eng.tick_trace()) == 8
        # chunked-prefill interleave held under churn: between any two
        # consecutive prefill chunks with active lanes, decode ticked
        trace = eng.tick_trace()
        decodes = [r for r in trace if r["kind"] == "decode"]
        assert len(decodes) >= max_tokens  # batched, not serialized
        # every reservation returned: at drain the ONLY live references
        # are the prefix cache's warm prompt blocks, exactly one each —
        # any request-held reference here is a leak
        refs = eng.kv.ref_counts()
        assert all(v == 1 for v in refs.values()), refs
        assert len(refs) == eng.prefix_stats()["cached_blocks"]
        assert eng.prefix_stats()["hits"] > 0  # sharing actually happened
    finally:
        eng.close()
    # close() drops the cache too: zero references, pool FULLY free
    assert eng.kv.ref_counts() == {}
    assert eng.kv.used_blocks == 0


def test_close_releases_everything(params):
    eng = LmEngine(params, CFG, max_slots=2, lane_counts=(2,),
                   block_size=8, prefill_chunk=16, min_bucket=4)
    q1, _ = eng.submit([1, 2], 50)
    assert q1.get(timeout=60) is not CLOSE
    q2, _ = eng.submit([3, 4], 50)
    q3, _ = eng.submit([5, 6], 50)  # pending (no free lane)
    eng.close()
    for q in (q1, q2, q3):
        while True:
            if q.get(timeout=10) is CLOSE:
                break
    assert eng.kv.used_blocks == 0
    # post-close submit is a closed stream, not queued work
    q4, h4 = eng.submit([1], 4)
    assert h4 is None
    assert q4.get(timeout=10) is CLOSE
