"""End-to-end tests for the asyncio clients (http.aio + grpc.aio)."""

import asyncio

import numpy as np
import pytest

from client_tpu.serve import Server
from client_tpu.utils import InferenceServerException


@pytest.fixture(scope="module")
def server():
    with Server(grpc_port=0) as s:
        yield s


def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def _simple_inputs(mod):
    inputs = [
        mod.InferInput("INPUT0", [1, 16], "INT32"),
        mod.InferInput("INPUT1", [1, 16], "INT32"),
    ]
    i0 = np.arange(16, dtype=np.int32).reshape(1, 16)
    i1 = np.ones((1, 16), dtype=np.int32)
    inputs[0].set_data_from_numpy(i0)
    inputs[1].set_data_from_numpy(i1)
    return inputs, i0, i1


class TestHttpAio:
    def test_full_flow(self, server):
        import client_tpu.http.aio as aioclient

        async def flow():
            async with aioclient.InferenceServerClient(server.http_address) as c:
                assert await c.is_server_live()
                assert await c.is_server_ready()
                assert await c.is_model_ready("simple")
                meta = await c.get_server_metadata()
                assert meta["name"] == "client_tpu.serve"
                inputs, i0, i1 = _simple_inputs(aioclient)
                result = await c.infer("simple", inputs)
                np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), i0 + i1)
                stats = await c.get_inference_statistics("simple")
                assert stats["model_stats"][0]["inference_count"] >= 1
                index = await c.get_model_repository_index()
                assert any(m["name"] == "simple" for m in index)

        _run(flow())

    def test_concurrent_infers(self, server):
        import client_tpu.http.aio as aioclient

        async def flow():
            async with aioclient.InferenceServerClient(server.http_address) as c:
                inputs, i0, i1 = _simple_inputs(aioclient)
                results = await asyncio.gather(
                    *(c.infer("simple", inputs) for _ in range(8))
                )
                for r in results:
                    np.testing.assert_array_equal(r.as_numpy("OUTPUT0"), i0 + i1)

        _run(flow())

    def test_error(self, server):
        import client_tpu.http.aio as aioclient

        async def flow():
            async with aioclient.InferenceServerClient(server.http_address) as c:
                inputs, _, _ = _simple_inputs(aioclient)
                with pytest.raises(InferenceServerException, match="unknown model"):
                    await c.infer("nope", inputs)

        _run(flow())

    def test_compression(self, server):
        import client_tpu.http.aio as aioclient

        async def flow():
            async with aioclient.InferenceServerClient(server.http_address) as c:
                inputs, i0, i1 = _simple_inputs(aioclient)
                result = await c.infer(
                    "simple",
                    inputs,
                    request_compression_algorithm="gzip",
                    response_compression_algorithm="gzip",
                )
                np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), i0 + i1)

        _run(flow())


class TestGrpcAio:
    def test_full_flow(self, server):
        import client_tpu.grpc.aio as aioclient

        async def flow():
            async with aioclient.InferenceServerClient(server.grpc_address) as c:
                assert await c.is_server_live()
                assert await c.is_model_ready("simple")
                meta = await c.get_server_metadata()
                assert meta.name == "client_tpu.serve"
                inputs, i0, i1 = _simple_inputs(aioclient)
                result = await c.infer("simple", inputs)
                np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), i0 + i1)
                cfg = await c.get_model_config("simple")
                assert cfg.config.max_batch_size == 8
                stats = await c.get_inference_statistics("simple")
                assert stats.model_stats[0].inference_count >= 1

        _run(flow())

    def test_stream_infer(self, server):
        import client_tpu.grpc.aio as aioclient

        async def flow():
            async with aioclient.InferenceServerClient(server.grpc_address) as c:
                async def requests():
                    for v in (1, 2, 3):
                        inp = aioclient.InferInput("INPUT", [1], "INT32")
                        inp.set_data_from_numpy(np.array([v], dtype=np.int32))
                        yield {
                            "model_name": "simple_sequence",
                            "inputs": [inp],
                            "sequence_id": 777,
                            "sequence_start": v == 1,
                            "sequence_end": v == 3,
                        }

                acc = []
                count = 0
                async for result, error in c.stream_infer(requests()):
                    assert error is None
                    acc.append(int(result.as_numpy("OUTPUT")[0]))
                    count += 1
                    if count == 3:
                        break
                assert acc == [1, 3, 6]

        _run(flow())

    def test_error(self, server):
        import client_tpu.grpc.aio as aioclient

        async def flow():
            async with aioclient.InferenceServerClient(server.grpc_address) as c:
                inputs, _, _ = _simple_inputs(aioclient)
                with pytest.raises(InferenceServerException) as e:
                    await c.infer("nope", inputs)
                assert e.value.status() == "INVALID_ARGUMENT"

        _run(flow())


class TestHttpAioParity:
    """Surface parity with the sync client: trace/log settings, model
    control, shm verbs, and the pipelining statics."""

    def test_trace_settings_roundtrip(self, server):
        import client_tpu.http.aio as aioclient

        async def flow():
            async with aioclient.InferenceServerClient(server.http_address) as c:
                got = await c.update_trace_settings(
                    "simple", {"trace_level": ["TIMESTAMPS"], "trace_rate": "1"}
                )
                assert got["trace_level"] == ["TIMESTAMPS"]
                got = await c.get_trace_settings("simple")
                assert got["trace_rate"] == "1"
                # global settings view exists too
                assert isinstance(await c.get_trace_settings(), dict)

        _run(flow())

    def test_log_settings_roundtrip(self, server):
        import client_tpu.http.aio as aioclient

        async def flow():
            async with aioclient.InferenceServerClient(server.http_address) as c:
                got = await c.update_log_settings({"log_verbose_level": 1})
                assert int(got["log_verbose_level"]) == 1
                got = await c.get_log_settings()
                assert "log_verbose_level" in got

        _run(flow())

    def test_model_control(self, server):
        import client_tpu.http.aio as aioclient

        async def flow():
            async with aioclient.InferenceServerClient(server.http_address) as c:
                await c.unload_model("identity")
                assert not await c.is_model_ready("identity")
                await c.load_model("identity")
                assert await c.is_model_ready("identity")

        _run(flow())

    def test_system_shm_verbs(self, server):
        import client_tpu.http.aio as aioclient
        from client_tpu.utils import shared_memory as shm

        handle = shm.create_shared_memory_region("aio_shm", "/aio_shm", 64)
        try:
            async def flow():
                async with aioclient.InferenceServerClient(
                    server.http_address
                ) as c:
                    await c.register_system_shared_memory(
                        "aio_shm", "/aio_shm", 64
                    )
                    status = await c.get_system_shared_memory_status()
                    assert any(r["name"] == "aio_shm" for r in status)
                    await c.unregister_system_shared_memory("aio_shm")
                    status = await c.get_system_shared_memory_status()
                    assert not any(r["name"] == "aio_shm" for r in status)

            _run(flow())
        finally:
            shm.destroy_shared_memory_region(handle)

    def test_generate_request_body_static_pipelines(self, server):
        """The statics build/parse bodies with no client instance — wire a
        hand-carried request through the sync transport and parse the raw
        response with the aio static."""
        import urllib3

        import client_tpu.http.aio as aioclient

        inputs, i0, i1 = _simple_inputs(aioclient)
        body, json_size = aioclient.InferenceServerClient.generate_request_body(
            inputs
        )
        http = urllib3.PoolManager()
        r = http.request(
            "POST",
            f"http://{server.http_address}/v2/models/simple/infer",
            body=body,
            headers={
                "Content-Type": "application/octet-stream",
                "Inference-Header-Content-Length": str(json_size),
            },
        )
        assert r.status == 200
        result = aioclient.InferenceServerClient.parse_response_body(
            r.data,
            header_length=r.headers.get("Inference-Header-Content-Length"),
        )
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), i0 + i1)

    def test_nonbinary_json_tensors(self, server):
        import client_tpu.http.aio as aioclient

        async def flow():
            async with aioclient.InferenceServerClient(server.http_address) as c:
                inputs = [
                    aioclient.InferInput("INPUT0", [1, 16], "INT32"),
                    aioclient.InferInput("INPUT1", [1, 16], "INT32"),
                ]
                i0 = np.arange(16, dtype=np.int32).reshape(1, 16)
                i1 = np.ones((1, 16), dtype=np.int32)
                inputs[0].set_data_from_numpy(i0, binary_data=False)
                inputs[1].set_data_from_numpy(i1, binary_data=False)
                result = await c.infer("simple", inputs)
                np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), i0 + i1)

        _run(flow())


class TestGrpcAioParity:
    def test_trace_settings_roundtrip(self, server):
        import client_tpu.grpc.aio as aioclient

        async def flow():
            async with aioclient.InferenceServerClient(server.grpc_address) as c:
                got = await c.update_trace_settings(
                    "simple", {"trace_level": ["TIMESTAMPS"], "trace_rate": 1},
                    as_json=True,
                )
                assert "settings" in got
                got = await c.get_trace_settings("simple", as_json=True)
                assert "settings" in got

        _run(flow())

    def test_log_settings_roundtrip(self, server):
        import client_tpu.grpc.aio as aioclient

        async def flow():
            async with aioclient.InferenceServerClient(server.grpc_address) as c:
                got = await c.update_log_settings(
                    {"log_verbose_level": 2}, as_json=True
                )
                assert "settings" in got
                got = await c.get_log_settings(as_json=True)
                assert "settings" in got

        _run(flow())

    def test_model_control(self, server):
        import client_tpu.grpc.aio as aioclient

        async def flow():
            async with aioclient.InferenceServerClient(server.grpc_address) as c:
                await c.unload_model("identity_bytes")
                assert not await c.is_model_ready("identity_bytes")
                await c.load_model("identity_bytes")
                assert await c.is_model_ready("identity_bytes")

        _run(flow())

    def test_system_shm_verbs(self, server):
        import client_tpu.grpc.aio as aioclient
        from client_tpu.utils import shared_memory as shm

        handle = shm.create_shared_memory_region("aio_gshm", "/aio_gshm", 64)
        try:
            async def flow():
                async with aioclient.InferenceServerClient(
                    server.grpc_address
                ) as c:
                    await c.register_system_shared_memory(
                        "aio_gshm", "/aio_gshm", 64
                    )
                    status = await c.get_system_shared_memory_status(
                        as_json=True
                    )
                    names = [
                        r["name"] for r in status.get("regions", {}).values()
                    ] + [
                        r.get("name") for r in status.get("regions", [])
                        if isinstance(r, dict)
                    ]
                    assert "aio_gshm" in names
                    await c.unregister_system_shared_memory("aio_gshm")

            _run(flow())
        finally:
            shm.destroy_shared_memory_region(handle)

    def test_tpu_shm_verbs(self, server):
        import client_tpu.grpc.aio as aioclient
        from client_tpu.utils import tpu_shared_memory as tpushm

        handle = tpushm.create_shared_memory_region("aio_tpu", 64)
        try:
            async def flow():
                async with aioclient.InferenceServerClient(
                    server.grpc_address
                ) as c:
                    await c.register_tpu_shared_memory(
                        "aio_tpu", tpushm.get_raw_handle(handle), 0, 64
                    )
                    status = await c.get_tpu_shared_memory_status(as_json=True)
                    assert status
                    await c.unregister_tpu_shared_memory("aio_tpu")

            _run(flow())
        finally:
            tpushm.destroy_shared_memory_region(handle)

    def test_decoupled_stream(self, server):
        import client_tpu.grpc.aio as aioclient

        async def flow():
            async with aioclient.InferenceServerClient(server.grpc_address) as c:
                inp = aioclient.InferInput("IN", [1], "INT32")
                inp.set_data_from_numpy(np.array([4], dtype=np.int32))

                async def requests():
                    yield {"model_name": "repeat_int32", "inputs": [inp]}

                seen = []
                async for result, error in c.stream_infer(requests()):
                    assert error is None
                    seen.append(int(result.as_numpy("OUT")[0]))
                    if len(seen) == 4:
                        break
                assert seen == [0, 1, 2, 3]

        _run(flow())

    def test_metadata_as_json(self, server):
        import client_tpu.grpc.aio as aioclient

        async def flow():
            async with aioclient.InferenceServerClient(server.grpc_address) as c:
                meta = await c.get_model_metadata("simple", as_json=True)
                assert meta["name"] == "simple"
                idx = await c.get_model_repository_index(as_json=True)
                names = [m["name"] for m in idx.get("models", [])]
                assert "simple" in names

        _run(flow())
