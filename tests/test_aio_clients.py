"""End-to-end tests for the asyncio clients (http.aio + grpc.aio)."""

import asyncio

import numpy as np
import pytest

from client_tpu.serve import Server
from client_tpu.utils import InferenceServerException


@pytest.fixture(scope="module")
def server():
    with Server(grpc_port=0) as s:
        yield s


def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def _simple_inputs(mod):
    inputs = [
        mod.InferInput("INPUT0", [1, 16], "INT32"),
        mod.InferInput("INPUT1", [1, 16], "INT32"),
    ]
    i0 = np.arange(16, dtype=np.int32).reshape(1, 16)
    i1 = np.ones((1, 16), dtype=np.int32)
    inputs[0].set_data_from_numpy(i0)
    inputs[1].set_data_from_numpy(i1)
    return inputs, i0, i1


class TestHttpAio:
    def test_full_flow(self, server):
        import client_tpu.http.aio as aioclient

        async def flow():
            async with aioclient.InferenceServerClient(server.http_address) as c:
                assert await c.is_server_live()
                assert await c.is_server_ready()
                assert await c.is_model_ready("simple")
                meta = await c.get_server_metadata()
                assert meta["name"] == "client_tpu.serve"
                inputs, i0, i1 = _simple_inputs(aioclient)
                result = await c.infer("simple", inputs)
                np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), i0 + i1)
                stats = await c.get_inference_statistics("simple")
                assert stats["model_stats"][0]["inference_count"] >= 1
                index = await c.get_model_repository_index()
                assert any(m["name"] == "simple" for m in index)

        _run(flow())

    def test_concurrent_infers(self, server):
        import client_tpu.http.aio as aioclient

        async def flow():
            async with aioclient.InferenceServerClient(server.http_address) as c:
                inputs, i0, i1 = _simple_inputs(aioclient)
                results = await asyncio.gather(
                    *(c.infer("simple", inputs) for _ in range(8))
                )
                for r in results:
                    np.testing.assert_array_equal(r.as_numpy("OUTPUT0"), i0 + i1)

        _run(flow())

    def test_error(self, server):
        import client_tpu.http.aio as aioclient

        async def flow():
            async with aioclient.InferenceServerClient(server.http_address) as c:
                inputs, _, _ = _simple_inputs(aioclient)
                with pytest.raises(InferenceServerException, match="unknown model"):
                    await c.infer("nope", inputs)

        _run(flow())

    def test_compression(self, server):
        import client_tpu.http.aio as aioclient

        async def flow():
            async with aioclient.InferenceServerClient(server.http_address) as c:
                inputs, i0, i1 = _simple_inputs(aioclient)
                result = await c.infer(
                    "simple",
                    inputs,
                    request_compression_algorithm="gzip",
                    response_compression_algorithm="gzip",
                )
                np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), i0 + i1)

        _run(flow())


class TestGrpcAio:
    def test_full_flow(self, server):
        import client_tpu.grpc.aio as aioclient

        async def flow():
            async with aioclient.InferenceServerClient(server.grpc_address) as c:
                assert await c.is_server_live()
                assert await c.is_model_ready("simple")
                meta = await c.get_server_metadata()
                assert meta.name == "client_tpu.serve"
                inputs, i0, i1 = _simple_inputs(aioclient)
                result = await c.infer("simple", inputs)
                np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), i0 + i1)
                cfg = await c.get_model_config("simple")
                assert cfg.config.max_batch_size == 8
                stats = await c.get_inference_statistics("simple")
                assert stats.model_stats[0].inference_count >= 1

        _run(flow())

    def test_stream_infer(self, server):
        import client_tpu.grpc.aio as aioclient

        async def flow():
            async with aioclient.InferenceServerClient(server.grpc_address) as c:
                async def requests():
                    for v in (1, 2, 3):
                        inp = aioclient.InferInput("INPUT", [1], "INT32")
                        inp.set_data_from_numpy(np.array([v], dtype=np.int32))
                        yield {
                            "model_name": "simple_sequence",
                            "inputs": [inp],
                            "sequence_id": 777,
                            "sequence_start": v == 1,
                            "sequence_end": v == 3,
                        }

                acc = []
                count = 0
                async for result, error in c.stream_infer(requests()):
                    assert error is None
                    acc.append(int(result.as_numpy("OUTPUT")[0]))
                    count += 1
                    if count == 3:
                        break
                assert acc == [1, 3, 6]

        _run(flow())

    def test_error(self, server):
        import client_tpu.grpc.aio as aioclient

        async def flow():
            async with aioclient.InferenceServerClient(server.grpc_address) as c:
                inputs, _, _ = _simple_inputs(aioclient)
                with pytest.raises(InferenceServerException) as e:
                    await c.infer("nope", inputs)
                assert e.value.status() == "INVALID_ARGUMENT"

        _run(flow())
