"""perf harness unit tests on the MockClientBackend — the reference's
doctest+mock test design (SURVEY.md §4: MockClientBackend simulates the load
path with injectable latency/error schedules; managers and profiler are
tested with no server).
"""

import time

import numpy as np
import pytest

from client_tpu.perf import (
    BackendKind,
    ClientBackendFactory,
    ConcurrencyManager,
    CustomLoadManager,
    DataLoader,
    InferenceProfiler,
    MockClientBackend,
    MockStats,
    RequestRateManager,
    SequenceManager,
    create_infer_data_manager,
)
from client_tpu.perf.load_manager import RequestRecord
from client_tpu.utils import InferenceServerException

META = [{"name": "INPUT0", "datatype": "FP32", "shape": [1, 4]}]
OUT_META = [{"name": "OUTPUT0", "datatype": "FP32", "shape": [1, 4]}]


def _mk_manager(cls, stats=None, latency_s=0.0, error_schedule=None, **kwargs):
    stats = stats or MockStats()

    def factory():
        return MockClientBackend(
            latency_s=latency_s, error_schedule=error_schedule, stats=stats
        )

    loader = DataLoader(META)
    loader.generate_data()
    dm = create_infer_data_manager(factory(), loader, META, OUT_META)
    dm.init()
    mgr = cls(
        backend_factory=factory,
        data_loader=loader,
        data_manager=dm,
        model_name="mock",
        **kwargs,
    )
    return mgr, stats


class TestDataLoader:
    def test_generate_random(self):
        loader = DataLoader(META)
        loader.generate_data()
        arr = loader.get_input_data(0, 0)["INPUT0"].array
        assert arr.shape == (1, 4) and arr.dtype == np.float32

    def test_generate_zero(self):
        loader = DataLoader(META)
        loader.generate_data(zero_data=True)
        assert not loader.get_input_data(0, 0)["INPUT0"].array.any()

    def test_dynamic_batch_dim_uses_batch_size(self):
        loader = DataLoader(
            [{"name": "X", "datatype": "FP32", "shape": [-1, 4]}], batch_size=3
        )
        loader.generate_data()
        assert loader.get_input_data(0, 0)["X"].array.shape == (3, 4)

    def test_dynamic_non_batch_dim_requires_override(self):
        loader = DataLoader([{"name": "X", "datatype": "FP32", "shape": [1, -1]}])
        with pytest.raises(InferenceServerException, match="dynamic"):
            loader.generate_data()

    def test_shape_override(self):
        loader = DataLoader(
            [{"name": "X", "datatype": "FP32", "shape": [-1, 4]}],
            shape_overrides={"X": [2, 4]},
        )
        loader.generate_data()
        assert loader.get_input_data(0, 0)["X"].array.shape == (2, 4)

    def test_json_streams_and_validation(self):
        doc = {
            "data": [
                [{"INPUT0": [1.0, 2.0, 3.0, 4.0]}],
                [{"INPUT0": {"content": [5.0, 6.0, 7.0, 8.0], "shape": [1, 4]}}],
            ],
            "validation_data": [
                [{"OUTPUT0": [1.0, 2.0, 3.0, 4.0]}],
                [{"OUTPUT0": [5.0, 6.0, 7.0, 8.0]}],
            ],
        }
        loader = DataLoader(META)
        loader.read_data_from_json(doc)
        assert loader.num_streams == 2
        np.testing.assert_allclose(
            loader.get_input_data(0, 0)["INPUT0"].array.flatten(),
            [1, 2, 3, 4],
        )
        assert loader.get_expected_outputs(1, 0)["OUTPUT0"].array.size == 4

    def test_bytes_generation(self):
        loader = DataLoader([{"name": "S", "datatype": "BYTES", "shape": [2]}])
        loader.generate_data(string_length=5)
        arr = loader.get_input_data(0, 0)["S"].array
        assert arr.dtype == np.object_ and len(arr[0]) == 5


class TestSequenceManager:
    def test_id_allocation_and_wraparound(self):
        sm = SequenceManager(start_sequence_id=10, sequence_id_range=3,
                             sequence_length=2, sequence_length_specified=True)
        ids = [sm.begin_sequence(slot).seq_id for slot in range(4)]
        assert ids == [10, 11, 12, 10]

    def test_advance_flags(self):
        sm = SequenceManager(sequence_length=3, sequence_length_specified=True)
        st = sm.begin_sequence(0)
        flags = [sm.advance(st) for _ in range(3)]
        assert flags == [(True, False), (False, False), (False, True)]

    def test_length_variation_bounds(self):
        sm = SequenceManager(sequence_length=100,
                             sequence_length_variation=20,
                             sequence_length_specified=True)
        lengths = {sm.begin_sequence(i).remaining_queries for i in range(50)}
        assert all(80 <= n <= 120 for n in lengths)
        assert len(lengths) > 1


class TestConcurrencyManager:
    def test_workers_send_requests(self):
        mgr, stats = _mk_manager(ConcurrencyManager)
        try:
            mgr.change_concurrency_level(4)
            time.sleep(0.3)
            records = mgr.swap_timestamps()
            assert len(records) > 50
            assert stats.num_infer_calls > 50
            assert mgr.get_and_reset_num_sent() > 0
        finally:
            mgr.cleanup()

    def test_records_survive_stop_workers(self):
        # profile_completion stops workers (quiescing sends before the output
        # drain) and only then swaps timestamps; stopping must not discard the
        # window's records with the thread list.
        mgr, _ = _mk_manager(ConcurrencyManager)
        try:
            mgr.change_concurrency_level(4)
            time.sleep(0.3)
            mgr.stop_workers()
            records = mgr.swap_timestamps()
            assert len(records) > 50
            assert mgr.swap_timestamps() == []  # drained exactly once
        finally:
            mgr.cleanup()

    def test_reconfigure_threads(self):
        mgr, _ = _mk_manager(ConcurrencyManager)
        try:
            mgr.change_concurrency_level(2)
            assert len(mgr._threads) == 2
            mgr.change_concurrency_level(6)
            assert len(mgr._threads) == 6
        finally:
            mgr.cleanup()

    def test_request_errors_counted_not_fatal(self):
        mgr, _ = _mk_manager(
            ConcurrencyManager, error_schedule=[True] * 500_000
        )
        try:
            mgr.change_concurrency_level(1)
            time.sleep(0.2)
            mgr.check_health()  # per-request failures never abort the run
            records = mgr.swap_timestamps()
            assert records and all(not r.ok for r in records)
        finally:
            mgr.cleanup()

    def test_concurrency_beyond_max_threads_refused(self):
        mgr, _ = _mk_manager(ConcurrencyManager, max_threads=2)
        try:
            with pytest.raises(InferenceServerException, match="max-threads"):
                mgr.change_concurrency_level(3)
        finally:
            mgr.cleanup()

    def test_sequences_have_correlation_ids(self):
        stats = MockStats()
        sm = SequenceManager(sequence_length=4, sequence_length_specified=True)
        mgr, stats = _mk_manager(
            ConcurrencyManager, stats=stats, sequence_manager=sm
        )
        try:
            mgr.change_concurrency_level(2)
            time.sleep(0.3)
        finally:
            mgr.cleanup()
        assert stats.sequence_ids
        # two slots -> at most two distinct live sequences at any moment,
        # and ids keep increasing as sequences retire
        assert len(set(stats.sequence_ids)) >= 2


class TestRequestRateManager:
    def test_constant_rate(self):
        mgr, stats = _mk_manager(RequestRateManager)
        try:
            mgr.change_request_rate(200)
            time.sleep(1.0)
            n = stats.num_infer_calls
            assert 120 <= n <= 280, n
        finally:
            mgr.cleanup()

    def test_poisson_schedule_distribution(self):
        mgr, _ = _mk_manager(RequestRateManager, distribution="poisson")
        gaps = mgr._make_schedule(100, horizon=10000)
        mean = float(np.mean(gaps))
        assert 0.8 * 1e7 < mean < 1.2 * 1e7
        assert np.std(gaps.astype(float)) > 0.5 * mean  # exponential-ish

    def test_delayed_flagging(self):
        # schedule far faster than the mock latency can sustain
        mgr, _ = _mk_manager(RequestRateManager, latency_s=0.05)
        try:
            mgr.change_request_rate(500, num_threads=2)
            time.sleep(0.5)
            records = mgr.swap_timestamps()
            assert any(r.delayed for r in records)
        finally:
            mgr.cleanup()


class TestCustomLoadManager:
    def test_replays_intervals(self, tmp_path):
        path = tmp_path / "intervals.txt"
        path.write_text("\n".join(["5000000"] * 100))  # 5ms gaps
        mgr, stats = _mk_manager(CustomLoadManager, intervals_file=str(path))
        try:
            mgr.start(num_threads=2)
            time.sleep(0.5)
            assert 50 <= stats.num_infer_calls <= 140
        finally:
            mgr.cleanup()


class _FakeManager:
    """Deterministic manager stand-in for profiler-only tests."""

    model_name = "mock"

    def __init__(self, schedule):
        # schedule: list of lists of (latency_ns, ok) generated per window
        self._schedule = list(schedule)
        self._sent = 0

    def get_and_reset_num_sent(self):
        n = self._sent
        self._sent = 0
        return n

    def swap_timestamps(self):
        if not self._schedule:
            return []
        batch = self._schedule.pop(0)
        now = time.monotonic_ns()
        recs = []
        for lat_ns, ok in batch:
            recs.append(RequestRecord(now - lat_ns, now, ok))
        self._sent += len(batch)
        return recs

    def check_health(self):
        pass


class TestProfiler:
    def _profiler(self, schedule, **kwargs):
        kwargs.setdefault("measurement_window_s", 0.02)
        return InferenceProfiler(_FakeManager(schedule), **kwargs)

    def test_stable_after_three_windows(self):
        window = [(1_000_000, True)] * 20
        prof = self._profiler([window] * 5)
        status = prof.profile_level("concurrency", 1)
        assert status.stable
        assert status.completed_requests == 60  # exactly 3 stable windows
        assert abs(status.latency_avg_us - 1000) < 1

    def test_unstable_without_convergence(self):
        # throughput alternates wildly -> never stable
        schedule = [
            [(1_000_000, True)] * (5 if i % 2 else 100) for i in range(10)
        ]
        prof = self._profiler(schedule, max_trials=6)
        status = prof.profile_level("concurrency", 1)
        assert not status.stable

    def test_window_clipping_drops_stale_requests(self):
        prof = self._profiler([])
        mgr = prof.manager
        t0 = time.monotonic_ns()

        class _Mgr(_FakeManager):
            def swap_timestamps(self):
                # one record finished long before the window opened
                return [RequestRecord(t0 - 10**12, t0 - 10**11, True)]

        prof.manager = _Mgr([])
        m = prof.measure()
        assert m.throughput == 0

    def test_errors_counted(self):
        window = [(1_000_000, True)] * 10 + [(1_000_000, False)] * 3
        prof = self._profiler([window] * 3)
        status = prof.profile_level("concurrency", 1)
        assert status.error_count == 9  # 3 per window

    def test_percentiles_monotone(self):
        lats = [(int(n), True) for n in np.linspace(1e6, 9e6, 50)]
        prof = self._profiler([lats] * 3)
        status = prof.profile_level("concurrency", 1)
        p = status.percentiles_us
        assert p[50] <= p[90] <= p[95] <= p[99]


class TestEndToEndInprocess:
    """Full harness against the real in-process engine (no sockets)."""

    def test_concurrency_sweep(self, capsys):
        from client_tpu.perf.__main__ import main

        rc = main([
            "-m", "simple", "--hermetic",
            "--concurrency-range", "1:2",
            "--measurement-interval", "100",
            "--max-trials", "4",
            "-s", "50",
        ])
        out = capsys.readouterr().out
        assert "Concurrency: 1" in out
        assert "Concurrency: 2" in out
        assert "infer/sec" in out
        assert rc == 0

    def test_csv_export(self, tmp_path, capsys):
        from client_tpu.perf.__main__ import main

        csv_path = tmp_path / "report.csv"
        rc = main([
            "-m", "simple", "--hermetic",
            "--concurrency-range", "1",
            "--measurement-interval", "100",
            "--max-trials", "3",
            "-s", "90",
            "-f", str(csv_path),
        ])
        assert rc == 0
        lines = csv_path.read_text().strip().splitlines()
        assert len(lines) == 2
        assert lines[0].startswith("Level,Inferences/Second")

    def test_request_rate_mode(self, capsys):
        from client_tpu.perf.__main__ import main

        rc = main([
            "-m", "simple", "--hermetic",
            "--request-rate-range", "100",
            "--request-distribution", "poisson",
            "--measurement-interval", "200",
            "--max-trials", "3",
            "-s", "90",
        ])
        out = capsys.readouterr().out
        assert "Request Rate: 100" in out
        assert rc == 0


class TestValidation:
    def test_validation_data_marks_mismatches(self):
        """validation_data wiring: wrong expected output -> records not ok."""
        from client_tpu.perf import BackendKind, ClientBackendFactory
        from client_tpu.serve import InferenceEngine
        from client_tpu.serve.builtins import default_models

        engine = InferenceEngine(default_models())
        backend = ClientBackendFactory.create(BackendKind.INPROCESS, engine=engine)
        loader = DataLoader(
            [
                {"name": "INPUT0", "datatype": "INT32", "shape": [1, 16]},
                {"name": "INPUT1", "datatype": "INT32", "shape": [1, 16]},
            ]
        )
        ones = [1] * 16
        doc = {
            "data": [[{"INPUT0": ones, "INPUT1": ones}]],
            "validation_data": [[{"OUTPUT0": [2] * 16}]],  # correct sum
        }
        loader.read_data_from_json(doc)
        out_meta = [{"name": "OUTPUT0", "datatype": "INT32", "shape": [1, 16]}]
        dm = create_infer_data_manager(backend, loader, loader._inputs, out_meta)
        dm.init()
        mgr = ConcurrencyManager(
            backend_factory=lambda: backend, data_loader=loader,
            data_manager=dm, model_name="simple",
        )
        try:
            mgr.change_concurrency_level(1)
            time.sleep(0.2)
            records = mgr.swap_timestamps()
            assert records and all(r.ok for r in records)
        finally:
            mgr.stop_workers()
        # now poison the expectation -> every request flagged failed
        loader.expected_outputs[0][0]["OUTPUT0"].array[:] = 99
        mgr2 = ConcurrencyManager(
            backend_factory=lambda: backend, data_loader=loader,
            data_manager=dm, model_name="simple",
        )
        try:
            mgr2.change_concurrency_level(1)
            time.sleep(0.2)
            records = mgr2.swap_timestamps()
            assert records and all(not r.ok for r in records)
        finally:
            mgr2.cleanup()
            engine.close()
