"""perf harness unit tests on the MockClientBackend — the reference's
doctest+mock test design (SURVEY.md §4: MockClientBackend simulates the load
path with injectable latency/error schedules; managers and profiler are
tested with no server).
"""

import time

import numpy as np
import pytest

from client_tpu.perf import (
    BackendKind,
    ClientBackendFactory,
    ConcurrencyManager,
    CustomLoadManager,
    DataLoader,
    InferenceProfiler,
    MockClientBackend,
    MockStats,
    RequestRateManager,
    SequenceManager,
    create_infer_data_manager,
)
from client_tpu.perf.infer_data import InferDataManager
from client_tpu.perf.load_manager import RequestRecord
from client_tpu.utils import InferenceServerException

META = [{"name": "INPUT0", "datatype": "FP32", "shape": [1, 4]}]
OUT_META = [{"name": "OUTPUT0", "datatype": "FP32", "shape": [1, 4]}]


def _write_self_signed_cert(path):
    """Emit a throwaway self-signed cert PEM (openssl CLI ships in-image)."""
    import subprocess

    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(path) + ".key", "-out", str(path),
         "-days", "1", "-subj", "/CN=localhost"],
        check=True, capture_output=True,
    )


def _mk_manager(cls, stats=None, latency_s=0.0, error_schedule=None, **kwargs):
    stats = stats or MockStats()

    def factory():
        return MockClientBackend(
            latency_s=latency_s, error_schedule=error_schedule, stats=stats
        )

    loader = DataLoader(META)
    loader.generate_data()
    dm = create_infer_data_manager(factory(), loader, META, OUT_META)
    dm.init()
    mgr = cls(
        backend_factory=factory,
        data_loader=loader,
        data_manager=dm,
        model_name="mock",
        **kwargs,
    )
    return mgr, stats


class TestDataLoader:
    def test_generate_random(self):
        loader = DataLoader(META)
        loader.generate_data()
        arr = loader.get_input_data(0, 0)["INPUT0"].array
        assert arr.shape == (1, 4) and arr.dtype == np.float32

    def test_generate_zero(self):
        loader = DataLoader(META)
        loader.generate_data(zero_data=True)
        assert not loader.get_input_data(0, 0)["INPUT0"].array.any()

    def test_dynamic_batch_dim_uses_batch_size(self):
        loader = DataLoader(
            [{"name": "X", "datatype": "FP32", "shape": [-1, 4]}], batch_size=3
        )
        loader.generate_data()
        assert loader.get_input_data(0, 0)["X"].array.shape == (3, 4)

    def test_dynamic_non_batch_dim_requires_override(self):
        loader = DataLoader([{"name": "X", "datatype": "FP32", "shape": [1, -1]}])
        with pytest.raises(InferenceServerException, match="dynamic"):
            loader.generate_data()

    def test_shape_override(self):
        loader = DataLoader(
            [{"name": "X", "datatype": "FP32", "shape": [-1, 4]}],
            shape_overrides={"X": [2, 4]},
        )
        loader.generate_data()
        assert loader.get_input_data(0, 0)["X"].array.shape == (2, 4)

    def test_json_streams_and_validation(self):
        doc = {
            "data": [
                [{"INPUT0": [1.0, 2.0, 3.0, 4.0]}],
                [{"INPUT0": {"content": [5.0, 6.0, 7.0, 8.0], "shape": [1, 4]}}],
            ],
            "validation_data": [
                [{"OUTPUT0": [1.0, 2.0, 3.0, 4.0]}],
                [{"OUTPUT0": [5.0, 6.0, 7.0, 8.0]}],
            ],
        }
        loader = DataLoader(META)
        loader.read_data_from_json(doc)
        assert loader.num_streams == 2
        np.testing.assert_allclose(
            loader.get_input_data(0, 0)["INPUT0"].array.flatten(),
            [1, 2, 3, 4],
        )
        assert loader.get_expected_outputs(1, 0)["OUTPUT0"].array.size == 4

    def test_prefix_share_generation(self):
        """--prefix-share workload shape: num_prompts streams whose token
        input shares its leading FRAC with one of shared_pool prefixes,
        scalar INT inputs pinned to a sane budget, values in-vocab."""
        meta = [
            {"name": "TOKENS", "datatype": "INT32", "shape": [32]},
            {"name": "MAX_TOKENS", "datatype": "INT32", "shape": [1]},
        ]
        loader = DataLoader(meta)
        loader.generate_prefix_share(0.75, num_prompts=8, shared_pool=2)
        assert loader.num_streams == 8
        rows = [loader.get_input_data(i, 0)["TOKENS"].array.reshape(-1)
                for i in range(8)]
        prefix_len = int(round(0.75 * 32))
        for i, row in enumerate(rows):
            assert row.shape == (32,)
            assert row.min() >= 1 and row.max() < 256  # byte-vocab safe
            # same pool slot -> identical prefix
            np.testing.assert_array_equal(
                row[:prefix_len], rows[i % 2][:prefix_len]
            )
        # the two pools differ, and tails are (overwhelmingly) unique
        assert not np.array_equal(rows[0][:prefix_len],
                                  rows[1][:prefix_len])
        budgets = {int(loader.get_input_data(i, 0)["MAX_TOKENS"]
                       .array.reshape(-1)[0]) for i in range(8)}
        assert budgets == {16}  # pinned, never a random negative

    def test_prefix_share_needs_token_input_and_valid_share(self):
        loader = DataLoader(META)  # FP32 only: nothing to build prompts in
        with pytest.raises(InferenceServerException):
            loader.generate_prefix_share(0.5)
        loader2 = DataLoader(
            [{"name": "TOKENS", "datatype": "INT32", "shape": [8]}]
        )
        with pytest.raises(InferenceServerException):
            loader2.generate_prefix_share(1.5)

    def test_bytes_generation(self):
        loader = DataLoader([{"name": "S", "datatype": "BYTES", "shape": [2]}])
        loader.generate_data(string_length=5)
        arr = loader.get_input_data(0, 0)["S"].array
        assert arr.dtype == np.object_ and len(arr[0]) == 5


class TestSequenceManager:
    def test_id_allocation_and_wraparound(self):
        sm = SequenceManager(start_sequence_id=10, sequence_id_range=3,
                             sequence_length=2, sequence_length_specified=True)
        ids = [sm.begin_sequence(slot).seq_id for slot in range(4)]
        assert ids == [10, 11, 12, 10]

    def test_advance_flags(self):
        sm = SequenceManager(sequence_length=3, sequence_length_specified=True)
        st = sm.begin_sequence(0)
        flags = [sm.advance(st) for _ in range(3)]
        assert flags == [(True, False), (False, False), (False, True)]

    def test_length_variation_bounds(self):
        sm = SequenceManager(sequence_length=100,
                             sequence_length_variation=20,
                             sequence_length_specified=True)
        lengths = {sm.begin_sequence(i).remaining_queries for i in range(50)}
        assert all(80 <= n <= 120 for n in lengths)
        assert len(lengths) > 1


class TestConcurrencyManager:
    def test_workers_send_requests(self):
        mgr, stats = _mk_manager(ConcurrencyManager)
        try:
            mgr.change_concurrency_level(4)
            time.sleep(0.3)
            records = mgr.swap_timestamps()
            assert len(records) > 50
            assert stats.num_infer_calls > 50
            assert mgr.get_and_reset_num_sent() > 0
        finally:
            mgr.cleanup()

    def test_records_survive_stop_workers(self):
        # profile_completion stops workers (quiescing sends before the output
        # drain) and only then swaps timestamps; stopping must not discard the
        # window's records with the thread list.
        mgr, _ = _mk_manager(ConcurrencyManager)
        try:
            mgr.change_concurrency_level(4)
            time.sleep(0.3)
            mgr.stop_workers()
            records = mgr.swap_timestamps()
            assert len(records) > 50
            assert mgr.swap_timestamps() == []  # drained exactly once
        finally:
            mgr.cleanup()

    def test_reconfigure_threads(self):
        mgr, _ = _mk_manager(ConcurrencyManager)
        try:
            mgr.change_concurrency_level(2)
            assert len(mgr._threads) == 2
            mgr.change_concurrency_level(6)
            assert len(mgr._threads) == 6
        finally:
            mgr.cleanup()

    def test_request_errors_counted_not_fatal(self):
        mgr, _ = _mk_manager(
            ConcurrencyManager, error_schedule=[True] * 500_000
        )
        try:
            mgr.change_concurrency_level(1)
            time.sleep(0.2)
            mgr.check_health()  # per-request failures never abort the run
            records = mgr.swap_timestamps()
            assert records and all(not r.ok for r in records)
        finally:
            mgr.cleanup()

    def test_concurrency_beyond_max_threads_refused(self):
        mgr, _ = _mk_manager(ConcurrencyManager, max_threads=2)
        try:
            with pytest.raises(InferenceServerException, match="max-threads"):
                mgr.change_concurrency_level(3)
        finally:
            mgr.cleanup()

    def test_sequences_have_correlation_ids(self):
        stats = MockStats()
        sm = SequenceManager(sequence_length=4, sequence_length_specified=True)
        mgr, stats = _mk_manager(
            ConcurrencyManager, stats=stats, sequence_manager=sm
        )
        try:
            mgr.change_concurrency_level(2)
            time.sleep(0.3)
        finally:
            mgr.cleanup()
        assert stats.sequence_ids
        # two slots -> at most two distinct live sequences at any moment,
        # and ids keep increasing as sequences retire
        assert len(set(stats.sequence_ids)) >= 2


class TestRequestRateManager:
    def test_constant_rate(self):
        mgr, stats = _mk_manager(RequestRateManager)
        try:
            mgr.change_request_rate(200)
            time.sleep(1.0)
            n = stats.num_infer_calls
            assert 120 <= n <= 280, n
        finally:
            mgr.cleanup()

    def test_poisson_schedule_distribution(self):
        mgr, _ = _mk_manager(RequestRateManager, distribution="poisson")
        gaps = mgr._make_schedule(100, horizon=10000)
        mean = float(np.mean(gaps))
        assert 0.8 * 1e7 < mean < 1.2 * 1e7
        assert np.std(gaps.astype(float)) > 0.5 * mean  # exponential-ish

    def test_delayed_flagging(self):
        # schedule far faster than the mock latency can sustain
        mgr, _ = _mk_manager(RequestRateManager, latency_s=0.05)
        try:
            mgr.change_request_rate(500, num_threads=2)
            time.sleep(0.5)
            records = mgr.swap_timestamps()
            assert any(r.delayed for r in records)
        finally:
            mgr.cleanup()


class TestCustomLoadManager:
    def test_replays_intervals(self, tmp_path):
        path = tmp_path / "intervals.txt"
        path.write_text("\n".join(["5000000"] * 100))  # 5ms gaps
        mgr, stats = _mk_manager(CustomLoadManager, intervals_file=str(path))
        try:
            mgr.start(num_threads=2)
            time.sleep(0.5)
            assert 50 <= stats.num_infer_calls <= 140
        finally:
            mgr.cleanup()


class _FakeManager:
    """Deterministic manager stand-in for profiler-only tests."""

    model_name = "mock"

    def __init__(self, schedule):
        # schedule: list of lists of (latency_ns, ok) generated per window
        self._schedule = list(schedule)
        self._sent = 0

    def get_and_reset_num_sent(self):
        n = self._sent
        self._sent = 0
        return n

    def swap_timestamps(self):
        if not self._schedule:
            return []
        batch = self._schedule.pop(0)
        now = time.monotonic_ns()
        recs = []
        for lat_ns, ok in batch:
            recs.append(RequestRecord(now - lat_ns, now, ok))
        self._sent += len(batch)
        return recs

    def check_health(self):
        pass


class TestProfiler:
    def _profiler(self, schedule, **kwargs):
        kwargs.setdefault("measurement_window_s", 0.02)
        return InferenceProfiler(_FakeManager(schedule), **kwargs)

    def test_stable_after_three_windows(self):
        window = [(1_000_000, True)] * 20
        prof = self._profiler([window] * 5)
        status = prof.profile_level("concurrency", 1)
        assert status.stable
        assert status.completed_requests == 60  # exactly 3 stable windows
        assert abs(status.latency_avg_us - 1000) < 1

    def test_unstable_without_convergence(self):
        # throughput alternates wildly -> never stable
        schedule = [
            [(1_000_000, True)] * (5 if i % 2 else 100) for i in range(10)
        ]
        prof = self._profiler(schedule, max_trials=6)
        status = prof.profile_level("concurrency", 1)
        assert not status.stable

    def test_window_clipping_drops_stale_requests(self):
        prof = self._profiler([])
        mgr = prof.manager
        t0 = time.monotonic_ns()

        class _Mgr(_FakeManager):
            def swap_timestamps(self):
                # one record finished long before the window opened
                return [RequestRecord(t0 - 10**12, t0 - 10**11, True)]

        prof.manager = _Mgr([])
        m = prof.measure()
        assert m.throughput == 0

    def test_errors_counted(self):
        window = [(1_000_000, True)] * 10 + [(1_000_000, False)] * 3
        prof = self._profiler([window] * 10)
        status = prof.profile_level("concurrency", 1)
        assert status.error_count == 9  # 3 per window

    def test_request_rate_binary_probes_start(self):
        """Bisection midpoints never reach lo, so `start` gets its own
        explicit probe: a capacity at/just above start must report start
        as the best passing rate, not 'SLO violated everywhere'."""

        class _RateMgr(_FakeManager):
            def __init__(self):
                super().__init__([])
                self.rate = None

            def change_request_rate(self, r):
                self.rate = r

            def swap_timestamps(self):
                now = time.monotonic_ns()
                lat = 1_000_000 if self.rate <= 60 else 50_000_000
                self._sent += 20
                return [RequestRecord(now - lat, now, True)
                        for _ in range(20)]

        prof = InferenceProfiler(_RateMgr(), measurement_window_s=0.02)
        results, best = prof.profile_request_rate_binary(50, 400, 10_000)
        assert best is not None
        assert best.level_value == 50
        # and an SLO no rate meets still reports None (start probed+failed)
        prof2 = InferenceProfiler(_RateMgr(), measurement_window_s=0.02)
        _, none_best = prof2.profile_request_rate_binary(50, 400, 1)
        assert none_best is None

    def test_percentiles_monotone(self):
        lats = [(int(n), True) for n in np.linspace(1e6, 9e6, 50)]
        prof = self._profiler([lats] * 10)
        status = prof.profile_level("concurrency", 1)
        p = status.percentiles_us
        assert p[50] <= p[90] <= p[95] <= p[99]


class TestEndToEndInprocess:
    """Full harness against the real in-process engine (no sockets)."""

    def test_concurrency_sweep(self, capsys):
        from client_tpu.perf.__main__ import main

        rc = main([
            "-m", "simple", "--hermetic",
            "--concurrency-range", "1:2",
            "--measurement-interval", "100",
            "--max-trials", "4",
            "-s", "50",
        ])
        out = capsys.readouterr().out
        assert "Concurrency: 1" in out
        assert "Concurrency: 2" in out
        assert "infer/sec" in out
        assert rc == 0

    def test_csv_export(self, tmp_path, capsys):
        from client_tpu.perf.__main__ import main

        csv_path = tmp_path / "report.csv"
        rc = main([
            "-m", "simple", "--hermetic",
            "--concurrency-range", "1",
            "--measurement-interval", "100",
            "--max-trials", "3",
            "-s", "90",
            "-f", str(csv_path),
        ])
        assert rc == 0
        lines = csv_path.read_text().strip().splitlines()
        assert len(lines) == 2
        assert lines[0].startswith("Level,Inferences/Second")

    def test_prefix_share_sweep_reports_columns(self, tmp_path, capsys):
        """--prefix-share drives the rotated shared-prefix workload and
        lands the per-sweep prefix columns in summary + CSV + JSON (the
        builtin simple model has no prefix cache, so the numbers are 0 —
        the LM savings themselves are asserted at engine level in
        tests/test_lm.py, where CPU-speed models make it cheap)."""
        import json

        from client_tpu.perf.__main__ import main

        csv_path = tmp_path / "prefix.csv"
        json_path = tmp_path / "prefix.json"
        rc = main([
            "-m", "simple", "--hermetic",
            "--prefix-share", "0.8", "--prefix-pool", "2",
            "--prefix-prompts", "6",
            "--concurrency-range", "2",
            "--measurement-interval", "100",
            "--max-trials", "3",
            "-s", "90",
            "-f", str(csv_path),
            "--json-export", str(json_path),
        ])
        assert rc == 0
        out = capsys.readouterr().out
        assert "prefix cache:" in out
        header = csv_path.read_text().splitlines()[0]
        assert "Prefix Hit %" in header
        assert "Prefill Tokens Saved %" in header
        doc = json.loads(json_path.read_text())
        rec = doc["results"][0]["lm_prefix"]
        assert set(rec) >= {"prefix_hit_pct", "prefill_tokens_saved_pct"}

    def test_prefix_share_rejects_custom_input_data(self):
        from client_tpu.perf.__main__ import main

        with pytest.raises(SystemExit):
            main([
                "-m", "simple", "--hermetic",
                "--prefix-share", "0.5", "--input-data", "zero",
                "--concurrency-range", "1",
            ])

    def test_trace_options_applied_hermetic(self, capsys):
        """--trace-* flags reach the engine's trace-settings control plane."""
        from client_tpu.perf.__main__ import main

        rc = main([
            "-m", "simple", "--hermetic",
            "--concurrency-range", "1",
            "--measurement-interval", "100",
            "--max-trials", "3",
            "-s", "90",
            "--trace-level", "TIMESTAMPS",
            "--trace-rate", "500",
            "--trace-count", "100",
            "--log-frequency", "50",
            "-v",
        ])
        assert rc == 0
        err = capsys.readouterr().err
        assert "trace settings applied" in err
        assert "'trace_rate': '500'" in err

    def test_ssl_options_reach_clients(self, monkeypatch):
        """ssl_options build SSL-configured clients (no connect needed:
        channel/pool construction is lazy)."""
        import grpc as grpc_mod

        from client_tpu.perf.client_backend import (
            BackendKind,
            ClientBackendFactory,
        )

        secure_calls = []
        real_secure = grpc_mod.secure_channel
        monkeypatch.setattr(
            grpc_mod, "secure_channel",
            lambda url, creds, options=None: secure_calls.append(url)
            or real_secure(url, creds, options=options),
        )
        grpc_be = ClientBackendFactory.create(
            BackendKind.TRITON_GRPC, url="localhost:1",
            ssl_options={"use_ssl": True},
        )
        assert secure_calls == ["localhost:1"]  # SSL path, not insecure
        grpc_be.close()

        http_be = ClientBackendFactory.create(
            BackendKind.TRITON_HTTP, url="localhost:1",
            ssl_options={"use_ssl": True, "verify_peer": False},
        )
        assert http_be._client._base_url.startswith("https://")
        http_be.close()

    def test_ssl_http_ca_with_verify_peer_off(self, tmp_path):
        """A CA file + verify_peer=0 must build a non-verifying context, not
        a context urllib3 will reject at connect time."""
        import ssl as ssl_mod

        from client_tpu.perf.client_backend import (
            BackendKind,
            ClientBackendFactory,
        )

        # self-signed CA stand-in: any PEM-loadable cert would do, but the
        # context is built with cafile=... so write a real self-signed cert
        pem = tmp_path / "ca.pem"
        _write_self_signed_cert(pem)
        be = ClientBackendFactory.create(
            BackendKind.TRITON_HTTP, url="localhost:1",
            ssl_options={
                "use_ssl": True,
                "verify_peer": False,
                "ca_certificates_file": str(pem),
            },
        )
        ctx = be._client._pool.connection_pool_kw.get("ssl_context")
        assert ctx is not None
        assert ctx.check_hostname is False
        assert ctx.verify_mode == ssl_mod.CERT_NONE
        be.close()

    def test_trace_unsupported_on_non_kserve(self):
        from client_tpu.perf.client_backend import MockClientBackend
        from client_tpu.utils import InferenceServerException

        with pytest.raises(InferenceServerException, match="trace settings"):
            MockClientBackend().update_trace_settings(settings={"trace_rate": "1"})

    def test_request_rate_mode(self, capsys):
        from client_tpu.perf.__main__ import main

        rc = main([
            "-m", "simple", "--hermetic",
            "--request-rate-range", "100",
            "--request-distribution", "poisson",
            "--measurement-interval", "200",
            "--max-trials", "3",
            "-s", "90",
        ])
        out = capsys.readouterr().out
        assert "Request Rate: 100" in out
        assert rc == 0

    def test_request_rate_binary_search_finds_slo_rate(self, capsys):
        """--binary-search + --request-rate-range + -l: SLO-seeking
        bisection over REQUEST RATE (the capacity-planning search;
        profile_concurrency_binary only answers the closed-loop
        question) — converges to a passing rate under a generous SLO."""
        from client_tpu.perf.__main__ import main

        rc = main([
            "-m", "simple", "--hermetic",
            "--request-rate-range", "50:400",
            "--binary-search",
            "-l", "500",  # msec; hermetic latencies are ~0.2 ms
            "--measurement-interval", "100",
            "--max-trials", "3",
            "-s", "90",
        ])
        out = capsys.readouterr().out
        assert "Max sustainable rate under SLO" in out
        assert rc == 0

    def test_request_rate_binary_search_slo_unmeetable(self, capsys):
        """An SLO below any achievable latency reports no passing rate
        (best=None) instead of fabricating one."""
        from client_tpu.perf.__main__ import main

        rc = main([
            "-m", "simple", "--hermetic",
            "--request-rate-range", "50:200",
            "--binary-search",
            "-l", "0.000001",
            "--measurement-interval", "100",
            "--max-trials", "3",
            "-s", "90",
        ])
        out = capsys.readouterr().out
        assert "SLO violated at every probed rate" in out
        assert rc == 0

    def test_json_export_per_sweep_point(self, tmp_path, capsys):
        """--json-export writes one full record per sweep point (all
        percentiles + server stats deltas — the fields the flat CSV
        cannot hold) alongside the CSV."""
        import json

        from client_tpu.perf.__main__ import main

        json_path = tmp_path / "report.json"
        csv_path = tmp_path / "report.csv"
        rc = main([
            "-m", "simple", "--hermetic",
            "--concurrency-range", "1:2",
            "--measurement-interval", "100",
            "--max-trials", "3",
            "-s", "90",
            "-f", str(csv_path),
            "--json-export", str(json_path),
        ])
        assert rc == 0
        doc = json.loads(json_path.read_text())
        assert len(doc["results"]) == 2
        for rec in doc["results"]:
            assert rec["level_label"] == "concurrency"
            assert rec["throughput_infer_per_sec"] > 0
            assert set(rec["percentiles_us"]) == {"50", "90", "95", "99"}
            assert "server_stats" in rec and "per_tenant" in rec
        # CSV rode along untouched
        assert csv_path.read_text().startswith("Level,Inferences/Second")


class TestValidation:
    def test_validation_data_marks_mismatches(self):
        """validation_data wiring: wrong expected output -> records not ok."""
        from client_tpu.perf import BackendKind, ClientBackendFactory
        from client_tpu.serve import InferenceEngine
        from client_tpu.serve.builtins import default_models

        engine = InferenceEngine(default_models())
        backend = ClientBackendFactory.create(BackendKind.INPROCESS, engine=engine)
        loader = DataLoader(
            [
                {"name": "INPUT0", "datatype": "INT32", "shape": [1, 16]},
                {"name": "INPUT1", "datatype": "INT32", "shape": [1, 16]},
            ]
        )
        ones = [1] * 16
        doc = {
            "data": [[{"INPUT0": ones, "INPUT1": ones}]],
            "validation_data": [[{"OUTPUT0": [2] * 16}]],  # correct sum
        }
        loader.read_data_from_json(doc)
        out_meta = [{"name": "OUTPUT0", "datatype": "INT32", "shape": [1, 16]}]
        dm = create_infer_data_manager(backend, loader, loader._inputs, out_meta)
        dm.init()
        mgr = ConcurrencyManager(
            backend_factory=lambda: backend, data_loader=loader,
            data_manager=dm, model_name="simple",
        )
        try:
            mgr.change_concurrency_level(1)
            time.sleep(0.2)
            records = mgr.swap_timestamps()
            assert records and all(r.ok for r in records)
        finally:
            mgr.stop_workers()
        # now poison the expectation -> every request flagged failed
        loader.expected_outputs[0][0]["OUTPUT0"].array[:] = 99
        mgr2 = ConcurrencyManager(
            backend_factory=lambda: backend, data_loader=loader,
            data_manager=dm, model_name="simple",
        )
        try:
            mgr2.change_concurrency_level(1)
            time.sleep(0.2)
            records = mgr2.swap_timestamps()
            assert records and all(not r.ok for r in records)
        finally:
            mgr2.cleanup()
            engine.close()


class TestCountWindows:
    """count_windows measurement mode (reference --measurement-mode
    count_windows, MeasureForCountWindows)."""

    def _live_manager(self, latency_s=0.001):
        return _mk_manager(ConcurrencyManager, latency_s=latency_s)

    def test_window_closes_on_request_count(self):
        mgr, _ = self._live_manager()
        try:
            mgr.change_concurrency_level(2)
            prof = InferenceProfiler(
                mgr, measurement_window_s=5.0,  # time mode would take 5s
                measurement_mode="count_windows",
                measurement_request_count=30,
            )
            t0 = time.monotonic()
            m = prof.measure()
            elapsed = time.monotonic() - t0
            # closed by count, far before the 5s time window
            assert elapsed < 2.5
            assert m.latencies_ns.size >= 30
        finally:
            mgr.cleanup()

    def test_stalled_server_hits_time_cap_not_hang(self):
        prof = InferenceProfiler(
            _FakeManager([]),  # never produces records
            measurement_window_s=0.02,
            measurement_mode="count_windows",
            measurement_request_count=1000,
        )
        t0 = time.monotonic()
        m = prof.measure()
        assert time.monotonic() - t0 < 2.0  # 10x window cap
        assert m.throughput == 0

    def test_bad_mode_rejected(self):
        with pytest.raises(InferenceServerException, match="measurement mode"):
            InferenceProfiler(_FakeManager([]), measurement_mode="bogus")


class TestOverheadAccounting:
    def test_overhead_reflects_idle_slot_time(self):
        # 1ms mock latency, 2 slots: workers spend nearly all slot time
        # inside requests -> low overhead; assert it is computed and sane.
        mgr, _ = _mk_manager(ConcurrencyManager, latency_s=0.001)
        try:
            mgr.change_concurrency_level(2)
            prof = InferenceProfiler(
                mgr, measurement_window_s=0.2, max_trials=3,
                stability_threshold=5.0,
            )
            status = prof.profile_level("concurrency", 2)
            assert 0.0 <= status.overhead_pct < 60.0
        finally:
            mgr.cleanup()


class TestEnsemble:
    def test_engine_runs_config_driven_ensemble(self):
        from client_tpu.serve import InferenceEngine
        from client_tpu.serve.builtins import default_models

        engine = InferenceEngine(default_models())
        try:
            a = np.arange(16, dtype=np.int32).reshape(1, 16)
            b = np.ones((1, 16), dtype=np.int32)
            request = {
                "id": "e1",
                "inputs": [
                    {"name": "INPUT0", "datatype": "INT32", "shape": [1, 16],
                     "data": a.flatten().tolist()},
                    {"name": "INPUT1", "datatype": "INT32", "shape": [1, 16],
                     "data": b.flatten().tolist()},
                ],
            }
            response, blobs = engine.execute("simple_ensemble", "", request, b"")
            outs = {o["name"]: o for o in response["outputs"]}
            assert outs["OUTPUT0"]["data"] == (a + b).flatten().tolist()
            assert outs["OUTPUT1"]["data"] == (a - b).flatten().tolist()
            # composing models carry their own statistics
            stats = {
                s["name"]: s for s in engine.statistics("", "")
            }
            assert stats["simple"]["inference_stats"]["success"]["count"] >= 1
            assert (
                stats["identity_int32"]["inference_stats"]["success"]["count"]
                >= 2
            )
            cfg = engine.get_model("simple_ensemble", "").config()
            step_models = [
                s["model_name"] for s in cfg["ensemble_scheduling"]["step"]
            ]
            assert step_models == ["simple", "identity_int32", "identity_int32"]
        finally:
            engine.close()

    def test_profiler_recurses_composing_stats(self):
        from client_tpu.perf.client_backend import BackendKind, ClientBackendFactory
        from client_tpu.perf import create_infer_data_manager
        from client_tpu.serve import InferenceEngine
        from client_tpu.serve.builtins import default_models

        engine = InferenceEngine(default_models())
        try:
            def factory():
                return ClientBackendFactory.create(
                    BackendKind.INPROCESS, engine=engine
                )

            be = factory()
            meta = be.model_metadata("simple_ensemble")
            inputs_meta = [dict(m) for m in meta["inputs"]]
            for m in inputs_meta:
                m["shape"] = [1, 16]
            loader = DataLoader(inputs_meta, batch_size=1)
            loader.generate_data()
            dm = create_infer_data_manager(
                be, loader, inputs_meta, [dict(m) for m in meta["outputs"]],
                shared_memory="none",
            )
            dm.init()
            mgr = ConcurrencyManager(
                backend_factory=factory, data_loader=loader, data_manager=dm,
                model_name="simple_ensemble", max_threads=2,
            )
            prof = InferenceProfiler(
                mgr, backend=be, measurement_window_s=0.1, max_trials=3,
                stability_threshold=5.0,
            )
            try:
                results = prof.profile_concurrency_range(1, 1, 1)
                ens = results[0].ensemble_stats
                assert set(ens) == {"simple", "identity_int32"}
                assert ens["simple"]["success_count"] > 0
                assert ens["identity_int32"]["success_count"] > 0
            finally:
                mgr.cleanup()
        finally:
            engine.close()


class TestModelParser:
    """ModelParser normalization (reference model_parser.h:59-193)."""

    def _parser(self, name):
        from client_tpu.perf import ModelParser
        from client_tpu.perf.client_backend import BackendKind, ClientBackendFactory
        from client_tpu.serve import InferenceEngine
        from client_tpu.serve.builtins import default_models

        engine = InferenceEngine(default_models())
        be = ClientBackendFactory.create(BackendKind.INPROCESS, engine=engine)
        try:
            return ModelParser.create(be, name, batch_size=2)
        finally:
            engine.close()

    def test_dynamic_dims_resolved_and_batch_size(self):
        p = self._parser("simple")
        assert p.inputs[0]["shape"] == [2, 16]  # -1 -> batch_size
        assert p.max_batch_size == 8

    def test_scheduler_kinds(self):
        from client_tpu.perf import SchedulerType

        assert self._parser("simple").scheduler_type == SchedulerType.NONE
        assert (
            self._parser("simple_sequence").scheduler_type
            == SchedulerType.SEQUENCE
        )
        ens = self._parser("simple_ensemble")
        assert ens.scheduler_type == SchedulerType.ENSEMBLE
        assert ens.composing_models == ["simple", "identity_int32"]
        assert self._parser("simple_sequence").requires_sequence_flags()

    def test_decoupled_flag(self):
        assert self._parser("repeat_int32").is_decoupled
        assert not self._parser("simple").is_decoupled


def test_nested_ensemble_recurses():
    from client_tpu.serve import InferenceEngine
    from client_tpu.serve.builtins import default_models, ensemble_model
    from client_tpu.serve.model_runtime import Model, TensorSpec

    outer = Model(
        "outer_ensemble",
        inputs=[
            TensorSpec("INPUT0", "INT32", [-1, 16]),
            TensorSpec("INPUT1", "INT32", [-1, 16]),
        ],
        outputs=[TensorSpec("OUTPUT0", "INT32", [-1, 16])],
        fn=None,
        platform="ensemble",
        ensemble_steps=[
            {
                "model_name": "simple_ensemble",  # nested ensemble step
                "input_map": {"INPUT0": "INPUT0", "INPUT1": "INPUT1"},
                "output_map": {"OUTPUT0": "OUTPUT0"},
            },
        ],
    )
    engine = InferenceEngine(default_models() + [outer])
    try:
        a = np.arange(16, dtype=np.int32).reshape(1, 16)
        b = np.full((1, 16), 2, dtype=np.int32)
        request = {
            "id": "n1",
            "inputs": [
                {"name": "INPUT0", "datatype": "INT32", "shape": [1, 16],
                 "data": a.flatten().tolist()},
                {"name": "INPUT1", "datatype": "INT32", "shape": [1, 16],
                 "data": b.flatten().tolist()},
            ],
        }
        response, _ = engine.execute("outer_ensemble", "", request, b"")
        outs = {o["name"]: o for o in response["outputs"]}
        assert outs["OUTPUT0"]["data"] == (a + b).flatten().tolist()
    finally:
        engine.close()


class TestProcPool:
    """Multi-process load generation (client_tpu.perf.procpool) — the
    GIL-sidestep analog of the reference's native multi-worker perf_analyzer
    (perf_analyzer.cc:56-424)."""

    def test_multiproc_wire_load(self):
        from client_tpu.serve import Server
        from client_tpu.perf.procpool import run_completion_multiproc

        with Server(grpc_port=0) as server:
            res = run_completion_multiproc(
                server.grpc_address, "simple",
                processes=2, concurrency=4,
                window_s=1.0, warmup_s=0.2,
                spec={"mode": "wire"},
            )
            assert res.processes == 2
            assert res.error_count == 0
            assert res.completed_requests > 0
            assert res.throughput > 0
            assert 50 in res.percentiles_us

    def test_multiproc_worker_error_reported(self):
        from client_tpu.perf.procpool import run_completion_multiproc

        with pytest.raises(InferenceServerException, match="load worker"):
            run_completion_multiproc(
                "127.0.0.1:1", "nope", processes=1, concurrency=1,
                window_s=0.2, warmup_s=0.0, spec={"mode": "wire"},
                start_timeout_s=30,
            )

    def test_preregistered_shm_specs(self):
        """Region-by-name referencing: a worker-side data manager builds
        region-referencing requests without creating regions (no jax)."""
        from client_tpu.perf.procpool import (
            PreRegisteredShmInferDataManager,
            ShapeOnlyLoader,
        )

        class _FakeInput:
            def __init__(self, name, shape, datatype):
                self.name, self.shape, self.datatype = name, shape, datatype

            def set_shared_memory(self, region, nbytes, offset=0):
                self.region, self.nbytes = region, nbytes

        class _FakeOut:
            def __init__(self, name):
                self.name = name

            def set_shared_memory(self, region, nbytes, offset=0):
                self.region = region

        class _FakeBackend:
            infer_input_cls = _FakeInput
            requested_output_cls = _FakeOut

        mgr = PreRegisteredShmInferDataManager(
            _FakeBackend(),
            {(0, 0): [("IN", [1, 4], "FP32", "region_in", 16)]},
            [("OUT", "region_out", 16)],
        )
        mgr.init()
        data = mgr.get_infer_data(0, 0)
        assert data.inputs[0].region == "region_in"
        assert data.outputs[0].region == "region_out"
        loader = ShapeOnlyLoader(1, [1])
        assert loader.num_steps(0) == 1
        assert loader.get_expected_outputs(0, 0) == {}


class TestAsyncConcurrencyManager:
    """Async InferContext slots over grpc.aio (reference -a/--async)."""

    def test_async_slots_drive_requests(self):
        from client_tpu.perf.load_manager import AsyncConcurrencyManager
        from client_tpu.serve import Server

        with Server(grpc_port=0) as server:
            control = ClientBackendFactory.create(
                BackendKind.TRITON_GRPC, url=server.grpc_address
            )
            meta = control.model_metadata("simple")
            inputs_meta = [
                {"name": m["name"], "datatype": m["datatype"],
                 "shape": [1 if d == -1 else d for d in m["shape"]]}
                for m in meta["inputs"]
            ]
            outputs_meta = [dict(m) for m in meta["outputs"]]
            loader = DataLoader(inputs_meta, batch_size=1)
            loader.generate_data()
            mgr_dm = InferDataManager(
                control, loader, inputs_meta, outputs_meta
            )
            mgr_dm.init()
            manager = AsyncConcurrencyManager(
                url=server.grpc_address,
                data_loader=loader,
                data_manager=mgr_dm,
                model_name="simple",
                max_threads=16,
            )
            try:
                manager.change_concurrency_level(8)
                time.sleep(1.0)
                manager.check_health()
                records = manager.swap_timestamps()
                assert len(records) > 8
                assert all(r.ok for r in records)
                # reconfigure to a lower level works (slot teardown + restart)
                manager.change_concurrency_level(2)
                time.sleep(0.4)
                assert manager.get_and_reset_num_sent() > 0
            finally:
                manager.cleanup()
            control.close()

    def test_cli_async_mode(self):
        import subprocess
        import sys

        from client_tpu.serve import Server

        with Server(grpc_port=0) as server:
            proc = subprocess.run(
                [sys.executable, "-m", "client_tpu.perf", "-m", "simple",
                 "-u", server.grpc_address, "-i", "grpc", "--async",
                 "--concurrency-range", "4:4:1",
                 "--measurement-interval", "500", "--max-trials", "4"],
                capture_output=True, text=True, timeout=120,
            )
            assert proc.returncode == 0, proc.stdout + proc.stderr
            assert "Best: concurrency=" in proc.stdout
