"""traceview joins + critical-path attribution, the flight recorder, and
the SLO watchdog (the observability PR's new surfaces).

Covers:
- join_traces/critical_path on synthetic multi-source records (client,
  server, peer, tick) — attribution math pinned against hand-computed
  figures, overlap-safe server merging;
- the CLI: text timelines, ``--format json`` one-object-per-trace,
  ``--trace`` selection, bad-file exit code;
- FlightRecorder: bounded ring, JSON-lines render/dump, registry counter,
  unwritable-dir best-effort;
- LatencySketch: quantile bounds, exact mergeability;
- SloWatchdog: gauge export, objective breach -> counter + flight dump
  (rate-limited), 4xx-vs-5xx error accounting, window rotation.
"""

import json

import pytest

from client_tpu import traceview
from client_tpu.serve.flight import FlightRecorder
from client_tpu.serve.metrics import Registry
from client_tpu.serve.slo import BOUNDS_MS, LatencySketch, SloWatchdog
from client_tpu.tracing import ClientTracer, append_trace_record

MS = 1_000_000  # ns per ms


def _rec(trace_id, source, model, events, span_id="s", parent=None,
         tags=None):
    record = {
        "trace_id": trace_id,
        "span_id": span_id,
        "source": source,
        "model_name": model,
        "timestamps": [
            dict({"name": n, "ns": ns}, **(extra or {}))
            for n, ns, extra in events
        ],
    }
    if parent:
        record["parent_span_id"] = parent
    if tags:
        record["tags"] = tags
    return record


def _sample_records(t0=1_000 * MS):
    client = _rec("t1", "client", "m", [
        ("CLIENT_REQUEST_START", t0, None),
        ("CLIENT_ATTEMPT_START", t0 + 1 * MS, {"endpoint": "a:1"}),
        ("CLIENT_ATTEMPT_END", t0 + 19 * MS, {"endpoint": "a:1"}),
        ("CLIENT_REQUEST_END", t0 + 20 * MS, None),
    ], span_id="c1")
    server = _rec("t1", "server", "m", [
        ("REQUEST_START", t0 + 2 * MS, None),
        ("QUEUE_START", t0 + 2 * MS, None),
        ("QUEUE_END", t0 + 5 * MS, None),
        ("COMPUTE_START", t0 + 5 * MS, None),
        ("COMPUTE_END", t0 + 15 * MS, None),
        ("RESPONSE_SENT", t0 + 16 * MS, None),
    ], span_id="s1", parent="c1")
    peer = _rec("t1", "server", "__peer_prefix_get__", [
        ("PEER_START", t0 + 6 * MS, None),
        ("PEER_END", t0 + 10 * MS, None),
    ], span_id="p1", parent="s1", tags={"peer": "b:2", "hit": True})
    other = _rec("t2", "server", "n", [
        ("COMPUTE_START", t0, None),
        ("COMPUTE_END", t0 + 3 * MS, None),
    ])
    return [client, server, peer, other]


class TestJoin:
    def test_groups_by_trace_id_sorted_by_start(self):
        traces = traceview.join_traces(_sample_records())
        assert set(traces) == {"t1", "t2"}
        assert [r["span_id"] for r in traces["t1"]] == ["c1", "s1", "p1"]

    def test_drops_recordless_and_idless_spans(self):
        traces = traceview.join_traces([
            {"trace_id": "x", "timestamps": []},
            {"source": "client", "timestamps": [{"name": "A", "ns": 1}]},
        ])
        assert traces == {}

    def test_critical_path_attribution(self):
        traces = traceview.join_traces(_sample_records())
        cp = traceview.critical_path(traces["t1"])
        assert cp["total_ms"] == pytest.approx(20.0)
        assert cp["queue_ms"] == pytest.approx(3.0)
        assert cp["compute_ms"] == pytest.approx(10.0)
        assert cp["peer_ms"] == pytest.approx(4.0)
        # wire = client total (20) - server span extent (2..16 = 14)
        assert cp["wire_ms"] == pytest.approx(6.0)

    def test_overlapping_server_spans_do_not_double_count(self):
        t0 = 0
        spans = [
            _rec("t", "server", "m", [
                ("COMPUTE_START", t0, None),
                ("COMPUTE_END", t0 + 10 * MS, None),
            ]),
            _rec("t", "server", "m2", [
                ("COMPUTE_START", t0 + 5 * MS, None),
                ("COMPUTE_END", t0 + 12 * MS, None),
            ]),
        ]
        cp = traceview.critical_path(spans)
        # no client span: total falls back to the full extent
        assert cp["total_ms"] == pytest.approx(12.0)
        assert cp["wire_ms"] == 0.0

    def test_sequence_trace_sums_per_request_client_spans(self):
        t0 = 0
        spans = [
            _rec("t", "client", "m", [
                ("CLIENT_REQUEST_START", t0, None),
                ("CLIENT_REQUEST_END", t0 + 5 * MS, None),
            ], span_id="c1"),
            _rec("t", "client", "m", [
                ("CLIENT_REQUEST_START", t0 + 100 * MS, None),
                ("CLIENT_REQUEST_END", t0 + 107 * MS, None),
            ], span_id="c2"),
        ]
        cp = traceview.critical_path(spans)
        # the think-time gap between steps is NOT latency
        assert cp["total_ms"] == pytest.approx(12.0)


class TestCli:
    def _write(self, tmp_path, records, name="t.jsonl"):
        path = tmp_path / name
        for record in records:
            append_trace_record(str(path), record)
        return str(path)

    def test_text_timeline(self, tmp_path, capsys):
        path = self._write(tmp_path, _sample_records())
        assert traceview.main([path]) == 0
        out = capsys.readouterr().out
        assert "trace t1" in out and "trace t2" in out
        assert "critical path" in out
        assert "peer=b:2" in out and "hit=True" in out
        assert "QUEUE_END" in out

    def test_json_format_one_object_per_trace(self, tmp_path, capsys):
        path = self._write(tmp_path, _sample_records())
        assert traceview.main(["--format", "json", path]) == 0
        docs = [
            json.loads(line)
            for line in capsys.readouterr().out.strip().splitlines()
        ]
        assert {d["trace_id"] for d in docs} == {"t1", "t2"}
        t1 = next(d for d in docs if d["trace_id"] == "t1")
        assert t1["sources"] == ["client", "server"]
        assert t1["models"] == ["m"]
        assert t1["critical_path"]["peer_ms"] == pytest.approx(4.0)

    def test_trace_prefix_selection_and_min_spans(self, tmp_path, capsys):
        path = self._write(tmp_path, _sample_records())
        assert traceview.main(["--trace", "t2", path]) == 0
        out = capsys.readouterr().out
        assert "trace t2" in out and "trace t1" not in out
        assert traceview.main(["--min-spans", "2", path]) == 0
        out = capsys.readouterr().out
        assert "trace t1" in out and "trace t2" not in out

    def test_multi_file_join(self, tmp_path, capsys):
        records = _sample_records()
        a = self._write(tmp_path, records[:1], "client.jsonl")
        b = self._write(tmp_path, records[1:3], "server.jsonl")
        assert traceview.main(["--trace", "t1", a, b]) == 0
        assert "spans=3" in capsys.readouterr().out

    def test_missing_file_is_exit_2(self, tmp_path, capsys):
        assert traceview.main([str(tmp_path / "absent.jsonl")]) == 2
        assert "traceview:" in capsys.readouterr().err


class TestSequencePinnedSampling:
    def test_all_steps_share_one_trace_id(self):
        tracer = ClientTracer(trace_rate=1)
        traces = [
            tracer.sample("m", context_key=("sequence", 7))
            for _ in range(4)
        ]
        assert all(t is not None for t in traces)
        assert len({t.trace_id for t in traces}) == 1
        assert len({t.span_id for t in traces}) == 4

    def test_sequence_traced_whole_or_not_at_all(self):
        """With trace_rate > 1 the key's FIRST request decides for the
        whole sequence: an unsampled first step pins the key untraced —
        a trace must never start at a random mid-step."""
        tracer = ClientTracer(trace_rate=2)
        # request 0 (sampled slot) -> sequence A traced from step 1
        a = [tracer.sample("m", context_key="A") for _ in range(3)]
        assert all(t is not None for t in a)
        # the next fresh key lands on an unsampled slot: never traced,
        # even though later steps cross sampled slots
        b = [tracer.sample("m", context_key="B") for _ in range(5)]
        assert all(t is None for t in b)
        # release makes a restarted key re-decide
        tracer.release_context("B")
        assert tracer.sample("m", context_key="B") is not None

    def test_release_context_starts_fresh_trace(self):
        tracer = ClientTracer(trace_rate=1)
        # tpulint: disable=SPAN-LEAK -- ids compared only; never exported
        first = tracer.sample("m", context_key="k")
        tracer.release_context("k")
        # tpulint: disable=SPAN-LEAK -- ids compared only; never exported
        second = tracer.sample("m", context_key="k")
        assert first.trace_id != second.trace_id


class TestFlightRecorder:
    def test_ring_is_bounded(self):
        recorder = FlightRecorder(capacity=4)
        for i in range(10):
            recorder.note("e", i=i)
        snapshot = recorder.snapshot()
        assert len(snapshot) == 4
        assert [r["i"] for r in snapshot] == [6, 7, 8, 9]
        assert recorder.events_noted == 10

    def test_render_and_dump(self, tmp_path):
        registry = Registry()
        recorder = FlightRecorder(
            dump_dir=str(tmp_path), registry=registry, name="r1"
        )
        recorder.note("fault", kind_detail="kill")
        path = recorder.dump("unit test!")
        assert path and path in recorder.dumps
        lines = [json.loads(line) for line in open(path)]
        assert lines[0]["kind"] == "flight_dump"
        assert lines[0]["reason"] == "unit test!"
        assert lines[1]["kind"] == "fault"
        assert registry.get(
            "ctpu_flight_dumps_total", {"reason": "unit-test-"}
        ) == 1

    def test_dump_failure_returns_none(self):
        recorder = FlightRecorder(dump_dir="/proc/definitely/not/writable")
        assert recorder.dump("x") is None
        assert recorder.dumps == []

    def test_env_dump_dir(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPU_FLIGHT_DIR", str(tmp_path / "env"))
        recorder = FlightRecorder()
        path = recorder.dump("envtest")
        assert path is not None and str(tmp_path / "env") in path


class TestLatencySketch:
    def test_quantile_is_conservative_bucket_bound(self):
        sketch = LatencySketch()
        for ms in (1.0, 2.0, 3.0, 100.0):
            sketch.observe(ms)
        # p50 lands in the bucket holding 2.0; bound >= the true value
        assert sketch.quantile(0.5) >= 2.0
        assert sketch.quantile(0.5) <= 2.0 * 1.25
        assert sketch.quantile(1.0) >= 100.0

    def test_merge_is_exact(self):
        a, b = LatencySketch(), LatencySketch()
        for ms in (1, 5, 9):
            a.observe(ms)
        for ms in (2, 1000):
            b.observe(ms, error=True)
        merged = a.merged(b)
        assert merged.count == 5
        assert merged.errors == 2
        assert merged.error_rate() == pytest.approx(0.4)
        one_by_one = LatencySketch()
        for ms in (1, 5, 9, 2, 1000):
            one_by_one.observe(ms)
        assert merged.counts == one_by_one.counts

    def test_bounds_cover_serving_range(self):
        assert BOUNDS_MS[0] <= 0.05
        assert BOUNDS_MS[-1] > 10_000  # >10s


class TestSloWatchdog:
    def test_gauges_export_per_model_tenant(self):
        registry = Registry()
        watchdog = SloWatchdog(registry=registry, check_every=1)
        watchdog.observe("m", "gold", 0.010)
        labels = {"model": "m", "tenant": "gold"}
        assert registry.get("ctpu_slo_p99_ms", labels) >= 10.0
        assert registry.get("ctpu_slo_error_rate", labels) == 0.0

    def test_breach_counts_and_dumps_once_per_interval(self, tmp_path):
        registry = Registry()
        flight = FlightRecorder(dump_dir=str(tmp_path))
        watchdog = SloWatchdog(
            objectives={"*": {"p99_ms": 5.0}}, registry=registry,
            flight=flight, min_samples=4, check_every=4,
            dump_interval_s=3600.0,
        )
        for _ in range(16):
            watchdog.observe("m", "", 0.100)  # 100ms >> 5ms objective
        assert watchdog.breaches >= 1
        assert registry.get(
            "ctpu_slo_breaches_total",
            {"model": "m", "tenant": "", "kind": "p99_ms"},
        ) >= 1
        assert len(flight.dumps) == 1  # rate-limited
        breach_notes = [
            r for r in flight.snapshot() if r["kind"] == "slo_breach"
        ]
        assert breach_notes and breach_notes[0]["objective"] == 5.0

    def test_error_rate_objective(self, tmp_path):
        registry = Registry()
        watchdog = SloWatchdog(
            objectives={"m": {"error_rate": 0.05}}, registry=registry,
            min_samples=4, check_every=4,
        )
        for i in range(8):
            watchdog.observe("m", "", 0.001, error=(i % 2 == 0))
        assert registry.get(
            "ctpu_slo_breaches_total",
            {"model": "m", "tenant": "", "kind": "error_rate"},
        ) >= 1

    def test_exact_model_objective_beats_star(self):
        watchdog = SloWatchdog(
            objectives={"*": {"p99_ms": 1.0}, "m": {"p99_ms": 1e9}}
        )
        assert watchdog.objective_for("m") == {"p99_ms": 1e9}
        assert watchdog.objective_for("other") == {"p99_ms": 1.0}

    def test_no_objectives_observe_only(self):
        watchdog = SloWatchdog(registry=Registry(), check_every=1,
                               min_samples=1)
        for _ in range(8):
            watchdog.observe("m", "", 10.0)
        assert watchdog.breaches == 0
        summary = watchdog.summary()
        assert summary["m|"]["count"] == 8
        assert summary["m|"]["breaches"] == 0

    def test_key_cap_bounds_cardinality(self):
        watchdog = SloWatchdog(max_keys=3)
        for i in range(6):
            watchdog.observe(f"m{i}", "", 0.001)
        assert len(watchdog.summary()) == 3
