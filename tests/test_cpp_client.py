"""Native C++ client integration: run the compiled cc_client_test binary and
example against the in-process server over a real socket (the reference's
cc_client_test.cc pattern, SURVEY.md §4.3)."""

import os
import subprocess

import pytest

from client_tpu.serve import Server

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BUILD = os.path.join(_REPO, "build", "cpp")

needs_cpp = pytest.mark.skipif(
    not os.path.exists(os.path.join(_BUILD, "cc_client_test")),
    reason="native client not built (make cpp)",
)


@pytest.fixture(scope="module")
def server():
    with Server(http_port=0) as s:
        yield s


@needs_cpp
def test_cc_client_suite(server):
    proc = subprocess.run(
        [os.path.join(_BUILD, "cc_client_test"), server.http_address],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "PASS: cc_client_test" in proc.stdout


@needs_cpp
def test_native_example(server):
    proc = subprocess.run(
        [os.path.join(_BUILD, "simple_http_infer_client"), "-u",
         server.http_address],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "PASS" in proc.stdout
