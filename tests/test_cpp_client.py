"""Native C++ client integration: run the compiled cc_client_test binary and
example against the in-process server over a real socket (the reference's
cc_client_test.cc pattern, SURVEY.md §4.3)."""

import os
import subprocess

import pytest

from client_tpu.serve import Server

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BUILD = os.path.join(_REPO, "build", "cpp")

needs_cpp = pytest.mark.skipif(
    # probe the newest binary too, so a stale pre-expansion build dir skips
    # instead of erroring on the missing example
    not all(
        os.path.exists(os.path.join(_BUILD, exe))
        for exe in ("cc_client_test", "reuse_infer_objects_http_client")
    ),
    reason="native client not built (make cpp)",
)


@pytest.fixture(scope="module")
def server():
    with Server(http_port=0) as s:
        yield s


@needs_cpp
def test_cc_client_suite(server):
    proc = subprocess.run(
        [os.path.join(_BUILD, "cc_client_test"), server.http_address],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "PASS: cc_client_test" in proc.stdout


@needs_cpp
def test_native_http_examples(server):
    for exe in ("simple_http_infer_client",
                "simple_http_health_metadata",
                "simple_http_async_infer_client",
                "simple_http_string_infer_client",
                "simple_http_shm_client",
                "simple_http_sequence_sync_infer_client",
                "simple_http_ensemble_client",
                "simple_http_infer_multi_client",
                "reuse_infer_objects_http_client",
                "simple_http_model_control"):
        proc = subprocess.run(
            [os.path.join(_BUILD, exe), "-u", server.http_address],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, exe + ": " + proc.stdout + proc.stderr
        assert "PASS" in proc.stdout, exe


needs_grpc_cpp = pytest.mark.skipif(
    not all(
        os.path.exists(os.path.join(_BUILD, exe))
        for exe in ("cc_grpc_client_test", "simple_grpc_timeout_client")
    ),
    reason="native gRPC client not built (make grpc_cpp)",
)


@pytest.fixture(scope="module")
def grpc_server():
    with Server(grpc_port=0) as s:
        yield s


@needs_grpc_cpp
def test_hpack_unit(grpc_server):
    proc = subprocess.run(
        [os.path.join(_BUILD, "hpack_unit_test")],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


@needs_grpc_cpp
def test_cc_grpc_client_suite(grpc_server):
    """The gRPC half of the typed two-protocol suite (reference
    cc_client_test.cc:1626-1627): same check list as the HTTP binary, run
    against the in-process gRPC server over a real socket — exercises the
    hand-rolled HTTP/2 transport, HPACK, the async reactor (64 concurrent
    AsyncInfer), bidi sequence streaming, and the management surface."""
    proc = subprocess.run(
        [os.path.join(_BUILD, "cc_grpc_client_test"),
         grpc_server.grpc_address],
        capture_output=True, text=True, timeout=180,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "PASS: cc_grpc_client_test" in proc.stdout


@needs_grpc_cpp
def test_native_grpc_examples(grpc_server):
    for exe in ("simple_grpc_infer_client",
                "simple_grpc_sequence_stream_infer_client",
                "simple_grpc_sequence_sync_infer_client",
                "simple_grpc_async_infer_client",
                "simple_grpc_health_metadata",
                "simple_grpc_model_control",
                "simple_grpc_shm_client",
                "simple_grpc_string_infer_client",
                "simple_grpc_tpushm_client",
                "simple_grpc_ensemble_client",
                "simple_grpc_decoupled_repeat_client",
                "simple_grpc_custom_args_client",
                "simple_grpc_timeout_client",
                "image_client",
                "reuse_infer_objects_grpc_client"):
        proc = subprocess.run(
            [os.path.join(_BUILD, exe), "-u", grpc_server.grpc_address],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, exe + ": " + proc.stdout + proc.stderr
        assert "PASS" in proc.stdout, exe


@pytest.fixture(scope="module")
def dual_server():
    with Server(http_port=0, grpc_port=0) as s:
        yield s


@needs_grpc_cpp
def test_client_timeout_suite(dual_server):
    """Timeout behavior for both native clients (reference
    src/c++/tests/client_timeout_test.cc): a microscopic client_timeout on
    slow_identity errors promptly on sync HTTP, sync gRPC, and async gRPC;
    ample/absent deadlines succeed; the client stays usable afterwards."""
    exe = os.path.join(_BUILD, "client_timeout_test")
    if not os.path.exists(exe):
        pytest.skip("client_timeout_test not built")
    proc = subprocess.run(
        [exe, dual_server.http_address, dual_server.grpc_address],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "PASS: client_timeout_test" in proc.stdout


@needs_grpc_cpp
def test_memory_leak_suite(dual_server):
    """RSS-stability loop across both protocols, reused-client and
    fresh-client-per-iteration modes (reference memory_leak_test.cc)."""
    exe = os.path.join(_BUILD, "memory_leak_test")
    if not os.path.exists(exe):
        pytest.skip("memory_leak_test not built")
    proc = subprocess.run(
        [exe, dual_server.http_address, dual_server.grpc_address, "100"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "PASS: memory_leak_test" in proc.stdout


@needs_grpc_cpp
def test_native_perf_worker(dual_server):
    """The native C++ load engine (build/cpp/perf_worker — the reference
    perf_analyzer's async-InferContext load shape) drives a live server and
    reports sane JSON through the python driver."""
    from client_tpu.perf.native_worker import (
        native_worker_available,
        run_native_worker,
    )

    if not native_worker_available():
        pytest.skip("perf_worker not built")
    report = run_native_worker(
        dual_server.grpc_address, "simple",
        concurrency=8, duration_s=1.5, warmup_s=0.3,
        wire_inputs=[("INPUT0", "INT32", [1, 16]),
                     ("INPUT1", "INT32", [1, 16])],
    )
    assert report["errors"] == 0
    assert report["ok"] > 50
    assert report["throughput"] > 0
    assert 0 < report["p50_us"] <= report["p99_us"]


@needs_grpc_cpp
def test_native_perf_worker_rate_mode(dual_server):
    """Open-loop request-rate scheduling in the native engine (reference
    request_rate_worker.h:51-118): achieved throughput tracks the requested
    rate; poisson mode works; the report carries the delayed count."""
    from client_tpu.perf.native_worker import (
        native_worker_available,
        run_native_worker,
    )

    if not native_worker_available():
        pytest.skip("perf_worker not built")
    for distribution in ("constant", "poisson"):
        report = run_native_worker(
            dual_server.grpc_address, "simple",
            concurrency=8, duration_s=2.0, warmup_s=0.3,
            request_rate=100.0, distribution=distribution,
            wire_inputs=[("INPUT0", "INT32", [1, 16]),
                         ("INPUT1", "INT32", [1, 16])],
        )
        assert report["mode"] == "rate"
        assert report["errors"] == 0
        assert "delayed" in report
        # the server turns these around in <1ms, so the achieved rate
        # should sit near the schedule (loose band: CI timers jitter)
        assert 60.0 < report["throughput"] < 140.0, (distribution, report)


@needs_grpc_cpp
def test_native_perf_worker_windows(dual_server):
    """--window-interval emits per-window JSON lines the python driver
    surfaces as report['windows'] — the stability-loop feed."""
    from client_tpu.perf.native_worker import (
        native_worker_available,
        run_native_worker,
    )

    if not native_worker_available():
        pytest.skip("perf_worker not built")
    report = run_native_worker(
        dual_server.grpc_address, "simple",
        concurrency=4, duration_s=2.0, warmup_s=0.3,
        window_interval_s=0.5,
        wire_inputs=[("INPUT0", "INT32", [1, 16]),
                     ("INPUT1", "INT32", [1, 16])],
    )
    assert report["ok"] > 0
    windows = report.get("windows", [])
    assert len(windows) >= 2
    for w in windows:
        assert w["throughput"] > 0
        assert 0 < w["p50_us"] <= w["p99_us"]


@needs_grpc_cpp
def test_native_perf_worker_sequences(dual_server):
    """Bidi sequence streaming in the native engine (the reference's
    sequence workload over one ModelStreamInfer stream): stateful sequences
    complete with correct protocol flags and report message latencies."""
    from client_tpu.perf.native_worker import (
        native_worker_available,
        run_native_worker,
    )

    if not native_worker_available():
        pytest.skip("perf_worker not built")
    report = run_native_worker(
        dual_server.grpc_address, "simple_sequence",
        concurrency=1, duration_s=2.0, warmup_s=0.3,
        sequences=4, seq_steps=5,
        wire_inputs=[("INPUT", "INT32", [1])],
    )
    assert report["mode"] == "sequence"
    assert report["errors"] == 0
    assert report["ok"] > 50
    assert 0 < report["p50_us"] <= report["p99_us"]


@needs_grpc_cpp
def test_native_perf_worker_decoupled(dual_server):
    """Decoupled streaming in the native engine: each request to
    repeat_int32 (IN=5 via constant fill) yields 5 responses + the
    triton_final_response marker; latency is time-to-first-response and
    the report counts the content responses."""
    from client_tpu.perf.native_worker import (
        native_worker_available,
        run_native_worker,
    )

    if not native_worker_available():
        pytest.skip("perf_worker not built")
    report = run_native_worker(
        dual_server.grpc_address, "repeat_int32",
        concurrency=4, duration_s=2.0, warmup_s=0.3,
        decoupled=True,
        wire_inputs=[("IN", "INT32", [1], 5)],
    )
    assert report["mode"] == "decoupled"
    assert report["errors"] == 0
    assert report["ok"] > 20
    # ~5 content responses per completed request.  Up to `concurrency`
    # requests straddle the warmup/measurement reset with some of their
    # responses counted pre-reset, so the exact bound is (ok - c) * 5.
    assert report["responses"] >= max(report["ok"] - 4, 1) * 5
    assert 0 < report["p50_us"] <= report["p99_us"]


@needs_grpc_cpp
def test_perf_cli_native_loadgen(dual_server):
    """`python -m client_tpu.perf --native-loadgen` sweeps concurrency with
    the C++ engine (region setup python-side, measurement loop native)."""
    import subprocess
    import sys

    from client_tpu.perf.native_worker import native_worker_available

    if not native_worker_available():
        pytest.skip("perf_worker not built")
    proc = subprocess.run(
        [sys.executable, "-m", "client_tpu.perf", "-m", "simple",
         "-u", dual_server.grpc_address, "--native-loadgen",
         "--concurrency-range", "2:4:2", "--measurement-interval", "600"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "(native)" in proc.stdout
    assert "Best: concurrency=" in proc.stdout
    assert "windows" in proc.stdout  # stability-qualified levels


@needs_grpc_cpp
def test_perf_cli_native_rate_and_sequence(dual_server):
    """--native-loadgen with --request-rate-range (constant schedule) and
    with --sequence both ride the C++ engine end to end."""
    import subprocess
    import sys

    from client_tpu.perf.native_worker import native_worker_available

    if not native_worker_available():
        pytest.skip("perf_worker not built")
    proc = subprocess.run(
        [sys.executable, "-m", "client_tpu.perf", "-m", "simple",
         "-u", dual_server.grpc_address, "--native-loadgen",
         "--request-rate-range", "50:100:50",
         "--measurement-interval", "600"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "Request rate: 50" in proc.stdout
    assert "Best: rate=" in proc.stdout

    proc = subprocess.run(
        [sys.executable, "-m", "client_tpu.perf", "-m", "simple_sequence",
         "-u", dual_server.grpc_address, "--native-loadgen", "--sequence",
         "--sequence-length", "5", "--concurrency-range", "4",
         "--measurement-interval", "600"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "Sequences: 4" in proc.stdout
    assert "Best: sequences=" in proc.stdout
