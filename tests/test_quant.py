"""Int8 weight-only quantization (client_tpu.ops.quant): kernel numerics vs
dequantized reference, quantization error bounds, and the transformer's
quantized decode path.  On CPU the kernel runs in Pallas interpret mode."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from client_tpu.ops.quant import (
    int8_matmul,
    is_quantized,
    matmul,
    quantize_int8,
)
from client_tpu.serve.models import transformer as tfm


def test_quantize_roundtrip_error_bounded():
    w = jax.random.normal(jax.random.PRNGKey(0), (256, 128), jnp.float32)
    qw = quantize_int8(w)
    assert qw["q"].dtype == jnp.int8 and qw["s"].shape == (128,)
    deq = qw["q"].astype(jnp.float32) * qw["s"]
    # symmetric per-channel int8: error <= scale/2 per element
    assert float(jnp.abs(deq - w).max()) <= float(qw["s"].max()) / 2 + 1e-6


@pytest.mark.parametrize("m,k,n", [(8, 256, 128), (3, 512, 256), (1, 128, 128)])
def test_int8_matmul_matches_dequant(m, k, n):
    x = jax.random.normal(jax.random.PRNGKey(1), (m, k), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(2), (k, n), jnp.float32)
    qw = quantize_int8(w)
    ref = x @ (qw["q"].astype(jnp.float32) * qw["s"])
    out = int8_matmul(x, qw, block_m=8, block_n=128, block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-3, rtol=1e-4)


def test_int8_matmul_leading_dims_and_ragged_fallback():
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 5, 96), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(4), (96, 200), jnp.float32)  # ragged n
    qw = quantize_int8(w)
    ref = x @ (qw["q"].astype(jnp.float32) * qw["s"])
    out = int8_matmul(x, qw)
    assert out.shape == (2, 5, 200)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-3, rtol=1e-4)


def test_matmul_dispatch():
    x = jnp.ones((4, 32), jnp.float32)
    w = jnp.ones((32, 64), jnp.float32)
    assert not is_quantized(w)
    np.testing.assert_allclose(np.asarray(matmul(x, w)), np.asarray(x @ w))
    qw = quantize_int8(w)
    assert is_quantized(qw)
    np.testing.assert_allclose(
        np.asarray(matmul(x, qw)), np.asarray(x @ w), atol=1e-3, rtol=1e-4
    )


CFG = tfm.TransformerConfig(
    vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq=32, dtype="float32",
)


def test_quantized_forward_close_to_full_precision():
    params = tfm.init_params(jax.random.PRNGKey(5), CFG)
    qparams = tfm.quantize_params(params)
    tokens = jax.random.randint(jax.random.PRNGKey(6), (2, 16), 0, CFG.vocab_size)
    full = np.asarray(tfm.forward(params, tokens, CFG))
    quant = np.asarray(tfm.forward(qparams, tokens, CFG))
    # int8 weight error propagates; logits stay close and ranking stable
    assert np.abs(quant - full).max() < 0.35
    agree = (quant.argmax(-1) == full.argmax(-1)).mean()
    assert agree > 0.9, agree


def test_quantized_prefill_decode_matches_quantized_forward():
    """The quantized decode path is self-consistent (cache vs full seq)."""
    params = tfm.quantize_params(tfm.init_params(jax.random.PRNGKey(7), CFG))
    toks = jax.random.randint(jax.random.PRNGKey(8), (1, 10), 0, CFG.vocab_size)
    full = np.asarray(tfm.forward(params, toks, CFG))
    cache = tfm.init_cache(CFG, 1)
    logits, cache = tfm.prefill(params, toks[:, :6], CFG, cache)
    np.testing.assert_allclose(np.asarray(logits), full[:, 5],
                               atol=2e-4, rtol=1e-3)
    for i in range(6, 10):
        logits, cache = tfm.decode_step(params, toks[:, i], CFG, cache)
        np.testing.assert_allclose(np.asarray(logits), full[:, i],
                                   atol=2e-4, rtol=1e-3)


def test_quantized_params_reject_mesh():
    from client_tpu.parallel import make_mesh

    params = tfm.quantize_params(tfm.init_params(jax.random.PRNGKey(10), CFG))
    tokens = jnp.zeros((2, 16), jnp.int32)
    with pytest.raises(ValueError, match="single-device"):
        tfm.forward(params, tokens, CFG, mesh=make_mesh(dp=8))


def test_quantized_generate_streams():
    params = tfm.quantize_params(tfm.init_params(jax.random.PRNGKey(9), CFG))
    toks = list(tfm.generate(params, CFG, prompt=[1, 2, 3], max_new_tokens=4))
    assert len(toks) == 4
    assert all(0 <= t < CFG.vocab_size for t in toks)
