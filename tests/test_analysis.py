"""tpu-lint (client_tpu/analysis): each rule proven against the real bug
it encodes — hit on the known-violation fixture, silent on the clean
twin — plus suppression comments, the baseline ratchet, the CLI gate,
and the requirement that the repo's own tree scans clean."""

import json
import subprocess
import sys
from pathlib import Path

from client_tpu.analysis import REGISTRY, scan_paths, scan_source
from client_tpu.analysis import baseline as baseline_mod
from client_tpu.analysis.baseline import filter_findings

FIXTURES = Path(__file__).parent / "analysis_fixtures"
ROOT = Path(__file__).parent.parent


def _scan(name):
    path = FIXTURES / name
    return scan_source(path.read_text(), str(path))


def _rules_hit(findings):
    return sorted({f.rule for f in findings})


def test_registry_has_all_rules():
    assert set(REGISTRY) >= {
        "NPY-TRUTH", "ASYNC-BLOCK", "LOCK-DISPATCH", "QUEUE-SENTINEL",
        "CV-WAIT-LOOP", "SHARED-MUT", "TIME-WALL", "METRIC-LABEL",
        "RESP-PARAM-OVERWRITE",
    }
    assert len(REGISTRY) >= 9
    for rule in REGISTRY.values():
        assert rule.rationale  # every rule documents its motivating bug


# -- per-rule hits and misses ---------------------------------------------

def test_npy_truth_hits():
    findings = _scan("npy_truth_bad.py")
    assert _rules_hit(findings) == ["NPY-TRUTH"]
    # membership, remove, if-truthiness, bool(), while-not, assert, plus
    # the cross-method a2654c4 cancel() shape (membership + remove over a
    # numpy-bearing self-attribute, taint visible only in submit)
    assert len(findings) == 8
    cancel_hits = [f for f in findings if "self._pending" in f.message]
    assert len(cancel_hits) >= 2


def test_npy_truth_clean():
    assert _scan("npy_truth_ok.py") == []


def test_async_block_hits():
    findings = _scan("async_block_bad.py")
    assert _rules_hit(findings) == ["ASYNC-BLOCK"]
    # time.sleep, requests.get, self-queue get, local q.get, and the
    # bounded positional block=True put (unbounded puts never block)
    assert len(findings) == 5


def test_async_block_clean():
    assert _scan("async_block_ok.py") == []


def test_lock_dispatch_hits_prefix_admit():
    """The rule is proven against the real pre-fix _admit_locked: both
    jit dispatches under the *_locked convention plus the inline
    with-self._cv tick."""
    findings = _scan("prefix_admit_lock_dispatch.py")
    assert _rules_hit(findings) == ["LOCK-DISPATCH"]
    assert len(findings) == 3
    messages = " ".join(f.message for f in findings)
    assert "self._prefill" in messages
    assert "self._adopt" in messages
    assert "self._tick" in messages


def test_lock_dispatch_clean():
    assert _scan("lock_dispatch_ok.py") == []


def test_queue_sentinel_hits_prefix_cancel():
    """The rule is proven against the real pre-fix cancel(): the
    active-slot branch deactivates without closing the stream queue; the
    release-all path (put in the same branch) stays clean."""
    findings = _scan("prefix_cancel_queue_sentinel.py")
    assert _rules_hit(findings) == ["QUEUE-SENTINEL"]
    assert len(findings) == 1
    assert "slot.active = False" in findings[0].snippet


def test_queue_sentinel_clean():
    assert _scan("queue_sentinel_ok.py") == []


def test_cv_wait_loop_hits():
    findings = _scan("cv_wait_bad.py")
    assert _rules_hit(findings) == ["CV-WAIT-LOOP"]
    assert len(findings) == 1


def test_cv_wait_loop_clean():
    assert _scan("cv_wait_ok.py") == []


def test_shared_mut_hits():
    findings = _scan("shared_mut_bad.py")
    assert _rules_hit(findings) == ["SHARED-MUT"]
    assert len(findings) == 1
    assert "_backlog" in findings[0].message


def test_shared_mut_clean():
    assert _scan("shared_mut_ok.py") == []


def test_shared_mut_pool_hits():
    """Balancer-motivated shape: endpoint-pool health state written from
    request-side methods while the prober thread reads it."""
    findings = _scan("shared_mut_pool_bad.py")
    assert _rules_hit(findings) == ["SHARED-MUT"]
    assert len(findings) == 2
    messages = " ".join(f.message for f in findings)
    assert "_states" in messages and "_draining" in messages


def test_shared_mut_pool_clean():
    assert _scan("shared_mut_pool_ok.py") == []


def test_shared_mut_discovery_hits():
    """Discovery-motivated shape: pool membership mutated IN PLACE
    (append/remove) outside the pool lock while the prober thread
    iterates it — the rule's in-place-mutator extension."""
    findings = _scan("shared_mut_discovery_bad.py")
    assert _rules_hit(findings) == ["SHARED-MUT"]
    assert len(findings) == 2
    messages = " ".join(f.message for f in findings)
    assert "append" in messages and "remove" in messages
    assert "_endpoints" in messages


def test_shared_mut_discovery_clean():
    assert _scan("shared_mut_discovery_ok.py") == []


def test_resp_param_overwrite_hits():
    findings = _scan("resp_param_overwrite_bad.py")
    assert _rules_hit(findings) == ["RESP-PARAM-OVERWRITE"]
    # the subscript-chain stamp (rendered[0]) and the bare-name stamp on
    # a caller-owned response
    assert len(findings) == 2


def test_resp_param_overwrite_clean():
    assert _scan("resp_param_overwrite_ok.py") == []


def test_time_wall_hits():
    findings = _scan("time_wall_bad.py")
    assert _rules_hit(findings) == ["TIME-WALL"]
    # the wall-clock deadline assignment, its comparison, the
    # attribute-expiry assignment, and the annotated-assignment form
    assert len(findings) == 4


def test_time_wall_clean():
    # monotonic deadlines and wall-clock *timestamps* both scan clean
    assert _scan("time_wall_ok.py") == []


def test_metric_label_hits():
    """The rule is proven against the pre-fix serve/metrics.py shape:
    model/version/device names interpolated into label positions without
    the escape helper."""
    findings = _scan("metric_label_bad.py")
    assert _rules_hit(findings) == ["METRIC-LABEL"]
    # one per offending line (core reports one finding per rule+line):
    # the model/version labels f-string and the device-id one
    assert len(findings) == 2
    messages = " ".join(f.message for f in findings)
    assert "model" in messages and "device_id" in messages


def test_metric_label_clean():
    # escape_label()-wrapped label values and non-label interpolations
    # (sample values, metric name suffixes) both scan clean
    assert _scan("metric_label_ok.py") == []


def test_current_metrics_module_passes_metric_label():
    """The post-fix metrics renderer is the motivating module: every label
    value goes through escape_label()."""
    assert scan_paths(
        [str(ROOT / "client_tpu" / "serve" / "metrics.py")]
    ) == []


def test_current_continuous_passes_every_rule():
    """The post-fix scheduler is the motivating module: it must scan
    clean (cancel closes active queues; prefill dispatch left the lock)."""
    assert scan_paths(
        [str(ROOT / "client_tpu" / "serve" / "models" / "continuous.py")]
    ) == []


# -- suppression ----------------------------------------------------------

def test_suppression_comments():
    assert _scan("suppressed_ok.py") == []


def test_suppression_is_per_rule():
    src = (FIXTURES / "cv_wait_bad.py").read_text()
    # waiving a DIFFERENT rule must not silence the finding
    src = src.replace(
        "self._cv.wait()", "self._cv.wait()  # tpulint: disable=NPY-TRUTH"
    )
    findings = scan_source(src, "cv_wait_bad.py")
    assert _rules_hit(findings) == ["CV-WAIT-LOOP"]


def test_parse_error_is_reported():
    findings = scan_source("def broken(:\n", "broken.py")
    assert _rules_hit(findings) == ["PARSE-ERROR"]


# -- baseline ratchet -----------------------------------------------------

def test_baseline_ratchet(tmp_path):
    findings = _scan("prefix_cancel_queue_sentinel.py")
    assert findings
    baseline_path = tmp_path / "baseline.json"
    baseline_mod.save(str(baseline_path), findings)
    counter = baseline_mod.load(str(baseline_path))

    # grandfathered finding passes
    new, old = filter_findings(findings, counter)
    assert new == [] and len(old) == len(findings)

    # a finding NOT in the baseline fails
    extra = _scan("cv_wait_bad.py")
    new, old = filter_findings(findings + extra, counter)
    assert [f.rule for f in new] == ["CV-WAIT-LOOP"]

    # the ratchet never grows: a second occurrence of a baselined line
    # beyond its recorded count is new
    new, old = filter_findings(findings + findings, counter)
    assert len(new) == len(findings) and len(old) == len(findings)


def test_committed_baseline_loads():
    counter = baseline_mod.load(baseline_mod.DEFAULT_BASELINE)
    assert sum(counter.values()) >= 0  # well-formed (possibly empty)


# -- CLI gate -------------------------------------------------------------

def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "client_tpu.analysis", *args],
        cwd=str(ROOT), capture_output=True, text=True, timeout=120,
    )


def test_cli_exits_nonzero_on_findings():
    proc = _cli(
        "tests/analysis_fixtures/prefix_cancel_queue_sentinel.py",
        "tests/analysis_fixtures/prefix_admit_lock_dispatch.py",
        "--no-baseline",
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "QUEUE-SENTINEL" in proc.stdout
    assert "LOCK-DISPATCH" in proc.stdout


def test_cli_repo_tree_is_clean():
    """The acceptance gate: the post-fix tree (sources AND tests) holds
    every invariant the rules encode."""
    proc = _cli("client_tpu", "tests")
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_json_output():
    proc = _cli(
        "tests/analysis_fixtures/cv_wait_bad.py", "--json", "--no-baseline"
    )
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["count"] == 1
    assert payload["findings"][0]["rule"] == "CV-WAIT-LOOP"
    assert "CV-WAIT-LOOP" in payload["rules"]


def test_cli_rule_selection_and_catalog():
    proc = _cli("--list-rules")
    assert proc.returncode == 0
    for rule_id in REGISTRY:
        assert rule_id in proc.stdout
    # selecting only an unrelated rule silences the cv finding
    proc = _cli(
        "tests/analysis_fixtures/cv_wait_bad.py", "--rules", "NPY-TRUTH",
        "--no-baseline",
    )
    assert proc.returncode == 0
    proc = _cli("--rules", "NOT-A-RULE")
    assert proc.returncode == 2


def test_cli_missing_path_is_an_error():
    """A typo'd path must fail loudly (exit 2), not scan nothing and
    report a green gate."""
    proc = _cli("no_such_dir_anywhere", "--no-baseline")
    assert proc.returncode == 2
    assert "no such path" in proc.stderr


def test_fixtures_are_excluded_from_tree_scans():
    findings = scan_paths([str(Path("tests"))])
    assert all("analysis_fixtures" not in f.path for f in findings)


def test_write_baseline_rejects_filtered_scans():
    """A --rules- or path-filtered scan must not regenerate the baseline:
    it would silently drop every other rule's grandfathered entries."""
    proc = _cli("client_tpu", "--write-baseline")
    assert proc.returncode == 2
    proc = _cli("--rules", "NPY-TRUTH", "--write-baseline")
    assert proc.returncode == 2


def test_explicitly_named_excluded_dir_is_scanned():
    """Exclusion guards tree walks only: naming the fixtures dir directly
    must scan it (findings, exit 1), not report a silent green no-op."""
    proc = _cli("tests/analysis_fixtures", "--no-baseline")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "QUEUE-SENTINEL" in proc.stdout
